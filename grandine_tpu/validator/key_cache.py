"""Encrypted persistent cache of decrypted validator keys — reference:
validator_key_cache/src/lib.rs:1-12 (decrypted keystores are cached so a
restart skips the per-keystore scrypt/pbkdf2 KDF — at thousands of keys
that is minutes of wall time; the cache itself stays encrypted at rest).

File format (`keys.cache`):
    MAGIC | salt(16) | iv(16) | hmac(32) | ciphertext
One scrypt KDF unlocks the whole cache (vs one per keystore); payload is
AES-128-CTR over a JSON {pubkey_hex: secret_hex} map with an
encrypt-then-MAC HMAC-SHA256 over salt|iv|ciphertext.
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import json
import os
import secrets
from typing import Optional

from grandine_tpu.crypto import bls as A
from grandine_tpu.validator.keymanager import _aes128_ctr

_MAGIC = b"GTKC1\n"
#: one interactive unlock for the whole cache; lighter than the
#: per-keystore EIP-2335 default (2^18) by design — the cache is an
#: optimization layer, the keystores remain the root of trust
_SCRYPT_N = 1 << 14


class KeyCacheError(Exception):
    pass


def _derive(password: str, salt: bytes) -> "tuple[bytes, bytes]":
    dk = hashlib.scrypt(
        password.encode(), salt=salt, n=_SCRYPT_N, r=8, p=1, dklen=48,
        maxmem=128 * 1024 * 1024,
    )
    return dk[:16], dk[16:48]  # (aes key, mac key)


class ValidatorKeyCache:
    """pubkey(48B) -> SecretKey map with encrypted persistence.

    Entries are bound to a digest of the KEYSTORE password they were
    decrypted with: a cache hit still requires presenting the right
    keystore password (`get(pubkey, password)`), so the cache never
    weakens the keystores' role as the authorization gate — it only
    skips their expensive KDF."""

    def __init__(self, path: str, password: str) -> None:
        self.path = path
        self._password = password
        #: pubkey -> (keystore_pw_digest, SecretKey)
        self._keys: "dict[bytes, tuple]" = {}
        self._loaded = False

    # ------------------------------------------------------------- file IO

    def load(self) -> int:
        """Decrypt the cache file; returns the number of keys loaded
        (0 if the file does not exist). Raises KeyCacheError on a wrong
        password or a tampered file."""
        self._loaded = True
        if not os.path.exists(self.path):
            return 0
        with open(self.path, "rb") as f:
            blob = f.read()
        if not blob.startswith(_MAGIC) or len(blob) < len(_MAGIC) + 64:
            raise KeyCacheError("malformed key cache file")
        off = len(_MAGIC)
        salt = blob[off : off + 16]
        iv = blob[off + 16 : off + 32]
        mac = blob[off + 32 : off + 64]
        ct = blob[off + 64 :]
        aes_key, mac_key = _derive(self._password, salt)
        expect = hmac_mod.new(mac_key, salt + iv + ct, hashlib.sha256).digest()
        if not hmac_mod.compare_digest(mac, expect):
            raise KeyCacheError("key cache MAC mismatch (wrong password?)")
        payload = json.loads(_aes128_ctr(aes_key, iv, ct))
        for pk_hex, (pw_digest_hex, sk_hex) in payload.items():
            self._keys[bytes.fromhex(pk_hex)] = (
                bytes.fromhex(pw_digest_hex),
                A.SecretKey.from_bytes(bytes.fromhex(sk_hex)),
            )
        return len(self._keys)

    def save(self) -> None:
        """Atomically (re)write the encrypted cache (0600 perms, like the
        reference's mdbx env)."""
        salt = secrets.token_bytes(16)
        iv = secrets.token_bytes(16)
        aes_key, mac_key = _derive(self._password, salt)
        payload = json.dumps({
            pk.hex(): (digest.hex(), sk.to_bytes().hex())
            for pk, (digest, sk) in self._keys.items()
        }).encode()
        ct = _aes128_ctr(aes_key, iv, payload)
        mac = hmac_mod.new(mac_key, salt + iv + ct, hashlib.sha256).digest()
        tmp = f"{self.path}.{os.getpid()}.tmp"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(_MAGIC + salt + iv + mac + ct)
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    # ------------------------------------------------------------- lookups

    @staticmethod
    def _pw_digest(keystore_password: str) -> bytes:
        # stored only INSIDE the encrypted cache payload; anyone able to
        # read it already holds the cache password and the secret keys
        return hashlib.sha256(
            b"gtkc-pw:" + keystore_password.encode()
        ).digest()

    def get(
        self, pubkey: bytes, keystore_password: str
    ) -> "Optional[A.SecretKey]":
        """The cached key, only if `keystore_password` matches the one
        the entry was decrypted with."""
        if not self._loaded:
            self.load()
        hit = self._keys.get(bytes(pubkey))
        if hit is None:
            return None
        digest, sk = hit
        if not hmac_mod.compare_digest(
            digest, self._pw_digest(keystore_password)
        ):
            return None
        return sk

    def put(
        self, pubkey: bytes, secret_key: "A.SecretKey",
        keystore_password: str,
    ) -> bool:
        """Returns True when the entry is new or changed (callers skip
        the save() rewrite for pure cache-hit re-imports)."""
        entry = (self._pw_digest(keystore_password), secret_key)
        pk = bytes(pubkey)
        old = self._keys.get(pk)
        if (
            old is not None
            and old[0] == entry[0]
            and old[1].to_bytes() == secret_key.to_bytes()
        ):
            return False
        self._keys[pk] = entry
        return True

    def __len__(self) -> int:
        return len(self._keys)


__all__ = ["ValidatorKeyCache", "KeyCacheError"]
