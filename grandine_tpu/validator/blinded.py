"""Blinded block production + unblinding — reference:
validator/src/validator.rs:948,3091-3104 (builder path of propose:
getHeader → build blinded block → sign → submitBlindedBlock → unblind
and publish the full block) over the builder_api crate.

`produce_blinded_block` mirrors duties.produce_block_unsigned with the
relay's ExecutionPayloadHeader in place of a local payload;
`unblind_signed_block` grafts the relay-returned payload back into a
full SignedBeaconBlock, verifying it matches the committed header.
"""

from __future__ import annotations

from grandine_tpu.consensus import accessors, signing
from grandine_tpu.transition.block import payload_header_fields
from grandine_tpu.transition.combined import blinded_state_transition
from grandine_tpu.transition.fork_upgrade import state_phase
from grandine_tpu.transition.slots import process_slots
from grandine_tpu.types.containers import spec_types
from grandine_tpu.types.primitives import Phase


class UnblindError(Exception):
    pass


def header_from_bid(ns, bid_header: dict):
    """builder-specs bid header JSON → ExecutionPayloadHeader. The
    conversion is driven by each FIELD's SSZ type, not the JSON value's
    Python type — builder-specs serializes uint64 fields as DECIMAL
    strings ("30000000"), which must parse as ints, never as hex."""
    from grandine_tpu.ssz.base import UInt

    fields = {}
    for name, typ in ns.ExecutionPayloadHeader.FIELDS:
        if name not in bid_header:
            raise KeyError(f"bid header missing {name}")
        v = bid_header[name]
        if isinstance(typ, UInt):
            fields[name] = int(v)
        else:
            fields[name] = bytes.fromhex(str(v).removeprefix("0x"))
    return ns.ExecutionPayloadHeader(**fields)


#: builder-specs DOMAIN_APPLICATION_BUILDER (reference
#: builder_api/src/consts.rs:15).
DOMAIN_APPLICATION_BUILDER = bytes.fromhex("00000001")

_BID_CLASSES: dict = {}


def _builder_bid_class(header_cls, commitments: bool, max_commitments: int):
    """Per-fork BuilderBid container (builder-specs; reference
    builder_api/src/{bellatrix,capella,deneb}/containers.rs — deneb inserts
    blob_kzg_commitments between header and value)."""
    from grandine_tpu.consensus.misc import _container
    from grandine_tpu.ssz import Bytes48, List, uint256

    key = (header_cls, commitments, max_commitments)
    cls = _BID_CLASSES.get(key)
    if cls is None:
        fields: dict = {"header": header_cls}
        if commitments:
            fields["blob_kzg_commitments"] = List(Bytes48, max_commitments)
        fields["value"] = uint256
        fields["pubkey"] = Bytes48
        cls = _container("BuilderBid", fields)
        _BID_CLASSES[key] = cls
    return cls


def builder_bid_signing_root(
    header, value: int, pubkey: bytes, cfg, blob_kzg_commitments=None
) -> bytes:
    """Signing root of a builder bid: compute_domain(
    DOMAIN_APPLICATION_BUILDER, genesis_fork_version, zero root) — the
    reference's SignForAllForks impl for BuilderBid
    (builder_api/src/signing.rs:11-27, helper_functions signing.rs:59-64)."""
    from grandine_tpu.consensus.misc import compute_domain, compute_signing_root

    has_commitments = blob_kzg_commitments is not None
    bid_cls = _builder_bid_class(
        type(header), has_commitments,
        cfg.preset.MAX_BLOB_COMMITMENTS_PER_BLOCK,
    )
    fields = dict(header=header, value=int(value), pubkey=bytes(pubkey))
    if has_commitments:
        fields["blob_kzg_commitments"] = [
            bytes(c) for c in blob_kzg_commitments
        ]
    domain = compute_domain(
        DOMAIN_APPLICATION_BUILDER, cfg.genesis_fork_version
    )
    return compute_signing_root(bid_cls(**fields), domain)


def header_to_bid(header) -> dict:
    """ExecutionPayloadHeader → builder-specs bid header JSON (hex for
    byte fields, decimal strings for uints — the wire format a real
    relay serves)."""
    from grandine_tpu.ssz.base import UInt

    out = {}
    for name, typ in type(header).FIELDS:
        v = getattr(header, name)
        if isinstance(typ, UInt):
            out[name] = str(int(v))
        else:
            out[name] = "0x" + bytes(v).hex()
    return out


def produce_blinded_block(
    state,
    slot: int,
    cfg,
    payload_header,
    randao_reveal: bytes,
    attestations=(),
    sync_aggregate=None,
    graffiti: bytes = b"",
    proposer_slashings=(),
    attester_slashings=(),
    voluntary_exits=(),
    bls_to_execution_changes=(),
    deposits=(),
):
    """Unsigned BlindedBeaconBlock on `state` with the relay's payload
    header; returns (blinded_block, pre_state, post_state)."""
    from grandine_tpu.consensus.verifier import NullVerifier
    from grandine_tpu.validator.duties import empty_sync_aggregate

    p = cfg.preset
    if int(state.slot) < slot:
        state = process_slots(state, slot, cfg)
    phase = state_phase(state, cfg)
    if phase < Phase.BELLATRIX:
        raise ValueError("blinded blocks require bellatrix")
    ns = getattr(spec_types(p), phase.key)
    proposer_index = accessors.get_beacon_proposer_index(state, p)

    body_fields = dict(
        randao_reveal=bytes(randao_reveal),
        eth1_data=state.eth1_data,
        graffiti=graffiti.ljust(32, b"\x00")[:32],
        proposer_slashings=proposer_slashings,
        attester_slashings=attester_slashings,
        attestations=attestations,
        deposits=deposits,
        voluntary_exits=voluntary_exits,
        sync_aggregate=sync_aggregate
        if sync_aggregate is not None
        else empty_sync_aggregate(state, cfg),
        execution_payload_header=payload_header,
    )
    if phase >= Phase.CAPELLA:
        body_fields["bls_to_execution_changes"] = bls_to_execution_changes

    from grandine_tpu.validator.duties import parent_root_of

    body = ns.BlindedBeaconBlockBody(**body_fields)
    block = ns.BlindedBeaconBlock(
        slot=slot,
        proposer_index=proposer_index,
        parent_root=parent_root_of(state),
        state_root=b"\x00" * 32,
        body=body,
    )
    post = blinded_state_transition(
        state,
        ns.SignedBlindedBeaconBlock(message=block),
        cfg,
        NullVerifier(),
        state_root_policy="trust",
    )
    block = block.replace(state_root=post.hash_tree_root())
    return block, state, post


def unblind_signed_block(signed_blinded_block, execution_payload, cfg):
    """SignedBlindedBeaconBlock + relay payload → full SignedBeaconBlock
    (validator.rs:3091-3104). The payload must hash to the header the
    proposer committed to — a mismatching relay response is rejected."""
    block = signed_blinded_block.message
    phase = cfg.phase_at_slot(int(block.slot))
    ns = getattr(spec_types(cfg.preset), phase.key)
    committed = block.body.execution_payload_header
    derived = ns.ExecutionPayloadHeader(
        **payload_header_fields(execution_payload, phase)
    )
    if derived.hash_tree_root() != committed.hash_tree_root():
        raise UnblindError(
            "relay payload does not match the committed header"
        )
    body_fields = {
        name: getattr(block.body, name)
        for name, _ in ns.BlindedBeaconBlockBody.FIELDS
        if name != "execution_payload_header"
    }
    body_fields["execution_payload"] = execution_payload
    full_block = ns.BeaconBlock(
        slot=int(block.slot),
        proposer_index=int(block.proposer_index),
        parent_root=bytes(block.parent_root),
        state_root=bytes(block.state_root),
        body=ns.BeaconBlockBody(**body_fields),
    )
    return ns.SignedBeaconBlock(
        message=full_block, signature=bytes(signed_blinded_block.signature)
    )


def blinded_block_signing_root(state, blinded_block, cfg) -> bytes:
    """Same domain as a full block (DOMAIN_BEACON_PROPOSER over the
    blinded block's root)."""
    return signing.block_signing_root(state, blinded_block, cfg)


__all__ = [
    "UnblindError",
    "header_from_bid",
    "header_to_bid",
    "produce_blinded_block",
    "unblind_signed_block",
    "blinded_block_signing_root",
]
