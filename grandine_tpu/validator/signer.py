"""Key registry + batch signing — reference: signer/src/signer.rs
(`Signer` :40-49 key registry: local `SecretKey` OR remote Web3Signer per
pubkey; `sign` :154, batch `sign_triples` :173-229 fanning local keys to
rayon and remote keys to Web3Signer futures).

Local keys sign on host (anchor) or as one device batch through
`TpuBlsBackend.batch_sign`. Remote keys go through an injected Web3Signer
client (`web3signer` callable: (pubkey_hex, signing_root_hex) -> sig_hex —
the HTTP boundary, like every other I/O seam in this framework).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

from grandine_tpu.crypto import bls as A


class Signer:
    """pubkey-bytes -> local SecretKey or remote Web3Signer registry."""

    #: remote fan-out concurrency — one shared pool per Signer, NOT one
    #: per sign_triples call (a per-call pool leaked its threads when a
    #: remote future raised before shutdown)
    _REMOTE_WORKERS = 8

    def __init__(self, use_device: bool = False, backend=None,
                 web3signer: "Optional[Callable]" = None,
                 sign_plane=None) -> None:
        self._keys: "dict[bytes, A.SecretKey]" = {}
        self._remote: "set[bytes]" = set()
        self._use_device = use_device
        self._backend = backend
        self._web3signer = web3signer
        #: optional SigningPlane (runtime/sign_plane.py): when wired,
        #: sign_triples' local leg rides the plane's scheduled batches
        #: (release gate + slashing interlock included) instead of a
        #: private device batch; a shed/dropped ticket falls back to the
        #: signer's own host anchor so the duty is still produced
        self._sign_plane = sign_plane
        self._remote_pool = None  # lazy; see _remote_executor

    def _remote_executor(self):
        """The shared bounded pool for Web3Signer fan-out. Created on
        first remote signing, reused for the Signer's lifetime, shut
        down by close() — an exception in a remote future can no longer
        strand a per-call pool's threads."""
        if self._remote_pool is None:
            import concurrent.futures

            self._remote_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=self._REMOTE_WORKERS,
                thread_name_prefix="web3signer",
            )
        return self._remote_pool

    def close(self) -> None:
        """Shut down the shared remote-signing pool (idempotent)."""
        pool, self._remote_pool = self._remote_pool, None
        if pool is not None:
            pool.shutdown(wait=True, cancel_futures=True)

    # -- registry ----------------------------------------------------------

    def add_key(self, secret_key: "A.SecretKey") -> bytes:
        pk = secret_key.public_key().to_bytes()
        self._keys[pk] = secret_key
        self._remote.discard(pk)  # local signing supersedes remote
        return pk

    def add_remote_key(self, pubkey: bytes) -> None:
        """Register a key signed by the Web3Signer client
        (signer.rs KeyOrigin::Web3Signer). A key already registered
        locally stays local (no double registration)."""
        if self._web3signer is None:
            raise ValueError("no web3signer client configured")
        pubkey = bytes(pubkey)
        if pubkey not in self._keys:
            self._remote.add(pubkey)

    def remove_key(self, pubkey: bytes) -> bool:
        pubkey = bytes(pubkey)
        removed = self._keys.pop(pubkey, None) is not None
        if pubkey in self._remote:
            self._remote.discard(pubkey)
            removed = True
        return removed

    def secret_key(self, pubkey: bytes) -> "Optional[A.SecretKey]":
        """The local SecretKey for `pubkey`, or None when the key is
        remote/unknown (the signing plane needs the raw key; remote keys
        stay on the Web3Signer path)."""
        return self._keys.get(bytes(pubkey))

    def has_key(self, pubkey: bytes) -> bool:
        pubkey = bytes(pubkey)
        return pubkey in self._keys or pubkey in self._remote

    def pubkeys(self) -> "list[bytes]":
        return list(self._keys) + sorted(self._remote)

    def remote_pubkeys(self) -> "list[bytes]":
        return sorted(self._remote)

    def __len__(self) -> int:
        return len(self._keys) + len(self._remote)

    # -- signing -----------------------------------------------------------

    def sign(self, pubkey: bytes, signing_root: bytes) -> bytes:
        pubkey = bytes(pubkey)
        sk = self._keys.get(pubkey)
        if sk is not None:
            return sk.sign(signing_root).to_bytes()
        if pubkey in self._remote:
            return self._sign_remote(pubkey, signing_root)
        raise KeyError(f"no key for {pubkey.hex()[:16]}…")

    def _sign_remote(self, pubkey: bytes, signing_root: bytes) -> bytes:
        sig_hex = self._web3signer(pubkey.hex(), bytes(signing_root).hex())
        sig = bytes.fromhex(sig_hex.removeprefix("0x"))
        if len(sig) != 96:
            raise ValueError("web3signer returned a malformed signature")
        return sig

    def sign_triples(
        self, items: "Sequence[tuple[bytes, bytes]]"
    ) -> "list[bytes]":
        """Batch sign (pubkey, signing_root) pairs — signer.rs sign_triples:
        local keys as ONE device batch (or host loop), remote keys fanned
        out CONCURRENTLY to the Web3Signer client (the reference fans
        remote signings into futures alongside the local batch);
        results keep input order.

        With a `sign_plane` wired, the local leg is submitted as plane
        tickets that batch/settle WHILE the remote fan-out is in
        flight; a ticket the plane sheds (overload, shutdown) degrades
        to the signer's own host signing so no duty is lost."""
        local_idx, local_sks, out = [], [], [None] * len(items)
        remote_idx = []
        for i, (pubkey, root) in enumerate(items):
            pubkey = bytes(pubkey)
            sk = self._keys.get(pubkey)
            if sk is not None:
                local_idx.append(i)
                local_sks.append(sk)
            elif pubkey in self._remote:
                remote_idx.append(i)
            else:
                raise KeyError(f"no key for {pubkey.hex()[:16]}…")
        remote_futures = []
        if remote_idx:
            pool = self._remote_executor()
            remote_futures = [
                (i, pool.submit(
                    self._sign_remote, bytes(items[i][0]), items[i][1]
                ))
                for i in remote_idx
            ]
        try:
            if self._sign_plane is not None and local_idx:
                # plane tickets enqueue first so the device batch forms
                # while the Web3Signer round-trips overlap it
                plane_tickets = [
                    (i, sk, self._sign_plane.submit(
                        bytes(items[i][1]), sk
                    ))
                    for i, sk in zip(local_idx, local_sks)
                ]
                for i, future in remote_futures:
                    out[i] = future.result()
                for i, sk, tk in plane_tickets:
                    try:
                        out[i] = tk.result()
                    except RuntimeError:
                        # shed at overload/shutdown: the signer's own
                        # host anchor still produces the duty
                        out[i] = sk.sign(bytes(items[i][1])).to_bytes()
                return out
            if self._use_device and len(local_idx) > 1:
                backend = self._backend
                if backend is None:
                    from grandine_tpu.tpu.bls import TpuBlsBackend

                    backend = self._backend = TpuBlsBackend()
                sigs = backend.batch_sign(
                    [bytes(items[i][1]) for i in local_idx], local_sks
                )
                for i, s in zip(local_idx, sigs):
                    out[i] = s.to_bytes()
            else:
                for i, sk in zip(local_idx, local_sks):
                    out[i] = sk.sign(bytes(items[i][1])).to_bytes()
            for i, future in remote_futures:
                out[i] = future.result()
        except BaseException:
            # a failing remote (or device) must not leave sibling
            # futures running against a half-built result
            for _, future in remote_futures:
                future.cancel()
            raise
        return out


__all__ = ["Signer"]
