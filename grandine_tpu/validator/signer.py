"""Key registry + batch signing — reference: signer/src/signer.rs
(`Signer` :40-49 key registry, `sign` :154, batch `sign_triples` :173-229).

Local keys sign either on host (anchor, one at a time) or as one device
batch through `TpuBlsBackend.batch_sign` (the signer/src rayon fan-out
mapped onto the accelerator's batch axis). Remote/Web3Signer keys are out
of scope for this build (the registry records the kind for parity).
"""

from __future__ import annotations

from typing import Optional, Sequence

from grandine_tpu.crypto import bls as A


class Signer:
    """pubkey-bytes -> SecretKey registry with single and batch signing."""

    def __init__(self, use_device: bool = False, backend=None) -> None:
        self._keys: "dict[bytes, A.SecretKey]" = {}
        self._use_device = use_device
        self._backend = backend

    # -- registry ----------------------------------------------------------

    def add_key(self, secret_key: "A.SecretKey") -> bytes:
        pk = secret_key.public_key().to_bytes()
        self._keys[pk] = secret_key
        return pk

    def remove_key(self, pubkey: bytes) -> bool:
        return self._keys.pop(bytes(pubkey), None) is not None

    def has_key(self, pubkey: bytes) -> bool:
        return bytes(pubkey) in self._keys

    def pubkeys(self) -> "list[bytes]":
        return list(self._keys)

    def __len__(self) -> int:
        return len(self._keys)

    # -- signing -----------------------------------------------------------

    def sign(self, pubkey: bytes, signing_root: bytes) -> bytes:
        sk = self._keys.get(bytes(pubkey))
        if sk is None:
            raise KeyError(f"no key for {bytes(pubkey).hex()[:16]}…")
        return sk.sign(signing_root).to_bytes()

    def sign_triples(
        self, items: "Sequence[tuple[bytes, bytes]]"
    ) -> "list[bytes]":
        """Batch sign (pubkey, signing_root) pairs — signer.rs sign_triples.
        Device path: ONE `batch_sign_kernel` launch for all N items."""
        sks = []
        for pubkey, _root in items:
            sk = self._keys.get(bytes(pubkey))
            if sk is None:
                raise KeyError(f"no key for {bytes(pubkey).hex()[:16]}…")
            sks.append(sk)
        if self._use_device and len(items) > 1:
            backend = self._backend
            if backend is None:
                from grandine_tpu.tpu.bls import TpuBlsBackend

                backend = self._backend = TpuBlsBackend()
            sigs = backend.batch_sign([root for _, root in items], sks)
            return [s.to_bytes() for s in sigs]
        return [
            sk.sign(bytes(root)).to_bytes() for sk, (_, root) in zip(sks, items)
        ]


__all__ = ["Signer"]
