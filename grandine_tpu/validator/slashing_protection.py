"""Slashing protection — reference: `slashing_protection` crate (EIP-3076
interchange format + min/max source/target tracking validated against the
slashing-protection-interchange-tests submodule).

Rules enforced (EIP-3076):
  blocks:       never sign a slot <= the recorded minimum-allowed slot
                (double proposal / rollback protection)
  attestations: never sign source > target, a double vote (same target,
                different data), or a surround vote in either direction.

Backed by the Database layer (in-memory or sqlite) so restarts keep
history; import/export speaks the EIP-3076 JSON interchange format.
"""

from __future__ import annotations

import json
from typing import Optional

from grandine_tpu.storage.database import Database

_PREFIX_BLOCK = b"sp:b:"       # pubkey -> last signed block slot (8B LE)
_PREFIX_ATT = b"sp:a:"         # pubkey -> json [ [source, target], ... ]
_KEY_GVR = b"sp:gvr"


class SlashingProtectionError(Exception):
    """Signing refused: it would violate slashing protection."""


class SlashingProtection:
    def __init__(self, database: "Optional[Database]" = None,
                 genesis_validators_root: bytes = b"\x00" * 32) -> None:
        self.db = database or Database.in_memory()
        stored = self.db.get(_KEY_GVR)
        if stored is None:
            self.db.put(_KEY_GVR, genesis_validators_root)
        elif bytes(stored) != bytes(genesis_validators_root):
            raise SlashingProtectionError(
                "database belongs to a different chain "
                f"({bytes(stored).hex()[:16]}…)"
            )

    # -------------------------------------------------------------- blocks

    def check_and_insert_block(self, pubkey: bytes, slot: int) -> None:
        key = _PREFIX_BLOCK + bytes(pubkey)
        prev = self.db.get(key)
        if prev is not None and slot <= int.from_bytes(prev, "little"):
            raise SlashingProtectionError(
                f"block slot {slot} <= previously signed "
                f"{int.from_bytes(prev, 'little')}"
            )
        self.db.put(key, int(slot).to_bytes(8, "little"))

    # -------------------------------------------------------- attestations

    def _att_history(self, pubkey: bytes) -> "list[list[int]]":
        raw = self.db.get(_PREFIX_ATT + bytes(pubkey))
        return json.loads(raw) if raw else []

    def check_and_insert_attestation(
        self, pubkey: bytes, source_epoch: int, target_epoch: int
    ) -> None:
        if source_epoch > target_epoch:
            raise SlashingProtectionError("source epoch after target epoch")
        history = self._att_history(pubkey)
        for s, t in history:
            if t == target_epoch:
                raise SlashingProtectionError(
                    f"double vote for target epoch {target_epoch}"
                )
            if s < source_epoch and target_epoch < t:
                raise SlashingProtectionError("attestation would be surrounded")
            if source_epoch < s and t < target_epoch:
                raise SlashingProtectionError("attestation would surround")
        # EIP-3076 minimal guard: never sign sources/targets older than the
        # recorded minimums
        if history:
            min_source = min(s for s, _ in history)
            min_target = min(t for _, t in history)
            if source_epoch < min_source:
                raise SlashingProtectionError("source below recorded minimum")
            if target_epoch < min_target:
                raise SlashingProtectionError("target below recorded minimum")
        history.append([source_epoch, target_epoch])
        # bounded history: keep the most RECENT targets (signing order is
        # target-monotonic under the min-target guard above, so recency ==
        # largest targets; dropping older pairs cannot un-detect a double
        # vote for a still-reachable target)
        if len(history) > 1024:
            history = sorted(history, key=lambda st: st[1])[-1024:]
        self.db.put(
            _PREFIX_ATT + bytes(pubkey), json.dumps(history).encode()
        )

    # --------------------------------------------------------- interchange

    def export_interchange(self) -> dict:
        """EIP-3076 interchange JSON (complete format)."""
        data = []
        seen = set()
        for key, raw in self.db.iterate_prefix(_PREFIX_BLOCK):
            pubkey = key[len(_PREFIX_BLOCK):]
            seen.add(pubkey)
        for key, raw in self.db.iterate_prefix(_PREFIX_ATT):
            seen.add(key[len(_PREFIX_ATT):])
        for pubkey in sorted(seen):
            blocks = []
            raw = self.db.get(_PREFIX_BLOCK + pubkey)
            if raw is not None:
                blocks.append(
                    {"slot": str(int.from_bytes(raw, "little"))}
                )
            atts = [
                {"source_epoch": str(s), "target_epoch": str(t)}
                for s, t in self._att_history(pubkey)
            ]
            data.append({
                "pubkey": "0x" + pubkey.hex(),
                "signed_blocks": blocks,
                "signed_attestations": atts,
            })
        gvr = self.db.get(_KEY_GVR) or b"\x00" * 32
        return {
            "metadata": {
                "interchange_format_version": "5",
                "genesis_validators_root": "0x" + bytes(gvr).hex(),
            },
            "data": data,
        }

    def import_interchange(self, interchange: dict) -> None:
        meta = interchange.get("metadata", {})
        gvr = bytes.fromhex(
            meta.get("genesis_validators_root", "0x" + "00" * 32)[2:]
        )
        stored = self.db.get(_KEY_GVR)
        if stored is not None and bytes(stored) != gvr:
            raise SlashingProtectionError(
                "interchange genesis_validators_root mismatch"
            )
        for record in interchange.get("data", []):
            pubkey = bytes.fromhex(record["pubkey"][2:])
            max_slot = max(
                (int(b["slot"]) for b in record.get("signed_blocks", [])),
                default=None,
            )
            if max_slot is not None:
                cur = self.db.get(_PREFIX_BLOCK + pubkey)
                if cur is None or int.from_bytes(cur, "little") < max_slot:
                    self.db.put(
                        _PREFIX_BLOCK + pubkey,
                        max_slot.to_bytes(8, "little"),
                    )
            history = self._att_history(pubkey)
            known = {(s, t) for s, t in history}
            for a in record.get("signed_attestations", []):
                pair = (int(a["source_epoch"]), int(a["target_epoch"]))
                if pair not in known:
                    history.append(list(pair))
                    known.add(pair)
            if history:
                self.db.put(
                    _PREFIX_ATT + pubkey, json.dumps(history).encode()
                )


__all__ = ["SlashingProtection", "SlashingProtectionError"]
