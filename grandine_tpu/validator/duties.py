"""Duty production: blocks, attestations, sync aggregates — reference:
validator/src/validator.rs (`propose` :1292, `build_beacon_block` :1007,
`attest_and_start_aggregating` :1492, sync-committee duties :1751-2213).

These functions produce *valid* objects against a head state: the block
producer advances slots, builds a body (matching execution payload for
post-merge forks, expected-withdrawals sweep, sync aggregate), runs the
trusted transition to fill in the state root, and signs. They power the
in-process chain used by tests, the runtime, and the block-replay bench.
"""

from __future__ import annotations

import hashlib
from typing import Callable, Optional, Sequence

import numpy as np

from grandine_tpu.consensus import accessors, misc, signing
from grandine_tpu.consensus.verifier import NullVerifier
from grandine_tpu.crypto import bls as A
from grandine_tpu.transition import block as block_mod
from grandine_tpu.transition.fork_upgrade import state_phase
from grandine_tpu.transition.slots import process_slots
from grandine_tpu.types.containers import spec_types
from grandine_tpu.types.primitives import Phase

KeyProvider = Callable[[int], "A.SecretKey"]

#: aggregate-construction seam: groups of signatures in → one aggregate
#: per group out. None means the host anchor (`A.Signature.aggregate`
#: per group — the differential twin); `device_aggregator()` routes all
#: groups through ONE `g2_aggregate_groups` kernel dispatch.
Aggregator = Callable[
    ["Sequence[Sequence[A.Signature]]"], "list[A.Signature]"
]


def _interop_keys(index: int) -> "A.SecretKey":
    from grandine_tpu.transition.genesis import interop_secret_key

    return interop_secret_key(index)


def host_aggregator(groups) -> "list[A.Signature]":
    """The host twin of `device_aggregator` (same shape, loop per
    group)."""
    return [A.Signature.aggregate(list(g)) for g in groups]


def device_aggregator(metrics=None) -> Aggregator:
    """An Aggregator backed by the on-device contiguous-group G2 sum
    (`tpu.bls.g2_aggregate_groups`): every committee of the slot lands
    in one kernel dispatch instead of one host point-loop each."""
    from grandine_tpu.tpu import bls as B

    def _aggregate(groups):
        groups = [list(g) for g in groups]
        if not groups:
            return []
        return B.g2_aggregate_groups(groups, metrics=metrics)

    return _aggregate


# ------------------------------------------------------------- attestations


def produce_attestations(
    state,
    cfg,
    keys: KeyProvider = _interop_keys,
    slot: "Optional[int]" = None,
    participation: float = 1.0,
    aggregate: "Optional[Aggregator]" = None,
):
    """One aggregate attestation per committee of `slot` (default: the
    state's current slot), signed by the first `participation` fraction of
    each committee. `state` must be at or past `slot` (committees and the
    head vote are read from it). `aggregate` routes aggregate
    CONSTRUCTION (all committees as one batch) — None is the host
    anchor."""
    p = cfg.preset
    if slot is None:
        slot = int(state.slot)
    epoch = misc.compute_epoch_at_slot(slot, p)
    cur = accessors.get_current_epoch(state, p)
    phase = state_phase(state, cfg)
    ns = getattr(spec_types(p), phase.key)

    if slot == int(state.slot):
        # attesting to the head at its own slot: the block root is the
        # latest header with its state root filled in
        header = state.latest_block_header
        if bytes(header.state_root) == b"\x00" * 32:
            header = header.replace(state_root=state.hash_tree_root())
        head_root = header.hash_tree_root()
    else:
        head_root = accessors.get_block_root_at_slot(state, slot, p)

    target_slot = misc.compute_start_slot_at_epoch(epoch, p)
    if target_slot == slot:
        target_root = head_root
    else:
        target_root = accessors.get_block_root_at_slot(state, target_slot, p)
    source = (
        state.current_justified_checkpoint
        if epoch == cur
        else state.previous_justified_checkpoint
    )

    count = accessors.get_committee_count_per_slot(state, epoch, p)
    pending = []  # (data, bits, committee signature group)
    for index in range(count):
        committee = accessors.get_beacon_committee(state, slot, index, p)
        data = ns.AttestationData(
            slot=slot,
            index=index,
            beacon_block_root=head_root,
            source=source,
            target=ns.Checkpoint(epoch=epoch, root=target_root),
        )
        root = signing.attestation_signing_root(state, data, cfg)
        n_sign = max(1, int(len(committee) * participation))
        bits = np.zeros(len(committee), dtype=bool)
        bits[:n_sign] = True
        sigs = [keys(int(v)).sign(root) for v in committee[:n_sign]]
        pending.append((data, bits, sigs))
    # aggregate construction: all committees of the slot in one pass
    if aggregate is not None:
        aggs = aggregate([sigs for _, _, sigs in pending])
    else:
        aggs = host_aggregator([sigs for _, _, sigs in pending])
    return [
        ns.Attestation(
            aggregation_bits=bits,
            data=data,
            signature=agg.to_bytes(),
        )
        for (data, bits, _), agg in zip(pending, aggs)
    ]


# ----------------------------------------------------------- sync aggregate


def produce_sync_aggregate(state, cfg, keys: KeyProvider = _interop_keys,
                           aggregate: "Optional[Aggregator]" = None):
    """Full-participation sync aggregate for a block built on `state`
    (signs the previous block root under DOMAIN_SYNC_COMMITTEE).
    `aggregate` routes the committee-wide G2 sum (one single-group
    device dispatch) — None is the host anchor."""
    p = cfg.preset
    phase = state_phase(state, cfg)
    ns = getattr(spec_types(p), phase.key)
    lookup = {
        pk: i
        for i, pk in enumerate(accessors.registry_columns(state).pubkeys)
    }
    root = signing.sync_aggregate_signing_root(state, cfg)
    sigs = []
    bits = np.ones(p.SYNC_COMMITTEE_SIZE, dtype=bool)
    for pk in state.current_sync_committee.pubkeys:
        index = lookup[bytes(pk)]
        sigs.append(keys(index).sign(root))
    if aggregate is not None:
        agg = aggregate([sigs])[0]
    else:
        agg = A.Signature.aggregate(sigs)
    return ns.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=agg.to_bytes(),
    )


def empty_sync_aggregate(state, cfg):
    p = cfg.preset
    ns = getattr(spec_types(p), state_phase(state, cfg).key)
    return ns.SyncAggregate(
        sync_committee_bits=np.zeros(p.SYNC_COMMITTEE_SIZE, dtype=bool),
        sync_committee_signature=A.Signature.empty().to_bytes(),
    )


# ------------------------------------------------------------------ payload


def build_matching_payload(state, cfg, ns, phase: Phase):
    """Execution payload consistent with the (slot-advanced) pre-state:
    right parent hash, prev_randao, timestamp; synthetic block hash."""
    p = cfg.preset
    slot = int(state.slot)
    prev = state.latest_execution_payload_header
    fields = dict(
        parent_hash=bytes(prev.block_hash),
        prev_randao=misc.get_randao_mix(
            state, accessors.get_current_epoch(state, p), p
        ),
        block_number=int(prev.block_number) + 1,
        timestamp=int(state.genesis_time) + slot * cfg.seconds_per_slot,
        block_hash=hashlib.sha256(b"payload@%d" % slot).digest(),
        gas_limit=30_000_000,
    )
    if phase >= Phase.CAPELLA:
        from grandine_tpu.consensus.mutators import StateDraft

        draft = StateDraft(state, cfg)
        fields["withdrawals"] = block_mod.get_expected_withdrawals(
            state, draft, ns
        )
    return ns.ExecutionPayload(**fields)


# -------------------------------------------------------------------- block


def parent_root_of(state) -> bytes:
    """Root of the chain's latest block as seen from `state`: the latest
    header with its state_root backfilled if still zeroed (it is zeroed
    until the next block's slot processing fills it)."""
    header = state.latest_block_header
    if bytes(header.state_root) == b"\x00" * 32:
        header = header.replace(state_root=state.hash_tree_root())
    return header.hash_tree_root()


def produce_block_unsigned(
    state,
    slot: int,
    cfg,
    randao_reveal: bytes,
    keys: KeyProvider = _interop_keys,
    attestations: "Sequence" = (),
    full_sync_participation: bool = True,
    deposits: "Sequence" = (),
    voluntary_exits: "Sequence" = (),
    proposer_slashings: "Sequence" = (),
    attester_slashings: "Sequence" = (),
    bls_to_execution_changes: "Sequence" = (),
    graffiti: bytes = b"",
    sync_aggregate=None,
    blob_kzg_commitments: "Sequence" = (),
):
    """Build an UNSIGNED BeaconBlock for `slot` with a caller-provided
    `randao_reveal` — the Beacon API produce-block path
    (validator.rs:1007 build_beacon_block; the API hands us the reveal,
    the caller signs the block). Returns (block, pre_state, post_state):
    `block` carries the computed post-state root."""
    from grandine_tpu.transition.combined import custom_state_transition

    p = cfg.preset
    if int(state.slot) < slot:
        state = process_slots(state, slot, cfg)
    phase = state_phase(state, cfg)
    ns = getattr(spec_types(p), phase.key)

    proposer_index = accessors.get_beacon_proposer_index(state, p)

    body_fields = dict(
        randao_reveal=bytes(randao_reveal),
        eth1_data=state.eth1_data,
        graffiti=graffiti.ljust(32, b"\x00")[:32],
        proposer_slashings=proposer_slashings,
        attester_slashings=attester_slashings,
        attestations=attestations,
        deposits=deposits,
        voluntary_exits=voluntary_exits,
    )
    if phase >= Phase.ALTAIR:
        if sync_aggregate is not None:
            body_fields["sync_aggregate"] = sync_aggregate
        else:
            body_fields["sync_aggregate"] = (
                produce_sync_aggregate(state, cfg, keys)
                if full_sync_participation
                else empty_sync_aggregate(state, cfg)
            )
    if phase >= Phase.BELLATRIX:
        body_fields["execution_payload"] = build_matching_payload(
            state, cfg, ns, phase
        )
    if phase >= Phase.CAPELLA:
        body_fields["bls_to_execution_changes"] = bls_to_execution_changes
    if phase >= Phase.DENEB:
        body_fields["blob_kzg_commitments"] = [
            bytes(c) for c in blob_kzg_commitments
        ]

    body = ns.BeaconBlockBody(**body_fields)
    block = ns.BeaconBlock(
        slot=slot,
        proposer_index=proposer_index,
        parent_root=parent_root_of(state),
        state_root=b"\x00" * 32,
        body=body,
    )

    unsigned = ns.SignedBeaconBlock(message=block)
    post = custom_state_transition(
        state, unsigned, cfg, NullVerifier(), state_root_policy="trust"
    )
    block = block.replace(state_root=post.hash_tree_root())
    return block, state, post


def produce_block(
    state,
    slot: int,
    cfg,
    keys: KeyProvider = _interop_keys,
    **kwargs,
):
    """Produce a valid SignedBeaconBlock for `slot` on top of `state`
    (validator.rs propose :1292 → build_beacon_block :1007). Returns
    (signed_block, post_state)."""
    p = cfg.preset
    if int(state.slot) < slot:
        state = process_slots(state, slot, cfg)
    proposer_index = accessors.get_beacon_proposer_index(state, p)
    proposer_key = keys(proposer_index)
    epoch = accessors.get_current_epoch(state, p)
    reveal = proposer_key.sign(
        signing.randao_signing_root(state, epoch, cfg)
    ).to_bytes()
    block, pre, post = produce_block_unsigned(
        state, slot, cfg, reveal, keys=keys, **kwargs
    )
    phase = state_phase(pre, cfg)
    ns = getattr(spec_types(p), phase.key)
    signature = proposer_key.sign(
        signing.block_signing_root(pre, block, cfg)
    ).to_bytes()
    return ns.SignedBeaconBlock(message=block, signature=signature), post


__all__ = [
    "produce_attestations",
    "produce_sync_aggregate",
    "empty_sync_aggregate",
    "host_aggregator",
    "device_aggregator",
    "build_matching_payload",
    "produce_block_unsigned",
    "produce_block",
]
