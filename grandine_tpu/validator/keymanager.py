"""Keymanager — reference: `keymanager` crate (keystore import/export
keystores.rs, remote keys remote_keys.rs, proposer configs
proposer_configs.rs serving the keymanager API) and `eip_2335` (keystore
crypto: scrypt/PBKDF2 + AES-128-CTR).

EIP-2335 keystores are implemented with hashlib.scrypt / pbkdf2_hmac and a
CTR-mode AES built on the stdlib — no external crypto dependency. The
checksum is SHA-256 per the spec.
"""

from __future__ import annotations

import hashlib
import json
import os
import secrets
import uuid
from typing import Optional

from grandine_tpu.crypto import bls as A

# --- minimal AES-128 (encryption only, used in CTR mode) -------------------

_SBOX = None


def _build_sbox():
    global _SBOX
    if _SBOX is not None:
        return _SBOX
    sbox = [0] * 256
    p = q = 1
    sbox[0] = 0x63
    while True:
        # multiply p by 3 in GF(2^8)
        p = p ^ ((p << 1) & 0xFF) ^ (0x1B if p & 0x80 else 0)
        # divide q by 3
        q ^= (q << 1) & 0xFF
        q ^= (q << 2) & 0xFF
        q ^= (q << 4) & 0xFF
        q ^= 0x09 if q & 0x80 else 0
        x = q ^ ((q << 1) | (q >> 7)) & 0xFF ^ ((q << 2) | (q >> 6)) & 0xFF \
            ^ ((q << 3) | (q >> 5)) & 0xFF ^ ((q << 4) | (q >> 4)) & 0xFF
        sbox[p] = (x ^ 0x63) & 0xFF
        if p == 1:
            break
    _SBOX = sbox
    return sbox


def _aes128_expand_key(key: bytes):
    sbox = _build_sbox()
    rcon = 1
    words = [list(key[i : i + 4]) for i in range(0, 16, 4)]
    for i in range(4, 44):
        w = list(words[i - 1])
        if i % 4 == 0:
            w = w[1:] + w[:1]
            w = [sbox[b] for b in w]
            w[0] ^= rcon
            rcon = ((rcon << 1) ^ 0x1B) & 0xFF if rcon & 0x80 else rcon << 1
        words.append([a ^ b for a, b in zip(words[i - 4], w)])
    return words


def _aes128_encrypt_block(block: bytes, words) -> bytes:
    sbox = _build_sbox()
    state = [list(block[i::4]) for i in range(4)]  # column-major

    def add_round_key(rnd):
        for c in range(4):
            for r in range(4):
                state[r][c] ^= words[rnd * 4 + c][r]

    def sub_shift():
        for r in range(4):
            row = [sbox[b] for b in state[r]]
            state[r] = row[r:] + row[:r]

    def xtime(b):
        return ((b << 1) ^ 0x1B) & 0xFF if b & 0x80 else b << 1

    def mix():
        for c in range(4):
            a = [state[r][c] for r in range(4)]
            state[0][c] = xtime(a[0]) ^ (xtime(a[1]) ^ a[1]) ^ a[2] ^ a[3]
            state[1][c] = a[0] ^ xtime(a[1]) ^ (xtime(a[2]) ^ a[2]) ^ a[3]
            state[2][c] = a[0] ^ a[1] ^ xtime(a[2]) ^ (xtime(a[3]) ^ a[3])
            state[3][c] = (xtime(a[0]) ^ a[0]) ^ a[1] ^ a[2] ^ xtime(a[3])

    add_round_key(0)
    for rnd in range(1, 10):
        sub_shift()
        mix()
        add_round_key(rnd)
    sub_shift()
    add_round_key(10)
    return bytes(state[r][c] for c in range(4) for r in range(4))


def _aes128_ctr(key: bytes, iv: bytes, data: bytes) -> bytes:
    words = _aes128_expand_key(key)
    out = bytearray()
    counter = int.from_bytes(iv, "big")
    for i in range(0, len(data), 16):
        keystream = _aes128_encrypt_block(
            counter.to_bytes(16, "big"), words
        )
        chunk = data[i : i + 16]
        out += bytes(a ^ b for a, b in zip(chunk, keystream))
        counter = (counter + 1) % (1 << 128)
    return bytes(out)


# --- EIP-2335 keystores -----------------------------------------------------


def encrypt_keystore(
    secret_key: "A.SecretKey",
    password: str,
    path: str = "m/12381/3600/0/0/0",
    kdf: str = "pbkdf2",
) -> dict:
    """EIP-2335 keystore JSON (pbkdf2 or scrypt KDF)."""
    salt = secrets.token_bytes(32)
    if kdf == "scrypt":
        dk = hashlib.scrypt(
            password.encode(), salt=salt, n=262144, r=8, p=1, dklen=32,
            maxmem=512 * 1024 * 1024,
        )
        kdf_module = {
            "function": "scrypt",
            "params": {"dklen": 32, "n": 262144, "p": 1, "r": 8,
                       "salt": salt.hex()},
            "message": "",
        }
    else:
        dk = hashlib.pbkdf2_hmac(
            "sha256", password.encode(), salt, 262144, dklen=32
        )
        kdf_module = {
            "function": "pbkdf2",
            "params": {"dklen": 32, "c": 262144, "prf": "hmac-sha256",
                       "salt": salt.hex()},
            "message": "",
        }
    iv = secrets.token_bytes(16)
    secret = secret_key.to_bytes()
    cipher_text = _aes128_ctr(dk[:16], iv, secret)
    checksum = hashlib.sha256(dk[16:32] + cipher_text).digest()
    return {
        "crypto": {
            "kdf": kdf_module,
            "checksum": {"function": "sha256", "params": {},
                         "message": checksum.hex()},
            "cipher": {"function": "aes-128-ctr", "params": {"iv": iv.hex()},
                       "message": cipher_text.hex()},
        },
        "path": path,
        "pubkey": secret_key.public_key().to_bytes().hex(),
        "uuid": str(uuid.uuid4()),
        "version": 4,
    }


def decrypt_keystore(keystore: dict, password: str) -> "A.SecretKey":
    crypto = keystore["crypto"]
    kdf = crypto["kdf"]
    params = kdf["params"]
    salt = bytes.fromhex(params["salt"])
    if kdf["function"] == "scrypt":
        dk = hashlib.scrypt(
            password.encode(), salt=salt, n=params["n"], r=params["r"],
            p=params["p"], dklen=params["dklen"],
            maxmem=512 * 1024 * 1024,
        )
    elif kdf["function"] == "pbkdf2":
        dk = hashlib.pbkdf2_hmac(
            "sha256", password.encode(), salt, params["c"],
            dklen=params["dklen"],
        )
    else:
        raise ValueError(f"unsupported KDF {kdf['function']}")
    cipher_text = bytes.fromhex(crypto["cipher"]["message"])
    checksum = hashlib.sha256(dk[16:32] + cipher_text).digest()
    if checksum.hex() != crypto["checksum"]["message"]:
        raise ValueError("keystore checksum mismatch (wrong password?)")
    iv = bytes.fromhex(crypto["cipher"]["params"]["iv"])
    secret = _aes128_ctr(dk[:16], iv, cipher_text)
    return A.SecretKey.from_bytes(secret)


# --- keymanager surface -----------------------------------------------------


class KeyManager:
    """Local keystore import/export + proposer configs (the keymanager-API
    backend: keystores.rs, proposer_configs.rs). An optional
    `ValidatorKeyCache` (validator/key_cache.py) skips the per-keystore
    KDF on re-import after a restart."""

    def __init__(self, signer, slashing_protection=None, key_cache=None) -> None:
        self.signer = signer
        self.slashing_protection = slashing_protection
        self.key_cache = key_cache
        self.proposer_configs: "dict[bytes, dict]" = {}

    def import_keystores(
        self, keystores: "list[dict]", passwords: "list[str]"
    ) -> "list[dict]":
        out = []
        cache_dirty = False
        for ks, pw in zip(keystores, passwords):
            try:
                sk = None
                ks_pk = ks.get("pubkey")
                if self.key_cache is not None and ks_pk:
                    try:
                        sk = self.key_cache.get(
                            bytes.fromhex(str(ks_pk).removeprefix("0x")), pw
                        )
                    except Exception:
                        sk = None  # cache trouble must not block the KDF path
                if sk is None:
                    sk = decrypt_keystore(ks, pw)
                pk = self.signer.add_key(sk)
                if self.key_cache is not None:
                    if self.key_cache.put(pk, sk, pw):
                        cache_dirty = True
                out.append({"status": "imported",
                            "message": "0x" + pk.hex()})
            except Exception as e:
                out.append({"status": "error", "message": repr(e)})
        if cache_dirty:
            try:
                self.key_cache.save()
            except OSError:
                pass  # the cache is an optimization, not the key store
        return out

    def list_keystores(self) -> "list[dict]":
        return [
            {"validating_pubkey": "0x" + pk.hex(), "derivation_path": "",
             "readonly": False}
            for pk in self.signer.pubkeys()
        ]

    def delete_keystores(self, pubkeys: "list[bytes]") -> "list[dict]":
        out = []
        for pk in pubkeys:
            removed = self.signer.remove_key(pk)
            out.append({"status": "deleted" if removed else "not_found"})
        return out

    # -- remote (Web3Signer) keys — keymanager remote_keys.rs ---------------

    def list_remote_keys(self) -> "list[dict]":
        return [
            {"pubkey": "0x" + pk.hex(), "url": "", "readonly": False}
            for pk in self.signer.remote_pubkeys()
        ]

    def import_remote_keys(self, remote_keys: "list[dict]") -> "list[dict]":
        out = []
        for entry in remote_keys:
            try:
                pk = bytes.fromhex(entry["pubkey"].removeprefix("0x"))
                if len(pk) != 48:
                    raise ValueError("pubkey must be 48 bytes")
                already = self.signer.has_key(pk)
                self.signer.add_remote_key(pk)
                out.append({"status": "duplicate" if already else "imported"})
            except Exception as e:
                out.append({"status": "error", "message": repr(e)})
        return out

    def delete_remote_keys(self, pubkeys: "list[bytes]") -> "list[dict]":
        out = []
        for pk in pubkeys:
            pk = bytes(pk)
            if pk in self.signer.remote_pubkeys():
                self.signer.remove_key(pk)
                out.append({"status": "deleted"})
            else:
                out.append({"status": "not_found"})
        return out

    def set_fee_recipient(self, pubkey: bytes, address: bytes) -> None:
        self.proposer_configs.setdefault(bytes(pubkey), {})[
            "fee_recipient"
        ] = bytes(address)

    def set_gas_limit(self, pubkey: bytes, gas_limit: int) -> None:
        self.proposer_configs.setdefault(bytes(pubkey), {})[
            "gas_limit"
        ] = int(gas_limit)

    def set_graffiti(self, pubkey: bytes, graffiti: bytes) -> None:
        self.proposer_configs.setdefault(bytes(pubkey), {})[
            "graffiti"
        ] = bytes(graffiti)

    def proposer_config(self, pubkey: bytes) -> dict:
        return dict(self.proposer_configs.get(bytes(pubkey), {}))

    def delete_proposer_field(self, pubkey: bytes, field: str) -> bool:
        cfg = self.proposer_configs.get(bytes(pubkey))
        if cfg is None or field not in cfg:
            return False
        del cfg[field]
        if not cfg:
            del self.proposer_configs[bytes(pubkey)]
        return True


__all__ = [
    "encrypt_keystore",
    "decrypt_keystore",
    "KeyManager",
]
