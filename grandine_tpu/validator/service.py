"""Tick-driven validator service — reference: validator/src/validator.rs
(`run` :290 / `handle_tick` :645-770: Propose/Attest/Aggregate branches;
propose :1292 with pool-packed attestations and eth1 votes; attestation
production :1492; aggregate publication :1646), threading the signer,
slashing protection, operation pools and network publishing together.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from grandine_tpu.consensus import accessors, misc, signing
from grandine_tpu.crypto import bls as A
from grandine_tpu.fork_choice.store import Tick, TickKind
from grandine_tpu.transition.fork_upgrade import state_phase
from grandine_tpu.transition.slots import process_slots
from grandine_tpu.types.containers import spec_types
from grandine_tpu.runtime.sign_plane import SignRefused
from grandine_tpu.validator.slashing_protection import (
    SlashingProtection,
    SlashingProtectionError,
)


class _PostSignFailure(Exception):
    """Builder flow failed AFTER the blinded block was signed — the relay
    may hold a valid signature, so no other block may be signed for this
    slot."""


class ValidatorService:
    """Drives duties for every key in the signer registry."""

    def __init__(
        self,
        controller,
        signer,
        cfg,
        slashing_protection: "Optional[SlashingProtection]" = None,
        attestation_pool=None,
        operation_pool=None,
        sync_pool=None,
        eth1_cache=None,
        network=None,
        subnet_service=None,
        builder_api=None,
        sign_plane=None,
        plane_timeout_s: float = 30.0,
    ) -> None:
        self.controller = controller
        self.signer = signer
        self.cfg = cfg
        self.p = cfg.preset
        self.slashing_protection = slashing_protection or SlashingProtection()
        self.attestation_pool = attestation_pool
        self.operation_pool = operation_pool
        self.sync_pool = sync_pool
        self.eth1_cache = eth1_cache
        self.network = network
        self.subnet_service = subnet_service
        self.builder_api = builder_api
        #: optional runtime.sign_plane.SigningPlane: local-key duty
        #: signings coalesce into device batches (remote keys keep the
        #: Web3Signer path through the signer)
        self.sign_plane = sign_plane
        self.plane_timeout_s = float(plane_timeout_s)
        self.stats = {"proposed": 0, "attested": 0, "aggregated": 0,
                      "slashing_refusals": 0}

    # -- plane routing ------------------------------------------------------

    def _sign_duty(self, pubkey: bytes, signing_root: bytes,
                   duty_kind: str, index: "Optional[int]" = None) -> bytes:
        """One duty signature: through the signing plane when one is
        wired and the key is local, else the signer's own path. A
        dropped plane ticket (shutdown/shed) degrades to the signer —
        the duty is never missed."""
        if self.sign_plane is not None:
            sk = self.signer.secret_key(pubkey)
            if sk is not None:
                ticket = self.sign_plane.submit(
                    signing_root, sk, duty_kind=duty_kind, index=index
                )
                try:
                    return ticket.result(self.plane_timeout_s)
                except RuntimeError:
                    pass  # dropped → host path below
        return self.signer.sign(pubkey, signing_root)

    def _sign_duty_batch(self, to_sign, duty_kind: str) -> "list[bytes]":
        """Batch duty signatures for (pubkey, signing_root) pairs —
        plane-coalesced when every key is local, else the signer's
        sign_triples (which still device-batches local keys)."""
        if self.sign_plane is not None and to_sign:
            sks = [self.signer.secret_key(pk) for pk, _ in to_sign]
            if all(sk is not None for sk in sks):
                tickets = [
                    self.sign_plane.submit(root, sk, duty_kind=duty_kind)
                    for (_, root), sk in zip(to_sign, sks)
                ]
                out = []
                for (pk, root), ticket in zip(to_sign, tickets):
                    try:
                        out.append(ticket.result(self.plane_timeout_s))
                    except RuntimeError:
                        out.append(self.signer.sign(pk, root))
                return out
        return self.signer.sign_triples(to_sign)

    # -- index resolution ---------------------------------------------------

    def _own_indices(self, state) -> "dict[int, bytes]":
        cols = accessors.registry_columns(state)
        owned = {}
        for i, pk in enumerate(cols.pubkeys):
            if self.signer.has_key(pk):
                owned[i] = pk
        return owned

    # -- tick dispatch ------------------------------------------------------

    def handle_tick(self, tick: Tick) -> None:
        if tick.kind == TickKind.PROPOSE:
            if self.subnet_service is not None:
                self.subnet_service.on_slot(tick.slot)
            self.maybe_propose(tick.slot)
        elif tick.kind == TickKind.ATTEST:
            self.attest(tick.slot)
            self.sync_committee_messages(tick.slot)
        elif tick.kind == TickKind.AGGREGATE:
            self.aggregate(tick.slot)

    # -- propose ------------------------------------------------------------

    def maybe_propose(self, slot: int):
        """Build, protect, sign and submit a block if one of our keys is
        the proposer (validator.rs propose :1292)."""
        pre = self.controller.state_at_slot(slot)  # StateCache advancer
        proposer_index = accessors.get_beacon_proposer_index(pre, self.p)
        owned = self._own_indices(pre)
        pubkey = owned.get(proposer_index)
        if pubkey is None:
            return None
        try:
            self.slashing_protection.check_and_insert_block(pubkey, slot)
        except SlashingProtectionError:
            self.stats["slashing_refusals"] += 1
            return None

        from grandine_tpu.eth1 import DepositCacheError

        # builder (MEV) path first when configured and the circuit
        # breaker allows (validator.rs:948 builder-vs-local selection).
        # Fallback to local building is ONLY safe before the blinded
        # block is signed: once a signature exists the relay may hold
        # (and publish) it, and signing a second, different block for
        # the same slot is a slashable equivocation — post-sign failures
        # abort the proposal instead.
        if self.builder_api is not None and self.builder_api.can_use_builder(
            self.controller, slot, self.p.SLOTS_PER_EPOCH
        ):
            try:
                signed_block = self._build_blinded_block(
                    pre, slot, proposer_index, pubkey
                )
            except _PostSignFailure:
                self.stats["builder_aborts"] = (
                    self.stats.get("builder_aborts", 0) + 1
                )
                return None
            except Exception:
                signed_block = None
                self.stats["builder_fallbacks"] = (
                    self.stats.get("builder_fallbacks", 0) + 1
                )
            if signed_block is not None:
                self.controller.on_own_block(signed_block)
                if self.network is not None:
                    self.network.publish_block(signed_block)
                self.stats["proposed"] += 1
                self.stats["builder_blocks"] = (
                    self.stats.get("builder_blocks", 0) + 1
                )
                return signed_block

        try:
            signed_block = self._build_block(pre, slot, proposer_index, pubkey)
        except SignRefused:
            # the plane's interlock watermark (persisted) outlived this
            # process's slashing-protection view — refuse the proposal
            self.stats["slashing_refusals"] += 1
            return None
        except DepositCacheError:
            # the deposit cache is behind the state's required deposits: an
            # invalid block would be worse than no block (any OTHER failure
            # propagates — silent skipping would mask real bugs)
            self.stats["skipped_proposals"] = (
                self.stats.get("skipped_proposals", 0) + 1
            )
            return None
        self.controller.on_own_block(signed_block)
        if self.network is not None:
            self.network.publish_block(signed_block)
        self.stats["proposed"] += 1
        return signed_block

    def _build_block(self, pre, slot: int, proposer_index: int, pubkey: bytes):
        """build_beacon_block (:1007): eth1 vote + pool ops + packed
        attestations + payload + sync aggregate, then state root + sign."""
        from grandine_tpu.consensus.mutators import StateDraft
        from grandine_tpu.consensus.verifier import NullVerifier
        from grandine_tpu.transition import block as block_mod
        from grandine_tpu.transition.combined import custom_state_transition

        phase = state_phase(pre, self.cfg)
        ns = getattr(spec_types(self.p), phase.key)
        epoch = accessors.get_current_epoch(pre, self.p)

        reveal = self._sign_duty(
            pubkey, signing.randao_signing_root(pre, epoch, self.cfg),
            "randao",
        )

        attestations = (
            self.attestation_pool.pack_attestations(pre, self.cfg, slot=slot)
            if self.attestation_pool is not None
            else []
        )
        ops = (
            self.operation_pool.pack(pre)
            if self.operation_pool is not None
            else {"proposer_slashings": [], "attester_slashings": [],
                  "voluntary_exits": [], "bls_to_execution_changes": []}
        )
        from grandine_tpu.eth1 import select_eth1_vote

        candidates = []
        if (
            self.eth1_cache is not None
            and self.eth1_cache.deposit_count > int(pre.eth1_data.deposit_count)
        ):
            candidates.append(self.eth1_cache.eth1_data(ns))
        eth1_data = select_eth1_vote(pre, candidates, self.cfg)
        deposits = (
            self.eth1_cache.deposits_for_block(pre, ns)
            if self.eth1_cache is not None
            else []
        )

        from grandine_tpu.types.primitives import Phase
        from grandine_tpu.validator.duties import (
            build_matching_payload,
            empty_sync_aggregate,
        )

        body_fields = dict(
            randao_reveal=reveal,
            eth1_data=eth1_data,
            proposer_slashings=ops["proposer_slashings"],
            attester_slashings=ops["attester_slashings"],
            attestations=attestations,
            deposits=deposits,
            voluntary_exits=ops["voluntary_exits"],
        )
        if phase >= Phase.ALTAIR:
            prev_root = accessors.get_block_root_at_slot(
                pre, max(slot, 1) - 1, self.p
            ) if slot > 0 else b"\x00" * 32
            body_fields["sync_aggregate"] = (
                self.sync_pool.best_aggregate(max(slot, 1) - 1, prev_root, ns)
                if self.sync_pool is not None
                else empty_sync_aggregate(pre, self.cfg)
            )
        if phase >= Phase.BELLATRIX:
            body_fields["execution_payload"] = build_matching_payload(
                pre, self.cfg, ns, phase
            )
        if phase >= Phase.CAPELLA:
            body_fields["bls_to_execution_changes"] = ops[
                "bls_to_execution_changes"
            ]

        body = ns.BeaconBlockBody(**body_fields)
        header = pre.latest_block_header
        if bytes(header.state_root) == b"\x00" * 32:
            header = header.replace(state_root=pre.hash_tree_root())
        block = ns.BeaconBlock(
            slot=slot,
            proposer_index=proposer_index,
            parent_root=header.hash_tree_root(),
            state_root=b"\x00" * 32,
            body=body,
        )
        post = custom_state_transition(
            pre, ns.SignedBeaconBlock(message=block), self.cfg,
            NullVerifier(), state_root_policy="trust",
        )
        block = block.replace(state_root=post.hash_tree_root())
        sig = self._sign_duty(
            pubkey, signing.block_signing_root(pre, block, self.cfg),
            "block", index=slot,
        )
        return ns.SignedBeaconBlock(message=block, signature=sig)

    def _build_blinded_block(
        self, pre, slot: int, proposer_index: int, pubkey: bytes
    ):
        """Builder flow (validator.rs:3091-3104): getHeader → blinded
        block → sign → submitBlindedBlock → unblind. Returns the FULL
        SignedBeaconBlock (the blinded and full block share one signing
        root, so the signature carries over)."""
        from grandine_tpu.validator import blinded as blinded_mod

        phase = state_phase(pre, self.cfg)
        ns = getattr(spec_types(self.p), phase.key)
        if int(pre.slot) < slot:
            pre = process_slots(pre, slot, self.cfg)
        parent_hash = bytes(pre.latest_execution_payload_header.block_hash)
        bid = self.builder_api.get_execution_payload_header(
            slot, parent_hash, pubkey, ns=ns
        )
        header = blinded_mod.header_from_bid(ns, bid["header"])
        epoch = accessors.get_current_epoch(pre, self.p)
        reveal = self._sign_duty(
            pubkey, signing.randao_signing_root(pre, epoch, self.cfg),
            "randao",
        )
        attestations = (
            self.attestation_pool.pack_attestations(pre, self.cfg, slot=slot)
            if self.attestation_pool is not None
            else []
        )
        ops = (
            self.operation_pool.pack(pre)
            if self.operation_pool is not None
            else {}
        )
        deposits = (
            self.eth1_cache.deposits_for_block(pre, ns)
            if self.eth1_cache is not None
            else []
        )
        block, pre2, _post = blinded_mod.produce_blinded_block(
            pre,
            slot,
            self.cfg,
            header,
            reveal,
            attestations=attestations,
            deposits=deposits,
            proposer_slashings=ops.get("proposer_slashings", ()),
            attester_slashings=ops.get("attester_slashings", ()),
            voluntary_exits=ops.get("voluntary_exits", ()),
            bls_to_execution_changes=ops.get("bls_to_execution_changes", ()),
        )
        # ---- point of no return: from the signature on, a failure must
        # NOT fall back to local building (equivocation risk)
        try:
            sig = self._sign_duty(
                pubkey, signing.block_signing_root(pre2, block, self.cfg),
                "block", index=slot,
            )
            signed_blinded = ns.SignedBlindedBeaconBlock(
                message=block, signature=sig
            )
            response = self.builder_api.submit_blinded_block(signed_blinded)
            payload = ns.ExecutionPayload.deserialize(
                bytes.fromhex(
                    response["execution_payload"].removeprefix("0x")
                )
                if isinstance(response["execution_payload"], str)
                else bytes(response["execution_payload"])
            )
            return blinded_mod.unblind_signed_block(
                signed_blinded, payload, self.cfg
            )
        except Exception as e:
            raise _PostSignFailure(repr(e)) from e

    # -- attest -------------------------------------------------------------

    def attest(self, slot: int) -> list:
        """One attestation per owned committee member
        (attest_and_start_aggregating :1492), batch-signed through the
        signer (sign_triples — the device batch path when enabled)."""
        snapshot = self.controller.snapshot()
        # On an empty/missed slot the head block is behind the duty slot;
        # attest to the current head with the state *advanced* through the
        # empty slots (StateCache advancer), as the reference does — never
        # skip the duty (validator/src/validator.rs attest path).
        state = self.controller.state_at_slot(slot, snapshot=snapshot)
        p = self.p
        epoch = misc.compute_epoch_at_slot(slot, p)
        owned = self._own_indices(state)
        if not owned:
            return []

        head_root = snapshot.head_root
        target_slot = misc.compute_start_slot_at_epoch(epoch, p)
        target_root = (
            head_root
            if target_slot >= int(state.slot)
            else accessors.get_block_root_at_slot(state, target_slot, p)
        )
        phase = state_phase(state, self.cfg)
        ns = getattr(spec_types(p), phase.key)
        source = state.current_justified_checkpoint

        count = accessors.get_committee_count_per_slot(state, epoch, p)
        to_sign = []
        pending = []
        for index in range(count):
            committee = accessors.get_beacon_committee(state, slot, index, p)
            members = [
                (pos, int(v)) for pos, v in enumerate(committee)
                if int(v) in owned
            ]
            if not members:
                continue
            if self.subnet_service is not None:
                # own-duty subscription (own_attestation_subscriptions.rs)
                for _pos, vi in members:
                    self.subnet_service.subscribe_attestation(
                        validator_index=vi,
                        committee_index=index,
                        committees_at_slot=count,
                        slot=slot,
                        is_aggregator=True,
                    )
            data = ns.AttestationData(
                slot=slot, index=index, beacon_block_root=head_root,
                source=source,
                target=ns.Checkpoint(epoch=epoch, root=target_root),
            )
            root = signing.attestation_signing_root(state, data, self.cfg)
            for pos, vi in members:
                pubkey = owned[vi]
                try:
                    self.slashing_protection.check_and_insert_attestation(
                        pubkey, int(data.source.epoch), epoch
                    )
                except SlashingProtectionError:
                    self.stats["slashing_refusals"] += 1
                    continue
                to_sign.append((pubkey, root))
                pending.append((data, committee, pos))

        signatures = self._sign_duty_batch(to_sign, "attestation")
        out = []
        for (data, committee, pos), sig in zip(pending, signatures):
            bits = np.zeros(len(committee), dtype=bool)
            bits[pos] = True
            att = ns.Attestation(
                aggregation_bits=bits, data=data, signature=sig
            )
            out.append(att)
            if self.attestation_pool is not None:
                self.attestation_pool.insert(att)
            if self.network is not None:
                from grandine_tpu.p2p.subnets import compute_subnet_id

                self.network.publish_attestation(
                    att,
                    subnet=compute_subnet_id(
                        int(data.index), slot, count, p,
                        self.cfg.attestation_subnet_count,
                    ),
                )
        self.stats["attested"] += len(out)
        return out

    # -- sync committee -----------------------------------------------------

    def sync_committee_messages(self, slot: int) -> int:
        """Every owned member of the current sync committee signs the head
        root (validator.rs sync-committee duties :1751-2213), feeding the
        contribution pool for the NEXT slot's proposer."""
        if self.sync_pool is None:
            return 0
        snapshot = self.controller.snapshot()
        # advance to the duty slot: across a sync-committee period boundary
        # the head state's current_sync_committee would be the OLD period's
        state = self.controller.state_at_slot(slot)
        from grandine_tpu.types.primitives import Phase

        if state_phase(state, self.cfg) < Phase.ALTAIR:
            return 0
        head_root = snapshot.head_root
        epoch = misc.compute_epoch_at_slot(slot, self.p)
        # loop-invariant: one signing root serves every member
        root = signing.sync_committee_message_signing_root(
            state, head_root, epoch, self.cfg
        )
        to_sign = []
        positions = []
        for pos, pk in enumerate(state.current_sync_committee.pubkeys):
            pk = bytes(pk)
            if not self.signer.has_key(pk):
                continue
            to_sign.append((pk, root))
            positions.append(pos)
        if not to_sign:
            return 0
        if self.subnet_service is not None:
            # own sync-committee subscription until the period's end
            # (own_sync_committee_subscriptions.rs)
            period_epochs = self.p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
            until = (epoch // period_epochs + 1) * period_epochs
            self.subnet_service.subscribe_sync_committee(
                validator_index=-1,
                sync_committee_indices=positions,
                until_epoch=until,
            )
        signatures = self._sign_duty_batch(to_sign, "sync_message")
        for pos, sig in zip(positions, signatures):
            self.sync_pool.insert_message(slot, head_root, pos, sig)
        self.stats["sync_messages"] = (
            self.stats.get("sync_messages", 0) + len(positions)
        )
        return len(positions)

    # -- aggregate ----------------------------------------------------------

    def aggregate(self, slot: int) -> list:
        """Publish best-known aggregates for committees where an owned
        validator is the selected aggregator (publish_aggregates_and_proofs
        :1646 — selection via DOMAIN_SELECTION_PROOF hash modulo)."""
        if self.attestation_pool is None:
            return []
        snapshot = self.controller.snapshot()
        state = snapshot.head_state
        if int(state.slot) < slot:
            return []
        p = self.p
        epoch = misc.compute_epoch_at_slot(slot, p)
        owned = self._own_indices(state)
        phase = state_phase(state, self.cfg)
        ns = getattr(spec_types(p), phase.key)
        out = []
        count = accessors.get_committee_count_per_slot(state, epoch, p)
        for index in range(count):
            committee = accessors.get_beacon_committee(state, slot, index, p)
            members = [int(v) for v in committee if int(v) in owned]
            if not members:
                continue
            # member-independent: one pool lookup per committee
            best = self.attestation_pool.best_for_committee(slot, index)
            if best is None:
                continue
            for vi in members:
                pubkey = owned[vi]
                proof = self._sign_duty(
                    pubkey,
                    signing.selection_proof_signing_root(state, slot, self.cfg),
                    "selection_proof",
                )
                modulo = max(
                    1,
                    len(committee) // self.cfg.target_aggregators_per_committee,
                )
                if misc.bytes_to_uint64(misc.sha256(proof)[:8]) % modulo != 0:
                    continue  # not the aggregator
                aap = ns.AggregateAndProof(
                    aggregator_index=vi, aggregate=best,
                    selection_proof=proof,
                )
                sig = self._sign_duty(
                    pubkey,
                    signing.aggregate_and_proof_signing_root(
                        state, aap, self.cfg
                    ),
                    "aggregate",
                )
                signed = ns.SignedAggregateAndProof(message=aap, signature=sig)
                out.append(signed)
                if self.network is not None:
                    self.network.publish_aggregate(signed)
        self.stats["aggregated"] += len(out)
        return out


__all__ = ["ValidatorService"]
