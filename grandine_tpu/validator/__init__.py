"""Validator duty engine — reference: `validator` crate
(validator/src/validator.rs: propose/attest/aggregate driven by clock
ticks) plus the `signer` key registry.

`duties.py` holds the duty *production* functions (blocks, attestations,
sync aggregates); `signer.py` the key registry with device batch signing;
the tick-driven service loop lives in grandine_tpu.runtime.
"""

from grandine_tpu.validator.duties import (  # noqa: F401
    produce_attestations,
    produce_block,
    produce_sync_aggregate,
)
from grandine_tpu.validator.signer import Signer  # noqa: F401
