"""Phase0 epoch processing — reference:
transition_functions/src/phase0/epoch_processing.rs (pending-attestation
matching, component deltas, inclusion-delay rewards, inactivity penalties).

The per-attestation committee expansion reuses the globally-cached
committee partitions; all per-validator accounting is numpy over registry
columns.
"""

from __future__ import annotations

import numpy as np

from grandine_tpu.consensus import accessors, misc, mutators
from grandine_tpu.consensus.mutators import StateDraft
from grandine_tpu.transition import epoch_common
from grandine_tpu.types.primitives import GENESIS_EPOCH, Phase

BASE_REWARDS_PER_EPOCH = 4


def _base_rewards(state, p) -> np.ndarray:
    """Phase0 per-validator base reward column."""
    cols = accessors.registry_columns(state)
    total = accessors.get_total_active_balance(state, p)
    sqrt_total = misc.integer_squareroot(total)
    return (
        cols.effective_balance.astype(np.int64)
        * p.BASE_REWARD_FACTOR
        // sqrt_total
        // BASE_REWARDS_PER_EPOCH
    )


def _matching_attestations(state, epoch: int, p):
    cur = accessors.get_current_epoch(state, p)
    if epoch == cur:
        return list(state.current_epoch_attestations)
    if epoch == accessors.get_previous_epoch(state, p):
        return list(state.previous_epoch_attestations)
    raise ValueError("attestations only tracked for current/previous epoch")


def _attesting_mask(state, attestations, p) -> np.ndarray:
    """Union of attesting indices (unslashed) as a registry mask."""
    cols = accessors.registry_columns(state)
    mask = np.zeros(len(cols), dtype=bool)
    for att in attestations:
        idx = accessors.get_attesting_indices(
            state, att.data, att.aggregation_bits, p
        )
        mask[idx] = True
    return mask & ~cols.slashed


def _matching_target(state, attestations, epoch: int, p):
    root = accessors.get_block_root(state, epoch, p)
    return [a for a in attestations if bytes(a.data.target.root) == root]


def _matching_head(state, attestations, epoch: int, p):
    return [
        a
        for a in _matching_target(state, attestations, epoch, p)
        if bytes(a.data.beacon_block_root)
        == accessors.get_block_root_at_slot(state, int(a.data.slot), p)
    ]


def process_justification_and_finalization(draft: StateDraft) -> None:
    state = object.__getattribute__(draft, "base")
    p = draft.p
    if accessors.get_current_epoch(state, p) <= GENESIS_EPOCH + 1:
        return
    prev = accessors.get_previous_epoch(state, p)
    cur = accessors.get_current_epoch(state, p)
    cols = accessors.registry_columns(state)
    eb = cols.effective_balance.astype(np.int64)

    def target_balance(epoch):
        atts = _matching_target(
            state, _matching_attestations(state, epoch, p), epoch, p
        )
        mask = _attesting_mask(state, atts, p)
        return max(p.EFFECTIVE_BALANCE_INCREMENT, int(eb[mask].sum()))

    epoch_common.weigh_justification_and_finalization(
        draft,
        accessors.get_total_active_balance(state, p),
        target_balance(prev),
        target_balance(cur),
    )


def process_rewards_and_penalties(draft: StateDraft) -> None:
    state = object.__getattribute__(draft, "base")
    p = draft.p
    if accessors.get_current_epoch(state, p) == GENESIS_EPOCH:
        return
    prev = accessors.get_previous_epoch(state, p)
    cols = accessors.registry_columns(state)
    n = len(cols)
    eb = cols.effective_balance.astype(np.int64)
    base = _base_rewards(state, p)
    eligible = epoch_common.get_eligible_validator_mask(state, p)
    total = accessors.get_total_active_balance(state, p)
    increment = p.EFFECTIVE_BALANCE_INCREMENT
    in_leak = epoch_common.is_in_inactivity_leak(state, p)

    rewards = np.zeros(n, dtype=np.int64)
    penalties = np.zeros(n, dtype=np.int64)

    source_atts = _matching_attestations(state, prev, p)
    target_atts = _matching_target(state, source_atts, prev, p)
    head_atts = _matching_head(state, source_atts, prev, p)

    # --- source/target/head component deltas
    for atts in (source_atts, target_atts, head_atts):
        mask = _attesting_mask(state, atts, p)
        attesting_balance = max(increment, int(eb[mask].sum()))
        got = eligible & mask
        missed = eligible & ~mask
        if in_leak:
            rewards[got] += base[got]
        else:
            rewards[got] += (
                base[got] * (attesting_balance // increment)
                // (total // increment)
            )
        penalties[missed] += base[missed]

    # --- inclusion-delay rewards (earliest source attestation per attester)
    best_delay = np.full(n, np.iinfo(np.int64).max, dtype=np.int64)
    best_proposer = np.full(n, -1, dtype=np.int64)
    source_mask = _attesting_mask(state, source_atts, p)
    for att in source_atts:
        idx = accessors.get_attesting_indices(
            state, att.data, att.aggregation_bits, p
        )
        delay = int(att.inclusion_delay)
        better = best_delay[idx] > delay
        upd = idx[better]
        best_delay[upd] = delay
        best_proposer[upd] = int(att.proposer_index)
    attesters = np.nonzero(source_mask)[0]
    proposer_rewards = base[attesters] // p.PROPOSER_REWARD_QUOTIENT
    for i, prop_reward in zip(attesters, proposer_rewards):
        rewards[best_proposer[i]] += int(prop_reward)
        max_attester = int(base[i]) - int(prop_reward)
        rewards[i] += max_attester // int(best_delay[i])

    # --- inactivity penalties
    if in_leak:
        delay = epoch_common.finality_delay(state, p)
        target_mask = _attesting_mask(state, target_atts, p)
        penalties[eligible] += (
            BASE_REWARDS_PER_EPOCH * base[eligible]
            - base[eligible] // p.PROPOSER_REWARD_QUOTIENT
        )
        missed_target = eligible & ~target_mask
        penalties[missed_target] += (
            eb[missed_target] * delay // p.INACTIVITY_PENALTY_QUOTIENT
        )

    balances = draft.balances_array
    net = balances.astype(np.int64) + rewards - penalties
    np.maximum(net, 0, out=net)
    balances[:] = net.astype(np.uint64)


def process_participation_record_updates(draft: StateDraft) -> None:
    draft.set("previous_epoch_attestations", draft.current_epoch_attestations)
    draft.set("current_epoch_attestations", ())


def process_epoch(state, cfg):
    """Phase0 `process_epoch` (transition_functions/src/phase0)."""
    p = cfg.preset
    draft = StateDraft(state, cfg)
    process_justification_and_finalization(draft)
    process_rewards_and_penalties(draft)
    epoch_common.process_registry_updates(draft, Phase.PHASE0)
    epoch_common.process_slashings(draft, Phase.PHASE0)
    epoch_common.process_eth1_data_reset(draft)
    epoch_common.process_effective_balance_updates(draft)
    epoch_common.process_slashings_reset(draft)
    epoch_common.process_randao_mixes_reset(draft)
    epoch_common.process_historical_roots_update(draft, Phase.PHASE0)
    process_participation_record_updates(draft)
    return draft.commit()


__all__ = ["process_epoch"]
