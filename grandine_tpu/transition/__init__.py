"""State-transition functions — the equivalent of the reference's
`transition_functions` crate (per-fork slot/epoch/block processing with the
fork-dispatching `combined` entry points and the verify-∥-process split).

Layout:
  genesis.py       — interop/genesis state construction (genesis/interop crates)
  slots.py         — process_slot(s) incl. epoch-boundary dispatch
  epoch_common.py  — justification/finality engine + final-updates shared code
  epoch_phase0.py  — pending-attestation-based epoch processing
  epoch_altair.py  — participation-flag epoch processing (altair..deneb)
  block.py         — per-fork block processing + signature collection
  combined.py      — fork dispatch: state_transition / untrusted_state_transition
"""

from grandine_tpu.transition.combined import (  # noqa: F401
    custom_state_transition,
    process_slots,
    state_transition,
    untrusted_state_transition,
    verify_signatures,
)
