"""Block processing, phase0..deneb — reference:
transition_functions/src/{phase0,altair,…}/block_processing.rs and
unphased/block_processing.rs (shared operation processing).

Structure mirrors the reference's verify-∥-process split: `collect_signatures`
builds every deferred signature check for a signed block into a Verifier
(the batch side), while `process_block` performs the state mutation with NO
pairing work inside. `combined.state_transition` overlaps the two: the
device batch is dispatched asynchronously before host-side processing runs
(the XLA-async equivalent of the reference's
`rayon::join(verify_signatures, process_block)`,
transition_functions/src/altair/state_transition.rs:65).

Raises TransitionError (structural) or SignatureInvalid (crypto) on
invalid blocks.
"""

from __future__ import annotations

import numpy as np

from grandine_tpu.consensus import accessors, misc, mutators, predicates, signing
from grandine_tpu.consensus.keys import decompress_pubkey
from grandine_tpu.consensus.mutators import StateDraft
from grandine_tpu.consensus.verifier import SignatureInvalid, Verifier
from grandine_tpu.crypto import bls as A
from grandine_tpu.types.primitives import (
    DEPOSIT_CONTRACT_TREE_DEPTH,
    ETH1_ADDRESS_WITHDRAWAL_PREFIX,
    FAR_FUTURE_EPOCH,
    PARTICIPATION_FLAG_WEIGHTS,
    PROPOSER_WEIGHT,
    SYNC_REWARD_WEIGHT,
    WEIGHT_DENOMINATOR,
    Phase,
)

ZERO32 = b"\x00" * 32


class TransitionError(ValueError):
    """Structurally invalid block/operation."""


def _require(cond: bool, what: str) -> None:
    if not cond:
        raise TransitionError(what)


# =========================================================== signature plane


def collect_signatures(state, signed_block, verifier: Verifier, cfg, phase: Phase):
    """Build every deferred signature check of `signed_block` against the
    (slot-advanced) pre-state into `verifier` — the verify half of the
    reference's per-fork `verify_signatures`
    (transition_functions/src/altair/state_transition.rs:72-197).

    Deposits are excluded: their proof-of-possession uses a fork-agnostic
    domain and an invalid deposit signature skips the deposit rather than
    invalidating the block (spec apply_deposit), so they are settled
    separately in process_operations.
    """
    block = signed_block.message
    body = block.body

    signing.extend_with_block_signature(verifier, state, signed_block, cfg)
    signing.extend_with_randao_reveal(verifier, state, block, cfg)

    for ps in body.proposer_slashings:
        for signed_header in (ps.signed_header_1, ps.signed_header_2):
            header = signed_header.message
            root = signing.header_signing_root(state, header, cfg)
            verifier.verify_singular(
                root,
                bytes(signed_header.signature),
                _registry_pubkey(state, int(header.proposer_index)),
            )

    for aslash in body.attester_slashings:
        for indexed in (aslash.attestation_1, aslash.attestation_2):
            signing.extend_with_indexed_attestation(verifier, state, indexed, cfg)

    from grandine_tpu.types.containers import spec_types

    ns = getattr(spec_types(cfg.preset), phase.key)
    for att in body.attestations:
        indexed = accessors.get_indexed_attestation(state, att, ns, cfg.preset)
        signing.extend_with_indexed_attestation(verifier, state, indexed, cfg)

    for exit_ in body.voluntary_exits:
        signing.extend_with_voluntary_exit(verifier, state, exit_, cfg, phase)

    if phase >= Phase.ALTAIR:
        signing.extend_with_sync_aggregate(verifier, state, body.sync_aggregate, cfg)

    if phase >= Phase.CAPELLA:
        for change in body.bls_to_execution_changes:
            signing.extend_with_bls_to_execution_change(verifier, state, change, cfg)


def _registry_pubkey(state, index: int):
    cols = accessors.registry_columns(state)
    if index >= len(cols):
        raise TransitionError(f"validator index {index} out of range")
    return decompress_pubkey(cols.pubkeys[index], trusted=True)


# ============================================================= block header


def process_block_header(draft: StateDraft, block) -> None:
    state = object.__getattribute__(draft, "base")
    p = draft.p
    _require(int(block.slot) == int(state.slot), "block slot != state slot")
    _require(
        int(block.slot) > int(state.latest_block_header.slot),
        "block not newer than latest header",
    )
    proposer_index = accessors.get_beacon_proposer_index(state, p)
    _require(
        int(block.proposer_index) == proposer_index,
        f"wrong proposer {int(block.proposer_index)} != {proposer_index}",
    )
    _require(
        bytes(block.parent_root) == state.latest_block_header.hash_tree_root(),
        "parent root mismatch",
    )
    proposer = draft.validator(proposer_index)
    _require(not bool(proposer.slashed), "proposer is slashed")
    Header = type(state.latest_block_header)
    draft.set(
        "latest_block_header",
        Header(
            slot=int(block.slot),
            proposer_index=proposer_index,
            parent_root=bytes(block.parent_root),
            state_root=ZERO32,
            body_root=block.body.hash_tree_root(),
        ),
    )


# ==================================================================== randao


def process_randao(draft: StateDraft, body) -> None:
    state = object.__getattribute__(draft, "base")
    p = draft.p
    epoch = accessors.get_current_epoch(state, p)
    mix = misc.xor(
        misc.get_randao_mix(state, epoch, p),
        misc.sha256(bytes(body.randao_reveal)),
    )
    mixes = draft.randao_mixes
    draft.set(
        "randao_mixes", mixes.set(epoch % p.EPOCHS_PER_HISTORICAL_VECTOR, mix)
    )


# ================================================================= eth1 data


def process_eth1_data(draft: StateDraft, body) -> None:
    p = draft.p
    votes = list(draft.eth1_data_votes)
    votes.append(body.eth1_data)
    period_slots = p.EPOCHS_PER_ETH1_VOTING_PERIOD * p.SLOTS_PER_EPOCH
    if sum(1 for v in votes if v == body.eth1_data) * 2 > period_slots:
        draft.set("eth1_data", body.eth1_data)
    draft.set("eth1_data_votes", tuple(votes))


# ================================================================ operations


def process_proposer_slashing(draft: StateDraft, ps, phase: Phase) -> None:
    state = object.__getattribute__(draft, "base")
    p = draft.p
    h1 = ps.signed_header_1.message
    h2 = ps.signed_header_2.message
    _require(int(h1.slot) == int(h2.slot), "proposer slashing: slot mismatch")
    _require(
        int(h1.proposer_index) == int(h2.proposer_index),
        "proposer slashing: proposer mismatch",
    )
    _require(h1 != h2, "proposer slashing: identical headers")
    index = int(h1.proposer_index)
    _require(index < draft.num_validators(), "proposer slashing: bad index")
    proposer = draft.validator(index)
    _require(
        predicates.is_slashable_validator(
            proposer, accessors.get_current_epoch(state, p)
        ),
        "proposer slashing: not slashable",
    )
    mutators.slash_validator(draft, index, phase)


def process_attester_slashing(draft: StateDraft, aslash, phase: Phase) -> "list[int]":
    state = object.__getattribute__(draft, "base")
    p = draft.p
    att1, att2 = aslash.attestation_1, aslash.attestation_2
    _require(
        predicates.is_slashable_attestation_data(att1.data, att2.data),
        "attester slashing: data not slashable",
    )
    # structural validity of both indexed attestations (signatures were
    # already deferred into the verifier by collect_signatures)
    for indexed in (att1, att2):
        indices = list(indexed.attesting_indices)
        _require(bool(indices), "attester slashing: empty indices")
        _require(
            indices == sorted(set(indices)), "attester slashing: unsorted indices"
        )
        _require(
            indices[-1] < draft.num_validators(),
            "attester slashing: index out of range",
        )
    epoch = accessors.get_current_epoch(state, p)
    slashed_any = []
    common = sorted(
        set(map(int, att1.attesting_indices))
        & set(map(int, att2.attesting_indices))
    )
    for index in common:
        if predicates.is_slashable_validator(draft.validator(index), epoch):
            mutators.slash_validator(draft, index, phase)
            slashed_any.append(index)
    _require(bool(slashed_any), "attester slashing: nobody slashed")
    return slashed_any


def _attestation_structural_checks(draft: StateDraft, att, phase: Phase):
    state = object.__getattribute__(draft, "base")
    p = draft.p
    data = att.data
    cur = accessors.get_current_epoch(state, p)
    prev = accessors.get_previous_epoch(state, p)
    target_epoch = int(data.target.epoch)
    _require(target_epoch in (prev, cur), "attestation: target epoch out of range")
    _require(
        target_epoch == misc.compute_epoch_at_slot(int(data.slot), p),
        "attestation: target epoch != slot epoch",
    )
    slot = int(data.slot)
    _require(
        slot + p.MIN_ATTESTATION_INCLUSION_DELAY <= int(state.slot),
        "attestation: too fresh",
    )
    if phase < Phase.DENEB:  # EIP-7045 removes the upper bound
        _require(
            int(state.slot) <= slot + p.SLOTS_PER_EPOCH, "attestation: too old"
        )
    _require(
        int(data.index)
        < accessors.get_committee_count_per_slot(state, target_epoch, p),
        "attestation: bad committee index",
    )
    committee = accessors.get_beacon_committee(state, slot, int(data.index), p)
    _require(
        len(att.aggregation_bits) == len(committee),
        "attestation: bits/committee size mismatch",
    )
    return committee


def process_attestation_phase0(draft: StateDraft, att, types_ns) -> None:
    state = object.__getattribute__(draft, "base")
    p = draft.p
    committee = _attestation_structural_checks(draft, att, Phase.PHASE0)
    data = att.data
    pending = types_ns.PendingAttestation(
        aggregation_bits=att.aggregation_bits,
        data=data,
        inclusion_delay=int(state.slot) - int(data.slot),
        proposer_index=accessors.get_beacon_proposer_index(state, p),
    )
    cur = accessors.get_current_epoch(state, p)
    if int(data.target.epoch) == cur:
        _require(
            data.source == state.current_justified_checkpoint,
            "attestation: source != current justified",
        )
        draft.set(
            "current_epoch_attestations",
            tuple(draft.current_epoch_attestations) + (pending,),
        )
    else:
        _require(
            data.source == state.previous_justified_checkpoint,
            "attestation: source != previous justified",
        )
        draft.set(
            "previous_epoch_attestations",
            tuple(draft.previous_epoch_attestations) + (pending,),
        )


def process_attestation_altair(draft: StateDraft, att, cfg, phase: Phase) -> None:
    state = object.__getattribute__(draft, "base")
    p = draft.p
    committee = _attestation_structural_checks(draft, att, phase)
    data = att.data
    inclusion_delay = int(state.slot) - int(data.slot)
    try:
        flag_indices = accessors.get_attestation_participation_flag_indices(
            state, data, inclusion_delay, cfg, phase
        )
    except ValueError as e:
        raise TransitionError(str(e)) from e

    attesting = committee[np.asarray(att.aggregation_bits.array, dtype=bool)]
    cur = accessors.get_current_epoch(state, p)
    col_name = (
        "current_epoch_participation"
        if int(data.target.epoch) == cur
        else "previous_epoch_participation"
    )
    participation = draft.array_field(col_name)
    base_per_increment = accessors.get_base_reward_per_increment(state, p)
    cols = accessors.registry_columns(state)
    increments = cols.effective_balance.astype(np.int64) // p.EFFECTIVE_BALANCE_INCREMENT

    proposer_reward_numerator = 0
    flags = participation[attesting].astype(np.int64)
    for flag_index in flag_indices:
        weight = PARTICIPATION_FLAG_WEIGHTS[flag_index]
        fresh = (flags >> flag_index) & 1 == 0
        if not fresh.any():
            continue
        idx = attesting[fresh]
        flags[fresh] |= 1 << flag_index
        proposer_reward_numerator += int(
            (increments[idx] * base_per_increment).sum()
        ) * weight
    participation[attesting] = flags.astype(participation.dtype)

    if proposer_reward_numerator:
        denominator = (
            (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
            * WEIGHT_DENOMINATOR
            // PROPOSER_WEIGHT
        )
        mutators.increase_balance(
            draft,
            accessors.get_beacon_proposer_index(state, p),
            proposer_reward_numerator // denominator,
        )


def process_deposit(draft: StateDraft, deposit, cfg, phase: Phase) -> None:
    """Spec `process_deposit`/`apply_deposit`: merkle proof against the
    eth1 deposit root, then top-up or new-validator with eager
    proof-of-possession (an invalid PoP skips the deposit, it does NOT
    invalidate the block — hence no Verifier deferral; reference batches
    these optimistically, unphased/block_processing.rs:376-404)."""
    p = draft.p
    leaf = deposit.data.hash_tree_root()
    _require(
        predicates.is_valid_merkle_branch(
            leaf,
            [bytes(b) for b in deposit.proof],
            DEPOSIT_CONTRACT_TREE_DEPTH + 1,
            int(draft.eth1_deposit_index),
            bytes(draft.eth1_data.deposit_root),
        ),
        "deposit: bad merkle proof",
    )
    draft.set("eth1_deposit_index", int(draft.eth1_deposit_index) + 1)
    apply_deposit(draft, deposit.data, cfg, phase)


def apply_deposit(draft: StateDraft, data, cfg, phase: Phase) -> None:
    p = draft.p
    pubkey = bytes(data.pubkey)
    amount = int(data.amount)
    index = _pubkey_index(draft, pubkey)
    if index is not None:
        mutators.increase_balance(draft, index, amount)
        return
    # new validator: verify proof of possession eagerly
    root = signing.deposit_signing_root(data, cfg)
    try:
        sig = A.Signature.from_bytes(bytes(data.signature))
        pk = A.PublicKey.from_bytes(pubkey)
    except A.BlsError:
        return  # malformed: skip deposit
    if not sig.verify(root, pk):
        return  # invalid PoP: skip deposit
    Validator = type(draft.validator(0)) if draft.num_validators() else None
    if Validator is None:
        from grandine_tpu.types.containers import spec_types

        Validator = spec_types(p).phase0.Validator
    new_validator = Validator(
        pubkey=pubkey,
        withdrawal_credentials=bytes(data.withdrawal_credentials),
        effective_balance=min(
            amount - amount % p.EFFECTIVE_BALANCE_INCREMENT,
            p.MAX_EFFECTIVE_BALANCE,
        ),
        slashed=False,
        activation_eligibility_epoch=FAR_FUTURE_EPOCH,
        activation_epoch=FAR_FUTURE_EPOCH,
        exit_epoch=FAR_FUTURE_EPOCH,
        withdrawable_epoch=FAR_FUTURE_EPOCH,
    )
    draft.append_validator(new_validator, amount)
    _register_pubkey(draft, pubkey, draft.num_validators() - 1)
    if phase >= Phase.ALTAIR:
        for name in ("previous_epoch_participation", "current_epoch_participation"):
            arr = draft.array_field(name)
            draft.set(name, np.append(arr, np.uint8(0)))
        scores = draft.array_field("inactivity_scores")
        draft.set("inactivity_scores", np.append(scores, np.uint64(0)))


def _pubkey_index(draft: StateDraft, pubkey: bytes) -> "int | None":
    state = object.__getattribute__(draft, "base")
    lookup = draft.scratch.get("pubkey_lookup")
    if lookup is None:
        cols = accessors.registry_columns(state)
        lookup = {pk: i for i, pk in enumerate(cols.pubkeys)}
        draft.scratch["pubkey_lookup"] = lookup
    return lookup.get(pubkey)


def _register_pubkey(draft: StateDraft, pubkey: bytes, index: int) -> None:
    lookup = draft.scratch.get("pubkey_lookup")
    if lookup is not None:
        lookup[pubkey] = index


def process_voluntary_exit(draft: StateDraft, signed_exit) -> None:
    state = object.__getattribute__(draft, "base")
    p, cfg = draft.p, draft.cfg
    exit_msg = signed_exit.message
    index = int(exit_msg.validator_index)
    _require(index < draft.num_validators(), "exit: bad index")
    validator = draft.validator(index)
    cur = accessors.get_current_epoch(state, p)
    _require(predicates.is_active_validator(validator, cur), "exit: not active")
    _require(
        int(validator.exit_epoch) == FAR_FUTURE_EPOCH, "exit: already exiting"
    )
    _require(cur >= int(exit_msg.epoch), "exit: epoch in the future")
    _require(
        cur >= int(validator.activation_epoch) + cfg.shard_committee_period,
        "exit: too young",
    )
    mutators.initiate_validator_exit(draft, index)


def process_bls_to_execution_change(draft: StateDraft, signed_change) -> None:
    change = signed_change.message
    index = int(change.validator_index)
    _require(index < draft.num_validators(), "bls change: bad index")
    validator = draft.validator(index)
    creds = bytes(validator.withdrawal_credentials)
    _require(creds[:1] == b"\x00", "bls change: not BLS credentials")
    _require(
        creds[1:] == misc.sha256(bytes(change.from_bls_pubkey))[1:],
        "bls change: pubkey does not match credentials",
    )
    draft.set_validator(
        index,
        validator.replace(
            withdrawal_credentials=(
                ETH1_ADDRESS_WITHDRAWAL_PREFIX
                + b"\x00" * 11
                + bytes(change.to_execution_address)
            )
        ),
    )


def process_operations(draft: StateDraft, body, cfg, phase: Phase, types_ns) -> None:
    state = object.__getattribute__(draft, "base")
    p = draft.p
    expected_deposits = min(
        p.MAX_DEPOSITS,
        int(state.eth1_data.deposit_count) - int(state.eth1_deposit_index),
    )
    _require(
        len(body.deposits) == expected_deposits,
        f"expected {expected_deposits} deposits, block has {len(body.deposits)}",
    )
    for ps in body.proposer_slashings:
        process_proposer_slashing(draft, ps, phase)
    for aslash in body.attester_slashings:
        process_attester_slashing(draft, aslash, phase)
    for att in body.attestations:
        if phase == Phase.PHASE0:
            process_attestation_phase0(draft, att, types_ns)
        else:
            process_attestation_altair(draft, att, cfg, phase)
    for deposit in body.deposits:
        process_deposit(draft, deposit, cfg, phase)
    for exit_ in body.voluntary_exits:
        process_voluntary_exit(draft, exit_)
    if phase >= Phase.CAPELLA:
        for change in body.bls_to_execution_changes:
            process_bls_to_execution_change(draft, change)


# ============================================================ sync aggregate


def process_sync_aggregate(draft: StateDraft, sync_aggregate) -> None:
    """Altair `process_sync_aggregate` reward flow (the signature was
    deferred by collect_signatures)."""
    state = object.__getattribute__(draft, "base")
    p = draft.p
    total_active_increments = (
        accessors.get_total_active_balance(state, p) // p.EFFECTIVE_BALANCE_INCREMENT
    )
    total_base_rewards = (
        accessors.get_base_reward_per_increment(state, p) * total_active_increments
    )
    max_participant_rewards = (
        total_base_rewards * SYNC_REWARD_WEIGHT
        // WEIGHT_DENOMINATOR
        // p.SLOTS_PER_EPOCH
    )
    participant_reward = max_participant_rewards // p.SYNC_COMMITTEE_SIZE
    proposer_reward = (
        participant_reward * PROPOSER_WEIGHT // (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
    )
    proposer_index = accessors.get_beacon_proposer_index(state, p)

    committee_pubkeys = [
        bytes(pk) for pk in state.current_sync_committee.pubkeys
    ]
    committee_indices = [
        _pubkey_index(draft, pk) for pk in committee_pubkeys
    ]
    bits = sync_aggregate.sync_committee_bits
    for participant_index, bit in zip(committee_indices, bits):
        _require(participant_index is not None, "sync committee pubkey unknown")
        if bit:
            mutators.increase_balance(draft, participant_index, participant_reward)
            mutators.increase_balance(draft, proposer_index, proposer_reward)
        else:
            mutators.decrease_balance(draft, participant_index, participant_reward)


# ======================================================== execution payload


def _is_merge_transition_complete(state) -> bool:
    header = state.latest_execution_payload_header
    return header != type(header)()


def process_withdrawals(draft: StateDraft, payload, types_ns) -> None:
    """Capella `process_withdrawals`: sweep, compare against payload, debit."""
    state = object.__getattribute__(draft, "base")
    expected = get_expected_withdrawals(state, draft, types_ns)
    got = list(payload.withdrawals)
    _require(
        len(got) == len(expected) and all(a == b for a, b in zip(got, expected)),
        "withdrawals: payload does not match expected sweep",
    )
    _apply_withdrawals_sweep(draft, state, expected)


def _apply_withdrawals_sweep(draft: StateDraft, state, expected) -> None:
    p = draft.p
    for w in expected:
        mutators.decrease_balance(draft, int(w.validator_index), int(w.amount))
    if expected:
        draft.set("next_withdrawal_index", int(expected[-1].index) + 1)
    n = draft.num_validators()
    if len(expected) == p.MAX_WITHDRAWALS_PER_PAYLOAD:
        draft.set(
            "next_withdrawal_validator_index",
            (int(expected[-1].validator_index) + 1) % n,
        )
    else:
        draft.set(
            "next_withdrawal_validator_index",
            (int(state.next_withdrawal_validator_index)
             + p.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP) % n,
        )


def get_expected_withdrawals(state, draft, types_ns) -> list:
    p = draft.p if draft is not None else None
    if p is None:
        raise ValueError("draft required")
    epoch = accessors.get_current_epoch(state, p)
    withdrawal_index = int(state.next_withdrawal_index)
    validator_index = int(state.next_withdrawal_validator_index)
    cols = accessors.registry_columns(state)
    balances = (
        draft.balances_array
        if object.__getattribute__(draft, "_balances") is not None
        else np.asarray(state.balances.array, dtype=np.uint64)
    )
    n = len(cols)
    out = []
    for _ in range(min(n, p.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)):
        balance = int(balances[validator_index])
        creds = cols.withdrawal_credentials[validator_index]
        has_eth1 = creds[:1] == ETH1_ADDRESS_WITHDRAWAL_PREFIX
        fully = (
            has_eth1
            and int(cols.withdrawable_epoch[validator_index]) <= epoch
            and balance > 0
        )
        partially = (
            has_eth1
            and int(cols.effective_balance[validator_index]) == p.MAX_EFFECTIVE_BALANCE
            and balance > p.MAX_EFFECTIVE_BALANCE
        )
        if fully or partially:
            out.append(
                types_ns.Withdrawal(
                    index=withdrawal_index,
                    validator_index=validator_index,
                    address=creds[12:],
                    amount=balance if fully else balance - p.MAX_EFFECTIVE_BALANCE,
                )
            )
            withdrawal_index += 1
        if len(out) == p.MAX_WITHDRAWALS_PER_PAYLOAD:
            break
        validator_index = (validator_index + 1) % n
    return out


def process_execution_payload(
    draft: StateDraft, body, cfg, phase: Phase, execution_engine, types_ns
) -> None:
    state = object.__getattribute__(draft, "base")
    p = draft.p
    payload = body.execution_payload
    if phase >= Phase.CAPELLA or _is_merge_transition_complete(state):
        _require(
            bytes(payload.parent_hash)
            == bytes(state.latest_execution_payload_header.block_hash),
            "payload: parent hash mismatch",
        )
    _require(
        bytes(payload.prev_randao)
        == misc.get_randao_mix(state, accessors.get_current_epoch(state, p), p),
        "payload: prev_randao mismatch",
    )
    expected_ts = int(state.genesis_time) + int(state.slot) * cfg.seconds_per_slot
    _require(int(payload.timestamp) == expected_ts, "payload: bad timestamp")
    if phase >= Phase.DENEB:
        _require(
            len(body.blob_kzg_commitments) <= p.MAX_BLOBS_PER_BLOCK,
            "too many blob commitments",
        )
    from grandine_tpu.execution import PayloadStatus

    status = execution_engine.notify_new_payload(payload)
    _require(
        status in (PayloadStatus.VALID, PayloadStatus.SYNCING, PayloadStatus.ACCEPTED),
        f"payload rejected by execution engine: {status}",
    )

    draft.set(
        "latest_execution_payload_header",
        types_ns.ExecutionPayloadHeader(
            **payload_header_fields(payload, phase)
        ),
    )


def payload_header_fields(payload, phase: Phase) -> dict:
    """ExecutionPayload → ExecutionPayloadHeader field dict (shared by
    payload processing and the builder/blinded flow)."""
    fields = dict(
        parent_hash=bytes(payload.parent_hash),
        fee_recipient=bytes(payload.fee_recipient),
        state_root=bytes(payload.state_root),
        receipts_root=bytes(payload.receipts_root),
        logs_bloom=bytes(payload.logs_bloom),
        prev_randao=bytes(payload.prev_randao),
        block_number=int(payload.block_number),
        gas_limit=int(payload.gas_limit),
        gas_used=int(payload.gas_used),
        timestamp=int(payload.timestamp),
        extra_data=bytes(payload.extra_data),
        base_fee_per_gas=int(payload.base_fee_per_gas),
        block_hash=bytes(payload.block_hash),
        transactions_root=payload.transactions.hash_tree_root(),
    )
    if phase >= Phase.CAPELLA:
        fields["withdrawals_root"] = payload.withdrawals.hash_tree_root()
    if phase >= Phase.DENEB:
        fields["blob_gas_used"] = int(payload.blob_gas_used)
        fields["excess_blob_gas"] = int(payload.excess_blob_gas)
    return fields


# ============================================================ blinded block
# reference: transition_functions/src/*/blinded_block_processing.rs — the
# builder flow's transition: the block carries an ExecutionPayloadHeader
# instead of the payload; consistency checks run against the header and
# it is stored as-is (the EL sees the payload after unblinding).


def process_blinded_execution_payload(
    draft: StateDraft, body, cfg, phase: Phase, types_ns
) -> None:
    state = object.__getattribute__(draft, "base")
    p = draft.p
    header = body.execution_payload_header
    if phase >= Phase.CAPELLA or _is_merge_transition_complete(state):
        _require(
            bytes(header.parent_hash)
            == bytes(state.latest_execution_payload_header.block_hash),
            "blinded payload: parent hash mismatch",
        )
    _require(
        bytes(header.prev_randao)
        == misc.get_randao_mix(state, accessors.get_current_epoch(state, p), p),
        "blinded payload: prev_randao mismatch",
    )
    expected_ts = int(state.genesis_time) + int(state.slot) * cfg.seconds_per_slot
    _require(int(header.timestamp) == expected_ts, "blinded payload: bad timestamp")
    if phase >= Phase.DENEB:
        _require(
            len(body.blob_kzg_commitments) <= p.MAX_BLOBS_PER_BLOCK,
            "too many blob commitments",
        )
    draft.set("latest_execution_payload_header", header)


def process_blinded_withdrawals(draft: StateDraft, header, types_ns) -> None:
    """Capella blinded withdrawals: the block carries only the
    withdrawals_root; verify it equals the expected sweep's root, then
    apply the sweep's debits."""
    state = object.__getattribute__(draft, "base")
    expected = get_expected_withdrawals(state, draft, types_ns)
    withdrawals_type = None
    for name, typ in types_ns.ExecutionPayload.FIELDS:
        if name == "withdrawals":
            withdrawals_type = typ
            break
    _require(withdrawals_type is not None, "no withdrawals field in payload")
    expected_root = withdrawals_type.hash_tree_root(
        withdrawals_type.coerce(expected)
    )
    _require(
        bytes(header.withdrawals_root) == expected_root,
        "blinded withdrawals: root does not match expected sweep",
    )
    _apply_withdrawals_sweep(draft, state, expected)


def process_blinded_block(
    draft: StateDraft, block, cfg, phase: Phase, types_ns
) -> None:
    """process_block for a BlindedBeaconBlock (blinded_block_processing.rs):
    identical except the payload half runs against the header."""
    _require(phase >= Phase.BELLATRIX, "blinded blocks require bellatrix")
    process_block_header(draft, block)
    body = block.body
    if phase >= Phase.CAPELLA:
        process_blinded_withdrawals(
            draft, body.execution_payload_header, types_ns
        )
    process_blinded_execution_payload(draft, body, cfg, phase, types_ns)
    process_randao(draft, body)
    process_eth1_data(draft, body)
    process_operations(draft, body, cfg, phase, types_ns)
    process_sync_aggregate(draft, body.sync_aggregate)


# ================================================================ full block


def process_block(
    draft: StateDraft, block, cfg, phase: Phase, execution_engine, types_ns
) -> None:
    """The mutation half (no pairings): header → (withdrawals → payload) →
    randao → eth1 → operations → sync aggregate."""
    process_block_header(draft, block)
    body = block.body
    if phase >= Phase.BELLATRIX:
        # bellatrix `is_execution_enabled`: merge complete or a real payload
        execution_enabled = (
            phase >= Phase.CAPELLA
            or _is_merge_transition_complete(object.__getattribute__(draft, "base"))
            or body.execution_payload != type(body.execution_payload)()
        )
        if execution_enabled:
            if phase >= Phase.CAPELLA:
                process_withdrawals(draft, body.execution_payload, types_ns)
            process_execution_payload(
                draft, body, cfg, phase, execution_engine, types_ns
            )
    process_randao(draft, body)
    process_eth1_data(draft, body)
    process_operations(draft, body, cfg, phase, types_ns)
    if phase >= Phase.ALTAIR:
        process_sync_aggregate(draft, body.sync_aggregate)


__all__ = [
    "TransitionError",
    "collect_signatures",
    "process_block",
    "process_block_header",
    "process_randao",
    "process_eth1_data",
    "process_operations",
    "process_attestation_phase0",
    "process_attestation_altair",
    "process_deposit",
    "apply_deposit",
    "process_voluntary_exit",
    "process_bls_to_execution_change",
    "process_sync_aggregate",
    "process_withdrawals",
    "get_expected_withdrawals",
    "process_execution_payload",
]
