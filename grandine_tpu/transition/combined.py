"""Fork-dispatching state transition — reference:
transition_functions/src/combined.rs (`untrusted_state_transition` :45,
`custom_state_transition` :101-160) and the per-fork `state_transition`
(altair/state_transition.rs:23-70).

The verify-∥-process split: signatures are collected into the Verifier and
dispatched (asynchronously, for the TPU backend — XLA execution overlaps
host Python) BEFORE block processing runs; the result is awaited after.
This is the accelerator-era twin of the reference's
`rayon::join(verify_signatures, process_block)`
(altair/state_transition.rs:65).
"""

from __future__ import annotations

from typing import Optional

from grandine_tpu.consensus.mutators import StateDraft
from grandine_tpu.consensus.verifier import (
    MultiVerifier,
    NullVerifier,
    SignatureInvalid,
    Verifier,
)
from grandine_tpu.transition import block as block_mod
from grandine_tpu.transition.block import TransitionError
from grandine_tpu.transition.fork_upgrade import state_phase
from grandine_tpu.transition.slots import process_slots  # noqa: F401 (re-export)
from grandine_tpu.types.containers import spec_types
from grandine_tpu.types.primitives import Phase

ZERO32 = b"\x00" * 32


class StateRootMismatch(TransitionError):
    pass


def verify_signatures(state, signed_block, verifier: Verifier, cfg) -> None:
    """Collect + settle all of a block's signatures against `state` (the
    slot-advanced pre-state) without mutating anything — reference
    combined::verify_signatures (used by the block-verification pool)."""
    phase = cfg.phase_at_slot(int(signed_block.message.slot))
    block_mod.collect_signatures(state, signed_block, verifier, cfg, phase)
    verifier.finish()


def custom_state_transition(
    state,
    signed_block,
    cfg,
    verifier: "Optional[Verifier]" = None,
    execution_engine=None,
    state_root_policy: str = "verify",
):
    """Full state transition with a caller-chosen verifier and execution
    engine (reference custom_state_transition, combined.rs:101).

    state_root_policy: "verify" compares the post-state root against
    block.state_root (raising StateRootMismatch), "trust" skips the
    comparison (reference StateRootPolicy::Trust for own blocks).
    """
    if verifier is None:
        verifier = MultiVerifier()
    if execution_engine is None:
        from grandine_tpu.execution import NullExecutionEngine

        execution_engine = NullExecutionEngine()

    block = signed_block.message
    slot = int(block.slot)
    if int(state.slot) < slot:
        state = process_slots(state, slot, cfg)
    phase = state_phase(state, cfg)
    ns = getattr(spec_types(cfg.preset), phase.key)

    # --- verify ∥ process: dispatch the signature batch, then mutate
    block_mod.collect_signatures(state, signed_block, verifier, cfg, phase)
    settle = verifier.finish_async()

    draft = StateDraft(state, cfg)
    process_error: "Optional[Exception]" = None
    try:
        block_mod.process_block(draft, block, cfg, phase, execution_engine, ns)
    except Exception as e:  # settle the device batch either way; an invalid
        process_error = e   # signature outranks a processing error
    settle()
    if process_error is not None:
        raise process_error
    post = draft.commit()

    if state_root_policy == "verify":
        expected = bytes(block.state_root)
        actual = post.hash_tree_root()
        if actual != expected:
            raise StateRootMismatch(
                f"state root {actual.hex()} != block.state_root {expected.hex()}"
            )
    return post


def blinded_state_transition(
    state,
    signed_blinded_block,
    cfg,
    verifier: "Optional[Verifier]" = None,
    state_root_policy: str = "verify",
):
    """State transition over a SignedBlindedBeaconBlock (reference
    transition_functions blinded_block_processing): signature collection
    is identical (a blinded body carries the same signed operations); the
    payload half runs against the ExecutionPayloadHeader."""
    if verifier is None:
        verifier = MultiVerifier()
    block = signed_blinded_block.message
    slot = int(block.slot)
    if int(state.slot) < slot:
        state = process_slots(state, slot, cfg)
    phase = state_phase(state, cfg)
    ns = getattr(spec_types(cfg.preset), phase.key)

    block_mod.collect_signatures(
        state, signed_blinded_block, verifier, cfg, phase
    )
    settle = verifier.finish_async()
    draft = StateDraft(state, cfg)
    process_error: "Optional[Exception]" = None
    try:
        block_mod.process_blinded_block(draft, block, cfg, phase, ns)
    except Exception as e:
        process_error = e
    settle()
    if process_error is not None:
        raise process_error
    post = draft.commit()
    if state_root_policy == "verify":
        expected = bytes(block.state_root)
        actual = post.hash_tree_root()
        if actual != expected:
            raise StateRootMismatch(
                f"state root {actual.hex()} != block.state_root {expected.hex()}"
            )
    return post


def state_transition(state, signed_block, cfg, verifier=None, **kw):
    """Alias of custom_state_transition (per-fork dispatch is internal)."""
    return custom_state_transition(state, signed_block, cfg, verifier, **kw)


def untrusted_state_transition(state, signed_block, cfg):
    """Spec `state_transition(..., validate_result=True)` — batch signature
    verification, state-root check (reference untrusted_state_transition,
    combined.rs:45)."""
    return custom_state_transition(
        state, signed_block, cfg, MultiVerifier(), state_root_policy="verify"
    )


def trusted_state_transition(state, signed_block, cfg):
    """No signature checks, no state-root check (own blocks / replays)."""
    return custom_state_transition(
        state, signed_block, cfg, NullVerifier(), state_root_policy="trust"
    )


__all__ = [
    "StateRootMismatch",
    "verify_signatures",
    "custom_state_transition",
    "blinded_state_transition",
    "state_transition",
    "untrusted_state_transition",
    "trusted_state_transition",
    "process_slots",
]
