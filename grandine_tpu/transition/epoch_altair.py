"""Altair..Deneb epoch processing — reference:
transition_functions/src/altair/epoch_processing.rs and
epoch_intermediates.rs (participation-flag deltas, inactivity scores, sync
committee rotation), with bellatrix+ penalty-quotient overrides.

Every per-validator pass is one numpy expression over registry columns —
the whole epoch's reward accounting is a handful of vectorized ops.
"""

from __future__ import annotations

import numpy as np

from grandine_tpu.consensus import accessors, misc
from grandine_tpu.consensus.mutators import StateDraft
from grandine_tpu.transition import epoch_common
from grandine_tpu.types.primitives import (
    GENESIS_EPOCH,
    PARTICIPATION_FLAG_WEIGHTS,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    WEIGHT_DENOMINATOR,
    Phase,
)


def _inactivity_penalty_quotient(p, phase: Phase) -> int:
    if phase >= Phase.BELLATRIX:
        return p.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX
    return p.INACTIVITY_PENALTY_QUOTIENT_ALTAIR


def _participation(state, epoch: int, p) -> np.ndarray:
    cur = accessors.get_current_epoch(state, p)
    col = (
        state.current_epoch_participation
        if epoch == cur
        else state.previous_epoch_participation
    )
    return np.asarray(col.array, dtype=np.uint8)


def _unslashed_flag_mask(state, flag_index: int, epoch: int, p) -> np.ndarray:
    return accessors.get_unslashed_participating_mask(state, flag_index, epoch, p)


def process_justification_and_finalization(draft: StateDraft) -> None:
    state = object.__getattribute__(draft, "base")
    p = draft.p
    if accessors.get_current_epoch(state, p) <= GENESIS_EPOCH + 1:
        return
    cols = accessors.registry_columns(state)
    eb = cols.effective_balance.astype(np.int64)
    prev = accessors.get_previous_epoch(state, p)
    cur = accessors.get_current_epoch(state, p)

    def target_balance(epoch):
        mask = _unslashed_flag_mask(state, TIMELY_TARGET_FLAG_INDEX, epoch, p)
        return max(p.EFFECTIVE_BALANCE_INCREMENT, int(eb[mask].sum()))

    epoch_common.weigh_justification_and_finalization(
        draft,
        accessors.get_total_active_balance(state, p),
        target_balance(prev),
        target_balance(cur),
    )


def process_inactivity_updates(draft: StateDraft) -> None:
    state = object.__getattribute__(draft, "base")
    p, cfg = draft.p, draft.cfg
    if accessors.get_current_epoch(state, p) == GENESIS_EPOCH:
        return
    prev = accessors.get_previous_epoch(state, p)
    eligible = epoch_common.get_eligible_validator_mask(state, p)
    target_mask = _unslashed_flag_mask(state, TIMELY_TARGET_FLAG_INDEX, prev, p)
    sc = draft.inactivity_scores
    scores = np.asarray(getattr(sc, "array", sc), dtype=np.uint64).astype(
        np.int64
    )
    n = len(scores)
    el = eligible[:n]
    tm = target_mask[:n]
    new = scores.copy()
    # participating: score -= min(1, score); else: += bias
    new[el & tm] -= np.minimum(1, new[el & tm])
    new[el & ~tm] += cfg.inactivity_score_bias
    if not epoch_common.is_in_inactivity_leak(state, p):
        dec = np.minimum(cfg.inactivity_score_recovery_rate, new[el])
        new[el] -= dec
    if not np.array_equal(new, scores):
        draft.set("inactivity_scores", new.astype(np.uint64))


def process_rewards_and_penalties(draft: StateDraft, phase: Phase) -> None:
    state = object.__getattribute__(draft, "base")
    p = draft.p
    if accessors.get_current_epoch(state, p) == GENESIS_EPOCH:
        return
    prev = accessors.get_previous_epoch(state, p)
    cols = accessors.registry_columns(state)
    n = len(cols)
    eligible = epoch_common.get_eligible_validator_mask(state, p)
    increment = p.EFFECTIVE_BALANCE_INCREMENT
    total = accessors.get_total_active_balance(state, p)
    active_increments = total // increment
    base_per_increment = accessors.get_base_reward_per_increment(state, p)
    base = (
        cols.effective_balance.astype(np.int64) // increment * base_per_increment
    )
    in_leak = epoch_common.is_in_inactivity_leak(state, p)
    eb = cols.effective_balance.astype(np.int64)

    rewards = np.zeros(n, dtype=np.int64)
    penalties = np.zeros(n, dtype=np.int64)

    for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
        mask = _unslashed_flag_mask(state, flag_index, prev, p)
        participating_increments = int(eb[mask].sum()) // increment
        got = eligible & mask
        missed = eligible & ~mask
        if not in_leak:
            rewards[got] += (
                base[got] * weight * participating_increments
                // (active_increments * WEIGHT_DENOMINATOR)
            )
        if flag_index != TIMELY_HEAD_FLAG_INDEX:
            penalties[missed] += base[missed] * weight // WEIGHT_DENOMINATOR

    # inactivity penalties (score-scaled, always on) — reads the scores as
    # updated by process_inactivity_updates earlier in this epoch (the spec
    # mutates in place; the draft carries the update)
    sc = draft.inactivity_scores
    target_mask = _unslashed_flag_mask(state, TIMELY_TARGET_FLAG_INDEX, prev, p)
    scores = np.asarray(getattr(sc, "array", sc), dtype=np.uint64).astype(
        np.int64
    )
    missed_target = eligible & ~target_mask
    denominator = draft.cfg.inactivity_score_bias * _inactivity_penalty_quotient(
        p, phase
    )
    # exact integer math (eb * score can exceed int64 only at absurd scores;
    # go through object dtype for the hit set, which is small in practice)
    hit = np.nonzero(missed_target)[0]
    if len(hit):
        pen = (
            eb[hit].astype(object) * scores[hit].astype(object) // denominator
        )
        penalties[hit] += pen.astype(np.int64)

    balances = draft.balances_array
    net = balances.astype(np.int64) + rewards - penalties
    np.maximum(net, 0, out=net)
    balances[:] = net.astype(np.uint64)


def process_participation_flag_updates(draft: StateDraft) -> None:
    draft.set("previous_epoch_participation", draft.current_epoch_participation)
    draft.set(
        "current_epoch_participation",
        np.zeros(draft.num_validators(), dtype=np.uint8),
    )


def process_sync_committee_updates(state, cfg):
    """Runs on the already-committed epoch state so the new committee's
    balance-weighted sampling sees this epoch's effective-balance updates
    (the spec mutates in place; order matters)."""
    p = cfg.preset
    next_epoch = accessors.get_current_epoch(state, p) + 1
    if next_epoch % p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD != 0:
        return state
    from grandine_tpu.types.containers import spec_types

    phase = cfg.phase_at_epoch(next_epoch)
    ns = getattr(spec_types(p), phase.key)
    return state.replace(
        current_sync_committee=state.next_sync_committee,
        next_sync_committee=accessors.get_next_sync_committee(state, ns, cfg),
    )


def process_epoch(state, cfg, phase: Phase):
    """Altair..Deneb `process_epoch`."""
    draft = StateDraft(state, cfg)
    process_justification_and_finalization(draft)
    process_inactivity_updates(draft)
    process_rewards_and_penalties(draft, phase)
    epoch_common.process_registry_updates(draft, phase)
    epoch_common.process_slashings(draft, phase)
    epoch_common.process_eth1_data_reset(draft)
    epoch_common.process_effective_balance_updates(draft)
    epoch_common.process_slashings_reset(draft)
    epoch_common.process_randao_mixes_reset(draft)
    epoch_common.process_historical_roots_update(draft, phase)
    process_participation_flag_updates(draft)
    return process_sync_committee_updates(draft.commit(), cfg)


__all__ = ["process_epoch"]
