"""Epoch-processing machinery shared by every fork — reference:
transition_functions/src/unphased/epoch_processing.rs (justification/
finality engine, registry updates, slashings, final updates).

Everything registry-wide is a vectorized numpy pass over
`accessors.RegistryColumns` — the TPU-era answer to the reference's rayon
epoch intermediates.
"""

from __future__ import annotations

import numpy as np

from grandine_tpu.consensus import accessors, misc, mutators
from grandine_tpu.consensus.mutators import StateDraft
from grandine_tpu.types.primitives import (
    FAR_FUTURE_EPOCH,
    GENESIS_EPOCH,
    JUSTIFICATION_BITS_LENGTH,
    Phase,
)


def get_eligible_validator_mask(state, p) -> np.ndarray:
    """Spec `get_eligible_validator_indices` as a registry mask: active in
    the previous epoch, or slashed and not yet withdrawable."""
    cols = accessors.registry_columns(state)
    prev = accessors.get_previous_epoch(state, p)
    active_prev = np.zeros(len(cols), dtype=bool)
    active_prev[cols.active_indices(prev)] = True
    slashed_pending = cols.slashed & (
        np.uint64(prev + 1) < cols.withdrawable_epoch
    )
    return active_prev | slashed_pending


def finality_delay(state, p) -> int:
    return accessors.get_previous_epoch(state, p) - int(
        state.finalized_checkpoint.epoch
    )


def is_in_inactivity_leak(state, p) -> bool:
    return finality_delay(state, p) > p.MIN_EPOCHS_TO_INACTIVITY_PENALTY


# --- justification & finality ----------------------------------------------


def weigh_justification_and_finalization(
    draft: StateDraft,
    total_active_balance: int,
    previous_target_balance: int,
    current_target_balance: int,
) -> None:
    """Spec `weigh_justification_and_finalization` — identical across forks
    once target balances are computed (pending attestations in phase0,
    participation flags in altair+)."""
    state = object.__getattribute__(draft, "base")
    p = draft.p
    previous_epoch = accessors.get_previous_epoch(state, p)
    current_epoch = accessors.get_current_epoch(state, p)
    old_previous_justified = draft.previous_justified_checkpoint
    old_current_justified = draft.current_justified_checkpoint
    Checkpoint = type(old_current_justified)

    # shift justification bits
    bits = draft.justification_bits
    new_bits = [False] + [bool(bits[i]) for i in range(JUSTIFICATION_BITS_LENGTH - 1)]
    draft.set("previous_justified_checkpoint", old_current_justified)

    if previous_target_balance * 3 >= total_active_balance * 2:
        draft.set(
            "current_justified_checkpoint",
            Checkpoint(
                epoch=previous_epoch,
                root=accessors.get_block_root(state, previous_epoch, p),
            ),
        )
        new_bits[1] = True
    if current_target_balance * 3 >= total_active_balance * 2:
        draft.set(
            "current_justified_checkpoint",
            Checkpoint(
                epoch=current_epoch,
                root=accessors.get_block_root(state, current_epoch, p),
            ),
        )
        new_bits[0] = True
    draft.set("justification_bits", new_bits)

    # finalization rules (234/23/123/12)
    if (
        all(new_bits[1:4])
        and int(old_previous_justified.epoch) + 3 == current_epoch
    ):
        draft.set("finalized_checkpoint", old_previous_justified)
    if (
        all(new_bits[1:3])
        and int(old_previous_justified.epoch) + 2 == current_epoch
    ):
        draft.set("finalized_checkpoint", old_previous_justified)
    if (
        all(new_bits[0:3])
        and int(old_current_justified.epoch) + 2 == current_epoch
    ):
        draft.set("finalized_checkpoint", old_current_justified)
    if (
        all(new_bits[0:2])
        and int(old_current_justified.epoch) + 1 == current_epoch
    ):
        draft.set("finalized_checkpoint", old_current_justified)


# --- registry updates -------------------------------------------------------


def process_registry_updates(draft: StateDraft, phase: Phase) -> None:
    """Spec `process_registry_updates`: eligibility, ejection, and the
    churn-limited activation queue — scans vectorized over columns."""
    state = object.__getattribute__(draft, "base")
    p, cfg = draft.p, draft.cfg
    cols = accessors.registry_columns(state)
    current_epoch = accessors.get_current_epoch(state, p)

    # eligibility for the activation queue
    eligible_queue = np.nonzero(
        (cols.activation_eligibility_epoch == np.uint64(FAR_FUTURE_EPOCH))
        & (cols.effective_balance == np.uint64(p.MAX_EFFECTIVE_BALANCE))
    )[0]
    for i in eligible_queue:
        v = draft.validator(int(i))
        draft.set_validator(
            int(i), v.replace(activation_eligibility_epoch=current_epoch + 1)
        )

    # ejections
    active = cols.active_indices(current_epoch)
    eject = active[
        cols.effective_balance[active] <= np.uint64(cfg.ejection_balance)
    ]
    for i in eject:
        mutators.initiate_validator_exit(draft, int(i))

    # activation queue, ordered by (eligibility epoch, index)
    finalized = int(draft.finalized_checkpoint.epoch)
    # draft may have just set eligibility epochs — rescan from the draft
    elig = cols.activation_eligibility_epoch.copy()
    elig[eligible_queue] = np.uint64(current_epoch + 1)
    queue_mask = (elig <= np.uint64(finalized)) & (
        cols.activation_epoch == np.uint64(FAR_FUTURE_EPOCH)
    )
    queue = np.nonzero(queue_mask)[0]
    order = np.lexsort((queue, elig[queue]))
    queue = queue[order]

    churn = (
        misc.get_validator_activation_churn_limit(len(active), cfg)
        if phase >= Phase.DENEB
        else misc.get_validator_churn_limit(len(active), cfg)
    )
    activation_epoch = misc.compute_activation_exit_epoch(current_epoch, p)
    for i in queue[:churn]:
        v = draft.validator(int(i))
        draft.set_validator(int(i), v.replace(activation_epoch=activation_epoch))


# --- slashings sweep --------------------------------------------------------


def process_slashings(draft: StateDraft, phase: Phase) -> None:
    state = object.__getattribute__(draft, "base")
    p = draft.p
    epoch = accessors.get_current_epoch(state, p)
    cols = accessors.registry_columns(state)
    total_balance = accessors.get_total_active_balance(state, p)
    multiplier = mutators.proportional_slashing_multiplier(p, phase)
    adjusted = min(
        int(np.asarray(state.slashings.array, dtype=np.uint64).sum(dtype=np.uint64))
        * multiplier,
        total_balance,
    )
    target_epoch = np.uint64(epoch + p.EPOCHS_PER_SLASHINGS_VECTOR // 2)
    hit = np.nonzero(cols.slashed & (cols.withdrawable_epoch == target_epoch))[0]
    if len(hit) == 0:
        return
    increment = p.EFFECTIVE_BALANCE_INCREMENT
    eb = cols.effective_balance[hit].astype(object)  # exact int math
    penalties = eb // increment * adjusted // total_balance * increment
    balances = draft.balances_array
    for i, pen in zip(hit, penalties):
        balances[i] = np.uint64(max(0, int(balances[i]) - int(pen)))


# --- final updates ----------------------------------------------------------


def process_eth1_data_reset(draft: StateDraft) -> None:
    state = object.__getattribute__(draft, "base")
    p = draft.p
    next_epoch = accessors.get_current_epoch(state, p) + 1
    if next_epoch % p.EPOCHS_PER_ETH1_VOTING_PERIOD == 0:
        draft.set("eth1_data_votes", ())


def process_effective_balance_updates(draft: StateDraft) -> None:
    """Hysteresis sweep, vectorized: one compare over the registry, then
    per-index replacement only where the effective balance actually moves."""
    state = object.__getattribute__(draft, "base")
    p = draft.p
    cols = accessors.registry_columns(state)
    balances = (
        draft.balances_array
        if object.__getattribute__(draft, "_balances") is not None
        else np.asarray(state.balances.array, dtype=np.uint64)
    )
    increment = p.EFFECTIVE_BALANCE_INCREMENT
    hysteresis_increment = increment // p.HYSTERESIS_QUOTIENT
    downward = hysteresis_increment * p.HYSTERESIS_DOWNWARD_MULTIPLIER
    upward = hysteresis_increment * p.HYSTERESIS_UPWARD_MULTIPLIER
    eb = cols.effective_balance
    n = min(len(eb), len(balances))
    bal = balances[:n].astype(np.int64)
    ebi = eb[:n].astype(np.int64)
    needs_update = (bal + downward < ebi) | (ebi + upward < bal)
    new_eb = np.minimum(bal - bal % increment, p.MAX_EFFECTIVE_BALANCE)
    for i in np.nonzero(needs_update)[0]:
        v = draft.validator(int(i))
        draft.set_validator(int(i), v.replace(effective_balance=int(new_eb[i])))
    # validators appended this epoch (deposits) keep their init-time EB


def process_slashings_reset(draft: StateDraft) -> None:
    state = object.__getattribute__(draft, "base")
    p = draft.p
    next_epoch = accessors.get_current_epoch(state, p) + 1
    slashings = draft.slashings
    draft.set(
        "slashings", slashings.set(next_epoch % p.EPOCHS_PER_SLASHINGS_VECTOR, 0)
    )


def process_randao_mixes_reset(draft: StateDraft) -> None:
    state = object.__getattribute__(draft, "base")
    p = draft.p
    current_epoch = accessors.get_current_epoch(state, p)
    next_epoch = current_epoch + 1
    mixes = draft.randao_mixes
    draft.set(
        "randao_mixes",
        mixes.set(
            next_epoch % p.EPOCHS_PER_HISTORICAL_VECTOR,
            misc.get_randao_mix(state, current_epoch, p),
        ),
    )


def process_historical_roots_update(draft: StateDraft, phase: Phase) -> None:
    """Pre-capella: append HistoricalBatch root to historical_roots.
    Capella+: append a HistoricalSummary instead."""
    state = object.__getattribute__(draft, "base")
    p = draft.p
    next_epoch = accessors.get_current_epoch(state, p) + 1
    if next_epoch % (p.SLOTS_PER_HISTORICAL_ROOT // p.SLOTS_PER_EPOCH) != 0:
        return
    from grandine_tpu.types.containers import spec_types

    T = spec_types(p)
    if phase >= Phase.CAPELLA:
        ns = getattr(T, phase.key)
        summary = ns.HistoricalSummary(
            block_summary_root=draft.block_roots.hash_tree_root(),
            state_summary_root=draft.state_roots.hash_tree_root(),
        )
        draft.set(
            "historical_summaries",
            tuple(draft.historical_summaries) + (summary,),
        )
    else:
        batch = T.phase0.HistoricalBatch(
            block_roots=draft.block_roots, state_roots=draft.state_roots
        )
        draft.set(
            "historical_roots",
            tuple(bytes(r) for r in draft.historical_roots)
            + (batch.hash_tree_root(),),
        )


__all__ = [
    "get_eligible_validator_mask",
    "finality_delay",
    "is_in_inactivity_leak",
    "weigh_justification_and_finalization",
    "process_registry_updates",
    "process_slashings",
    "process_eth1_data_reset",
    "process_effective_balance_updates",
    "process_slashings_reset",
    "process_randao_mixes_reset",
    "process_historical_roots_update",
]
