"""Fork-boundary state upgrades — reference:
transition_functions/src/{altair,bellatrix,capella,deneb}/fork.rs
(`upgrade_to_*` run at the first slot of the fork epoch).
"""

from __future__ import annotations

import numpy as np

from grandine_tpu.consensus import accessors, misc
from grandine_tpu.types.containers import spec_types
from grandine_tpu.types.primitives import (
    PARTICIPATION_FLAG_WEIGHTS,
    Phase,
)


def state_phase(state, cfg) -> Phase:
    """Determine a state's phase from its fork's current version."""
    version = bytes(state.fork.current_version)
    for phase in reversed(list(Phase)):
        if cfg.fork_version(phase) == version:
            return phase
    raise ValueError(f"unknown fork version {version.hex()}")


def _shared_fields(pre, post_cls) -> dict:
    """Copy every field the new state class shares with the old one."""
    post_names = {name for name, _ in post_cls.FIELDS}
    return {
        name: getattr(pre, name)
        for name, _ in type(pre).FIELDS
        if name in post_names
    }


def _new_fork(pre, ns, version: bytes, epoch: int):
    return ns.Fork(
        previous_version=bytes(pre.fork.current_version),
        current_version=version,
        epoch=epoch,
    )


def upgrade_to_altair(pre, cfg):
    p = cfg.preset
    ns = spec_types(p).altair
    epoch = accessors.get_current_epoch(pre, p)
    n = len(pre.validators)
    fields = _shared_fields(pre, ns.BeaconState)
    fields.pop("previous_epoch_attestations", None)
    fields.pop("current_epoch_attestations", None)
    fields["fork"] = _new_fork(pre, ns, cfg.altair_fork_version, epoch)
    fields["previous_epoch_participation"] = np.zeros(n, np.uint8)
    fields["current_epoch_participation"] = np.zeros(n, np.uint8)
    fields["inactivity_scores"] = np.zeros(n, np.uint64)
    post = ns.BeaconState(**fields)
    # translate_participation: replay previous-epoch pending attestations
    # into participation flags on the post state
    part = np.zeros(n, np.uint8)
    for att in pre.previous_epoch_attestations:
        inclusion_delay = int(att.inclusion_delay)
        try:
            flag_indices = accessors.get_attestation_participation_flag_indices(
                post, att.data, inclusion_delay, cfg, Phase.ALTAIR
            )
        except ValueError:
            continue
        idx = accessors.get_attesting_indices(
            post, att.data, att.aggregation_bits, p
        )
        for flag_index in flag_indices:
            part[idx] |= np.uint8(1 << flag_index)
    post = post.replace(previous_epoch_participation=part)
    # both committees sample the same (state, epoch+1) seed — one compute
    committee = accessors.get_next_sync_committee(post, ns, cfg)
    return post.replace(
        current_sync_committee=committee, next_sync_committee=committee
    )


def upgrade_to_bellatrix(pre, cfg):
    p = cfg.preset
    ns = spec_types(p).bellatrix
    epoch = accessors.get_current_epoch(pre, p)
    fields = _shared_fields(pre, ns.BeaconState)
    fields["fork"] = _new_fork(pre, ns, cfg.bellatrix_fork_version, epoch)
    fields["latest_execution_payload_header"] = ns.ExecutionPayloadHeader()
    return ns.BeaconState(**fields)


def upgrade_to_capella(pre, cfg):
    p = cfg.preset
    ns = spec_types(p).capella
    epoch = accessors.get_current_epoch(pre, p)
    fields = _shared_fields(pre, ns.BeaconState)
    old = pre.latest_execution_payload_header
    fields["fork"] = _new_fork(pre, ns, cfg.capella_fork_version, epoch)
    fields["latest_execution_payload_header"] = ns.ExecutionPayloadHeader(
        **{
            name: getattr(old, name)
            for name, _ in type(old).FIELDS
        },
        withdrawals_root=b"\x00" * 32,
    )
    fields["next_withdrawal_index"] = 0
    fields["next_withdrawal_validator_index"] = 0
    fields["historical_summaries"] = ()
    return ns.BeaconState(**fields)


def upgrade_to_deneb(pre, cfg):
    p = cfg.preset
    ns = spec_types(p).deneb
    epoch = accessors.get_current_epoch(pre, p)
    fields = _shared_fields(pre, ns.BeaconState)
    old = pre.latest_execution_payload_header
    fields["fork"] = _new_fork(pre, ns, cfg.deneb_fork_version, epoch)
    fields["latest_execution_payload_header"] = ns.ExecutionPayloadHeader(
        **{name: getattr(old, name) for name, _ in type(old).FIELDS},
        blob_gas_used=0,
        excess_blob_gas=0,
    )
    return ns.BeaconState(**fields)


_UPGRADES = {
    Phase.ALTAIR: upgrade_to_altair,
    Phase.BELLATRIX: upgrade_to_bellatrix,
    Phase.CAPELLA: upgrade_to_capella,
    Phase.DENEB: upgrade_to_deneb,
}


def maybe_upgrade_state(state, cfg):
    """Apply every fork upgrade scheduled at the state's current epoch
    (called by process_slots right after crossing into an epoch start)."""
    p = cfg.preset
    epoch = accessors.get_current_epoch(state, p)
    current = state_phase(state, cfg)
    target = cfg.phase_at_epoch(epoch)
    while current < target:
        nxt = Phase(current + 1)
        if cfg.fork_epoch(nxt) > epoch:
            break
        state = _UPGRADES[nxt](state, cfg)
        current = nxt
    return state


__all__ = [
    "state_phase",
    "maybe_upgrade_state",
    "upgrade_to_altair",
    "upgrade_to_bellatrix",
    "upgrade_to_capella",
    "upgrade_to_deneb",
]
