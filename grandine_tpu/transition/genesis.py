"""Genesis state construction — reference: `genesis`/`interop`/`factory`
crates (deterministic interop validators, genesis state assembly per fork).

`interop_genesis_state` builds a valid genesis BeaconState at whatever
phase the config activates at epoch 0, with deterministic interop keys —
the test/bench substrate for the whole framework (no eth1 needed).
"""

from __future__ import annotations

from typing import Optional, Sequence

from grandine_tpu.consensus import accessors
from grandine_tpu.crypto import bls as A
from grandine_tpu.types.config import Config
from grandine_tpu.types.containers import spec_types
from grandine_tpu.types.primitives import (
    BLS_WITHDRAWAL_PREFIX,
    FAR_FUTURE_EPOCH,
    GENESIS_EPOCH,
    Phase,
)

_SK_CACHE: dict = {}


def interop_secret_key(index: int) -> "A.SecretKey":
    """Deterministic interop key for validator `index` (the well-known
    interop scheme's spirit: keyed from the index; NOT the eth2 interop
    curve-order derivation — these keys are for in-framework testing)."""
    sk = _SK_CACHE.get(index)
    if sk is None:
        sk = A.SecretKey.keygen(index.to_bytes(32, "little"), b"interop")
        _SK_CACHE[index] = sk
    return sk


def interop_pubkeys(n: int) -> "list[bytes]":
    return [interop_secret_key(i).public_key().to_bytes() for i in range(n)]


def interop_genesis_state(
    n_validators: int,
    cfg: Config,
    genesis_time: int = 0,
    eth1_block_hash: bytes = b"\x42" * 32,
    pubkeys: "Optional[Sequence[bytes]]" = None,
):
    """Genesis BeaconState at the phase `cfg` activates at epoch 0
    (spec `initialize_beacon_state_from_eth1` + per-fork upgrades folded
    into direct construction)."""
    p = cfg.preset
    phase = cfg.phase_at_epoch(GENESIS_EPOCH)
    T = spec_types(p)
    ns = getattr(T, phase.key)

    if pubkeys is None:
        pubkeys = interop_pubkeys(n_validators)
    balance = p.MAX_EFFECTIVE_BALANCE

    validators = [
        ns.Validator(
            pubkey=bytes(pk),
            withdrawal_credentials=BLS_WITHDRAWAL_PREFIX + b"\x00" * 31,
            effective_balance=balance,
            slashed=False,
            activation_eligibility_epoch=GENESIS_EPOCH,
            activation_epoch=GENESIS_EPOCH,
            exit_epoch=FAR_FUTURE_EPOCH,
            withdrawable_epoch=FAR_FUTURE_EPOCH,
        )
        for pk in pubkeys
    ]

    state_fields = dict(
        genesis_time=genesis_time,
        slot=0,
        fork=ns.Fork(
            previous_version=cfg.fork_version(phase),
            current_version=cfg.fork_version(phase),
            epoch=GENESIS_EPOCH,
        ),
        latest_block_header=ns.BeaconBlockHeader(
            body_root=ns.BeaconBlockBody().hash_tree_root()
        ),
        eth1_data=ns.Eth1Data(
            deposit_root=b"\x00" * 32,
            deposit_count=len(validators),
            block_hash=eth1_block_hash,
        ),
        eth1_deposit_index=len(validators),
        validators=validators,
        balances=[balance] * len(validators),
        randao_mixes=[eth1_block_hash] * p.EPOCHS_PER_HISTORICAL_VECTOR,
    )
    if phase >= Phase.ALTAIR:
        state_fields["inactivity_scores"] = [0] * len(validators)
        state_fields["previous_epoch_participation"] = [0] * len(validators)
        state_fields["current_epoch_participation"] = [0] * len(validators)

    state = ns.BeaconState(**state_fields)
    # genesis_validators_root commits to the registry
    state = state.replace(
        genesis_validators_root=state.validators.hash_tree_root()
    )

    if phase >= Phase.ALTAIR:
        # both committees derive identically from the genesis state
        # (altair fork spec) — one compute
        committee = accessors.get_next_sync_committee(state, ns, cfg)
        state = state.replace(
            current_sync_committee=committee, next_sync_committee=committee
        )
    return state


__all__ = ["interop_secret_key", "interop_pubkeys", "interop_genesis_state"]
