"""Slot processing — reference: transition_functions/src/*/slot_processing.rs
(`process_slots` loop with per-boundary epoch processing) and the cache of
rolling block/state roots.
"""

from __future__ import annotations

from grandine_tpu.types.primitives import Phase


def process_slot(state, cfg):
    """Spec `process_slot`: cache the state root, backfill the header's
    state root, cache the block root."""
    p = cfg.preset
    slot = int(state.slot)
    idx = slot % p.SLOTS_PER_HISTORICAL_ROOT
    previous_state_root = state.hash_tree_root()
    changes = {
        "state_roots": state.state_roots.set(idx, previous_state_root),
    }
    header = state.latest_block_header
    if bytes(header.state_root) == b"\x00" * 32:
        header = header.replace(state_root=previous_state_root)
        changes["latest_block_header"] = header
    changes["block_roots"] = state.block_roots.set(idx, header.hash_tree_root())
    return state.replace(**changes)


def process_slots(state, slot: int, cfg):
    """Spec `process_slots`: advance through empty slots, running epoch
    processing (and fork upgrades) at epoch boundaries."""
    from grandine_tpu.transition import epoch_altair, epoch_phase0
    from grandine_tpu.transition.fork_upgrade import maybe_upgrade_state

    p = cfg.preset
    if int(state.slot) > slot:
        raise ValueError(f"state slot {int(state.slot)} is past target {slot}")
    while int(state.slot) < slot:
        state = process_slot(state, cfg)
        next_slot = int(state.slot) + 1
        if next_slot % p.SLOTS_PER_EPOCH == 0:
            phase = cfg.phase_at_slot(int(state.slot))
            if phase == Phase.PHASE0:
                state = epoch_phase0.process_epoch(state, cfg)
            else:
                state = epoch_altair.process_epoch(state, cfg, phase)
        state = state.replace(slot=next_slot)
        if next_slot % p.SLOTS_PER_EPOCH == 0:
            state = maybe_upgrade_state(state, cfg)
    return state


__all__ = ["process_slot", "process_slots"]
