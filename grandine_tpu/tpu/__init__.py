"""TPU (JAX/XLA) execution backend for the BLS12-381 signature plane.

The compute strategy (SURVEY.md §7, BASELINE.md):
  - 381-bit field elements are decomposed into 24 × 16-bit limbs held in
    uint32 lanes (products of canonical limbs fit uint32; column sums stay
    < 2³² without intermediate carries), in Montgomery form with R = 2³⁸⁴.
  - All ops are batched over a leading axis and jit/vmap-friendly: fixed
    trip counts, no data-dependent shapes, branchless edge-case handling
    via select — exactly the XLA-compilation model the framework targets.
  - Miller loops are vmapped across a signature batch; the final
    exponentiation is shared per batch (the multi_verify structure of
    bls/src/signature.rs:96-129 mapped onto the accelerator).
  - Multi-chip: the batch axis is sharded over a jax.sharding.Mesh; the
    pairing-product reduction is the only cross-device collective.

Differential testing: every function here is tested against the
pure-Python anchor in grandine_tpu/crypto/.
"""
