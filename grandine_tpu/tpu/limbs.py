"""Limb-decomposed Montgomery arithmetic for Fp (BLS12-381 base field) on TPU.

Representation ("relaxed signed digits", limb-major form): an Fp element is
ONE int32 array of shape (26, *batch) — little-endian 15-bit digits along the
LEADING axis, Montgomery form (value·R mod p, R = 2³⁹⁰). Digits are redundant
and signed: |digit| ≤ LMAX = 2¹⁵ + 256; values are only canonical modulo p at
explicit canonicalization points (equality tests, host export).

Why the limb axis is LEADING (three designs were measured on v5e — see
tools/kernel_microbench.py):
  - Trailing limb axis (batch, 26): the minor axis maps to the 128 vector
    lanes, so 26/128 lanes do work AND every shifted-column accumulation in
    the Montgomery product is a cross-lane concatenate (a relayout of the
    whole tensor): ~47 ns/montmul/element.
  - One array per limb (pytree of 26 arrays): montmul becomes pure
    elementwise code at full lane occupancy (~12 ns/element), but every
    cheap op (add, select) costs ~100 HLO instructions, and an XLA
    optimization pass that is quadratic in computation size pushes compiles
    of real kernels into minutes (and tens of GB of compiler memory).
  - Limb-major array (26, *batch) — this file: adds/selects are single HLO
    ops (the batch owns the minor axes: full lanes), the carry-relaxation
    shift moves whole batch planes along the major axis (a cheap copy, no
    lane shuffles), and montmul internally scans over the leading limb axis
    with its column accumulators as a 27-tuple carry that lives in VMEM —
    keeping the ~12 ns/element speed with ~30 flat ops per call site.

Why 15-bit signed digits:
  - products of two digits: ≤ LMAX² < 2³¹ — exact in int32;
  - CIOS column accumulators stay |·| < 2²² — no wide accumulator needed;
  - add/sub/neg are a plain elementwise op plus ONE flat carry-relaxation
    round (arithmetic shift + mask): no borrow ripples, no conditional
    subtracts. Signed digits are what make subtraction free.
  - value bounds are machine-checked: tools/ranges abstract-interprets
    every kernel call site and certifies the per-site digit-product,
    accumulator, and operand-value bounds into tools/ranges/bounds.txt
    (regenerate with `python -m tools.ranges --write-cert`). The int32
    bounds above hold unconditionally at every site. The |v| < 20p
    montmul working bound is proven per-site on the Fp/G1 paths;
    through Fp2 Karatsuba chains the worst-case interval hull exceeds
    it (each product's m·p/R term is [0, p) and independent in the
    abstraction — see the annotated sites in field.py), which is why
    the 20p figure is a working envelope, not a blanket invariant.
    Montgomery products land in (−0.1p, 2p) (see montmul docstring),
    which keeps the dropped top carry of the relaxation round provably
    zero.

Reference counterpart: the blst field arithmetic behind
bls/src/signature.rs:96-129 (multi_verify) — re-designed here for a vector
unit instead of 64-bit scalar pipelines.
"""

from __future__ import annotations

import os

import numpy as np
import jax.numpy as jnp
from jax import lax

from grandine_tpu.crypto.constants import P

#: lax.scan unroll factor for the CIOS loop. unroll=1 measured fastest on
#: v5e with honest (host-fetch) timing; kept as an env knob for experiments.
MONTMUL_UNROLL = int(os.environ.get("GT_MONTMUL_UNROLL", "1"))

#: Below this static batch size the CIOS loop would be FULLY unrolled:
#: narrow-width products (final exponentiation at width ≤54) are
#: latency-bound on the 26-iteration inner scan. Disabled by default (0):
#: measured on the axon TPU platform, the unrolled bodies push XLA compile
#: past 10 minutes while the no-inversion final exp (pairing.py
#: final_exp_is_one) already removes most narrow-width latency. Kept as an
#: experiment knob.
MONTMUL_UNROLL_NUMEL = int(os.environ.get("GT_MONTMUL_UNROLL_NUMEL", "0"))

LIMB_BITS = 15
NLIMBS = 26
MASK = (1 << LIMB_BITS) - 1
LMAX = (1 << LIMB_BITS) + 256  # relaxed digit bound (see module docstring)
R_MONT = 1 << (LIMB_BITS * NLIMBS)  # 2^390
R_INV = pow(R_MONT, -1, P)
R2 = R_MONT * R_MONT % P
N0_INV = (-pow(P, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS)

_DT = jnp.int32


# --- host-side conversions -------------------------------------------------


def int_to_limbs(v: int) -> np.ndarray:
    """Canonical (non-Montgomery) digit decomposition, (26,) int32."""
    assert 0 <= v < R_MONT
    return np.array(
        [(v >> (LIMB_BITS * i)) & MASK for i in range(NLIMBS)], dtype=np.int32
    )


def limbs_to_int(a) -> int:
    """(…, 26) trailing-limb REST-format array → int."""
    a = np.asarray(a)
    return sum(int(a[..., i]) << (LIMB_BITS * i) for i in range(NLIMBS))


def to_mont(v: int) -> np.ndarray:
    return int_to_limbs(v * R_MONT % P)


def from_mont(a) -> int:
    """Host conversion out of Montgomery form (REST format — trailing limb
    axis; handles redundant/signed digits via exact integer arithmetic)."""
    return limbs_to_int(a) * R_INV % P


P_LIMBS = int_to_limbs(P)
ZERO = np.zeros(NLIMBS, dtype=np.int32)
ONE_MONT = to_mont(1)
# R mod p as digits — folds the 27th result column of montmul back in.
R_MOD_P = int_to_limbs(R_MONT % P)
EIGHT_P = int_to_limbs(8 * P)
# canonical digit patterns of k·p, k = 0..15 (for is_zero after a +8p offset)
_KP_PATTERNS = np.stack([int_to_limbs(k * P) for k in range(16)])  # (16, 26)

# Python-int digit views for use as broadcast scalars in compute code.
P_DIGITS = [int(x) for x in P_LIMBS]
R_MOD_P_DIGITS = [int(x) for x in R_MOD_P]
ONE_MONT_DIGITS = [int(x) for x in ONE_MONT]
EIGHT_P_DIGITS = [int(x) for x in EIGHT_P]


# --- structure helpers -----------------------------------------------------
#
# Device Fp = (26, *batch) int32. REST format (host buffers, kernel
# boundaries) keeps the limb axis TRAILING (…, 26) — layout-agnostic and
# cheap to assemble on host; `split`/`merge` move between the two (one
# transpose, fused by XLA into adjacent compute).


def split(arr) -> jnp.ndarray:
    """REST (…, 26) → device (26, …)."""
    return jnp.moveaxis(jnp.asarray(arr), -1, 0)


def merge(fp) -> jnp.ndarray:
    """Device (26, …) → REST (…, 26)."""
    return jnp.moveaxis(fp, 0, -1)


def merge_np(fp) -> np.ndarray:
    return np.moveaxis(np.asarray(fp), 0, -1)


def const_fp(digits, shape=()) -> jnp.ndarray:
    """Digit vector (length 26, host ints) → (26, *shape) constant."""
    d = jnp.asarray(np.asarray(digits, dtype=np.int32))
    return jnp.broadcast_to(
        d.reshape((NLIMBS,) + (1,) * len(shape)), (NLIMBS,) + tuple(shape)
    )


def zeros_fp(shape=()) -> jnp.ndarray:
    return jnp.zeros((NLIMBS,) + tuple(shape), _DT)


def stack_fp(elems, axis: int = 1) -> jnp.ndarray:
    """Stack K independent Fp elements along a new batch axis (default:
    right after the limb axis)."""
    return jnp.stack(list(elems), axis=axis)


def unstack_fp(fp, k: int, axis: int = 1) -> list:
    return [jnp.take(fp, i, axis=axis) for i in range(k)]


def concat_fp(elems, axis: int = 1) -> jnp.ndarray:
    """Concatenate Fp elements along an existing batch axis."""
    return jnp.concatenate(list(elems), axis=axis)


def index_fp(fp, idx) -> jnp.ndarray:
    """Index the leading batch axis (device axis 1)."""
    return fp[:, idx]


def batch_shape(fp):
    return fp.shape[1:]


# --- flat primitives -------------------------------------------------------


def relax(s) -> jnp.ndarray:
    """One carry-relaxation round, exactly value-preserving: digits 0..24 go
    to [0,2¹⁵) + a signed carry into the next digit; the TOP digit is left
    unsplit (signed). Under the |value| < 20p invariant the top digit stays
    |·| ≲ 2¹¹, so products involving it remain far below int32 overflow.
    The carry shift moves batch planes along the major axis — no lane
    shuffles."""
    hi = s[: NLIMBS - 1] >> LIMB_BITS
    lo = s[: NLIMBS - 1] & MASK
    top = s[NLIMBS - 1 :] + hi[NLIMBS - 2 :]
    shifted = jnp.concatenate([jnp.zeros_like(hi[:1]), hi[: NLIMBS - 2]], 0)
    return jnp.concatenate([lo + shifted, top], axis=0)


def add_mod(a, b) -> jnp.ndarray:
    return relax(a + b)


def sub_mod(a, b) -> jnp.ndarray:
    return relax(a - b)


def neg_mod(a) -> jnp.ndarray:
    return relax(-a)


def double_mod(a) -> jnp.ndarray:
    return relax(a + a)


def montmul(a, b) -> jnp.ndarray:
    """Montgomery product a·b·R⁻¹ mod p: CIOS over signed digits, scanned
    over the 26 limb rows of `a` with the 27 column accumulators as a tuple
    carry (they live in VMEM — see module docstring).

    Value bound: for |a|,|b| < 20p, |a·b| < 400p² ≲ R·p, so the reduced value
    lies in (-0.1p, 2p) and the relaxed output digits are ≤ LMAX. Inputs are
    digit-bounded by LMAX (products < 2³¹) and value-bounded by callers.
    """
    shape = jnp.broadcast_shapes(a.shape[1:], b.shape[1:])
    a = jnp.broadcast_to(a, (NLIMBS,) + shape).astype(_DT)
    b = jnp.broadcast_to(b, (NLIMBS,) + shape).astype(_DT)
    bl = [b[j] for j in range(NLIMBS)]
    t0 = tuple(jnp.zeros(shape, _DT) for _ in range(NLIMBS + 1))

    def step(t, ai):
        t = list(t)
        for j in range(NLIMBS):
            prod = ai * bl[j]  # |·| < 2^31 exact
            t[j] = t[j] + (prod & MASK)
            t[j + 1] = t[j + 1] + (prod >> LIMB_BITS)
        m = (t[0] * N0_INV) & MASK
        for j in range(NLIMBS):
            prod2 = m * P_DIGITS[j]
            t[j] = t[j] + (prod2 & MASK)
            t[j + 1] = t[j + 1] + (prod2 >> LIMB_BITS)
        carry = t[0] >> LIMB_BITS  # exact: t[0] ≡ 0 mod 2^15
        t = t[1:] + [jnp.zeros(shape, _DT)]
        t[0] = t[0] + carry
        return tuple(t), None

    numel = 1
    for d in shape:
        numel *= int(d)
    unroll = NLIMBS if numel <= MONTMUL_UNROLL_NUMEL else MONTMUL_UNROLL
    t, _ = lax.scan(step, t0, a, unroll=unroll)
    # fold the 27th column (weight 2^390 = R) back in via R mod p, relax
    main = jnp.stack(
        [t[j] + t[NLIMBS] * R_MOD_P_DIGITS[j] for j in range(NLIMBS)], 0
    )
    return relax(main)


def montsq(a) -> jnp.ndarray:
    return montmul(a, a)


# --- packed transfer format -------------------------------------------------
#
# Canonical Fp values travel host→device as 13 little-endian uint32 words
# (the 13th is always zero padding) — 52 bytes instead of the 104-byte
# int32 limb form. The device unpacks to 15-bit limbs with static
# shifts/gathers and one montmul by R² lifts the batch into Montgomery
# form. Halving upload bytes matters because tunnel/PCIe transfers
# serialize with execution on the per-batch clock (bench.py pipeline).

NWORDS = 13
_UNPACK_J = np.array([(15 * i) >> 5 for i in range(NLIMBS)], np.int32)
_UNPACK_OFF = np.array([(15 * i) & 31 for i in range(NLIMBS)], np.int32)
R2_DIGITS = [int(x) for x in int_to_limbs(R2)]


def pack_fp_words_host(values) -> np.ndarray:
    """Canonical ints → (N, 13) uint32 little-endian words."""
    n = len(values)
    out = np.zeros((n, NWORDS), np.uint32)
    for i, v in enumerate(values):
        v = int(v)
        assert 0 <= v < (1 << 384)
        for j in range(12):
            out[i, j] = (v >> (32 * j)) & 0xFFFFFFFF
    return out


def unpack_words(w) -> jnp.ndarray:
    """(…, 13) uint32 REST words → canonical device limbs (26, …) int32
    (NON-Montgomery; multiply by R² via montmul to enter the field)."""
    w = jnp.asarray(w, jnp.uint32)
    j = jnp.asarray(_UNPACK_J)
    off = jnp.asarray(_UNPACK_OFF.astype(np.uint32))
    lo = jnp.take(w, j, axis=-1) >> off  # (…, 26)
    hi_src = jnp.take(w, j + 1, axis=-1)
    hi = jnp.where(off == 0, jnp.uint32(0), hi_src << (32 - off))
    limbs = ((lo | hi) & jnp.uint32(MASK)).astype(_DT)
    return jnp.moveaxis(limbs, -1, 0)


def to_mont_dev(x_canonical) -> jnp.ndarray:
    """Canonical device limbs → Montgomery form (one fused montmul)."""
    r2 = const_fp(R2_DIGITS, x_canonical.shape[1:])
    return montmul(x_canonical, r2)


def pow_fixed(a, exponent: int) -> jnp.ndarray:
    """a^e for a host-known exponent (LSB-first square-and-multiply scan)."""
    nbits = max(exponent.bit_length(), 1)
    bits = np.array([(exponent >> i) & 1 for i in range(nbits)], dtype=np.int32)
    one = const_fp(ONE_MONT_DIGITS, a.shape[1:])
    a = a.astype(_DT)

    def step(carry, bit):
        result, base = carry
        taken = montmul(result, base)
        result = jnp.where(bit.astype(bool), taken, result)
        base = montsq(base)
        return (result, base), None

    (result, _), _ = lax.scan(step, (one, a), jnp.asarray(bits))
    return result


def inv_mod(a) -> jnp.ndarray:
    """a⁻¹ via Fermat (Montgomery in/out). inv(0) = 0."""
    return pow_fixed(a, P - 2)


# --- canonicalization & predicates ----------------------------------------


def canonical_digits(t) -> jnp.ndarray:
    """Full ripple to canonical digits in [0, 2¹⁵). Only correct for
    non-negative values < 2³⁹⁰ — callers offset by +8p first. lax.scan over
    the limb axis (sequential carry chain — off the hot path)."""

    def step(c, v):
        s = v + c
        return s >> LIMB_BITS, s & MASK

    carry, ys = lax.scan(step, jnp.zeros(t.shape[1:], _DT), t[: NLIMBS - 1])
    return jnp.concatenate([ys, t[NLIMBS - 1 :] + carry[None]], axis=0)


def is_zero_val(a) -> jnp.ndarray:
    """value(a) ≡ 0 (mod p), for |value| < 8p (the widest bound any caller
    reaches — mixed-add Z outputs are < 6p): canonicalize a+8p and compare
    against the digit patterns of k·p, k = 0..15. Returns a bool array of
    the batch shape."""
    a = jnp.asarray(a)
    canon = canonical_digits(a + const_fp(EIGHT_P_DIGITS, a.shape[1:]))
    pats = jnp.asarray(np.ascontiguousarray(_KP_PATTERNS.T))  # (26, 16)
    pats = pats.reshape((NLIMBS, 16) + (1,) * (canon.ndim - 1))
    eq = canon[:, None] == pats  # (26, 16, *batch)
    return jnp.any(jnp.all(eq, axis=0), axis=0)


def is_zero_val_many(elems) -> list:
    """Zero tests for K same-shape elements in ONE canonicalization pass
    (canonical_digits is a 25-step sequential scan — the dominant latency of
    a zero test at narrow widths; stacking amortizes it)."""
    stacked = stack_fp(list(elems))  # (26, K, *batch)
    z = is_zero_val(stacked)  # (K, *batch)
    return [z[i] for i in range(len(elems))]


def is_one_mont(a) -> jnp.ndarray:
    """value(a) ≡ 1·R (mod p) — same bound discipline as is_zero_val."""
    a = jnp.asarray(a)
    return is_zero_val(a - const_fp(ONE_MONT_DIGITS, a.shape[1:]))


def select(cond, a, b) -> jnp.ndarray:
    """cond ? a : b, with cond of the batch shape (broadcast over limbs)."""
    return jnp.where(cond[None], a, b)
