"""Limb-decomposed Montgomery arithmetic for Fp (BLS12-381 base field) on TPU.

Representation: little-endian 24 × 16-bit limbs in uint32, shape (..., 24),
canonical (each limb < 2¹⁶, integer value < p), Montgomery form (value·R mod p,
R = 2³⁸⁴) except where noted.

Why 16-bit limbs in uint32: limb products (< 2³²) fit a uint32 exactly, and
CIOS column accumulators stay < 2²⁴ ≪ 2³², so multiplication needs no wide
accumulator — a direct fit for 32-bit integer vector lanes.

Compilation model: every sequential dependency (CIOS iterations, carry and
borrow ripples, square-and-multiply) is a `lax.scan`, so one field op costs
O(1) HLO nodes regardless of limb count, and composite ops (Fp2/Fp6/Fp12 in
field.py) stack their independent multiplications into a single wide montmul
call. This keeps the traced Miller-loop graph small enough to compile while
leaving the batch axis fully vectorized.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from grandine_tpu.crypto.constants import P

LIMB_BITS = 16
NLIMBS = 24
MASK = (1 << LIMB_BITS) - 1
R_MONT = 1 << (LIMB_BITS * NLIMBS)  # 2^384
R_INV = pow(R_MONT, -1, P)
R2 = R_MONT * R_MONT % P
N0_INV = (-pow(P, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS)


# --- host-side conversions -------------------------------------------------


def int_to_limbs(v: int) -> np.ndarray:
    """Plain (non-Montgomery) limb decomposition."""
    assert 0 <= v < (1 << (LIMB_BITS * NLIMBS))
    return np.array(
        [(v >> (LIMB_BITS * i)) & MASK for i in range(NLIMBS)], dtype=np.uint32
    )


def limbs_to_int(a) -> int:
    a = np.asarray(a)
    return sum(int(a[..., i]) << (LIMB_BITS * i) for i in range(NLIMBS))


def to_mont(v: int) -> np.ndarray:
    """Host conversion into Montgomery-form limbs."""
    return int_to_limbs(v * R_MONT % P)


def from_mont(a) -> int:
    """Host conversion out of Montgomery-form limbs."""
    return limbs_to_int(a) * R_INV % P


P_LIMBS = int_to_limbs(P)
ZERO = np.zeros(NLIMBS, dtype=np.uint32)
ONE_MONT = to_mont(1)


# --- device primitives -----------------------------------------------------
#
# Scan axis convention: limb axis is moved to the front for lax.scan, batch
# dims stay behind it.


def _scan_limbs(f, init, t: jnp.ndarray):
    """Scan f over the last (limb) axis of t; returns stacked outputs with
    the limb axis back in last position."""
    xs = jnp.moveaxis(t, -1, 0)
    _, ys = lax.scan(f, init, xs)
    return jnp.moveaxis(ys, 0, -1)


def carry_propagate(t: jnp.ndarray) -> jnp.ndarray:
    """Normalize accumulator columns to canonical 16-bit limbs (the final
    carry out of the top limb must be zero — guaranteed by callers' bounds)."""

    def step(c, v):
        s = v + c
        return s >> LIMB_BITS, s & MASK

    zero_c = jnp.zeros(t.shape[:-1], jnp.uint32)
    return _scan_limbs(step, zero_c, t)


def _sub_limbs(a: jnp.ndarray, b: jnp.ndarray):
    """(a - b) limbwise with borrow ripple; returns (diff, underflow_flag).
    Inputs canonical; same trailing width."""

    def step(borrow, ab):
        av, bv = ab
        d = av + np.uint32(MASK + 1) - bv - borrow
        return jnp.uint32(1) - (d >> LIMB_BITS), d & MASK

    xs = (jnp.moveaxis(a, -1, 0), jnp.moveaxis(b, -1, 0))
    zero_b = jnp.zeros(a.shape[:-1], jnp.uint32)
    borrow, ys = lax.scan(lambda c, x: step(c, x), zero_b, xs)
    return jnp.moveaxis(ys, 0, -1), borrow.astype(bool)


def _cond_sub_p(t: jnp.ndarray) -> jnp.ndarray:
    """Given canonical limbs of a value < 2p (width NLIMBS or NLIMBS+1),
    subtract p iff value ≥ p. Returns NLIMBS limbs."""
    n = t.shape[-1]
    p_ext = np.zeros(n, dtype=np.uint32)
    p_ext[:NLIMBS] = P_LIMBS
    p_arr = jnp.broadcast_to(jnp.asarray(p_ext), t.shape)
    diff, under = _sub_limbs(t, p_arr)
    out = jnp.where(under[..., None], t, diff)
    return out[..., :NLIMBS]


def add_mod(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    s = a + b  # limbwise, < 2^17
    s = jnp.concatenate(
        [s, jnp.zeros(jnp.broadcast_shapes(a.shape, b.shape)[:-1] + (1,), jnp.uint32)],
        axis=-1,
    )
    return _cond_sub_p(carry_propagate(s))


def sub_mod(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    # (a + p) - b, then conditional subtract p. a+p < 2^17 per limb.
    s = a + P_LIMBS
    s = jnp.concatenate(
        [s, jnp.zeros(jnp.broadcast_shapes(a.shape, b.shape)[:-1] + (1,), jnp.uint32)],
        axis=-1,
    )
    s = carry_propagate(s)
    b_ext = jnp.concatenate(
        [jnp.broadcast_to(b, s.shape[:-1] + (NLIMBS,)),
         jnp.zeros(s.shape[:-1] + (1,), jnp.uint32)],
        axis=-1,
    )
    diff, _ = _sub_limbs(s, b_ext)
    return _cond_sub_p(diff)


def neg_mod(a: jnp.ndarray) -> jnp.ndarray:
    """-a mod p (maps 0 to 0)."""
    p_arr = jnp.broadcast_to(jnp.asarray(P_LIMBS), a.shape)
    diff, _ = _sub_limbs(p_arr, a)
    is_zero_a = jnp.all(a == 0, axis=-1, keepdims=True)
    return jnp.where(is_zero_a, jnp.zeros_like(a), diff)


def montmul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Montgomery product a·b·R⁻¹ mod p (CIOS, lazy column carries, as a
    scan over the 24 operand limbs).

    Bound sketch: a column accumulates ≤ 4 halves (< 2¹⁶ each) per iteration
    plus a shifted-in carry, over ≤ 24 live iterations ⇒ < 2²³ ≪ 2³².
    """
    p_limbs = jnp.asarray(P_LIMBS)
    batch = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    b = jnp.broadcast_to(b, batch + (NLIMBS,))
    a = jnp.broadcast_to(a, batch + (NLIMBS,))
    t0 = jnp.zeros(batch + (NLIMBS + 2,), jnp.uint32)
    zpad2 = jnp.zeros(batch + (2,), jnp.uint32)
    zpad1 = jnp.zeros(batch + (1,), jnp.uint32)

    def step(t, ai):
        prod = ai[..., None] * b  # (..., 24) < 2^32 exact in uint32
        t = t + jnp.concatenate([prod & MASK, zpad2], axis=-1)
        t = t + jnp.concatenate([zpad1, prod >> LIMB_BITS, zpad1], axis=-1)
        m = (t[..., 0] * N0_INV) & MASK
        prod2 = m[..., None] * p_limbs
        t = t + jnp.concatenate([prod2 & MASK, zpad2], axis=-1)
        t = t + jnp.concatenate([zpad1, prod2 >> LIMB_BITS, zpad1], axis=-1)
        # low limb ≡ 0 mod 2^16: shift down one limb, pushing its carry up
        carry = t[..., 0] >> LIMB_BITS
        t = jnp.concatenate([t[..., 1:], zpad1], axis=-1)
        t = t + jnp.concatenate([carry[..., None], jnp.zeros_like(t[..., 1:])], axis=-1)
        return t, None

    t, _ = lax.scan(step, t0, jnp.moveaxis(a, -1, 0))
    return _cond_sub_p(carry_propagate(t))


def montsq(a: jnp.ndarray) -> jnp.ndarray:
    return montmul(a, a)


def pow_fixed(a: jnp.ndarray, exponent: int) -> jnp.ndarray:
    """a^e for a host-known exponent, via lax.scan over its bits (LSB-first
    square-and-multiply with branchless select)."""
    nbits = max(exponent.bit_length(), 1)
    bits = np.array([(exponent >> i) & 1 for i in range(nbits)], dtype=np.uint32)
    one = jnp.broadcast_to(jnp.asarray(ONE_MONT), a.shape).astype(jnp.uint32)

    def step(carry, bit):
        result, base = carry
        taken = montmul(result, base)
        result = jnp.where(bit.astype(bool), taken, result)
        base = montsq(base)
        return (result, base), None

    (result, _), _ = lax.scan(step, (one, a), jnp.asarray(bits))
    return result


def inv_mod(a: jnp.ndarray) -> jnp.ndarray:
    """a⁻¹ (Montgomery form in, Montgomery form out) via Fermat."""
    return pow_fixed(a, P - 2)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == 0, axis=-1)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """cond ? a : b, with cond shaped like the element's batch prefix."""
    return jnp.where(cond[..., None], a, b)
