"""Limb-decomposed Montgomery arithmetic for Fp (BLS12-381 base field) on TPU.

Representation ("relaxed signed digits"): little-endian 26 × 15-bit digits in
int32, shape (..., 26), Montgomery form (value·R mod p, R = 2³⁹⁰). Digits are
redundant and signed: |digit| ≤ LMAX = 2¹⁵ + 256; values are only canonical
modulo p at explicit canonicalization points (equality tests, host export).

Why this shape:
  - products of two digits: ≤ LMAX² < 2³¹ — exact in int32;
  - CIOS column accumulators stay |·| < 2²² — no wide accumulator needed;
  - add/sub/neg are a plain limbwise op plus ONE flat carry-relaxation round
    (arithmetic shift + mask): no borrow ripples, no scans, no conditional
    subtracts. Signed digits are what make subtraction free.
  - value bounds are tracked statically: every intermediate stays |v| < 20p,
    montgomery products then stay < 2p (see montmul docstring), which keeps
    the dropped top carry of the relaxation round provably zero.

The only sequential structures left are the 26-step CIOS scan inside montmul,
the bit scans of fixed-exponent powering, and the canonicalization ripple
used by equality tests. Everything else is flat vector code — the shape XLA
compiles and fuses well.
"""

from __future__ import annotations

import os

import numpy as np
import jax.numpy as jnp
from jax import lax

from grandine_tpu.crypto.constants import P

#: lax.scan unroll factor for the CIOS inner loop (1 = plain while loop;
#: larger values trade compile time for fused step bodies). Tunable via env
#: for kernel experiments.
MONTMUL_UNROLL = int(os.environ.get("GT_MONTMUL_UNROLL", "1"))

LIMB_BITS = 15
NLIMBS = 26
MASK = (1 << LIMB_BITS) - 1
LMAX = (1 << LIMB_BITS) + 256  # relaxed digit bound (see module docstring)
R_MONT = 1 << (LIMB_BITS * NLIMBS)  # 2^390
R_INV = pow(R_MONT, -1, P)
R2 = R_MONT * R_MONT % P
N0_INV = (-pow(P, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS)

_DT = jnp.int32


# --- host-side conversions -------------------------------------------------


def int_to_limbs(v: int) -> np.ndarray:
    """Canonical (non-Montgomery) digit decomposition."""
    assert 0 <= v < R_MONT
    return np.array(
        [(v >> (LIMB_BITS * i)) & MASK for i in range(NLIMBS)], dtype=np.int32
    )


def limbs_to_int(a) -> int:
    a = np.asarray(a)
    return sum(int(a[..., i]) << (LIMB_BITS * i) for i in range(NLIMBS))


def to_mont(v: int) -> np.ndarray:
    return int_to_limbs(v * R_MONT % P)


def from_mont(a) -> int:
    """Host conversion out of Montgomery form (handles redundant/signed
    digits and any value range via exact integer arithmetic)."""
    return limbs_to_int(a) * R_INV % P


P_LIMBS = int_to_limbs(P)
ZERO = np.zeros(NLIMBS, dtype=np.int32)
ONE_MONT = to_mont(1)
# R mod p as digits — folds the 27th result column of montmul back in.
R_MOD_P = int_to_limbs(R_MONT % P)
EIGHT_P = int_to_limbs(8 * P)
# canonical digit patterns of k·p, k = 0..15 (for is_zero after a +8p offset)
_KP_PATTERNS = np.stack([int_to_limbs(k * P) for k in range(16)])  # (16, 26)


# --- flat primitives -------------------------------------------------------


def relax(s: jnp.ndarray) -> jnp.ndarray:
    """One carry-relaxation round, exactly value-preserving: digits 0..24 go
    to [0,2¹⁵) + a signed carry into the next digit; the TOP digit is left
    unsplit (signed). Under the |value| < 20p invariant the top digit stays
    |·| ≲ 2¹¹ (value/2³⁷⁵ plus ≤ 2 of lower-digit compensation), so products
    involving it remain far below int32 overflow. No modular wrap ever
    happens here — values are preserved as integers."""
    hi = s >> LIMB_BITS  # arithmetic shift (floor division)
    lo = s & MASK
    low = lo[..., : NLIMBS - 1] + jnp.concatenate(
        [jnp.zeros(s.shape[:-1] + (1,), _DT), hi[..., : NLIMBS - 2]], axis=-1
    )
    top = s[..., NLIMBS - 1 :] + hi[..., NLIMBS - 2 : NLIMBS - 1]
    return jnp.concatenate([low, top], axis=-1)


def add_mod(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return relax(a + b)


def sub_mod(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return relax(a - b)


def neg_mod(a: jnp.ndarray) -> jnp.ndarray:
    return relax(-a)


def montmul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Montgomery product a·b·R⁻¹ mod p (CIOS over signed digits).

    Value bound: for |a|,|b| < 20p, |a·b| < 400p² ≲ R·p, so the reduced value
    lies in (-0.1p, 2p) and the relaxed output digits are ≤ LMAX. Inputs are
    digit-bounded by LMAX (products < 2³¹) and value-bounded by callers.
    """
    p_limbs = jnp.asarray(P_LIMBS)
    batch = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    b = jnp.broadcast_to(b, batch + (NLIMBS,)).astype(_DT)
    a = jnp.broadcast_to(a, batch + (NLIMBS,)).astype(_DT)
    t0 = jnp.zeros(batch + (NLIMBS + 1,), _DT)
    zpad1 = jnp.zeros(batch + (1,), _DT)
    zpadN = jnp.zeros(batch + (NLIMBS - 1,), _DT)

    def step(t, ai):
        prod = ai[..., None] * b  # |·| < 2^31 exact
        t = t + jnp.concatenate([prod & MASK, zpad1], axis=-1)
        t = t + jnp.concatenate([zpad1, prod >> LIMB_BITS], axis=-1)
        m = (t[..., 0] * N0_INV) & MASK
        prod2 = m[..., None] * p_limbs
        t = t + jnp.concatenate([prod2 & MASK, zpad1], axis=-1)
        t = t + jnp.concatenate([zpad1, prod2 >> LIMB_BITS], axis=-1)
        carry = t[..., 0] >> LIMB_BITS  # exact: t[...,0] ≡ 0 mod 2^15
        t = jnp.concatenate([t[..., 1:], zpad1], axis=-1)
        t = t + jnp.concatenate([carry[..., None], zpadN, zpad1], axis=-1)
        return t, None

    t, _ = lax.scan(step, t0, jnp.moveaxis(a, -1, 0), unroll=MONTMUL_UNROLL)
    # fold the 27th column (weight 2^390 = R) back in via R mod p
    main = t[..., :NLIMBS] + t[..., NLIMBS : NLIMBS + 1] * jnp.asarray(R_MOD_P)
    return relax(main)


def montsq(a: jnp.ndarray) -> jnp.ndarray:
    return montmul(a, a)


def pow_fixed(a: jnp.ndarray, exponent: int) -> jnp.ndarray:
    """a^e for a host-known exponent (LSB-first square-and-multiply scan)."""
    nbits = max(exponent.bit_length(), 1)
    bits = np.array([(exponent >> i) & 1 for i in range(nbits)], dtype=np.int32)
    one = jnp.broadcast_to(jnp.asarray(ONE_MONT), a.shape).astype(_DT)

    def step(carry, bit):
        result, base = carry
        taken = montmul(result, base)
        result = jnp.where(bit.astype(bool), taken, result)
        base = montsq(base)
        return (result, base), None

    (result, _), _ = lax.scan(step, (one, a), jnp.asarray(bits))
    return result


def inv_mod(a: jnp.ndarray) -> jnp.ndarray:
    """a⁻¹ via Fermat (Montgomery in/out). inv(0) = 0."""
    return pow_fixed(a, P - 2)


# --- canonicalization & predicates ----------------------------------------


def canonical_digits(t: jnp.ndarray) -> jnp.ndarray:
    """Full ripple to canonical digits in [0, 2¹⁵). Only correct for
    non-negative values < 2³⁹⁰ — callers offset by +4p first."""

    def step(c, v):
        s = v + c
        return s >> LIMB_BITS, s & MASK

    xs = jnp.moveaxis(t, -1, 0)
    _, ys = lax.scan(step, jnp.zeros(t.shape[:-1], _DT), xs)
    return jnp.moveaxis(ys, 0, -1)


def is_zero_val(a: jnp.ndarray) -> jnp.ndarray:
    """value(a) ≡ 0 (mod p), for |value| < 8p (the widest bound any caller
    reaches — mixed-add Z outputs are < 6p): canonicalize a+8p and compare
    against the digit patterns of k·p, k = 0..15."""
    canon = canonical_digits(a + jnp.asarray(EIGHT_P))
    pats = jnp.asarray(_KP_PATTERNS)  # (16, 26)
    eq = jnp.all(canon[..., None, :] == pats, axis=-1)  # (..., 16)
    return jnp.any(eq, axis=-1)


def is_one_mont(a: jnp.ndarray) -> jnp.ndarray:
    """value(a) ≡ 1·R (mod p) — same bound discipline as is_zero_val."""
    return is_zero_val(a - jnp.asarray(ONE_MONT))


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """cond ? a : b, with cond shaped like the element's batch prefix."""
    return jnp.where(cond[..., None], a, b)
