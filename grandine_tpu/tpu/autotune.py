"""MSM window/bucket calibration sweep (ROADMAP lever d).

The analytic op model in `bls.pick_msm_window` predicts the cheapest
Pippenger window width; this module MEASURES it. For each probed
(n_points, n_groups) shape it times the real MSM device graph —
`expand_glv_points` + `msm_bucket_scan` over the same plan arrays the
verify kernels use — once per candidate window, and records the fastest.

The winning table persists next to the shape manifest as
`tools/shapes/msm_tune.json` ({"windows": {"<n>:<g>": w}}), where
`bls.load_msm_tuning` picks it up ahead of the analytic model and
`runtime/warmup.py` loads it before warming, so the warmed kernel plans
and the steady-state plans agree (a tuned window only helps if the
warmup compiled THAT window's shapes).

Probe cost is dominated by XLA compiles (shapes × windows programs), so
the default sweep is deliberately small; `python -m tools.shapes
--autotune` runs it and reports per-cell timings.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

import jax
import jax.numpy as jnp

from grandine_tpu.tpu import bls as B
from grandine_tpu.tpu import curve as C
from grandine_tpu.tpu import limbs as L
from grandine_tpu.tpu import msm as M

#: candidate Pippenger window widths (matches pick_msm_window's scan)
WINDOWS = (4, 5, 6, 7, 8)

#: default probed (n_points, n_groups) cells — pow-2 bucket shapes the
#: dispatch plane actually produces (flat multi_verify G2 MSM and the
#: grouped aggregate G1 MSM's widest tier-1 shapes)
DEFAULT_SHAPES = ((64, 1), (256, 1), (64, 16))


def _probe_field_rows(n: int, seed: int) -> "np.ndarray":
    """(n, NLIMBS) int32 host rows of deterministic pseudo-random Fp
    elements in Montgomery form. The MSM graph's op count and memory
    traffic do not depend on point VALIDITY, only on shapes — arbitrary
    field elements time identically to curve points."""
    rng = np.random.RandomState(seed)
    rows = np.zeros((n, L.NLIMBS), np.int32)
    for i in range(n):
        v = int.from_bytes(rng.bytes(48), "big") % L.P
        rows[i] = [int(d) for d in L.to_mont(v)]
    return rows


def _probe_fn(windows: int, wbits: int, n_groups: int):
    """The jitted MSM probe body: GLV expansion + bucket scan, identical
    structure to the verify kernels' G1 MSM stage."""

    def probe(px, py, inf, pidx, valid, flush, gidx, gvalid):
        x, y = B._g1_in(px, py)
        n = inf.shape[0]
        ex, ey, live = M.expand_glv_points(
            x, y, jnp.asarray(inf), B._g1_endo(n), C.FP_OPS
        )
        acc = M.msm_bucket_scan(
            ex, ey, live, pidx, valid, flush, gidx, gvalid,
            windows=windows, window_bits=wbits, n_groups=n_groups,
            ops=C.FP_OPS,
        )
        # one limb plane is enough to force the whole scan
        return acc[0][0]

    return probe


def time_window(n_points: int, n_groups: int, wbits: int,
                repeats: int = 3, seed: int = 7) -> float:
    """Best-of-`repeats` wall seconds for one (shape, window) cell,
    compile excluded (first call pays it, timing starts after)."""
    rng = np.random.RandomState(seed)
    r_lo = rng.randint(1, 1 << 31, size=n_points).astype(np.uint64)
    r_hi = rng.randint(1, 1 << 31, size=n_points).astype(np.uint64)
    inf = np.zeros(n_points, bool)
    groups = (
        None if n_groups == 1
        else np.arange(n_points, dtype=np.int64) % n_groups
    )
    plan = M.plan_msm(r_lo, r_hi, inf, groups, n_groups, window_bits=wbits)
    px = _probe_field_rows(n_points, seed)
    py = _probe_field_rows(n_points, seed + 1)
    fn = jax.jit(_probe_fn(plan.windows, plan.window_bits, plan.n_groups))
    args = [jax.device_put(a) for a in
            (px, py, inf) + tuple(plan.arrays)]
    fn(*args).block_until_ready()  # compile
    best = None
    for _ in range(max(1, int(repeats))):
        t0 = time.perf_counter()
        fn(*args).block_until_ready()
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return best


def sweep(shapes=DEFAULT_SHAPES, windows=WINDOWS, repeats: int = 3,
          verbose=print) -> "dict[str, int]":
    """Measure every (shape, window) cell; return the winning window per
    shape keyed exactly as `pick_msm_window` looks them up."""
    table: "dict[str, int]" = {}
    for n_points, n_groups in shapes:
        n_b = B._bucket(n_points)
        g_b = B._bucket(max(1, n_groups), lo=1)
        key = "%d:%d" % (n_b, g_b)
        best_w, best_t = None, None
        for w in windows:
            dt = time_window(n_b, g_b, w, repeats=repeats)
            if verbose is not None:
                verbose("  msm %s w=%d: %.4fs" % (key, w, dt))
            if best_t is None or dt < best_t:
                best_w, best_t = w, dt
        table[key] = int(best_w)
        if verbose is not None:
            verbose("  msm %s -> w=%d" % (key, best_w))
    return table


def write_tuning(table: "dict[str, int]", path=None) -> str:
    """Persist the table where `bls.load_msm_tuning` reads it, and drop
    the in-process cache so this process sees it immediately."""
    path = path or B.msm_tune_path()
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(
            {"windows": {k: int(v) for k, v in sorted(table.items())}},
            fh, indent=2, sort_keys=True,
        )
        fh.write("\n")
    os.replace(tmp, path)
    B.set_msm_tuning(None)
    return path


def autotune(shapes=DEFAULT_SHAPES, windows=WINDOWS, repeats: int = 3,
             path=None, verbose=print) -> "dict[str, int]":
    """Full lever-d cycle: sweep, persist, reload."""
    table = sweep(shapes=shapes, windows=windows, repeats=repeats,
                  verbose=verbose)
    out = write_tuning(table, path=path)
    if verbose is not None:
        verbose("wrote %d tuned windows -> %s" % (len(table), out))
    return table


__all__ = [
    "WINDOWS",
    "DEFAULT_SHAPES",
    "time_window",
    "sweep",
    "write_tuning",
    "autotune",
]
