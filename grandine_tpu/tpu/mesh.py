"""The verify plane's device-mesh seam.

Every multi-device decision in the verify plane flows through ONE object
built here: a `VerifyMesh` wrapping a 1-D `jax.sharding.Mesh` over the
`"batch"` axis (the SNIPPETS [1]-[3] pjit/shard_map exemplars). The seam
exists so that

  - device topology is INJECTED, never discovered, inside dispatch paths
    (`tools/lint` forbids `jax.devices()` calls there — `VerifyMesh.build`
    below is the single sanctioned enumeration point);
  - the single-device node is the degenerate case: `device_count == 1`
    makes every consumer behave exactly as if no mesh existed (no
    `NamedSharding` placements, same jit cache keys, same executables),
    so `verify_recompiles_total == 0` steady-state and all single-chip
    behavior hold unchanged;
  - sharding layouts are named once: batch-dim sharding for per-signature
    operands and registry rows, `P(None, "batch")` for (M, K) grouped
    member arrays, replication for per-group messages.

The mesh is 1-D on purpose. The workload's only cross-chip reduction is
the pairing-product all-gather (a few KB per chip — see
`tpu/bls.py make_sharded_multi_verify`); a second mesh axis buys nothing
until single-axis scaling saturates ICI, which the `bench.py --devices`
sweep exists to detect.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

#: the one mesh axis name the verify plane shards over
BATCH_AXIS = "batch"


class VerifyMesh:
    """An injected device mesh + its named sharding vocabulary.

    Construction is lazy-import friendly: building a `VerifyMesh` touches
    jax (backend initialization), so runtime modules hold `mesh=None`
    until a caller that already owns a jax backend hands one in.
    """

    def __init__(self, devices: "Sequence", axis: str = BATCH_AXIS) -> None:
        from jax.sharding import Mesh

        devices = list(devices)
        if not devices:
            raise ValueError("VerifyMesh needs at least one device")
        n = len(devices)
        if n & (n - 1):
            raise ValueError(
                f"VerifyMesh needs a power-of-two device count, got {n}"
            )
        self.axis = axis
        self.mesh = Mesh(np.array(devices), (axis,))
        self.devices = tuple(devices)

    # ----------------------------------------------------------- topology

    @property
    def device_count(self) -> int:
        return len(self.devices)

    @property
    def is_single(self) -> bool:
        """True for the degenerate 1-device mesh — consumers must treat
        this exactly like `mesh is None` (no placements, no sharded
        kernels) so single-chip behavior stays byte-identical."""
        return self.device_count == 1

    def describe(self) -> str:
        """Stable shape string for flight records / bench JSON (a field,
        never a Prometheus label)."""
        return f"{self.axis}:{self.device_count}"

    def divides(self, n: int) -> bool:
        """True when a length-n batch axis shards evenly over the mesh."""
        return n >= self.device_count and n % self.device_count == 0

    # ---------------------------------------------------------- shardings

    def batch_sharding(self):
        """Rows sharded over the mesh: per-signature operands, registry
        rows, per-chip plan stacks — `P("batch")` on axis 0."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P(self.axis))

    def member_sharding(self):
        """(M, K, ...) grouped member arrays sharded over K —
        `P(None, "batch")`."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P(None, self.axis))

    def replicated(self):
        """One full copy per device: per-group messages, small scalars."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    def put(self, arrays: tuple, sharding) -> tuple:
        """Place a tuple of host arrays with one explicit sharding."""
        import jax

        return tuple(jax.device_put(a, sharding) for a in arrays)

    # -------------------------------------------------------- construction

    @classmethod
    def build(cls, count: "Optional[int]" = None,
              platform: "Optional[str]" = None) -> "VerifyMesh":
        """Enumerate devices and build the mesh — the ONE place the verify
        plane calls `jax.devices()`. `count=None` takes every visible
        device (rounded down to a power of two); an explicit `count` must
        be satisfiable or this raises.

        On the CPU platform the visible device count comes from
        `XLA_FLAGS=--xla_force_host_platform_device_count=N`, which XLA
        parses once per process BEFORE the first backend call — callers
        wanting an N-device CPU mesh must set it pre-import (bench.py's
        `--devices` sweep runs each count in a fresh subprocess for
        exactly this reason).
        """
        import jax

        devices = jax.devices(platform) if platform else jax.devices()
        if count is None:
            count = 1 << (len(devices).bit_length() - 1)
        if count < 1 or count > len(devices):
            raise ValueError(
                f"mesh of {count} devices requested, platform has "
                f"{len(devices)}"
            )
        return cls(devices[:count])


def mesh_or_none(mesh: "Optional[VerifyMesh]") -> "Optional[VerifyMesh]":
    """Normalize the degenerate mesh: a 1-device VerifyMesh and None are
    the SAME configuration to every consumer; collapsing here keeps the
    `mesh is None or mesh.is_single` predicate out of call sites."""
    if mesh is None or mesh.is_single:
        return None
    return mesh


__all__ = ["VerifyMesh", "mesh_or_none", "BATCH_AXIS"]
