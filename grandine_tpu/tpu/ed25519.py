"""Batched Ed25519 verification on device — the curve25519 entry of the
scheme dispatch table (tpu/schemes.py).

Field plane: the limbs.py representation instantiated for p = 2²⁵⁵−19 —
limb-major relaxed signed 15-bit digits, int32, Montgomery form with
R = 2²⁷⁰ (18 limbs). 17 limbs would cover 255 bits exactly but leaves
ZERO headroom between p and R: the |value| < 20p working bound that
makes the relaxation round's dropped carry provably zero needs value
room above p, and R·p must dominate the 400p² Montgomery product bound
(2²⁷⁰·p ≈ 2⁵²⁵ vs 400p² ≈ 2⁵¹⁹ — the 18th limb is the safety margin,
exactly like 26 limbs over the 381-bit BLS field). The per-site
digit-product/accumulator/operand bounds of this plane are
machine-checked alongside the BLS plane — with LIMB_BITS/NLIMBS parsed
from this file's source — and certified into tools/ranges/bounds.txt
(`python -m tools.ranges --write-cert`); p/R = 2⁻¹⁵ here, so every
Montgomery product contracts the value hull far harder than on the
BLS plane. All structural
choices (leading limb axis, tuple-carry CIOS scan, one relaxation round
per add) are limbs.py's, re-derived here for the smaller field; see
that module's docstring for the measurements behind them.

Curve plane: twisted Edwards a = −1 in extended coordinates with the
strongly-unified add-2008-hwcd-3 formula — COMPLETE for a = −1 on
points with correct T, so one formula serves add and double, identity
needs no special case, and padding slots are plain (0, 1) identity
points with zero scalars (algebraically neutral, branch-free).

Verification is the cofactored RFC 8032 batch equation under a random
linear combination. Host prep draws 128-bit z_i, folds the S_i into one
base-point scalar c_B = Σ z_i·S_i mod L, and pre-negates R_i and A_i,
so the device evaluates ONE multi-scalar multiplication

    T = [c_B]B + Σ [z_i](−R_i) + Σ [z_i·k_i mod L](−A_i)

as a batched 253-bit MSB ladder + a log-depth sum tree, then clears the
cofactor with three unified doublings ([8]T) and runs the fused
identity test (X ≡ 0 ∧ Y ≡ Z). Reducing z_i·k_i mod L is sound ONLY
because the ×8 follows the sum: L·A_i is 8-torsion for any decoded
point, and the final ×8 kills it — the same reason the host twin
(crypto/ed25519.py) must be cofactored for verdicts to match
bit-for-bit. All verdict-relevant decode checks (canonical y, S < L
malleability bound) run on host in `prepare`, identically to the twin.

Kernel registration rides the BLS plane's global jit cache +
shape-ledger (`_jitted_global` / `note_dispatch_shapes` in tpu/bls.py),
so persistent-cache behavior and the zero-post-warmup-recompile
invariant cover this scheme with no new machinery.
"""

from __future__ import annotations

import secrets

import numpy as np
import jax.numpy as jnp
from jax import lax

from grandine_tpu.crypto import ed25519 as HE
from grandine_tpu.tracing import NULL_TRACER

LIMB_BITS = 15
NLIMBS = 18
MASK = (1 << LIMB_BITS) - 1
P = HE.P
R_MONT = 1 << (LIMB_BITS * NLIMBS)  # 2^270
R_INV = pow(R_MONT, -1, P)
N0_INV = (-pow(P, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS)
#: ladder bit width: every RLC scalar is < 2^253 (c_B and z·k are
#: reduced mod L < 2^253; the z_i are 128-bit)
NBITS = 253

_DT = jnp.int32


# --- host-side conversions -------------------------------------------------


def int_to_limbs(v: int) -> np.ndarray:
    assert 0 <= v < R_MONT
    return np.array(
        [(v >> (LIMB_BITS * i)) & MASK for i in range(NLIMBS)], dtype=np.int32
    )


def limbs_to_int(a) -> int:
    a = np.asarray(a)
    return sum(int(a[..., i]) << (LIMB_BITS * i) for i in range(NLIMBS))


def to_mont(v: int) -> np.ndarray:
    return int_to_limbs(v * R_MONT % P)


def from_mont(a) -> int:
    return limbs_to_int(a) * R_INV % P


P_LIMBS = int_to_limbs(P)
ONE_MONT = to_mont(1)
R_MOD_P = int_to_limbs(R_MONT % P)
EIGHT_P = int_to_limbs(8 * P)
_KP_PATTERNS = np.stack([int_to_limbs(k * P) for k in range(16)])  # (16, 18)

P_DIGITS = [int(x) for x in P_LIMBS]
R_MOD_P_DIGITS = [int(x) for x in R_MOD_P]
ONE_MONT_DIGITS = [int(x) for x in ONE_MONT]
EIGHT_P_DIGITS = [int(x) for x in EIGHT_P]
#: 2d in Montgomery form (the unified-add constant)
K2D_DIGITS = [int(x) for x in to_mont(2 * HE.D % P)]


def ints_to_mont_limbs(values) -> np.ndarray:
    """[v_0, …] → (N, 18) int32 Montgomery digit arrays, vectorized
    (curve.ints_to_mont_limbs re-derived for the 25519 field)."""
    n = len(values)
    if n == 0:
        return np.zeros((0, NLIMBS), np.int32)
    nb = (LIMB_BITS * NLIMBS + 7) // 8  # 34 bytes for 270 bits
    buf = bytearray(n * nb)
    for i, v in enumerate(values):
        buf[i * nb : (i + 1) * nb] = (v * R_MONT % P).to_bytes(nb, "little")
    raw = np.frombuffer(bytes(buf), np.uint8).reshape(n, nb)
    bits = np.unpackbits(raw, axis=1, bitorder="little")
    bits = bits[:, : NLIMBS * LIMB_BITS].reshape(n, NLIMBS, LIMB_BITS)
    weights = (1 << np.arange(LIMB_BITS, dtype=np.int64)).astype(np.int32)
    return (bits.astype(np.int32) * weights).sum(axis=2).astype(np.int32)


# --- structure helpers (device fp = (18, *batch) int32) --------------------


def split(arr) -> jnp.ndarray:
    """REST (…, 18) → device (18, …)."""
    return jnp.moveaxis(jnp.asarray(arr), -1, 0)


def merge(fp) -> jnp.ndarray:
    return jnp.moveaxis(fp, 0, -1)


def const_fp(digits, shape=()) -> jnp.ndarray:
    d = jnp.asarray(np.asarray(digits, dtype=np.int32))
    return jnp.broadcast_to(
        d.reshape((NLIMBS,) + (1,) * len(shape)), (NLIMBS,) + tuple(shape)
    )


def select(cond, a, b) -> jnp.ndarray:
    return jnp.where(cond[None], a, b)


# --- flat primitives (limbs.py technique at 18 limbs) ----------------------


def relax(s) -> jnp.ndarray:
    """One carry-relaxation round, exactly value-preserving; the top
    digit stays unsplit (signed) — |value| < 20p keeps it ≲ 2⁵."""
    hi = s[: NLIMBS - 1] >> LIMB_BITS
    lo = s[: NLIMBS - 1] & MASK
    top = s[NLIMBS - 1 :] + hi[NLIMBS - 2 :]
    shifted = jnp.concatenate([jnp.zeros_like(hi[:1]), hi[: NLIMBS - 2]], 0)
    return jnp.concatenate([lo + shifted, top], axis=0)


def add_mod(a, b) -> jnp.ndarray:
    return relax(a + b)


def sub_mod(a, b) -> jnp.ndarray:
    return relax(a - b)


def double_mod(a) -> jnp.ndarray:
    return relax(a + a)


def montmul(a, b) -> jnp.ndarray:
    """Montgomery product a·b·R⁻¹ mod p: CIOS over signed digits (see
    limbs.montmul — same scan, 19 column accumulators). For |a|,|b| <
    20p, |a·b| < 400p² < R·p, so the reduced value lies in (−0.1p, 2p)
    and the relaxed output digits are bounded."""
    shape = jnp.broadcast_shapes(a.shape[1:], b.shape[1:])
    a = jnp.broadcast_to(a, (NLIMBS,) + shape).astype(_DT)
    b = jnp.broadcast_to(b, (NLIMBS,) + shape).astype(_DT)
    bl = [b[j] for j in range(NLIMBS)]
    t0 = tuple(jnp.zeros(shape, _DT) for _ in range(NLIMBS + 1))

    def step(t, ai):
        t = list(t)
        for j in range(NLIMBS):
            prod = ai * bl[j]  # |·| < 2^31 exact
            t[j] = t[j] + (prod & MASK)
            t[j + 1] = t[j + 1] + (prod >> LIMB_BITS)
        m = (t[0] * N0_INV) & MASK
        for j in range(NLIMBS):
            prod2 = m * P_DIGITS[j]
            t[j] = t[j] + (prod2 & MASK)
            t[j + 1] = t[j + 1] + (prod2 >> LIMB_BITS)
        carry = t[0] >> LIMB_BITS  # exact: t[0] ≡ 0 mod 2^15
        t = t[1:] + [jnp.zeros(shape, _DT)]
        t[0] = t[0] + carry
        return tuple(t), None

    t, _ = lax.scan(step, t0, a)
    # fold the 19th column (weight 2^270 = R) back in via R mod p, relax
    main = jnp.stack(
        [t[j] + t[NLIMBS] * R_MOD_P_DIGITS[j] for j in range(NLIMBS)], 0
    )
    return relax(main)


def canonical_digits(t) -> jnp.ndarray:
    """Full ripple to canonical digits in [0, 2¹⁵) — non-negative values
    < 2²⁷⁰ only; callers offset by +8p first."""

    def step(c, v):
        s = v + c
        return s >> LIMB_BITS, s & MASK

    carry, ys = lax.scan(step, jnp.zeros(t.shape[1:], _DT), t[: NLIMBS - 1])
    return jnp.concatenate([ys, t[NLIMBS - 1 :] + carry[None]], axis=0)


def is_zero_val(a) -> jnp.ndarray:
    """value(a) ≡ 0 (mod p) for |value| < 8p: canonicalize a+8p and
    compare against the digit patterns of k·p, k = 0..15."""
    a = jnp.asarray(a)
    canon = canonical_digits(a + const_fp(EIGHT_P_DIGITS, a.shape[1:]))
    pats = jnp.asarray(np.ascontiguousarray(_KP_PATTERNS.T))  # (18, 16)
    pats = pats.reshape((NLIMBS, 16) + (1,) * (canon.ndim - 1))
    eq = canon[:, None] == pats
    return jnp.any(jnp.all(eq, axis=0), axis=0)


# --- Edwards curve plane ---------------------------------------------------


def ed_add(p, q):
    """Unified add-2008-hwcd-3 (a = −1): complete on correctly-extended
    points — also the doubling. 8 montmuls + the 2d constant mult; every
    montmul input is relaxed (digit-bounded) and value-bounded < 6p."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    k2d = const_fp(K2D_DIGITS, x1.shape[1:])
    a = montmul(sub_mod(y1, x1), sub_mod(y2, x2))
    b = montmul(add_mod(y1, x1), add_mod(y2, x2))
    c = montmul(montmul(t1, k2d), t2)
    d = double_mod(montmul(z1, z2))
    e = sub_mod(b, a)
    f = sub_mod(d, c)
    g = add_mod(d, c)
    h = add_mod(b, a)
    return (montmul(e, f), montmul(g, h), montmul(f, g), montmul(e, h))


def _ladder(px, py, pt, bits_msb):
    """[k_i]P_i for a batch of affine extended points, k as (NBITS, B)
    MSB-first bits. Identity accumulator + complete adds: no started
    flag, zero scalars yield the identity (padding is free)."""
    shape = px.shape[1:]
    one = const_fp(ONE_MONT_DIGITS, shape)
    zero = jnp.zeros_like(px)
    base = (px, py, one, pt)
    acc0 = (zero, one, one, jnp.zeros_like(px))

    def step(acc, bit):
        acc = ed_add(acc, acc)
        added = ed_add(acc, base)
        cond = bit.astype(bool)
        return tuple(
            select(cond, after, before)
            for before, after in zip(acc, added)
        ), None

    acc, _ = lax.scan(step, acc0, bits_msb)
    return acc


def _sum_tree(pts):
    """Reduce the (18, B) point batch to one point: fixed-shape
    masked-roll reduction (curve._tree_reduce_points' trick — one
    compiled body for all log₂B levels)."""
    n = pts[0].shape[1]
    assert n & (n - 1) == 0, "ed25519 sum tree requires a power-of-two batch"
    levels = n.bit_length() - 1
    if levels:

        def body(_, carry):
            y, s = carry
            rolled = tuple(jnp.roll(c, -s, axis=1) for c in y)
            y = ed_add(y, rolled)
            return (y, s // 2)

        (pts, _) = lax.fori_loop(0, levels, body, (pts, jnp.int32(n // 2)))
    return tuple(c[:, 0] for c in pts)


def verify_kernel(px, py, pt, bits):
    """One batched cofactored RLC verdict: px/py/pt (B, 18) REST-format
    Montgomery affine-extended coords, bits (B, 253) MSB-first scalar
    bits. Returns a scalar bool."""
    x, y, t = split(px), split(py), split(pt)
    acc = _ladder(x, y, t, jnp.transpose(jnp.asarray(bits)))
    s = _sum_tree(acc)
    for _ in range(3):  # ×8: clear the cofactor AFTER the RLC sum
        s = ed_add(s, s)
    sx, sy, sz, _st = s
    # identity in extended projective form: X ≡ 0 ∧ Y ≡ Z (mod p)
    zt = jnp.stack([sx, sub_mod(sy, sz)], axis=1)  # (18, 2)
    return jnp.all(is_zero_val(zt))


# --- host-facing backend ---------------------------------------------------


def _ladder_bucket(m: int) -> int:
    """Pow-4 bucket ladder {8, 32, 128}: fewer warm shapes than pow-2
    at the cost of ≤ 4× padding — the ladder is batched, so padding
    costs lanes, not steps."""
    b = 8
    while b < m:
        b *= 4
    return b


class Ed25519Backend:
    """The ed25519 scheme backend (built via schemes.get("ed25519"),
    one per lane). Host prep decodes strictly (canonical y, S < L),
    draws the RLC coefficients, and buckets the MSM batch; the device
    runs one ladder + sum-tree + cofactor-clear + identity-test pass."""

    ASYNC_SEAM = ("verify_batch_async",)
    #: beyond this the 2n+1-point MSM leaves the warmed {8,32,128}
    #: ladder buckets — prepare reports "oversize" and the scheduler
    #: degrades the batch to the host twin (never a new shape mid-slot)
    MAX_ITEMS = 63

    def __init__(self, *, metrics=None, tracer=None, lane: str = "ed25519",
                 mesh=None, rng=None) -> None:
        self.metrics = metrics
        self.tracer = tracer or NULL_TRACER
        self.lane = lane
        #: randbits source for the RLC coefficients (tests inject a
        #: deterministic twin)
        self.rng = rng if rng is not None else secrets

    def _count_kernel(self, kernel: str, sigs: int) -> None:
        if self.metrics is not None:
            self.metrics.device_kernel_calls.labels(kernel).inc()
            if sigs:
                self.metrics.device_kernel_sigs.labels(kernel).inc(sigs)

    def prepare(self, items):
        """(status, payload): "ok" → arrays for verify_batch_async,
        "invalid" → some item can never verify (bad encoding, S ≥ L —
        the batch must FAIL so bisection isolates), "oversize" → degrade
        to the host path."""
        n = len(items)
        if n == 0:
            return "ok", ()
        if n > self.MAX_ITEMS:
            return "oversize", None
        decoded = []
        for it in items:
            keys = it.public_keys
            if keys is None or len(keys) != 1:
                return "invalid", None
            sig = bytes(it.signature)
            if len(sig) != 64:
                return "invalid", None
            pk = bytes(keys[0])
            a_pt = HE.point_decompress(pk)
            r_pt = HE.point_decompress(sig[:32])
            if a_pt is None or r_pt is None:
                return "invalid", None
            s = int.from_bytes(sig[32:], "little")
            if s >= HE.L:  # malleability bound, same rule as the twin
                return "invalid", None
            k = int.from_bytes(
                HE.sha512(sig[:32] + pk + bytes(it.message)), "little"
            ) % HE.L
            decoded.append((a_pt, r_pt, s, k))
        zs = [self.rng.randbits(128) | 1 for _ in range(n)]
        c_b = sum(z * s for z, (_, _, s, _) in zip(zs, decoded)) % HE.L
        # MSM rows: [c_B]B, [z_i](−R_i), [z_i·k_i](−A_i); pads are the
        # identity point with scalar zero
        points = [(HE.BASE[0], HE.BASE[1])]
        scalars = [c_b]
        for z, (_, r_pt, _, _) in zip(zs, decoded):
            points.append(((P - r_pt[0]) % P, r_pt[1]))
            scalars.append(z)
        for z, (a_pt, _, _, k) in zip(zs, decoded):
            points.append(((P - a_pt[0]) % P, a_pt[1]))
            scalars.append(z * k % HE.L)
        bm = _ladder_bucket(len(points))
        while len(points) < bm:
            points.append((0, 1))
            scalars.append(0)
        xs = [x for x, _ in points]
        ys = [y for _, y in points]
        ts = [x * y % P for x, y in points]
        limbs = ints_to_mont_limbs(xs + ys + ts)
        px, py, pt = limbs[:bm], limbs[bm : 2 * bm], limbs[2 * bm :]
        from grandine_tpu.tpu import curve as C

        bits = C.scalars_to_bits_msb(scalars, NBITS)
        return "ok", (px, py, pt, bits, n)

    def verify_batch_async(self, prep):
        """Dispatch the prepared batch; returns the zero-arg settle
        (forces the device verdict)."""
        if not prep:
            return lambda: True
        px, py, pt, bits, n = prep
        from grandine_tpu.tpu import bls as B

        fn = B._jitted_global("ed25519_verify", verify_kernel)
        args = (px, py, pt, bits)
        B.note_dispatch_shapes("ed25519_verify", args, self.metrics)
        self._count_kernel("ed25519_verify", n)
        with self.tracer.span(
            "device_dispatch", {"kernel": "ed25519_verify", "lane": self.lane}
        ):
            with B._node_profiler().annotate("ed25519_verify", n):
                out = fn(*args)

        def settle() -> bool:
            return bool(np.asarray(out))

        return settle


__all__ = [
    "Ed25519Backend",
    "NBITS",
    "NLIMBS",
    "ed_add",
    "verify_kernel",
    "to_mont",
    "from_mont",
    "ints_to_mont_limbs",
    "montmul",
    "is_zero_val",
]
