"""Device-resident validator pubkey registry.

Committee-based consensus re-verifies the SAME validator keys every slot,
yet the verify plane used to re-upload each batch's pubkey rows (26 limbs
× 2 coords × 4 B = 208 B/key) on the per-batch clock — ~4× the device
execute time at the 50k-validator operating point (BENCH r5). This module
keeps the whole validator set's decompressed G1 pubkeys resident on the
accelerator as flat rest-format limb arrays; the indexed verify kernels
(`tpu/bls.py` *_idx_kernel) `gather` rows on-device from an int32 index
vector, so per-batch host→device traffic shrinks to signatures + message
points + indices.

Freshness model (the registry is an append-mostly mirror of
`state.validators`):
  - `ensure(pubkeys)` is called with the head state's compressed-pubkey
    tuple (`accessors.registry_columns(state).pubkeys`). States sharing an
    unmodified registry share ONE tuple object, so the hot check is a
    single identity comparison.
  - Validator-set GROWTH (deposits) extends the registry without touching
    existing rows: a prefix match appends only the new rows (an O(new)
    device scatter into spare capacity; capacity grows in powers of two so
    the gather kernels recompile only on capacity doubling).
  - `mark_stale()` (wired to the controller's `on_validator_set_change`
    hook: validator-count or finalized-epoch change) demotes the next
    `ensure` from the identity fast path to the full prefix check;
    `invalidate()` drops everything and forces a cold rebuild.

Ingest is the compressed-ingest path (PR 17): deposit-batch churn uploads
the RAW 48-byte compressed rows (48 B/row instead of 208 B/row of affine
limbs — ~4.3× less per-row traffic) and decompresses them on device with
the batched `g1_decompress` kernel (tpu/curve.py sqrt ladders), so the
per-key pure-Python `Fq2`-style host sqrt disappears from registry builds
too. The host mirror holds the same raw bytes, so capacity growth
re-uploads without re-decompressing anything anywhere.

Rows are guaranteed non-identity: `_raw_rows` rejects the infinity
encoding (and any wire-malformed blob) before it can enter the mirror, so
indexed kernels need no per-row infinity handling beyond the batch
padding mask the caller supplies. A payload that is wire-well-formed but
off-curve/non-canonical (possible only for corrupted input — registry
bytes passed KeyValidate at deposit time) is zeroed by the device
decompressor's validity mask: fail-closed, any verification naming that
row fails, and the host mirror stays authoritative for naming it.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

import numpy as np

from grandine_tpu.consensus import keys
from grandine_tpu.crypto import bls as A
from grandine_tpu.tpu import curve as C
from grandine_tpu.tpu import limbs as L

#: smallest device capacity — below this, padding waste is noise and a
#: stable floor avoids recompiling the gather kernels for tiny devnets
MIN_CAPACITY = 16

#: the mainnet operating point: ≥1M active validators. A manifest bound
#: and warmup-ladder row (tools/shapes), so the 2^20 gather-kernel
#: capacity pre-warms like any other contract instead of compiling the
#: first time a mainnet-sized state walks in.
MAINNET_CAPACITY = 1 << 20


def _next_pow2(n: int, lo: int = MIN_CAPACITY) -> int:
    b = lo
    while b < n:
        b <<= 1
    return b


class DevicePubkeyRegistry:
    """The validator set's G1 pubkeys, device-resident and index-addressable.

    Thread-safe: `ensure` may be called from any verify-pool thread; the
    controller's mutator thread calls `mark_stale`/`invalidate` through the
    validator-set-change hook.
    """

    def __init__(self, metrics=None, mesh=None) -> None:
        from grandine_tpu.tpu.mesh import mesh_or_none

        self.metrics = metrics
        #: injected VerifyMesh (tpu/mesh.py): with a multi-device mesh the
        #: device arrays are row-sharded over it (`P("batch")` on axis 0),
        #: so the table's residency scales with the fleet — capacity is
        #: always a power of two ≥ MIN_CAPACITY, so any power-of-two mesh
        #: divides it evenly. None (or a 1-device mesh) keeps the plain
        #: single-chip placement byte-for-byte.
        self.mesh = mesh_or_none(mesh)
        self._lock = threading.RLock()
        #: host mirror: the exact compressed-bytes tuple the device arrays
        #: were built from (identity-compared against head-state columns)
        self._pubkeys: "Optional[tuple]" = None
        self._stale = False
        #: host raw-bytes rows ((capacity, 48) uint8, `_hcount` occupied)
        #: — the compressed wire encoding itself, kept so capacity growth
        #: re-uploads without re-decompressing (the device kernel redoes
        #: the sqrt, the host never does). Growth is geometric: at 2^20
        #: rows a per-append `np.concatenate` would copy the whole mirror
        #: per deposit batch; in-place writes make churn O(new) with
        #: O(log n) reallocations over the set's lifetime.
        self._hraw: "Optional[np.ndarray]" = None
        self._hcount = 0
        #: device arrays, (capacity, NLIMBS) int32 Montgomery limbs
        self._x = None
        self._y = None
        self.stats = {
            "hits": 0, "misses": 0, "appends": 0, "refreshes": 0,
            "uploaded_bytes": 0, "host_grows": 0,
        }

    # --------------------------------------------------------------- state

    @property
    def count(self) -> int:
        with self._lock:  # RLock: fine from already-locked callers
            return 0 if self._pubkeys is None else len(self._pubkeys)

    @property
    def capacity(self) -> int:
        with self._lock:
            return 0 if self._x is None else int(self._x.shape[0])

    def arrays(self):
        """(device_x, device_y, count) — rows past `count` are zero
        padding and must be masked by the caller's batch padding mask."""
        with self._lock:
            return self._x, self._y, self.count

    def public_keys(self, indices: "Sequence[int]"):
        """Decompressed PublicKeys for `indices` from the host mirror —
        the upload-path fallback for batches the indexed kernels cannot
        take (out-of-range index, committee wider than a bucket)."""
        with self._lock:
            pks = self._pubkeys or ()
        return keys.decompress_pubkeys(
            (pks[int(i)] for i in indices), trusted=True
        )

    # ------------------------------------------------------------- metrics

    def _event(self, event: str) -> None:
        if self.metrics is not None:
            self.metrics.pubkey_registry_events.labels(event).inc()

    def _sync_gauges(self) -> None:
        if self.metrics is None:
            return
        self.metrics.pubkey_registry_size.set(self.count)
        cap = self.capacity
        self.metrics.pubkey_registry_capacity.set(cap)
        host = 0 if self._hraw is None else int(self._hraw.nbytes)
        self.metrics.pubkey_registry_host_bytes.set(host)
        dev = cap * L.NLIMBS * 4 * 2
        self.metrics.pubkey_registry_device_bytes.set(dev)
        shards = 1 if self.mesh is None else max(1, self.mesh.device_count)
        self.metrics.pubkey_registry_shard_bytes.set(dev // shards)

    def _count_upload(self, nbytes: int) -> None:
        self.stats["uploaded_bytes"] += nbytes
        if self.metrics is not None:
            # labeled apart from the per-batch verify kernels: registry
            # uploads are amortized over the set's lifetime, not charged
            # to any batch (tools/check_no_per_batch_upload.py relies on
            # this separation)
            self.metrics.device_upload_bytes.labels("pubkey_registry").inc(
                nbytes
            )

    # ------------------------------------------------------------ lifecycle

    def mark_stale(self) -> None:
        """Demote the next ensure() from the identity fast path to the
        full prefix check (controller validator-set-change hook)."""
        with self._lock:
            self._stale = True

    def invalidate(self) -> None:
        """Drop device arrays and the host mirror; the next ensure() does
        a cold rebuild."""
        with self._lock:
            self._pubkeys = None
            self._hraw = None
            self._hcount = 0
            self._x = self._y = None
            self._stale = False
            self._event("invalidate")
            self._sync_gauges()

    # --------------------------------------------------------------- ensure

    def ensure(self, pubkeys: "Sequence[bytes]") -> bool:
        """Make the registry cover `pubkeys` (the head state's compressed
        pubkey tuple). Identity match → free hit; prefix growth → O(new)
        append; anything else → full refresh. Returns True when the
        device arrays are usable (always, barring an empty set)."""
        if not isinstance(pubkeys, tuple):
            pubkeys = tuple(bytes(b) for b in pubkeys)
        if len(pubkeys) == 0:
            return False
        with self._lock:
            old = self._pubkeys
            if old is pubkeys and not self._stale:
                self.stats["hits"] += 1
                self._event("hit")
                return True
            self.stats["misses"] += 1
            self._event("miss")
            if (
                old is not None
                and len(pubkeys) >= len(old)
                and pubkeys[: len(old)] == old
            ):
                if len(pubkeys) > len(old):
                    self._append(pubkeys, start=len(old))
                # equal prefix, equal length: same set under a new tuple
                # object (or a stale-flag re-check) — adopt the new tuple
                # so the next ensure() hits on identity
                self._pubkeys = pubkeys
            else:
                self._refresh(pubkeys)
            self._stale = False
            self._sync_gauges()
            return True

    # ------------------------------------------------------------ internals

    def _raw_rows(self, pubkey_bytes: "Sequence[bytes]") -> "np.ndarray":
        """Compressed bytes → (n, 48) uint8 raw rows for device-side
        decompression. Raises BlsError on what the WIRE alone can
        answer: wrong length, missing compressed flag, or the identity
        encoding (identity keys never enter the registry — the indexed
        kernels rely on it). Off-curve/non-canonical payloads pass
        through and are zeroed per-row by the device decompressor's
        validity mask (fail-closed; see module docstring)."""
        try:
            rows = C.compressed_rows(pubkey_bytes, 48)
        except ValueError as e:
            raise A.BlsError(str(e)) from None
        if rows.shape[0]:
            flags = rows[:, 0]
            if ((flags & C.COMPRESSED_FLAG) == 0).any():
                raise A.BlsError("uncompressed pubkey in registry input")
            if ((flags & C.INFINITY_FLAG) != 0).any():
                raise A.BlsError("identity pubkey can not enter the registry")
        return rows

    def _decompress_dev(self, raw: "np.ndarray"):
        """Upload (b, 48) uint8 raw rows and run the batched
        g1_decompress kernel: returns device ((b, NLIMBS) x, (b, NLIMBS)
        y) Montgomery rows. Rows the decompressor rejects (and zero
        padding rows) come back zeroed — never batch-fatal."""
        from grandine_tpu.tpu import bls as B

        x, y, _inf, _ok, _be, _bc, _bi = B.g1_decompress_rows(
            raw, self.metrics
        )
        return x, y

    def _host_reserve(self, rows: int) -> None:
        """Grow the host mirror to hold `rows`, geometrically — appends
        within capacity are pure in-place writes."""
        cur = 0 if self._hraw is None else int(self._hraw.shape[0])
        if rows <= cur:
            return
        cap = _next_pow2(rows)
        nraw = np.zeros((cap, 48), np.uint8)
        if self._hraw is not None and self._hcount:
            nraw[: self._hcount] = self._hraw[: self._hcount]
        self._hraw = nraw
        self.stats["host_grows"] += 1

    def _append(self, pubkeys: tuple, start: int) -> None:
        import jax

        raw = self._raw_rows(pubkeys[start:])
        end = len(pubkeys)
        n_new = end - start
        self._host_reserve(end)
        self._hraw[start:end] = raw
        self._hcount = end
        if end <= self.capacity:
            # in-place device scatter of O(new) rows: upload the RAW
            # 48-byte rows (bucketed so the decompress kernel's dispatch
            # shapes stay on the warm ladder) and decompress on device —
            # 48 B/row of traffic instead of 208 B/row of affine limbs
            b = _next_pow2(n_new)
            pad = np.zeros((b, 48), np.uint8)
            pad[:n_new] = raw
            dx, dy = self._decompress_dev(pad)
            self._x = self._x.at[start:end].set(dx[:n_new])
            self._y = self._y.at[start:end].set(dy[:n_new])
            if self.mesh is not None:
                # re-pin the row sharding: the eager scatter's output
                # layout is XLA's choice, and the shard-per-device
                # invariant is what the indexed kernels compile against
                sharding = self.mesh.batch_sharding()
                self._x = jax.device_put(self._x, sharding)
                self._y = jax.device_put(self._y, sharding)
            self._count_upload(int(pad.nbytes))
        else:
            self._upload_full(end)
        self._pubkeys = pubkeys
        self.stats["appends"] += 1
        self._event("append")

    def _refresh(self, pubkeys: tuple) -> None:
        raw = self._raw_rows(pubkeys)
        self._hraw = None
        self._hcount = 0
        self._host_reserve(len(pubkeys))
        self._hraw[: len(pubkeys)] = raw
        self._hcount = len(pubkeys)
        self._pubkeys = pubkeys
        self._upload_full(len(pubkeys))
        self.stats["refreshes"] += 1
        self._event("refresh")

    def _upload_full(self, count: int) -> None:
        """(Re)build the device arrays at power-of-two capacity from the
        host mirror: ONE raw-bytes upload + ONE batched decompress at
        capacity shape (the same bucket the gather kernels compile
        against, so warmup's capacity row covers it). Zero rows pad
        count..capacity — the decompressor zeroes them under an invalid
        mask, which is exactly the padding the gather kernels expect."""
        import jax

        cap = _next_pow2(count)
        if self.mesh is not None:
            # a power-of-two mesh must divide the power-of-two capacity;
            # MIN_CAPACITY floors the row count above any sane mesh width
            cap = max(cap, _next_pow2(self.mesh.device_count))
        praw = np.zeros((cap, 48), np.uint8)
        praw[:count] = self._hraw[:count]
        dx, dy = self._decompress_dev(praw)
        if self.mesh is not None:
            # row-sharded residency: the indexed kernels gather rows
            # on-device and XLA routes cross-shard lookups over the mesh
            sharding = self.mesh.batch_sharding()
            self._x = jax.device_put(dx, sharding)
            self._y = jax.device_put(dy, sharding)
        else:
            self._x = dx
            self._y = dy
        self._count_upload(int(praw.nbytes))


__all__ = ["DevicePubkeyRegistry", "MIN_CAPACITY", "MAINNET_CAPACITY"]
