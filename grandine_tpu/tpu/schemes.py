"""Scheme-generic kernel dispatch table: the verify plane's registry of
verification schemes.

A *scheme* is everything the scheduler needs to serve one signature /
proof system from a lane: field/curve parameters (documentation-grade —
the kernels own the arithmetic), a backend factory, the host scalar
twin (bisection leaf + degradation target), the device dispatch
function, the backend's ASYNC_SEAM members, the warmup kinds its
kernels pre-compile under, and the flight-record kernel label. BLS is
the first registered entry — `_dispatch_bls` below is the former
`VerifyScheduler._device_dispatch` body, moved verbatim so no kernel
name, verdict, or persistent-cache/shape-ledger behavior changed — and
a new curve is a table entry, not a fork of `tpu/bls.py`.

Lane → scheme binding lives in `LaneConfig.scheme`
(runtime/verify_scheduler.py); every scheduler seam that used to
hardcode BLS (`_backend_for`, `_device_dispatch`, the bisection leaf,
the host degradation pass, the flush kernel label, cross-lane merge
eligibility) resolves through `get(lane.scheme)` instead.

Import discipline: this module must import NO jax and NO kernel module
at top level — schemes register lazily so a `use_device=False`
scheduler (pure host path) never pays a kernel import. The lint rule
`scheme-dispatch` (tools/lint/rules/scheme_dispatch.py) enforces the
other direction: runtime/ code reaches kernel factories only through
this table.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Optional, Sequence

from grandine_tpu.consensus.verifier import SignatureInvalid
from grandine_tpu.crypto import bls as A


class SigningDescriptor:
    """Sign-side row of a scheme: how the signing plane batches,
    anchors, and release-gates signing for this scheme.

    The four callables mirror the verify side's backend/host-twin split:
    `batch_sign` is the device dispatch, `host_sign` the scalar anchor
    (degradation target — byte-identical by contract), `release_verify`
    the gate that batch-verifies every device-produced signature against
    the caller's public keys BEFORE release (a faulty device must never
    emit a bad signature), and `warm_kinds` the runtime/warmup.py rows
    that pre-compile the sign kernels."""

    __slots__ = ("batch_sign", "host_sign", "release_verify", "warm_kinds")

    def __init__(
        self,
        *,
        batch_sign: Callable,
        host_sign: Callable,
        release_verify: Callable,
        warm_kinds: "Sequence[str]" = (),
    ) -> None:
        #: batch_sign(backend, messages, secret_keys) → list[bytes]
        self.batch_sign = batch_sign
        #: host_sign(message, secret_key) → bytes (the scalar anchor)
        self.host_sign = host_sign
        #: release_verify(backend, messages, sig_bytes, public_keys)
        #: → bool: ALL device signatures verify against their keys
        self.release_verify = release_verify
        self.warm_kinds = tuple(warm_kinds)


class Scheme:
    """One registered verification scheme (see module docstring)."""

    __slots__ = (
        "name", "field_bits", "curve", "make_backend", "host_check",
        "device_dispatch", "async_seam", "warm_kinds", "kernel_label",
        "canary", "signing",
    )

    def __init__(
        self,
        name: str,
        *,
        field_bits: int,
        curve: str,
        make_backend: Callable,
        host_check: Callable,
        device_dispatch: Callable,
        async_seam: "Sequence[str]" = (),
        warm_kinds: "Sequence[str]" = (),
        kernel_label: "Optional[Callable]" = None,
        canary: bool = False,
        signing: "Optional[SigningDescriptor]" = None,
    ) -> None:
        self.name = name
        #: base-field modulus bit length (381 for BLS12-381, 255 for
        #: curve25519) — shape-contract documentation, not compute state
        self.field_bits = int(field_bits)
        self.curve = curve
        #: make_backend(metrics=, tracer=, lane=, mesh=) → backend
        self.make_backend = make_backend
        #: host_check(item) → bool: the scalar twin — bisection leaf and
        #: degradation target; must agree bit-for-bit with the device
        #: verdict on every input
        self.host_check = host_check
        #: device_dispatch(sched, lane, backend, items) → zero-arg
        #: settle callable, or None when no async device seam applies
        #: (the scheduler then degrades the batch to host_check)
        self.device_dispatch = device_dispatch
        #: backend method names the warmup/shape tooling treats as the
        #: async kernel seam (mirrors TpuBlsBackend.ASYNC_SEAM)
        self.async_seam = tuple(async_seam)
        #: runtime/warmup.py WARM_KINDS entries owned by this scheme
        self.warm_kinds = tuple(warm_kinds)
        #: kernel_label(backend) → flight-record kernel name
        self.kernel_label = (
            kernel_label if kernel_label is not None
            else (lambda backend: f"{name}_verify")
        )
        #: only the scheme whose backend answers breaker canary probes
        #: (BLS — the health supervisor's specimens are BLS triples)
        self.canary = bool(canary)
        #: sign-side descriptor (runtime/sign_plane.py), or None when
        #: the scheme has no device signing path (the plane refuses it)
        self.signing = signing


_REGISTRY: "dict[str, Scheme]" = {}
_LOCK = threading.Lock()


def register(scheme: Scheme) -> Scheme:
    """Register a scheme. Re-registering a name replaces the entry (the
    seam tests use this to shadow a scheme with an instrumented twin)."""
    with _LOCK:
        _REGISTRY[scheme.name] = scheme
    return scheme


def get(name: str) -> Scheme:
    with _LOCK:
        try:
            return _REGISTRY[name]
        except KeyError:
            raise KeyError(
                f"unknown verification scheme {name!r}; "
                f"registered: {sorted(_REGISTRY)}"
            ) from None


def names() -> "list[str]":
    with _LOCK:
        return sorted(_REGISTRY)


# --- BLS12-381 (the founding entry) ----------------------------------------


def _make_bls_backend(*, metrics=None, tracer=None, lane="attestation",
                      mesh=None):
    from grandine_tpu.tpu.bls import TpuBlsBackend

    return TpuBlsBackend(metrics=metrics, tracer=tracer, lane=lane,
                         mesh=mesh)


def _host_check_bls(item) -> bool:
    # resolved through the scheduler module AT CALL TIME so tests that
    # monkeypatch verify_scheduler.host_check_item keep reaching every
    # leaf (bisection, degradation, localization) exactly as before
    from grandine_tpu.runtime import verify_scheduler as _vs

    return _vs.host_check_item(item)


def _bls_kernel_label(backend) -> str:
    return (
        "fast_aggregate_fused"
        if getattr(backend, "fuse_subgroup", False)
        else "fast_aggregate"
    )


def _device_decompress_enabled() -> bool:
    """GRANDINE_TPU_DEVICE_DECOMPRESS gates the compressed-ingest path
    (default ON). Read at dispatch time so an operator can flip a live
    process back to the host-decompress anchor without a restart."""
    return os.environ.get(
        "GRANDINE_TPU_DEVICE_DECOMPRESS", "1"
    ).lower() not in ("0", "false", "no")


def _dispatch_bls(sched, lane, backend, items):
    """Route one coalesced BLS batch to the device. Default: the
    compressed-ingest path — signatures stay raw 96-byte wire encodings
    all the way into the verify kernel, where decompression, the fused
    ψ-ladder subgroup check, and the pairing run as ONE device pass
    (no per-item host Fq2.sqrt — the `host_prep op=g2_decompress` stage
    that made BENCH_r05 prep-bound disappears). The host-decompress twin
    below is retained verbatim as the anchor and degradation target:
    GRANDINE_TPU_DEVICE_DECOMPRESS=0 or a backend without the compressed
    seam falls back to it."""
    if _device_decompress_enabled():
        settle = _dispatch_bls_compressed(sched, lane, backend, items)
        if settle is not None:
            return settle
    return _dispatch_bls_host_decompress(sched, lane, backend, items)


def _dispatch_bls_compressed(sched, lane, backend, items):
    """Compressed-ingest dispatch: forward raw signature bytes to the
    backend's *_compressed_async seam. Host-side wire screening is
    limited to what bytes alone answer with the same verdict as the host
    twin: a wrong-length blob or an infinity-flagged signature fails the
    batch (the twin's BlsError / is_infinity() gates). Non-canonical,
    off-curve, and non-residue payloads are rejected PER ROW by the
    device decompressor's validity masks and fail the batch for the
    bisection to isolate — never batch-fatally on the host. Returns None
    when the backend lacks the compressed seam (host-decompress twin
    takes over)."""
    if backend is None or not (
        hasattr(backend, "fast_aggregate_verify_batch_compressed_async")
        and hasattr(
            backend, "fast_aggregate_verify_batch_indexed_compressed_async"
        )
    ):
        return None
    with sched._stage(lane, "host_prep", op="sig_bytes", items=len(items)):
        sig_bytes = [bytes(it.signature) for it in items]
        if any(len(sb) != 96 for sb in sig_bytes):
            return lambda: False  # twin: BlsError on bad length
        if any(sb[0] & 0x40 for sb in sig_bytes):
            # infinity flag: the twin rejects an infinity signature
            # (canonical payload) or raises BlsError (junk payload) —
            # both verdicts are False
            return lambda: False
    registry = sched._sync_registry(lane, items)
    indexed, keyed = [], []
    for i, it in enumerate(items):
        if registry is not None and it.member_indices is not None:
            indexed.append(i)
        else:
            keyed.append(i)
    try:
        with sched._stage(lane, "host_prep", op="resolve_keys"):
            keyed_keys = [items[i].resolve_keys() for i in keyed]
    except SignatureInvalid:
        return lambda: False
    if sched.metrics is not None:
        sched.metrics.device_batch_sigs.inc(len(items))
    settles = []
    if indexed:
        settles.append(
            backend.fast_aggregate_verify_batch_indexed_compressed_async(
                [items[i].message for i in indexed],
                [sig_bytes[i] for i in indexed],
                [list(items[i].member_indices) for i in indexed],
                registry,
            )
        )
    if keyed:
        settles.append(backend.fast_aggregate_verify_batch_compressed_async(
            [items[i].message for i in keyed],
            [sig_bytes[i] for i in keyed],
            keyed_keys,
        ))

    def settle() -> bool:
        return all(bool(s()) for s in settles)

    return settle


def _dispatch_bls_host_decompress(sched, lane, backend, items):
    """Host prep + async device dispatch of one coalesced BLS batch;
    returns a zero-arg settle callable (the batch verdict) or None when
    no async device seam is available. Mirrors the attestation pipeline:
    decompress signatures WITHOUT the per-item host subgroup scalar-mul,
    stack the device ψ-ladder subgroup check and the verify kernel(s),
    read back nothing yet. (Moved verbatim from
    VerifyScheduler._device_dispatch — the scheduler now routes here
    through the scheme table. Retained as the compressed-ingest path's
    anchor and degradation target.)"""
    if backend is None or not (
        hasattr(backend, "fast_aggregate_verify_batch_async")
        and hasattr(backend, "g2_subgroup_check_batch_async")
    ):
        return None
    try:
        with sched._stage(lane, "host_prep", op="g2_decompress",
                          items=len(items)):
            points = [
                A.g2_from_bytes(it.signature, subgroup_check=False)
                for it in items
            ]
    except A.BlsError:
        return lambda: False
    if any(p.is_infinity() for p in points):
        return lambda: False
    registry = sched._sync_registry(lane, items)
    indexed, keyed = [], []
    for i, it in enumerate(items):
        if registry is not None and it.member_indices is not None:
            indexed.append(i)
        else:
            keyed.append(i)
    try:
        with sched._stage(lane, "host_prep", op="resolve_keys"):
            keyed_keys = [items[i].resolve_keys() for i in keyed]
    except SignatureInvalid:
        # a keyless/malformed item: fail the batch, bisection isolates
        return lambda: False
    # fused backends fold the ψ-ladder membership check into the
    # verify kernel (one dispatch per batch); two-pass backends stack
    # the subgroup ladder ahead of the verify dispatch
    fused = getattr(backend, "fuse_subgroup", False)
    sub_settle = (
        None if fused else backend.g2_subgroup_check_batch_async(points)
    )
    sigs = [A.Signature(p) for p in points]
    if sched.metrics is not None:
        sched.metrics.device_batch_sigs.inc(len(sigs))
    settles = []
    if indexed:
        settles.append(backend.fast_aggregate_verify_batch_indexed_async(
            [items[i].message for i in indexed],
            [sigs[i] for i in indexed],
            [list(items[i].member_indices) for i in indexed],
            registry,
        ))
    if keyed:
        settles.append(backend.fast_aggregate_verify_batch_async(
            [items[i].message for i in keyed],
            [sigs[i] for i in keyed],
            keyed_keys,
        ))

    def settle() -> bool:
        if sub_settle is not None and not bool(sub_settle().all()):
            return False
        return all(bool(s()) for s in settles)

    return settle


def _bls_batch_sign(backend, messages, secret_keys):
    """Device batch signing: N G2 GLV dual-ladders in one dispatch
    (tpu/bls.py batch_sign_kernel). Returns wire-encoded signatures in
    request order — byte-identical to the host anchor by contract."""
    return [
        s.to_bytes()
        for s in backend.batch_sign(list(messages), list(secret_keys))
    ]


def _bls_host_sign(message, secret_key) -> bytes:
    """The scalar anchor: `sk.sign` (crypto/bls.py). Degradation target
    for breaker-open and release-gate-failed batches."""
    return secret_key.sign(message).to_bytes()


def _bls_release_verify(backend, messages, sig_bytes, public_keys) -> bool:
    """Release gate: batch-verify the device-produced signatures against
    the registered public keys in one RLC multi_verify pass BEFORE any
    caller sees them. Undecodable bytes (a device fault corrupted the
    point) fail the gate outright — the plane then re-signs the batch on
    the host anchor and files a verdict fault with the breaker."""
    try:
        sigs = [
            A.Signature(A.g2_from_bytes(sb, subgroup_check=False))
            for sb in sig_bytes
        ]
    except A.BlsError:
        return False
    return bool(
        backend.multi_verify(list(messages), sigs, list(public_keys))
    )


register(Scheme(
    "bls",
    field_bits=381,
    curve="BLS12-381",
    make_backend=_make_bls_backend,
    host_check=_host_check_bls,
    device_dispatch=_dispatch_bls,
    async_seam=(
        "fast_aggregate_verify_batch_async",
        "g2_subgroup_check_batch_async",
        "fast_aggregate_verify_batch_indexed_async",
        "multi_verify_async",
        "rlc_partition_verify_async",
        "multi_verify_compressed_async",
        "fast_aggregate_verify_batch_compressed_async",
        "fast_aggregate_verify_batch_indexed_compressed_async",
    ),
    warm_kinds=("aggregate", "aggregate_idx", "subgroup", "multi_verify",
                "rlc_partition", "aggregate_comp", "aggregate_idx_comp",
                "multi_verify_comp", "g1_decompress"),
    kernel_label=_bls_kernel_label,
    canary=True,
    signing=SigningDescriptor(
        batch_sign=_bls_batch_sign,
        host_sign=_bls_host_sign,
        release_verify=_bls_release_verify,
        warm_kinds=("sign", "g2_aggregate", "g1_aggregate"),
    ),
))


# --- Ed25519 (RFC 8032, cofactored batch) ----------------------------------


def _make_ed25519_backend(*, metrics=None, tracer=None, lane="ed25519",
                          mesh=None):
    from grandine_tpu.tpu.ed25519 import Ed25519Backend

    return Ed25519Backend(metrics=metrics, tracer=tracer, lane=lane)


def _host_check_ed25519(item) -> bool:
    from grandine_tpu.crypto import ed25519 as _he

    return _he.check_item(item)


def _dispatch_ed25519(sched, lane, backend, items):
    """Host prep (point decode, malleability bound, RLC scalars) + one
    async batched-verify dispatch. Malformed encodings fail the batch
    (bisection isolates against the host twin); an over-bucket batch
    returns None so the scheduler degrades it to the host path."""
    if backend is None or not hasattr(backend, "verify_batch_async"):
        return None
    with sched._stage(lane, "host_prep", op="ed25519_decode",
                      items=len(items)):
        status, prep = backend.prepare(items)
    if status == "invalid":
        return lambda: False
    if status != "ok":
        return None
    if sched.metrics is not None:
        sched.metrics.device_batch_sigs.inc(len(items))
    return backend.verify_batch_async(prep)


register(Scheme(
    "ed25519",
    field_bits=255,
    curve="curve25519",
    make_backend=_make_ed25519_backend,
    host_check=_host_check_ed25519,
    device_dispatch=_dispatch_ed25519,
    async_seam=("verify_batch_async",),
    warm_kinds=("ed25519_verify",),
))


# --- KZG blob proofs (EIP-4844, deneb) -------------------------------------


def _make_blob_kzg_backend(*, metrics=None, tracer=None, lane="blob_kzg",
                           mesh=None):
    from grandine_tpu.kzg.eip4844 import KzgDeviceBackend

    return KzgDeviceBackend(metrics=metrics, tracer=tracer, lane=lane)


def _host_check_blob_kzg(item) -> bool:
    from grandine_tpu.kzg import eip4844 as _kz

    return _kz.host_check_item(item)


def _dispatch_blob_kzg(sched, lane, backend, items):
    """Host prep (commitment/proof decode, Fiat–Shamir challenges,
    barycentric evaluations, batch-RLC scalars) + ONE device pass: two
    shape-contracted MSMs and a width-2 pairing check. Mixed blob widths
    or an over-bucket batch return None (host degradation — per-item
    verdicts stay correct); undecodable bytes fail the batch for the
    bisection to isolate."""
    if backend is None or not hasattr(backend, "verify_blobs_async"):
        return None
    with sched._stage(lane, "host_prep", op="kzg_prep", items=len(items)):
        status, prep = backend.prepare(items)
    if status == "invalid":
        return lambda: False
    if status != "ok":
        return None
    if sched.metrics is not None:
        sched.metrics.device_batch_sigs.inc(len(items))
    return backend.verify_blobs_async(prep)


register(Scheme(
    "blob_kzg",
    field_bits=381,
    curve="BLS12-381",
    make_backend=_make_blob_kzg_backend,
    host_check=_host_check_blob_kzg,
    device_dispatch=_dispatch_blob_kzg,
    async_seam=("verify_blobs_async",),
    warm_kinds=("kzg_blob",),
))


__all__ = ["Scheme", "SigningDescriptor", "register", "get", "names"]
