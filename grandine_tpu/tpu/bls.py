"""Batched BLS signature-plane kernels on device + the host-facing backend.

This is the TPU equivalent of the reference's `bls` crate hot surface
(bls/src/signature.rs:96-129 `multi_verify`, :78-93 `fast_aggregate_verify`,
bls/src/secret_key.rs:82-86 `sign`) re-designed for the accelerator:

  - `multi_verify_kernel` — random-linear-combination batch verification:
    N (message, signature, pubkey) triples are checked with batched Miller
    loops, a log-depth Fp12 product tree, and ONE shared final
    exponentiation:  e(g1, Σ rᵢ·sigᵢ) == ∏ e(rᵢ·pkᵢ, H(mᵢ)).
  - `grouped_multi_verify_kernel` — triples grouped by message, so Miller
    loops collapse from N to the number of distinct messages.
  - `aggregate_fast_verify_kernel` — the gossip-attestation firehose shape:
    M attestations × K committee members; pubkey aggregation is a log-depth
    complete-addition tree over the k-major flat batch, then the RLC check.
  - `batch_sign_kernel` / `batch_pubkey_kernel` — G2/G1 fixed-base scalar
    multiplications for multi-validator signing (signer/src/signer.rs:173-229).

Kernel boundary: hosts speak the REST FORMAT — numpy arrays with a trailing
limb axis (pk (N, 26), G2 coords (N, 2, 26), bool masks (N,), scalar bit
arrays (N, nbits)) — which is layout-agnostic and cheap to assemble. The
first traced ops of every kernel split rest-format arrays into the limb-list
form the device plane computes in (see limbs.py for why), and outputs are
merged back; XLA fuses both boundaries into the adjacent compute.

All kernels are shape-static (host pads to power-of-two buckets), branchless,
and batched over the trailing axis of every limb array. Padding slots are
all-infinity triples, which are algebraically neutral in every reduction.
Host-side policy checks (identity pubkey rejection, empty batches, subgroup
checks on decompression) happen in `TpuBlsBackend` before data reaches the
device, mirroring where the reference enforces them.

Multi-chip: the batch axis shards over a `jax.sharding.Mesh`; each chip
reduces its local Fp12 product and the cross-chip product is a single
all-gather of one Fp12 element per chip (see __graft_entry__.py).
"""

from __future__ import annotations

import json
import os
import secrets
import sys
import threading
import time
from collections import OrderedDict
from contextlib import contextmanager
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from grandine_tpu.crypto import constants
from grandine_tpu.crypto import bls as A
from grandine_tpu.crypto.curves import G1, LAMBDA, decompose_glv, endo_constants
from grandine_tpu.crypto.hash_to_curve import hash_to_g2
from grandine_tpu.tpu import curve as C
from grandine_tpu.tpu import field as F
from grandine_tpu.tpu import limbs as L
from grandine_tpu.tpu import msm as M
from grandine_tpu.tpu import pairing as TP

try:  # jax >= 0.6 exports shard_map at top level (kwarg: check_vma)
    shard_map = jax.shard_map
except AttributeError:  # 0.4.x: experimental module, kwarg named check_rep
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_impl(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )


# --- module constants (host, Montgomery limb form) -------------------------

_NEG_G1_DEV = C.g1_point_to_dev(-G1)  # (x, y, inf=False)

# verified ψ coordinate-scaling constants (crypto.curves derivation) for
# the device subgroup-check kernel
from grandine_tpu.crypto.curves import psi_constants_ints

_PSI_HOST = psi_constants_ints()
_ABS_X = -constants.X  # the (negative) BLS parameter, as |x|

# GLV/ψ² endomorphism constants (derived + asserted in crypto/curves.py):
# (cx·x, cy·y) = [LAMBDA]·(x, y) on the respective curve.
_ENDO_HOST = endo_constants()


def _g1_endo(n: int):
    bx, by = _ENDO_HOST["g1"]
    return (
        L.const_fp([int(d) for d in L.to_mont(bx)], (n,)),
        L.const_fp([int(d) for d in L.to_mont(by)], (n,)),
    )


def _g2_endo(n: int):
    wx, wy = _ENDO_HOST["g2"]
    z = L.zeros_fp((n,))
    return (
        (L.const_fp([int(d) for d in L.to_mont(wx)], (n,)), z),
        (L.const_fp([int(d) for d in L.to_mont(wy)], (n,)), z),
    )


def rlc_bits_host(pairs, pad_to: int) -> np.ndarray:
    """[(r0, r1), …] 32-bit RLC pairs → (pad_to, 64) rest-format bit array
    ([r0 MSB-first 32 | r1 MSB-first 32]); padding rows are (1, 0).

    The RLC scalar of a row is r0 + r1·LAMBDA (mod r) — a set of 2⁶⁴
    distinct values (r0 + r1·λ < 2¹⁶⁰ < r, so the map is injective), so the
    forgery bound of the random-linear-combination check is the same 2⁻⁶⁴
    as uniform 64-bit scalars, while both scalar ladders run at half
    length (curve.scalar_mul_glv)."""
    n = len(pairs)
    r0 = [p[0] for p in pairs] + [1] * (pad_to - n)
    r1 = [p[1] for p in pairs] + [0] * (pad_to - n)
    lo = C.scalars_to_bits_msb(r0, 32)
    hi = C.scalars_to_bits_msb(r1, 32)
    return np.concatenate([lo, hi], axis=1)


def sign_bits_host(scalars, pad_to: int):
    """Secret scalars → GLV-decomposed ((pad_to, 256) bits, (pad_to, 2) neg
    masks) for batch_sign_kernel / batch_pubkey_kernel."""
    decs = [decompose_glv(int(k)) for k in scalars]
    decs += [(1, 1, 0, 1)] * (pad_to - len(decs))
    lo = C.scalars_to_bits_msb([d[0] for d in decs], 128)
    hi = C.scalars_to_bits_msb([d[2] for d in decs], 128)
    neg = np.array([[d[1] < 0, d[3] < 0] for d in decs], dtype=bool)
    return np.concatenate([lo, hi], axis=1), neg


def _rlc_ladders(bits64):
    """(N, 64) packed RLC bit rows → ((32, N) lo, (32, N) hi) scan arrays."""
    b = jnp.asarray(bits64)
    return jnp.transpose(b[:, :32]), jnp.transpose(b[:, 32:])


# --- rest-format ↔ limb-list adapters (first/last traced ops of kernels) ---


def _g1_in(x, y):
    """(N, 26) coord arrays → affine G1 limb-list pair."""
    return L.split(jnp.asarray(x)), L.split(jnp.asarray(y))


def _g2_in(x, y):
    return F.fp2_split(jnp.asarray(x)), F.fp2_split(jnp.asarray(y))


def _flat_km(arr, m: int, k: int):
    """(M, K, …) rest array → k-major flat (K·M, …) — the order
    sum_points_grouped reduces over."""
    a = jnp.asarray(arr)
    return jnp.swapaxes(a, 0, 1).reshape((k * m,) + a.shape[2:])


def _rlc_finish(f, sig_acc_jac):
    """Multiply the accumulated Fp12 product by the single e(−g1, Σ rᵢ·sigᵢ)
    factor and run the shared final exponentiation. The one place (single-
    and multi-chip) that evaluates the RLC product equation."""
    sig_inf = F.fp2_is_zero(sig_acc_jac[2])
    sig_h = TP.jacobian_to_homogeneous(sig_acc_jac)
    neg_x = L.const_fp([int(d) for d in _NEG_G1_DEV[0]], (1,))
    neg_y = L.const_fp([int(d) for d in _NEG_G1_DEV[1]], (1,))
    neg_z = L.const_fp(L.ONE_MONT_DIGITS, (1,))
    sig_h1 = tuple(F.lead2(c) for c in sig_h)
    f_sig = TP.miller_loop((neg_x, neg_y, neg_z), sig_h1, sig_inf[None])
    f_total = F.fp12_mul(f, tuple(F.take6(c, 0) for c in f_sig))
    return TP.final_exp_is_one(f_total)


def _rlc_finish_grouped(f_groups, sig_acc_jac, g: int):
    """Width-g generalization of _rlc_finish: f_groups is a (g,)-batched
    Fp12 (per-group Miller products), sig_acc_jac a (g,)-batched Jacobian
    G2 (per-group Σ rᵢ·sigᵢ). Each group gets its own e(−g1, ·) factor and
    the shared final exponentiation runs ONCE at width g — the per-group
    verdicts cost one device pass, not g."""
    sig_inf = F.fp2_is_zero(sig_acc_jac[2])
    sig_h = TP.jacobian_to_homogeneous(sig_acc_jac)
    neg_x = L.const_fp([int(d) for d in _NEG_G1_DEV[0]], (g,))
    neg_y = L.const_fp([int(d) for d in _NEG_G1_DEV[1]], (g,))
    neg_z = L.const_fp(L.ONE_MONT_DIGITS, (g,))
    f_sig = TP.miller_loop((neg_x, neg_y, neg_z), sig_h, sig_inf)
    f_total = F.fp12_mul(f_groups, f_sig)
    return TP.final_exp_is_one(f_total)


def _rlc_pairing_check(rpk_jac, pair_inf, msg_x, msg_y, sig_acc_jac):
    """Shared tail of the verify kernels: given rᵢ·pkᵢ (Jacobian G1), the
    per-pair infinity mask, affine message points H(mᵢ) on the twist, and
    Σ rᵢ·sigᵢ (Jacobian G2), evaluate

        ∏ e(rᵢ·pkᵢ, H(mᵢ)) · e(−g1, Σ rᵢ·sigᵢ) == 1

    with one shared final exponentiation."""
    n = msg_x[0].shape[1]
    # message points: affine → homogeneous projective on the twist
    msg_q = (msg_x, msg_y, F.fp2_one((n,)))
    f_msgs = TP.miller_loop(rpk_jac, msg_q, pair_inf)
    return _rlc_finish(TP.fp12_product_tree(f_msgs), sig_acc_jac)


def _psi_ladder_check(P, inf, x_bits):
    """Traced core of the ψ-criterion subgroup check (Bowe, the check
    blst ships): P ∈ G2 ⇔ ψ(P) == [x]P ⇔ ψ(P) + [|x|]P == ∞ (the BLS
    parameter x is negative). `P` is an already-split affine G2 limb-list
    pair, `inf` the (N,) mask, `x_bits` the (64, N) MSB-first |x| ladder.
    Returns (N,) bool; infinity rows pass (padding slots are neutral —
    callers reject real infinity signatures by policy)."""
    xp = C.scalar_mul(P[0], P[1], inf, x_bits, C.FP2_OPS)
    n = inf.shape[0]
    (cx0, cx1), (cy0, cy1) = _PSI_HOST
    cx = (
        L.const_fp([int(d) for d in L.to_mont(cx0)], (n,)),
        L.const_fp([int(d) for d in L.to_mont(cx1)], (n,)),
    )
    cy = (
        L.const_fp([int(d) for d in L.to_mont(cy0)], (n,)),
        L.const_fp([int(d) for d in L.to_mont(cy1)], (n,)),
    )

    def conj(a):
        return (a[0], L.neg_mod(a[1]))

    psi_x = F.fp2_mul(cx, conj(P[0]))
    psi_y = F.fp2_mul(cy, conj(P[1]))
    one = C.FP2_OPS.one_like(psi_x)
    total = C.point_add_complete(xp, (psi_x, psi_y, one), C.FP2_OPS)
    return jnp.logical_or(inf, F.fp2_is_zero(total[2]))


def _fused_subgroup_mask(sig, sig_inf):
    """ψ-membership of the signature plane INSIDE a verify kernel body:
    the |x| bit ladder is a trace-time constant (the batch width is
    static under jit), so the fused check adds NO kernel operands — the
    64-step batched ladder simply joins the traced graph ahead of the
    pairing, eliminating the separate g2_subgroup_check dispatch (and
    its HBM round-trip) per batch."""
    n = sig_inf.shape[0]
    x_bits = jnp.asarray(np.ascontiguousarray(
        C.scalars_to_bits_msb([_ABS_X] * n, 64).T
    ))
    return _psi_ladder_check(sig, sig_inf, x_bits)


def multi_verify_kernel(
    pk_x, pk_y, pk_inf, sig_x, sig_y, sig_inf, msg_x, msg_y, msg_inf, r_bits
):
    """RLC batch verify of N (msg, sig, pk) triples. Rest-format shapes:
    pk_x/pk_y (N, L); sig/msg coords (N, 2, L); inf masks (N,) bool;
    r_bits (N, 64) packed RLC rows (rlc_bits_host — the scalar is
    r0 + r1·LAMBDA, run as a half-length dual ladder). N must be a power of
    two; padding slots are all-infinity (neutral). Returns a scalar bool.

    Algebraic twin of Signature::multi_verify (bls/src/signature.rs:96-129).
    """
    pk = _g1_in(pk_x, pk_y)
    sig = _g2_in(sig_x, sig_y)
    msg = _g2_in(msg_x, msg_y)
    pk_inf = jnp.asarray(pk_inf)
    sig_inf = jnp.asarray(sig_inf)
    msg_inf = jnp.asarray(msg_inf)
    n = pk_inf.shape[0]
    lo, hi = _rlc_ladders(r_bits)
    rpk = C.scalar_mul_glv(pk[0], pk[1], pk_inf, lo, hi, _g1_endo(n), C.FP_OPS)
    rsig = C.scalar_mul_glv(
        sig[0], sig[1], sig_inf, lo, hi, _g2_endo(n), C.FP2_OPS
    )
    sig_acc = C.sum_points(rsig, C.FP2_OPS)
    pair_inf = pk_inf | msg_inf
    return _rlc_pairing_check(rpk, pair_inf, msg[0], msg[1], sig_acc)


def rlc_partition_verify_kernel(
    pk_x, pk_y, pk_inf, sig_x, sig_y, sig_inf, msg_x, msg_y, msg_inf,
    r_bits, group_tag, check_subgroup: int = 0
):
    """Fault-localization variant of multi_verify_kernel: same RLC math,
    but instead of one whole-batch verdict it returns PER-SUB-BATCH
    verdicts — the batch's N slots split into G = group_tag.shape[0]
    contiguous groups of N/G, each group evaluating its own

        ∏ᵢ∈g e(rᵢ·pkᵢ, H(mᵢ)) · e(−g1, Σᵢ∈g rᵢ·sigᵢ) == 1

    in ONE device pass (the ladders and Miller loops run once at full
    width; only the product tree stops at group boundaries and the final
    exponentiation runs at width G). Returns a (G,) bool array. group_tag
    is a (G,)-shaped carrier whose only job is making G part of the jit
    shape signature (and the dispatch shape ledger). All-padding groups
    (all-infinity slots) report True — neutral, like padding in the
    whole-batch kernel. N and G must be powers of two with G | N."""
    pk = _g1_in(pk_x, pk_y)
    sig = _g2_in(sig_x, sig_y)
    msg = _g2_in(msg_x, msg_y)
    pk_inf = jnp.asarray(pk_inf)
    sig_inf = jnp.asarray(sig_inf)
    msg_inf = jnp.asarray(msg_inf)
    n = pk_inf.shape[0]
    g = group_tag.shape[0]
    lo, hi = _rlc_ladders(r_bits)
    rpk = C.scalar_mul_glv(pk[0], pk[1], pk_inf, lo, hi, _g1_endo(n), C.FP_OPS)
    rsig = C.scalar_mul_glv(
        sig[0], sig[1], sig_inf, lo, hi, _g2_endo(n), C.FP2_OPS
    )
    sig_acc = C.sum_points_contiguous(rsig, n // g, C.FP2_OPS)
    pair_inf = pk_inf | msg_inf
    msg_q = (msg[0], msg[1], F.fp2_one((n,)))
    f_items = TP.miller_loop(rpk, msg_q, pair_inf)
    f_groups = TP.fp12_product_tree_grouped(f_items, n // g)
    ok = _rlc_finish_grouped(f_groups, sig_acc, g)
    if check_subgroup:
        member = _fused_subgroup_mask(sig, sig_inf)
        ok = jnp.logical_and(ok, member.reshape(g, n // g).all(axis=1))
    return ok


def grouped_multi_verify_kernel(
    pk_x, pk_y, pk_inf, sig_x, sig_y, sig_inf, msg_x, msg_y, msg_inf, r_bits
):
    """RLC batch verify with triples GROUPED BY MESSAGE: pk/sig/r have
    rest-format shape (M, K, …) — M distinct messages × up to K triples each
    (padding slots all-infinity) — msg has shape (M, …).

    Algebraic identity:  ∏ᵢ e(rᵢ·pkᵢ, H(mᵢ)) = ∏ⱼ e(Σᵢ∈ⱼ rᵢ·pkᵢ, H(mⱼ)),
    so only M (+1) Miller loops run instead of N (+1) while every triple
    keeps its own 64-bit randomizer (soundness unchanged — cancellation
    inside a group needs a collision against rᵢ). This is the shape of the
    real workloads: gossip batches and block replays carry few distinct
    AttestationData values per many signatures (BASELINE configs 2–4).
    """
    m, k = pk_inf.shape
    pk = _g1_in(_flat_km(pk_x, m, k), _flat_km(pk_y, m, k))
    sig = _g2_in(_flat_km(sig_x, m, k), _flat_km(sig_y, m, k))
    msg = _g2_in(msg_x, msg_y)
    pk_inf_f = _flat_km(pk_inf, m, k)
    sig_inf_f = _flat_km(sig_inf, m, k)
    msg_inf = jnp.asarray(msg_inf)
    lo, hi = _rlc_ladders(_flat_km(r_bits, m, k))
    rpk = C.scalar_mul_glv(
        pk[0], pk[1], pk_inf_f, lo, hi, _g1_endo(m * k), C.FP_OPS
    )
    rsig = C.scalar_mul_glv(
        sig[0], sig[1], sig_inf_f, lo, hi, _g2_endo(m * k), C.FP2_OPS
    )
    sig_acc = C.sum_points(rsig, C.FP2_OPS)
    gpk = C.sum_points_grouped(rpk, k, C.FP_OPS)  # (M,) Jacobian, m-order
    pair_inf = L.is_zero_val(gpk[2]) | msg_inf
    return _rlc_pairing_check(gpk, pair_inf, msg[0], msg[1], sig_acc)


# --- MSM window autotune table ----------------------------------------------
#
# A measured calibration sweep (tools.shapes --autotune → tpu/autotune.py)
# persists its winning window widths next to the shape manifest as
# tools/shapes/msm_tune.json: {"windows": {"<n_points>:<n_groups>": w}}.
# pick_msm_window consults the table first (keys quantized up to the same
# pow-2 buckets the dispatch plane uses) and falls back to the analytic op
# model for unmeasured shapes, so a node with no table behaves exactly as
# before.

_MSM_TUNE: "Optional[dict]" = None
_MSM_TUNE_LOCK = threading.Lock()


def msm_tune_path() -> str:
    """Path of the persisted MSM autotune table (GRANDINE_TPU_MSM_TUNE
    overrides; default lives next to tools/shapes/manifest.txt)."""
    env = os.environ.get("GRANDINE_TPU_MSM_TUNE")
    if env:
        return env
    root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    return os.path.join(root, "tools", "shapes", "msm_tune.json")


def load_msm_tuning(path: "Optional[str]" = None) -> "Optional[dict]":
    """Load (and cache) the measured window table. Returns the
    {"<n>:<g>": w} mapping, or None when the file is absent/unreadable —
    the analytic model then stands alone. Thread-safe; first caller pays
    the read."""
    global _MSM_TUNE
    with _MSM_TUNE_LOCK:
        if _MSM_TUNE is not None and path is None:
            return _MSM_TUNE or None
        try:
            with open(path or msm_tune_path(), encoding="utf-8") as fh:
                raw = json.load(fh)
            table = {}
            # per-entry validation: one corrupt row must not discard the
            # rest of the measured table
            for k, v in dict(raw.get("windows", {})).items():
                try:
                    w = int(v)
                except (ValueError, TypeError):
                    continue
                if 4 <= w <= 8:
                    table[str(k)] = w
        except (OSError, ValueError, TypeError, AttributeError):
            table = {}
        if path is None:
            _MSM_TUNE = table
        return table or None


def set_msm_tuning(table: "Optional[dict]") -> None:
    """Test/CLI seam: install a window table directly ({"<n>:<g>": w}),
    or None to drop the cache so the next lookup re-reads the file."""
    global _MSM_TUNE
    with _MSM_TUNE_LOCK:
        _MSM_TUNE = None if table is None else {
            str(k): int(v) for k, v in table.items()
        }


def pick_msm_window(n_points: int, n_groups: int = 1) -> int:
    """Window width minimizing the modeled MSM op count: scan work
    windows·2N plus suffix/reduce work 2w·(groups·windows·2^w).

    A sequential-call-count "latency" model was tried (round 5) and
    measured WORSE end-to-end: it pushes w up, and wide bucket planes
    (n_groups·W·2^w lanes) spill the montmul carry out of VMEM — the op
    count model's preference for narrow windows under many groups is
    also, in practice, the VMEM-resident choice.

    A measured entry in the autotune table (load_msm_tuning) wins over
    the model; lookup keys quantize to the dispatch plane's pow-2
    buckets so a table built from the calibration sweep covers every
    shape the warmed kernels can see."""
    table = load_msm_tuning()
    if table:
        key = "%d:%d" % (_bucket(n_points), _bucket(max(1, n_groups), lo=1))
        w = table.get(key)
        if w is not None:
            return w
    best, best_cost = 4, None
    for w in range(4, 9):
        W = (32 + w - 1) // w
        cost = W * 2 * n_points + 2 * w * n_groups * W * (1 << w)
        if best_cost is None or cost < best_cost:
            best, best_cost = w, cost
    return best


def _grouped_msm_verify_tail(
    pk, sig, msg, pk_inf_f, sig_inf_f, msg_inf, m, k,
    g1_pidx, g1_valid, g1_flush, g1_gidx, g1_gvalid,
    g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
    g1_windows: int, g1_wbits: int, g2_windows: int, g2_wbits: int,
    check_subgroup: int = 0,
):
    """Shared tail of the grouped MSM verify kernels: per-group pubkey MSM,
    global signature MSM, then the RLC pairing check over M messages."""
    epx, epy, eplive = M.expand_glv_points(
        pk[0], pk[1], pk_inf_f, _g1_endo(m * k), C.FP_OPS
    )
    gpk = M.msm_bucket_scan(
        epx, epy, eplive,
        g1_pidx, g1_valid, g1_flush, g1_gidx, g1_gvalid,
        windows=g1_windows, window_bits=g1_wbits, n_groups=m, ops=C.FP_OPS,
    )
    esx, esy, eslive = M.expand_glv_points(
        sig[0], sig[1], sig_inf_f, _g2_endo(m * k), C.FP2_OPS
    )
    sig_acc_g = M.msm_bucket_scan(
        esx, esy, eslive,
        g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
        windows=g2_windows, window_bits=g2_wbits, n_groups=1, ops=C.FP2_OPS,
    )
    sig_acc = tuple(C.FP2_OPS.index(e, 0) for e in sig_acc_g)
    pair_inf = L.is_zero_val(gpk[2]) | msg_inf
    ok = _rlc_pairing_check(gpk, pair_inf, msg[0], msg[1], sig_acc)
    if check_subgroup:
        ok = jnp.logical_and(ok, _fused_subgroup_mask(sig, sig_inf_f).all())
    return ok


def grouped_multi_verify_msm_kernel(
    pk_x, pk_y, pk_inf, sig_x, sig_y, sig_inf, msg_x, msg_y, msg_inf,
    g1_pidx, g1_valid, g1_flush, g1_gidx, g1_gvalid,
    g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
    g1_windows: int, g1_wbits: int, g2_windows: int, g2_wbits: int,
    check_subgroup: int = 0,
):
    """Message-grouped RLC batch verify with BOTH scalar planes as device
    Pippenger MSMs (msm.py) instead of per-signature ladders: per-group
    Σᵢ∈ⱼ rᵢ·pkᵢ (M-group MSM) and the global Σᵢ rᵢ·sigᵢ (1-group MSM).
    Point layouts as grouped_multi_verify_kernel; the RLC scalars travel as
    MsmPlan index arrays (flat k-major point order, group of point f =
    f mod M) built by the host, which draws the randomizers.

    Replaces the ladder plane per VERDICT r3 #1; matches blst's
    Pippenger-backed multi_verify (bls/src/signature.rs:96-129)."""
    m, k = pk_inf.shape
    return _grouped_msm_verify_tail(
        _g1_in(_flat_km(pk_x, m, k), _flat_km(pk_y, m, k)),
        _g2_in(_flat_km(sig_x, m, k), _flat_km(sig_y, m, k)),
        _g2_in(msg_x, msg_y),
        jnp.asarray(_flat_km(pk_inf, m, k)),
        jnp.asarray(_flat_km(sig_inf, m, k)),
        jnp.asarray(msg_inf), m, k,
        g1_pidx, g1_valid, g1_flush, g1_gidx, g1_gvalid,
        g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
        g1_windows=g1_windows, g1_wbits=g1_wbits,
        g2_windows=g2_windows, g2_wbits=g2_wbits,
        check_subgroup=check_subgroup,
    )


def _g2_packed_in(sig_words, m: int, k: int):
    """(M, K, 4, 13) uint32 packed canonical coords → k-major flat Fp2
    (x, y) limb-list pairs in Montgomery form (limbs.py packed transfer
    format; ONE fused montmul lifts all four coordinates)."""
    w = _flat_km(sig_words, m, k)  # (KM, 4, 13)
    canon = L.unpack_words(w)  # (26, KM, 4)
    mont = L.to_mont_dev(canon)
    x = (mont[:, :, 0], mont[:, :, 1])
    y = (mont[:, :, 2], mont[:, :, 3])
    return x, y


def grouped_multi_verify_msm_packed_kernel(
    pk_x, pk_y, pk_inf, sig_words, sig_inf, msg_x, msg_y, msg_inf,
    g1_pidx, g1_valid, g1_flush, g1_gidx, g1_gvalid,
    g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
    g1_windows: int, g1_wbits: int, g2_windows: int, g2_wbits: int,
    check_subgroup: int = 0,
):
    """grouped_multi_verify_msm_kernel with the SIGNATURE plane arriving
    as packed canonical words ((M, K, 4, 13) uint32 — 52 B/coord instead
    of 104 B): signatures are the one per-batch upload a production
    verifier cannot avoid, and host→device transfer serializes with
    execution on the per-batch clock, so halving sig bytes cuts batch
    latency directly (bench.py pipeline notes)."""
    m, k = pk_inf.shape
    return _grouped_msm_verify_tail(
        _g1_in(_flat_km(pk_x, m, k), _flat_km(pk_y, m, k)),
        _g2_packed_in(sig_words, m, k),
        _g2_in(msg_x, msg_y),
        jnp.asarray(_flat_km(pk_inf, m, k)),
        jnp.asarray(_flat_km(sig_inf, m, k)),
        jnp.asarray(msg_inf), m, k,
        g1_pidx, g1_valid, g1_flush, g1_gidx, g1_gvalid,
        g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
        g1_windows=g1_windows, g1_wbits=g1_wbits,
        g2_windows=g2_windows, g2_wbits=g2_wbits,
        check_subgroup=check_subgroup,
    )


def _flat_msm_verify_tail(
    pk, pk_inf, sig, sig_inf, msg_x, msg_y, msg_inf, r_bits,
    g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
    g2_windows: int, g2_wbits: int, check_subgroup: int = 0,
):
    """Shared tail of the flat MSM verify kernels: per-signature G1 GLV
    ladders (each rᵢ·pkᵢ feeds its own Miller loop), Σ rᵢ·sigᵢ as one
    Pippenger sum, then the RLC pairing check. `pk` arrives as a limb-list
    pair — built either from uploaded coords or a registry gather; `sig`
    arrives as a split Fp2 (x, y) pair — built from uploaded coords or the
    on-device decompressor. With `check_subgroup` the ψ-ladder membership
    of the signature plane runs fused in the same pass and ANDs into the
    verdict."""
    msg = _g2_in(msg_x, msg_y)
    pk_inf = jnp.asarray(pk_inf)
    sig_inf = jnp.asarray(sig_inf)
    msg_inf = jnp.asarray(msg_inf)
    n = pk_inf.shape[0]
    lo, hi = _rlc_ladders(r_bits)
    rpk = C.scalar_mul_glv(pk[0], pk[1], pk_inf, lo, hi, _g1_endo(n), C.FP_OPS)
    esx, esy, eslive = M.expand_glv_points(
        sig[0], sig[1], sig_inf, _g2_endo(n), C.FP2_OPS
    )
    sig_acc_g = M.msm_bucket_scan(
        esx, esy, eslive,
        g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
        windows=g2_windows, window_bits=g2_wbits, n_groups=1, ops=C.FP2_OPS,
    )
    sig_acc = tuple(C.FP2_OPS.index(e, 0) for e in sig_acc_g)
    pair_inf = pk_inf | msg_inf
    ok = _rlc_pairing_check(rpk, pair_inf, msg[0], msg[1], sig_acc)
    if check_subgroup:
        ok = jnp.logical_and(ok, _fused_subgroup_mask(sig, sig_inf).all())
    return ok


def multi_verify_msm_kernel(
    pk_x, pk_y, pk_inf, sig_x, sig_y, sig_inf, msg_x, msg_y, msg_inf, r_bits,
    g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
    g2_windows: int, g2_wbits: int, check_subgroup: int = 0,
):
    """Flat RLC batch verify (one Miller loop per signature) with the G2
    scalar plane as a device MSM. The G1 side keeps per-signature GLV
    ladders — each rᵢ·pkᵢ is needed individually for its Miller loop —
    while Σ rᵢ·sigᵢ is a single Pippenger sum."""
    return _flat_msm_verify_tail(
        _g1_in(pk_x, pk_y), pk_inf,
        _g2_in(sig_x, sig_y), sig_inf, msg_x, msg_y, msg_inf, r_bits,
        g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
        g2_windows=g2_windows, g2_wbits=g2_wbits,
        check_subgroup=check_subgroup,
    )


def multi_verify_msm_idx_kernel(
    reg_x, reg_y, pk_idx, pk_inf,
    sig_x, sig_y, sig_inf, msg_x, msg_y, msg_inf, r_bits,
    g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
    g2_windows: int, g2_wbits: int, check_subgroup: int = 0,
):
    """multi_verify_msm_kernel with the PUBKEY plane gathered on-device
    from the resident registry (tpu/registry.py): reg_x/reg_y are the
    (capacity, L) registry arrays (already device-resident — NOT part of
    the per-batch upload), pk_idx (N,) int32 selects each signer's row.
    Padding slots carry pk_idx 0 under pk_inf True (registry rows are
    never the identity, so only the batch mask matters)."""
    idx = jnp.asarray(pk_idx)
    pk = _g1_in(
        jnp.take(jnp.asarray(reg_x), idx, axis=0),
        jnp.take(jnp.asarray(reg_y), idx, axis=0),
    )
    return _flat_msm_verify_tail(
        pk, pk_inf,
        _g2_in(sig_x, sig_y), sig_inf, msg_x, msg_y, msg_inf, r_bits,
        g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
        g2_windows=g2_windows, g2_wbits=g2_wbits,
        check_subgroup=check_subgroup,
    )


def aggregate_fast_verify_kernel(
    mem_x, mem_y, mem_inf, slot_pad,
    sig_x, sig_y, sig_inf, msg_x, msg_y, msg_inf, r_bits,
):
    """Firehose kernel: M aggregates (gossip attestations), each signed by up
    to K committee members over one message. Rest-format shapes: mem_x/mem_y
    (M, K, L) affine member pubkeys with mem_inf (M, K) padding mask;
    slot_pad (M,) marks batch-padding slots; sig/msg per aggregate as in
    multi_verify_kernel; r_bits (M, 64).

    Computes pkᵢ = Σₖ memᵢₖ (complete-add tree over the k-major flat batch),
    then the RLC check. A REAL slot whose members sum to the identity is
    rejected (matching the anchor's fast_aggregate_verify: an adversary
    could pair a [P, −P] committee with an infinity signature to fake
    participation); padding slots stay algebraically neutral.
    Reference shape: attestation_batch_triples + MultiVerifier::finish
    (p2p/src/attestation_verifier.rs:431-457, helper_functions verifier.rs:302).
    """
    m, k = mem_inf.shape
    mem = _g1_in(_flat_km(mem_x, m, k), _flat_km(mem_y, m, k))
    mem_inf_f = _flat_km(mem_inf, m, k)
    one = C.FP_OPS.one_like(mem[0])
    zero = C.FP_OPS.zeros_like(mem[0])
    mem_jac = (
        C.FP_OPS.select(mem_inf_f, one, mem[0]),
        C.FP_OPS.select(mem_inf_f, one, mem[1]),
        C.FP_OPS.select(mem_inf_f, zero, one),
    )
    agg_pk = C.sum_points_grouped(mem_jac, k, C.FP_OPS)  # (M,) Jacobian G1
    agg_inf = L.is_zero_val(agg_pk[2])
    slot_pad = jnp.asarray(slot_pad)
    forged = jnp.any(jnp.logical_and(jnp.logical_not(slot_pad), agg_inf))
    sig = _g2_in(sig_x, sig_y)
    msg = _g2_in(msg_x, msg_y)
    sig_inf = jnp.asarray(sig_inf)
    msg_inf = jnp.asarray(msg_inf)
    lo, hi = _rlc_ladders(r_bits)
    rpk = C.scalar_mul_jac_glv(agg_pk, agg_inf, lo, hi, _g1_endo(m), C.FP_OPS)
    rsig = C.scalar_mul_glv(
        sig[0], sig[1], sig_inf, lo, hi, _g2_endo(m), C.FP2_OPS
    )
    sig_acc = C.sum_points(rsig, C.FP2_OPS)
    pair_inf = agg_inf | msg_inf
    ok = _rlc_pairing_check(rpk, pair_inf, msg[0], msg[1], sig_acc)
    return jnp.logical_and(ok, jnp.logical_not(forged))


def _aggregate_msm_verify_tail(
    mem, mem_inf_f, m, k, slot_pad,
    sig, sig_inf, msg_x, msg_y, msg_inf, r_bits,
    g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
    g2_windows: int, g2_wbits: int, check_subgroup: int = 0,
):
    """Shared tail of the firehose MSM kernels: member aggregation tree,
    identity-forgery rejection, per-aggregate G1 ladder, Σ rᵢ·sigᵢ as one
    MSM, then the RLC pairing check. `mem` arrives as a k-major flat
    limb-list pair — built either from uploaded coords or a registry
    gather; `sig` as a split Fp2 (x, y) pair — from uploaded coords or
    the on-device decompressor."""
    one = C.FP_OPS.one_like(mem[0])
    zero = C.FP_OPS.zeros_like(mem[0])
    mem_jac = (
        C.FP_OPS.select(mem_inf_f, one, mem[0]),
        C.FP_OPS.select(mem_inf_f, one, mem[1]),
        C.FP_OPS.select(mem_inf_f, zero, one),
    )
    agg_pk = C.sum_points_grouped(mem_jac, k, C.FP_OPS)  # (M,) Jacobian G1
    agg_inf = L.is_zero_val(agg_pk[2])
    slot_pad = jnp.asarray(slot_pad)
    forged = jnp.any(jnp.logical_and(jnp.logical_not(slot_pad), agg_inf))
    msg = _g2_in(msg_x, msg_y)
    sig_inf = jnp.asarray(sig_inf)
    msg_inf = jnp.asarray(msg_inf)
    lo, hi = _rlc_ladders(r_bits)
    rpk = C.scalar_mul_jac_glv(agg_pk, agg_inf, lo, hi, _g1_endo(m), C.FP_OPS)
    esx, esy, eslive = M.expand_glv_points(
        sig[0], sig[1], sig_inf, _g2_endo(m), C.FP2_OPS
    )
    sig_acc_g = M.msm_bucket_scan(
        esx, esy, eslive,
        g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
        windows=g2_windows, window_bits=g2_wbits, n_groups=1, ops=C.FP2_OPS,
    )
    sig_acc = tuple(C.FP2_OPS.index(e, 0) for e in sig_acc_g)
    pair_inf = agg_inf | msg_inf
    ok = _rlc_pairing_check(rpk, pair_inf, msg[0], msg[1], sig_acc)
    if check_subgroup:
        ok = jnp.logical_and(ok, _fused_subgroup_mask(sig, sig_inf).all())
    return jnp.logical_and(ok, jnp.logical_not(forged))


def aggregate_fast_verify_msm_kernel(
    mem_x, mem_y, mem_inf, slot_pad,
    sig_x, sig_y, sig_inf, msg_x, msg_y, msg_inf, r_bits,
    g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
    g2_windows: int, g2_wbits: int, check_subgroup: int = 0,
):
    """Firehose kernel with the Σ rᵢ·sigᵢ side as a device MSM. The G1 side
    keeps the per-aggregate Jacobian GLV ladder — each rᵢ·(Σ memᵢₖ) is
    needed individually for its Miller loop. Layouts and rejection
    semantics identical to aggregate_fast_verify_kernel."""
    m, k = mem_inf.shape
    mem = _g1_in(_flat_km(mem_x, m, k), _flat_km(mem_y, m, k))
    return _aggregate_msm_verify_tail(
        mem, _flat_km(mem_inf, m, k), m, k, slot_pad,
        _g2_in(sig_x, sig_y), sig_inf, msg_x, msg_y, msg_inf, r_bits,
        g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
        g2_windows=g2_windows, g2_wbits=g2_wbits,
        check_subgroup=check_subgroup,
    )


def aggregate_fast_verify_msm_idx_kernel(
    reg_x, reg_y, mem_idx, mem_inf, slot_pad,
    sig_x, sig_y, sig_inf, msg_x, msg_y, msg_inf, r_bits,
    g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
    g2_windows: int, g2_wbits: int, check_subgroup: int = 0,
):
    """Firehose kernel with MEMBER PUBKEYS gathered on-device from the
    resident registry: reg_x/reg_y are the (capacity, L) registry arrays
    (device-resident, not uploaded per batch); mem_idx (M, K) int32 selects
    each committee member's registry row, with mem_inf (M, K) masking the
    padding slots (which carry index 0 — registry rows are never the
    identity, so the mask alone is authoritative). The per-batch upload
    shrinks to signatures + messages + the index plane: 4 B/member instead
    of 208 B/member of affine G1 coordinates."""
    m, k = mem_inf.shape
    idx_f = _flat_km(mem_idx, m, k)  # k-major flat, like the coord layout
    mem = _g1_in(
        jnp.take(jnp.asarray(reg_x), idx_f, axis=0),
        jnp.take(jnp.asarray(reg_y), idx_f, axis=0),
    )
    return _aggregate_msm_verify_tail(
        mem, _flat_km(mem_inf, m, k), m, k, slot_pad,
        _g2_in(sig_x, sig_y), sig_inf, msg_x, msg_y, msg_inf, r_bits,
        g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
        g2_windows=g2_windows, g2_wbits=g2_wbits,
        check_subgroup=check_subgroup,
    )


def _g2_compressed_in(sig_rows):
    """(B, 96) uint8 compressed signature rows → on-device decompression
    (tpu/curve.py): split Fp2 (x, y) in Montgomery form, the decoded
    infinity mask, and a per-row validity mask covering all three failure
    classes (non-canonical encoding, non-residue/off-curve x,
    infinity-with-payload). Invalid rows come back zeroed under ok=False —
    the caller masks them out of the group law and ANDs `ok.all()` into
    the verdict so a malformed item fails its batch without ever being
    batch-fatal on the host."""
    x, y, inf, ok, _be, _bc, _bi = C.g2_decompress_dev(sig_rows)
    return (x, y), inf, ok


def multi_verify_msm_comp_kernel(
    pk_x, pk_y, pk_inf, sig_rows, sig_inf, msg_x, msg_y, msg_inf, r_bits,
    g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
    g2_windows: int, g2_wbits: int, check_subgroup: int = 0,
):
    """multi_verify_msm_kernel with the SIGNATURE plane arriving as raw
    compressed wire bytes ((B, 96) uint8 — the gossip format itself):
    decompression runs as part of the same device pass, replacing the
    per-item pure-Python Fq2.sqrt host stage that made BENCH_r05
    prep-bound (47.6s host vs 12.54s device). `sig_inf` is the host's
    padding ∪ infinity-flag mask (padding rows carry the canonical
    infinity encoding, so they decompress valid); a row the decompressor
    rejects is masked out of the MSM and fails the batch via ok.all()."""
    sig, dec_inf, dec_ok = _g2_compressed_in(sig_rows)
    sig_inf = jnp.asarray(sig_inf) | dec_inf | jnp.logical_not(dec_ok)
    ok = _flat_msm_verify_tail(
        _g1_in(pk_x, pk_y), pk_inf,
        sig, sig_inf, msg_x, msg_y, msg_inf, r_bits,
        g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
        g2_windows=g2_windows, g2_wbits=g2_wbits,
        check_subgroup=check_subgroup,
    )
    return jnp.logical_and(ok, dec_ok.all())


def aggregate_fast_verify_msm_comp_kernel(
    mem_x, mem_y, mem_inf, slot_pad,
    sig_rows, sig_inf, msg_x, msg_y, msg_inf, r_bits,
    g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
    g2_windows: int, g2_wbits: int, check_subgroup: int = 0,
):
    """aggregate_fast_verify_msm_kernel with compressed-bytes signature
    ingest ((M, 96) uint8). Same rejection semantics as the uncompressed
    twin plus the decompressor's per-row validity classes ANDed into the
    verdict."""
    m, k = mem_inf.shape
    mem = _g1_in(_flat_km(mem_x, m, k), _flat_km(mem_y, m, k))
    sig, dec_inf, dec_ok = _g2_compressed_in(sig_rows)
    sig_inf = jnp.asarray(sig_inf) | dec_inf | jnp.logical_not(dec_ok)
    ok = _aggregate_msm_verify_tail(
        mem, _flat_km(mem_inf, m, k), m, k, slot_pad,
        sig, sig_inf, msg_x, msg_y, msg_inf, r_bits,
        g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
        g2_windows=g2_windows, g2_wbits=g2_wbits,
        check_subgroup=check_subgroup,
    )
    return jnp.logical_and(ok, dec_ok.all())


def aggregate_fast_verify_msm_idx_comp_kernel(
    reg_x, reg_y, mem_idx, mem_inf, slot_pad,
    sig_rows, sig_inf, msg_x, msg_y, msg_inf, r_bits,
    g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
    g2_windows: int, g2_wbits: int, check_subgroup: int = 0,
):
    """aggregate_fast_verify_msm_idx_kernel with compressed-bytes
    signature ingest: member pubkeys gathered on-device from the resident
    registry AND signatures decompressed on-device. The per-batch upload
    collapses to 96 B/aggregate of wire bytes + 4 B/member of indices —
    nothing in the hot path is host-converted any more."""
    m, k = mem_inf.shape
    idx_f = _flat_km(mem_idx, m, k)
    mem = _g1_in(
        jnp.take(jnp.asarray(reg_x), idx_f, axis=0),
        jnp.take(jnp.asarray(reg_y), idx_f, axis=0),
    )
    sig, dec_inf, dec_ok = _g2_compressed_in(sig_rows)
    sig_inf = jnp.asarray(sig_inf) | dec_inf | jnp.logical_not(dec_ok)
    ok = _aggregate_msm_verify_tail(
        mem, _flat_km(mem_inf, m, k), m, k, slot_pad,
        sig, sig_inf, msg_x, msg_y, msg_inf, r_bits,
        g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
        g2_windows=g2_windows, g2_wbits=g2_wbits,
        check_subgroup=check_subgroup,
    )
    return jnp.logical_and(ok, dec_ok.all())


def g1_decompress_kernel(rows):
    """Batched on-device G1 decompression for the pubkey registry's
    deposit-churn path: (B, 48) uint8 compressed rows → rest-format
    (B, 26) Montgomery affine coords plus infinity/validity masks and the
    three per-row failure classes. Invalid rows come back zeroed (NOT
    batch-fatal); the registry scatter keeps them as zero rows and the
    host mirror (which validated the same bytes) is authoritative for
    naming the bad deposit."""
    x, y, inf, ok, bad_enc, bad_curve, bad_inf = C.g1_decompress_dev(rows)
    return (
        L.merge(x), L.merge(y), inf, ok,
        bad_enc, bad_curve, bad_inf,
    )


def g1_decompress_rows(rows, metrics=None):
    """Dispatch g1_decompress_kernel on pre-padded (B, 48) uint8 rows.

    The one sanctioned dispatch seam for the kernel: the registry's
    churn path and warmup both come through here so the jit cache sees a
    single registration site (and scheme-owned code keeps the factory
    call out of runtime/)."""
    fn = _jitted_global("g1_decompress", g1_decompress_kernel)
    args = (jnp.asarray(rows),)
    note_dispatch_shapes("g1_decompress", args, metrics)
    return fn(*args)


def batch_sign_kernel(msg_x, msg_y, msg_inf, sk_bits, sk_neg):
    """N signatures: [skᵢ]·H(mᵢ) on the twist. sk_bits (N, 256) packed GLV
    halves with sk_neg (N, 2) sign masks (sign_bits_host): the 255-bit
    ladder becomes a 128-step dual ladder. Returns a Jacobian G2 batch in
    rest format (N, 2, 26) per coord.

    NOTE: secret scalars live on the accelerator; the kernel is branchless
    (fixed trip count, select-based) but NOT hardened against physical side
    channels — acceptable for benching, keep hot production signing host-side
    (SURVEY.md §7 risks)."""
    msg = _g2_in(msg_x, msg_y)
    n = jnp.asarray(msg_inf).shape[0]
    b = jnp.asarray(sk_bits)
    neg = jnp.asarray(sk_neg)
    X, Y, Z = C.scalar_mul_glv(
        msg[0], msg[1], jnp.asarray(msg_inf),
        jnp.transpose(b[:, :128]), jnp.transpose(b[:, 128:]),
        _g2_endo(n), C.FP2_OPS,
        neg_lo=neg[:, 0], neg_hi=neg[:, 1],
    )
    return F.fp2_merge(X), F.fp2_merge(Y), F.fp2_merge(Z)


def g2_subgroup_check_kernel(sx, sy, s_inf, x_bits):
    """Batched ψ-criterion subgroup check (Bowe, the check blst ships):
    P ∈ G2  ⇔  ψ(P) == [x]P  ⇔  ψ(P) + [|x|]P == ∞ (the BLS parameter x
    is negative). Inputs are AFFINE on-curve G2 points in rest format
    ((N, 2, 26) coords, (N,) inf mask); x_bits is the shared |x| ladder
    ((64, N) MSB-first). Returns (N,) bool; infinity rows pass (the
    caller rejects infinity signatures by policy, as the anchor does).

    This moves the per-signature host subgroup scalar-mul (~9 ms each,
    THE firehose batch bottleneck) onto the device as one 64-step
    batched ladder. The same traced math also runs fused INSIDE the
    verify kernels (`_fused_subgroup_mask`); this standalone entry stays
    for the fault localizer's per-item attribution pass and the health
    seam."""
    return _psi_ladder_check(
        _g2_in(sx, sy), jnp.asarray(s_inf), jnp.asarray(x_bits)
    )


def g1_normalize_kernel(X, Y, Z):
    """Batched Jacobian → affine on device (one Fermat inversion scan for
    the whole batch): (x, y, inf) in rest format. Infinity rows return
    garbage coords under a True mask."""
    Xl, Yl, Zl = L.split(jnp.asarray(X)), L.split(jnp.asarray(Y)), L.split(jnp.asarray(Z))
    zinv = L.inv_mod(Zl)
    zinv2 = L.montmul(zinv, zinv)
    zinv3 = L.montmul(zinv2, zinv)
    x = L.montmul(Xl, zinv2)
    y = L.montmul(Yl, zinv3)
    return L.merge(x), L.merge(y), L.is_zero_val(Zl)


def g2_normalize_kernel(X, Y, Z):
    Xl, Yl, Zl = (F.fp2_split(jnp.asarray(c)) for c in (X, Y, Z))
    zinv = F.fp2_inv(Zl)
    zinv2 = F.fp2_sq(zinv)
    zinv3 = F.fp2_mul(zinv2, zinv)
    x = F.fp2_mul(Xl, zinv2)
    y = F.fp2_mul(Yl, zinv3)
    return F.fp2_merge(x), F.fp2_merge(y), F.fp2_is_zero(Zl)


def batch_pubkey_kernel(sk_bits, sk_neg):
    """N public keys: [skᵢ]·g1. sk_bits (N, 256) packed GLV halves with
    sk_neg (N, 2) sign masks (sign_bits_host); rest-format out."""
    gx, gy, _ = C.g1_point_to_dev(G1)
    n = sk_bits.shape[0]
    qx = L.const_fp([int(d) for d in gx], (n,))
    qy = L.const_fp([int(d) for d in gy], (n,))
    q_inf = jnp.zeros((n,), bool)
    b = jnp.asarray(sk_bits)
    neg = jnp.asarray(sk_neg)
    X, Y, Z = C.scalar_mul_glv(
        qx, qy, q_inf,
        jnp.transpose(b[:, :128]), jnp.transpose(b[:, 128:]),
        _g1_endo(n), C.FP_OPS,
        neg_lo=neg[:, 0], neg_hi=neg[:, 1],
    )
    return L.merge(X), L.merge(Y), L.merge(Z)


def g2_aggregate_kernel(sig_x, sig_y, sig_inf, group_tag):
    """Contiguous-group G2 sums for aggregate CONSTRUCTION: the batch's
    N affine signature points split into G = group_tag.shape[0]
    contiguous groups of N/G, each reduced to one Jacobian aggregate in
    a single masked-roll tree pass (curve.sum_points_contiguous). This
    is the sign-side twin of the verify plane's partition reducer: one
    device dispatch builds every attestation / sync-contribution
    aggregate of a slot instead of a host G2 point loop per committee.

    Padding slots are infinity (the identity is neutral in complete
    addition); an all-padding group returns infinity, matching the host
    anchor `Signature.aggregate([])`. group_tag is a (G,)-shaped carrier
    whose only job is making G part of the jit shape signature (and the
    dispatch shape ledger). N and G must be powers of two with G | N.
    Returns Jacobian (G, 2, L) coords in rest format."""
    sig = _g2_in(sig_x, sig_y)
    inf = jnp.asarray(sig_inf)
    n = inf.shape[0]
    g = group_tag.shape[0]
    one = C.FP2_OPS.one_like(sig[0])
    zero = C.FP2_OPS.zeros_like(sig[0])
    p = (
        C.FP2_OPS.select(inf, one, sig[0]),
        C.FP2_OPS.select(inf, one, sig[1]),
        C.FP2_OPS.select(inf, zero, one),
    )
    X, Y, Z = C.sum_points_contiguous(p, n // g, C.FP2_OPS)
    return F.fp2_merge(X), F.fp2_merge(Y), F.fp2_merge(Z)


def g1_aggregate_kernel(pk_x, pk_y, pk_inf, group_tag):
    """G1 twin of g2_aggregate_kernel: contiguous-group sums of affine
    public-key points → per-group Jacobian aggregate keys (the
    fast-aggregate-verify prep and proposer-boost style key aggregation
    run as one pass next to the registry). Same padding and group_tag
    conventions; returns Jacobian (G, L) coords in rest format."""
    pk = _g1_in(pk_x, pk_y)
    inf = jnp.asarray(pk_inf)
    n = inf.shape[0]
    g = group_tag.shape[0]
    one = C.FP_OPS.one_like(pk[0])
    zero = C.FP_OPS.zeros_like(pk[0])
    p = (
        C.FP_OPS.select(inf, one, pk[0]),
        C.FP_OPS.select(inf, one, pk[1]),
        C.FP_OPS.select(inf, zero, one),
    )
    X, Y, Z = C.sum_points_contiguous(p, n // g, C.FP_OPS)
    return L.merge(X), L.merge(Y), L.merge(Z)


def g2_aggregate_groups(groups, metrics=None):
    """Batched aggregate construction: a list of signature groups → one
    aggregate `A.Signature` per group, reduced on device in ONE
    contiguous-group sum pass (g2_aggregate_kernel).

    The one sanctioned dispatch seam for the kernel: duty aggregation
    (validator/duties.py), the signing plane, and warmup all come
    through here so the jit cache sees a single registration site. The
    group width pads to its pow-2 bucket with infinity slots (neutral)
    and the group count pads to its own pow-2 bucket with all-padding
    groups, so the (batch, groups) jit universe stays enumerable. Host
    `Signature.aggregate` is the differential twin (byte-identical
    aggregates, asserted in tests/test_sign_plane.py)."""
    if not groups:
        return []
    m = len(groups)
    s = _bucket(max(max((len(grp) for grp in groups), default=1), 1))
    per_chunk = max(1, MAX_BUCKET // s)
    if m > per_chunk:
        out: list = []
        for i in range(0, m, per_chunk):
            out.extend(g2_aggregate_groups(groups[i : i + per_chunk],
                                           metrics))
        return out
    gb = _bucket(m)
    n = gb * s
    x, y, inf = C.g2_points_to_dev(
        [sig.point for grp in groups for sig in grp]
    )
    sx = np.zeros((n, 2, L.NLIMBS), np.int32)
    sy = np.zeros((n, 2, L.NLIMBS), np.int32)
    sinf = np.ones((n,), bool)
    pos = 0
    for gi, grp in enumerate(groups):
        k = len(grp)
        base = gi * s
        sx[base : base + k] = x[pos : pos + k]
        sy[base : base + k] = y[pos : pos + k]
        sinf[base : base + k] = inf[pos : pos + k]
        pos += k
    fn = _jitted_global("g2_aggregate", g2_aggregate_kernel)
    args = (
        jnp.asarray(sx), jnp.asarray(sy), jnp.asarray(sinf),
        jnp.zeros((gb,), jnp.int32),
    )
    note_dispatch_shapes("g2_aggregate", args, metrics)
    X, Y, Z = fn(*args)
    X, Y, Z = np.asarray(X), np.asarray(Y), np.asarray(Z)
    return [
        A.Signature(C.dev_to_g2_point(X[i], Y[i], Z[i])) for i in range(m)
    ]


def g1_aggregate_groups(groups, metrics=None):
    """G1 twin seam: a list of public-key groups → one aggregate
    `A.PublicKey` per group via g1_aggregate_kernel. Host
    `PublicKey.aggregate` is the differential twin. Same bucketing and
    chunking conventions as g2_aggregate_groups."""
    if not groups:
        return []
    m = len(groups)
    s = _bucket(max(max((len(grp) for grp in groups), default=1), 1))
    per_chunk = max(1, MAX_BUCKET // s)
    if m > per_chunk:
        out: list = []
        for i in range(0, m, per_chunk):
            out.extend(g1_aggregate_groups(groups[i : i + per_chunk],
                                           metrics))
        return out
    gb = _bucket(m)
    n = gb * s
    x, y, inf = C.g1_points_to_dev(
        [pk.point for grp in groups for pk in grp]
    )
    px = np.zeros((n, L.NLIMBS), np.int32)
    py = np.zeros((n, L.NLIMBS), np.int32)
    pinf = np.ones((n,), bool)
    pos = 0
    for gi, grp in enumerate(groups):
        k = len(grp)
        base = gi * s
        px[base : base + k] = x[pos : pos + k]
        py[base : base + k] = y[pos : pos + k]
        pinf[base : base + k] = inf[pos : pos + k]
        pos += k
    fn = _jitted_global("g1_aggregate", g1_aggregate_kernel)
    args = (
        jnp.asarray(px), jnp.asarray(py), jnp.asarray(pinf),
        jnp.zeros((gb,), jnp.int32),
    )
    note_dispatch_shapes("g1_aggregate", args, metrics)
    X, Y, Z = fn(*args)
    X, Y, Z = np.asarray(X), np.asarray(Y), np.asarray(Z)
    return [
        A.PublicKey(C.dev_to_g1_point(X[i], Y[i], Z[i])) for i in range(m)
    ]


# --- multi-chip (SPMD over a device mesh) -----------------------------------


def make_sharded_multi_verify(mesh, axis: str = "batch",
                              check_subgroup: int = 0):
    """Build the multi-chip RLC batch verify: the batch axis is sharded over
    `mesh`'s `axis`; each chip runs its local Miller loops, scalar muls, and
    local Fp12 product / G2 partial sum; the only collectives are two
    all-gathers of ONE Fp12 element and ONE Jacobian G2 point per chip (a few
    KB over ICI). The final exponentiation runs replicated (it is per-batch,
    not per-signature). Returns a jitted fn with the same signature as
    `multi_verify_kernel`; per-chip batch must be a power of two.

    This is the framework's scale-out plane (SURVEY.md §2.4): the pairing
    product is the one cross-chip reduction the workload needs.
    """
    from jax import lax
    from jax.sharding import PartitionSpec as P

    n_dev = mesh.shape[axis]
    assert n_dev & (n_dev - 1) == 0, (
        "make_sharded_multi_verify requires a power-of-two device count"
    )

    def gather_tree(t):
        # gather batchless (26,) limb-major leaves into (26, n_dev): the
        # device axis becomes the batch axis (position 1)
        return jax.tree.map(lambda x: lax.all_gather(x, axis, axis=1), t)

    def local_step(
        pk_x, pk_y, pk_inf, sig_x, sig_y, sig_inf, msg_x, msg_y, msg_inf, r_bits
    ):
        pk = _g1_in(pk_x, pk_y)
        sig = _g2_in(sig_x, sig_y)
        msg = _g2_in(msg_x, msg_y)
        n_local = pk_inf.shape[0]
        lo, hi = _rlc_ladders(r_bits)
        rpk = C.scalar_mul_glv(
            pk[0], pk[1], pk_inf, lo, hi, _g1_endo(n_local), C.FP_OPS
        )
        rsig = C.scalar_mul_glv(
            sig[0], sig[1], sig_inf, lo, hi, _g2_endo(n_local), C.FP2_OPS
        )
        sX, sY, sZ = C.sum_points(rsig, C.FP2_OPS)  # local G2 partial sum
        n = msg_x.shape[0]
        msg_q = (msg[0], msg[1], F.fp2_one((n,)))
        f_local = TP.fp12_product_tree(
            TP.miller_loop(rpk, msg_q, pk_inf | msg_inf)
        )
        # cross-chip: gather the per-chip partials (tiny), finish replicated.
        # Each limb array is a scalar per chip → all_gather yields (n_dev,).
        f_all = gather_tree(f_local)
        sig_all = gather_tree((sX, sY, sZ))
        sig_acc = C.sum_points(sig_all, C.FP2_OPS)
        ok = _rlc_finish(TP.fp12_product_tree(f_all), sig_acc)
        if check_subgroup:
            # fused ψ membership: each chip checks its local signature
            # rows, one bool crosses the mesh
            mem_local = _fused_subgroup_mask(sig, sig_inf).all()
            ok = jnp.logical_and(ok, lax.all_gather(mem_local, axis).all())
        return ok

    batch = P(axis)
    shardings = (
        batch, batch, batch,  # pk x/y/inf
        batch, batch, batch,  # sig
        batch, batch, batch,  # msg
        batch,                # r_bits
    )
    # check_vma=False: montmul's lax.scan carries start as replicated
    # constants and become device-varying, which the VMA checker rejects
    # (the computation is still correct SPMD — every collective is explicit).
    fn = shard_map(
        local_step, mesh=mesh, in_specs=shardings, out_specs=P(), check_vma=False
    )
    return _no_persistent_cache_first_call(jax.jit(fn))


def sharded_msm_plans(r_lo, r_hi, pk_inf, sig_inf, n_dev: int):
    """Per-chip MsmPlans for the sharded grouped verify: the (M, K) batch
    is sharded over K (each chip owns K/n_dev members of every group), so
    chip d's scalars are the k-major rows kk ∈ [d·K/D, (d+1)·K/D). All
    chips share one (windows, window_bits, S, T, J) shape — J is padded to
    the fleet max so the stacked plan arrays are rectangular.

    Returns (g1_arrays, g2_arrays, g1_plan0, g2_plan0) where *_arrays are
    the MsmPlan.arrays tuples stacked on a leading device axis."""
    m, k = pk_inf.shape
    assert k % n_dev == 0, "K must divide over the mesh"
    k_loc = k // n_dev
    r_lo = np.asarray(r_lo, np.uint64).reshape(k, m)
    r_hi = np.asarray(r_hi, np.uint64).reshape(k, m)
    pk_inf_km = np.asarray(pk_inf, bool).T  # (K, M)
    sig_inf_km = np.asarray(sig_inf, bool).T
    groups_loc = np.arange(k_loc * m) % m
    g1_w = pick_msm_window(k_loc * m, m)
    g2_w = pick_msm_window(k_loc * m, 1)
    g1_plans, g2_plans = [], []
    for d in range(n_dev):
        sl = slice(d * k_loc, (d + 1) * k_loc)
        lo = r_lo[sl].reshape(-1)
        hi = r_hi[sl].reshape(-1)
        g1_plans.append(M.plan_msm(
            lo, hi, pk_inf_km[sl].reshape(-1), groups_loc, m,
            window_bits=g1_w,
        ))
        g2_plans.append(M.plan_msm(
            lo, hi, sig_inf_km[sl].reshape(-1), None, 1, window_bits=g2_w,
        ))

    def stack(plans):
        j_max = max(p.gather_idx.shape[0] for p in plans)

        def pad_j(a):
            if a.shape[0] == j_max:
                return a
            pad = np.zeros((j_max - a.shape[0],) + a.shape[1:], a.dtype)
            return np.concatenate([a, pad], axis=0)

        cols = list(zip(*(p.arrays for p in plans)))
        out = []
        for i, col in enumerate(cols):
            col = [pad_j(a) if i >= 3 else a for a in col]  # gather_* pads
            out.append(np.stack(col, axis=0))
        return tuple(out)

    return stack(g1_plans), stack(g2_plans), g1_plans[0], g2_plans[0]


def make_sharded_multi_verify_msm(
    mesh, g1_windows: int, g1_wbits: int, g2_windows: int, g2_wbits: int,
    axis: str = "batch", check_subgroup: int = 0,
):
    """Multi-chip grouped RLC batch verify on the MSM plane (VERDICT r4
    weak #4): the (M, K) member axis is sharded over the mesh; each chip
    runs the Pippenger bucket scan on its K/D members of every group, the
    per-group partial sums cross chips in ONE all-gather of M (+1) points,
    and the Miller plane is sharded by MESSAGE (chip d pairs groups
    [d·M/D, (d+1)·M/D) with the reduced sums). A second all-gather moves
    one Fp12 partial per chip; the final exponentiation runs replicated.

    Collectives: two tiny all-gathers over ICI — the pairing-product
    reduction is the only cross-chip communication the workload needs
    (SURVEY §2.4)."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    n_dev = mesh.shape[axis]
    assert n_dev & (n_dev - 1) == 0, "power-of-two mesh required"

    def reduce_over_devices(pt, ops):
        """All-gather per-chip partial points and tree-add over the device
        axis (leaves gain the gathered axis at position 1)."""
        gathered = tuple(
            jax.tree.map(lambda x: lax.all_gather(x, axis, axis=1), e)
            for e in pt
        )

        def body(_, carry):
            y, s = carry
            rolled = tuple(
                jax.tree.map(lambda a: jnp.roll(a, -s, axis=1), e)
                for e in y
            )
            y = C.point_add_complete(y, rolled, ops)
            return (y, s // 2)

        levels = n_dev.bit_length() - 1
        if levels:
            gathered, _ = lax.fori_loop(
                0, levels, body, (gathered, jnp.int32(n_dev // 2))
            )
        return tuple(jax.tree.map(lambda a: a[:, 0], e) for e in gathered)

    # NOT named `local_step`: the plain RLC factory's inner fn already
    # compiles as XLA module `jit_local_step`, and sharing the name made
    # one MSM compile read as a double compile of the RLC kernel in the
    # MULTICHIP dryrun logs (two identically-named slow-compile alarms)
    def local_step_msm(
        pk_x, pk_y, pk_inf, sig_x, sig_y, sig_inf, msg_x, msg_y, msg_inf,
        g1_pidx, g1_valid, g1_flush, g1_gidx, g1_gvalid,
        g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
    ):
        # plan blocks arrive with a length-1 leading device axis
        (g1_pidx, g1_valid, g1_flush, g1_gidx, g1_gvalid,
         g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid) = (
            a[0] for a in (
                g1_pidx, g1_valid, g1_flush, g1_gidx, g1_gvalid,
                g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
            )
        )
        m, k_loc = pk_inf.shape
        pk = _g1_in(_flat_km(pk_x, m, k_loc), _flat_km(pk_y, m, k_loc))
        sig = _g2_in(_flat_km(sig_x, m, k_loc), _flat_km(sig_y, m, k_loc))
        msg = _g2_in(msg_x, msg_y)
        pk_inf_f = jnp.asarray(_flat_km(pk_inf, m, k_loc))
        sig_inf_f = jnp.asarray(_flat_km(sig_inf, m, k_loc))
        msg_inf_l = jnp.asarray(msg_inf)

        epx, epy, eplive = M.expand_glv_points(
            pk[0], pk[1], pk_inf_f, _g1_endo(m * k_loc), C.FP_OPS
        )
        gpk_local = M.msm_bucket_scan(
            epx, epy, eplive,
            g1_pidx, g1_valid, g1_flush, g1_gidx, g1_gvalid,
            windows=g1_windows, window_bits=g1_wbits, n_groups=m,
            ops=C.FP_OPS,
        )
        esx, esy, eslive = M.expand_glv_points(
            sig[0], sig[1], sig_inf_f, _g2_endo(m * k_loc), C.FP2_OPS
        )
        sig_local = M.msm_bucket_scan(
            esx, esy, eslive,
            g2_pidx, g2_valid, g2_flush, g2_gidx, g2_gvalid,
            windows=g2_windows, window_bits=g2_wbits, n_groups=1,
            ops=C.FP2_OPS,
        )
        # cross-chip: group sums and the G2 partial (one all-gather each)
        gpk = reduce_over_devices(gpk_local, C.FP_OPS)  # (M,)
        sig_acc_g = reduce_over_devices(sig_local, C.FP2_OPS)  # (1,)
        sig_acc = tuple(C.FP2_OPS.index(e, 0) for e in sig_acc_g)

        # Miller plane sharded by MESSAGE: chip d takes its M/D slice
        assert m % n_dev == 0, "group count must divide over the mesh"
        m_loc = m // n_dev
        start = lax.axis_index(axis) * m_loc

        def slice_m(e):
            return jax.tree.map(
                lambda a: lax.dynamic_slice_in_dim(a, start, m_loc, axis=1),
                e,
            )

        gpk_s = tuple(slice_m(e) for e in gpk)
        msg_s = tuple(slice_m(e) for e in (msg[0], msg[1]))
        pair_inf = lax.dynamic_slice_in_dim(
            L.is_zero_val(gpk[2]) | msg_inf_l, start, m_loc, axis=0
        )
        msg_q = (msg_s[0], msg_s[1], F.fp2_one((m_loc,)))
        f_local = TP.fp12_product_tree(TP.miller_loop(gpk_s, msg_q, pair_inf))
        f_all = jax.tree.map(
            lambda x: lax.all_gather(x, axis, axis=1), f_local
        )
        ok = _rlc_finish(TP.fp12_product_tree(f_all), sig_acc)
        if check_subgroup:
            mem_local = _fused_subgroup_mask(sig, sig_inf_f).all()
            ok = jnp.logical_and(ok, lax.all_gather(mem_local, axis).all())
        return ok

    member = P(None, axis)  # shard the K axis of (M, K, …) point arrays
    plan = P(axis)          # per-chip plan stacks (D, S, T)
    in_specs = (
        member, member, member,  # pk
        member, member, member,  # sig
        P(), P(), P(),           # msg replicated
        plan, plan, plan, plan, plan,   # g1 plan
        plan, plan, plan, plan, plan,   # g2 plan
    )
    fn = shard_map(
        local_step_msm, mesh=mesh, in_specs=in_specs, out_specs=P(),
        check_vma=False,
    )
    return _no_persistent_cache_first_call(jax.jit(fn))


# --- promoted sharded dispatch targets --------------------------------------
#
# The make_* factories above build a FRESH jax.jit wrapper per call — fine
# for one-shot dryruns, but the production verify plane dispatches per
# batch, and a fresh wrapper per batch would re-trace and re-compile every
# time. Promotion to registered dispatch targets means ONE process-wide
# executable per (kernel, mesh, statics), cached here — the mesh twin of
# `_JITTED` (kept separate because the key carries device identity and
# every entry is already wrapped in the persistent-cache bypass).

_SHARDED_FACTORIES: dict = {}
_SHARDED_FACTORY_LOCK = threading.Lock()


def _mesh_factory_key(mesh, axis: str) -> tuple:
    return (axis,) + tuple(
        int(d.id) for d in np.asarray(mesh.devices).flat
    )


def sharded_multi_verify(mesh, axis: str = "batch", check_subgroup: int = 0):
    """The registered multi-chip RLC batch-verify dispatch target: one
    cached `make_sharded_multi_verify` wrapper per (mesh, axis, statics),
    so every backend and every batch shares one compiled executable per
    shape."""
    key = (
        "sharded_multi_verify", _mesh_factory_key(mesh, axis),
        int(check_subgroup),
    )
    with _SHARDED_FACTORY_LOCK:
        fn = _SHARDED_FACTORIES.get(key)
        if fn is None:
            fn = make_sharded_multi_verify(
                mesh, axis=axis, check_subgroup=check_subgroup
            )
            _SHARDED_FACTORIES[key] = fn
    return fn


def sharded_multi_verify_msm(
    mesh, g1_windows: int, g1_wbits: int, g2_windows: int, g2_wbits: int,
    axis: str = "batch", check_subgroup: int = 0,
):
    """The registered multi-chip grouped-MSM dispatch target, cached per
    (mesh, axis, MSM window statics) like `sharded_multi_verify`."""
    key = (
        "sharded_multi_verify_msm", _mesh_factory_key(mesh, axis),
        int(g1_windows), int(g1_wbits), int(g2_windows), int(g2_wbits),
        int(check_subgroup),
    )
    with _SHARDED_FACTORY_LOCK:
        fn = _SHARDED_FACTORIES.get(key)
        if fn is None:
            fn = make_sharded_multi_verify_msm(
                mesh, g1_windows=g1_windows, g1_wbits=g1_wbits,
                g2_windows=g2_windows, g2_wbits=g2_wbits, axis=axis,
                check_subgroup=check_subgroup,
            )
            _SHARDED_FACTORIES[key] = fn
    return fn


import threading as _threading

_CACHE_BYPASS_LOCK = _threading.RLock()
_CACHE_BYPASS_DEPTH = [0]


def _no_persistent_cache_first_call(jitted):
    """Wrap a jitted MULTI-DEVICE function so every call runs with the
    persistent compilation cache bypassed in both directions (jax.jit
    compiles once per input SHAPE, so any call may compile).

    Multi-device executables and the on-disk cache do not mix here:
    serializing one ABORTS inside XLA (proto-size CHECK in
    put_executable_and_time), and deserializing an entry written by an
    earlier/killed run SEGFAULTS in get_executable_and_time — both
    observed on the 8-device CPU mesh. The bypass is SCOPED: the
    thread-local config context manager (enable_compilation_cache)
    disables the cache for this call stack only — the process-global
    jax_enable_compilation_cache flag is never touched, so threads
    outside the wrapper keep their own setting. The cache-enabled
    decision is LATCHED per process (compilation_cache.is_cache_used
    memoizes its first config read), so the scoped flag is paired with
    a latch reset on both sides, and the latch is re-primed from THIS
    thread (whose scoped view is "disabled") before the jitted call so
    a concurrent compile cannot latch it enabled first. A depth-counted
    lock makes concurrent sharded calls nest instead of racing the
    window shut; unrelated kernels that compile inside an open window
    merely skip their cache entry (benign, unchanged from before)."""
    def call(*args):
        return _cache_bypassed_call(jitted, *args)

    return call


def _cache_bypassed_call(fn, *args):
    """Run one call with the persistent compilation cache scoped OFF (see
    `_no_persistent_cache_first_call` for the full rationale). Also used
    directly by the backend's mesh-mode indexed dispatches, whose
    executables become multi-device once the registry rows are sharded."""
    from jax._src import compilation_cache as _cc
    from jax._src import config as _jcfg

    with _jcfg.enable_compilation_cache(False):
        with _CACHE_BYPASS_LOCK:
            _CACHE_BYPASS_DEPTH[0] += 1
            if _CACHE_BYPASS_DEPTH[0] == 1:
                _cc.reset_cache()
                try:  # prime the latch under the scoped "disabled"
                    _cc.is_cache_used(jax.devices()[0].client)
                except Exception:
                    pass  # latch priming is best-effort
        try:
            return fn(*args)
        finally:
            with _CACHE_BYPASS_LOCK:
                _CACHE_BYPASS_DEPTH[0] -= 1
                if _CACHE_BYPASS_DEPTH[0] == 0:
                    _cc.reset_cache()  # re-latch lazily outside


# --- host-facing backend ----------------------------------------------------


#: Largest device bucket; bigger host batches are split into chunks of this
#: size (each chunk is one RLC check — all chunks must pass).
MAX_BUCKET = 1 << 14


def _bucket(n: int, lo: int = 4, hi: int = MAX_BUCKET) -> int:
    b = lo
    while b < n:
        b <<= 1
    if b > hi:
        raise ValueError(f"batch of {n} exceeds max bucket {hi}")
    return b


# jax.jit caches per wrapper object — keep one wrapper per kernel for the
# whole process so every TpuBlsBackend instance shares compilations.
_JITTED: dict = {}


def _jitted_global(name: str, fn, donate=()):
    """One process-wide jitted wrapper per (kernel, donation policy).
    `donate` names the positional operands XLA may alias as outputs
    (donate_argnums): the dispatch sites only donate per-batch uploads —
    never registry arrays — and the donated-buffer-reuse lint rule
    enforces that no donated operand is touched after dispatch."""
    key = name if not donate else name + "|donate=" + repr(tuple(donate))
    f = _JITTED.get(key)
    if f is None:
        f = jax.jit(fn, donate_argnums=tuple(donate))
        _JITTED[key] = f
    return f


# --- shape-signature tracking (tools/shapes contract) -----------------------
#
# Process-wide ledger of every (kernel, arg-shapes) signature dispatched
# through _run_kernel. jax.jit compiles per signature, so after warmup
# declares the manifest compiled, a NOVEL signature means a live batch is
# stalling on XLA — counted in `verify_recompiles_total` and asserted
# zero by bench soaks and tests. Global (not per-backend) because
# _JITTED is: every TpuBlsBackend shares one compile cache.

_SHAPE_LOCK = threading.Lock()
_SHAPES_SEEN: set = set()
_WARMUP_SEALED = [False]
_POST_WARMUP_COMPILES = [0]


def _shape_key(kernel: str, args: tuple):
    return (kernel, tuple(
        (tuple(a.shape), str(a.dtype)) if hasattr(a, "shape") else repr(a)
        for a in args
    ))


def _node_profiler():
    """runtime.profiler.get_profiler, resolved lazily: the kernel layer
    must not pull the runtime package in at module import time. The
    profiler's annotate() is a dict bump when no capture session is
    active; during a session it opens the TraceAnnotation scope keyed
    (scheme, kernel, bucket)."""
    mod = sys.modules.get("grandine_tpu.runtime.profiler")
    if mod is None:
        from grandine_tpu.runtime import profiler as mod
    return mod.get_profiler()


def note_dispatch_shapes(kernel: str, args: tuple, metrics=None) -> bool:
    """Record a dispatch signature; True when it is novel this process.

    Novel-after-seal increments the recompile accounting (and the
    `verify_recompiles_total` counter when metrics are wired)."""
    key = _shape_key(kernel, args)
    with _SHAPE_LOCK:
        if key in _SHAPES_SEEN:
            return False
        _SHAPES_SEEN.add(key)
        sealed = _WARMUP_SEALED[0]
        if sealed:
            _POST_WARMUP_COMPILES[0] += 1
    if sealed and metrics is not None:
        metrics.verify_recompiles.inc()
    return True


def declare_warmup_complete() -> None:
    """Seal the shape ledger: every signature from here on is a recompile."""
    with _SHAPE_LOCK:
        _WARMUP_SEALED[0] = True


def warmup_declared() -> bool:
    with _SHAPE_LOCK:
        return _WARMUP_SEALED[0]


def post_warmup_recompiles() -> int:
    with _SHAPE_LOCK:
        return _POST_WARMUP_COMPILES[0]


def reset_shape_tracking() -> None:
    """Test seam: forget signatures and unseal (compiles in _JITTED stay)."""
    with _SHAPE_LOCK:
        _SHAPES_SEEN.clear()
        _WARMUP_SEALED[0] = False
        _POST_WARMUP_COMPILES[0] = 0


_ZERO2 = np.zeros((2, L.NLIMBS), np.int32)


#: cap on the per-backend hash-to-curve device-point cache; gossip traffic
#: churns through distinct AttestationData roots, so an unbounded cache is a
#: slow leak (~1.3 KB/entry) — override for benchmarking via the environment
H2C_CACHE_CAP = int(os.environ.get("GT_H2C_CACHE_CAP", "4096"))


class _LruCache:
    """Bounded thread-safe LRU keyed by hashables, with labeled metrics.

    Used for the hash-to-G2 message-point cache: hits remove a ~1 ms host
    hash_to_curve from the batch clock, but gossip churn means the key
    space is unbounded, so eviction (not clearing) keeps the hot working
    set — the current epoch's AttestationData points — resident."""

    def __init__(self, cap: int, name: str, metrics=None) -> None:
        self.cap = max(1, int(cap))
        self.name = name
        self.metrics = metrics
        self._lock = threading.Lock()
        self._entries: "OrderedDict" = OrderedDict()

    def __len__(self) -> int:
        return len(self._entries)

    def _event(self, event: str) -> None:
        if self.metrics is not None:
            self.metrics.device_cache_events.labels(self.name, event).inc()

    def get(self, key):
        with self._lock:
            hit = self._entries.get(key)
            if hit is None:
                self._event("miss")
                return None
            self._entries.move_to_end(key)
            self._event("hit")
            return hit

    def put(self, key, value) -> None:
        with self._lock:
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self.cap:
                self._entries.popitem(last=False)
                self._event("evict")
            if self.metrics is not None:
                self.metrics.device_cache_size.set(
                    self.name, value=float(len(self._entries))
                )


class TpuBlsBackend:
    """Host façade: anchor-typed in/out, device execution, bucket-padded jit.

    The policy mirror of grandine_tpu/crypto/bls.py's multi_verify /
    fast_aggregate_verify — same edge-case semantics (empty batch, identity
    pubkeys), differential-tested against the anchor."""

    #: the async verify seam the runtime dispatches through (the first
    #: two are what runtime/health.py's REQUIRED_SEAM_METHODS detects;
    #: fault injection wraps exactly these — testing/chaos.ChaosBackend)
    ASYNC_SEAM = (
        "fast_aggregate_verify_batch_async",
        "g2_subgroup_check_batch_async",
        "fast_aggregate_verify_batch_indexed_async",
        "multi_verify_async",
        "rlc_partition_verify_async",
        "multi_verify_compressed_async",
        "fast_aggregate_verify_batch_compressed_async",
        "fast_aggregate_verify_batch_indexed_compressed_async",
    )

    def __init__(self, metrics=None, tracer=None,
                 lane: str = "attestation", mesh=None,
                 fuse_subgroup: "Optional[bool]" = None,
                 donate_buffers: "Optional[bool]" = None) -> None:
        from grandine_tpu.tpu.mesh import mesh_or_none

        #: observability seams (wired by runtime/attestation_verifier):
        #: per-stage histograms/spans + per-kernel-variant counters when
        #: set; with both None every hook is a cheap early return
        self.metrics = metrics
        self.tracer = tracer
        #: injected VerifyMesh (tpu/mesh.py) — None (or a degenerate
        #: 1-device mesh, normalized away here) keeps every dispatch below
        #: byte-identical to the single-chip backend: same kernels, same
        #: jit cache keys, same executables. Topology is NEVER discovered
        #: here (no jax.devices() in dispatch paths — lint-enforced);
        #: whoever owns the process hands the mesh in.
        self.mesh = mesh_or_none(mesh)
        #: lane label on verify_stage_seconds — the verify scheduler
        #: builds one façade per lane so device stages attribute to the
        #: lane that dispatched them (jitted kernels stay shared)
        self.lane = lane
        self._h2c_cache = _LruCache(
            H2C_CACHE_CAP, "hash_to_g2_dev", metrics=metrics
        )
        #: single-pass fused verification: the ψ-ladder subgroup check
        #: runs INSIDE each verify kernel (check_subgroup static) and the
        #: dispatchers skip the separate g2_subgroup_check pass — one
        #: device dispatch per batch instead of two. Default ON;
        #: GRANDINE_TPU_FUSE_SUBGROUP=0 restores the two-pass plane (the
        #: differential tests compare both).
        if fuse_subgroup is None:
            fuse_subgroup = os.environ.get(
                "GRANDINE_TPU_FUSE_SUBGROUP", "1"
            ) not in ("0", "false", "no")
        self.fuse_subgroup = bool(fuse_subgroup)
        #: buffer donation (donate_argnums): per-batch uploads are handed
        #: to XLA for output aliasing, stopping the HBM round-trip per
        #: pipelined kernel. Donation is unimplemented on CPU (jax warns
        #: per call and falls back to copies), so the default is
        #: platform-gated; GRANDINE_TPU_DONATE=0/1 overrides. Registry
        #: arrays are NEVER donated — they persist across batches.
        if donate_buffers is None:
            env = os.environ.get("GRANDINE_TPU_DONATE")
            if env is not None:
                donate_buffers = env not in ("0", "false", "no")
            else:
                donate_buffers = jax.default_backend() != "cpu"
        self.donate_buffers = bool(donate_buffers)
        #: (kernel, arg shapes) pairs already dispatched — a miss means
        #: the next dispatch blocks on XLA compilation, so its host-side
        #: call time is attributed to the `compile` stage
        self._seen_shapes: set = set()

    # -- conversions -------------------------------------------------------

    def _hash_to_g2_dev(self, message: bytes, dst: bytes):
        key = (message, dst)
        hit = self._h2c_cache.get(key)
        if hit is None:
            hit = C.g2_point_to_dev(hash_to_g2(message, dst))
            self._h2c_cache.put(key, hit)
        return hit

    def _jitted(self, name: str, fn, donate=()):
        return _jitted_global(name, fn, donate=donate)

    def _donate(self, n: int, skip: int = 0) -> tuple:
        """donate_argnums for a kernel taking `n` per-batch operands after
        `skip` persistent ones (registry arrays at positions < skip are
        never donated). Empty when donation is off."""
        if not self.donate_buffers:
            return ()
        return tuple(range(skip, skip + n))

    # -- observability -----------------------------------------------------

    def _observed(self) -> bool:
        return self.metrics is not None or self.tracer is not None

    @contextmanager
    def _stage(self, stage: str, **attrs):
        """One device-plane stage: span (when tracing) + one
        `verify_stage_seconds{stage=...}` observation (when metered)."""
        if not self._observed():
            yield
            return
        t0 = time.perf_counter()
        if self.tracer is not None:
            with self.tracer.span(stage, attrs or None):
                yield
        else:
            yield
        if self.metrics is not None:
            self.metrics.verify_stage_seconds.labels(
                stage, self.lane
            ).observe(time.perf_counter() - t0)

    def _count_kernel(self, kernel: str, sigs: int) -> None:
        if self.metrics is not None:
            self.metrics.device_kernel_calls.labels(kernel).inc()
            if sigs:
                self.metrics.device_kernel_sigs.labels(kernel).inc(sigs)

    @staticmethod
    def _block(out):
        for leaf in jax.tree_util.tree_leaves(out):
            if hasattr(leaf, "block_until_ready"):
                leaf.block_until_ready()
        return out

    def _upload(self, args: tuple, kernel: str = "unlabeled") -> tuple:
        """upload_bytes stage: push host arrays to the device explicitly
        so the transfer is attributable PER KERNEL (dispatch would do the
        identical transfer implicitly). Device-resident operands — the
        pubkey registry arrays — must bypass this seam: the per-kernel
        `device_upload_bytes_total` counter is the accounting that
        tools/check_no_per_batch_upload.py audits. No-op when unobserved."""
        if not self._observed():
            return args
        nbytes = sum(int(getattr(a, "nbytes", 0)) for a in args)
        if self.metrics is not None:
            self.metrics.device_upload_bytes.labels(kernel).inc(nbytes)
        with self._stage("upload_bytes", bytes=nbytes, kernel=kernel):
            return self._block(jax.device_put(args))

    def _upload_sharded(self, args: tuple, shardings, kernel: str) -> tuple:
        """Mesh-mode upload: place each host array with its explicit
        `NamedSharding` (jit would infer the same placement from the
        shard_map in_specs, but explicit placement keeps the transfer on
        the upload_bytes clock and out of the dispatch stage). Unlike
        `_upload` this must run even unobserved — the placement is the
        point, not the accounting."""
        if not self._observed():
            return tuple(
                jax.device_put(a, s) for a, s in zip(args, shardings)
            )
        nbytes = sum(int(getattr(a, "nbytes", 0)) for a in args)
        if self.metrics is not None:
            self.metrics.device_upload_bytes.labels(kernel).inc(nbytes)
        with self._stage("upload_bytes", bytes=nbytes, kernel=kernel):
            return self._block(tuple(
                jax.device_put(a, s) for a, s in zip(args, shardings)
            ))

    def _run_kernel(self, kernel: str, fn, args: tuple, sigs: int = 0,
                    block: bool = True, mesh_operands: bool = False):
        """Dispatch with compile/execute attribution. The first dispatch
        for a (kernel, shapes) pair blocks on trace+XLA compilation, so
        its host-side call time IS the compile stage; warm dispatches are
        async µs and the device run is timed via block_until_ready. With
        block=False the caller keeps the async seam and settles later
        (see _settle). `mesh_operands` marks kernels consuming
        mesh-committed arrays (sharded registry rows): on a multi-device
        mesh their executables are multi-device, which the persistent XLA
        cache cannot round-trip, so the call runs cache-bypassed."""
        self._count_kernel(kernel, sigs)
        note_dispatch_shapes(kernel, args, self.metrics)
        prof = _node_profiler()
        if mesh_operands and self.mesh is not None:
            inner = fn

            def fn(*a):
                return _cache_bypassed_call(inner, *a)
        if not self._observed():
            with prof.annotate(kernel, sigs):
                return fn(*args)
        shapes = tuple(
            (tuple(a.shape), str(a.dtype)) if hasattr(a, "shape") else repr(a)
            for a in args
        )
        key = (kernel, shapes)
        if key not in self._seen_shapes:
            with self._stage("compile", kernel=kernel):
                with prof.annotate(kernel, sigs):
                    out = fn(*args)
            self._seen_shapes.add(key)
        else:
            with prof.annotate(kernel, sigs):
                out = fn(*args)
        if block:
            with self._stage("execute", kernel=kernel):
                self._block(out)
        return out

    def _settle(self, kernel: str, result) -> bool:
        """Force an async dispatch: remaining device time under execute,
        the host conversion under readback."""
        if not self._observed():
            return bool(result)
        with self._stage("execute", kernel=kernel):
            self._block(result)
        with self._stage("readback", kernel=kernel):
            return bool(result)

    # -- verification ------------------------------------------------------

    def multi_verify(
        self,
        messages: Sequence[bytes],
        signatures: Sequence["A.Signature"],
        public_keys: Sequence["A.PublicKey"],
        dst: bytes = constants.DST_SIGNATURE,
        rng=secrets,
    ) -> bool:
        return self.multi_verify_async(messages, signatures, public_keys, dst, rng)()

    def multi_verify_async(
        self,
        messages: Sequence[bytes],
        signatures: Sequence["A.Signature"],
        public_keys: Sequence["A.PublicKey"],
        dst: bytes = constants.DST_SIGNATURE,
        rng=secrets,
    ):
        """Dispatch the batch to the device WITHOUT blocking: returns a
        zero-arg callable producing the bool. XLA execution is async until
        the result is forced, so host work (block processing) overlaps the
        device pairing — the seam `combined.custom_state_transition` uses
        for its verify-∥-process split."""
        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            return lambda: False
        if n == 0:
            return lambda: True
        if n > MAX_BUCKET:
            # Two-deep pipeline: only chunk 0 is dispatched now (so callers
            # still overlap it with host work); settle() dispatches chunk
            # k+1 before forcing chunk k. Bounds device residency at two
            # chunks and stops dispatching after the first failure.
            def chunk(i):
                return self.multi_verify_async(
                    messages[i : i + MAX_BUCKET],
                    signatures[i : i + MAX_BUCKET],
                    public_keys[i : i + MAX_BUCKET],
                    dst,
                    rng,
                )

            first = chunk(0)

            def settle_chunks() -> bool:
                pending = first
                for i in range(MAX_BUCKET, n, MAX_BUCKET):
                    nxt = chunk(i)
                    if not pending():
                        return False
                    pending = nxt
                return pending()

            return settle_chunks
        if any(pk.point.is_infinity() for pk in public_keys):
            return lambda: False
        with self._stage("host_prep", op="point_convert", items=n):
            # batched host conversions: one inversion + one limb pass per
            # class
            g1x, g1y, g1inf = C.g1_points_to_dev(
                [pk.point for pk in public_keys]
            )
            g2x, g2y, g2inf = C.g2_points_to_dev(
                [s.point for s in signatures]
            )

            # group triples by message: Miller loops collapse from N to the
            # number of DISTINCT messages (grouped_multi_verify_msm_kernel)
            groups: "dict[bytes, list[int]]" = {}
            for i, msg in enumerate(messages):
                groups.setdefault(bytes(msg), []).append(i)
        n_groups = len(groups)
        if 2 * n_groups <= n:
            bm = _bucket(n_groups)
            bk = _bucket(max(len(v) for v in groups.values()))
            if bm * bk <= 4 * _bucket(n):  # bounded padding waste
                return self._grouped_multi_verify_async(
                    groups, g1x, g1y, g1inf, g2x, g2y, g2inf,
                    bm, bk, dst, rng,
                )

        with self._stage("host_prep", op="pack", items=n):
            b = _bucket(n)
            pk_x = np.zeros((b, L.NLIMBS), np.int32)
            pk_y = np.zeros((b, L.NLIMBS), np.int32)
            pk_inf = np.ones((b,), bool)
            sig_x = np.zeros((b, 2, L.NLIMBS), np.int32)
            sig_y = np.zeros((b, 2, L.NLIMBS), np.int32)
            sig_inf = np.ones((b,), bool)
            msg_x = np.zeros((b, 2, L.NLIMBS), np.int32)
            msg_y = np.zeros((b, 2, L.NLIMBS), np.int32)
            msg_inf = np.ones((b,), bool)
            pk_x[:n], pk_y[:n], pk_inf[:n] = g1x, g1y, g1inf
            sig_x[:n], sig_y[:n], sig_inf[:n] = g2x, g2y, g2inf
            for i in range(n):
                x, y, inf = self._hash_to_g2_dev(messages[i], dst)
                msg_x[i], msg_y[i], msg_inf[i] = x, y, inf
            pairs = [self._rlc_pair(rng) for _ in range(n)]
            r_bits = rlc_bits_host(pairs, b)
        mesh = self.mesh
        if mesh is not None and mesh.divides(b) and b >= 2 * mesh.device_count:
            # data-parallel whole-batch dispatch over the promoted sharded
            # RLC kernel: batch rows shard over the mesh, each chip runs
            # its local ladders/Miller loops, and the pairing-product
            # all-gather is the only collective (tpu/mesh.py seam)
            fn = sharded_multi_verify(
                mesh.mesh, axis=mesh.axis,
                check_subgroup=int(self.fuse_subgroup),
            )
            args = self._upload_sharded(
                (pk_x, pk_y, pk_inf, sig_x, sig_y, sig_inf,
                 msg_x, msg_y, msg_inf, r_bits),
                (mesh.batch_sharding(),) * 10,
                kernel="sharded_multi_verify",
            )
            result = self._run_kernel(
                "sharded_multi_verify", fn, args, sigs=n, block=False,
                mesh_operands=True,
            )
            return lambda: self._settle("sharded_multi_verify", result)
        with self._stage("host_prep", op="msm_plan", items=n):
            g2_plan = self._g2_plan(pairs, b, sig_inf)
        args = self._upload((
            pk_x, pk_y, pk_inf, sig_x, sig_y, sig_inf, msg_x, msg_y, msg_inf,
            r_bits, *g2_plan.arrays,
        ), kernel="multi_verify_msm")
        fn = self._jitted_msm(
            "multi_verify_msm", multi_verify_msm_kernel,
            donate=self._donate(len(args)),
            g2_windows=g2_plan.windows, g2_wbits=g2_plan.window_bits,
            check_subgroup=int(self.fuse_subgroup),
        )
        # async dispatch; forcing happens in the returned closure
        result = self._run_kernel(
            "multi_verify_msm", fn, args, sigs=n, block=False
        )
        return lambda: self._settle("multi_verify_msm", result)

    @staticmethod
    def _g2_plan(pairs, b, sig_inf):
        """MSM plan for Σ rᵢ·sigᵢ over a padded bucket of b slots (real
        pairs first; padding masked out via sig_inf)."""
        r_lo = np.zeros(b, np.uint64)
        r_hi = np.zeros(b, np.uint64)
        n = len(pairs)
        r_lo[:n] = [p[0] for p in pairs]
        r_hi[:n] = [p[1] for p in pairs]
        return M.plan_msm(
            r_lo, r_hi, np.asarray(sig_inf, bool), None, 1,
            window_bits=pick_msm_window(b, 1),
        )

    def _jitted_msm(self, name: str, fn, donate=(), **static_kw):
        key = name + repr(sorted(static_kw.items()))
        if donate:
            key += "|donate=" + repr(tuple(donate))
        cached = _JITTED.get(key)
        if cached is None:
            import functools

            # functools.partial applies keywords only, so positional
            # donate_argnums indices are unaffected by the static binding
            cached = jax.jit(
                functools.partial(fn, **static_kw),
                donate_argnums=tuple(donate),
            )
            _JITTED[key] = cached
        return cached

    def _grouped_multi_verify_async(
        self, groups, g1x, g1y, g1inf, g2x, g2y, g2inf, bm, bk, dst, rng
    ):
        """Pack per-message groups into the (M, K) grouped MSM kernel.

        Kernel-flat point index f ↔ grouped slot (f mod bm, f div bm), so
        the MSM plans carry scalars in f = kk·bm + j order with
        group(f) = f mod bm."""
        with self._stage("host_prep", op="pack_grouped", items=bm * bk):
            pk_x = np.zeros((bm, bk, L.NLIMBS), np.int32)
            pk_y = np.zeros((bm, bk, L.NLIMBS), np.int32)
            pk_inf = np.ones((bm, bk), bool)
            sig_x = np.zeros((bm, bk, 2, L.NLIMBS), np.int32)
            sig_y = np.zeros((bm, bk, 2, L.NLIMBS), np.int32)
            sig_inf = np.ones((bm, bk), bool)
            msg_x = np.zeros((bm, 2, L.NLIMBS), np.int32)
            msg_y = np.zeros((bm, 2, L.NLIMBS), np.int32)
            msg_inf = np.ones((bm,), bool)
            r_lo = np.zeros(bm * bk, np.uint64)
            r_hi = np.zeros(bm * bk, np.uint64)
            n_real = 0
            for j, (msg, idxs) in enumerate(groups.items()):
                x, y, inf = self._hash_to_g2_dev(msg, dst)
                msg_x[j], msg_y[j], msg_inf[j] = x, y, inf
                for kk, i in enumerate(idxs):
                    pk_x[j, kk], pk_y[j, kk], pk_inf[j, kk] = (
                        g1x[i], g1y[i], g1inf[i],
                    )
                    sig_x[j, kk], sig_y[j, kk], sig_inf[j, kk] = (
                        g2x[i], g2y[i], g2inf[i],
                    )
                    r_lo[kk * bm + j], r_hi[kk * bm + j] = self._rlc_pair(rng)
                    n_real += 1
        mesh = self.mesh
        if (
            mesh is not None
            and bk % mesh.device_count == 0
            and bm % mesh.device_count == 0
        ):
            return self._sharded_grouped_verify_async(
                mesh, pk_x, pk_y, pk_inf, sig_x, sig_y, sig_inf,
                msg_x, msg_y, msg_inf, r_lo, r_hi, n_real,
            )
        with self._stage("host_prep", op="msm_plan", items=bm * bk):
            flat_inf = pk_inf.T.reshape(-1)  # f = kk·bm + j order; pads True
            flat_groups = np.arange(bm * bk) % bm
            g1_plan = M.plan_msm(
                r_lo, r_hi, flat_inf, flat_groups, bm,
                window_bits=pick_msm_window(n_real, bm),
            )
            g2_plan = M.plan_msm(
                r_lo, r_hi, sig_inf.T.reshape(-1), None, 1,
                window_bits=pick_msm_window(n_real, 1),
            )
        args = self._upload((
            pk_x, pk_y, pk_inf, sig_x, sig_y, sig_inf,
            msg_x, msg_y, msg_inf, *g1_plan.arrays, *g2_plan.arrays,
        ), kernel="grouped_multi_verify_msm")
        fn = self._jitted_msm(
            "grouped_multi_verify_msm", grouped_multi_verify_msm_kernel,
            donate=self._donate(len(args)),
            g1_windows=g1_plan.windows, g1_wbits=g1_plan.window_bits,
            g2_windows=g2_plan.windows, g2_wbits=g2_plan.window_bits,
            check_subgroup=int(self.fuse_subgroup),
        )
        result = self._run_kernel(
            "grouped_multi_verify_msm", fn, args, sigs=n_real, block=False
        )
        return lambda: self._settle("grouped_multi_verify_msm", result)

    def _sharded_grouped_verify_async(
        self, mesh, pk_x, pk_y, pk_inf, sig_x, sig_y, sig_inf,
        msg_x, msg_y, msg_inf, r_lo, r_hi, n_real,
    ):
        """Grouped batch over the promoted sharded MSM kernel: the (M, K)
        member axis shards across the mesh, per-chip Pippenger bucket
        scans reduce in one all-gather of group partials, and the Miller
        plane shards by message (make_sharded_multi_verify_msm)."""
        bm, bk = pk_inf.shape
        with self._stage("host_prep", op="sharded_msm_plan", items=bm * bk):
            g1_stack, g2_stack, g1_p0, g2_p0 = sharded_msm_plans(
                r_lo, r_hi, pk_inf, sig_inf, mesh.device_count
            )
        fn = sharded_multi_verify_msm(
            mesh.mesh,
            g1_windows=g1_p0.windows, g1_wbits=g1_p0.window_bits,
            g2_windows=g2_p0.windows, g2_wbits=g2_p0.window_bits,
            axis=mesh.axis,
            check_subgroup=int(self.fuse_subgroup),
        )
        plan = mesh.batch_sharding()
        args = self._upload_sharded(
            (pk_x, pk_y, pk_inf, sig_x, sig_y, sig_inf,
             msg_x, msg_y, msg_inf, *g1_stack, *g2_stack),
            (mesh.member_sharding(),) * 6 + (mesh.replicated(),) * 3
            + (plan,) * (len(g1_stack) + len(g2_stack)),
            kernel="sharded_multi_verify_msm",
        )
        result = self._run_kernel(
            "sharded_multi_verify_msm", fn, args, sigs=n_real, block=False,
            mesh_operands=True,
        )
        return lambda: self._settle("sharded_multi_verify_msm", result)

    def verify(
        self,
        message: bytes,
        signature: "A.Signature",
        public_key: "A.PublicKey",
        dst: bytes = constants.DST_SIGNATURE,
    ) -> bool:
        return self.multi_verify([message], [signature], [public_key], dst)

    def fast_aggregate_verify_batch(
        self,
        messages: Sequence[bytes],
        signatures: Sequence["A.Signature"],
        member_keys: Sequence[Sequence["A.PublicKey"]],
        dst: bytes = constants.DST_SIGNATURE,
        rng=secrets,
    ) -> bool:
        """M aggregates, each over its own committee (the gossip firehose)."""
        return self.fast_aggregate_verify_batch_async(
            messages, signatures, member_keys, dst, rng
        )()

    def fast_aggregate_verify_batch_async(
        self,
        messages: Sequence[bytes],
        signatures: Sequence["A.Signature"],
        member_keys: Sequence[Sequence["A.PublicKey"]],
        dst: bytes = constants.DST_SIGNATURE,
        rng=secrets,
    ):
        """Async firehose verify: host prep + dispatch now, a zero-arg
        settle callable forces the device result. This is the seam the
        pipelined AttestationVerifier uses to overlap batch N+1's host
        prep with batch N's device execute."""
        m = len(messages)
        if not (m == len(signatures) == len(member_keys)):
            return lambda: False
        if m == 0:
            return lambda: True
        if any(not ks for ks in member_keys):
            return lambda: False
        if m > MAX_BUCKET:
            # Two-deep chunk pipeline, same shape as multi_verify_async:
            # settle() dispatches chunk k+1 before forcing chunk k.
            def chunk(i):
                return self.fast_aggregate_verify_batch_async(
                    messages[i : i + MAX_BUCKET],
                    signatures[i : i + MAX_BUCKET],
                    member_keys[i : i + MAX_BUCKET],
                    dst,
                    rng,
                )

            first = chunk(0)

            def settle_chunks() -> bool:
                pending = first
                for i in range(MAX_BUCKET, m, MAX_BUCKET):
                    nxt = chunk(i)
                    if not pending():
                        return False
                    pending = nxt
                return pending()

            return settle_chunks
        if any(pk.point.is_infinity() for ks in member_keys for pk in ks):
            return lambda: False
        with self._stage("host_prep", op="pack_aggregate", items=m):
            if max(len(ks) for ks in member_keys) > MAX_BUCKET:
                # committee wider than a device bucket: host-aggregate those
                # committees to a single key (same check: e(agg_pk, H(m)))
                member_keys = [
                    ks if len(ks) <= MAX_BUCKET else [A.PublicKey.aggregate(ks)]
                    for ks in member_keys
                ]
            bm = _bucket(m)
            bk = _bucket(max(len(ks) for ks in member_keys), lo=4)
            mem_x = np.zeros((bm, bk, L.NLIMBS), np.int32)
            mem_y = np.zeros((bm, bk, L.NLIMBS), np.int32)
            mem_inf = np.ones((bm, bk), bool)
            slot_pad = np.arange(bm) >= m
            sig_x = np.zeros((bm, 2, L.NLIMBS), np.int32)
            sig_y = np.zeros((bm, 2, L.NLIMBS), np.int32)
            sig_inf = np.ones((bm,), bool)
            msg_x = np.zeros((bm, 2, L.NLIMBS), np.int32)
            msg_y = np.zeros((bm, 2, L.NLIMBS), np.int32)
            msg_inf = np.ones((bm,), bool)
            flat_keys = [pk.point for ks in member_keys for pk in ks]
            fx, fy, finf = C.g1_points_to_dev(flat_keys)
            pos = 0
            for i in range(m):
                k = len(member_keys[i])
                mem_x[i, :k] = fx[pos : pos + k]
                mem_y[i, :k] = fy[pos : pos + k]
                mem_inf[i, :k] = finf[pos : pos + k]
                pos += k
            g2x, g2y, g2inf = C.g2_points_to_dev([s.point for s in signatures])
            sig_x[:m], sig_y[:m], sig_inf[:m] = g2x, g2y, g2inf
            for i in range(m):
                x, y, inf = self._hash_to_g2_dev(messages[i], dst)
                msg_x[i], msg_y[i], msg_inf[i] = x, y, inf
            pairs = [self._rlc_pair(rng) for _ in range(m)]
            r_bits = rlc_bits_host(pairs, bm)
            g2_plan = self._g2_plan(pairs, bm, sig_inf)
        args = self._upload((
            mem_x, mem_y, mem_inf, slot_pad, sig_x, sig_y, sig_inf,
            msg_x, msg_y, msg_inf, r_bits, *g2_plan.arrays,
        ), kernel="agg_fast_verify_msm")
        fn = self._jitted_msm(
            "agg_fast_verify_msm", aggregate_fast_verify_msm_kernel,
            donate=self._donate(len(args)),
            g2_windows=g2_plan.windows, g2_wbits=g2_plan.window_bits,
            check_subgroup=int(self.fuse_subgroup),
        )
        out = self._run_kernel(
            "agg_fast_verify_msm", fn, args, sigs=m, block=False
        )
        return lambda: self._settle("agg_fast_verify_msm", out)

    def fast_aggregate_verify_batch_indexed(
        self,
        messages: Sequence[bytes],
        signatures: Sequence["A.Signature"],
        member_indices: Sequence[Sequence[int]],
        registry,
        dst: bytes = constants.DST_SIGNATURE,
        rng=secrets,
    ) -> bool:
        return self.fast_aggregate_verify_batch_indexed_async(
            messages, signatures, member_indices, registry, dst, rng
        )()

    def fast_aggregate_verify_batch_indexed_async(
        self,
        messages: Sequence[bytes],
        signatures: Sequence["A.Signature"],
        member_indices: Sequence[Sequence[int]],
        registry,
        dst: bytes = constants.DST_SIGNATURE,
        rng=secrets,
    ):
        """Registry firehose verify: committee pubkeys stay device-resident
        (tpu/registry.py), gathered on-device by validator index — the
        per-batch upload shrinks from 208 B/member of affine coordinates to
        4 B/member of int32 indices. Registry rows never hold the identity
        (decompress raises), so the infinity policy reduces to the padding
        mask. A committee wider than a device bucket falls back to the
        upload path through the registry's host mirror; an index the
        registry does not cover (cold registry, out-of-range) is a
        verification failure — it names a validator outside the set the
        caller synced the registry to."""
        m = len(messages)
        if not (m == len(signatures) == len(member_indices)):
            return lambda: False
        if m == 0:
            return lambda: True
        if any(len(ix) == 0 for ix in member_indices):
            return lambda: False
        if m > MAX_BUCKET:
            def chunk(i):
                return self.fast_aggregate_verify_batch_indexed_async(
                    messages[i : i + MAX_BUCKET],
                    signatures[i : i + MAX_BUCKET],
                    member_indices[i : i + MAX_BUCKET],
                    registry,
                    dst,
                    rng,
                )

            first = chunk(0)

            def settle_chunks() -> bool:
                pending = first
                for i in range(MAX_BUCKET, m, MAX_BUCKET):
                    nxt = chunk(i)
                    if not pending():
                        return False
                    pending = nxt
                return pending()

            return settle_chunks
        reg_x, reg_y, reg_n = registry.arrays()
        widest = max(len(ix) for ix in member_indices)
        if reg_x is None or any(
            not 0 <= int(i) < reg_n for ix in member_indices for i in ix
        ):
            # an index the registry has never seen names a validator
            # outside the head state's set — the signature cannot verify
            return lambda: False
        if widest > MAX_BUCKET:
            # committee wider than a device bucket: resolve through the
            # host mirror and take the upload path (which host-aggregates
            # oversized committees to a single key)
            return self.fast_aggregate_verify_batch_async(
                messages,
                signatures,
                [registry.public_keys(ix) for ix in member_indices],
                dst,
                rng,
            )
        with self._stage("host_prep", op="pack_aggregate_idx", items=m):
            bm = _bucket(m)
            bk = _bucket(widest, lo=4)
            mem_idx = np.zeros((bm, bk), np.int32)
            mem_inf = np.ones((bm, bk), bool)  # True = padding slot
            slot_pad = np.arange(bm) >= m
            sig_x = np.zeros((bm, 2, L.NLIMBS), np.int32)
            sig_y = np.zeros((bm, 2, L.NLIMBS), np.int32)
            sig_inf = np.ones((bm,), bool)
            msg_x = np.zeros((bm, 2, L.NLIMBS), np.int32)
            msg_y = np.zeros((bm, 2, L.NLIMBS), np.int32)
            msg_inf = np.ones((bm,), bool)
            for i, ix in enumerate(member_indices):
                k = len(ix)
                mem_idx[i, :k] = np.fromiter(
                    (int(v) for v in ix), np.int32, count=k
                )
                mem_inf[i, :k] = False
            g2x, g2y, g2inf = C.g2_points_to_dev([s.point for s in signatures])
            sig_x[:m], sig_y[:m], sig_inf[:m] = g2x, g2y, g2inf
            for i in range(m):
                x, y, inf = self._hash_to_g2_dev(messages[i], dst)
                msg_x[i], msg_y[i], msg_inf[i] = x, y, inf
            pairs = [self._rlc_pair(rng) for _ in range(m)]
            r_bits = rlc_bits_host(pairs, bm)
            g2_plan = self._g2_plan(pairs, bm, sig_inf)
        # registry arrays are already device-resident: they are passed to
        # the kernel directly, NOT through _upload, so the per-batch
        # upload accounting stays honest (check_no_per_batch_upload.py)
        args = self._upload((
            mem_idx, mem_inf, slot_pad, sig_x, sig_y, sig_inf,
            msg_x, msg_y, msg_inf, r_bits, *g2_plan.arrays,
        ), kernel="agg_fast_verify_msm_idx")
        # donation skips the two registry operands — they outlive the batch
        fn = self._jitted_msm(
            "agg_fast_verify_msm_idx", aggregate_fast_verify_msm_idx_kernel,
            donate=self._donate(len(args), skip=2),
            g2_windows=g2_plan.windows, g2_wbits=g2_plan.window_bits,
            check_subgroup=int(self.fuse_subgroup),
        )
        out = self._run_kernel(
            "agg_fast_verify_msm_idx", fn, (reg_x, reg_y, *args),
            sigs=m, block=False, mesh_operands=True,
        )
        return lambda: self._settle("agg_fast_verify_msm_idx", out)

    # -- compressed-ingest verification ------------------------------------
    #
    # The *_compressed_async trio takes SIGNATURES AS RAW WIRE BYTES
    # (48/96-byte compressed encodings) and decompresses them on device
    # inside the verify kernel itself, replacing the per-item pure-Python
    # Fq2.sqrt host stage (`host_prep op=g2_decompress` in tpu/schemes.py)
    # that made the plane prep-bound. The host twin path is retained
    # verbatim as the anchor and degradation target.

    @staticmethod
    def _pack_sig_rows(signatures, b: int):
        """(b, 96) uint8 padded compressed signature rows + the host-side
        sig_inf mask (padding ∪ wire infinity flag). Padding rows carry
        the canonical infinity encoding (0xC0 ‖ 0⁹⁵) so they decompress
        as valid neutral slots; malformed payloads are NOT screened here —
        per-row rejection is the device kernel's job. Raises ValueError
        on a wrong-length blob (the one structural property bytes can't
        defer)."""
        rows = C.compressed_rows(signatures, 96)
        n = rows.shape[0]
        sig_rows = np.zeros((b, 96), np.uint8)
        sig_rows[:, 0] = C.COMPRESSED_FLAG | C.INFINITY_FLAG
        sig_rows[:n] = rows
        sig_inf = np.ones((b,), bool)
        sig_inf[:n] = C.compressed_infinity_flags(rows)
        return sig_rows, sig_inf

    def multi_verify_compressed(
        self,
        messages: Sequence[bytes],
        signatures: Sequence[bytes],
        public_keys: Sequence["A.PublicKey"],
        dst: bytes = constants.DST_SIGNATURE,
        rng=secrets,
    ) -> bool:
        return self.multi_verify_compressed_async(
            messages, signatures, public_keys, dst, rng
        )()

    def multi_verify_compressed_async(
        self,
        messages: Sequence[bytes],
        signatures: Sequence[bytes],
        public_keys: Sequence["A.PublicKey"],
        dst: bytes = constants.DST_SIGNATURE,
        rng=secrets,
    ):
        """multi_verify_async with signatures as compressed wire bytes:
        host prep shrinks to a memcpy row-pack (no Fq2.sqrt, no Montgomery
        lift), decompression + subgroup + pairing run as ONE device pass
        (multi_verify_msm_comp_kernel). Always takes the flat MSM path —
        grouping/sharding stay on the uncompressed twins."""
        n = len(messages)
        if not (n == len(signatures) == len(public_keys)):
            return lambda: False
        if n == 0:
            return lambda: True
        if n > MAX_BUCKET:
            def chunk(i):
                return self.multi_verify_compressed_async(
                    messages[i : i + MAX_BUCKET],
                    signatures[i : i + MAX_BUCKET],
                    public_keys[i : i + MAX_BUCKET],
                    dst,
                    rng,
                )

            first = chunk(0)

            def settle_chunks() -> bool:
                pending = first
                for i in range(MAX_BUCKET, n, MAX_BUCKET):
                    nxt = chunk(i)
                    if not pending():
                        return False
                    pending = nxt
                return pending()

            return settle_chunks
        if any(pk.point.is_infinity() for pk in public_keys):
            return lambda: False
        with self._stage("host_prep", op="pack_compressed", items=n):
            b = _bucket(n)
            try:
                sig_rows, sig_inf = self._pack_sig_rows(signatures, b)
            except ValueError:
                return lambda: False  # wrong-length blob
            g1x, g1y, g1inf = C.g1_points_to_dev(
                [pk.point for pk in public_keys]
            )
            pk_x = np.zeros((b, L.NLIMBS), np.int32)
            pk_y = np.zeros((b, L.NLIMBS), np.int32)
            pk_inf = np.ones((b,), bool)
            msg_x = np.zeros((b, 2, L.NLIMBS), np.int32)
            msg_y = np.zeros((b, 2, L.NLIMBS), np.int32)
            msg_inf = np.ones((b,), bool)
            pk_x[:n], pk_y[:n], pk_inf[:n] = g1x, g1y, g1inf
            for i in range(n):
                x, y, inf = self._hash_to_g2_dev(messages[i], dst)
                msg_x[i], msg_y[i], msg_inf[i] = x, y, inf
            pairs = [self._rlc_pair(rng) for _ in range(n)]
            r_bits = rlc_bits_host(pairs, b)
        with self._stage("host_prep", op="msm_plan", items=n):
            g2_plan = self._g2_plan(pairs, b, sig_inf)
        args = self._upload((
            pk_x, pk_y, pk_inf, sig_rows, sig_inf,
            msg_x, msg_y, msg_inf, r_bits, *g2_plan.arrays,
        ), kernel="multi_verify_msm_comp")
        # compressed ingest ALWAYS fuses the ψ-ladder subgroup check:
        # the decompressed points never exist on the host, so the
        # two-pass g2_subgroup_check_batch_async fallback cannot cover
        # them — check_subgroup is not optional here
        fn = self._jitted_msm(
            "multi_verify_msm_comp", multi_verify_msm_comp_kernel,
            donate=self._donate(len(args)),
            g2_windows=g2_plan.windows, g2_wbits=g2_plan.window_bits,
            check_subgroup=1,
        )
        result = self._run_kernel(
            "multi_verify_msm_comp", fn, args, sigs=n, block=False
        )
        return lambda: self._settle("multi_verify_msm_comp", result)

    def fast_aggregate_verify_batch_compressed(
        self,
        messages: Sequence[bytes],
        signatures: Sequence[bytes],
        member_keys: Sequence[Sequence["A.PublicKey"]],
        dst: bytes = constants.DST_SIGNATURE,
        rng=secrets,
    ) -> bool:
        return self.fast_aggregate_verify_batch_compressed_async(
            messages, signatures, member_keys, dst, rng
        )()

    def fast_aggregate_verify_batch_compressed_async(
        self,
        messages: Sequence[bytes],
        signatures: Sequence[bytes],
        member_keys: Sequence[Sequence["A.PublicKey"]],
        dst: bytes = constants.DST_SIGNATURE,
        rng=secrets,
    ):
        """fast_aggregate_verify_batch_async with signatures as compressed
        wire bytes — the gossip firehose's native format, decompressed on
        device in the verify pass (aggregate_fast_verify_msm_comp_kernel)."""
        m = len(messages)
        if not (m == len(signatures) == len(member_keys)):
            return lambda: False
        if m == 0:
            return lambda: True
        if any(not ks for ks in member_keys):
            return lambda: False
        if m > MAX_BUCKET:
            def chunk(i):
                return self.fast_aggregate_verify_batch_compressed_async(
                    messages[i : i + MAX_BUCKET],
                    signatures[i : i + MAX_BUCKET],
                    member_keys[i : i + MAX_BUCKET],
                    dst,
                    rng,
                )

            first = chunk(0)

            def settle_chunks() -> bool:
                pending = first
                for i in range(MAX_BUCKET, m, MAX_BUCKET):
                    nxt = chunk(i)
                    if not pending():
                        return False
                    pending = nxt
                return pending()

            return settle_chunks
        if any(pk.point.is_infinity() for ks in member_keys for pk in ks):
            return lambda: False
        with self._stage("host_prep", op="pack_aggregate_compressed", items=m):
            if max(len(ks) for ks in member_keys) > MAX_BUCKET:
                member_keys = [
                    ks if len(ks) <= MAX_BUCKET else [A.PublicKey.aggregate(ks)]
                    for ks in member_keys
                ]
            bm = _bucket(m)
            bk = _bucket(max(len(ks) for ks in member_keys), lo=4)
            try:
                sig_rows, sig_inf = self._pack_sig_rows(signatures, bm)
            except ValueError:
                return lambda: False
            mem_x = np.zeros((bm, bk, L.NLIMBS), np.int32)
            mem_y = np.zeros((bm, bk, L.NLIMBS), np.int32)
            mem_inf = np.ones((bm, bk), bool)
            slot_pad = np.arange(bm) >= m
            msg_x = np.zeros((bm, 2, L.NLIMBS), np.int32)
            msg_y = np.zeros((bm, 2, L.NLIMBS), np.int32)
            msg_inf = np.ones((bm,), bool)
            flat_keys = [pk.point for ks in member_keys for pk in ks]
            fx, fy, finf = C.g1_points_to_dev(flat_keys)
            pos = 0
            for i in range(m):
                k = len(member_keys[i])
                mem_x[i, :k] = fx[pos : pos + k]
                mem_y[i, :k] = fy[pos : pos + k]
                mem_inf[i, :k] = finf[pos : pos + k]
                pos += k
            for i in range(m):
                x, y, inf = self._hash_to_g2_dev(messages[i], dst)
                msg_x[i], msg_y[i], msg_inf[i] = x, y, inf
            pairs = [self._rlc_pair(rng) for _ in range(m)]
            r_bits = rlc_bits_host(pairs, bm)
            g2_plan = self._g2_plan(pairs, bm, sig_inf)
        args = self._upload((
            mem_x, mem_y, mem_inf, slot_pad, sig_rows, sig_inf,
            msg_x, msg_y, msg_inf, r_bits, *g2_plan.arrays,
        ), kernel="agg_fast_verify_msm_comp")
        # subgroup check always fused on compressed ingest (see
        # multi_verify_compressed_async)
        fn = self._jitted_msm(
            "agg_fast_verify_msm_comp", aggregate_fast_verify_msm_comp_kernel,
            donate=self._donate(len(args)),
            g2_windows=g2_plan.windows, g2_wbits=g2_plan.window_bits,
            check_subgroup=1,
        )
        out = self._run_kernel(
            "agg_fast_verify_msm_comp", fn, args, sigs=m, block=False
        )
        return lambda: self._settle("agg_fast_verify_msm_comp", out)

    def fast_aggregate_verify_batch_indexed_compressed(
        self,
        messages: Sequence[bytes],
        signatures: Sequence[bytes],
        member_indices: Sequence[Sequence[int]],
        registry,
        dst: bytes = constants.DST_SIGNATURE,
        rng=secrets,
    ) -> bool:
        return self.fast_aggregate_verify_batch_indexed_compressed_async(
            messages, signatures, member_indices, registry, dst, rng
        )()

    def fast_aggregate_verify_batch_indexed_compressed_async(
        self,
        messages: Sequence[bytes],
        signatures: Sequence[bytes],
        member_indices: Sequence[Sequence[int]],
        registry,
        dst: bytes = constants.DST_SIGNATURE,
        rng=secrets,
    ):
        """The fully-device-fed firehose: member pubkeys gathered from the
        resident registry by index AND signatures decompressed on device.
        Per-batch upload = 96 B/aggregate of wire bytes + 4 B/member of
        indices; host prep does no field arithmetic at all."""
        m = len(messages)
        if not (m == len(signatures) == len(member_indices)):
            return lambda: False
        if m == 0:
            return lambda: True
        if any(len(ix) == 0 for ix in member_indices):
            return lambda: False
        if m > MAX_BUCKET:
            def chunk(i):
                return self.fast_aggregate_verify_batch_indexed_compressed_async(
                    messages[i : i + MAX_BUCKET],
                    signatures[i : i + MAX_BUCKET],
                    member_indices[i : i + MAX_BUCKET],
                    registry,
                    dst,
                    rng,
                )

            first = chunk(0)

            def settle_chunks() -> bool:
                pending = first
                for i in range(MAX_BUCKET, m, MAX_BUCKET):
                    nxt = chunk(i)
                    if not pending():
                        return False
                    pending = nxt
                return pending()

            return settle_chunks
        reg_x, reg_y, reg_n = registry.arrays()
        widest = max(len(ix) for ix in member_indices)
        if reg_x is None or any(
            not 0 <= int(i) < reg_n for ix in member_indices for i in ix
        ):
            return lambda: False
        if widest > MAX_BUCKET:
            return self.fast_aggregate_verify_batch_compressed_async(
                messages,
                signatures,
                [registry.public_keys(ix) for ix in member_indices],
                dst,
                rng,
            )
        with self._stage(
            "host_prep", op="pack_aggregate_idx_compressed", items=m
        ):
            bm = _bucket(m)
            bk = _bucket(widest, lo=4)
            try:
                sig_rows, sig_inf = self._pack_sig_rows(signatures, bm)
            except ValueError:
                return lambda: False
            mem_idx = np.zeros((bm, bk), np.int32)
            mem_inf = np.ones((bm, bk), bool)  # True = padding slot
            slot_pad = np.arange(bm) >= m
            msg_x = np.zeros((bm, 2, L.NLIMBS), np.int32)
            msg_y = np.zeros((bm, 2, L.NLIMBS), np.int32)
            msg_inf = np.ones((bm,), bool)
            for i, ix in enumerate(member_indices):
                k = len(ix)
                mem_idx[i, :k] = np.fromiter(
                    (int(v) for v in ix), np.int32, count=k
                )
                mem_inf[i, :k] = False
            for i in range(m):
                x, y, inf = self._hash_to_g2_dev(messages[i], dst)
                msg_x[i], msg_y[i], msg_inf[i] = x, y, inf
            pairs = [self._rlc_pair(rng) for _ in range(m)]
            r_bits = rlc_bits_host(pairs, bm)
            g2_plan = self._g2_plan(pairs, bm, sig_inf)
        # registry arrays are device-resident: passed directly, NOT through
        # _upload, so per-batch upload accounting stays honest
        args = self._upload((
            mem_idx, mem_inf, slot_pad, sig_rows, sig_inf,
            msg_x, msg_y, msg_inf, r_bits, *g2_plan.arrays,
        ), kernel="agg_fast_verify_msm_idx_comp")
        # subgroup check always fused on compressed ingest (see
        # multi_verify_compressed_async)
        fn = self._jitted_msm(
            "agg_fast_verify_msm_idx_comp",
            aggregate_fast_verify_msm_idx_comp_kernel,
            donate=self._donate(len(args), skip=2),
            g2_windows=g2_plan.windows, g2_wbits=g2_plan.window_bits,
            check_subgroup=1,
        )
        out = self._run_kernel(
            "agg_fast_verify_msm_idx_comp", fn, (reg_x, reg_y, *args),
            sigs=m, block=False, mesh_operands=True,
        )
        return lambda: self._settle("agg_fast_verify_msm_idx_comp", out)

    def multi_verify_indexed(
        self,
        messages: Sequence[bytes],
        signatures: Sequence["A.Signature"],
        indices: Sequence[int],
        registry,
        dst: bytes = constants.DST_SIGNATURE,
        rng=secrets,
    ) -> bool:
        """Flat RLC batch verify with signer pubkeys gathered on-device
        from the registry by validator index (one signer per triple).
        Batches beyond one bucket fall back to the upload path through
        the host mirror; an index the registry does not cover fails."""
        n = len(messages)
        if not (n == len(signatures) == len(indices)):
            return False
        if n == 0:
            return True
        reg_x, reg_y, reg_n = registry.arrays()
        if reg_x is None or any(not 0 <= int(i) < reg_n for i in indices):
            return False  # unknown validator index → cannot verify
        if n > MAX_BUCKET:
            return self.multi_verify(
                messages, signatures, registry.public_keys(indices), dst, rng
            )
        with self._stage("host_prep", op="pack_idx", items=n):
            b = _bucket(n)
            pk_idx = np.zeros((b,), np.int32)
            pk_inf = np.ones((b,), bool)  # True = padding slot
            pk_idx[:n] = np.fromiter(
                (int(v) for v in indices), np.int32, count=n
            )
            pk_inf[:n] = False
            sig_x = np.zeros((b, 2, L.NLIMBS), np.int32)
            sig_y = np.zeros((b, 2, L.NLIMBS), np.int32)
            sig_inf = np.ones((b,), bool)
            msg_x = np.zeros((b, 2, L.NLIMBS), np.int32)
            msg_y = np.zeros((b, 2, L.NLIMBS), np.int32)
            msg_inf = np.ones((b,), bool)
            g2x, g2y, g2inf = C.g2_points_to_dev([s.point for s in signatures])
            sig_x[:n], sig_y[:n], sig_inf[:n] = g2x, g2y, g2inf
            for i in range(n):
                x, y, inf = self._hash_to_g2_dev(messages[i], dst)
                msg_x[i], msg_y[i], msg_inf[i] = x, y, inf
            pairs = [self._rlc_pair(rng) for _ in range(n)]
            r_bits = rlc_bits_host(pairs, b)
            g2_plan = self._g2_plan(pairs, b, sig_inf)
        args = self._upload((
            pk_idx, pk_inf, sig_x, sig_y, sig_inf, msg_x, msg_y, msg_inf,
            r_bits, *g2_plan.arrays,
        ), kernel="multi_verify_msm_idx")
        fn = self._jitted_msm(
            "multi_verify_msm_idx", multi_verify_msm_idx_kernel,
            donate=self._donate(len(args), skip=2),
            g2_windows=g2_plan.windows, g2_wbits=g2_plan.window_bits,
            check_subgroup=int(self.fuse_subgroup),
        )
        result = self._run_kernel(
            "multi_verify_msm_idx", fn, (reg_x, reg_y, *args),
            sigs=n, block=False, mesh_operands=True,
        )
        return self._settle("multi_verify_msm_idx", result)

    def fast_aggregate_verify(
        self,
        message: bytes,
        signature: "A.Signature",
        public_keys: Sequence["A.PublicKey"],
        dst: bytes = constants.DST_SIGNATURE,
    ) -> bool:
        return self.fast_aggregate_verify_batch(
            [message], [signature], [public_keys], dst
        )

    def g2_subgroup_check_batch(self, points) -> "np.ndarray":
        """Batched subgroup membership for decompressed (on-curve) G2
        points — ONE device ladder replaces N host scalar-muls. Accepts
        anchor `Point[Fq2]` values; returns an (N,) bool array (infinity
        rows True; reject them separately by policy)."""
        return self.g2_subgroup_check_batch_async(points)()

    def g2_subgroup_check_batch_async(self, points):
        """Async variant of g2_subgroup_check_batch: dispatch now, force
        via the returned zero-arg callable. The pipelined verifier stacks
        this dispatch with the verify-kernel dispatch so both device runs
        queue back-to-back ahead of any host readback."""
        n = len(points)
        if n == 0:
            return lambda: np.zeros((0,), bool)
        with self._stage("host_prep", op="pack_subgroup", items=n):
            bn = _bucket(n)
            sx = np.zeros((bn, 2, L.NLIMBS), np.int32)
            sy = np.zeros((bn, 2, L.NLIMBS), np.int32)
            s_inf = np.ones((bn,), bool)
            gx, gy, ginf = C.g2_points_to_dev(points)
            sx[:n], sy[:n], s_inf[:n] = gx, gy, ginf
            x_bits = np.ascontiguousarray(
                C.scalars_to_bits_msb([_ABS_X] * bn, 64).T
            )
        args = self._upload((sx, sy, s_inf, x_bits), kernel="g2_subgroup_check")
        fn = self._jitted(
            "g2_subgroup_check", g2_subgroup_check_kernel,
            donate=self._donate(len(args)),
        )
        dev_out = self._run_kernel(
            "g2_subgroup_check", fn, args, sigs=n, block=False
        )

        def settle() -> "np.ndarray":
            if not self._observed():
                return np.asarray(dev_out)[:n]
            with self._stage("execute", kernel="g2_subgroup_check"):
                self._block(dev_out)
            with self._stage("readback", kernel="g2_subgroup_check"):
                return np.asarray(dev_out)[:n]

        return settle

    # -- fault localization ------------------------------------------------

    def rlc_partition_verify(
        self,
        messages: Sequence[bytes],
        signatures: Sequence["A.Signature"],
        member_keys: Sequence[Sequence["A.PublicKey"]],
        groups: int,
        dst: bytes = constants.DST_SIGNATURE,
        rng=secrets,
    ) -> "np.ndarray":
        return self.rlc_partition_verify_async(
            messages, signatures, member_keys, groups, dst, rng
        )()

    def rlc_partition_verify_async(
        self,
        messages: Sequence[bytes],
        signatures: Sequence["A.Signature"],
        member_keys: Sequence[Sequence["A.PublicKey"]],
        groups: int,
        dst: bytes = constants.DST_SIGNATURE,
        rng=secrets,
    ):
        """Per-sub-batch verdicts for fault localization: the batch's
        bucket splits into `groups` contiguous groups and ONE device pass
        (rlc_partition_verify_kernel) reports a bool per group — the seam
        runtime/isolation.py descends through after a failed batch, so
        the host never single-verifies more than the named-bad leaves.
        Items keep the firehose shape (one signature over an aggregate of
        member keys); committees collapse to one key by host aggregation
        (only paid on already-failed batches). Items with no keys or an
        identity key are named bad on the host and their slots stay
        padding, so they cannot poison their group's device verdict.
        Returns a zero-arg settle producing a (groups,) bool array
        (padding-only groups True)."""
        n = len(messages)
        g = _bucket(groups, lo=4)
        if not (n and n == len(signatures) == len(member_keys)):
            return lambda: np.zeros((0,), bool)
        b = _bucket(n)
        if g > b:
            g = b
        with self._stage("host_prep", op="pack_partition", items=n):
            bad_host = np.zeros((b,), bool)
            agg_pts = []
            slots = []
            for i, ks in enumerate(member_keys):
                if not ks or any(pk.point.is_infinity() for pk in ks):
                    bad_host[i] = True
                    continue
                key = ks[0] if len(ks) == 1 else A.PublicKey.aggregate(ks)
                agg_pts.append(key.point)
                slots.append(i)
            pk_x = np.zeros((b, L.NLIMBS), np.int32)
            pk_y = np.zeros((b, L.NLIMBS), np.int32)
            pk_inf = np.ones((b,), bool)
            sig_x = np.zeros((b, 2, L.NLIMBS), np.int32)
            sig_y = np.zeros((b, 2, L.NLIMBS), np.int32)
            sig_inf = np.ones((b,), bool)
            msg_x = np.zeros((b, 2, L.NLIMBS), np.int32)
            msg_y = np.zeros((b, 2, L.NLIMBS), np.int32)
            msg_inf = np.ones((b,), bool)
            if agg_pts:
                g1x, g1y, g1inf = C.g1_points_to_dev(agg_pts)
                g2x, g2y, g2inf = C.g2_points_to_dev(
                    [signatures[i].point for i in slots]
                )
                pk_x[slots], pk_y[slots], pk_inf[slots] = g1x, g1y, g1inf
                sig_x[slots], sig_y[slots], sig_inf[slots] = g2x, g2y, g2inf
                for i in slots:
                    x, y, inf = self._hash_to_g2_dev(messages[i], dst)
                    msg_x[i], msg_y[i], msg_inf[i] = x, y, inf
            pairs = [self._rlc_pair(rng) for _ in range(n)]
            r_bits = rlc_bits_host(pairs, b)
            group_tag = np.zeros((g,), np.int32)
        args = self._upload((
            pk_x, pk_y, pk_inf, sig_x, sig_y, sig_inf,
            msg_x, msg_y, msg_inf, r_bits, group_tag,
        ), kernel="rlc_partition")
        fn = self._jitted_msm(
            "rlc_partition", rlc_partition_verify_kernel,
            donate=self._donate(len(args)),
            check_subgroup=int(self.fuse_subgroup),
        )
        dev_out = self._run_kernel(
            "rlc_partition", fn, args, sigs=n, block=False
        )
        span = b // g

        def settle() -> "np.ndarray":
            if self._observed():
                with self._stage("execute", kernel="rlc_partition"):
                    self._block(dev_out)
            verdicts = np.array(np.asarray(dev_out), bool)
            for i in np.nonzero(bad_host)[0]:
                verdicts[i // span] = False
            return verdicts

        return settle

    # -- signing -----------------------------------------------------------

    def batch_sign(
        self,
        messages: Sequence[bytes],
        secret_keys: Sequence["A.SecretKey"],
        dst: bytes = constants.DST_SIGNATURE,
    ) -> "list[A.Signature]":
        """N signatures on device (signer/src/signer.rs:173-229 equivalent)."""
        n = len(messages)
        assert n == len(secret_keys)
        if n == 0:
            return []
        if n > MAX_BUCKET:
            out: list = []
            for i in range(0, n, MAX_BUCKET):
                out.extend(
                    self.batch_sign(
                        messages[i : i + MAX_BUCKET],
                        secret_keys[i : i + MAX_BUCKET],
                        dst,
                    )
                )
            return out
        with self._stage("host_prep", op="pack_sign", items=n):
            b = _bucket(n)
            msg_x = np.zeros((b, 2, L.NLIMBS), np.int32)
            msg_y = np.zeros((b, 2, L.NLIMBS), np.int32)
            msg_inf = np.ones((b,), bool)
            for i in range(n):
                x, y, inf = self._hash_to_g2_dev(messages[i], dst)
                msg_x[i], msg_y[i], msg_inf[i] = x, y, inf
            sk_bits, sk_neg = sign_bits_host(
                [sk.scalar for sk in secret_keys], b
            )
        fn = self._jitted("batch_sign", batch_sign_kernel)
        args = self._upload(
            (msg_x, msg_y, msg_inf, sk_bits, sk_neg), kernel="batch_sign"
        )
        X, Y, Z = self._run_kernel("batch_sign", fn, args, sigs=n)
        with self._stage("readback", kernel="batch_sign"):
            X, Y, Z = np.asarray(X), np.asarray(Y), np.asarray(Z)
        return [A.Signature(C.dev_to_g2_point(X[i], Y[i], Z[i])) for i in range(n)]

    @staticmethod
    def _rlc_pair(rng) -> "tuple[int, int]":
        """A nonzero (r0, r1) 32-bit RLC pair (see rlc_bits_host)."""
        a, b = 0, 0
        while a == 0 and b == 0:
            a, b = rng.randbits(32), rng.randbits(32)
        return a, b


__all__ = [
    "TpuBlsBackend",
    "rlc_bits_host",
    "sign_bits_host",
    "pick_msm_window",
    "msm_tune_path",
    "load_msm_tuning",
    "set_msm_tuning",
    "multi_verify_kernel",
    "rlc_partition_verify_kernel",
    "multi_verify_msm_kernel",
    "multi_verify_msm_idx_kernel",
    "multi_verify_msm_comp_kernel",
    "aggregate_fast_verify_msm_comp_kernel",
    "aggregate_fast_verify_msm_idx_comp_kernel",
    "g1_decompress_kernel",
    "g1_decompress_rows",
    "g2_aggregate_kernel",
    "g1_aggregate_kernel",
    "g2_aggregate_groups",
    "g1_aggregate_groups",
    "grouped_multi_verify_kernel",
    "grouped_multi_verify_msm_kernel",
    "grouped_multi_verify_msm_packed_kernel",
    "aggregate_fast_verify_kernel",
    "aggregate_fast_verify_msm_kernel",
    "aggregate_fast_verify_msm_idx_kernel",
    "batch_sign_kernel",
    "batch_pubkey_kernel",
    "g1_normalize_kernel",
    "g2_normalize_kernel",
    "make_sharded_multi_verify",
    "make_sharded_multi_verify_msm",
    "sharded_multi_verify",
    "sharded_multi_verify_msm",
    "sharded_msm_plans",
    "note_dispatch_shapes",
    "declare_warmup_complete",
    "warmup_declared",
    "post_warmup_recompiles",
    "reset_shape_tracking",
]
