"""Batched Jacobian curve arithmetic on device, generic over the coordinate
field (G1 over Fp, G2 over Fp2 on the twist), in limb-list form.

Conventions:
  - A point is a tuple (X, Y, Z) of field elements (limb-list pytrees);
    Z = 0 ⇒ infinity.
  - Every formula groups its independent field multiplications into
    `mul_many` calls over element LISTS (one fused Montgomery product each —
    see field.py on why this is about graph size, not lanes).
  - Branchless: degenerate cases are computed-and-selected, never branched.
    Doubling is complete for our curves (no 2-torsion: both cofactors are
    odd, so Y=0 never occurs on-curve and Z3=2YZ=0 only propagates infinity).
  - Scalar multiplication is MSB-first double-and-add with an affine base,
    which keeps every addition a mixed add and (for scalars < 2^255 < r)
    provably avoids the T = ±Q degeneracies mid-loop.

Differentially tested against grandine_tpu/crypto/curves.py.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from grandine_tpu.tpu import field as F
from grandine_tpu.tpu import limbs as L


def _fp_mul_many(aa, bb):
    """Multiply paired Fp lists elementwise, fused into one montmul."""
    # Interval worst case reaches ~23p (> 20p) through the G1 double's
    # t = E·(D - X3) chain, whose 3D - F form carries coefficient weight
    # ~19 over independent m·p/R terms; theorem (a) holds regardless
    # (see tools/ranges/bounds.txt).
    r = L.montmul(L.stack_fp(aa), L.stack_fp(bb))  # lint: disable=limb-range
    return L.unstack_fp(r, len(aa))


def _fp2_mul_many(aa, bb):
    return F.fp2_pair_products(list(zip(aa, bb)))


def _fp_one_like(a):
    return L.const_fp(L.ONE_MONT_DIGITS, a.shape[1:])


def _fp_zeros_like(a):
    return L.zeros_fp(a.shape[1:])


def _fp2_one_like(a):
    return F.fp2_one(a[0].shape[1:])


def _fp2_zeros_like(a):
    return F.fp2_zero(a[0].shape[1:])


@dataclass(frozen=True)
class FieldOps:
    """The field-op surface the curve formulas need."""

    mul_many: Callable  # ([elem], [elem]) -> [elem]
    add: Callable
    sub: Callable
    neg: Callable
    select: Callable  # (cond_bool_batch, a, b) -> a where cond else b
    is_zero: Callable  # elem -> bool batch
    is_zero_many: Callable  # [elem] -> [bool batch] (one canonical pass)
    zeros_like: Callable
    one_like: Callable
    index: Callable  # (elem, idx) -> elem (numpy-style batch index)
    concat: Callable  # ([elem], axis) -> elem
    batch_len: Callable  # elem -> size of the leading batch axis
    make_zero: Callable  # batch shape -> zero elem
    make_one: Callable  # batch shape -> Montgomery-one elem


def _fp_index(a, idx):
    return L.index_fp(a, idx)


def _fp_concat(elems, axis=0):
    return L.concat_fp(elems, axis=axis)


def _fp2_index(a, idx):
    return (L.index_fp(a[0], idx), L.index_fp(a[1], idx))


def _fp2_concat(elems, axis=0):
    return (
        L.concat_fp([e[0] for e in elems], axis=axis),
        L.concat_fp([e[1] for e in elems], axis=axis),
    )


FP_OPS = FieldOps(
    mul_many=_fp_mul_many,
    add=L.add_mod,
    sub=L.sub_mod,
    neg=L.neg_mod,
    select=L.select,
    is_zero=L.is_zero_val,
    is_zero_many=L.is_zero_val_many,
    zeros_like=_fp_zeros_like,
    one_like=_fp_one_like,
    index=_fp_index,
    concat=_fp_concat,
    batch_len=lambda e: e.shape[1],
    make_zero=lambda shape: L.zeros_fp(tuple(shape)),
    make_one=lambda shape: L.const_fp(L.ONE_MONT_DIGITS, tuple(shape)),
)

FP2_OPS = FieldOps(
    mul_many=_fp2_mul_many,
    add=F.fp2_add,
    sub=F.fp2_sub,
    neg=F.fp2_neg,
    select=F.fp2_select,
    is_zero=F.fp2_is_zero,
    is_zero_many=F.fp2_is_zero_many,
    zeros_like=_fp2_zeros_like,
    one_like=_fp2_one_like,
    index=_fp2_index,
    concat=_fp2_concat,
    batch_len=lambda e: e[0].shape[1],
    make_zero=lambda shape: F.fp2_zero(tuple(shape)),
    make_one=lambda shape: F.fp2_one(tuple(shape)),
)


def point_infinity_like(x, ops: FieldOps):
    one = ops.one_like(x)
    return (one, one, ops.zeros_like(x))


def point_double(p, ops: FieldOps):
    """dbl-2009-l (a=0): complete on our curves (see module docstring).
    Scheduled in THREE fused montmul levels (E = 3A is known after level 1,
    so F = E² joins C/T1 in level 2) — sequential montmul calls are the
    latency unit of every kernel built on these formulas."""
    X, Y, Z = p
    A, Bq, YZ = ops.mul_many([X, Y, Y], [X, Y, Z])
    XB = ops.add(X, Bq)
    E = ops.add(ops.add(A, A), A)
    C, T1, Fv = ops.mul_many([Bq, XB, E], [Bq, XB, E])
    D = ops.sub(T1, ops.add(A, C))
    D = ops.add(D, D)  # 2((X+B)² - A - C)
    X3 = ops.sub(Fv, ops.add(D, D))
    (t,) = ops.mul_many([E], [ops.sub(D, X3)])
    C2 = ops.add(C, C)
    C4 = ops.add(C2, C2)
    C8 = ops.add(C4, C4)
    Y3 = ops.sub(t, C8)
    Z3 = ops.add(YZ, YZ)
    return (X3, Y3, Z3)


def point_madd_unsafe(p, qx, qy, ops: FieldOps):
    """Mixed add P(jacobian) + Q(affine) assuming P ≠ ±Q and P, Q ≠ ∞
    (madd-2007-bl). Degeneracies must be selected away by the caller."""
    X, Y, Z = p
    (Z2,) = ops.mul_many([Z], [Z])
    U2, ZZZ = ops.mul_many([qx, Z], [Z2, Z2])
    H = ops.sub(U2, X)
    S2, HH = ops.mul_many([qy, H], [ZZZ, H])
    I = ops.add(HH, HH)
    I = ops.add(I, I)  # 4HH
    r = ops.sub(S2, Y)
    r = ops.add(r, r)
    J, V, R2 = ops.mul_many([H, X, r], [I, I, r])
    X3 = ops.sub(R2, ops.add(J, ops.add(V, V)))
    ZH = ops.add(Z, H)
    t, YJ, ZH2 = ops.mul_many(
        [r, Y, ZH], [ops.sub(V, X3), J, ZH]
    )
    Y3 = ops.sub(t, ops.add(YJ, YJ))
    Z3 = ops.sub(ZH2, ops.add(Z2, HH))
    return (X3, Y3, Z3)


def point_add_complete(p, q, ops: FieldOps):
    """Full Jacobian addition handling ∞, P=Q (→ double) and P=-Q (→ ∞),
    branchlessly (add-2007-bl + selects).

    Scheduled in FIVE fused montmul levels with the 2P fallback's products
    (dbl-2009-l on p) STACKED INTO the same calls — sequential montmul
    calls, not field products, are the latency unit of the MSM scan and
    every reduction tree, and the naive schedule (separate add + double,
    four separate zero tests) pays 11 calls plus 4 canonicalization scans
    where this pays 5 plus 1:
      L1  Z1², Z2², + double's A=X1², B=Y1², YZ=Y1·Z1
      L2  U1, U2, t1, t2, Z1·Z2 (Z3 = 2·Z1Z2·H replaces the
          (Z1+Z2)²-Z1Z1-Z2Z2 form, saving the level-6 square),
          + double's C=B², T1=(X1+B)², F=E²  (E = 3A)
      L3  S1, S2, I=(2H)², Z3=(2·Z1Z2)·H, + double's t=E·(D−X3d)
      L4  J=H·I, V=U1·I, r²
      L5  t=r·(V−X3), S1·J
    All four degeneracy tests (Z1, Z2, H, r zero) share one stacked
    canonicalization pass."""
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    Z1Z1, Z2Z2, dA, dB, dYZ = ops.mul_many(
        [Z1, Z2, X1, Y1, Y1], [Z1, Z2, X1, Y1, Z1]
    )
    dE = ops.add(ops.add(dA, dA), dA)
    dXB = ops.add(X1, dB)
    U1, U2, t1, t2, Z1Z2, dC, dT1, dF = ops.mul_many(
        [X1, X2, Z2, Z1, Z1, dB, dXB, dE],
        [Z2Z2, Z1Z1, Z2Z2, Z1Z1, Z2, dB, dXB, dE],
    )
    H = ops.sub(U2, U1)
    H2 = ops.add(H, H)
    ZZ2 = ops.add(Z1Z2, Z1Z2)
    dD = ops.sub(dT1, ops.add(dA, dC))
    dD = ops.add(dD, dD)
    dX3 = ops.sub(dF, ops.add(dD, dD))
    S1, S2, I, Z3, dt = ops.mul_many(
        [Y1, Y2, H2, ZZ2, dE],
        [t1, t2, H2, H, ops.sub(dD, dX3)],
    )
    r = ops.sub(S2, S1)
    r = ops.add(r, r)
    p_inf, q_inf, eq_x, eq_y = ops.is_zero_many([Z1, Z2, H, r])
    J, V, R2 = ops.mul_many([H, U1, r], [I, I, r])
    X3 = ops.sub(R2, ops.add(J, ops.add(V, V)))
    t, S1J = ops.mul_many([r, S1], [ops.sub(V, X3), J])
    Y3 = ops.sub(t, ops.add(S1J, S1J))
    dC2 = ops.add(dC, dC)
    dC4 = ops.add(dC2, dC2)
    dY3 = ops.sub(dt, ops.add(dC4, dC4))
    dbl = (dX3, dY3, ops.add(dYZ, dYZ))
    inf = point_infinity_like(X1, ops)

    def sel3(cond, a, b):
        return tuple(ops.select(cond, ai, bi) for ai, bi in zip(a, b))

    out = (X3, Y3, Z3)
    out = sel3(
        eq_x
        & jnp.logical_not(eq_y)
        & jnp.logical_not(p_inf)
        & jnp.logical_not(q_inf),
        inf,
        out,
    )
    out = sel3(eq_x & eq_y, dbl, out)
    out = sel3(q_inf, p, out)
    out = sel3(p_inf, q, out)
    return out


def scalar_mul(qx, qy, q_inf, bits_msb: jnp.ndarray, ops: FieldOps):
    """[k]Q for affine Q (batched), k given as an MSB-first bit array
    (nbits, *batch) int32. Returns a Jacobian point. Scalars must be < r
    (see module docstring for why mixed adds suffice)."""
    one = ops.one_like(qx)
    zero = ops.zeros_like(qx)
    started0 = jnp.zeros(bits_msb.shape[1:], bool)
    init = ((one, one, zero), started0)  # infinity, nothing accumulated yet

    def step(carry, bit):
        st, started = carry
        st = point_double(st, ops)
        added = point_madd_unsafe(st, qx, qy, ops)
        bitb = bit.astype(bool)
        # first set bit embeds Q (∞ + Q = Q); later ones use the mixed add
        X = ops.select(bitb, ops.select(started, added[0], qx), st[0])
        Y = ops.select(bitb, ops.select(started, added[1], qy), st[1])
        Z = ops.select(bitb, ops.select(started, added[2], one), st[2])
        return ((X, Y, Z), jnp.logical_or(started, bitb)), None

    (st, _), _ = lax.scan(step, init, bits_msb)
    # [k]∞ = ∞
    X = ops.select(q_inf, one, st[0])
    Y = ops.select(q_inf, one, st[1])
    Z = ops.select(q_inf, zero, st[2])
    return (X, Y, Z)


def scalar_mul_jac(q, q_inf, bits_msb: jnp.ndarray, ops: FieldOps):
    """[k]Q for a Jacobian (possibly adversarial) base Q, batched. Uses
    complete additions throughout, so no degeneracy preconditions: correct
    for any k (including 0) and any Q (including infinity). Costlier than
    `scalar_mul` (full add vs mixed add) — used where the base is an
    accumulated point that is not affine, e.g. r·(Σ pkᵢ) in the aggregate
    fast-verify kernel."""
    one = ops.one_like(q[0])
    zero = ops.zeros_like(q[0])
    # mask an infinite base to the (valid) representation (1, 1, 0)
    Q = (
        ops.select(q_inf, one, q[0]),
        ops.select(q_inf, one, q[1]),
        ops.select(q_inf, zero, q[2]),
    )
    init = (one, one, zero)  # infinity

    def step(st, bit):
        st = point_double(st, ops)
        added = point_add_complete(st, Q, ops)
        bitb = bit.astype(bool)
        st = tuple(ops.select(bitb, a, s) for a, s in zip(added, st))
        return st, None

    st, _ = lax.scan(step, init, bits_msb)
    X = ops.select(q_inf, one, st[0])
    Y = ops.select(q_inf, one, st[1])
    Z = ops.select(q_inf, zero, st[2])
    return (X, Y, Z)


def _roll_elem(e, shift):
    """Roll every component array of a field element by -shift along the
    leading batch axis (shift may be a traced scalar)."""
    return jax.tree.map(lambda x: jnp.roll(x, -shift, axis=1), e)


def _tree_reduce_points(p, levels: int, stride0: int, ops: FieldOps):
    """Pairwise reduction with a FIXED shape: `levels` iterations of
    y <- y + roll(y, -s), s = stride0, stride0/2, ..., so index 0 of each
    group accumulates its whole group sum. Tail positions compute garbage
    (valid field elements, wrong points) that the shrinking valid prefix
    never reads.

    Why not a classic halving tree: each halving level is a DIFFERENT shape,
    so XLA gets log2(N) copies of the complete-addition graph — measured
    minutes of compile time (and tens of GB of compiler RSS on CPU) for what
    this formulation compiles ONCE as a fori_loop body. The price is <=2x
    more point additions (every level runs at full width), cheap next to the
    montmul work it feeds.
    """
    if levels == 0:
        return p

    def body(_, carry):
        y, s = carry
        rolled = tuple(_roll_elem(e, s) for e in y)
        y = point_add_complete(y, rolled, ops)
        return (y, s // 2)

    y, _ = lax.fori_loop(0, levels, body, (p, jnp.int32(stride0)))
    return y


def sum_points(p, ops: FieldOps):
    """Reduce a batch of Jacobian points (leading batch axis on every limb
    array) to a single point. Batch must be a power of two (pad with
    infinity — the identity is neutral in complete addition)."""
    n = ops.batch_len(p[0])
    assert n & (n - 1) == 0, "sum_points requires a power-of-two batch"
    y = _tree_reduce_points(p, n.bit_length() - 1, n // 2, ops)
    return tuple(ops.index(e, 0) for e in y)


def sum_points_grouped(p, k: int, ops: FieldOps):
    """Reduce a k-major flat batch of M*K Jacobian points (index = j*M + m)
    to M group sums (returned as the flat prefix): pairs (j, m) with
    (j + K/2, m) each level. K must be a power of two (pad with infinity).
    This is the committee-aggregation kernel: M attestations x K member
    public keys -> M aggregate keys."""
    assert k & (k - 1) == 0, "sum_points_grouped requires power-of-two K"
    total = ops.batch_len(p[0])
    m = total // k
    y = _tree_reduce_points(p, k.bit_length() - 1, (k // 2) * m, ops)
    return tuple(ops.index(e, slice(0, m)) for e in y)


def sum_points_contiguous(p, s: int, ops: FieldOps):
    """Reduce a flat batch of N Jacobian points into N/s sums over
    CONTIGUOUS groups [0,s), [s,2s), ... (pad with infinity — neutral).
    s must be a power of two. Same masked-roll reduction as sum_points,
    but the level strides stop at group width: after strides s/2 ... 1,
    position g*s holds the sum of group g, read out with one strided
    slice. This is the fault-localization kernel's reducer: one device
    pass yields per-sub-batch signature aggregates for every group."""
    assert s & (s - 1) == 0, "sum_points_contiguous requires power-of-two s"
    total = ops.batch_len(p[0])
    if s <= 1:
        return p
    y = _tree_reduce_points(p, s.bit_length() - 1, s // 2, ops)
    return tuple(ops.index(e, slice(0, total, s)) for e in y)


def scalars_to_bits_msb(scalars, nbits: int) -> np.ndarray:
    """Host helper: int scalars → (len, nbits) int32 MSB-first bit array.
    Vectorized: ints → little-endian bytes → one unpackbits (the Python
    per-bit loop was the old prep bottleneck at firehose batch sizes)."""
    n = len(scalars)
    if n == 0:
        return np.zeros((0, nbits), dtype=np.int32)
    nb = (nbits + 7) // 8
    buf = bytearray(n * nb)
    for i, s in enumerate(scalars):
        s = int(s)
        assert 0 <= s < (1 << nbits)
        buf[i * nb : (i + 1) * nb] = s.to_bytes(nb, "little")
    raw = np.frombuffer(bytes(buf), np.uint8).reshape(n, nb)
    bits = np.unpackbits(raw, axis=1, bitorder="little")[:, :nbits]
    return np.ascontiguousarray(bits[:, ::-1]).astype(np.int32)


# --- host conversions ------------------------------------------------------
#
# Rest format: G1 affine (x (…, 26), y (…, 26), inf bool); G2 affine with
# (…, 2, 26) coords — identical to the array-form design, so the host prep
# pipeline (batched inversions + one unpackbits pass) is unchanged.


def g1_point_to_dev(pt) -> "tuple[np.ndarray, np.ndarray, np.ndarray]":
    """Anchor G1 Point (affine view) → device affine (x, y, inf_flag)."""
    aff = pt.to_affine()
    if aff is None:
        return L.ZERO.copy(), L.ZERO.copy(), np.array(True)
    return L.to_mont(aff[0].n), L.to_mont(aff[1].n), np.array(False)


def g2_point_to_dev(pt):
    aff = pt.to_affine()
    if aff is None:
        z = np.zeros((2, L.NLIMBS), np.int32)
        return z, z.copy(), np.array(True)
    return F.fq2_to_dev(aff[0]), F.fq2_to_dev(aff[1]), np.array(False)


def dev_to_g1_point(X, Y, Z):
    """Device Jacobian G1 (rest-format (26,) arrays) → anchor Point."""
    from grandine_tpu.crypto.curves import B1, Point, g1_infinity
    from grandine_tpu.crypto.fields import Fq

    x, y, z = (L.from_mont(np.asarray(c)) for c in (X, Y, Z))
    if z == 0:
        return g1_infinity()
    return Point(Fq(x), Fq(y), Fq(z), B1)


def dev_to_g2_point(X, Y, Z):
    from grandine_tpu.crypto.curves import B2, Point, g2_infinity

    zf = F.dev_to_fq2(np.asarray(Z))
    if zf.is_zero():
        return g2_infinity()
    return Point(F.dev_to_fq2(np.asarray(X)), F.dev_to_fq2(np.asarray(Y)), zf, B2)


# --- batched host conversions ----------------------------------------------
#
# The single-point converters above pay a Python field inversion per
# to_affine and a per-limb loop per coordinate; at firehose batch sizes the
# host prep dominated device time (VERDICT r1 weak #4). The batch variants
# do ONE Montgomery-trick inversion for all Z coordinates and ONE
# unpackbits pass for all limb decompositions.

from grandine_tpu.crypto.constants import P as _P  # noqa: E402


def ints_to_mont_limbs(values) -> np.ndarray:
    """[v_0, …] → (N, NLIMBS) int32 Montgomery digit arrays, vectorized."""
    n = len(values)
    if n == 0:
        return np.zeros((0, L.NLIMBS), np.int32)
    nb = (L.LIMB_BITS * L.NLIMBS + 7) // 8  # 49 bytes for 390 bits
    buf = bytearray(n * nb)
    r = L.R_MONT
    for i, v in enumerate(values):
        buf[i * nb : (i + 1) * nb] = (v * r % _P).to_bytes(nb, "little")
    raw = np.frombuffer(bytes(buf), np.uint8).reshape(n, nb)
    bits = np.unpackbits(raw, axis=1, bitorder="little")
    bits = bits[:, : L.NLIMBS * L.LIMB_BITS].reshape(n, L.NLIMBS, L.LIMB_BITS)
    weights = (1 << np.arange(L.LIMB_BITS, dtype=np.int64)).astype(np.int32)
    return (bits.astype(np.int32) * weights).sum(axis=2).astype(np.int32)


def _batch_inv_mod_p(values) -> "list[int]":
    """Montgomery batch inversion mod p; zeros map to zero."""
    from grandine_tpu.crypto.fields import batch_inverse

    return batch_inverse(values, _P)


def g1_points_to_dev(points):
    """Anchor G1 points (any Z) → ((N, L) x, (N, L) y, (N,) inf), with one
    batched inversion + one batched limb pass."""
    n = len(points)
    inf = np.zeros(n, dtype=bool)
    zs = []
    for i, pt in enumerate(points):
        z = pt.z.n
        if z == 0:
            inf[i] = True
        zs.append(z)
    zinv = _batch_inv_mod_p(zs)
    xs, ys = [], []
    for pt, zi in zip(points, zinv):
        if zi == 0:
            xs.append(0)
            ys.append(0)
        else:
            zi2 = zi * zi % _P
            xs.append(pt.x.n * zi2 % _P)
            ys.append(pt.y.n * zi2 % _P * zi % _P)
    limbs = ints_to_mont_limbs(xs + ys)
    return limbs[:n], limbs[n:], inf


def g2_points_to_dev(points):
    """Anchor G2 points → ((N, 2, L) x, (N, 2, L) y, (N,) inf)."""
    n = len(points)
    inf = np.zeros(n, dtype=bool)
    norms = []
    for i, pt in enumerate(points):
        z = pt.z
        if pt.is_infinity():
            inf[i] = True
            norms.append(0)
        else:
            norms.append((z.c0.n * z.c0.n + z.c1.n * z.c1.n) % _P)
    ninv = _batch_inv_mod_p(norms)
    # z⁻¹ = conj(z)·norm(z)⁻¹ in Fp[u]/(u²+1)
    coords = []  # x.c0, x.c1 then y.c0, y.c1 interleaved per point
    for pt, nv in zip(points, ninv):
        if nv == 0:
            coords.append((0, 0, 0, 0))
            continue
        z = pt.z
        zi0 = z.c0.n * nv % _P
        zi1 = (-z.c1.n) % _P * nv % _P
        # zi² and zi³ in Fq2
        zi2_0 = (zi0 * zi0 - zi1 * zi1) % _P
        zi2_1 = 2 * zi0 * zi1 % _P
        zi3_0 = (zi2_0 * zi0 - zi2_1 * zi1) % _P
        zi3_1 = (zi2_0 * zi1 + zi2_1 * zi0) % _P
        x0, x1 = pt.x.c0.n, pt.x.c1.n
        y0, y1 = pt.y.c0.n, pt.y.c1.n
        coords.append((
            (x0 * zi2_0 - x1 * zi2_1) % _P,
            (x0 * zi2_1 + x1 * zi2_0) % _P,
            (y0 * zi3_0 - y1 * zi3_1) % _P,
            (y0 * zi3_1 + y1 * zi3_0) % _P,
        ))
    flat = [c for quad in coords for c in quad]
    limbs = ints_to_mont_limbs(flat).reshape(n, 2, 2, L.NLIMBS)
    return limbs[:, 0], limbs[:, 1], inf


def g2_points_to_packed(points):
    """Anchor G2 points → ((N, 4, 13) uint32 packed canonical affine
    coords [x.c0, x.c1, y.c0, y.c1], (N,) inf). Half the bytes of the
    Montgomery limb REST format — for transfer-bound upload paths; the
    device unpacks (limbs.unpack_words + one montmul by R²)."""
    n = len(points)
    inf = np.zeros(n, dtype=bool)
    norms = []
    for i, pt in enumerate(points):
        if pt.is_infinity():
            inf[i] = True
            norms.append(0)
        else:
            z = pt.z
            norms.append((z.c0.n * z.c0.n + z.c1.n * z.c1.n) % _P)
    ninv = _batch_inv_mod_p(norms)
    coords = []
    for pt, nv in zip(points, ninv):
        if nv == 0:
            coords.extend((0, 0, 0, 0))
            continue
        z = pt.z
        zi0 = z.c0.n * nv % _P
        zi1 = (-z.c1.n) % _P * nv % _P
        zi2_0 = (zi0 * zi0 - zi1 * zi1) % _P
        zi2_1 = 2 * zi0 * zi1 % _P
        zi3_0 = (zi2_0 * zi0 - zi2_1 * zi1) % _P
        zi3_1 = (zi2_0 * zi1 + zi2_1 * zi0) % _P
        x0, x1 = pt.x.c0.n, pt.x.c1.n
        y0, y1 = pt.y.c0.n, pt.y.c1.n
        coords.extend((
            (x0 * zi2_0 - x1 * zi2_1) % _P,
            (x0 * zi2_1 + x1 * zi2_0) % _P,
            (y0 * zi3_0 - y1 * zi3_1) % _P,
            (y0 * zi3_1 + y1 * zi3_0) % _P,
        ))
    packed = L.pack_fp_words_host(coords).reshape(n, 4, L.NWORDS)
    return packed, inf


def scalar_mul_glv(
    qx, qy, q_inf, bits_lo, bits_hi, endo, ops: FieldOps,
    neg_lo=None, neg_hi=None,
):
    """[k]Q for affine Q (batched) with k = k0 + k1·LAMBDA given as TWO
    MSB-first bit arrays (nbits, *batch) — the dual-scalar GLV/ψ² ladder:
    half the doubles of the single 2·nbits ladder.

    `endo` = (cx, cy): field constants with (cx·x, cy·y) = [LAMBDA]·(x, y)
    (crypto/curves.py endo_constants — derived and asserted numerically).
    Optional neg_lo/neg_hi bool masks negate the respective slot's scalar
    (the base's y is negated), for signed GLV decompositions.

    Degeneracy safety (mixed adds): the accumulator is [a + b·LAMBDA]Q with
    partial a, b < 2¹²⁹; T = ±(slot base) requires (a∓1, b) or (a, b∓1) in
    the LAMBDA-lattice, whose nonzero vectors have a coordinate ≥ λ−1 ≈
    2¹²⁷·⁷ in absolute value in any combination reachable here — impossible
    for in-range partials except the handled first-set-bit embedding (same
    argument family as scalar_mul; LAMBDA structure in crypto/curves.py).
    """
    ex, ey = endo
    q2x, q2y = ops.mul_many([qx, qy], [ex, ey])
    if neg_lo is not None:
        qy = ops.select(neg_lo, ops.neg(qy), qy)
    if neg_hi is not None:
        q2y = ops.select(neg_hi, ops.neg(q2y), q2y)
    one = ops.one_like(qx)
    zero = ops.zeros_like(qx)
    started0 = jnp.zeros(bits_lo.shape[1:], bool)
    init = ((one, one, zero), started0)  # infinity, nothing accumulated yet

    def slot(st, started, bit, bx, by):
        added = point_madd_unsafe(st, bx, by, ops)
        bitb = bit.astype(bool)
        X = ops.select(bitb, ops.select(started, added[0], bx), st[0])
        Y = ops.select(bitb, ops.select(started, added[1], by), st[1])
        Z = ops.select(bitb, ops.select(started, added[2], one), st[2])
        return (X, Y, Z), jnp.logical_or(started, bitb)

    def step(carry, bits):
        st, started = carry
        b0, b1 = bits
        st = point_double(st, ops)
        st, started = slot(st, started, b0, qx, qy)
        st, started = slot(st, started, b1, q2x, q2y)
        return (st, started), None

    (st, _), _ = lax.scan(step, init, (bits_lo, bits_hi))
    X = ops.select(q_inf, one, st[0])
    Y = ops.select(q_inf, one, st[1])
    Z = ops.select(q_inf, zero, st[2])
    return (X, Y, Z)


def scalar_mul_jac_glv(q, q_inf, bits_lo, bits_hi, endo, ops: FieldOps):
    """GLV ladder for a Jacobian (possibly adversarial) base — complete
    additions throughout, so no degeneracy preconditions (the firehose
    kernel's aggregated-pubkey path)."""
    ex, ey = endo
    one = ops.one_like(q[0])
    zero = ops.zeros_like(q[0])
    Qx = ops.select(q_inf, one, q[0])
    Qy = ops.select(q_inf, one, q[1])
    Qz = ops.select(q_inf, zero, q[2])
    e2x, e2y = ops.mul_many([Qx, Qy], [ex, ey])
    init = (one, one, zero)  # infinity

    def step(st, bits):
        b0, b1 = bits
        st = point_double(st, ops)
        a1 = point_add_complete(st, (Qx, Qy, Qz), ops)
        st = tuple(ops.select(b0.astype(bool), a, s) for a, s in zip(a1, st))
        a2 = point_add_complete(st, (e2x, e2y, Qz), ops)
        st = tuple(ops.select(b1.astype(bool), a, s) for a, s in zip(a2, st))
        return st, None

    st, _ = lax.scan(step, init, (bits_lo, bits_hi))
    X = ops.select(q_inf, one, st[0])
    Y = ops.select(q_inf, one, st[1])
    Z = ops.select(q_inf, zero, st[2])
    return (X, Y, Z)


# --- batched on-device point decompression ---------------------------------
#
# Raw compressed rows (48-byte G1 / 96-byte G2, ZCash flag convention —
# crypto/bls.py g1_from_bytes / g2_from_bytes are the anchors) decode to
# affine Montgomery limbs entirely on device: big-endian bytes → canonical
# limbs, y² = x³ + b, batched fixed-exponent square root (field.fq_sqrt /
# fq2_sqrt), sign bit via the lexicographically-largest-y convention.
# Malformed rows NEVER fault the batch: every item carries a validity mask
# split into the three mandatory failure classes (non-canonical encoding,
# not-on-curve/non-residue, infinity-with-payload), and invalid rows decode
# to the zero point so downstream kernels can mask them as infinity slots.

#: byte-0 flag bits of the ZCash BLS12-381 serialization convention
COMPRESSED_FLAG = 0x80
INFINITY_FLAG = 0x40
SIGN_FLAG = 0x20

_B_MONT_DIGITS = [int(v) for v in L.to_mont(4)]  # b = 4 (G1), 4+4u (G2)
_ONE_DIGITS = [int(v) for v in L.int_to_limbs(1)]
#: canonical digits of (p+1)/2 — `y ≥ (p+1)/2` ⇔ `y > p − y` for y ∈ [0,p)
_P_HALF_UP_DIGITS = [int(v) for v in L.int_to_limbs((_P + 1) // 2)]
_KP_DIGITS = {
    k: [int(v) for v in L.int_to_limbs(k * _P)] for k in (1, 2, 4, 8)
}


def _geq_digits(a, digits) -> jnp.ndarray:
    """value(a) ≥ value(digits) for CANONICAL limb arrays (exact digit
    forms) — LSB→MSB sweep so the verdict is dominated by the top limb."""
    ge = jnp.ones(a.shape[1:], bool)
    for i in range(L.NLIMBS):
        d = int(digits[i])
        ge = jnp.where(a[i] > d, True, jnp.where(a[i] < d, False, ge))
    return ge


def _canonical_mod_p(a) -> jnp.ndarray:
    """Exact canonical digits of value(a) mod p, for |value(a)| < 8p:
    offset by +8p into [0, 16p), then a 4-step binary descent subtracting
    {8,4,2,1}·p wherever it fits. Needed where the VALUE itself must be
    compared (sign-bit convention), not just tested against 0 mod p."""
    w = L.canonical_digits(a + L.const_fp(L.EIGHT_P_DIGITS, a.shape[1:]))
    for k in (8, 4, 2, 1):
        kp = _KP_DIGITS[k]
        take = _geq_digits(w, kp)
        sub = w - L.const_fp(kp, a.shape[1:])
        w = L.canonical_digits(jnp.where(take[None], sub, w))
    return w


def _mont_to_canonical(a) -> jnp.ndarray:
    """Montgomery limbs → exact canonical digits of the value in [0, p)."""
    one = L.const_fp(_ONE_DIGITS, a.shape[1:])
    return _canonical_mod_p(L.montmul(a, one))


def _bytes_to_canonical(payload) -> jnp.ndarray:
    """(N, 48) uint8 big-endian payload (flags pre-masked) → (26, N)
    canonical limbs, via the packed-word unpack path (limbs.unpack_words
    wants little-endian uint32 words)."""
    le = payload[:, ::-1].astype(jnp.uint32)  # big-endian wire → LE bytes
    groups = le.reshape(le.shape[0], 12, 4)
    weights = jnp.asarray([1, 1 << 8, 1 << 16, 1 << 24], jnp.uint32)
    w = jnp.sum(groups * weights, axis=-1, dtype=jnp.uint32)
    w13 = jnp.concatenate(
        [w, jnp.zeros((w.shape[0], 1), jnp.uint32)], axis=-1
    )
    return L.unpack_words(w13)


def _decompress_flags(data):
    flags = data[:, 0]
    c_flag = (flags & COMPRESSED_FLAG) != 0
    i_flag = (flags & INFINITY_FLAG) != 0
    s_flag = (flags & SIGN_FLAG) != 0
    return c_flag, i_flag, s_flag


def g1_decompress_dev(data):
    """(N, 48) uint8 compressed G1 rows → (x, y, inf, ok, bad_encoding,
    bad_curve, bad_infinity); x/y are (26, N) Montgomery limbs (zeroed on
    invalid or infinity rows). Byte-identical accept/reject semantics to
    crypto/bls.py g1_from_bytes, but per-item: a malformed row flips its
    masks, never the batch."""
    data = jnp.asarray(data, jnp.uint8)
    c_flag, i_flag, s_flag = _decompress_flags(data)
    mask = jnp.concatenate([
        jnp.asarray([0x1F], jnp.uint8),
        jnp.full((47,), 0xFF, jnp.uint8),
    ])
    payload = data & mask[None]
    payload_zero = jnp.all(payload == 0, axis=-1)
    xc = _bytes_to_canonical(payload)
    x_lt_p = ~_geq_digits(xc, L.P_DIGITS)
    x = L.to_mont_dev(xc)
    b = L.const_fp(_B_MONT_DIGITS, x.shape[1:])
    y2 = L.add_mod(L.montmul(L.montsq(x), x), b)
    y, y_ok = F.fq_sqrt(y2)
    y_canon = _mont_to_canonical(y)
    y_larger = _geq_digits(y_canon, _P_HALF_UP_DIGITS)
    y = L.select(s_flag != y_larger, L.neg_mod(y), y)
    inf = c_flag & i_flag & ~s_flag & payload_zero
    bad_infinity = c_flag & i_flag & ~inf
    bad_encoding = ~c_flag | (c_flag & ~i_flag & ~x_lt_p)
    bad_curve = c_flag & ~i_flag & x_lt_p & ~y_ok
    ok = inf | (c_flag & ~i_flag & x_lt_p & y_ok)
    live = ok & ~inf
    x = L.select(live, x, L.zeros_fp(x.shape[1:]))
    y = L.select(live, y, L.zeros_fp(y.shape[1:]))
    return x, y, inf, ok, bad_encoding, bad_curve, bad_infinity


def g2_decompress_dev(data):
    """(N, 96) uint8 compressed G2 rows → (x, y, inf, ok, bad_encoding,
    bad_curve, bad_infinity); x/y are Fp2 pairs of (26, N) Montgomery
    limbs. Anchor: crypto/bls.py g2_from_bytes (c1 travels first on the
    wire; sign bit = lexicographically-largest-y over (c1, c0))."""
    data = jnp.asarray(data, jnp.uint8)
    c_flag, i_flag, s_flag = _decompress_flags(data)
    mask = jnp.concatenate([
        jnp.asarray([0x1F], jnp.uint8),
        jnp.full((95,), 0xFF, jnp.uint8),
    ])
    payload = data & mask[None]
    payload_zero = jnp.all(payload == 0, axis=-1)
    x1c = _bytes_to_canonical(payload[:, :48])
    x0c = _bytes_to_canonical(payload[:, 48:])
    lt_p = ~_geq_digits(x0c, L.P_DIGITS) & ~_geq_digits(x1c, L.P_DIGITS)
    x = (L.to_mont_dev(x0c), L.to_mont_dev(x1c))
    b2 = (
        L.const_fp(_B_MONT_DIGITS, x[0].shape[1:]),
        L.const_fp(_B_MONT_DIGITS, x[0].shape[1:]),
    )
    y2 = F.fp2_add(F.fp2_mul(F.fp2_sq(x), x), b2)
    y, y_ok = F.fq2_sqrt(y2)
    y0_canon = _mont_to_canonical(y[0])
    y1_canon = _mont_to_canonical(y[1])
    y_larger = _geq_digits(y1_canon, _P_HALF_UP_DIGITS) | (
        jnp.all(y1_canon == 0, axis=0)
        & _geq_digits(y0_canon, _P_HALF_UP_DIGITS)
    )
    y = F.fp2_select(s_flag != y_larger, F.fp2_neg(y), y)
    inf = c_flag & i_flag & ~s_flag & payload_zero
    bad_infinity = c_flag & i_flag & ~inf
    bad_encoding = ~c_flag | (c_flag & ~i_flag & ~lt_p)
    bad_curve = c_flag & ~i_flag & lt_p & ~y_ok
    ok = inf | (c_flag & ~i_flag & lt_p & y_ok)
    live = ok & ~inf
    zero2 = F.fp2_zero(x[0].shape[1:])
    x = F.fp2_select(live, x, zero2)
    y = F.fp2_select(live, y, zero2)
    return x, y, inf, ok, bad_encoding, bad_curve, bad_infinity


def compressed_rows(blobs, nbytes: int) -> np.ndarray:
    """List of `nbytes`-long byte strings → (N, nbytes) uint8 upload rows.
    No per-item bigint work — decoding happens on device. Length is the
    ONLY property checked on host (a wrong-size blob has no row shape)."""
    for blob in blobs:
        if len(blob) != nbytes:
            raise ValueError(
                f"compressed row must be {nbytes} bytes, got {len(blob)}"
            )
    if not blobs:
        return np.zeros((0, nbytes), np.uint8)
    return np.frombuffer(b"".join(blobs), np.uint8).reshape(
        len(blobs), nbytes
    )


def compressed_infinity_flags(rows: np.ndarray) -> np.ndarray:
    """(N, W) uint8 rows → (N,) bool infinity-flag bits (host-side, one
    vectorized byte test — the cheap prefilter MSM planning needs)."""
    return (rows[:, 0] & INFINITY_FLAG) != 0
