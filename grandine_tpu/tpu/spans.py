"""Device span-update kernel for the slasher's bulk-replay feed.

The slasher's chunked min/max target spans (slasher.py) take one range
update per attesting validator. The gossip path batches an aggregate's
updates with numpy; the bulk-replay feed is wider — thousands of
attesting indices per window, each with its own (source, target) — and
that merge is a pure elementwise min/max over a (validators × epochs)
grid, exactly the shape the accelerator wants.

`SpanPlane.update` merges one EPOCH-GRID window: for each row v with
attestation (s_v, t_v),

  new_min[v][e] = min(old_min[v][e], t_v if e < s_v else UNSET)
  new_max[v][e] = max(old_max[v][e], t_v if s_v < e <= t_v else 0)

over the fixed grid [base, base + SPAN_GRID_EPOCHS). The grid is the
chunk-aligned window covering the batch's source/target range; epochs
below the grid (the long min-span tail toward `history_epochs`) stay on
the host where per-chunk early exit prunes almost all of the work
(slasher._walk_min_below). Rows are padded to a pow-2 bucket and epochs
are fixed at SPAN_GRID_EPOCHS, so the kernel holds exactly one compiled
shape per row bucket — registered through `_jitted_global` under the
shape-contract machinery (tools/shapes) and pre-warmed from the
manifest's `span_update` rows like any other contract.

Epochs ride as int32 on device (jax x64 is off): the min-side UNSET
sentinel maps uint64 0xFFFF..FF ↔ INT32_UNSET at the host boundary, and
the caller falls back to the host merge for targets ≥ 2^31 (no real
chain gets there).
"""

from __future__ import annotations

import numpy as np

#: epochs per device grid — four span chunks (slasher.CHUNK_EPOCHS × 4),
#: wide enough for any gossip-fresh window (sources and targets within a
#: few epochs of head); wider historical mixes fall back to the host walk
SPAN_GRID_EPOCHS = 64

#: int32 stand-in for the slasher's uint64 UNSET min sentinel
INT32_UNSET = np.int32(0x7FFF_FFFF)


def _span_grid_compute(min_block, max_block, src, tgt, valid, base):
    """The jitted body: elementwise grid merge (shapes fixed by bucket)."""
    import jax.numpy as jnp

    e = base[0] + jnp.arange(SPAN_GRID_EPOCHS, dtype=jnp.int32)[None, :]
    src_c = src[:, None]
    tgt_c = tgt[:, None]
    v = valid[:, None]
    new_min = jnp.minimum(
        min_block, jnp.where(v & (e < src_c), tgt_c, INT32_UNSET)
    )
    new_max = jnp.maximum(
        max_block,
        jnp.where(v & (e > src_c) & (e <= tgt_c), tgt_c, jnp.int32(0)),
    )
    return new_min, new_max


class SpanPlane:
    """Host façade for the span-update grid kernel.

    One instance per slasher; stateless apart from observability seams,
    so a single verify-pool thread owns each call (the slasher serializes
    its mutating calls behind the firehose's _slasher_lock)."""

    def __init__(self, metrics=None) -> None:
        self.metrics = metrics

    def _count_kernel(self, kernel: str) -> None:
        if self.metrics is not None:
            self.metrics.device_kernel_calls.labels(kernel).inc()

    def _run_kernel(self, kernel: str, fn, args: tuple):
        """Dispatch with shape-ledger accounting (tpu/bls.py): a novel
        signature after warmup seal counts as a steady-state recompile,
        the same zero-recompile contract the verify kernels live under."""
        from grandine_tpu.tpu import bls as B

        self._count_kernel(kernel)
        B.note_dispatch_shapes(kernel, args, self.metrics)
        with B._node_profiler().annotate(kernel, len(args[0])):
            out = fn(*args)
        for leaf in out:
            if hasattr(leaf, "block_until_ready"):
                leaf.block_until_ready()
        return out

    def update(self, min_block, max_block, src, tgt, base_epoch: int):
        """Merge one grid window on the device.

        `min_block`/`max_block`: (n, SPAN_GRID_EPOCHS) int32 current
        values (min side already sentinel-mapped to INT32_UNSET);
        `src`/`tgt`: (n,) int32 per-row attestation epochs; `base_epoch`:
        the grid's first epoch. Returns (new_min, new_max) as (n, E)
        int32 numpy arrays."""
        from grandine_tpu.tpu import bls as B

        n = int(min_block.shape[0])
        vb = B._bucket(n, lo=256)
        mn = np.full((vb, SPAN_GRID_EPOCHS), INT32_UNSET, np.int32)
        mx = np.zeros((vb, SPAN_GRID_EPOCHS), np.int32)
        sr = np.zeros((vb,), np.int32)
        tg = np.zeros((vb,), np.int32)
        va = np.zeros((vb,), bool)
        base = np.full((1,), int(base_epoch), np.int32)
        mn[:n] = min_block
        mx[:n] = max_block
        sr[:n] = src
        tg[:n] = tgt
        va[:n] = True
        fn = B._jitted_global("span_update_grid", _span_grid_compute)
        out_min, out_max = self._run_kernel(
            "span_update_grid", fn, (mn, mx, sr, tg, va, base)
        )
        return (
            np.asarray(out_min)[:n],
            np.asarray(out_max)[:n],
        )


def grid_merge_host(min_block, max_block, src, tgt, base_epoch: int):
    """Numpy mirror of `_span_grid_compute` — the fallback engine when no
    SpanPlane is wired (and the differential oracle for the kernel)."""
    e = np.int64(base_epoch) + np.arange(SPAN_GRID_EPOCHS, dtype=np.int64)
    e = e[None, :]
    src_c = np.asarray(src, np.int64)[:, None]
    tgt_c = np.asarray(tgt, np.int64)[:, None]
    new_min = np.minimum(
        np.asarray(min_block, np.int64),
        np.where(e < src_c, tgt_c, np.int64(INT32_UNSET)),
    )
    new_max = np.maximum(
        np.asarray(max_block, np.int64),
        np.where((e > src_c) & (e <= tgt_c), tgt_c, 0),
    )
    return new_min.astype(np.int32), new_max.astype(np.int32)


__all__ = [
    "SPAN_GRID_EPOCHS",
    "INT32_UNSET",
    "SpanPlane",
    "grid_merge_host",
]
