"""Device multi-scalar multiplication (Pippenger) for the RLC signature plane.

Replaces per-signature GLV double-and-add ladders wherever only SUMS of
rᵢ·Pᵢ are needed — the Σ rᵢ·sigᵢ side of every RLC batch verify, and the
per-message-group Σᵢ∈ⱼ rᵢ·pkᵢ side of the grouped kernel. A ladder computes
N scalar muls at ~96 point-ops each; Pippenger buckets the whole batch per
scalar window so the total is ~(windows · 2N) point additions — several
times less field work at headline batch sizes. Reference counterpart:
blst's Pippenger-backed `verify_multiple_aggregate_signatures`
(bls/src/signature.rs:96-129).

TPU-first formulation (no data-dependent control flow on device):
  - The HOST knows the RLC scalars (the verifier draws them), so all
    data-dependent structure — GLV digit extraction, bucket membership,
    sort order — is computed on host as static-shape int32 index arrays
    (`MsmPlan`). The device only gathers, scans, and reduces.
  - Scalars are split GLV-style: rᵢ = r0ᵢ + r1ᵢ·λ, so the expanded batch is
    2N points (Pᵢ and φPᵢ) with 32-bit scalars, cut into W windows of w
    bits. Zero digits are dropped at plan time (they contribute nothing).
  - Bucket accumulation is a SORTED-LANE SEGMENTED SCAN: expanded entries
    are sorted by (section, digit) key — section = group·W + window — and
    dealt contiguously into T lanes of exactly S slots (no alignment
    padding). One lax.scan of S steps runs a width-T complete addition per
    step, emitting its post-add accumulator every step and resetting at
    host-marked segment boundaries. Buckets that span lanes flush in ≤J
    pieces; a host-built gather reassembles (section, digit) bucket sums
    and a J-step scan folds the pieces.
  - Bucket weighting Σ d·S_d uses the suffix-sum identity (Σ_{d≥1} U_d with
    U_d = Σ_{e≥d} S_e), run as a Hillis-Steele suffix over the digit axis;
    window recombination is a Horner scan (w doubles + 1 complete add per
    window) batched over groups.

Complete additions are used throughout (points are adversary-supplied:
duplicates and ∞ must be handled), with Z=0 encoding ∞ so invalid/padding
slots are algebraically neutral — no masks in the hot loop.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from grandine_tpu.tpu import curve as C

#: Scan lane count T (bucket-accumulation width). More lanes = fewer
#: sequential scan steps (S = ceil(2NW / T)), BUT the montmul inner scan
#: carries 27 column accumulators of width (products × T) that must live
#: in VMEM: at T=32768 with ~8 stacked products that carry is ~28 MB and
#: SPILLS (measured 5× slower end-to-end on v5e via
#: device_residency_probe variant C: 391 ms at 8192 vs 2100 ms at 32768).
#: 8192 keeps the carry ~5 MB — comfortably resident.
MSM_LANES = int(os.environ.get("GT_MSM_LANES", "8192"))


def _next_pow2(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


@dataclass(frozen=True)
class MsmPlan:
    """Static-shape device plan for one MSM batch (host-built, numpy).

    Shapes: point_idx/valid/flush (S, T); gather_idx/gather_valid
    (J, n_groups·W, B). point_idx indexes the EXPANDED point array
    (e < N → r0-slot of point e; e ≥ N → r1/φ-slot of point e−N).
    """

    point_idx: np.ndarray
    valid: np.ndarray
    flush: np.ndarray
    gather_idx: np.ndarray
    gather_valid: np.ndarray
    n_groups: int
    windows: int
    window_bits: int

    @property
    def arrays(self):
        return (
            self.point_idx, self.valid, self.flush,
            self.gather_idx, self.gather_valid,
        )


def plan_msm(
    r_lo,
    r_hi,
    inf_mask,
    group_of_point=None,
    n_groups: int = 1,
    window_bits: int = 8,
    lanes: "int | None" = None,
    j_min: int = 2,
) -> MsmPlan:
    """Build the device plan for Σᵢ (r0ᵢ + r1ᵢ·λ)·Pᵢ (per group).

    r_lo/r_hi: (N,) 32-bit GLV scalar halves. inf_mask: (N,) bool — points
    at infinity contribute nothing and are dropped here. group_of_point:
    (N,) ints (None → all group 0). All numpy-vectorized; the only
    per-batch host cost is one argsort of the expanded entries.
    """
    r_lo = np.asarray(r_lo, dtype=np.uint64)
    r_hi = np.asarray(r_hi, dtype=np.uint64)
    n = r_lo.shape[0]
    w = window_bits
    W = (32 + w - 1) // w
    B = 1 << w
    if group_of_point is None:
        group_of_point = np.zeros(n, dtype=np.int64)
    else:
        group_of_point = np.asarray(group_of_point, dtype=np.int64)
    inf_mask = np.asarray(inf_mask, dtype=bool)

    # expanded scalars (2N,) and their point groups
    scal = np.concatenate([r_lo, r_hi])
    grp = np.concatenate([group_of_point, group_of_point])
    live = ~np.concatenate([inf_mask, inf_mask])

    # digits (2N, W); drop zero digits and ∞ points
    shifts = (np.arange(W, dtype=np.uint64) * np.uint64(w))[None, :]
    digits = (scal[:, None] >> shifts) & np.uint64(B - 1)
    keep = (digits != 0) & live[:, None]
    e_idx, e_win = np.nonzero(keep)  # entry → (expanded point, window)
    e_dig = digits[e_idx, e_win].astype(np.int64)
    e_sec = grp[e_idx] * W + e_win  # section = group·W + window
    key = e_sec * B + e_dig

    order = np.argsort(key, kind="stable")
    k_sorted = key[order]
    E = order.shape[0]

    # T lanes × S slots; lane t owns sorted ranks [t·S, (t+1)·S). S is a
    # static function of the UNPRUNED total so jit shapes don't depend on
    # the random scalars.
    T = int(lanes if lanes is not None else MSM_LANES)
    total = 2 * n * W
    while T > 256 and total < 8 * T:
        T //= 2
    S = max(1, -(-total // T))

    point_idx = np.zeros((S, T), dtype=np.int32)
    valid = np.zeros((S, T), dtype=bool)
    flush = np.zeros((S, T), dtype=bool)
    rank = np.arange(E)
    rs, rt = rank % S, rank // S
    point_idx[rs, rt] = e_idx[order].astype(np.int32)
    valid[rs, rt] = True
    # a rank flushes when the next rank starts a new key or a new lane
    last = np.empty(E, dtype=bool)
    if E:
        last[:-1] = (k_sorted[1:] != k_sorted[:-1]) | (rt[1:] != rt[:-1])
        last[-1] = True
    flush[rs, rt] = last

    # pieces: flush ranks ascending are grouped by key; the j-th flush of a
    # key is that bucket's piece j
    fr = rank[last] if E else rank[:0]
    fkey = k_sorted[fr]
    m = fr.shape[0]
    pos = np.arange(m)
    first_of_key = np.empty(m, dtype=bool)
    if m:
        first_of_key[0] = True
        first_of_key[1:] = fkey[1:] != fkey[:-1]
    first_pos = np.maximum.accumulate(np.where(first_of_key, pos, 0)) if m else pos
    piece_j = pos - first_pos
    # J is a compile-time shape, so batch-to-batch variation would trigger
    # multi-minute recompiles mid-verify. Floor it with a DATA-INDEPENDENT
    # prediction (4× the mean bucket occupancy, in lanes-spanned units)
    # that dominates the realized max for all but astronomically unlikely
    # draws; j_min guards the smallest shapes.
    mean_bucket = total / max(1, n_groups * W * B)
    # a bucket of c entries spans ≤ ceil(c/S)+1 lanes; c concentrates at
    # mean + O(√mean) (binomial), so mean + 6√mean + 8 covers ~every draw
    tail_bucket = mean_bucket + 6.0 * mean_bucket ** 0.5 + 8.0
    predicted = int(-(-tail_bucket // S)) + 1
    actual = int(piece_j.max()) + 1 if m else 1
    J = _next_pow2(max(j_min, predicted, actual))

    n_sec = n_groups * W
    gather_idx = np.zeros((J, n_sec, B), dtype=np.int32)
    gather_valid = np.zeros((J, n_sec, B), dtype=bool)
    fsec, fdig = fkey // B, fkey % B
    # emit slot of rank r in the (S, T) scan output = (r % S)·T + (r // S)
    gather_idx[piece_j, fsec, fdig] = ((fr % S) * T + fr // S).astype(np.int32)
    gather_valid[piece_j, fsec, fdig] = True

    return MsmPlan(
        point_idx=point_idx,
        valid=valid,
        flush=flush,
        gather_idx=gather_idx,
        gather_valid=gather_valid,
        n_groups=n_groups,
        windows=W,
        window_bits=w,
    )


# --- device side ------------------------------------------------------------


def _sel3(ops, cond, a, b):
    return tuple(ops.select(cond, x, y) for x, y in zip(a, b))


def _point_inf(ops, shape):
    one = ops.make_one(shape)
    return (one, one, ops.make_zero(shape))


def _gather(e, idx):
    """Gather a field element's batch (device axis 1 of every limb array)
    by a flat int32 index array."""
    return jax.tree.map(lambda a: jnp.take(a, idx, axis=1), e)


def _reduce_last_axis(p, size: int, ops):
    """Sum a point batch over its LAST batch axis (size must be a power of
    two) via the fixed-shape roll tree; returns points indexed at 0."""
    assert size & (size - 1) == 0

    def body(_, carry):
        y, s = carry
        rolled = tuple(
            jax.tree.map(lambda a: jnp.roll(a, -s, axis=-1), e) for e in y
        )
        y = C.point_add_complete(y, rolled, ops)
        return (y, s // 2)

    levels = size.bit_length() - 1
    y, _ = lax.fori_loop(0, levels, body, (p, jnp.int32(size // 2)))
    return tuple(jax.tree.map(lambda a: a[..., 0], e) for e in y)


def msm_bucket_scan(
    px, py, p_live,
    point_idx, valid, flush, gather_idx, gather_valid,
    windows: int, window_bits: int, n_groups: int, ops,
):
    """Σᵢ rᵢ·Pᵢ per group on device, driven by an MsmPlan's index arrays.

    px/py: affine coordinates of the EXPANDED point array (batch E, limb
    form); p_live (E,) bool marks real points. Returns (n_groups,) Jacobian
    points (groups in index order).
    """
    S, T = point_idx.shape
    J, n_sec, B = gather_idx.shape
    assert n_sec == n_groups * windows

    # 1. gather scan operands into sorted-lane order (S, T)
    flat = jnp.asarray(point_idx.reshape(-1))
    gx = _gather(px, flat)
    gy = _gather(py, flat)
    glive = jnp.take(jnp.asarray(p_live), flat) & jnp.asarray(
        valid.reshape(-1)
    )

    def to_scan_layout(e):
        # leaves (26, S·T) → (S, 26, T) so lax.scan slices rows
        return jax.tree.map(
            lambda a: jnp.moveaxis(a.reshape(a.shape[0], S, T), 1, 0), e
        )

    gx, gy = to_scan_layout(gx), to_scan_layout(gy)
    glive_st = glive.reshape(S, T)

    inf_T = _point_inf(ops, (T,))
    one_T, zero_T = inf_T[0], inf_T[2]

    def step(acc, xs):
        sx, sy, lv, fl = xs
        pt = (sx, sy, ops.select(lv, one_T, zero_T))  # Z=0 ⇒ ∞ (neutral)
        new = C.point_add_complete(acc, pt, ops)
        nxt = _sel3(ops, fl, inf_T, new)
        return nxt, new

    _, emits = lax.scan(
        step, inf_T, (gx, gy, glive_st, jnp.asarray(flush))
    )
    # emits leaves (S, 26, T) → flat emit axis (26, S·T), index = s·T + t
    emits = tuple(
        jax.tree.map(
            lambda a: jnp.moveaxis(a, 0, 1).reshape(a.shape[1], S * T), e
        )
        for e in emits
    )

    # 2. reassemble bucket sums: gather pieces, fold over J
    gidx = jnp.asarray(gather_idx.reshape(-1))
    pieces = tuple(
        jax.tree.map(
            lambda a: jnp.moveaxis(
                jnp.take(a, gidx, axis=1).reshape(a.shape[0], J, n_sec, B),
                1, 0,
            ),
            e,
        )
        for e in emits
    )
    gv = jnp.asarray(gather_valid)
    inf_secB = _point_inf(ops, (n_sec, B))

    def fold(acc, xs):
        pc, vmask = xs
        pc = _sel3(ops, vmask, pc, inf_secB)
        return C.point_add_complete(acc, pc, ops), None

    buckets, _ = lax.scan(fold, inf_secB, (pieces, gv))

    # 3. suffix-weight: T_sec = Σ_{d≥1} d·S_d = Σ_{d≥1} U_d, U_d = Σ_{e≥d} S_e
    # (Hillis-Steele as a fori_loop with a TRACED shift: one add graph. The
    # unrolled-python-loop form with constant shifts MISCOMPILES on the
    # axon TPU platform at (4, 256)-batch — fori/scan forms are exact; see
    # round-4 notes. fori is also the compile-friendly shape.)
    idx_b = jnp.arange(B)

    def suffix_body(_, carry):
        U, k = carry
        rolled = tuple(
            jax.tree.map(lambda a: jnp.roll(a, -k, axis=-1), e) for e in U
        )
        rolled = _sel3(ops, idx_b < (B - k), rolled, inf_secB)
        U = C.point_add_complete(U, rolled, ops)
        return (U, k * 2)

    levels = B.bit_length() - 1
    U, _ = lax.fori_loop(0, levels, suffix_body, (buckets, jnp.int32(1)))
    U = _sel3(ops, idx_b >= 1, U, inf_secB)  # digit 0 carries weight 0
    totals = _reduce_last_axis(U, B, ops)  # (n_sec,)

    # 4. Horner over windows (hi → lo): acc = 2^w·acc ⊞ T_win, per group
    W, w = windows, window_bits
    xs_rev = tuple(
        jax.tree.map(
            lambda a: jnp.moveaxis(
                a.reshape(a.shape[0], n_groups, W), 2, 0
            )[::-1],
            e,
        )
        for e in totals
    )
    init = _point_inf(ops, (n_groups,))

    def horner(acc, win_pt):
        # w doubles as a fori_loop (same anti-unroll discipline as above)
        acc = lax.fori_loop(0, w, lambda _i, a: C.point_double(a, ops), acc)
        return C.point_add_complete(acc, tuple(win_pt), ops), None

    acc, _ = lax.scan(horner, init, xs_rev)
    return acc


def expand_glv_points(x, y, inf, endo, ops):
    """Affine batch (N,) → expanded affine batch (2N,): [P…, φP…], with
    φ(x, y) = (cx·x, cy·y) = [λ]·(x, y) (crypto/curves.py endo_constants).
    Returns (px, py, p_live) for msm_bucket_scan."""
    ex, ey = endo
    x2, y2 = ops.mul_many([x, y], [ex, ey])
    px = ops.concat([x, x2], 1)  # device batch axis
    py = ops.concat([y, y2], 1)
    live = jnp.concatenate([~inf, ~inf])
    return px, py, live


__all__ = ["MsmPlan", "plan_msm", "msm_bucket_scan", "expand_glv_points"]
