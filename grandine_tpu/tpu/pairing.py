"""Batched optimal ate pairing on device.

Differences from the anchor (crypto/pairing.py), all validated differentially:
  - G2 loop point T is homogeneous projective on the twist (no inversions);
    lines are evaluated via the D-twist untwist structure, landing in the
    sparse Fp12 subspace spanned by {1, w³, w⁵} over Fp2.
  - Each line is freely scaled by Fp2/Fp factors (killed by the final
    exponentiation), which lets the G1 point stay Jacobian — no batch
    inversion anywhere.
  - The final exponentiation easy part uses conjugate/Frobenius; the hard
    part uses the x-chain (x-1)²(x+p)(x²+p²-1)+3 = 3·(p⁴-p²+1)/r, i.e. the
    device computes FE(f)³ — equivalent for pairing-product checks since
    gcd(3, r) = 1, and differentially tested as anchor_FE(f)**3.
  - The Miller loop is segmented by the static bit pattern of |x|
    (5 add positions), so pure-double runs share one scanned body.

Batch semantics: all inputs carry a leading batch axis; infinity inputs
yield f = 1 (neutral in the product), matching anchor miller_loop.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from grandine_tpu.crypto.constants import X
from grandine_tpu.tpu import field as F
from grandine_tpu.tpu import limbs as L

# |x| = 2^63 + 2^62 + 2^60 + 2^57 + 2^48 + 2^16; MSB handled by T = Q.
_ABS_X = abs(X)
_BITS_AFTER_MSB = [(_ABS_X >> i) & 1 for i in range(62, -1, -1)]
# segment structure: (n_doubles_before_this_add) per add bit, plus tail doubles
_SEGMENTS: "list[int]" = []
_run = 0
for _b in _BITS_AFTER_MSB:
    _run += 1
    if _b:
        _SEGMENTS.append(_run)
        _run = 0
_TAIL_DOUBLES = _run
assert len(_SEGMENTS) == 5 and _TAIL_DOUBLES == 16


def _line_to_fp12(a, b, c):
    """Assemble sparse line a·1 + b·w³ + c·w⁵ into a full Fp12 element:
    C0 = (a, 0, 0), C1 = (0, b, c) over the Fp6 basis {1, v, v²}."""
    z = jnp.zeros_like(a)
    c0 = jnp.stack([a, z, z], axis=-3)
    c1 = jnp.stack([z, b, c], axis=-3)
    return jnp.stack([c0, c1], axis=-4)


def prepare_g1(P):
    """Precompute the Miller-loop constants of a Jacobian G1 point
    P = (Xp, Yp, Zp): (ξ·yP·Zp³, xP·Zp³) = ((Yp, Yp), Xp·Zp) and Zp³."""
    Xp, Yp, Zp = P
    m = L.montmul(jnp.stack([Xp, Zp]), jnp.stack([Zp, Zp]))
    XpZp, Zp2 = m[0], m[1]
    Zp3 = L.montmul(Zp2, Zp)
    xi_yp = jnp.stack([Yp, Yp], axis=-2)  # ξ·Yp with ξ = 1+u
    neg_xpzp = L.neg_mod(XpZp)
    return xi_yp, neg_xpzp, Zp3


def _double_step(T, g1c):
    """One Miller doubling: T ← 2T, return the evaluated line."""
    Xt, Yt, Zt = T
    xi_yp, neg_xpzp, zp3 = g1c
    sq = F.fp2_sq_many(jnp.stack([Xt, Yt]))
    X2, _Y2 = sq[0], sq[1]
    A = F.fp2_add(F.fp2_add(X2, X2), X2)  # 3X²
    m1 = F.fp2_mul_many(jnp.stack([Yt, A]), jnp.stack([Zt, Xt]))
    YZ, AX = m1[0], m1[1]
    B = F.fp2_add(YZ, YZ)  # 2YZ
    m2 = F.fp2_mul_many(
        jnp.stack([Yt, B, A, B]), jnp.stack([B, Zt, Zt, B])
    )
    YB, BZ, AZ, B2 = m2[0], m2[1], m2[2], m2[3]
    # line coefficients (scaled by BZ·Zp³)
    l_a = F.fp2_mul(BZ, xi_yp)
    l_b = F.fp2_scale(F.fp2_sub(AX, YB), zp3)
    l_c = F.fp2_scale(AZ, neg_xpzp)
    # new point: X₂ = B(A²Z − 2XB²), Y₂ = A(3XB² − A²Z) − YB³, Z₂ = B³Z
    m3 = F.fp2_mul_many(jnp.stack([A, Xt, B]), jnp.stack([A, B2, B2]))
    A2, XB2, B3 = m3[0], m3[1], m3[2]
    m4 = F.fp2_mul_many(jnp.stack([A2, Yt, B3]), jnp.stack([Zt, B3, Zt]))
    A2Z, YB3, Z2 = m4[0], m4[1], m4[2]
    XB2_2 = F.fp2_add(XB2, XB2)
    XB2_3 = F.fp2_add(XB2_2, XB2)
    m5 = F.fp2_mul_many(
        jnp.stack([B, A]),
        jnp.stack([F.fp2_sub(A2Z, XB2_2), F.fp2_sub(XB2_3, A2Z)]),
    )
    Xn = m5[0]
    Yn = F.fp2_sub(m5[1], YB3)
    return (Xn, Yn, Z2), _line_to_fp12(l_a, l_b, l_c)


def _add_step(T, Q, g1c):
    """Miller addition: T ← T + Q (both homogeneous projective), return line."""
    Xt, Yt, Zt = T
    Xq, Yq, Zq = Q
    xi_yp, neg_xpzp, zp3 = g1c
    m1 = F.fp2_mul_many(
        jnp.stack([Yt, Yq, Xt, Xq]), jnp.stack([Zq, Zt, Zq, Zt])
    )
    YZq, YqZ, XZq, XqZ = m1[0], m1[1], m1[2], m1[3]
    E = F.fp2_sub(YZq, YqZ)
    Fv = F.fp2_sub(XZq, XqZ)
    m2 = F.fp2_mul_many(
        jnp.stack([E, Fv, E, Fv, Fv]),
        jnp.stack([Xq, Yq, Zq, Zq, Fv]),
    )
    EXq, FYq, EZq, FZq, F2 = m2[0], m2[1], m2[2], m2[3], m2[4]
    l_a = F.fp2_mul(FZq, xi_yp)
    l_b = F.fp2_scale(F.fp2_sub(EXq, FYq), zp3)
    l_c = F.fp2_scale(EZq, neg_xpzp)
    # point update
    m3 = F.fp2_mul_many(
        jnp.stack([E, Fv, F2, F2]),
        jnp.stack([E, F2, F.fp2_add(XZq, XqZ), Xt]),
    )
    E2, F3, Fsum, XF2 = m3[0], m3[1], m3[2], m3[3]
    m4 = F.fp2_mul_many(
        jnp.stack([E2, XF2, F3, F3]),
        jnp.stack([Zt, Zq, Yt, Zt]),
    )
    E2Z, XF2Zq, YF3, F3Z = m4[0], m4[1], m4[2], m4[3]
    m5 = F.fp2_mul_many(jnp.stack([E2Z, YF3, F3Z]), jnp.stack([Zq, Zq, Zq]))
    E2ZZq, YF3Zq, Z3 = m5[0], m5[1], m5[2]
    G = F.fp2_sub(E2ZZq, Fsum)
    m6 = F.fp2_mul_many(
        jnp.stack([Fv, E]), jnp.stack([G, F.fp2_sub(XF2Zq, G)])
    )
    X3 = m6[0]
    Y3 = F.fp2_sub(m6[1], YF3Zq)
    return (X3, Y3, Z3), _line_to_fp12(l_a, l_b, l_c)


def miller_loop(P_jac, Q_proj, inf_mask):
    """f_{|x|,Q}(P) conjugated (negative x), batched.

    P_jac: G1 Jacobian (X, Y, Z) each (..., 24).
    Q_proj: G2 homogeneous projective on the twist, (..., 2, 24) coords.
    inf_mask: bool (...,) — True where either input is the identity; those
    slots yield f = 1 (neutral in the product). Passed explicitly by the
    host (which knows the flags) so no value-level zero test is needed.
    """
    g1c = prepare_g1(P_jac)
    f0 = F.fp12_one(Q_proj[0].shape[:-2])
    T0 = Q_proj

    def double_body(carry, _):
        T, f = carry
        f = F.fp12_mul(f, f)
        T, line = _double_step(T, g1c)
        f = F.fp12_mul(f, line)
        return (T, f), None

    def run_doubles(T, f, n):
        (T, f), _ = lax.scan(double_body, (T, f), None, length=n)
        return T, f

    T, f = T0, f0
    for n_doubles in _SEGMENTS:
        T, f = run_doubles(T, f, n_doubles)
        T, line = _add_step(T, Q_proj, g1c)
        f = F.fp12_mul(f, line)
    T, f = run_doubles(T, f, _TAIL_DOUBLES)

    f = F.fp12_conj(f)  # negative BLS parameter
    return F.fp12_select(inf_mask, F.fp12_one(f.shape[:-4]), f)


_ABS_X_BITS_MSB = np.array(
    [(_ABS_X >> i) & 1 for i in range(_ABS_X.bit_length() - 1, -1, -1)],
    dtype=np.int32,
)


def expx_abs(m):
    """m^|x| (square-and-multiply, MSB-first, seeded with m for the MSB)."""

    def step(acc, bit):
        acc = F.fp12_mul(acc, acc)
        taken = F.fp12_mul(acc, m)
        return F.fp12_select(
            jnp.broadcast_to(bit.astype(bool), acc.shape[:-4]), taken, acc
        ), None

    acc, _ = lax.scan(step, m, jnp.asarray(_ABS_X_BITS_MSB[1:]))
    return acc


def final_exponentiation(f):
    """f^(3·(p¹²-1)/r): easy part by conjugate/Frobenius, hard part by the
    x-chain (x-1)²(x+p)(x²+p²-1)+3 (identity verified in tests)."""
    t = F.fp12_mul(F.fp12_conj(f), F.fp12_inv(f))  # f^(p⁶-1)
    m = F.fp12_mul(F.fp12_frobenius_n(t, 2), t)  # ^(p²+1)

    conj = F.fp12_conj
    mul = F.fp12_mul
    t1 = conj(mul(expx_abs(m), m))  # m^(x-1)
    t2 = conj(mul(expx_abs(t1), t1))  # ^(x-1) again
    t3 = mul(conj(expx_abs(t2)), F.fp12_frobenius(t2))  # ^(x+p)
    t4 = conj(expx_abs(conj(expx_abs(t3))))  # ^(x²)
    m3 = mul(mul(m, m), m)
    return mul(mul(mul(t4, F.fp12_frobenius_n(t3, 2)), conj(t3)), m3)


def multi_pairing_check(P_jac, Q_proj, inf_mask):
    """∏ e(Pᵢ, Qᵢ) == 1 over the batch (power-of-two length; pad with
    infinity pairs). One shared final exponentiation."""
    f = miller_loop(P_jac, Q_proj, inf_mask)
    n = f.shape[0]
    assert n & (n - 1) == 0
    while n > 1:
        h = n // 2
        f = F.fp12_mul_many(f[:h], f[h:n])
        n = h
    return F.fp12_is_one(final_exponentiation(f[0]))


def jacobian_to_homogeneous(P):
    """(X, Y, Z) Jacobian → (XZ, Y, Z³) homogeneous (no inversion); generic
    over the field via the ops module functions used (Fp2 here)."""
    Xj, Yj, Zj = P
    m = F.fp2_mul_many(jnp.stack([Xj, Zj]), jnp.stack([Zj, Zj]))
    XZ, Z2 = m[0], m[1]
    Z3 = F.fp2_mul(Z2, Zj)
    return (XZ, Yj, Z3)
