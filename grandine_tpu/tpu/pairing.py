"""Batched optimal ate pairing on device, in limb-list form.

Differences from the anchor (crypto/pairing.py), all validated differentially:
  - G2 loop point T is homogeneous projective on the twist (no inversions);
    lines are evaluated via the D-twist untwist structure, landing in the
    sparse Fp12 subspace spanned by {1, w³, w⁵} over Fp2.
  - Each line is freely scaled by Fp2/Fp factors (killed by the final
    exponentiation), which lets the G1 point stay Jacobian — no batch
    inversion anywhere.
  - Line factors multiply in SPARSELY (`mul_by_line`, 14 Fp2 products vs 18
    for a full Fp12 Karatsuba) and loop squarings use the complex-squaring
    shape (`fp12_sq_fast`, 12 Fp2 products) — in both cases every Fp2
    product of the operation runs in ONE fused montmul call.
  - The final exponentiation easy part uses conjugate/Frobenius; the hard
    part uses the x-chain (x-1)²(x+p)(x²+p²-1)+3 = 3·(p⁴-p²+1)/r, i.e. the
    device computes FE(f)³ — equivalent for pairing-product checks since
    gcd(3, r) = 1, and differentially tested as anchor_FE(f)**3.
  - The Miller loop is ONE lax.scan over the bit pattern of |x|, the 5
    add steps gated by lax.cond — a single compiled body with no wasted
    add work (see miller_loop).

Batch semantics: all inputs carry a batch shape on every limb array;
infinity inputs yield f = 1 (neutral in the product), matching anchor
miller_loop.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from grandine_tpu.crypto.constants import X
from grandine_tpu.tpu import field as F
from grandine_tpu.tpu import limbs as L

# |x| = 2^63 + 2^62 + 2^60 + 2^57 + 2^48 + 2^16; MSB handled by T = Q.
_ABS_X = abs(X)
_BITS_AFTER_MSB = [(_ABS_X >> i) & 1 for i in range(62, -1, -1)]
# segment structure: (n_doubles_before_this_add) per add bit, plus tail doubles
_SEGMENTS: "list[int]" = []
_run = 0
for _b in _BITS_AFTER_MSB:
    _run += 1
    if _b:
        _SEGMENTS.append(_run)
        _run = 0
_TAIL_DOUBLES = _run
assert len(_SEGMENTS) == 5 and _TAIL_DOUBLES == 16


_fp2_many = F.fp2_pair_products


def mul_by_line(f, line):
    """f · (a + b·w³ + c·w⁵), sparse: 14 Fp2 products in one montmul call.

    With ℓ = (ℓ0, ℓ1) = ((a,0,0), (0,b,c)) over Fp6 and w² = v:
      c0 = f0·ℓ0 + v·(f1·ℓ1),  c1 = (f0+f1)·(ℓ0+ℓ1) − f0·ℓ0 − f1·ℓ1.
    f0·ℓ0 is a v-degree-0 scale (3 products); f1·ℓ1 is a 2-sparse Fp6
    product (5 with one Karatsuba share); (f0+f1)(a,b,c) is a full Fp6
    product (6, Karatsuba hybrid).
    """
    a, b, c = line
    f0, f1 = f
    g0, g1, g2 = f0
    h0, h1, h2 = f1
    s0, s1, s2 = (F.fp2_add(x, y) for x, y in zip(f0, f1))
    bc = F.fp2_add(b, c)
    ab = a  # ℓ0+ℓ1 = (a, b, c)
    # Karatsuba pre-sums for t2 = (s0,s1,s2)·(a,b,c)
    s12 = F.fp2_add(s1, s2)
    s01 = F.fp2_add(s0, s1)
    s02 = F.fp2_add(s0, s2)
    prods = _fp2_many([
        (g0, a), (g1, a), (g2, a),                    # t0 = f0·ℓ0
        (h0, b), (h0, c), (h1, b), (h2, c),           # t1 parts
        (F.fp2_add(h1, h2), bc),                      # t1 Karatsuba share
        (s0, ab), (s1, b), (s2, c),                   # t2 diagonal
        (s12, bc), (s01, F.fp2_add(ab, b)), (s02, F.fp2_add(ab, c)),
    ])
    (g0a, g1a, g2a,
     h0b, h0c, h1b, h2c, h12bc,
     s0a, s1b, s2c, t12, t01, t02) = prods
    # t1 = f1·(0,b,c) = (ξ(h1c + h2b), h0b + ξ·h2c, h0c + h1b)
    #   with h1c + h2b = (h1+h2)(b+c) − h1b − h2c
    h1c_h2b = F.fp2_sub(h12bc, F.fp2_add(h1b, h2c))
    t1 = (
        F.fp2_mul_by_xi(h1c_h2b),
        F.fp2_add(h0b, F.fp2_mul_by_xi(h2c)),
        F.fp2_add(h0c, h1b),
    )
    # t2 = (s0+s1 v+s2 v²)(a+b v+c v²), Karatsuba hybrid
    #   d0 = s0a + ξ(s12·bc − s1b − s2c)
    #   d1 = (s01·(a+b) − s0a − s1b) + ξ s2c
    #   d2 = (s02·(a+c) − s0a − s2c) + s1b
    d0 = F.fp2_add(s0a, F.fp2_mul_by_xi(F.fp2_sub(t12, F.fp2_add(s1b, s2c))))
    d1 = F.fp2_add(F.fp2_sub(t01, F.fp2_add(s0a, s1b)), F.fp2_mul_by_xi(s2c))
    d2 = F.fp2_add(F.fp2_sub(t02, F.fp2_add(s0a, s2c)), s1b)
    t2 = (d0, d1, d2)
    t0 = (g0a, g1a, g2a)
    c0 = F.fp6_add(t0, F.fp6_mul_by_v(t1))
    c1 = F.fp6_sub(t2, F.fp6_add(t0, t1))
    return (c0, c1)


def fp12_sq_fast(f):
    """f² via complex squaring over Fp6 (w² = v): c0 = f0² + v·f1²,
    c1 = 2·f0·f1 — expressed as two Fp6 products (f0+f1)(f0+v·f1) and f0·f1
    (12 Fp2 products, one montmul call) instead of a full 18-product mul."""
    f0, f1 = f
    vf1 = F.fp6_mul_by_v(f1)
    A = F.cat6([F.lead6(F.fp6_add(f0, f1)), F.lead6(f0)])
    B = F.cat6([F.lead6(F.fp6_add(f0, vf1)), F.lead6(f1)])
    T = F.fp6_mul_many(A, B)
    s = F.unlead6(F.slice6(T, 0, 1))   # (f0+f1)(f0+v f1)
    m = F.unlead6(F.slice6(T, 1, 2))   # f0·f1
    c0 = F.fp6_sub(s, F.fp6_add(m, F.fp6_mul_by_v(m)))
    c1 = F.fp6_add(m, m)
    return (c0, c1)


def prepare_g1(P):
    """Precompute the Miller-loop constants of a Jacobian G1 point
    P = (Xp, Yp, Zp): (ξ·yP·Zp³, xP·Zp³) = ((Yp, Yp), Xp·Zp) and Zp³."""
    Xp, Yp, Zp = P
    m = L.montmul(L.stack_fp([Xp, Zp]), L.stack_fp([Zp, Zp]))
    XpZp, Zp2 = L.unstack_fp(m, 2)
    Zp3 = L.montmul(Zp2, Zp)
    xi_yp = (Yp, Yp)  # ξ·Yp with ξ = 1+u, as an Fp2 element
    neg_xpzp = L.neg_mod(XpZp)
    return xi_yp, neg_xpzp, Zp3


def _as_fp2(x):
    """Fp scalar → Fp2 element (x, 0)."""
    return (x, L.zeros_fp(x.shape[1:]))


def _double_step(T, g1c):
    """One Miller doubling: T ← 2T, return the evaluated line."""
    Xt, Yt, Zt = T
    xi_yp, neg_xpzp, zp3 = g1c
    X2 = F.fp2_sq(Xt)
    A = F.fp2_add(F.fp2_add(X2, X2), X2)  # 3X²
    m1 = _fp2_many([(Yt, Zt), (A, Xt)])
    YZ, AX = m1
    B = F.fp2_add(YZ, YZ)  # 2YZ
    m2 = _fp2_many([(Yt, B), (B, Zt), (A, Zt), (B, B)])
    YB, BZ, AZ, B2 = m2
    # line coefficients (scaled by BZ·Zp³)
    la_lb_lc = _fp2_many([
        (BZ, xi_yp),
        (F.fp2_sub(AX, YB), _as_fp2(zp3)),
        (AZ, _as_fp2(neg_xpzp)),
    ])
    l_a, l_b, l_c = la_lb_lc
    # new point: X₂ = B(A²Z − 2XB²), Y₂ = A(3XB² − A²Z) − YB³, Z₂ = B³Z
    m3 = _fp2_many([(A, A), (Xt, B2), (B, B2)])
    A2, XB2, B3 = m3
    m4 = _fp2_many([(A2, Zt), (Yt, B3), (B3, Zt)])
    A2Z, YB3, Z2 = m4
    XB2_2 = F.fp2_add(XB2, XB2)
    XB2_3 = F.fp2_add(XB2_2, XB2)
    m5 = _fp2_many([
        (B, F.fp2_sub(A2Z, XB2_2)),
        (A, F.fp2_sub(XB2_3, A2Z)),
    ])
    Xn = m5[0]
    Yn = F.fp2_sub(m5[1], YB3)
    return (Xn, Yn, Z2), (l_a, l_b, l_c)


def _add_step(T, Q, g1c):
    """Miller addition: T ← T + Q (both homogeneous projective), return line."""
    Xt, Yt, Zt = T
    Xq, Yq, Zq = Q
    xi_yp, neg_xpzp, zp3 = g1c
    m1 = _fp2_many([(Yt, Zq), (Yq, Zt), (Xt, Zq), (Xq, Zt)])
    YZq, YqZ, XZq, XqZ = m1
    E = F.fp2_sub(YZq, YqZ)
    Fv = F.fp2_sub(XZq, XqZ)
    m2 = _fp2_many([(E, Xq), (Fv, Yq), (E, Zq), (Fv, Zq), (Fv, Fv)])
    EXq, FYq, EZq, FZq, F2 = m2
    lines = _fp2_many([
        (FZq, xi_yp),
        (F.fp2_sub(EXq, FYq), _as_fp2(zp3)),
        (EZq, _as_fp2(neg_xpzp)),
    ])
    l_a, l_b, l_c = lines
    # point update
    m3 = _fp2_many([
        (E, E), (Fv, F2), (F2, F.fp2_add(XZq, XqZ)), (F2, Xt),
    ])
    E2, F3, Fsum, XF2 = m3
    m4 = _fp2_many([(E2, Zt), (XF2, Zq), (F3, Yt), (F3, Zt)])
    E2Z, XF2Zq, YF3, F3Z = m4
    m5 = _fp2_many([(E2Z, Zq), (YF3, Zq), (F3Z, Zq)])
    E2ZZq, YF3Zq, Z3 = m5
    G = F.fp2_sub(E2ZZq, Fsum)
    m6 = _fp2_many([(Fv, G), (E, F.fp2_sub(XF2Zq, G))])
    X3 = m6[0]
    Y3 = F.fp2_sub(m6[1], YF3Zq)
    return (X3, Y3, Z3), (l_a, l_b, l_c)


def miller_loop(P_jac, Q_proj, inf_mask):
    """f_{|x|,Q}(P) conjugated (negative x), batched.

    P_jac: G1 Jacobian (X, Y, Z), limb-list Fp elements.
    Q_proj: G2 homogeneous projective on the twist, limb-list Fp2 coords.
    inf_mask: bool batch array — True where either input is the identity;
    those slots yield f = 1 (neutral in the product). Passed explicitly by
    the host (which knows the flags) so no value-level zero test is needed.

    Structure: ONE lax.scan over the 63 post-MSB bits of |x|; each step
    doubles, and on the 5 set bits a lax.cond runs the add step — the cond
    executes its taken branch only, so zero bits pay nothing, and the whole
    loop is a single compiled body (the Python-unrolled segment structure
    compiled the same graph six times over — XLA compile time is
    superlinear in graph size).
    """
    g1c = prepare_g1(P_jac)
    shape = Q_proj[0][0].shape[1:]
    f0 = F.fp12_one(shape)

    def step(carry, bit):
        T, f = carry
        f = fp12_sq_fast(f)
        T, line = _double_step(T, g1c)
        f = mul_by_line(f, line)

        def with_add(args):
            T, f = args
            T, line_a = _add_step(T, Q_proj, g1c)
            return T, mul_by_line(f, line_a)

        T, f = lax.cond(bit.astype(bool), with_add, lambda a: a, (T, f))
        return (T, f), None

    bits = jnp.asarray(np.array(_BITS_AFTER_MSB, dtype=np.int32))
    (_, f), _ = lax.scan(step, (Q_proj, f0), bits)

    f = F.fp12_conj(f)  # negative BLS parameter
    return F.fp12_select(inf_mask, F.fp12_one(shape), f)


_ABS_X_BITS_MSB = np.array(
    [(_ABS_X >> i) & 1 for i in range(_ABS_X.bit_length() - 1, -1, -1)],
    dtype=np.int32,
)


def expx_abs(m):
    """m^|x| (square-and-multiply, MSB-first, seeded with m for the MSB).
    |x| has only six set bits, so the multiply is gated behind lax.cond —
    5 of 63 steps pay it instead of all (the step bit is a scan-carried
    scalar, so cond executes one branch)."""

    def step(acc, bit):
        acc = fp12_sq_fast(acc)
        acc = lax.cond(
            bit.astype(bool), lambda a: F.fp12_mul(a, m), lambda a: a, acc
        )
        return acc, None

    acc, _ = lax.scan(step, m, jnp.asarray(_ABS_X_BITS_MSB[1:]))
    return acc


def _hard_part(m):
    """m^(3·(p⁴-p²+1)/r) via the x-chain (x-1)²(x+p)(x²+p²-1)+3. Valid for
    m in the cyclotomic subgroup, where conj is the inverse; also valid
    componentwise on a (num, den) pair whose QUOTIENT is cyclotomic —
    every op here (mul, conj, Frobenius, expx) is a quotient homomorphism."""
    conj = F.fp12_conj
    mul = F.fp12_mul
    t1 = conj(mul(expx_abs(m), m))  # m^(x-1)
    t2 = conj(mul(expx_abs(t1), t1))  # ^(x-1) again
    t3 = mul(conj(expx_abs(t2)), F.fp12_frobenius(t2))  # ^(x+p)
    t4 = conj(expx_abs(conj(expx_abs(t3))))  # ^(x²)
    m3 = mul(mul(m, m), m)
    return mul(mul(mul(t4, F.fp12_frobenius_n(t3, 2)), conj(t3)), m3)


def final_exponentiation(f):
    """f^(3·(p¹²-1)/r): easy part by conjugate/Frobenius, hard part by the
    x-chain (identity verified in tests)."""
    t = F.fp12_mul(F.fp12_conj(f), F.fp12_inv(f))  # f^(p⁶-1)
    m = F.fp12_mul(F.fp12_frobenius_n(t, 2), t)  # ^(p²+1)
    return _hard_part(m)


def _stack12(a, b):
    """Stack two same-shape Fp12 elements along a NEW leading batch axis."""
    return jax.tree.map(lambda x, y: jnp.stack([x, y], axis=1), a, b)


def final_exp_is_one(f):
    """final_exponentiation(f) == 1, WITHOUT the Fp12 inversion.

    f^(p⁶-1) = conj(f)/f, so the easy-part output is carried as a
    numerator/denominator PAIR stacked into one width-2 batch — the hard
    part then runs once at width 2 (same latency as width 1) and the check
    becomes num == den. The ~580-sequential-multiply Fermat inversion this
    replaces was ~90% of the final-exp wall time on device (round-4
    profile: fp12_inv 482 ms of 532 ms at width 1)."""
    pair = _stack12(F.fp12_conj(f), f)  # (num, den) ≡ f^(p⁶-1)
    m = F.fp12_mul(F.fp12_frobenius_n(pair, 2), pair)  # ^(p²+1)
    e = _hard_part(m)
    num = jax.tree.map(lambda x: x[:, 0], e)
    den = jax.tree.map(lambda x: x[:, 1], e)
    diff = jax.tree.leaves(jax.tree.map(L.sub_mod, num, den))
    # one fused Montgomery reduction (×R·R⁻¹ = identity) pulls the 12
    # component values into (−0.1p, 2p) before the 8p-bounded zero test
    stacked = L.stack_fp(diff)
    one = L.const_fp(L.ONE_MONT_DIGITS, (1,) * (stacked.ndim - 1))
    # Interval worst case of the fp12 difference reaches ~123p via
    # compounded m·p/R terms; theorem (a) still holds and the product
    # contracts into (-0.1p, 2p) (see tools/ranges/bounds.txt).
    red = L.montmul(stacked, one)  # lint: disable=limb-range
    return jnp.all(L.is_zero_val(red), axis=0)


def multi_pairing_check(P_jac, Q_proj, inf_mask):
    """∏ e(Pᵢ, Qᵢ) == 1 over the batch. Batch must be a power of two (pad
    with infinity pairs — neutral). One shared final exponentiation."""
    f = miller_loop(P_jac, Q_proj, inf_mask)
    f = fp12_product_tree(f)
    return final_exp_is_one(f)


def fp12_product_tree(f):
    """Reduce a batch of Fp12 elements (leading batch axis on every limb
    array) to one element. Batch must be a power of two (pad with one — the
    neutral element). Fixed-shape masked-roll reduction, one compiled body
    (see curve._tree_reduce_points for why)."""
    n = f[0][0][0].shape[1]
    assert n & (n - 1) == 0, "fp12_product_tree requires a power-of-two batch"
    levels = n.bit_length() - 1
    if levels:

        def body(_, carry):
            y, s = carry
            rolled = jax.tree.map(lambda x: jnp.roll(x, -s, axis=1), y)
            y = F.fp12_mul_many(y, rolled)
            return (y, s // 2)

        f, _ = lax.fori_loop(0, levels, body, (f, jnp.int32(n // 2)))
    return tuple(F.take6(c, 0) for c in f)


def fp12_product_tree_grouped(f, group_size: int):
    """Reduce a batch of Fp12 elements to N/group_size products over
    CONTIGUOUS groups [0,S), [S,2S), ... (pad with one — neutral).
    group_size must be a power of two. Same one-compiled-body roll
    reduction as fp12_product_tree, but the strides stop at the group
    width so position g*S holds group g's product; the group products
    come out as a width-N/S batch via one strided slice. Feeds the
    fault-localization kernel's per-sub-batch pairing verdicts."""
    assert group_size & (group_size - 1) == 0, (
        "fp12_product_tree_grouped requires a power-of-two group size"
    )
    if group_size <= 1:
        return f
    levels = group_size.bit_length() - 1

    def body(_, carry):
        y, s = carry
        rolled = jax.tree.map(lambda x: jnp.roll(x, -s, axis=1), y)
        y = F.fp12_mul_many(y, rolled)
        return (y, s // 2)

    f, _ = lax.fori_loop(0, levels, body, (f, jnp.int32(group_size // 2)))
    return jax.tree.map(lambda x: x[:, ::group_size], f)


def jacobian_to_homogeneous(P):
    """(X, Y, Z) Jacobian → (XZ, Y, Z³) homogeneous (no inversion), Fp2."""
    Xj, Yj, Zj = P
    m = _fp2_many([(Xj, Zj), (Zj, Zj)])
    XZ, Z2 = m
    Z3 = F.fp2_mul(Z2, Zj)
    return (XZ, Yj, Z3)
