"""Device field tower on limb arrays.

Shapes (always trailing; any leading batch shape broadcasts):
  Fp   (..., 24)
  Fp2  (..., 2, 24)          c0 + c1·u
  Fp6  (..., 3, 2, 24)       over Fp2, v³ = ξ = 1+u
  Fp12 (..., 2, 3, 2, 24)    over Fp6, w² = v

Same tower and formulas as the anchor (grandine_tpu/crypto/fields.py); every
function is differentially tested against it. Frobenius coefficients are
imported from the anchor's derived values — a single source of truth.

The `*_many` variants take a stacked leading axis of independent pairs and
fold ALL their limb multiplications into a single wide montmul scan — one
Fp12 multiplication is exactly one 54-wide montmul call. This is what keeps
the Miller-loop XLA graph compilable and the VPU lanes full.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from grandine_tpu.crypto.fields import frobenius_coefficients
from grandine_tpu.tpu import limbs as L

NL = L.NLIMBS

# --- Fp2 -------------------------------------------------------------------


def fp2_add(a, b):
    return L.add_mod(a, b)


def fp2_sub(a, b):
    return L.sub_mod(a, b)


def fp2_neg(a):
    return L.neg_mod(a)


def fp2_mul_many(A, B):
    """Multiply K independent Fp2 pairs: (K, ..., 2, 24) → (K, ..., 2, 24),
    with all 3K limb products in one montmul call (Karatsuba)."""
    a0, a1 = A[..., 0, :], A[..., 1, :]
    b0, b1 = B[..., 0, :], B[..., 1, :]
    sa = L.add_mod(a0, a1)
    sb = L.add_mod(b0, b1)
    s = jnp.concatenate([a0, a1, sa], axis=0)
    t = jnp.concatenate([b0, b1, sb], axis=0)
    r = L.montmul(s, t)
    k = A.shape[0]
    r0, r1, r2 = r[:k], r[k : 2 * k], r[2 * k :]
    c0 = L.sub_mod(r0, r1)
    c1 = L.sub_mod(r2, L.add_mod(r0, r1))
    return jnp.stack([c0, c1], axis=-2)


def fp2_mul(a, b):
    a, b = jnp.broadcast_arrays(a, b)
    return fp2_mul_many(a[None], b[None])[0]


def fp2_sq_many(A):
    """Square K independent Fp2 elements with 2K limb products in one call."""
    a0, a1 = A[..., 0, :], A[..., 1, :]
    s = jnp.concatenate([L.add_mod(a0, a1), a0], axis=0)
    t = jnp.concatenate([L.sub_mod(a0, a1), a1], axis=0)
    r = L.montmul(s, t)
    k = A.shape[0]
    c0 = r[:k]
    c1 = r[k:]
    return jnp.stack([c0, L.add_mod(c1, c1)], axis=-2)


def fp2_sq(a):
    return fp2_sq_many(a[None])[0]


def fp2_scale(a, k):
    """Multiply Fp2 by an Fp scalar (shape broadcastable to (..., 24))."""
    kk = jnp.broadcast_to(k, a[..., 0, :].shape)
    r = L.montmul(jnp.stack([a[..., 0, :], a[..., 1, :]]), jnp.stack([kk, kk]))
    return jnp.stack([r[0], r[1]], axis=-2)


def fp2_conj(a):
    return jnp.stack([a[..., 0, :], L.neg_mod(a[..., 1, :])], axis=-2)


def fp2_mul_by_xi(a):
    """×(1+u): (c0 - c1, c0 + c1)."""
    a0, a1 = a[..., 0, :], a[..., 1, :]
    return jnp.stack([L.sub_mod(a0, a1), L.add_mod(a0, a1)], axis=-2)


def fp2_inv(a):
    a0, a1 = a[..., 0, :], a[..., 1, :]
    sq = L.montmul(jnp.stack([a0, a1]), jnp.stack([a0, a1]))
    norm = L.add_mod(sq[0], sq[1])
    ninv = L.inv_mod(norm)
    prod = L.montmul(jnp.stack([a0, L.neg_mod(a1)]), ninv[None])
    return jnp.stack([prod[0], prod[1]], axis=-2)


def fp2_is_zero(a):
    """Value-level zero test (digits are redundant; |value| < 4p required)."""
    return jnp.logical_and(
        L.is_zero_val(a[..., 0, :]), L.is_zero_val(a[..., 1, :])
    )


def fp2_select(cond, a, b):
    return jnp.where(cond[..., None, None], a, b)


def fp2_zero(shape=()):
    return jnp.zeros(shape + (2, NL), jnp.int32)


def fp2_one(shape=()):
    one = jnp.asarray(np.stack([L.ONE_MONT, L.ZERO]))
    return jnp.broadcast_to(one, shape + (2, NL)).astype(jnp.int32)


# --- Fp6 -------------------------------------------------------------------


def fp6_add(a, b):
    return L.add_mod(a, b)


def fp6_sub(a, b):
    return L.sub_mod(a, b)


def fp6_neg(a):
    return L.neg_mod(a)


def fp6_mul_many(A, B):
    """Multiply K independent Fp6 pairs: (K, ..., 3, 2, 24); all 18K limb
    products in one montmul call."""
    a0, a1, a2 = A[..., 0, :, :], A[..., 1, :, :], A[..., 2, :, :]
    b0, b1, b2 = B[..., 0, :, :], B[..., 1, :, :], B[..., 2, :, :]
    # the six Fp2 products per pair (schoolbook-Karatsuba hybrid)
    sums_a = L.add_mod(
        jnp.concatenate([a1, a0, a0], axis=0), jnp.concatenate([a2, a1, a2], axis=0)
    )
    sums_b = L.add_mod(
        jnp.concatenate([b1, b0, b0], axis=0), jnp.concatenate([b2, b1, b2], axis=0)
    )
    X = jnp.concatenate([a0, a1, a2, sums_a], axis=0)  # (6K, ..., 2, 24)
    Y = jnp.concatenate([b0, b1, b2, sums_b], axis=0)
    T = fp2_mul_many(X, Y)
    k = A.shape[0]
    t0, t1, t2 = T[:k], T[k : 2 * k], T[2 * k : 3 * k]
    t12, t01, t02 = T[3 * k : 4 * k], T[4 * k : 5 * k], T[5 * k :]
    # c0 = t0 + ξ(t12 - t1 - t2); c1 = (t01 - t0 - t1) + ξ t2; c2 = (t02 - t0 - t2) + t1
    d = L.sub_mod(
        jnp.concatenate([t12, t01, t02], axis=0),
        L.add_mod(
            jnp.concatenate([t1, t0, t0], axis=0),
            jnp.concatenate([t2, t1, t2], axis=0),
        ),
    )
    d0, d1, d2 = d[:k], d[k : 2 * k], d[2 * k :]
    xis = fp2_mul_by_xi(jnp.concatenate([d0, t2], axis=0))
    xi_d0, xi_t2 = xis[:k], xis[k:]
    c = L.add_mod(
        jnp.concatenate([t0, d1, d2], axis=0),
        jnp.concatenate([xi_d0, xi_t2, t1], axis=0),
    )
    return jnp.stack([c[:k], c[k : 2 * k], c[2 * k :]], axis=-3)


def fp6_mul(a, b):
    a, b = jnp.broadcast_arrays(a, b)
    return fp6_mul_many(a[None], b[None])[0]


def fp6_sq(a):
    return fp6_mul(a, a)


def fp6_mul_by_v(a):
    return jnp.stack(
        [fp2_mul_by_xi(a[..., 2, :, :]), a[..., 0, :, :], a[..., 1, :, :]], axis=-3
    )


def fp6_scale2(a, k):
    """Multiply Fp6 by an Fp2 scalar."""
    kk = jnp.broadcast_to(k, a[..., 0, :, :].shape)
    stacked = fp2_mul_many(
        jnp.stack([a[..., i, :, :] for i in range(3)]), jnp.stack([kk] * 3)
    )
    return jnp.stack([stacked[0], stacked[1], stacked[2]], axis=-3)


def fp6_inv(a):
    a0, a1, a2 = a[..., 0, :, :], a[..., 1, :, :], a[..., 2, :, :]
    sqs = fp2_sq_many(jnp.stack([a0, a2, a1]))
    prods = fp2_mul_many(jnp.stack([a1, a0, a0]), jnp.stack([a2, a1, a2]))
    A = fp2_sub(sqs[0], fp2_mul_by_xi(prods[0]))
    B = fp2_sub(fp2_mul_by_xi(sqs[1]), prods[1])
    C = fp2_sub(sqs[2], prods[2])
    inner = fp2_mul_many(jnp.stack([a0, a2, a1]), jnp.stack([A, B, C]))
    F = fp2_add(inner[0], fp2_mul_by_xi(fp2_add(inner[1], inner[2])))
    f_inv = fp2_inv(F)
    outs = fp2_mul_many(jnp.stack([A, B, C]), jnp.stack([f_inv] * 3))
    return jnp.stack([outs[0], outs[1], outs[2]], axis=-3)


def fp6_zero(shape=()):
    return jnp.zeros(shape + (3, 2, NL), jnp.int32)


def fp6_one(shape=()):
    z = np.zeros((3, 2, NL), dtype=np.uint32)
    z[0, 0] = L.ONE_MONT
    return jnp.broadcast_to(jnp.asarray(z), shape + (3, 2, NL)).astype(jnp.int32)


# --- Fp12 ------------------------------------------------------------------


def fp12_mul_many(A, B):
    """K independent Fp12 products: (K, ..., 2, 3, 2, 24); all 54K limb
    products in one montmul call (Karatsuba over Fp6)."""
    a0, a1 = A[..., 0, :, :, :], A[..., 1, :, :, :]
    b0, b1 = B[..., 0, :, :, :], B[..., 1, :, :, :]
    sa = L.add_mod(a0, a1)
    sb = L.add_mod(b0, b1)
    T = fp6_mul_many(
        jnp.concatenate([a0, a1, sa], axis=0), jnp.concatenate([b0, b1, sb], axis=0)
    )
    k = A.shape[0]
    t0, t1, t2 = T[:k], T[k : 2 * k], T[2 * k :]
    c0 = L.add_mod(t0, fp6_mul_by_v(t1))
    c1 = L.sub_mod(t2, L.add_mod(t0, t1))
    return jnp.stack([c0, c1], axis=-4)


def fp12_mul(a, b):
    a, b = jnp.broadcast_arrays(a, b)
    return fp12_mul_many(a[None], b[None])[0]


def fp12_sq(a):
    return fp12_mul(a, a)


def fp12_conj(a):
    return jnp.stack([a[..., 0, :, :, :], fp6_neg(a[..., 1, :, :, :])], axis=-4)


def fp12_inv(a):
    a0, a1 = a[..., 0, :, :, :], a[..., 1, :, :, :]
    sqs = fp6_mul_many(jnp.stack([a0, a1]), jnp.stack([a0, a1]))
    denom = fp6_inv(fp6_sub(sqs[0], fp6_mul_by_v(sqs[1])))
    outs = fp6_mul_many(jnp.stack([a0, fp6_neg(a1)]), jnp.stack([denom] * 2))
    return jnp.stack([outs[0], outs[1]], axis=-4)


def fp12_zero(shape=()):
    return jnp.zeros(shape + (2, 3, 2, NL), jnp.int32)


def fp12_one(shape=()):
    z = np.zeros((2, 3, 2, NL), dtype=np.uint32)
    z[0, 0, 0] = L.ONE_MONT
    return jnp.broadcast_to(jnp.asarray(z), shape + (2, 3, 2, NL)).astype(jnp.int32)


def fp12_select(cond, a, b):
    return jnp.where(cond[..., None, None, None, None], a, b)


def fp12_is_one(a):
    """Value-level equality with 1 (shared canonicalization ripple over the
    twelve Fp components)."""
    flat = a.reshape(a.shape[:-4] + (12, L.NLIMBS))
    one = fp12_one().reshape(12, L.NLIMBS)
    comp_zero = L.is_zero_val(flat - one)
    return jnp.all(comp_zero, axis=-1)


# --- Frobenius -------------------------------------------------------------

_coeffs = frobenius_coefficients()


def _fp2_const(pair) -> np.ndarray:
    return np.stack([L.to_mont(pair[0]), L.to_mont(pair[1])])


_G1_6 = jnp.asarray(_fp2_const(_coeffs["fq6_g1"]))
_G2_6 = jnp.asarray(_fp2_const(_coeffs["fq6_g2"]))
_GW_12 = jnp.asarray(_fp2_const(_coeffs["fq12_gw"]))


def fp6_frobenius(a):
    c0 = fp2_conj(a[..., 0, :, :])
    rest = fp2_mul_many(
        jnp.stack([fp2_conj(a[..., 1, :, :]), fp2_conj(a[..., 2, :, :])]),
        jnp.stack([jnp.broadcast_to(_G1_6, a[..., 1, :, :].shape),
                   jnp.broadcast_to(_G2_6, a[..., 2, :, :].shape)]),
    )
    return jnp.stack([c0, rest[0], rest[1]], axis=-3)


def fp12_frobenius(a):
    return jnp.stack(
        [
            fp6_frobenius(a[..., 0, :, :, :]),
            fp6_scale2(fp6_frobenius(a[..., 1, :, :, :]), _GW_12),
        ],
        axis=-4,
    )


def fp12_frobenius_n(a, n: int):
    for _ in range(n % 12):
        a = fp12_frobenius(a)
    return a


# --- host conversion helpers ----------------------------------------------


def fq2_to_dev(x) -> np.ndarray:
    """Anchor Fq2 → Montgomery limb array (2, 24)."""
    return np.stack([L.to_mont(x.c0.n), L.to_mont(x.c1.n)])


def fq6_to_dev(x) -> np.ndarray:
    return np.stack([fq2_to_dev(x.c0), fq2_to_dev(x.c1), fq2_to_dev(x.c2)])


def fq12_to_dev(x) -> np.ndarray:
    return np.stack([fq6_to_dev(x.c0), fq6_to_dev(x.c1)])


def dev_to_fq2(a):
    from grandine_tpu.crypto.fields import Fq2

    a = np.asarray(a)
    return Fq2.from_ints(L.from_mont(a[..., 0, :]), L.from_mont(a[..., 1, :]))


def dev_to_fq6(a):
    from grandine_tpu.crypto.fields import Fq6

    return Fq6(*[dev_to_fq2(np.asarray(a)[..., i, :, :]) for i in range(3)])


def dev_to_fq12(a):
    from grandine_tpu.crypto.fields import Fq12

    return Fq12(*[dev_to_fq6(np.asarray(a)[..., i, :, :, :]) for i in range(2)])
