"""Device field tower on limb-list elements.

Structure (nested tuples of limb-major arrays — JAX pytrees):
  Fp   one (26, *batch) int32 array (see limbs.py)
  Fp2  (c0, c1)            c0 + c1·u
  Fp6  (c0, c1, c2)        over Fp2, v³ = ξ = 1+u
  Fp12 (c0, c1)            over Fp6, w² = v

Every component array in one element shares one batch shape; functions
accept any batch shape, including stacked batch axes (axis 1).

Same tower and formulas as the anchor (grandine_tpu/crypto/fields.py); every
function is differentially tested against it. Frobenius coefficients are
imported from the anchor's derived values — a single source of truth.

The `*_many` variants take elements whose limb arrays carry a leading stack
axis of independent pairs and fold ALL their limb multiplications into a
single Montgomery-product call — one Fp12 multiplication is exactly one
54-wide montmul: fewer scan instances in the graph and fewer kernel
launches at runtime (the batch owns the vector lanes regardless — limbs.py
module docstring).

All products route through limbs.montmul — one implementation won on both
compile time and runtime (limbs.py module docstring).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from grandine_tpu.crypto.fields import frobenius_coefficients
from grandine_tpu.tpu import limbs as L

NL = L.NLIMBS


# --- lead/unlead helpers (add/remove a length-1 leading stack axis) --------


def lead_fp(a):
    return a[:, None]


def unlead_fp(a):
    return a[:, 0]


def lead2(a):
    return (lead_fp(a[0]), lead_fp(a[1]))


def unlead2(a):
    return (unlead_fp(a[0]), unlead_fp(a[1]))


def lead6(a):
    return tuple(lead2(c) for c in a)


def unlead6(a):
    return tuple(unlead2(c) for c in a)


def lead12(a):
    return tuple(lead6(c) for c in a)


def unlead12(a):
    return tuple(unlead6(c) for c in a)


def cat2(elems):
    """Concatenate Fp2 elements along the leading stack axis."""
    return (
        L.concat_fp([e[0] for e in elems]),
        L.concat_fp([e[1] for e in elems]),
    )


def slice2(a, lo, hi):
    return (L.index_fp(a[0], slice(lo, hi)), L.index_fp(a[1], slice(lo, hi)))


def take2(a, i):
    return (L.index_fp(a[0], i), L.index_fp(a[1], i))


def cat6(elems):
    return tuple(cat2([e[i] for e in elems]) for i in range(3))


def slice6(a, lo, hi):
    return tuple(slice2(c, lo, hi) for c in a)


def take6(a, i):
    return tuple(take2(c, i) for c in a)


# --- Fp2 -------------------------------------------------------------------


def fp2_add(a, b):
    return (L.add_mod(a[0], b[0]), L.add_mod(a[1], b[1]))


def fp2_sub(a, b):
    return (L.sub_mod(a[0], b[0]), L.sub_mod(a[1], b[1]))


def fp2_neg(a):
    return (L.neg_mod(a[0]), L.neg_mod(a[1]))


def fp2_double(a):
    return (L.double_mod(a[0]), L.double_mod(a[1]))


def fp2_mul_many(A, B):
    """Multiply K independent Fp2 pairs (leading stack axis K on every limb
    array) with all 3K limb products in one montmul call (Karatsuba)."""
    a0, a1 = A
    b0, b1 = B
    sa = L.add_mod(a0, a1)
    sb = L.add_mod(b0, b1)
    s = L.concat_fp([a0, a1, sa])
    t = L.concat_fp([b0, b1, sb])
    # The 20p working bound is not interval-derivable through Karatsuba
    # chains: each product's m·p/R reduction term lies in [0, p) and the
    # abstract interpreter must treat the terms as independent, so the
    # worst-case hull of c1 = r2 - r0 - r1 compounds across tower levels
    # (see tools/ranges/bounds.txt).  Theorem (a) — int32 digit safety —
    # is proven here unconditionally: relax bounds the digits regardless
    # of value growth, and montmul output values contract by p/R.
    r = L.montmul(s, t)  # lint: disable=limb-range
    k = a0.shape[1]
    r0 = L.index_fp(r, slice(0, k))
    r1 = L.index_fp(r, slice(k, 2 * k))
    r2 = L.index_fp(r, slice(2 * k, 3 * k))
    c0 = L.sub_mod(r0, r1)
    c1 = L.sub_mod(r2, L.add_mod(r0, r1))
    return (c0, c1)


def fp2_mul(a, b):
    return unlead2(fp2_mul_many(lead2(a), lead2(b)))


def fp2_pair_products(pairs):
    """Run the listed independent Fp2 products in ONE fused montmul call;
    pairs = [(x, y), …] of same-batch Fp2 elements. The shared fusion helper
    behind the curve formulas and the Miller-loop steps."""
    A = cat2([lead2(x) for x, _ in pairs])
    B = cat2([lead2(y) for _, y in pairs])
    T = fp2_mul_many(A, B)
    return [unlead2(slice2(T, i, i + 1)) for i in range(len(pairs))]


def fp2_sq_many(A):
    """Square K independent Fp2 elements with 2K limb products in one call:
    (a0+a1)(a0-a1) and a0·a1."""
    a0, a1 = A
    s = L.concat_fp([L.add_mod(a0, a1), a0])
    t = L.concat_fp([L.sub_mod(a0, a1), a1])
    # Same working-bound caveat as fp2_mul_many; theorem (a) is proven.
    r = L.montmul(s, t)  # lint: disable=limb-range
    k = a0.shape[1]
    c0 = L.index_fp(r, slice(0, k))
    c1 = L.index_fp(r, slice(k, 2 * k))
    return (c0, L.double_mod(c1))


def fp2_sq(a):
    return unlead2(fp2_sq_many(lead2(a)))


def fp2_scale(a, k):
    """Multiply Fp2 by an Fp scalar (broadcastable batch shapes)."""
    kk = jnp.broadcast_to(k, a[0].shape)
    r = L.montmul(L.stack_fp([a[0], a[1]]), L.stack_fp([kk, kk]))
    parts = L.unstack_fp(r, 2)
    return (parts[0], parts[1])


def fp2_conj(a):
    return (a[0], L.neg_mod(a[1]))


def fp2_mul_by_xi(a):
    """×(1+u): (c0 - c1, c0 + c1)."""
    return (L.sub_mod(a[0], a[1]), L.add_mod(a[0], a[1]))


def fp2_inv(a):
    a0, a1 = a
    sq = L.montmul(L.stack_fp([a0, a1]), L.stack_fp([a0, a1]))
    sqs = L.unstack_fp(sq, 2)
    norm = L.add_mod(sqs[0], sqs[1])
    ninv = L.inv_mod(norm)
    prod = L.montmul(
        L.stack_fp([a0, L.neg_mod(a1)]), L.stack_fp([ninv, ninv])
    )
    parts = L.unstack_fp(prod, 2)
    return (parts[0], parts[1])


def fp2_is_zero(a):
    """Value-level zero test (digits are redundant; |value| < 8p required)."""
    return jnp.logical_and(L.is_zero_val(a[0]), L.is_zero_val(a[1]))


def fp2_is_zero_many(elems) -> list:
    """Zero tests for K same-shape Fp2 elements in one canonicalization
    pass (both components of every element share one stacked scan)."""
    flat = [c for e in elems for c in (e[0], e[1])]
    # Worst-case interval hulls of Fp2 chain values reach ~14p vs. the
    # 8p zero-test precondition (independent m·p/R terms; see
    # tools/ranges/bounds.txt).  Callers keep real operands in range:
    # the tests consume differences of fresh Montgomery products, each
    # in (-0.1p, 2p).
    z = L.is_zero_val_many(flat)  # lint: disable=limb-range
    return [
        jnp.logical_and(z[2 * i], z[2 * i + 1]) for i in range(len(elems))
    ]


def fp2_select(cond, a, b):
    return (L.select(cond, a[0], b[0]), L.select(cond, a[1], b[1]))


def fp2_zero(shape=()):
    return (L.zeros_fp(shape), L.zeros_fp(shape))


def fp2_one(shape=()):
    return (L.const_fp(L.ONE_MONT_DIGITS, shape), L.zeros_fp(shape))


# --- Fp6 -------------------------------------------------------------------


def fp6_add(a, b):
    return tuple(fp2_add(x, y) for x, y in zip(a, b))


def fp6_sub(a, b):
    return tuple(fp2_sub(x, y) for x, y in zip(a, b))


def fp6_neg(a):
    return tuple(fp2_neg(x) for x in a)


def fp6_mul_many(A, B):
    """Multiply K independent Fp6 pairs (leading stack axis K); all 18K limb
    products in one montmul call (schoolbook-Karatsuba hybrid)."""
    a0, a1, a2 = A
    b0, b1, b2 = B
    sums_a = cat2([fp2_add(a1, a2), fp2_add(a0, a1), fp2_add(a0, a2)])
    sums_b = cat2([fp2_add(b1, b2), fp2_add(b0, b1), fp2_add(b0, b2)])
    X = cat2([a0, a1, a2, sums_a])  # (6K, ...)
    Y = cat2([b0, b1, b2, sums_b])
    T = fp2_mul_many(X, Y)
    k = a0[0].shape[1]
    t0 = slice2(T, 0, k)
    t1 = slice2(T, k, 2 * k)
    t2 = slice2(T, 2 * k, 3 * k)
    t12 = slice2(T, 3 * k, 4 * k)
    t01 = slice2(T, 4 * k, 5 * k)
    t02 = slice2(T, 5 * k, 6 * k)
    # c0 = t0 + ξ(t12 - t1 - t2); c1 = (t01 - t0 - t1) + ξ t2;
    # c2 = (t02 - t0 - t2) + t1
    c0 = fp2_add(t0, fp2_mul_by_xi(fp2_sub(t12, fp2_add(t1, t2))))
    c1 = fp2_add(fp2_sub(t01, fp2_add(t0, t1)), fp2_mul_by_xi(t2))
    c2 = fp2_add(fp2_sub(t02, fp2_add(t0, t2)), t1)
    return (c0, c1, c2)


def fp6_mul(a, b):
    return unlead6(fp6_mul_many(lead6(a), lead6(b)))


def fp6_sq(a):
    return fp6_mul(a, a)


def fp6_mul_by_v(a):
    return (fp2_mul_by_xi(a[2]), a[0], a[1])


def fp6_scale2(a, k):
    """Multiply Fp6 by an Fp2 scalar."""
    X = cat2([lead2(a[0]), lead2(a[1]), lead2(a[2])])
    Y = cat2([lead2(k)] * 3)
    r = fp2_mul_many(X, Y)
    return tuple(unlead2(slice2(r, i, i + 1)) for i in range(3))


def fp6_inv(a):
    a0, a1, a2 = a
    sqs = fp2_sq_many(cat2([lead2(a0), lead2(a2), lead2(a1)]))
    sq0 = unlead2(slice2(sqs, 0, 1))
    sq2 = unlead2(slice2(sqs, 1, 2))
    sq1 = unlead2(slice2(sqs, 2, 3))
    prods = fp2_mul_many(
        cat2([lead2(a1), lead2(a0), lead2(a0)]),
        cat2([lead2(a2), lead2(a1), lead2(a2)]),
    )
    p12 = unlead2(slice2(prods, 0, 1))
    p01 = unlead2(slice2(prods, 1, 2))
    p02 = unlead2(slice2(prods, 2, 3))
    A = fp2_sub(sq0, fp2_mul_by_xi(p12))
    B = fp2_sub(fp2_mul_by_xi(sq2), p01)
    C = fp2_sub(sq1, p02)
    inner = fp2_mul_many(
        cat2([lead2(a0), lead2(a2), lead2(a1)]),
        cat2([lead2(A), lead2(B), lead2(C)]),
    )
    i0 = unlead2(slice2(inner, 0, 1))
    i1 = unlead2(slice2(inner, 1, 2))
    i2 = unlead2(slice2(inner, 2, 3))
    Fv = fp2_add(i0, fp2_mul_by_xi(fp2_add(i1, i2)))
    f_inv = fp2_inv(Fv)
    outs = fp2_mul_many(
        cat2([lead2(A), lead2(B), lead2(C)]),
        cat2([lead2(f_inv)] * 3),
    )
    return tuple(unlead2(slice2(outs, i, i + 1)) for i in range(3))


def fp6_zero(shape=()):
    return tuple(fp2_zero(shape) for _ in range(3))


def fp6_one(shape=()):
    return (fp2_one(shape), fp2_zero(shape), fp2_zero(shape))


# --- Fp12 ------------------------------------------------------------------


def fp12_mul_many(A, B):
    """K independent Fp12 products (leading stack axis K); all 54K limb
    products in one montmul call (Karatsuba over Fp6)."""
    a0, a1 = A
    b0, b1 = B
    sa = fp6_add(a0, a1)
    sb = fp6_add(b0, b1)
    T = fp6_mul_many(cat6([a0, a1, sa]), cat6([b0, b1, sb]))
    k = a0[0][0].shape[1]
    t0 = slice6(T, 0, k)
    t1 = slice6(T, k, 2 * k)
    t2 = slice6(T, 2 * k, 3 * k)
    c0 = fp6_add(t0, fp6_mul_by_v(t1))
    c1 = fp6_sub(t2, fp6_add(t0, t1))
    return (c0, c1)


def fp12_mul(a, b):
    return unlead12(fp12_mul_many(lead12(a), lead12(b)))


def fp12_sq(a):
    return fp12_mul(a, a)


def fp12_conj(a):
    return (a[0], fp6_neg(a[1]))


def fp12_inv(a):
    a0, a1 = a
    sqs = fp6_mul_many(cat6([lead6(a0), lead6(a1)]),
                       cat6([lead6(a0), lead6(a1)]))
    sq0 = unlead6(slice6(sqs, 0, 1))
    sq1 = unlead6(slice6(sqs, 1, 2))
    denom = fp6_inv(fp6_sub(sq0, fp6_mul_by_v(sq1)))
    outs = fp6_mul_many(
        cat6([lead6(a0), lead6(fp6_neg(a1))]),
        cat6([lead6(denom)] * 2),
    )
    return (unlead6(slice6(outs, 0, 1)), unlead6(slice6(outs, 1, 2)))


def fp12_zero(shape=()):
    return (fp6_zero(shape), fp6_zero(shape))


def fp12_one(shape=()):
    return (fp6_one(shape), fp6_zero(shape))


def fp12_select(cond, a, b):
    return tuple(
        tuple(fp2_select(cond, x, y) for x, y in zip(c6a, c6b))
        for c6a, c6b in zip(a, b)
    )


def fp12_components(a):
    """Flat list of the twelve Fp components."""
    return [fp for c6 in a for c2 in c6 for fp in c2]


def fp12_from_components(comps):
    it = iter(comps)
    return tuple(
        tuple((next(it), next(it)) for _ in range(3)) for _ in range(2)
    )


def fp12_is_one(a):
    """Value-level equality with 1 (component-wise canonical zero tests)."""
    comps = fp12_components(a)
    ones = fp12_components(fp12_one(comps[0].shape[1:]))
    ok = None
    for fa, fo in zip(comps, ones):
        z = L.is_zero_val(fa - fo)
        ok = z if ok is None else (ok & z)
    return ok


# --- Frobenius -------------------------------------------------------------

_coeffs = frobenius_coefficients()


def _fp2_const(pair, shape=()):
    return (
        L.const_fp([int(d) for d in L.to_mont(pair[0])], shape),
        L.const_fp([int(d) for d in L.to_mont(pair[1])], shape),
    )


def fp6_frobenius(a):
    shape = a[0][0].shape[1:]
    g1 = _fp2_const(_coeffs["fq6_g1"], shape)
    g2 = _fp2_const(_coeffs["fq6_g2"], shape)
    c0 = fp2_conj(a[0])
    rest = fp2_mul_many(
        cat2([lead2(fp2_conj(a[1])), lead2(fp2_conj(a[2]))]),
        cat2([lead2(g1), lead2(g2)]),
    )
    r1 = unlead2(slice2(rest, 0, 1))
    r2 = unlead2(slice2(rest, 1, 2))
    return (c0, r1, r2)


def fp12_frobenius(a):
    shape = a[0][0][0].shape[1:]
    gw = _fp2_const(_coeffs["fq12_gw"], shape)
    return (
        fp6_frobenius(a[0]),
        fp6_scale2(fp6_frobenius(a[1]), gw),
    )


def fp12_frobenius_n(a, n: int):
    for _ in range(n % 12):
        a = fp12_frobenius(a)
    return a


# --- host conversion helpers ----------------------------------------------
#
# Rest format (host numpy): Fp (..., 26); Fp2 (..., 2, 26); Fp6 (..., 3, 2, 26);
# Fp12 (..., 2, 3, 2, 26) — unchanged from the array-form design, so all host
# prep, caching, and serialization code is layout-agnostic.


def fq2_to_dev(x) -> np.ndarray:
    """Anchor Fq2 → Montgomery limb array (2, 26) (rest format)."""
    return np.stack([L.to_mont(x.c0.n), L.to_mont(x.c1.n)])


def fq6_to_dev(x) -> np.ndarray:
    return np.stack([fq2_to_dev(x.c0), fq2_to_dev(x.c1), fq2_to_dev(x.c2)])


def fq12_to_dev(x) -> np.ndarray:
    return np.stack([fq6_to_dev(x.c0), fq6_to_dev(x.c1)])


def fp2_split(arr) -> tuple:
    """(..., 2, 26) rest-format array → Fp2 limb-list element."""
    return (L.split(arr[..., 0, :]), L.split(arr[..., 1, :]))


def fp2_merge(a) -> jnp.ndarray:
    """Fp2 limb-list element → (..., 2, 26) rest-format device array."""
    return jnp.stack([L.merge(a[0]), L.merge(a[1])], axis=-2)


def fp2_merge_np(a) -> np.ndarray:
    return np.stack([L.merge_np(a[0]), L.merge_np(a[1])], axis=-2)


def fp6_split(arr) -> tuple:
    return tuple(fp2_split(arr[..., i, :, :]) for i in range(3))


def fp6_merge_np(a) -> np.ndarray:
    return np.stack([fp2_merge_np(c2) for c2 in a], axis=-3)


def fp12_split(arr) -> tuple:
    return tuple(fp6_split(arr[..., i, :, :, :]) for i in range(2))


def fp12_merge_np(a) -> np.ndarray:
    return np.stack(
        [
            np.stack([fp2_merge_np(c2) for c2 in c6], axis=-3)
            for c6 in a
        ],
        axis=-4,
    )


def dev_to_fq2(a):
    from grandine_tpu.crypto.fields import Fq2

    a = np.asarray(a)
    return Fq2.from_ints(L.from_mont(a[..., 0, :]), L.from_mont(a[..., 1, :]))


def dev_to_fq6(a):
    from grandine_tpu.crypto.fields import Fq6

    return Fq6(*[dev_to_fq2(np.asarray(a)[..., i, :, :]) for i in range(3)])


def dev_to_fq12(a):
    from grandine_tpu.crypto.fields import Fq12

    return Fq12(*[dev_to_fq6(np.asarray(a)[..., i, :, :, :]) for i in range(2)])


# --- batched square roots (compressed-point decompression) -----------------
#
# Fixed-exponent ladders only: p ≡ 3 (mod 4) so √a = a^((p+1)/4) in Fq, and
# Fq2 roots come from the norm/half trick mirroring the anchor's Fq2.sqrt
# (crypto/fields.py). Both candidates of every data-dependent branch are
# computed and select()ed — no host-visible control flow, so one jit trace
# serves every batch and the shapes stay manifest-bucketable. Which square
# root (y vs −y) comes back is NOT pinned down here; decompression applies
# the compression sign bit afterwards, which collapses the ambiguity.

_SQRT_EXP = (L.P + 1) // 4
_LEGENDRE_EXP = (L.P - 1) // 2
_HALF_DIGITS = [int(x) for x in L.to_mont((L.P + 1) // 2)]


def fq_is_square(a) -> jnp.ndarray:
    """Legendre mask: value(a) is a QR mod p (0 counts as square).
    Montgomery in; bool array of the batch shape out."""
    ls = L.pow_fixed(a, _LEGENDRE_EXP)
    is_one, is_zero = L.is_zero_val_many(
        [ls - L.const_fp(L.ONE_MONT_DIGITS, a.shape[1:]), a]
    )
    return is_one | is_zero


def fq_sqrt(a) -> "tuple[jnp.ndarray, jnp.ndarray]":
    """(root, ok): root = a^((p+1)/4), ok ⇔ root² ≡ a (⇔ a is a QR).
    Montgomery in/out; either square root may come back."""
    s = L.pow_fixed(a, _SQRT_EXP)
    ok = L.is_zero_val(L.montsq(s) - a)
    return s, ok


def fq2_sqrt(a) -> "tuple[tuple, jnp.ndarray]":
    """((c0, c1), ok) batched Fq2 square root, mirroring the anchor's
    norm/half algorithm (crypto/fields.py Fq2.sqrt) with every branch
    flattened into selects.

    For x = a + b·u: norm = a² + b² must be a QR in Fq (else no root);
    with s = √norm, one of t² = (a ± s)/2 admits t ≠ 0 with candidate
    (t, b/(2t)); the b = 0 embedding takes (√a, 0) or (0, √−a). ok is
    the per-item solvability mask (False ⇔ non-residue)."""
    ca, cb = a
    batch = ca.shape[1:]
    half = L.const_fp(_HALF_DIGITS, batch)
    # ladder 1 (stacked): √a, √−a (b==0 embedding), √norm (general path)
    norm = L.add_mod(L.montsq(ca), L.montsq(cb))
    r1 = L.stack_fp([ca, L.neg_mod(ca), norm])
    s1 = L.pow_fixed(r1, _SQRT_EXP)
    ok1 = L.is_zero_val(L.montsq(s1) - r1)
    sa, sna, sn = (s1[:, i] for i in range(3))
    ok_a, ok_na, ok_n = (ok1[i] for i in range(3))
    # ladder 2 (stacked): t = √((a ± s)/2), both signs of s
    t2_pos = L.montmul(L.add_mod(ca, sn), half)
    t2_neg = L.montmul(L.sub_mod(ca, sn), half)
    r2 = L.stack_fp([t2_pos, t2_neg])
    s2 = L.pow_fixed(r2, _SQRT_EXP)
    ok2 = L.is_zero_val(L.montsq(s2) - r2) & ~L.is_zero_val(s2)
    # ladder 3 (stacked): 1/(2t) for both candidates (inv_mod(0) = 0)
    inv2t = L.inv_mod(L.double_mod(s2))
    c1_both = L.montmul(L.stack_fp([cb, cb]), inv2t)
    # verify each candidate squares back to the input (the anchor's
    # acceptance test) — guards the t = 0 / wrong-sign corners
    sq0 = L.montsq(s2) - L.montsq(c1_both)
    sq1 = L.double_mod(L.montmul(s2, c1_both))
    cand_ok = ok2 & (
        L.is_zero_val(sq0 - L.stack_fp([ca, ca]))
        & L.is_zero_val(sq1 - L.stack_fp([cb, cb]))
    )
    use_pos = cand_ok[0]
    gen_c0 = L.select(use_pos, s2[:, 0], s2[:, 1])
    gen_c1 = L.select(use_pos, c1_both[:, 0], c1_both[:, 1])
    gen_ok = ok_n & (cand_ok[0] | cand_ok[1])
    # b == 0 embedding: (√a, 0) when a is a QR, else (0, √−a)
    zero = L.zeros_fp(batch)
    emb_c0 = L.select(ok_a, sa, zero)
    emb_c1 = L.select(ok_a, zero, sna)
    emb_ok = ok_a | ok_na
    b_zero = L.is_zero_val(cb)
    c0 = L.select(b_zero, emb_c0, gen_c0)
    c1 = L.select(b_zero, emb_c1, gen_c1)
    ok = jnp.where(b_zero, emb_ok, gen_ok)
    return (c0, c1), ok
