"""Key-value database abstraction — reference: database/src/lib.rs
(`Database::{persistent, in_memory}` :21-70: libmdbx env or `im::OrdMap`,
snappy value compression, prefix iteration).

Backends:
  Database.in_memory()        — sorted dict (tests, light nodes)
  Database.persistent(path)   — sqlite3 B-tree, WAL mode

Values are snappy-framed (the in-tree codec) like the reference's
compressed puts; keys are raw bytes ordered lexicographically.
"""

from __future__ import annotations

import bisect
import sqlite3
import threading
from typing import Iterator, Optional, Tuple

from grandine_tpu.spec_tests.snappy import frame_compress, frame_decompress


class Database:
    """Interface; construct via `in_memory()` / `persistent(path)`."""

    @staticmethod
    def in_memory() -> "Database":
        return _MemoryDatabase()

    @staticmethod
    def persistent(path: str) -> "Database":
        return _SqliteDatabase(path)

    # -- operations --------------------------------------------------------

    def get(self, key: bytes) -> "Optional[bytes]":
        raise NotImplementedError

    def put(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def put_batch(self, items) -> None:
        for k, v in items:
            self.put(k, v)

    def delete(self, key: bytes) -> None:
        raise NotImplementedError

    def contains(self, key: bytes) -> bool:
        return self.get(key) is not None

    def iterate_prefix(
        self, prefix: bytes
    ) -> "Iterator[Tuple[bytes, bytes]]":
        """(key, value) pairs with `prefix`, ascending by key."""
        raise NotImplementedError

    def prev(self, prefix: bytes, upto: bytes) -> "Optional[Tuple[bytes, bytes]]":
        """Greatest key <= prefix+upto that still starts with `prefix`
        (the reference's cursor-prev lookups for 'latest at or before').
        Backends override with an indexed reverse lookup — the default
        would decode every value under the prefix."""
        best = None
        limit = prefix + upto
        for k, v in self.iterate_prefix(prefix):
            if k <= limit:
                best = (k, v)
            else:
                break
        return best

    def close(self) -> None:
        pass


def _prefix_upper_bound(prefix: bytes) -> "Optional[bytes]":
    """Smallest byte string greater than every key with `prefix`
    (None when the prefix is all 0xff)."""
    b = bytearray(prefix)
    for i in reversed(range(len(b))):
        if b[i] != 0xFF:
            b[i] += 1
            return bytes(b[: i + 1])
    return None


class _MemoryDatabase(Database):
    def __init__(self) -> None:
        self._data: "dict[bytes, bytes]" = {}
        self._keys: "list[bytes]" = []
        self._lock = threading.Lock()

    def get(self, key: bytes) -> "Optional[bytes]":
        with self._lock:
            v = self._data.get(bytes(key))
        return None if v is None else frame_decompress(v)

    def put(self, key: bytes, value: bytes) -> None:
        key = bytes(key)
        with self._lock:
            if key not in self._data:
                bisect.insort(self._keys, key)
            self._data[key] = frame_compress(bytes(value))

    def delete(self, key: bytes) -> None:
        key = bytes(key)
        with self._lock:
            if key in self._data:
                del self._data[key]
                i = bisect.bisect_left(self._keys, key)
                del self._keys[i]

    def iterate_prefix(self, prefix: bytes):
        prefix = bytes(prefix)
        with self._lock:
            start = bisect.bisect_left(self._keys, prefix)
            keys = self._keys[start:]
        for k in keys:
            if not k.startswith(prefix):
                break
            v = self.get(k)
            if v is not None:
                yield k, v

    def prev(self, prefix: bytes, upto: bytes):
        """Bisect on the sorted key list; only the hit is decompressed."""
        prefix = bytes(prefix)
        limit = prefix + bytes(upto)
        with self._lock:
            i = bisect.bisect_right(self._keys, limit) - 1
            key = self._keys[i] if 0 <= i < len(self._keys) else None
        if key is None or not key.startswith(prefix):
            return None
        v = self.get(key)
        return None if v is None else (key, v)


class _SqliteDatabase(Database):
    def __init__(self, path: str) -> None:
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv"
                " (key BLOB PRIMARY KEY, value BLOB NOT NULL)"
            )
            self._conn.commit()

    def get(self, key: bytes) -> "Optional[bytes]":
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM kv WHERE key = ?", (bytes(key),)
            ).fetchone()
        return None if row is None else frame_decompress(row[0])

    def put(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO kv (key, value) VALUES (?, ?)",
                (bytes(key), frame_compress(bytes(value))),
            )
            self._conn.commit()

    def put_batch(self, items) -> None:
        with self._lock:
            self._conn.executemany(
                "INSERT OR REPLACE INTO kv (key, value) VALUES (?, ?)",
                [(bytes(k), frame_compress(bytes(v))) for k, v in items],
            )
            self._conn.commit()

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE key = ?", (bytes(key),))
            self._conn.commit()

    def iterate_prefix(self, prefix: bytes):
        prefix = bytes(prefix)
        upper = _prefix_upper_bound(prefix)
        with self._lock:
            if upper is None:
                rows = self._conn.execute(
                    "SELECT key, value FROM kv WHERE key >= ?"
                    " ORDER BY key ASC",
                    (prefix,),
                ).fetchall()
            else:
                rows = self._conn.execute(
                    "SELECT key, value FROM kv WHERE key >= ? AND key < ?"
                    " ORDER BY key ASC",
                    (prefix, upper),
                ).fetchall()
        for k, v in rows:
            if bytes(k).startswith(prefix):
                yield bytes(k), frame_decompress(v)

    def prev(self, prefix: bytes, upto: bytes):
        """One indexed reverse lookup; only the hit is decompressed."""
        prefix = bytes(prefix)
        limit = prefix + bytes(upto)
        with self._lock:
            row = self._conn.execute(
                "SELECT key, value FROM kv WHERE key >= ? AND key <= ?"
                " ORDER BY key DESC LIMIT 1",
                (prefix, limit),
            ).fetchone()
        if row is None or not bytes(row[0]).startswith(prefix):
            return None
        return bytes(row[0]), frame_decompress(row[1])

    def close(self) -> None:
        with self._lock:
            self._conn.close()


__all__ = ["Database"]
