"""Storage — reference: `database` crate (libmdbx or in-memory OrdMap,
database/src/lib.rs:21-70, snappy-compressed values, prefix iteration) and
`fork_choice_control::storage` (persistence schema, archival states,
checkpoint load, storage.rs:769-868).

Here: a `Database` interface with in-memory and sqlite3 backends (sqlite is
the stdlib's battle-tested B-tree — the mdbx role), values snappy-framed
with the in-tree codec, and a `Storage` schema layer handling finalized
chain persistence, periodic archival states, and anchor load for restart /
checkpoint sync.
"""

from grandine_tpu.storage.database import Database  # noqa: F401
from grandine_tpu.storage.storage import StateLoadStrategy, Storage  # noqa: F401
