"""Persistence schema + anchor loading — reference:
fork_choice_control/src/storage.rs (schema :769-868: `cstate2`/`cblock`
anchor keys, per-root block/state prefixes, slot indexes, archival states
every DEFAULT_ARCHIVAL_EPOCH_INTERVAL=32 epochs :37) and
checkpoint_sync.rs / `StateLoadStrategy` (:39).

Schema (all values SSZ, snappy-framed by the Database layer):
  b"cstate"            anchor (latest persisted finalized) state
  b"cblock"            anchor block
  b"b" + root          finalized signed block by root
  b"s" + slot_be8      finalized block root by slot (canonical index)
  b"t" + slot_be8      archival state by slot (every archival interval)
  b"u" + root          unfinalized signed block (replayed into the store
                       on restart, mutator.process_unfinalized_blocks)
  b"meta:slot"         latest persisted finalized slot (u64 LE)
"""

from __future__ import annotations

import enum
from typing import Callable, Optional

from grandine_tpu.storage.database import Database
from grandine_tpu.types.combined import decode_signed_block, decode_state

DEFAULT_ARCHIVAL_EPOCH_INTERVAL = 32

KEY_ANCHOR_STATE = b"cstate"
KEY_ANCHOR_BLOCK = b"cblock"
KEY_GENESIS_STATE = b"gstate"
PREFIX_BLOCK = b"b"
PREFIX_SLOT_INDEX = b"s"
PREFIX_ARCHIVAL_STATE = b"t"
PREFIX_UNFINALIZED = b"u"
KEY_LATEST_SLOT = b"meta:slot"


class StateLoadStrategy(enum.Enum):
    AUTO = "auto"          # local DB if present, else anchor source
    ANCHOR = "anchor"      # provided genesis/anchor state
    REMOTE = "remote"      # checkpoint sync via a fetcher


def _slot_key(prefix: bytes, slot: int) -> bytes:
    return prefix + int(slot).to_bytes(8, "big")


class Storage:
    def __init__(
        self,
        database: Database,
        cfg,
        archival_epoch_interval: int = DEFAULT_ARCHIVAL_EPOCH_INTERVAL,
    ) -> None:
        self.db = database
        self.cfg = cfg
        self.archival_epoch_interval = archival_epoch_interval

    # ------------------------------------------------------------- persist

    def persist_anchor(self, state, signed_block=None) -> None:
        self.db.put(KEY_ANCHOR_STATE, state.serialize())
        if signed_block is not None:
            self.db.put(KEY_ANCHOR_BLOCK, signed_block.serialize())
        # the FIRST anchor (genesis / checkpoint start) is kept forever so
        # `replay` has a state to replay the finalized chain from
        if self.db.get(KEY_GENESIS_STATE) is None:
            self.db.put(KEY_GENESIS_STATE, state.serialize())

    def persist_unfinalized_block(self, root: bytes, signed_block) -> None:
        """Every applied block is persisted immediately (the reference
        stores blocks on insertion; restart replays them)."""
        if hasattr(signed_block, "serialize"):
            self.db.put(PREFIX_UNFINALIZED + bytes(root), signed_block.serialize())

    def persist_finalized_chain(self, store) -> None:
        """Persist everything at or below the store's finalized checkpoint
        and refresh the anchor to the finalized state (called by the
        controller after finality advances)."""
        p = self.cfg.preset
        fin_root = bytes(store.finalized_checkpoint.root)
        node = store.blocks.get(fin_root)
        if node is None:
            return
        items = []
        # walk the finalized chain down to what we already persisted
        latest = self.latest_persisted_slot()
        cursor = node
        while cursor is not None and cursor.slot > latest:
            signed = cursor.signed_block
            if hasattr(signed, "serialize"):
                raw = signed.serialize()
                items.append((PREFIX_BLOCK + cursor.root, raw))
                items.append(
                    (_slot_key(PREFIX_SLOT_INDEX, cursor.slot), cursor.root)
                )
            cursor = store.blocks.get(cursor.parent_root)
        if items:
            self.db.put_batch(items)
        self.db.put(KEY_LATEST_SLOT, int(node.slot).to_bytes(8, "little"))
        self.persist_anchor(
            node.state,
            node.signed_block if hasattr(node.signed_block, "serialize") else None,
        )
        # archival state every N epochs
        epoch = node.slot // p.SLOTS_PER_EPOCH
        if epoch % self.archival_epoch_interval == 0:
            self.db.put(
                _slot_key(PREFIX_ARCHIVAL_STATE, node.slot),
                node.state.serialize(),
            )
        # unfinalized set: everything above finality, for restart replay
        for root, n in store.blocks.items():
            if n.slot > node.slot and hasattr(n.signed_block, "serialize"):
                self.db.put(
                    PREFIX_UNFINALIZED + root, n.signed_block.serialize()
                )
        self._prune_unfinalized(node.slot, store)

    def _prune_unfinalized(self, finalized_slot: int, store) -> None:
        for key, raw in list(self.db.iterate_prefix(PREFIX_UNFINALIZED)):
            root = key[len(PREFIX_UNFINALIZED) :]
            if root in store.blocks and store.blocks[root].slot > finalized_slot:
                continue
            self.db.delete(key)

    # --------------------------------------------------------------- loads

    def latest_persisted_slot(self) -> int:
        raw = self.db.get(KEY_LATEST_SLOT)
        return int.from_bytes(raw, "little") if raw else -1

    def load_anchor_state(self):
        raw = self.db.get(KEY_ANCHOR_STATE)
        return None if raw is None else decode_state(raw, self.cfg)

    def load_genesis_state(self):
        raw = self.db.get(KEY_GENESIS_STATE)
        return None if raw is None else decode_state(raw, self.cfg)

    def load_unfinalized_blocks(self) -> list:
        """Unfinalized blocks sorted by slot (restart replay order —
        controller feeds them back through validation)."""
        out = []
        for _key, raw in self.db.iterate_prefix(PREFIX_UNFINALIZED):
            out.append(decode_signed_block(raw, self.cfg))
        out.sort(key=lambda b: int(b.message.slot))
        return out

    def finalized_block_by_root(self, root: bytes):
        raw = self.db.get(PREFIX_BLOCK + bytes(root))
        return None if raw is None else decode_signed_block(raw, self.cfg)

    def finalized_root_by_slot(self, slot: int) -> "Optional[bytes]":
        return self.db.get(_slot_key(PREFIX_SLOT_INDEX, slot))

    def archival_state_at_or_before(self, slot: int):
        hit = self.db.prev(
            PREFIX_ARCHIVAL_STATE, int(slot).to_bytes(8, "big")
        )
        return None if hit is None else decode_state(hit[1], self.cfg)

    # ------------------------------------------------------ anchor sources

    def load(
        self,
        strategy: StateLoadStrategy = StateLoadStrategy.AUTO,
        anchor_state=None,
        fetcher: "Optional[Callable[[str], bytes]]" = None,
    ):
        """Resolve the anchor state (reference StateLoadStrategy::{Auto,
        Anchor, Remote}): local DB first under AUTO, explicit state under
        ANCHOR, `fetcher('finalized_state')` bytes under REMOTE
        (checkpoint sync — the fetcher is the injected HTTP boundary).
        Returns (state, unfinalized_blocks)."""
        if strategy == StateLoadStrategy.ANCHOR:
            if anchor_state is None:
                raise ValueError("ANCHOR strategy requires anchor_state")
            return anchor_state, []
        if strategy == StateLoadStrategy.REMOTE:
            if fetcher is None:
                raise ValueError("REMOTE strategy requires a fetcher")
            state = decode_state(fetcher("finalized_state"), self.cfg)
            self.persist_anchor(state)
            return state, []
        stored = self.load_anchor_state()
        if stored is not None:
            return stored, self.load_unfinalized_blocks()
        if anchor_state is None:
            raise ValueError("no stored anchor and no anchor_state given")
        self.persist_anchor(anchor_state)
        return anchor_state, []


__all__ = ["Storage", "StateLoadStrategy", "DEFAULT_ARCHIVAL_EPOCH_INTERVAL"]
