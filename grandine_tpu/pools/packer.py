"""Attestation packing beyond greedy: max-clique pre-merge + exact
branch-and-bound selection.

Reference: operation_pools/src/attestation_packer.rs (ILP via HiGHS with a
greedy fallback) + max_clique.rs (Bron-Kerbosch). Same two phases here,
with the ILP replaced by a bounded branch-and-bound over the (small,
pool-frontier) candidate set — exact on real pool shapes, never worse
than greedy (the greedy solution seeds the incumbent), and dependency-free.

Phase 1 — max-clique merge: aggregates with IDENTICAL AttestationData and
pairwise-DISJOINT aggregation bits can be merged into one aggregate
(union bits, aggregated signature). Maximal cliques of the disjointness
graph yield the widest mergeable super-aggregates (max_clique.rs's role).

Phase 2 — selection: pick ≤ max_count aggregates maximizing the number of
distinct (committee, bit) inclusions — weighted max-coverage under a
cardinality constraint. Greedy is only (1−1/e)-optimal; the reference
bought exactness with an ILP, this module with DFS branch-and-bound using
the top-k residual bound, capped at `node_budget` expansions (fallback =
incumbent, which starts at greedy).
"""

from __future__ import annotations

from typing import Callable, Sequence


def bron_kerbosch_disjoint(
    bitsets: "Sequence[frozenset]", max_cliques: int = 64
) -> "list[list[int]]":
    """Maximal cliques of the DISJOINTNESS graph (vertices = aggregates,
    edge ⟺ bit-disjoint), with pivoting, truncated at max_cliques."""
    n = len(bitsets)
    adj = [set() for _ in range(n)]
    for i in range(n):
        for j in range(i + 1, n):
            if not (bitsets[i] & bitsets[j]):
                adj[i].add(j)
                adj[j].add(i)
    out: "list[list[int]]" = []

    def expand(r: "list[int]", p: set, x: set) -> bool:
        if len(out) >= max_cliques:
            return False
        if not p and not x:
            out.append(list(r))
            return True
        pivot = max(p | x, key=lambda v: len(adj[v] & p))
        for v in list(p - adj[pivot]):
            if not expand(r + [v], p & adj[v], x & adj[v]):
                return False
            p.discard(v)
            x.add(v)
        return True

    expand([], set(range(n)), set())
    return out


def select_max_coverage(
    element_sets: "Sequence[frozenset]",
    max_count: int,
    node_budget: int = 20000,
) -> "list[int]":
    """Indices of ≤ max_count sets maximizing |union| — exact within
    node_budget branch-and-bound expansions, else best-found (≥ greedy)."""
    n = len(element_sets)
    if n == 0 or max_count <= 0:
        return []
    order = sorted(range(n), key=lambda i: -len(element_sets[i]))

    # greedy incumbent
    best_sel: "list[int]" = []
    covered: set = set()
    for i in order:
        new = element_sets[i] - covered
        if not new:
            continue
        best_sel.append(i)
        covered |= new
        if len(best_sel) >= max_count:
            break
    best_val = len(covered)

    sizes = [len(element_sets[i]) for i in order]
    state = {"nodes": 0, "best_val": best_val, "best_sel": list(best_sel)}

    def dfs(pos: int, chosen: "list[int]", cov: set) -> None:
        if state["nodes"] >= node_budget:
            return
        state["nodes"] += 1
        if len(cov) > state["best_val"]:
            state["best_val"] = len(cov)
            state["best_sel"] = list(chosen)
        if len(chosen) >= max_count or pos >= n:
            return
        # admissible bound: ignore overlaps among the remaining top sets
        remaining = max_count - len(chosen)
        bound = len(cov) + sum(sizes[pos : pos + remaining])
        if bound <= state["best_val"]:
            return
        i = order[pos]
        new = element_sets[i] - cov
        if new:
            dfs(pos + 1, chosen + [i], cov | new)
        dfs(pos + 1, chosen, cov)

    dfs(0, [], set())
    return state["best_sel"]


def pack_optimized(
    entries,
    max_count: int,
    merge: "Callable",
    max_cliques: int = 64,
):
    """Full packer: entries are pool `_Entry`-likes (`.attestation`,
    `.bits`); `merge(a, b) -> entry` merges two same-data entries.
    Returns the packed attestation list."""
    # phase 1: per-data clique merge
    by_data: "dict[tuple, list]" = {}
    for e in entries:
        d = e.attestation.data
        key = (int(d.slot), int(d.index), d.hash_tree_root())
        by_data.setdefault(key, []).append(e)

    candidates = list(entries)
    for _key, group in by_data.items():
        if len(group) < 2:
            continue
        bitsets = [
            frozenset(int(i) for i in e.bits.nonzero_indices()) for e in group
        ]
        for clique in bron_kerbosch_disjoint(bitsets, max_cliques):
            if len(clique) < 2:
                continue
            acc = group[clique[0]]
            for v in clique[1:]:
                acc = merge(acc, group[v])
            candidates.append(acc)

    # phase 2: exact-within-budget selection over (committee, bit) elements
    element_sets = []
    for e in candidates:
        d = e.attestation.data
        cov_key = (int(d.slot), int(d.index))
        element_sets.append(frozenset(
            (cov_key, int(i)) for i in e.bits.nonzero_indices()
        ))
    chosen = select_max_coverage(element_sets, max_count)
    return [candidates[i].attestation for i in chosen]


__all__ = ["bron_kerbosch_disjoint", "select_max_coverage", "pack_optimized"]
