"""Pool for non-attestation operations — reference: the
BlsToExecutionChangePool (operation_pools) plus the slashing / voluntary-
exit accumulation the reference keeps alongside (fed to the proposer and
served by the Beacon API's pool endpoints).

Dedup keys follow the spec's inclusion semantics: one exit per validator,
one proposer slashing per proposer, attester slashings by content,
one BLS change per validator.
"""

from __future__ import annotations

import threading
from typing import Sequence


class OperationPool:
    def __init__(self, cfg) -> None:
        self.cfg = cfg
        self.p = cfg.preset
        self._lock = threading.Lock()
        self._proposer_slashings: dict = {}   # proposer index -> op
        self._attester_slashings: dict = {}   # content root -> op
        self._voluntary_exits: dict = {}      # validator index -> op
        self._bls_changes: dict = {}          # validator index -> op

    # ------------------------------------------------------------- inserts

    def insert_proposer_slashing(self, slashing) -> bool:
        key = int(slashing.signed_header_1.message.proposer_index)
        with self._lock:
            if key in self._proposer_slashings:
                return False
            self._proposer_slashings[key] = slashing
            return True

    def insert_attester_slashing(self, slashing) -> bool:
        key = slashing.hash_tree_root()
        with self._lock:
            if key in self._attester_slashings:
                return False
            self._attester_slashings[key] = slashing
            return True

    def insert_voluntary_exit(self, signed_exit) -> bool:
        key = int(signed_exit.message.validator_index)
        with self._lock:
            if key in self._voluntary_exits:
                return False
            self._voluntary_exits[key] = signed_exit
            return True

    def insert_bls_to_execution_change(self, signed_change) -> bool:
        key = int(signed_change.message.validator_index)
        with self._lock:
            if key in self._bls_changes:
                return False
            self._bls_changes[key] = signed_change
            return True

    # -------------------------------------------------------------- state

    def contents(self) -> dict:
        with self._lock:
            return {
                "proposer_slashings": list(self._proposer_slashings.values()),
                "attester_slashings": list(self._attester_slashings.values()),
                "voluntary_exits": list(self._voluntary_exits.values()),
                "bls_to_execution_changes": list(self._bls_changes.values()),
            }

    # ------------------------------------------------------------- packing

    def pack(self, state) -> dict:
        """Block-sized op sets, filtered to those still APPLICABLE to
        `state` — every spec applicability condition except signatures
        (a produced block must survive its own transition; the reference
        guarantees this by gossip-verifying at insert, we re-check the
        content rules at pack time)."""
        import hashlib

        from grandine_tpu.consensus import accessors, predicates
        from grandine_tpu.types.primitives import FAR_FUTURE_EPOCH

        p = self.p
        epoch = accessors.get_current_epoch(state, p)
        cols = accessors.registry_columns(state)
        n = len(cols)
        ops = self.contents()

        def slashable(i: int) -> bool:
            return i < n and not bool(cols.slashed[i]) and (
                int(cols.activation_epoch[i]) <= epoch
                < int(cols.withdrawable_epoch[i])
            )

        proposer_slashings = []
        for s in ops["proposer_slashings"]:
            h1, h2 = s.signed_header_1.message, s.signed_header_2.message
            if (
                int(h1.slot) == int(h2.slot)
                and int(h1.proposer_index) == int(h2.proposer_index)
                and h1.hash_tree_root() != h2.hash_tree_root()
                and slashable(int(h1.proposer_index))
            ):
                proposer_slashings.append(s)
            if len(proposer_slashings) >= p.MAX_PROPOSER_SLASHINGS:
                break

        attester_slashings = []
        for s in ops["attester_slashings"]:
            if not predicates.is_slashable_attestation_data(
                s.attestation_1.data, s.attestation_2.data
            ):
                continue
            common = set(map(int, s.attestation_1.attesting_indices)) & set(
                map(int, s.attestation_2.attesting_indices)
            )
            if any(slashable(i) for i in common):
                attester_slashings.append(s)
            if len(attester_slashings) >= p.MAX_ATTESTER_SLASHINGS:
                break

        exits = []
        for e in ops["voluntary_exits"]:
            i = int(e.message.validator_index)
            if (
                i < n
                and int(cols.exit_epoch[i]) == FAR_FUTURE_EPOCH
                and int(cols.activation_epoch[i]) <= epoch
                and epoch >= int(e.message.epoch)
                and epoch
                >= int(cols.activation_epoch[i])
                + self.cfg.shard_committee_period
            ):
                exits.append(e)
            if len(exits) >= p.MAX_VOLUNTARY_EXITS:
                break

        changes = []
        for c in ops["bls_to_execution_changes"]:
            i = int(c.message.validator_index)
            creds = (
                bytes(cols.withdrawal_credentials[i]) if i < n else b""
            )
            if (
                i < n
                and creds[:1] == b"\x00"
                and hashlib.sha256(bytes(c.message.from_bls_pubkey)).digest()[
                    1:
                ]
                == creds[1:]
            ):
                changes.append(c)
            if len(changes) >= p.MAX_BLS_TO_EXECUTION_CHANGES:
                break

        return {
            "proposer_slashings": proposer_slashings,
            "attester_slashings": attester_slashings,
            "voluntary_exits": exits,
            "bls_to_execution_changes": changes,
        }

    def on_block_applied(self, block) -> None:
        """Drop operations included in an accepted block."""
        body = block.message.body if hasattr(block, "message") else block.body
        with self._lock:
            for s in body.proposer_slashings:
                self._proposer_slashings.pop(
                    int(s.signed_header_1.message.proposer_index), None
                )
            for s in body.attester_slashings:
                self._attester_slashings.pop(s.hash_tree_root(), None)
            for e in body.voluntary_exits:
                self._voluntary_exits.pop(int(e.message.validator_index), None)
            if hasattr(body, "bls_to_execution_changes"):
                for c in body.bls_to_execution_changes:
                    self._bls_changes.pop(int(c.message.validator_index), None)


__all__ = ["OperationPool"]
