"""Operation pools — reference: `operation_pools` crate
(AttestationAggPool with aggregate-on-insert + the attestation packer,
SyncCommitteeAggPool, BlsToExecutionChangePool, and the slashing/exit
pools the reference keeps in http_api/validator state).

All pools are head-state-agnostic accumulators; the packer resolves
against a concrete pre-state at proposal time.
"""

from grandine_tpu.pools.attestation_pool import AttestationAggPool  # noqa: F401
from grandine_tpu.pools.operation_pool import OperationPool  # noqa: F401
from grandine_tpu.pools.sync_committee_pool import SyncCommitteeAggPool  # noqa: F401
