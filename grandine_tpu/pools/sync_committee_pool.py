"""Sync-committee aggregation pool — reference:
operation_pools/src/sync_committee_agg_pool (per-slot, per-subcommittee
contribution aggregation feeding the proposer's SyncAggregate).
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from grandine_tpu.crypto import bls as A


class SyncCommitteeAggPool:
    """(slot, beacon_block_root) -> per-subcommittee best contributions,
    foldable into one block-level SyncAggregate."""

    def __init__(self, cfg) -> None:
        self.cfg = cfg
        self.p = cfg.preset
        self.subcommittees = 4  # SYNC_COMMITTEE_SUBNET_COUNT
        self._contribs: "dict[tuple, dict[int, object]]" = {}
        self._lock = threading.Lock()

    def insert_message(
        self, slot: int, beacon_block_root: bytes,
        committee_position: int, signature,
    ) -> None:
        """One validator's SyncCommitteeMessage placed at its position(s)
        in the committee (positions map to subcommittees). `signature`
        may be compressed bytes or an already-decompressed
        `A.Signature` (the verify scheduler decompressed it to batch)."""
        self.insert_message_at_positions(
            slot, beacon_block_root, (committee_position,), signature
        )

    def insert_message_at_positions(
        self, slot: int, beacon_block_root: bytes,
        positions, signature,
    ) -> None:
        """One message inserted at every committee position its
        validator holds — the signature is decompressed ONCE, not per
        position (a validator can hold several positions)."""
        if not positions:
            return
        sub_size = self.p.SYNC_COMMITTEE_SIZE // self.subcommittees
        key = (int(slot), bytes(beacon_block_root))
        sig = (
            signature if isinstance(signature, A.Signature)
            else A.Signature.from_bytes(bytes(signature))
        )
        with self._lock:
            subs = self._contribs.setdefault(key, {})
            for committee_position in positions:
                sub = committee_position // sub_size
                pos_in_sub = committee_position % sub_size
                entry = subs.get(sub)
                bits = np.zeros(sub_size, dtype=bool)
                bits[pos_in_sub] = True
                if entry is None:
                    subs[sub] = (bits, sig)
                else:
                    old_bits, old_sig = entry
                    if old_bits[pos_in_sub]:
                        continue  # already have this participant
                    merged = old_bits | bits
                    subs[sub] = (
                        merged,
                        A.Signature.aggregate([old_sig, sig]),
                    )

    def insert_contribution(self, contribution) -> None:
        """An aggregated SyncCommitteeContribution (gossip aggregate)."""
        key = (
            int(contribution.slot),
            bytes(contribution.beacon_block_root),
        )
        sub = int(contribution.subcommittee_index)
        bits = np.asarray(contribution.aggregation_bits.array, dtype=bool)
        sig = A.Signature.from_bytes(
            bytes(contribution.signature)
        )
        with self._lock:
            subs = self._contribs.setdefault(key, {})
            entry = subs.get(sub)
            if entry is None or bits.sum() > entry[0].sum():
                subs[sub] = (bits.copy(), sig)

    def best_aggregate(self, slot: int, beacon_block_root: bytes, types_ns):
        """Fold the best per-subcommittee contributions into a block-level
        SyncAggregate (empty aggregate when nothing is known)."""
        sub_size = self.p.SYNC_COMMITTEE_SIZE // self.subcommittees
        with self._lock:
            subs = dict(
                self._contribs.get((int(slot), bytes(beacon_block_root)), {})
            )
        bits = np.zeros(self.p.SYNC_COMMITTEE_SIZE, dtype=bool)
        sigs = []
        for sub, (sub_bits, sig) in subs.items():
            bits[sub * sub_size : (sub + 1) * sub_size] = sub_bits
            sigs.append(sig)
        signature = (
            A.Signature.aggregate(sigs) if sigs else A.Signature.empty()
        )
        return types_ns.SyncAggregate(
            sync_committee_bits=bits,
            sync_committee_signature=signature.to_bytes(),
        )

    def prune_before(self, slot: int) -> None:
        with self._lock:
            for k in [k for k in self._contribs if k[0] < slot]:
                del self._contribs[k]


__all__ = ["SyncCommitteeAggPool"]
