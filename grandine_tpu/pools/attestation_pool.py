"""Attestation aggregation pool + block packer — reference:
operation_pools/src/attestation_agg_pool (aggregate-on-insert per
committee, pool.rs) and attestation_packer.rs (ILP packing via HiGHS with
a greedy fallback; greedy here — the ILP seam is `pack_attestations`).

Pool shape: (slot, committee_index, data_root) -> list of non-dominated
aggregates. Insertion merges disjoint aggregates eagerly (aggregate-on-
insert) and drops dominated ones, so the packer chooses among few,
near-maximal aggregates per committee.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

from grandine_tpu.crypto import bls as A


class _Entry:
    __slots__ = ("attestation", "bits")

    def __init__(self, attestation) -> None:
        self.attestation = attestation
        self.bits = attestation.aggregation_bits


class AttestationAggPool:
    def __init__(self, cfg, capacity_slots: "Optional[int]" = None) -> None:
        self.cfg = cfg
        self.p = cfg.preset
        # retain at most ~2 epochs of slots (packable window)
        self.capacity_slots = capacity_slots or 2 * self.p.SLOTS_PER_EPOCH
        self._by_key: "dict[tuple, list[_Entry]]" = {}
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._by_key.values())

    # ------------------------------------------------------------- insert

    def insert(self, attestation) -> None:
        """Aggregate-on-insert: merge with every disjoint aggregate of the
        same attestation data, keep the non-dominated frontier."""
        data = attestation.data
        key = (int(data.slot), int(data.index), data.hash_tree_root())
        new = _Entry(attestation)
        with self._lock:
            entries = self._by_key.setdefault(key, [])
            # merge into disjoint existing aggregates
            merged: "list[_Entry]" = []
            for e in entries:
                if not e.bits.intersects(new.bits):
                    merged.append(self._merge(e, new))
            candidates = entries + [new] + merged
            # non-dominated frontier (drop strict subsets)
            frontier: "list[_Entry]" = []
            for cand in sorted(
                candidates, key=lambda e: -e.bits.count()
            ):
                if not any(f.bits.covers(cand.bits) for f in frontier):
                    frontier.append(cand)
            self._by_key[key] = frontier[:8]  # bounded per committee
            self._evict()

    def _merge(self, a: _Entry, b: _Entry) -> _Entry:
        sig = A.Signature.aggregate(
            [
                A.Signature.from_bytes(bytes(a.attestation.signature)),
                A.Signature.from_bytes(bytes(b.attestation.signature)),
            ]
        )
        merged = a.attestation.replace(
            aggregation_bits=a.bits.union(b.bits),
            signature=sig.to_bytes(),
        )
        return _Entry(merged)

    def _evict(self) -> None:
        slots = sorted({k[0] for k in self._by_key})
        while len(slots) > self.capacity_slots:
            victim = slots.pop(0)
            for k in [k for k in self._by_key if k[0] == victim]:
                del self._by_key[k]

    # --------------------------------------------------------------- query

    def best_aggregate(self, slot: int, index: int, data_root: bytes):
        """Widest known aggregate for (slot, committee, data) — what the
        aggregator duty publishes."""
        with self._lock:
            entries = self._by_key.get((slot, index, bytes(data_root)), [])
            if not entries:
                return None
            return max(entries, key=lambda e: e.bits.count()).attestation

    def best_by_data_root(self, slot: int, data_root: bytes):
        """Widest aggregate for (slot, data) across committees — the
        Beacon API `aggregate_attestation` lookup (slot + data root)."""
        data_root = bytes(data_root)
        with self._lock:
            best = None
            for (s, _i, root), entries in self._by_key.items():
                if s != slot or root != data_root or not entries:
                    continue
                cand = max(entries, key=lambda e: e.bits.count()).attestation
                if best is None or (
                    cand.aggregation_bits.count()
                    > best.aggregation_bits.count()
                ):
                    best = cand
            return best

    def all_attestations(self) -> list:
        """Every pooled aggregate (GET /eth/v1/beacon/pool/attestations)."""
        with self._lock:
            return [
                e.attestation
                for entries in self._by_key.values()
                for e in entries
            ]

    def best_for_committee(self, slot: int, index: int):
        """Widest aggregate across ALL attestation data of one committee
        (what an aggregator publishes when it doesn't care which data)."""
        with self._lock:
            best = None
            for (s, i, _root), entries in self._by_key.items():
                if s != slot or i != index or not entries:
                    continue
                cand = max(entries, key=lambda e: e.bits.count()).attestation
                if best is None or (
                    cand.aggregation_bits.count()
                    > best.aggregation_bits.count()
                ):
                    best = cand
            return best

    def prune_before(self, slot: int) -> None:
        with self._lock:
            for k in [k for k in self._by_key if k[0] < slot]:
                del self._by_key[k]

    # --------------------------------------------------------------- pack

    def pack_attestations(
        self, state, cfg, max_count: "Optional[int]" = None,
        slot: "Optional[int]" = None,
    ):
        """Greedy weight packer for block production
        (attestation_packer.rs:142 greedy fallback; the ILP seam): pick
        includable attestations maximizing NEW attesting validators,
        de-duplicating across overlapping aggregates.

        `slot` is the slot of the block being built (defaults to the
        state's slot); inclusion windows are computed against it, so a
        packer fed the previous head state stays correct across epoch
        boundaries."""
        from grandine_tpu.consensus import accessors, misc
        from grandine_tpu.transition.fork_upgrade import state_phase
        from grandine_tpu.types.primitives import Phase

        p = cfg.preset
        max_count = max_count or p.MAX_ATTESTATIONS
        state_slot = int(state.slot) if slot is None else int(slot)
        cur = misc.compute_epoch_at_slot(state_slot, p)
        prev = max(0, cur - 1)
        pre_deneb = state_phase(state, cfg) < Phase.DENEB

        candidates = []
        with self._lock:
            items = [
                (k, e) for k, entries in self._by_key.items() for e in entries
            ]
        for (slot, index, _root), e in items:
            if slot + p.MIN_ATTESTATION_INCLUSION_DELAY > state_slot:
                continue
            # pre-Deneb upper inclusion bound (EIP-7045 removed it): packing
            # an aggregate older than one epoch would abort the proposal in
            # process_block's "attestation: too old" check.
            if pre_deneb and state_slot > slot + p.SLOTS_PER_EPOCH:
                continue
            target_epoch = misc.compute_epoch_at_slot(slot, p)
            if target_epoch not in (cur, prev):
                continue
            # source must match the state's justified checkpoint
            data = e.attestation.data
            justified = (
                state.current_justified_checkpoint
                if target_epoch == cur
                else state.previous_justified_checkpoint
            )
            if data.source != justified:
                continue
            candidates.append(e)

        from grandine_tpu import features

        if not features.is_enabled(features.Feature.GREEDY_ATTESTATION_PACKING):
            from grandine_tpu.pools.packer import pack_optimized

            return pack_optimized(candidates, max_count, self._merge)

        seen: "dict[tuple, set]" = {}
        packed = []
        # widest-first greedy with incremental coverage accounting
        for e in sorted(candidates, key=lambda e: -e.bits.count()):
            data = e.attestation.data
            cov_key = (int(data.slot), int(data.index))
            covered = seen.setdefault(cov_key, set())
            new_bits = set(int(i) for i in e.bits.nonzero_indices()) - covered
            if not new_bits:
                continue
            packed.append(e.attestation)
            covered |= new_bits
            if len(packed) >= max_count:
                break
        return packed


__all__ = ["AttestationAggPool"]
