"""ExecutionEngine interface + Null/Mock test seams.

Reference: execution_engine/src/execution_engine.rs:21-54 (trait with
`notify_new_payload` / `notify_forkchoice_updated`), :176 (Null), :210
(Mock with scripted payload statuses) — the two I/O boundaries SURVEY.md §4.3
swaps to run integration tests without a real chain.
"""

from __future__ import annotations

import enum
from typing import Optional


class PayloadStatus(enum.Enum):
    VALID = "VALID"
    INVALID = "INVALID"
    SYNCING = "SYNCING"
    ACCEPTED = "ACCEPTED"


class ExecutionEngine:
    """Interface: the consensus layer notifies, the EL answers."""

    def notify_new_payload(self, payload) -> PayloadStatus:
        raise NotImplementedError

    def notify_forkchoice_updated(
        self,
        head_block_hash: bytes,
        safe_block_hash: bytes,
        finalized_block_hash: bytes,
        payload_attributes=None,
    ) -> PayloadStatus:
        raise NotImplementedError

    def allow_optimistic_import(self) -> bool:
        return True


class NullExecutionEngine(ExecutionEngine):
    """Accepts everything (reference NullExecutionEngine: consensus-only
    operation, spec replays)."""

    def notify_new_payload(self, payload) -> PayloadStatus:
        return PayloadStatus.VALID

    def notify_forkchoice_updated(
        self, head_block_hash, safe_block_hash, finalized_block_hash,
        payload_attributes=None,
    ) -> PayloadStatus:
        return PayloadStatus.VALID


class MockExecutionEngine(ExecutionEngine):
    """Scripted statuses for fault-injection tests (reference
    MockExecutionEngine). `status_for` maps payload block_hash -> status;
    unknown hashes return `default`."""

    def __init__(
        self,
        default: PayloadStatus = PayloadStatus.VALID,
        status_for: "Optional[dict]" = None,
    ) -> None:
        self.default = default
        self.status_for = dict(status_for or {})
        self.new_payload_calls: list = []
        self.forkchoice_calls: list = []

    def notify_new_payload(self, payload) -> PayloadStatus:
        block_hash = bytes(payload.block_hash)
        self.new_payload_calls.append(block_hash)
        return self.status_for.get(block_hash, self.default)

    def notify_forkchoice_updated(
        self, head_block_hash, safe_block_hash, finalized_block_hash,
        payload_attributes=None,
    ) -> PayloadStatus:
        self.forkchoice_calls.append(
            (bytes(head_block_hash), bytes(safe_block_hash), bytes(finalized_block_hash))
        )
        return self.status_for.get(bytes(head_block_hash), self.default)


__all__ = [
    "PayloadStatus",
    "ExecutionEngine",
    "NullExecutionEngine",
    "MockExecutionEngine",
]
