"""ExecutionEngine interface + Null/Mock test seams + retry decorator.

Reference: execution_engine/src/execution_engine.rs:21-54 (trait with
`notify_new_payload` / `notify_forkchoice_updated`), :176 (Null), :210
(Mock with scripted payload statuses) — the two I/O boundaries SURVEY.md §4.3
swaps to run integration tests without a real chain.

`RetryingExecutionEngine` wraps any engine with capped exponential
backoff + jitter on transient failures, replacing the bare "stay
optimistic, retry on next head" behavior when the EL is unreachable.
"""

from __future__ import annotations

import enum
import random
import time
from typing import Optional


class PayloadStatus(enum.Enum):
    VALID = "VALID"
    INVALID = "INVALID"
    SYNCING = "SYNCING"
    ACCEPTED = "ACCEPTED"


class ExecutionEngine:
    """Interface: the consensus layer notifies, the EL answers."""

    def notify_new_payload(self, payload) -> PayloadStatus:
        raise NotImplementedError

    def notify_forkchoice_updated(
        self,
        head_block_hash: bytes,
        safe_block_hash: bytes,
        finalized_block_hash: bytes,
        payload_attributes=None,
    ) -> PayloadStatus:
        raise NotImplementedError

    def allow_optimistic_import(self) -> bool:
        return True


class NullExecutionEngine(ExecutionEngine):
    """Accepts everything (reference NullExecutionEngine: consensus-only
    operation, spec replays)."""

    def notify_new_payload(self, payload) -> PayloadStatus:
        return PayloadStatus.VALID

    def notify_forkchoice_updated(
        self, head_block_hash, safe_block_hash, finalized_block_hash,
        payload_attributes=None,
    ) -> PayloadStatus:
        return PayloadStatus.VALID


class MockExecutionEngine(ExecutionEngine):
    """Scripted statuses for fault-injection tests (reference
    MockExecutionEngine). `status_for` maps payload block_hash -> status;
    unknown hashes return `default`."""

    def __init__(
        self,
        default: PayloadStatus = PayloadStatus.VALID,
        status_for: "Optional[dict]" = None,
    ) -> None:
        self.default = default
        self.status_for = dict(status_for or {})
        self.new_payload_calls: list = []
        self.forkchoice_calls: list = []

    def notify_new_payload(self, payload) -> PayloadStatus:
        block_hash = bytes(payload.block_hash)
        self.new_payload_calls.append(block_hash)
        return self.status_for.get(block_hash, self.default)

    def notify_forkchoice_updated(
        self, head_block_hash, safe_block_hash, finalized_block_hash,
        payload_attributes=None,
    ) -> PayloadStatus:
        self.forkchoice_calls.append(
            (bytes(head_block_hash), bytes(safe_block_hash), bytes(finalized_block_hash))
        )
        return self.status_for.get(bytes(head_block_hash), self.default)


def _is_transient(error: BaseException) -> bool:
    """Transient = worth retrying: socket-level failures (OSError, or an
    HttpClientError whose `status` is None) and EL-side 5xx. Duck-typed
    on the `status` attribute so this module never imports http_clients
    (which imports this module)."""
    if isinstance(error, OSError):
        return True
    status = getattr(error, "status", False)
    if status is False:
        return False  # no status attribute at all: not an HTTP error
    return status is None or (
        isinstance(status, int) and 500 <= status < 600
    )


class RetryingExecutionEngine(ExecutionEngine):
    """Capped-exponential-backoff retry wrapper around any
    ExecutionEngine (in practice http_clients.EngineApiClient — built
    via its `.with_retries()`).

    Two cooperating mechanisms:
      in-call retries — a transient failure re-issues the call up to
          `max_attempts` times, sleeping a jittered, capped exponential
          delay between attempts (counted on `el_retry_total`);
      cross-call fail-fast — when a call exhausts its attempts, further
          calls raise the last error immediately until a backoff window
          (growing with consecutive failed calls, capped) expires, so a
          down EL costs one probe per window instead of a full retry
          ladder per fork-choice update.

    Non-transient errors (4xx, auth failures) propagate immediately.
    `clock`/`sleep`/`rng` are injectable for deterministic tests."""

    def __init__(
        self,
        inner: ExecutionEngine,
        max_attempts: int = 3,
        backoff_initial_s: float = 0.5,
        backoff_max_s: float = 30.0,
        jitter_frac: float = 0.1,
        metrics=None,
        clock=time.monotonic,
        sleep=time.sleep,
        rng: "Optional[random.Random]" = None,
    ) -> None:
        self.inner = inner
        self.max_attempts = max(1, int(max_attempts))
        self.backoff_initial_s = float(backoff_initial_s)
        self.backoff_max_s = float(backoff_max_s)
        self.jitter_frac = float(jitter_frac)
        self.metrics = metrics
        self.clock = clock
        self.sleep = sleep
        self.rng = rng if rng is not None else random.Random()
        self._failures = 0  # consecutive exhausted calls
        self._blocked_until = 0.0
        self._last_error: "Optional[BaseException]" = None
        self.stats = {"retries": 0, "fast_fails": 0, "giveups": 0}

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def _delay(self, attempt: int) -> float:
        base = min(
            self.backoff_initial_s * (2.0 ** (attempt - 1)),
            self.backoff_max_s,
        )
        return base * (1.0 + self.jitter_frac * (2.0 * self.rng.random() - 1.0))

    def _invoke(self, fn, *args, **kwargs):
        if self._last_error is not None and self.clock() < self._blocked_until:
            # fail-fast window: the EL just exhausted a retry ladder —
            # don't pay another one per head until the window expires
            self.stats["fast_fails"] += 1
            raise self._last_error
        attempt = 1
        while True:
            try:
                result = fn(*args, **kwargs)
            except Exception as e:
                if not _is_transient(e):
                    raise
                if attempt >= self.max_attempts:
                    self.stats["giveups"] += 1
                    self._failures += 1
                    self._last_error = e
                    self._blocked_until = (
                        self.clock() + self._delay(self._failures)
                    )
                    raise
                self.stats["retries"] += 1
                if self.metrics is not None:
                    self.metrics.el_retries.inc()
                self.sleep(self._delay(attempt))
                attempt += 1
                continue
            self._failures = 0
            self._last_error = None
            self._blocked_until = 0.0
            return result

    def notify_new_payload(self, payload) -> PayloadStatus:
        return self._invoke(self.inner.notify_new_payload, payload)

    def notify_forkchoice_updated(
        self, head_block_hash, safe_block_hash, finalized_block_hash,
        payload_attributes=None,
    ) -> PayloadStatus:
        return self._invoke(
            self.inner.notify_forkchoice_updated,
            head_block_hash, safe_block_hash, finalized_block_hash,
            payload_attributes,
        )

    def allow_optimistic_import(self) -> bool:
        return self.inner.allow_optimistic_import()


__all__ = [
    "PayloadStatus",
    "ExecutionEngine",
    "NullExecutionEngine",
    "MockExecutionEngine",
    "RetryingExecutionEngine",
]
