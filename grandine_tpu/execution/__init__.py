"""Execution-layer seam — reference: execution_engine crate
(`ExecutionEngine` trait execution_engine/src/execution_engine.rs:21-54,
`NullExecutionEngine` :176, `MockExecutionEngine` :210).

The consensus layer only needs the notification surface; the real
JSON-RPC engine-API client (eth1_api crate) plugs in behind the same
interface.
"""

from grandine_tpu.execution.engine import (  # noqa: F401
    ExecutionEngine,
    MockExecutionEngine,
    NullExecutionEngine,
    PayloadStatus,
)
