"""SSZ: SimpleSerialize codec + Merkleization — equivalent of the reference
`ssz` + `ssz_derive` crates (ssz/src/lib.rs:21 `SszRead/SszWrite/SszHash`,
ssz/src/merkle_tree.rs, ssz_derive's `#[derive(Ssz)]`).

Design (TPU-era host layer, not a translation):
  * SSZ *types* are descriptor objects (`uint64`, `List(Validator, N)`, ...)
    with `serialize / deserialize / hash_tree_root / default / chunk_count`.
  * SSZ *values* are plain Python data — int, bool, bytes — plus three thin
    wrappers: `Bits` (numpy-bool bitfields), `SszList`/`SszVector`
    (tuple- or numpy-backed sequences with cached roots), and `Container`
    (declared via class annotations; immutable, cached hash-tree-root —
    the reference's `Hc<_>` hash-caching wrapper, ssz/src/hc.rs, is
    subsumed by caching on every composite value).
  * The merkleization hot loop runs in the native SHA-NI extension
    (grandine_tpu.core.hashing); uint lists are numpy-backed so leaf-chunk
    packing is `ndarray.tobytes()`.

Public names mirror what the reference's `types` crate imports from `ssz`.
"""

from grandine_tpu.ssz.base import (
    Bits,
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Container,
    List,
    SszError,
    SszList,
    SszType,
    SszVector,
    Vector,
    boolean,
    byte,
    uint8,
    uint16,
    uint32,
    uint64,
    uint128,
    uint256,
)
from grandine_tpu.ssz.merkle import MerkleTree, verify_merkle_proof

Bytes4 = ByteVector(4)
Bytes20 = ByteVector(20)
Bytes32 = ByteVector(32)
Bytes48 = ByteVector(48)
Bytes96 = ByteVector(96)

__all__ = [
    "Bits", "Bitlist", "Bitvector", "ByteList", "ByteVector", "Container",
    "List", "SszError", "SszList", "SszType", "SszVector", "Vector",
    "boolean", "byte", "uint8", "uint16", "uint32", "uint64", "uint128",
    "uint256", "Bytes4", "Bytes20", "Bytes32", "Bytes48", "Bytes96",
    "MerkleTree", "verify_merkle_proof",
]
