"""SSZ type descriptors and value wrappers.

Every SSZ type is a *descriptor object* exposing:
    is_fixed()        -> bool
    fixed_size()      -> int           (fixed-size types only)
    serialize(v)      -> bytes
    deserialize(data) -> value         (strict: rejects trailing bytes,
                                        bad offsets, bad bitfield padding)
    hash_tree_root(v) -> bytes32
    default()         -> value
    coerce(v)         -> value         (accept convenient Python inputs)

Reference parity: ssz/src/lib.rs (SszRead/SszWrite/SszHash, ContiguousList/
Vector, BitList/BitVector, Uint256), ssz/src/hc.rs (hash caching — here a
`_htr` cache on every composite value), ssz_derive (here: Container class
annotations scanned by a metaclass).
"""

import struct
from typing import Any, Iterable, Optional, Sequence

import numpy as np

from grandine_tpu.core import hashing

OFFSET_SIZE = 4
_U32 = struct.Struct("<I")


class SszError(ValueError):
    pass


def _pad_chunks(data: bytes) -> bytes:
    rem = len(data) % 32
    return data if rem == 0 else data + b"\x00" * (32 - rem)


class SszType:
    def is_fixed(self) -> bool:
        raise NotImplementedError

    def fixed_size(self) -> int:
        raise SszError(f"{self} is variable-size")

    def serialize(self, v) -> bytes:
        raise NotImplementedError

    def deserialize(self, data) -> Any:
        raise NotImplementedError

    def hash_tree_root(self, v) -> bytes:
        raise NotImplementedError

    def default(self):
        raise NotImplementedError

    def coerce(self, v):
        return v

    # numpy dtype for packed basic types, else None
    np_dtype = None


# --------------------------------------------------------------- basic types


class UInt(SszType):
    __slots__ = ("bits", "size", "np_dtype")
    _cache: dict = {}

    # Interned by width (like ByteVector/Bitlist) so UInt(64) IS uint64:
    # composite types key their caches on element identity, and separately
    # constructed-but-equal descriptors must not yield distinct List/Vector
    # types whose values never compare equal.
    def __new__(cls, bits: int):
        hit = cls._cache.get(bits)
        if hit is None:
            hit = super().__new__(cls)
            hit.bits = bits
            hit.size = bits // 8
            hit.np_dtype = {8: np.uint8, 16: np.uint16, 32: np.uint32,
                            64: np.uint64}.get(bits)
            cls._cache[bits] = hit
        return hit

    def __repr__(self):
        return f"uint{self.bits}"

    def is_fixed(self):
        return True

    def fixed_size(self):
        return self.size

    def serialize(self, v) -> bytes:
        return int(v).to_bytes(self.size, "little")

    def deserialize(self, data) -> int:
        data = bytes(data)
        if len(data) != self.size:
            raise SszError(f"uint{self.bits}: got {len(data)} bytes")
        return int.from_bytes(data, "little")

    def hash_tree_root(self, v) -> bytes:
        return int(v).to_bytes(self.size, "little").ljust(32, b"\x00")

    def default(self):
        return 0

    def coerce(self, v):
        v = int(v)
        if not 0 <= v < (1 << self.bits):
            raise SszError(f"uint{self.bits} out of range: {v}")
        return v


class Boolean(SszType):
    np_dtype = np.uint8

    def __repr__(self):
        return "boolean"

    def is_fixed(self):
        return True

    def fixed_size(self):
        return 1

    def serialize(self, v) -> bytes:
        return b"\x01" if v else b"\x00"

    def deserialize(self, data) -> bool:
        data = bytes(data)
        if data == b"\x00":
            return False
        if data == b"\x01":
            return True
        raise SszError(f"boolean: invalid byte {data!r}")

    def hash_tree_root(self, v) -> bytes:
        return (b"\x01" if v else b"\x00").ljust(32, b"\x00")

    def default(self):
        return False

    def coerce(self, v):
        return bool(v)


uint8 = UInt(8)
uint16 = UInt(16)
uint32 = UInt(32)
uint64 = UInt(64)
uint128 = UInt(128)
uint256 = UInt(256)
byte = uint8
boolean = Boolean()


# --------------------------------------------------------------- byte arrays


class ByteVector(SszType):
    __slots__ = ("length",)
    _cache: dict = {}

    def __new__(cls, length: int):
        hit = cls._cache.get(length)
        if hit is None:
            hit = super().__new__(cls)
            hit.length = length
            cls._cache[length] = hit
        return hit

    def __repr__(self):
        return f"ByteVector[{self.length}]"

    def is_fixed(self):
        return True

    def fixed_size(self):
        return self.length

    def serialize(self, v) -> bytes:
        return bytes(v)

    def deserialize(self, data) -> bytes:
        data = bytes(data)
        if len(data) != self.length:
            raise SszError(f"{self}: got {len(data)} bytes")
        return data

    def hash_tree_root(self, v) -> bytes:
        if self.length <= 32:
            return bytes(v).ljust(32, b"\x00")
        return hashing.merkleize_chunks(_pad_chunks(bytes(v)))

    def default(self):
        return b"\x00" * self.length

    def coerce(self, v):
        v = bytes(v)
        if len(v) != self.length:
            raise SszError(f"{self}: got {len(v)} bytes")
        return v


class ByteList(SszType):
    __slots__ = ("limit",)
    _cache: dict = {}

    def __new__(cls, limit: int):
        hit = cls._cache.get(limit)
        if hit is None:
            hit = super().__new__(cls)
            hit.limit = limit
            cls._cache[limit] = hit
        return hit

    def __repr__(self):
        return f"ByteList[{self.limit}]"

    def is_fixed(self):
        return False

    def serialize(self, v) -> bytes:
        return bytes(v)

    def deserialize(self, data) -> bytes:
        data = bytes(data)
        if len(data) > self.limit:
            raise SszError(f"{self}: {len(data)} bytes over limit")
        return data

    def hash_tree_root(self, v) -> bytes:
        v = bytes(v)
        root = hashing.merkleize_chunks(
            _pad_chunks(v), (self.limit + 31) // 32)
        return hashing.mix_in_length(root, len(v))

    def default(self):
        return b""

    def coerce(self, v):
        v = bytes(v)
        if len(v) > self.limit:
            raise SszError(f"{self}: {len(v)} bytes over limit")
        return v


# ---------------------------------------------------------------- bitfields


class Bits:
    """Bitfield value: numpy bool array with SSZ byte packing."""

    __slots__ = ("array",)

    def __init__(self, array):
        a = np.array(array, dtype=bool)  # owning copy: frozen below without
        a.setflags(write=False)          # freezing the caller's buffer
        object.__setattr__(self, "array", a)

    @classmethod
    def zeros(cls, n: int) -> "Bits":
        return cls(np.zeros(n, dtype=bool))

    def __len__(self):
        return len(self.array)

    def __getitem__(self, i):
        out = self.array[i]
        return Bits(out) if isinstance(i, slice) else bool(out)

    def __iter__(self):
        return iter(bool(b) for b in self.array)

    def __eq__(self, other):
        return isinstance(other, Bits) and np.array_equal(
            self.array, other.array)

    def __hash__(self):
        return hash(self.to_bytes())

    def __repr__(self):
        return f"Bits({''.join('1' if b else '0' for b in self.array)})"

    def set(self, i: int, v: bool = True) -> "Bits":
        a = self.array.copy()
        a[i] = v
        return Bits(a)

    def count(self) -> int:
        return int(np.count_nonzero(self.array))

    def any(self) -> bool:
        return bool(self.array.any())

    def nonzero_indices(self) -> np.ndarray:
        return np.nonzero(self.array)[0]

    def union(self, other: "Bits") -> "Bits":
        return Bits(self.array | other.array)

    def intersects(self, other: "Bits") -> bool:
        return bool((self.array & other.array).any())

    def covers(self, other: "Bits") -> bool:
        """self is a superset of other's set bits."""
        return bool((other.array & ~self.array).sum() == 0)

    def to_bytes(self) -> bytes:
        return np.packbits(self.array, bitorder="little").tobytes()

    @classmethod
    def from_bytes(cls, data: bytes, n: int) -> "Bits":
        bits = np.unpackbits(np.frombuffer(data, np.uint8),
                             bitorder="little")
        return cls(bits[:n])


class Bitvector(SszType):
    __slots__ = ("length",)
    _cache: dict = {}

    def __new__(cls, length: int):
        hit = cls._cache.get(length)
        if hit is None:
            hit = super().__new__(cls)
            hit.length = length
            cls._cache[length] = hit
        return hit

    def __repr__(self):
        return f"Bitvector[{self.length}]"

    def is_fixed(self):
        return True

    def fixed_size(self):
        return (self.length + 7) // 8

    def serialize(self, v: Bits) -> bytes:
        return v.to_bytes()

    def deserialize(self, data) -> Bits:
        data = bytes(data)
        if len(data) != self.fixed_size():
            raise SszError(f"{self}: got {len(data)} bytes")
        bits = np.unpackbits(np.frombuffer(data, np.uint8),
                             bitorder="little")
        if bits[self.length:].any():
            raise SszError(f"{self}: nonzero padding bits")
        return Bits(bits[: self.length])

    def hash_tree_root(self, v: Bits) -> bytes:
        return hashing.merkleize_chunks(
            _pad_chunks(v.to_bytes()), (self.length + 255) // 256)

    def default(self):
        return Bits.zeros(self.length)

    def coerce(self, v):
        if not isinstance(v, Bits):
            v = Bits(v)
        if len(v) != self.length:
            raise SszError(f"{self}: got {len(v)} bits")
        return v


class Bitlist(SszType):
    __slots__ = ("limit",)
    _cache: dict = {}

    def __new__(cls, limit: int):
        hit = cls._cache.get(limit)
        if hit is None:
            hit = super().__new__(cls)
            hit.limit = limit
            cls._cache[limit] = hit
        return hit

    def __repr__(self):
        return f"Bitlist[{self.limit}]"

    def is_fixed(self):
        return False

    def serialize(self, v: Bits) -> bytes:
        a = np.append(v.array, True)  # delimiter bit
        return np.packbits(a, bitorder="little").tobytes()

    def deserialize(self, data) -> Bits:
        data = bytes(data)
        if not data:
            raise SszError(f"{self}: empty payload (delimiter missing)")
        if data[-1] == 0:
            raise SszError(f"{self}: last byte zero (delimiter missing)")
        bits = np.unpackbits(np.frombuffer(data, np.uint8),
                             bitorder="little")
        n = len(bits) - 1 - int(np.argmax(bits[::-1]))  # last set bit
        if n > self.limit:
            raise SszError(f"{self}: {n} bits over limit")
        # Invariant (no further check needed): n is the index of the last set
        # bit and data[-1] != 0 is enforced above, so the delimiter always
        # lies in the final byte and len(data) == (n + 8) // 8 holds.
        return Bits(bits[:n])

    def hash_tree_root(self, v: Bits) -> bytes:
        root = hashing.merkleize_chunks(
            _pad_chunks(v.to_bytes()), (self.limit + 255) // 256)
        return hashing.mix_in_length(root, len(v))

    def default(self):
        return Bits.zeros(0)

    def coerce(self, v):
        if not isinstance(v, Bits):
            v = Bits(v)
        if len(v) > self.limit:
            raise SszError(f"{self}: {len(v)} bits over limit")
        return v


# ------------------------------------------------------- homogeneous series


class _Series:
    """Shared value wrapper for Vector/List: tuple-backed for composite
    elements, numpy-backed for packed basic elements. Immutable; caches
    hash-tree-root and per-element roots."""

    __slots__ = ("typ", "items", "_htr")

    def __init__(self, typ, items):
        if isinstance(items, np.ndarray):
            items.setflags(write=False)  # constructors pass owned copies;
            # freezing keeps .array mutation from invalidating cached roots
        object.__setattr__(self, "typ", typ)
        object.__setattr__(self, "items", items)
        object.__setattr__(self, "_htr", None)

    def __setattr__(self, *_):
        raise AttributeError(f"{type(self).__name__} is immutable")

    def __len__(self):
        return len(self.items)

    def __getitem__(self, i):
        v = self.items[i]
        if isinstance(i, slice):
            return list(v)
        return v.item() if isinstance(v, np.generic) else v

    def __iter__(self):
        if isinstance(self.items, np.ndarray):
            return iter(self.items.tolist())
        return iter(self.items)

    def __eq__(self, other):
        if not isinstance(other, _Series) or self.typ is not other.typ:
            return NotImplemented
        if isinstance(self.items, np.ndarray):
            return np.array_equal(self.items, other.items)
        return self.items == other.items

    def __hash__(self):
        return hash(self.typ.hash_tree_root(self))

    def __repr__(self):
        inner = ", ".join(repr(x) for x in list(self)[:4])
        more = f", …×{len(self) - 4}" if len(self) > 4 else ""
        return f"{self.typ}[{inner}{more}]"

    @property
    def array(self) -> np.ndarray:
        """numpy view for vectorized paths (basic element types only)."""
        return self.items

    def set(self, i: int, v) -> "_Series":
        v = self.typ.elem.coerce(v)
        if isinstance(self.items, np.ndarray):
            a = self.items.copy()
            a[i] = v
            return type(self)(self.typ, a)
        items = list(self.items)
        items[i] = v
        return type(self)(self.typ, tuple(items))

    def hash_tree_root(self) -> bytes:
        r = self._htr
        if r is None:
            r = self.typ.hash_tree_root(self)
            object.__setattr__(self, "_htr", r)
        return r


class SszVector(_Series):
    __slots__ = ()


class SszList(_Series):
    __slots__ = ()

    def append(self, v) -> "SszList":
        typ = self.typ
        if len(self) >= typ.limit:
            raise SszError(f"{typ}: append over limit")
        v = typ.elem.coerce(v)
        if isinstance(self.items, np.ndarray):
            return SszList(
                typ,
                np.append(self.items,
                          np.asarray(v, dtype=self.items.dtype)))
        return SszList(typ, self.items + (v,))


def _elem_is_packed(elem: SszType) -> bool:
    return isinstance(elem, (UInt, Boolean))


class _SeriesType(SszType):
    __slots__ = ("elem", "value_cls")

    def _pack_chunks(self, v: _Series) -> bytes:
        elem = self.elem
        if isinstance(v.items, np.ndarray):
            return _pad_chunks(v.items.tobytes())
        return _pad_chunks(b"".join(elem.serialize(x) for x in v.items))

    def _elem_roots(self, v: _Series) -> bytes:
        elem = self.elem
        return b"".join(elem.hash_tree_root(x) for x in v.items)

    def _serialize_items(self, v: _Series) -> bytes:
        elem = self.elem
        if isinstance(v.items, np.ndarray):
            return v.items.tobytes()
        if elem.is_fixed():
            return b"".join(elem.serialize(x) for x in v.items)
        parts = [elem.serialize(x) for x in v.items]
        offset = OFFSET_SIZE * len(parts)
        head = bytearray()
        for p in parts:
            head += _U32.pack(offset)
            offset += len(p)
        return bytes(head) + b"".join(parts)

    def _deserialize_items(self, data, count_limit: int,
                           exact_count: Optional[int] = None) -> tuple:
        elem = self.elem
        data = bytes(data)
        if elem.is_fixed():
            size = elem.fixed_size()
            if exact_count is not None:
                if len(data) != size * exact_count:
                    raise SszError(
                        f"{self}: expected {size * exact_count} bytes, "
                        f"got {len(data)}")
                n = exact_count
            else:
                if len(data) % size:
                    raise SszError(f"{self}: length not a multiple of {size}")
                n = len(data) // size
                if n > count_limit:
                    raise SszError(f"{self}: {n} elements over limit")
            if elem.np_dtype is not None:
                arr = np.frombuffer(data, elem.np_dtype)
                if isinstance(elem, Boolean) and not np.isin(
                        arr, (0, 1)).all():
                    raise SszError(f"{self}: invalid boolean")
                return arr.copy()
            return tuple(
                elem.deserialize(data[size * i: size * (i + 1)])
                for i in range(n))
        # variable-size elements: offset table
        if not data:
            if exact_count not in (None, 0):
                raise SszError(f"{self}: empty data for {exact_count} items")
            return ()
        if len(data) < OFFSET_SIZE:
            raise SszError(f"{self}: truncated offset table")
        first = _U32.unpack_from(data, 0)[0]
        if first % OFFSET_SIZE or first == 0:
            raise SszError(f"{self}: bad first offset {first}")
        n = first // OFFSET_SIZE
        if n > count_limit or (exact_count is not None and n != exact_count):
            raise SszError(f"{self}: bad element count {n}")
        if len(data) < first:
            raise SszError(f"{self}: truncated offsets")
        offsets = list(struct.unpack_from(f"<{n}I", data, 0)) + [len(data)]
        out = []
        for i in range(n):
            if not first <= offsets[i] <= offsets[i + 1] <= len(data):
                raise SszError(f"{self}: non-monotonic offsets")
            out.append(elem.deserialize(data[offsets[i]: offsets[i + 1]]))
        return tuple(out)

    def _coerce_items(self, items) -> Any:
        elem = self.elem
        if isinstance(items, _Series):
            items = items.items
        if _elem_is_packed(elem) and elem.np_dtype is not None:
            if isinstance(items, np.ndarray) and items.dtype == elem.np_dtype:
                return items.copy()
            return np.array([elem.coerce(x) for x in items],
                            dtype=elem.np_dtype)
        return tuple(elem.coerce(x) for x in items)


class _VectorType(_SeriesType):
    __slots__ = ("length",)
    _cache: dict = {}

    def __new__(cls, elem: SszType, length: int):
        key = (id(elem), length)
        hit = cls._cache.get(key)
        if hit is None:
            hit = object.__new__(cls)
            hit.elem = elem
            hit.length = length
            cls._cache[key] = hit
        return hit

    def __repr__(self):
        return f"Vector[{self.elem}, {self.length}]"

    def is_fixed(self):
        return self.elem.is_fixed()

    def fixed_size(self):
        return self.elem.fixed_size() * self.length

    def serialize(self, v) -> bytes:
        return self._serialize_items(v)

    def deserialize(self, data) -> SszVector:
        items = self._deserialize_items(data, self.length, self.length)
        return SszVector(self, items)

    def hash_tree_root(self, v) -> bytes:
        if isinstance(v, _Series) and v._htr is not None:
            return v._htr
        if _elem_is_packed(self.elem):
            size = self.elem.fixed_size()
            limit = (self.length * size + 31) // 32
            root = hashing.merkleize_chunks(self._pack_chunks(v), limit)
        else:
            root = hashing.merkleize_chunks(
                self._elem_roots(v), self.length)
        if isinstance(v, _Series):
            object.__setattr__(v, "_htr", root)
        return root

    def default(self) -> SszVector:
        elem = self.elem
        if _elem_is_packed(elem) and elem.np_dtype is not None:
            return SszVector(self, np.zeros(self.length, elem.np_dtype))
        return SszVector(
            self, tuple(elem.default() for _ in range(self.length)))

    def coerce(self, v) -> SszVector:
        if isinstance(v, SszVector) and v.typ is self:
            return v
        items = self._coerce_items(v)
        if len(items) != self.length:
            raise SszError(f"{self}: got {len(items)} elements")
        return SszVector(self, items)


class _ListType(_SeriesType):
    __slots__ = ("limit",)
    _cache: dict = {}

    def __new__(cls, elem: SszType, limit: int):
        key = (id(elem), limit)
        hit = cls._cache.get(key)
        if hit is None:
            hit = object.__new__(cls)
            hit.elem = elem
            hit.limit = limit
            cls._cache[key] = hit
        return hit

    def __repr__(self):
        return f"List[{self.elem}, {self.limit}]"

    def is_fixed(self):
        return False

    def serialize(self, v) -> bytes:
        return self._serialize_items(v)

    def deserialize(self, data) -> SszList:
        items = self._deserialize_items(data, self.limit)
        return SszList(self, items)

    def hash_tree_root(self, v) -> bytes:
        if isinstance(v, _Series) and v._htr is not None:
            return v._htr
        if _elem_is_packed(self.elem):
            size = self.elem.fixed_size()
            limit = (self.limit * size + 31) // 32
            body = hashing.merkleize_chunks(self._pack_chunks(v), limit)
        else:
            body = hashing.merkleize_chunks(self._elem_roots(v), self.limit)
        root = hashing.mix_in_length(body, len(v))
        if isinstance(v, _Series):
            object.__setattr__(v, "_htr", root)
        return root

    def default(self) -> SszList:
        elem = self.elem
        if _elem_is_packed(elem) and elem.np_dtype is not None:
            return SszList(self, np.zeros(0, elem.np_dtype))
        return SszList(self, ())

    def coerce(self, v) -> SszList:
        if isinstance(v, SszList) and v.typ is self:
            return v
        items = self._coerce_items(v)
        if len(items) > self.limit:
            raise SszError(f"{self}: {len(items)} elements over limit")
        return SszList(self, items)


def Vector(elem: SszType, length: int) -> _VectorType:
    return _VectorType(elem, length)


def List(elem: SszType, limit: int) -> _ListType:
    return _ListType(elem, limit)


# ----------------------------------------------------------------- container


class ContainerMeta(type):
    """Makes each Container subclass double as its own SSZ type descriptor.

    NOTE on lookup: names defined in the Container class body (serialize,
    hash_tree_root — called generically as `typ.op(value)` with the value as
    sole argument) shadow the metaclass; descriptor ops with no instance-
    level counterpart (is_fixed, deserialize, default, coerce) live here.
    """

    def __new__(mcs, name, bases, ns):
        fields = []
        for base in bases:
            fields += getattr(base, "FIELDS", [])
        own = ns.get("__annotations__", {})
        own_fields = [
            (fname, ftyp) for fname, ftyp in own.items()
            if isinstance(ftyp, (SszType, ContainerMeta))]
        ns["FIELDS"] = tuple(fields + own_fields)
        ns["__slots__"] = tuple(ns.get("__slots__", ())) + tuple(
            fname for fname, _ in own_fields)
        return super().__new__(mcs, name, bases, ns)

    def is_fixed(cls):
        return all(t.is_fixed() for _, t in cls.FIELDS)

    def fixed_size(cls):
        return sum(t.fixed_size() for _, t in cls.FIELDS)

    def deserialize(cls, data):
        data = bytes(data)
        kwargs = {}
        var_fields = []
        offsets = []
        pos = 0
        fixed_len = sum(
            t.fixed_size() if t.is_fixed() else OFFSET_SIZE
            for _, t in cls.FIELDS)
        if len(data) < fixed_len:
            raise SszError(f"{cls.__name__}: truncated ({len(data)} bytes)")
        for fname, ftyp in cls.FIELDS:
            if ftyp.is_fixed():
                size = ftyp.fixed_size()
                kwargs[fname] = ftyp.deserialize(data[pos: pos + size])
                pos += size
            else:
                offsets.append(_U32.unpack_from(data, pos)[0])
                var_fields.append((fname, ftyp))
                pos += OFFSET_SIZE
        if var_fields:
            if offsets[0] != fixed_len:
                raise SszError(f"{cls.__name__}: bad first offset")
            offsets.append(len(data))
            for i, (fname, ftyp) in enumerate(var_fields):
                if not offsets[i] <= offsets[i + 1] <= len(data):
                    raise SszError(f"{cls.__name__}: non-monotonic offsets")
                kwargs[fname] = ftyp.deserialize(
                    data[offsets[i]: offsets[i + 1]])
        elif len(data) != fixed_len:
            raise SszError(f"{cls.__name__}: trailing bytes")
        return cls(**kwargs)

    def default(cls):
        return cls()

    def coerce(cls, v):
        if isinstance(v, cls):
            return v
        raise SszError(f"expected {cls.__name__}, got {type(v).__name__}")

    @property
    def np_dtype(cls):
        return None


class Container(metaclass=ContainerMeta):
    """Base for SSZ containers. Fields are class annotations whose values
    are SSZ type descriptors (or Container subclasses). Instances are
    immutable; `replace()` derives modified copies; hash-tree-root is
    computed once and cached."""

    __slots__ = ("_htr",)

    def __init__(self, **kwargs):
        cls = type(self)
        for fname, ftyp in cls.FIELDS:
            if fname in kwargs:
                val = ftyp.coerce(kwargs.pop(fname))
            else:
                val = ftyp.default()
            object.__setattr__(self, fname, val)
        if kwargs:
            raise SszError(
                f"{cls.__name__}: unknown fields {sorted(kwargs)}")
        object.__setattr__(self, "_htr", None)

    def __setattr__(self, *_):
        raise AttributeError(f"{type(self).__name__} is immutable")

    def __eq__(self, other):
        if type(other) is not type(self):
            return NotImplemented
        return all(
            _veq(getattr(self, f), getattr(other, f))
            for f, _ in type(self).FIELDS)

    def __hash__(self):
        return hash(self.hash_tree_root())

    def __repr__(self):
        cls = type(self)
        inner = ", ".join(
            f"{f}={getattr(self, f)!r}" for f, _ in cls.FIELDS[:3])
        more = ", …" if len(cls.FIELDS) > 3 else ""
        return f"{cls.__name__}({inner}{more})"

    def replace(self, **kwargs) -> "Container":
        cls = type(self)
        new = object.__new__(cls)
        for fname, ftyp in cls.FIELDS:
            if fname in kwargs:
                val = ftyp.coerce(kwargs.pop(fname))
            else:
                val = getattr(self, fname)
            object.__setattr__(new, fname, val)
        if kwargs:
            raise SszError(f"{cls.__name__}: unknown fields {sorted(kwargs)}")
        object.__setattr__(new, "_htr", None)
        return new

    def hash_tree_root(self) -> bytes:
        r = self._htr
        if r is None:
            cls = type(self)
            roots = b"".join(
                ftyp.hash_tree_root(getattr(self, fname))
                for fname, ftyp in cls.FIELDS)
            r = hashing.merkleize_chunks(roots, len(cls.FIELDS))
            object.__setattr__(self, "_htr", r)
        return r

    def serialize(self) -> bytes:
        cls = type(self)
        head = bytearray()
        tail = bytearray()
        fixed_len = sum(
            t.fixed_size() if t.is_fixed() else OFFSET_SIZE
            for _, t in cls.FIELDS)
        for fname, ftyp in cls.FIELDS:
            val = getattr(self, fname)
            if ftyp.is_fixed():
                head += ftyp.serialize(val)
            else:
                head += _U32.pack(fixed_len + len(tail))
                tail += ftyp.serialize(val)
        return bytes(head + tail)


def _veq(a, b) -> bool:
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        return np.array_equal(a, b)
    return a == b
