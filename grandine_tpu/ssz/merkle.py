"""Merkle proofs and the incremental left-filled tree.

Reference parity: ssz/src/merkle_tree.rs (proof construction) and the
`deposit_tree` crate (incremental deposit Merkle tree, depth 32 with a
length mixin — deposit_tree/src/lib.rs).
"""

from typing import Sequence

from grandine_tpu.core import hashing


def merkle_branch(leaves: Sequence[bytes], index: int, depth: int) -> list:
    """Sibling path for `leaves[index]` in a zero-padded depth-`depth`
    tree (proof production for deposit/commitment inclusion)."""
    branch = []
    level = list(leaves)
    idx = index
    for d in range(depth):
        sibling = idx ^ 1
        branch.append(
            level[sibling] if sibling < len(level) else hashing.ZERO_HASHES[d]
        )
        if len(level) % 2:
            level = level + [hashing.ZERO_HASHES[d]]
        level = [
            hashing.hash_pair(level[i], level[i + 1])
            for i in range(0, len(level), 2)
        ]
        idx >>= 1
    return branch


def verify_merkle_proof(leaf: bytes, branch: Sequence[bytes], depth: int,
                        index: int, root: bytes) -> bool:
    """Spec `is_valid_merkle_branch`."""
    if len(branch) < depth:
        return False
    node = leaf
    for i in range(depth):
        if (index >> i) & 1:
            node = hashing.hash_pair(branch[i], node)
        else:
            node = hashing.hash_pair(node, branch[i])
    return node == root


class MerkleTree:
    """Incremental left-filled binary Merkle tree of fixed depth.

    Only O(depth) state is kept (the left-edge frontier), like the
    reference's deposit tree: appending leaf i updates the frontier and the
    proof for any *past* leaf can be produced if `track` retained it.
    """

    __slots__ = ("depth", "count", "_frontier", "_full_root", "_leaves")

    def __init__(self, depth: int, track_leaves: bool = False):
        self.depth = depth
        self.count = 0
        self._frontier: list = [None] * depth
        self._full_root: bytes | None = None
        self._leaves: list | None = [] if track_leaves else None

    def push(self, leaf: bytes) -> None:
        if self.count >= (1 << self.depth):
            raise ValueError("tree full")
        if self._leaves is not None:
            self._leaves.append(leaf)
        node = leaf
        index = self.count
        for i in range(self.depth):
            if (index >> i) & 1:
                node = hashing.hash_pair(self._frontier[i], node)
            else:
                self._frontier[i] = node
                break
        else:
            # every index bit was 1: the tree just became full and `node`
            # is the finished root — the frontier has nowhere to hold it
            self._full_root = node
        self.count += 1

    def root(self) -> bytes:
        if self.count == (1 << self.depth):
            return self._full_root
        node = hashing.ZERO_HASHES[0]
        index = self.count
        for i in range(self.depth):
            if (index >> i) & 1:
                node = hashing.hash_pair(self._frontier[i], node)
            else:
                node = hashing.hash_pair(node, hashing.ZERO_HASHES[i])
        return node

    def root_with_length(self) -> bytes:
        """Deposit-contract style: hash(root ++ le_count) mixin."""
        return hashing.mix_in_length(self.root(), self.count)

    def proof(self, index: int) -> list:
        """Branch for leaf `index` against the current root (requires
        track_leaves=True; rebuilds the path — O(n) but proof generation
        is a cold path: deposits, API queries)."""
        if self._leaves is None:
            raise ValueError("leaf tracking disabled")
        if not 0 <= index < self.count:
            raise IndexError(index)
        level = list(self._leaves)
        branch = []
        idx = index
        for d in range(self.depth):
            sibling = idx ^ 1
            if sibling < len(level):
                branch.append(level[sibling])
            else:
                branch.append(hashing.ZERO_HASHES[d])
            if len(level) % 2:
                level.append(hashing.ZERO_HASHES[d])
            level = [
                hashing.hash_pair(level[i], level[i + 1])
                for i in range(0, len(level), 2)
            ]
            idx >>= 1
        return branch
