"""BLS12-381 curve groups G1 (E/Fp: y²=x³+4) and G2 (E'/Fp2: y²=x³+4(1+u)).

Jacobian-coordinate point arithmetic generic over the coordinate field
(Fq for G1, Fq2 for G2), plus subgroup checks and cofactor clearing.

Reference equivalents: blst's G1/G2 ops wrapped by `bls/src/public_key.rs`
(aggregation :35-55, subgroup validate :21-27) and `bls/src/secret_key.rs:82-86`
(signing = G2 scalar-mul). The TPU batched versions live in
grandine_tpu/tpu/curve.py and are differentially tested against this file.
"""

from __future__ import annotations

from typing import Generic, TypeVar

from grandine_tpu.crypto import constants
from grandine_tpu.crypto.fields import Fq, Fq2

F = TypeVar("F", Fq, Fq2)


class Point(Generic[F]):
    """Jacobian point (X, Y, Z): affine (X/Z², Y/Z³); Z=0 ⇒ infinity.

    `b` is the curve coefficient (y² = x³ + b); carried on the point so G1
    and G2 share one implementation.
    """

    __slots__ = ("x", "y", "z", "b")

    def __init__(self, x: F, y: F, z: F, b: F) -> None:
        self.x = x
        self.y = y
        self.z = z
        self.b = b

    # -- constructors ------------------------------------------------------
    @staticmethod
    def infinity(b: F) -> "Point[F]":
        one = b.__class__.one()
        return Point(one, one, b.__class__.zero(), b)

    @staticmethod
    def from_affine(x: F, y: F, b: F) -> "Point[F]":
        return Point(x, y, b.__class__.one(), b)

    # -- predicates --------------------------------------------------------
    def is_infinity(self) -> bool:
        return self.z.is_zero()

    def is_on_curve(self) -> bool:
        """Jacobian curve equation: Y² = X³ + b·Z⁶."""
        if self.is_infinity():
            return True
        z2 = self.z.square()
        z6 = z2.square() * z2
        return self.y.square() == self.x.square() * self.x + self.b * z6

    # -- affine view -------------------------------------------------------
    def to_affine(self) -> "tuple[F, F] | None":
        if self.is_infinity():
            return None
        zinv = self.z.inv()
        zinv2 = zinv.square()
        return (self.x * zinv2, self.y * zinv2 * zinv)

    # -- group law ---------------------------------------------------------
    def double(self) -> "Point[F]":
        if self.is_infinity() or self.y.is_zero():
            return Point.infinity(self.b)
        x, y, z = self.x, self.y, self.z
        a = x.square()
        bq = y.square()
        c = bq.square()
        t = (x + bq).square() - a - c
        d = t + t  # 4·x·y²
        e = a + a + a  # 3x²  (curve a-coefficient is 0)
        f = e.square()
        x3 = f - d - d
        eight_c = c + c
        eight_c = eight_c + eight_c
        eight_c = eight_c + eight_c
        y3 = e * (d - x3) - eight_c
        z3 = (y * z) + (y * z)
        return Point(x3, y3, z3, self.b)

    def __add__(self, o: "Point[F]") -> "Point[F]":
        if self.is_infinity():
            return o
        if o.is_infinity():
            return self
        z1z1 = self.z.square()
        z2z2 = o.z.square()
        u1 = self.x * z2z2
        u2 = o.x * z1z1
        s1 = self.y * o.z * z2z2
        s2 = o.y * self.z * z1z1
        if u1 == u2:
            if s1 == s2:
                return self.double()
            return Point.infinity(self.b)
        h = u2 - u1
        i = (h + h).square()
        j = h * i
        rr = (s2 - s1) + (s2 - s1)
        v = u1 * i
        x3 = rr.square() - j - v - v
        y3 = rr * (v - x3) - (s1 * j) - (s1 * j)
        z3 = ((self.z + o.z).square() - z1z1 - z2z2) * h
        return Point(x3, y3, z3, self.b)

    def __neg__(self) -> "Point[F]":
        return Point(self.x, -self.y, self.z, self.b)

    def __sub__(self, o: "Point[F]") -> "Point[F]":
        return self + (-o)

    def mul(self, k: int) -> "Point[F]":
        """Scalar multiplication (double-and-add; variable-time — fine for
        verification of public data; see SURVEY.md §7 on signing side-channels)."""
        if k < 0:
            return (-self).mul(-k)
        result = Point.infinity(self.b)
        base = self
        while k:
            if k & 1:
                result = result + base
            base = base.double()
            k >>= 1
        return result

    # -- subgroup ----------------------------------------------------------
    def in_subgroup(self) -> bool:
        """r-torsion membership via the endomorphism criteria (G1: GLV φ,
        G2: twist-ψ — Bowe, "Faster subgroup checks for BLS12-381", the
        checks blst ships): a 64/127-bit ladder + one endomorphism
        instead of a 255-bit ladder. `in_subgroup_slow` keeps the
        scalar-mul anchor for differential tests."""
        if isinstance(self.x, Fq2):
            return _g2_in_subgroup_fast(self)
        return _g1_in_subgroup_fast(self)

    def in_subgroup_slow(self) -> bool:
        return self.mul(constants.R).is_infinity()

    def __eq__(self, o: object) -> bool:
        if not isinstance(o, Point):
            return NotImplemented
        if self.is_infinity() or o.is_infinity():
            return self.is_infinity() and o.is_infinity()
        z1z1 = self.z.square()
        z2z2 = o.z.square()
        return (
            self.x * z2z2 == o.x * z1z1
            and self.y * o.z * z2z2 == o.y * self.z * z1z1
        )

    def __repr__(self) -> str:
        aff = self.to_affine()
        return f"Point({aff!r})"


# --- canonical generators and curve parameters -----------------------------

B1 = Fq(constants.B_G1)
B2 = Fq2.from_ints(*constants.B_G2)

G1 = Point.from_affine(Fq(constants.G1_X), Fq(constants.G1_Y), B1)
G2 = Point.from_affine(
    Fq2.from_ints(*constants.G2_X), Fq2.from_ints(*constants.G2_Y), B2
)


def g1_infinity() -> Point[Fq]:
    return Point.infinity(B1)


def g2_infinity() -> Point[Fq2]:
    return Point.infinity(B2)


def clear_cofactor_g1(p: Point[Fq]) -> Point[Fq]:
    return p.mul(constants.H1)


def clear_cofactor_g2(p: Point[Fq2]) -> Point[Fq2]:
    """h_eff·P per RFC 9380 §8.8.2 — NOT the full twist cofactor h2.

    Computed via the Budroni–Pintore ψ-endomorphism identity
        h_eff·P = [x²−x−1]·P + [x−1]·ψ(P) + ψ²([2]P)
    (two 64-bit scalar ladders + three ψ applications instead of one
    636-bit ladder — ~5× fewer point ops; this is also how blst clears
    the cofactor). Falls back to the literal h_eff scalar-mul if the ψ
    constants fail their self-check. The RFC 9380 official-vector tests
    pin the result either way."""
    psi = _psi_map()
    if psi is None:  # pragma: no cover — derivation self-check failed
        return p.mul(constants.H_EFF_G2)
    ax = -constants.X  # the BLS parameter is negative

    def mul_x(q: "Point[Fq2]") -> "Point[Fq2]":
        return -(q.mul(ax))  # [x]·q

    t1 = mul_x(p)
    psi_p = psi(p)
    t0 = psi(psi(p.double()))  # ψ²([2]P)
    t2 = mul_x(t1 + psi_p)  # [x²]P + [x]ψ(P)
    return t0 + t2 - t1 - psi_p - p


def _g1_in_subgroup_fast(p: "Point[Fq]") -> bool:
    """P ∈ G1 iff φ(P) == [x²]·P (φ = the cube-root GLV endomorphism,
    which acts as [λ] = [x² mod r] exactly on the r-subgroup)."""
    if p.is_infinity():
        return True
    bx, by = endo_constants()["g1"]
    aff = p.to_affine()
    phi = Point.from_affine(Fq(bx) * aff[0], Fq(by) * aff[1], p.b)
    return phi == p.mul(constants.X * constants.X)


def _g2_in_subgroup_fast(p: "Point[Fq2]") -> bool:
    """P ∈ G2 iff ψ(P) == [x]·P (ψ acts as [t−1] = [x] on the subgroup)."""
    if p.is_infinity():
        return True
    psi = _psi_map()
    if psi is None:  # pragma: no cover — derivation self-check failed
        return p.in_subgroup_slow()
    return psi(p) == -(p.mul(-constants.X))


# ψ = untwist–Frobenius–twist on E'/Fp2: ψ(x, y) = (c_x·x̄, c_y·ȳ) with
# x̄ the Fp2 conjugate. The constants are powers of (1+u); the exact
# power/inverse/sign choice is selected numerically by the Frobenius
# characteristic equation ψ² − [t]ψ + [p] = 0 (t = x+1) on the subgroup.
_PSI = None


def _fq2_pow(base: Fq2, e: int) -> Fq2:
    out = Fq2.from_ints(1, 0)
    while e:
        if e & 1:
            out = out * base
        base = base.square()
        e >>= 1
    return out


def _psi_map():
    global _PSI
    if _PSI is not None:
        return _PSI if _PSI != "failed" else None
    from .constants import P, R, X

    one_plus_u = Fq2.from_ints(1, 1)
    cx0 = _fq2_pow(one_plus_u, (P - 1) // 3)
    cy0 = _fq2_pow(one_plus_u, (P - 1) // 2)

    def conj(v: Fq2) -> Fq2:
        return Fq2(v.c0, -v.c1)

    def make_psi(cx: Fq2, cy: Fq2):
        def psi(pt: "Point[Fq2]") -> "Point[Fq2]":
            aff = pt.to_affine()
            if aff is None:
                return pt
            return Point.from_affine(
                cx * conj(aff[0]), cy * conj(aff[1]), pt.b
            )

        return psi

    for cx in (cx0, cx0.inv()):
        for cy in (cy0, cy0.inv(), -cy0, -(cy0.inv())):
            psi = make_psi(cx, cy)
            q = G2
            lhs = psi(psi(q)) + q.mul(P % R)
            rhs = psi(q).mul((X + 1) % R)
            if (
                lhs.is_on_curve()
                and lhs.to_affine() == rhs.to_affine()
            ):
                _PSI = psi
                global _PSI_CONSTS
                _PSI_CONSTS = (cx, cy)
                return psi
    _PSI = "failed"
    return None


_PSI_CONSTS: "tuple[Fq2, Fq2] | None" = None


def psi_constants_ints() -> "tuple[tuple[int, int], tuple[int, int]]":
    """The verified ψ coordinate-scaling constants as raw ints
    ((cx0, cx1), (cy0, cy1)) — consumed by the device subgroup-check
    kernel (tpu/bls.py batch ψ check)."""
    if _psi_map() is None:
        raise RuntimeError("psi derivation failed")
    cx, cy = _PSI_CONSTS
    return ((cx.c0.n, cx.c1.n), (cy.c0.n, cy.c1.n))


# --- GLV / psi² endomorphism constants --------------------------------------
#
# Both curves admit a degree-1 endomorphism that acts on the prime-order
# subgroup as multiplication by LAMBDA = x² mod r (x = the BLS parameter):
#   - on G1 it is P = (px, py) ↦ (βᵢ·px, ±py) (a cube-root-of-unity twist of
#     the classic GLV map — x² ≡ −λ² mod r for the cube root λ = x²−1);
#   - on G2 it is ψ² (untwist-Frobenius-twist squared), which collapses to
#     coordinate-wise Fp scalings because the Fp2 Frobenius squared is the
#     identity.
# The concrete constants are derived numerically below and asserted against
# scalar multiplication, so there is no sign/root-choice ambiguity to trust.
# They power the half-length dual-scalar ladders in the device kernels
# (grandine_tpu/tpu/curve.py scalar_mul_glv) and the host-side 2D scalar
# decomposition (decompose_glv).

LAMBDA = (constants.X * constants.X) % constants.R


def _derive_endo() -> "dict[str, tuple[int, int]]":
    from .constants import P

    # the two primitive cube roots of unity in Fp
    c = pow(2, (P - 1) // 3, P)
    while pow(c, 3, P) != 1 or c == 1:
        c = pow(c + 1, (P - 1) // 3, P)
    roots = [c, pow(c, 2, P)]
    out: dict = {}
    lam_g1 = G1.mul(LAMBDA).to_affine()
    for bx in roots:
        for by in (1, P - 1):
            cand = (Fq(bx * G1.x.n % P), Fq(by * G1.y.n % P))
            if (cand[0], cand[1]) == lam_g1:
                out["g1"] = (bx, by)
    lam_g2 = G2.mul(LAMBDA).to_affine()
    for bx in roots:
        for by in (1, P - 1):
            cand = (G2.x.scale(Fq(bx)), G2.y.scale(Fq(by)))
            if (cand[0], cand[1]) == lam_g2:
                out["g2"] = (bx, by)
    assert set(out) == {"g1", "g2"}, "endomorphism derivation failed"
    return out


_ENDO: "dict[str, tuple[int, int]] | None" = None


def endo_constants() -> "dict[str, tuple[int, int]]":
    """{'g1': (βx, βy), 'g2': (ωx, ωy)} with (βx·px, βy·py) = [LAMBDA]·P."""
    global _ENDO
    if _ENDO is None:
        _ENDO = _derive_endo()
    return _ENDO


def decompose_glv(k: int) -> "tuple[int, int, int, int]":
    """k ≡ k0 + k1·LAMBDA (mod r) with |k0|, |k1| < 2¹²⁹ (Babai rounding).

    LAMBDA = x² is a primitive SIXTH root of unity mod r (λ² − λ + 1 =
    x⁴ − x² + 1 = r exactly), so the lattice {(a, b) : a + b·λ ≡ 0 (mod r)}
    has the short basis v1 = (λ, −1), v2 = (1, λ − 1) with determinant
    exactly r. Returns (|k0|, sign0, |k1|, sign1) with signs ±1."""
    from .constants import R

    lam = LAMBDA

    def rnd(num: int, den: int) -> int:  # round-half-up, exact integers
        return (2 * num + den) // (2 * den)

    c1 = rnd(k * (lam - 1), R)
    c2 = rnd(k, R)
    k0 = k - c1 * lam - c2
    k1 = c1 - c2 * (lam - 1)
    assert (k0 + k1 * LAMBDA - k) % R == 0
    assert max(abs(k0), abs(k1)).bit_length() <= 129
    return (abs(k0), 1 if k0 >= 0 else -1, abs(k1), 1 if k1 >= 0 else -1)
