"""BLS12-381 parameters.

The base constants (p, r, the BLS parameter x, and the standard generator
coordinates) are the published curve parameters. Everything else in this
module is *derived* from them and cross-checked by the structural identities
asserted at import time (and more thoroughly in tests/test_crypto_*.py):

  r  == x^4 - x^2 + 1
  p  == (x-1)^2 * r / 3 + x
  #E(Fp)    == h1 * r     with h1 = (x-1)^2 / 3
  #E'(Fp2)  == h2 * r     (h2 disambiguated empirically between the two
                           twist orders divisible by r — see derivation
                           notebook reproduced in tests/test_crypto_curves.py)

Role in the framework: the parameter layer below the `bls` API, equivalent
to the constants baked into blst that the reference's `bls` crate wraps
(reference: bls/src/consts.rs, bls/src/signature.rs).
"""

# --- published curve parameters -------------------------------------------

# BLS parameter (the "x" of the BLS12 family); negative for BLS12-381.
X = -0xD201000000010000

# Base field modulus.
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB

# Subgroup order (scalar field modulus).
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

# Curve: E/Fp: y^2 = x^3 + 4.  Twist: E'/Fp2: y^2 = x^3 + 4(1+u)  (D-twist:
# untwist divides by w^2/w^3 where w^6 = 1+u; verified in pairing tests).
B_G1 = 4
B_G2 = (4, 4)  # 4 + 4u

# Standard generator of G1 (subgroup of E(Fp)).
G1_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1

# Standard generator of G2 (subgroup of E'(Fp2)); coordinates are Fp2 = c0 + c1*u.
G2_X = (
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
G2_Y = (
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)

# --- derived constants -----------------------------------------------------

# G1 cofactor: h1 = (x-1)^2 / 3.
H1 = (X - 1) ** 2 // 3

# G2 (twist) cofactor. The twist order n2 = p^2 + 1 - t' for one of the six
# possible twist traces t'; exactly two candidates are divisible by r, and
# the one below is the order that annihilates points of E'(Fp2) (verified
# empirically; see tests/test_crypto_curves.py::test_twist_cofactor_derivation).
H2 = 0x5D543A95414E7F1091D50792876A202CD91DE4547085ABAA68A205B2E5A7DDFA628F1CB4D9E82EF21537E293A6691AE1616EC6E786F0C70CF1C38E31C7238E5

# --- domain separation tags (IETF BLS signature suite / Ethereum 2.0) ------

DST_SIGNATURE = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"
DST_POP = b"BLS_POP_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

# SvdW map constants (RFC 9380 §6.6.1 admissibility; used for G1, whose
# hash-to-curve is unused by the Ethereum min_pk suite — see the G1 note in
# hash_to_curve.py. G2 uses the canonical SSWU+3-isogeny below.)
SVDW_Z_G1 = -3 % P

# --- G2 SSWU + 3-isogeny (the BLS12381G2_XMD:SHA-256_SSWU_RO_ suite) -------
#
# Published parameters from RFC 9380 §8.8.2 and Appendix E.3. Transcription
# errors are self-detecting: tests check (a) the isogeny maps E' points onto
# E (y² = x³ + 4(1+u)), (b) h_eff·P lands in the r-torsion, and (c) the
# end-to-end Appendix J.10.1 known-answer vectors.
#
# E'/Fp2 : y² = x³ + A'x + B' — the 3-isogenous curve SSWU targets.
SSWU_A_G2 = (0, 240)  # 240·u
SSWU_B_G2 = (1012, 1012)  # 1012·(1+u)
SSWU_Z_G2 = (-2 % P, -1 % P)  # -(2+u)

# 3-isogeny E' → E rational map coefficients (Fq2 as (c0, c1) ints).
# x = x_num/x_den, y = y'·y_num/y_den with
#   x_num = Σ K1[i]·x'^i   (deg 3)     x_den = x'² + K2[1]·x' + K2[0]
#   y_num = Σ K3[i]·x'^i   (deg 3)     y_den = x'³ + K4[2]·x'² + K4[1]·x' + K4[0]
ISO3_K1 = (
    (0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
     0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6),
    (0,
     0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71A),
    (0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71E,
     0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38D),
    (0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1,
     0),
)
ISO3_K2 = (
    (0,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA63),
    (0xC,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA9F),
)
ISO3_K3 = (
    (0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
     0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706),
    (0,
     0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97BE),
    (0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71C,
     0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38F),
    (0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10,
     0),
)
ISO3_K4 = (
    (0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB),
    (0,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA9D3),
    (0x12,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA99),
)

# Effective G2 cofactor (RFC 9380 §8.8.2): clear_cofactor(P) = h_eff·P.
# NOT the full twist cofactor h2 — every interoperable implementation uses
# h_eff, so the mapped point differs from h2·P by a scalar and only the
# h_eff choice matches the published suite vectors.
H_EFF_G2 = 0xBC69F08F2EE75B3584C6A0EA91B352888E2A8E9145AD7689986FF031508FFE1329C2F178731DB956D82BF015D1212B02EC0EC69D7477C1AE954CBC06689F6A359894C0ADEBBF6B4E8020005AAA95551

# --- structural identity checks (cheap; heavyweight checks live in tests) --

assert R == X**4 - X**2 + 1
assert P == (X - 1) ** 2 * R // 3 + X
assert P % 4 == 3 and P % 6 == 1
assert (P + 1 - (X + 1)) == H1 * R  # #E(Fp) = h1 * r
_t2 = (X + 1) ** 2 - 2 * P
_n2_cands = {P * P + 1 - _t2, P * P + 1 + _t2}
# h2*r must be one of the six twist orders; the two "quadratic" ones are
# checked cheaply here, membership among all six plus the empirical
# disambiguation is in tests.
assert H2 * R < (P + 1) ** 2  # Hasse bound over Fp2
assert H2 % R != 0
