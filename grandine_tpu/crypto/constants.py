"""BLS12-381 parameters.

The base constants (p, r, the BLS parameter x, and the standard generator
coordinates) are the published curve parameters. Everything else in this
module is *derived* from them and cross-checked by the structural identities
asserted at import time (and more thoroughly in tests/test_crypto_*.py):

  r  == x^4 - x^2 + 1
  p  == (x-1)^2 * r / 3 + x
  #E(Fp)    == h1 * r     with h1 = (x-1)^2 / 3
  #E'(Fp2)  == h2 * r     (h2 disambiguated empirically between the two
                           twist orders divisible by r — see derivation
                           notebook reproduced in tests/test_crypto_curves.py)

Role in the framework: the parameter layer below the `bls` API, equivalent
to the constants baked into blst that the reference's `bls` crate wraps
(reference: bls/src/consts.rs, bls/src/signature.rs).
"""

# --- published curve parameters -------------------------------------------

# BLS parameter (the "x" of the BLS12 family); negative for BLS12-381.
X = -0xD201000000010000

# Base field modulus.
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB

# Subgroup order (scalar field modulus).
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

# Curve: E/Fp: y^2 = x^3 + 4.  Twist: E'/Fp2: y^2 = x^3 + 4(1+u)  (D-twist:
# untwist divides by w^2/w^3 where w^6 = 1+u; verified in pairing tests).
B_G1 = 4
B_G2 = (4, 4)  # 4 + 4u

# Standard generator of G1 (subgroup of E(Fp)).
G1_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1

# Standard generator of G2 (subgroup of E'(Fp2)); coordinates are Fp2 = c0 + c1*u.
G2_X = (
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
G2_Y = (
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)

# --- derived constants -----------------------------------------------------

# G1 cofactor: h1 = (x-1)^2 / 3.
H1 = (X - 1) ** 2 // 3

# G2 (twist) cofactor. The twist order n2 = p^2 + 1 - t' for one of the six
# possible twist traces t'; exactly two candidates are divisible by r, and
# the one below is the order that annihilates points of E'(Fp2) (verified
# empirically; see tests/test_crypto_curves.py::test_twist_cofactor_derivation).
H2 = 0x5D543A95414E7F1091D50792876A202CD91DE4547085ABAA68A205B2E5A7DDFA628F1CB4D9E82EF21537E293A6691AE1616EC6E786F0C70CF1C38E31C7238E5

# --- domain separation tags (IETF BLS signature suite / Ethereum 2.0) ------

# NOTE on conformance: the DSTs are the standard Ethereum values, but our
# map_to_curve is the derivable Shallue–van de Woestijne map rather than the
# SSWU+3-isogeny fast suite (whose isogeny constants cannot be derived from
# first principles without the published tables, unavailable in this
# environment). The scheme is internally consistent (sign/verify/aggregate
# interoperate within this framework); swapping in SSWU constants later
# changes only hash_to_curve.map_to_curve_g2.
DST_SIGNATURE = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"
DST_POP = b"BLS_POP_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

# SvdW map constants (derived by search over small field elements satisfying
# the RFC 9380 §6.6.1 admissibility conditions; derivation in
# tests/test_crypto_hash_to_curve.py).
SVDW_Z_G1 = -3 % P
SVDW_Z_G2 = (-1 % P, -1 % P)  # -(1+u)

# --- structural identity checks (cheap; heavyweight checks live in tests) --

assert R == X**4 - X**2 + 1
assert P == (X - 1) ** 2 * R // 3 + X
assert P % 4 == 3 and P % 6 == 1
assert (P + 1 - (X + 1)) == H1 * R  # #E(Fp) = h1 * r
_t2 = (X + 1) ** 2 - 2 * P
_n2_cands = {P * P + 1 - _t2, P * P + 1 + _t2}
# h2*r must be one of the six twist orders; the two "quadratic" ones are
# checked cheaply here, membership among all six plus the empirical
# disambiguation is in tests.
assert H2 * R < (P + 1) ** 2  # Hasse bound over Fp2
assert H2 % R != 0
