"""Optimal ate pairing on BLS12-381 (pure-Python anchor).

e(P, Q) for P ∈ G1 ⊂ E(Fp), Q ∈ G2 ⊂ E'(Fp2): Q is untwisted into E(Fp12)
(D-twist: divide by w², w³) and the Miller loop runs in affine Fp12
coordinates — deliberately the clearest correct formulation rather than the
fastest; this file anchors the TPU kernels in grandine_tpu/tpu/pairing_kernel.py.

The product structure mirrors the reference's batch verification: N Miller
loops, one shared final exponentiation (`multi_pairing`), which is exactly
what `Signature::multi_verify` exploits (reference: bls/src/signature.rs:96-129).
"""

from __future__ import annotations

from grandine_tpu.crypto.constants import P, R, X
from grandine_tpu.crypto.curves import Point
from grandine_tpu.crypto.fields import Fq, Fq2, Fq6, Fq12

# Φ₁₂(p) = p⁴ - p² + 1 is divisible by r for BLS curves.
assert (P**4 - P**2 + 1) % R == 0
HARD_EXPONENT = (P**4 - P**2 + 1) // R

# Miller loop runs over |x|; x < 0 is handled by conjugating the result.
MILLER_BITS = bin(abs(X))[3:]  # bits below the MSB, msb-first

# w ∈ Fq12 with w² = v, w⁶ = ξ. Untwist divides by w², w³.
_W2 = Fq12(Fq6(Fq2.zero(), Fq2.one(), Fq2.zero()), Fq6.zero())  # = v
_W3 = Fq12(Fq6.zero(), Fq6(Fq2.zero(), Fq2.one(), Fq2.zero()))  # = v·w
_W2_INV = _W2.inv()
_W3_INV = _W3.inv()


def _embed_fq2(a: Fq2) -> Fq12:
    return Fq12(Fq6(a, Fq2.zero(), Fq2.zero()), Fq6.zero())


def _embed_fq(a: Fq) -> Fq12:
    return _embed_fq2(Fq2(a, Fq.zero()))


def untwist(q: Point[Fq2]) -> "tuple[Fq12, Fq12]":
    """Map an affine G2 point on the twist to affine coordinates on E(Fp12)."""
    aff = q.to_affine()
    assert aff is not None
    x, y = aff
    return (_embed_fq2(x) * _W2_INV, _embed_fq2(y) * _W3_INV)


def miller_loop(p: Point[Fq], q: Point[Fq2]) -> Fq12:
    """f_{|x|,Q}(P), conjugated for the negative BLS parameter.

    Returns 1 when either input is the identity (so products over batches
    treat infinity pairs as neutral, matching aggregate semantics).
    """
    if p.is_infinity() or q.is_infinity():
        return Fq12.one()
    p_aff = p.to_affine()
    assert p_aff is not None
    xp, yp = _embed_fq(p_aff[0]), _embed_fq(p_aff[1])
    xq, yq = untwist(q)

    f = Fq12.one()
    xt, yt = xq, yq
    for bit in MILLER_BITS:
        # Doubling step: line through (T, T) evaluated at P.
        lam = (xt.square() + xt.square() + xt.square()) * (yt + yt).inv()
        line = yp - yt - lam * (xp - xt)
        f = f.square() * line
        x2 = lam.square() - xt - xt
        yt = lam * (xt - x2) - yt
        xt = x2
        if bit == "1":
            # Addition step: line through (T, Q) evaluated at P.
            lam = (yq - yt) * (xq - xt).inv()
            line = yp - yt - lam * (xp - xt)
            f = f * line
            x2 = lam.square() - xt - xq
            yt = lam * (xt - x2) - yt
            xt = x2
    # x < 0: f_{x,Q} = conjugate(f_{|x|,Q})  (inverse on the unit cyclotomic
    # subgroup up to final exponentiation).
    return f.conjugate()


def final_exponentiation(f: Fq12) -> Fq12:
    """f^((p¹²-1)/r) via the easy part (Frobenius) and plain-pow hard part."""
    t = f.conjugate() * f.inv()  # f^(p⁶-1)
    t = t.frobenius_n(2) * t  # ^(p²+1)
    return t.pow(HARD_EXPONENT)  # ^((p⁴-p²+1)/r)


def pairing(p: Point[Fq], q: Point[Fq2]) -> Fq12:
    return final_exponentiation(miller_loop(p, q))


def multi_pairing(pairs: "list[tuple[Point[Fq], Point[Fq2]]]") -> Fq12:
    """∏ e(Pᵢ, Qᵢ) with one shared final exponentiation — the algebraic core
    of batch signature verification."""
    f = Fq12.one()
    for p, q in pairs:
        f = f * miller_loop(p, q)
    return final_exponentiation(f)


def pairing_check(pairs: "list[tuple[Point[Fq], Point[Fq2]]]") -> bool:
    """True iff ∏ e(Pᵢ, Qᵢ) == 1."""
    return multi_pairing(pairs).is_one()
