"""Hash-to-curve for BLS12-381 G2 (and G1), following the RFC 9380 structure:

    hash_to_field (expand_message_xmd/SHA-256) → map_to_curve → clear_cofactor

G2 implements the canonical Ethereum suite BLS12381G2_XMD:SHA-256_SSWU_RO_
exactly: simplified SWU on the 3-isogenous curve E' (RFC 9380 §6.6.3,
constants §8.8.2 / Appendix E.3) followed by the published 3-isogeny back to
E and h_eff cofactor clearing. Known-answer conformance vectors:
tests/test_rfc9380_vectors.py (Appendix J.10.1 / K.1).

G1 keeps the derivable Shallue–van de Woestijne map (§6.6.1): the min_pk
ciphersuite never hashes to G1 (messages → G2, keys live unhashed in G1),
so G1 hashing is internal-only; the 11-isogeny SSWU tables can be slotted
in later without touching callers.

Reference equivalent: blst's hash-to-G2 invoked by `SecretKey::sign`
(bls/src/secret_key.rs:82-86) and by all verify paths.
"""

from __future__ import annotations

import hashlib
from typing import Union

from grandine_tpu.crypto import constants
from grandine_tpu.crypto.curves import B1, B2, Point, clear_cofactor_g1, clear_cofactor_g2
from grandine_tpu.crypto.fields import Fq, Fq2

_B_IN_BYTES = 32  # SHA-256 output size
_R_IN_BYTES = 64  # SHA-256 block size
_L = 64  # ceil((381 + 128) / 8)


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """RFC 9380 §5.3.1 expand_message_xmd with SHA-256."""
    ell = (len_in_bytes + _B_IN_BYTES - 1) // _B_IN_BYTES
    if ell > 255 or len_in_bytes > 65535 or len(dst) > 255:
        raise ValueError("expand_message_xmd parameter out of range")
    dst_prime = dst + len(dst).to_bytes(1, "big")
    z_pad = b"\x00" * _R_IN_BYTES
    l_i_b_str = len_in_bytes.to_bytes(2, "big")
    msg_prime = z_pad + msg + l_i_b_str + b"\x00" + dst_prime
    b0 = hashlib.sha256(msg_prime).digest()
    b = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    uniform = b
    prev = b
    for i in range(2, ell + 1):
        prev = hashlib.sha256(
            bytes(x ^ y for x, y in zip(b0, prev)) + i.to_bytes(1, "big") + dst_prime
        ).digest()
        uniform += prev
    return uniform[:len_in_bytes]


def hash_to_field_fq2(msg: bytes, dst: bytes, count: int) -> "list[Fq2]":
    """RFC 9380 §5.2 hash_to_field with m=2, L=64."""
    len_in_bytes = count * 2 * _L
    uniform = expand_message_xmd(msg, dst, len_in_bytes)
    out = []
    for i in range(count):
        comps = []
        for j in range(2):
            off = _L * (j + i * 2)
            comps.append(int.from_bytes(uniform[off : off + _L], "big") % constants.P)
        out.append(Fq2.from_ints(*comps))
    return out


def hash_to_field_fq(msg: bytes, dst: bytes, count: int) -> "list[Fq]":
    len_in_bytes = count * _L
    uniform = expand_message_xmd(msg, dst, len_in_bytes)
    return [
        Fq(int.from_bytes(uniform[_L * i : _L * (i + 1)], "big")) for i in range(count)
    ]


FieldElem = Union[Fq, Fq2]


class _SvdwConstants:
    """Derived SvdW constants for a curve y² = x³ + b (a = 0)."""

    def __init__(self, b: FieldElem, z: FieldElem) -> None:
        one = b.__class__.one()
        self.b = b
        self.z = z
        g = lambda x: x.square() * x + b  # noqa: E731
        gz = g(z)
        three_z2 = z.square() + z.square() + z.square()
        assert not gz.is_zero() and not three_z2.is_zero()
        self.c1 = gz
        half = Fq((constants.P + 1) // 2)
        if isinstance(z, Fq2):
            self.c2 = -z.scale(half)
        else:
            self.c2 = -(z * half)
        c3 = (-(gz * three_z2)).sqrt()
        assert c3 is not None, "SvdW Z admissibility: -g(Z)(3Z²) must be square"
        if c3.sgn0() == 1:
            c3 = -c3
        self.c3 = c3
        four = one + one + one + one
        self.c4 = -(four * gz) * three_z2.inv()
        # admissibility condition (iv)
        assert g(self.c2).is_square() or gz.is_square()


_SVDW_G1 = _SvdwConstants(B1, Fq(constants.SVDW_Z_G1))


def _cmov(a: FieldElem, b: FieldElem, c: bool) -> FieldElem:
    return b if c else a


def _map_to_curve_svdw(u: FieldElem, k: _SvdwConstants) -> "tuple[FieldElem, FieldElem]":
    """RFC 9380 SvdW straight-line program (a = 0 curves)."""
    one = u.__class__.one()
    g = lambda x: x.square() * x + k.b  # noqa: E731

    tv1 = u.square() * k.c1
    tv2 = one + tv1
    tv1 = one - tv1
    tv3 = tv1 * tv2
    tv3 = tv3.inv() if not tv3.is_zero() else tv3  # inv0
    tv4 = u * tv1 * tv3 * k.c3
    x1 = k.c2 - tv4
    gx1 = g(x1)
    e1 = gx1.is_square()
    x2 = k.c2 + tv4
    gx2 = g(x2)
    e2 = gx2.is_square() and not e1
    x3 = tv2.square() * tv3
    x3 = x3.square() * k.c4 + k.z
    x = _cmov(x3, x1, e1)
    x = _cmov(x, x2, e2)
    gx = g(x)
    y = gx.sqrt()
    assert y is not None  # guaranteed by construction
    if u.sgn0() != y.sgn0():
        y = -y
    return x, y


# --- G2: simplified SWU on E' + 3-isogeny (RFC 9380 §6.6.2/§6.6.3) --------

_SSWU_A = Fq2.from_ints(*constants.SSWU_A_G2)
_SSWU_B = Fq2.from_ints(*constants.SSWU_B_G2)
_SSWU_Z = Fq2.from_ints(*constants.SSWU_Z_G2)
_ISO3_K1 = tuple(Fq2.from_ints(*k) for k in constants.ISO3_K1)
_ISO3_K2 = tuple(Fq2.from_ints(*k) for k in constants.ISO3_K2)
_ISO3_K3 = tuple(Fq2.from_ints(*k) for k in constants.ISO3_K3)
_ISO3_K4 = tuple(Fq2.from_ints(*k) for k in constants.ISO3_K4)


def _map_to_curve_sswu_g2(u: Fq2) -> "tuple[Fq2, Fq2]":
    """RFC 9380 §6.6.2 simplified SWU onto E': y² = x³ + A'x + B'."""
    a, b, z = _SSWU_A, _SSWU_B, _SSWU_Z
    u2 = u.square()
    tv1 = z * u2
    tv2 = tv1.square() + tv1
    x1_num = b * (tv2 + Fq2.one())
    if tv2.is_zero():
        x1_den = a * z
    else:
        x1_den = -(a * tv2)
    # g(x) = x³ + a·x + b evaluated as fraction num/den³ to avoid inversions
    # is overkill for the anchor: invert directly (anchor favors clarity).
    x1 = x1_num * x1_den.inv()
    gx1 = x1.square() * x1 + a * x1 + b
    y = gx1.sqrt()
    if y is not None:
        x = x1
    else:
        x2 = tv1 * x1
        gx2 = x2.square() * x2 + a * x2 + b
        x, y = x2, gx2.sqrt()
    assert y is not None
    if u.sgn0() != y.sgn0():
        y = -y
    return x, y


def _horner(coeffs: "tuple[Fq2, ...]", x: Fq2) -> Fq2:
    acc = coeffs[-1]
    for c in reversed(coeffs[:-1]):
        acc = acc * x + c
    return acc


def _iso3_map(x: Fq2, y: Fq2) -> "tuple[Fq2, Fq2] | None":
    """The published 3-isogeny E' → E (RFC 9380 Appendix E.3).

    Returns None for inputs in the isogeny kernel (x_den/y_den = 0), which
    map to the identity — unreachable via hash_to_g2 (it would require
    inverting SHA-256) but map_to_curve_g2 accepts arbitrary field elements.
    """
    x_den = _horner(_ISO3_K2 + (Fq2.one(),), x)
    y_den = _horner(_ISO3_K4 + (Fq2.one(),), x)
    if x_den.is_zero() or y_den.is_zero():
        return None
    x_num = _horner(_ISO3_K1, x)
    y_num = _horner(_ISO3_K3, x)
    return x_num * x_den.inv(), y * y_num * y_den.inv()


def map_to_curve_g2(u: Fq2) -> Point[Fq2]:
    """SSWU + 3-isogeny — the BLS12381G2_XMD:SHA-256_SSWU_RO_ map."""
    xp, yp = _map_to_curve_sswu_g2(u)
    image = _iso3_map(xp, yp)
    if image is None:
        return Point.infinity(B2)
    x, y = image
    return Point.from_affine(x, y, B2)


def map_to_curve_g1(u: Fq) -> Point[Fq]:
    x, y = _map_to_curve_svdw(u, _SVDW_G1)
    return Point.from_affine(x, y, B1)


def hash_to_g2(msg: bytes, dst: bytes = constants.DST_SIGNATURE) -> Point[Fq2]:
    """hash_to_curve for G2 (random-oracle construction: two maps + add)."""
    u0, u1 = hash_to_field_fq2(msg, dst, 2)
    q = map_to_curve_g2(u0) + map_to_curve_g2(u1)
    return clear_cofactor_g2(q)


def hash_to_g1(msg: bytes, dst: bytes) -> Point[Fq]:
    u0, u1 = hash_to_field_fq(msg, dst, 2)
    q = map_to_curve_g1(u0) + map_to_curve_g1(u1)
    return clear_cofactor_g1(q)
