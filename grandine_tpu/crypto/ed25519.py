"""Host Ed25519 (RFC 8032) — the scalar twin of the batched device
kernel in tpu/ed25519.py.

Pure-Python exact integer arithmetic over curve25519 in twisted-Edwards
form (a = −1, extended coordinates). This is the bisection leaf and the
degradation target for the `ed25519` scheduler lane, so the ONE verify
semantics both sides must agree on bit-for-bit is fixed here:

  COFACTORED verification —  [8][S]B == [8]R + [8][k]A

(the batch-friendly equation from the RFC 8032 security notes; it is
the only per-signature rule CONSISTENT with random-linear-combination
batching, because the RLC sum is taken before the shared ×8 cofactor
clearing kills small-order components). Decode rules are strict RFC
8032: non-canonical y (≥ p) rejected, S ≥ L rejected (malleability),
x = 0 with sign bit set rejected. Signatures that differ between
cofactored and cofactorless verification (torsion in R or A) ACCEPT
here, matching the device batch — the RFC permits either rule; the
plane just has to pick one and be consistent everywhere.

Point helpers (decompress/add/mul/neg) are exported for the tests that
craft torsion-edge specimens.
"""

from __future__ import annotations

import hashlib

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P

# base point: y = 4/5, x recovered with even parity
_BY = (4 * pow(5, P - 2, P)) % P
_BX = 15112221349535400772501151409588531511454012693041857206046113283949847762202
#: extended-coordinate points are (X, Y, Z, T) with x = X/Z, y = Y/Z,
#: T = XY/Z
BASE = (_BX, _BY, 1, (_BX * _BY) % P)
IDENTITY = (0, 1, 1, 0)
#: the order-2 torsion point (0, −1) — torsion-edge specimen material
ORDER2 = (0, P - 1, 1, 0)


def sha512(s: bytes) -> bytes:
    return hashlib.sha512(s).digest()


def point_add(p, q):
    """Unified add-2008-hwcd-3 (a = −1): complete — also the doubling."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = 2 * t1 * D % P * t2 % P
    d = 2 * z1 * z2 % P
    e, f, g, h = (b - a) % P, (d - c) % P, (d + c) % P, (b + a) % P
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def point_neg(p):
    x, y, z, t = p
    return ((-x) % P, y, z, (-t) % P)


def point_mul(s: int, p):
    """[s]P, double-and-add (host scalar path — exactness over speed)."""
    q = IDENTITY
    while s > 0:
        if s & 1:
            q = point_add(q, p)
        p = point_add(p, p)
        s >>= 1
    return q


def point_equal(p, q) -> bool:
    # x1/z1 == x2/z2  ∧  y1/z1 == y2/z2, cross-multiplied
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return (x1 * z2 - x2 * z1) % P == 0 and (y1 * z2 - y2 * z1) % P == 0


def _recover_x(y: int, sign: int):
    if y >= P:
        return None
    x2 = (y * y - 1) * pow(D * y * y + 1, P - 2, P) % P
    if x2 == 0:
        if sign:
            return None
        return 0
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * pow(2, (P - 1) // 4, P) % P
    if (x * x - x2) % P != 0:
        return None
    if (x & 1) != sign:
        x = P - x
    return x


def point_compress(p) -> bytes:
    x, y, z, _ = p
    zinv = pow(z, P - 2, P)
    x, y = x * zinv % P, y * zinv % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def point_decompress(b: bytes):
    """32 bytes → extended point, or None (strict RFC 8032 decode)."""
    if len(b) != 32:
        return None
    enc = int.from_bytes(b, "little")
    sign = enc >> 255
    y = enc & ((1 << 255) - 1)
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def secret_expand(secret: bytes):
    """32-byte seed → (clamped scalar a, prefix) — RFC 8032 §5.1.5."""
    if len(secret) != 32:
        raise ValueError("ed25519 secret must be 32 bytes")
    h = sha512(secret)
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def secret_to_public(secret: bytes) -> bytes:
    a, _ = secret_expand(secret)
    return point_compress(point_mul(a, BASE))


def sign(secret: bytes, msg: bytes) -> bytes:
    """RFC 8032 §5.1.6 (test-vector + bench traffic generation)."""
    a, prefix = secret_expand(secret)
    pk = point_compress(point_mul(a, BASE))
    r = int.from_bytes(sha512(prefix + msg), "little") % L
    r_enc = point_compress(point_mul(r, BASE))
    k = int.from_bytes(sha512(r_enc + pk + msg), "little") % L
    s = (r + k * a) % L
    return r_enc + s.to_bytes(32, "little")


def verify(public: bytes, msg: bytes, signature: bytes) -> bool:
    """Cofactored single verify: [8][S]B == [8]R + [8][k]A, evaluated as
    8·(S·B − R − k·A) == identity — one exact host evaluation of the
    same group equation the device batch takes an RLC over."""
    if len(signature) != 64:
        return False
    a_pt = point_decompress(bytes(public))
    if a_pt is None:
        return False
    r_pt = point_decompress(signature[:32])
    if r_pt is None:
        return False
    s = int.from_bytes(signature[32:], "little")
    if s >= L:  # malleability bound (RFC 8032 §5.1.7 step 1)
        return False
    k = int.from_bytes(
        sha512(bytes(signature[:32]) + bytes(public) + bytes(msg)), "little"
    ) % L
    acc = point_mul(s, BASE)
    acc = point_add(acc, point_neg(r_pt))
    acc = point_add(acc, point_neg(point_mul(k, a_pt)))
    return point_equal(point_mul(8, acc), IDENTITY)


def check_item(item) -> bool:
    """VerifyItem adapter (ed25519 lane geometry: message bytes, 64-byte
    signature, public_keys = (32-byte key,)) — the scheduler's bisection
    leaf and host degradation pass."""
    keys = item.public_keys
    if keys is None or len(keys) != 1:
        return False
    return verify(bytes(keys[0]), item.message, item.signature)


__all__ = [
    "P", "L", "D", "BASE", "IDENTITY", "ORDER2",
    "point_add", "point_neg", "point_mul", "point_equal",
    "point_compress", "point_decompress",
    "secret_expand", "secret_to_public", "sign", "verify", "check_item",
]
