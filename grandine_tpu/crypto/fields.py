"""BLS12-381 field tower: Fq, Fq2 = Fq[u]/(u²+1), Fq6 = Fq2[v]/(v³-ξ),
Fq12 = Fq6[w]/(w²-v), with ξ = 1 + u.

Pure-Python arbitrary-precision reference implementation. This is the
correctness anchor for the JAX/TPU limb-vectorized field arithmetic in
grandine_tpu/tpu/ — every TPU kernel is differentially tested against these
classes. (Reference equivalent: the Fp/Fp2/Fp12 arithmetic inside blst that
the reference's `bls` crate links; bls/src/signature.rs:3-7.)

Design notes:
  - Elements are immutable; operators return new objects.
  - Fq.sqrt uses p ≡ 3 (mod 4); Fq2.sqrt uses the norm/half trick.
  - Frobenius coefficients are computed once at import from ξ — not copied
    from tables — and are exported for the TPU backend via
    `frobenius_coefficients()`.
"""

from __future__ import annotations

from functools import lru_cache

from grandine_tpu.crypto.constants import P


class Fq:
    """Base field element (mod P)."""

    __slots__ = ("n",)

    def __init__(self, n: int) -> None:
        self.n = n % P

    # -- arithmetic --------------------------------------------------------
    def __add__(self, o: "Fq") -> "Fq":
        return Fq(self.n + o.n)

    def __sub__(self, o: "Fq") -> "Fq":
        return Fq(self.n - o.n)

    def __mul__(self, o: "Fq") -> "Fq":
        return Fq(self.n * o.n)

    def __neg__(self) -> "Fq":
        return Fq(-self.n)

    def square(self) -> "Fq":
        return Fq(self.n * self.n)

    def inv(self) -> "Fq":
        if self.n == 0:
            raise ZeroDivisionError("inverse of 0 in Fq")
        return Fq(pow(self.n, P - 2, P))

    def pow(self, e: int) -> "Fq":
        return Fq(pow(self.n, e, P))

    def conjugate(self) -> "Fq":
        return self

    def frobenius(self) -> "Fq":
        return self

    # -- predicates --------------------------------------------------------
    def is_zero(self) -> bool:
        return self.n == 0

    def is_square(self) -> bool:
        return self.n == 0 or pow(self.n, (P - 1) // 2, P) == 1

    def sqrt(self) -> "Fq | None":
        if self.n == 0:
            return Fq(0)
        s = pow(self.n, (P + 1) // 4, P)  # p ≡ 3 (mod 4)
        return Fq(s) if s * s % P == self.n else None

    def sgn0(self) -> int:
        return self.n & 1

    # -- misc --------------------------------------------------------------
    def __eq__(self, o: object) -> bool:
        return isinstance(o, Fq) and self.n == o.n

    def __hash__(self) -> int:
        return hash(("Fq", self.n))

    def __repr__(self) -> str:
        return f"Fq(0x{self.n:x})"

    @staticmethod
    def zero() -> "Fq":
        return Fq(0)

    @staticmethod
    def one() -> "Fq":
        return Fq(1)


class Fq2:
    """Fq2 = Fq[u] / (u² + 1); element c0 + c1·u."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fq, c1: Fq) -> None:
        self.c0 = c0
        self.c1 = c1

    @staticmethod
    def from_ints(c0: int, c1: int) -> "Fq2":
        return Fq2(Fq(c0), Fq(c1))

    # -- arithmetic --------------------------------------------------------
    def __add__(self, o: "Fq2") -> "Fq2":
        return Fq2(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fq2") -> "Fq2":
        return Fq2(self.c0 - o.c0, self.c1 - o.c1)

    def __mul__(self, o: "Fq2") -> "Fq2":
        a, b, c, d = self.c0, self.c1, o.c0, o.c1
        return Fq2(a * c - b * d, a * d + b * c)

    def __neg__(self) -> "Fq2":
        return Fq2(-self.c0, -self.c1)

    def square(self) -> "Fq2":
        a, b = self.c0, self.c1
        return Fq2((a + b) * (a - b), (a * b) + (a * b))

    def scale(self, k: Fq) -> "Fq2":
        return Fq2(self.c0 * k, self.c1 * k)

    def inv(self) -> "Fq2":
        a, b = self.c0, self.c1
        norm_inv = (a * a + b * b).inv()
        return Fq2(a * norm_inv, -b * norm_inv)

    def pow(self, e: int) -> "Fq2":
        result, base = Fq2.one(), self
        while e:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def conjugate(self) -> "Fq2":
        return Fq2(self.c0, -self.c1)

    def frobenius(self) -> "Fq2":
        # x ↦ x^p is conjugation in Fq2.
        return self.conjugate()

    def mul_by_xi(self) -> "Fq2":
        """Multiply by ξ = 1 + u."""
        return Fq2(self.c0 - self.c1, self.c0 + self.c1)

    # -- predicates --------------------------------------------------------
    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero()

    def is_square(self) -> bool:
        # x^((q²-1)/2) = N(x)^((q-1)/2) for q = p, so x is a square in Fq2
        # iff its norm c0²+c1² is a quadratic residue in Fq.
        return (self.c0 * self.c0 + self.c1 * self.c1).is_square()

    def sqrt(self) -> "Fq2 | None":
        a, b = self.c0, self.c1
        if b.is_zero():
            s = a.sqrt()
            if s is not None:
                return Fq2(s, Fq.zero())
            s = (-a).sqrt()
            if s is not None:
                return Fq2(Fq.zero(), s)  # (s·u)² = -s² = a
            return None
        norm = a * a + b * b
        s = norm.sqrt()
        if s is None:
            return None
        half = _HALF
        for sign in (s, -s):
            t2 = (a + sign) * half
            t = t2.sqrt()
            if t is not None and not t.is_zero():
                cand = Fq2(t, b * (t + t).inv())
                if cand.square() == self:
                    return cand
        return None

    def sgn0(self) -> int:
        # RFC 9380 sgn0 for m=2.
        sign_0 = self.c0.n & 1
        zero_0 = self.c0.n == 0
        return sign_0 | (zero_0 & (self.c1.n & 1))

    # -- misc --------------------------------------------------------------
    def __eq__(self, o: object) -> bool:
        return isinstance(o, Fq2) and self.c0 == o.c0 and self.c1 == o.c1

    def __hash__(self) -> int:
        return hash(("Fq2", self.c0.n, self.c1.n))

    def __repr__(self) -> str:
        return f"Fq2(0x{self.c0.n:x}, 0x{self.c1.n:x})"

    @staticmethod
    def zero() -> "Fq2":
        return Fq2(Fq.zero(), Fq.zero())

    @staticmethod
    def one() -> "Fq2":
        return Fq2(Fq.one(), Fq.zero())


#: 1/2 in Fq (used by Fq2.sqrt and the SvdW constants).
_HALF = Fq((P + 1) // 2)

#: ξ — the Fq6 non-residue (v³ = ξ).
XI = Fq2.from_ints(1, 1)


class Fq6:
    """Fq6 = Fq2[v] / (v³ - ξ); element c0 + c1·v + c2·v²."""

    __slots__ = ("c0", "c1", "c2")

    def __init__(self, c0: Fq2, c1: Fq2, c2: Fq2) -> None:
        self.c0 = c0
        self.c1 = c1
        self.c2 = c2

    # -- arithmetic --------------------------------------------------------
    def __add__(self, o: "Fq6") -> "Fq6":
        return Fq6(self.c0 + o.c0, self.c1 + o.c1, self.c2 + o.c2)

    def __sub__(self, o: "Fq6") -> "Fq6":
        return Fq6(self.c0 - o.c0, self.c1 - o.c1, self.c2 - o.c2)

    def __neg__(self) -> "Fq6":
        return Fq6(-self.c0, -self.c1, -self.c2)

    def __mul__(self, o: "Fq6") -> "Fq6":
        a0, a1, a2 = self.c0, self.c1, self.c2
        b0, b1, b2 = o.c0, o.c1, o.c2
        t0, t1, t2 = a0 * b0, a1 * b1, a2 * b2
        c0 = t0 + ((a1 + a2) * (b1 + b2) - t1 - t2).mul_by_xi()
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1 + t2.mul_by_xi()
        c2 = (a0 + a2) * (b0 + b2) - t0 - t2 + t1
        return Fq6(c0, c1, c2)

    def square(self) -> "Fq6":
        return self * self

    def scale2(self, k: Fq2) -> "Fq6":
        return Fq6(self.c0 * k, self.c1 * k, self.c2 * k)

    def mul_by_v(self) -> "Fq6":
        """Multiply by v (used by Fq12 multiplication)."""
        return Fq6(self.c2.mul_by_xi(), self.c0, self.c1)

    def inv(self) -> "Fq6":
        a0, a1, a2 = self.c0, self.c1, self.c2
        A = a0.square() - (a1 * a2).mul_by_xi()
        B = a2.square().mul_by_xi() - a0 * a1
        C = a1.square() - a0 * a2
        F = a0 * A + (a2 * B + a1 * C).mul_by_xi()
        f_inv = F.inv()
        return Fq6(A * f_inv, B * f_inv, C * f_inv)

    def frobenius(self) -> "Fq6":
        g1, g2 = _FROB6_G1, _FROB6_G2
        return Fq6(
            self.c0.frobenius(),
            self.c1.frobenius() * g1,
            self.c2.frobenius() * g2,
        )

    # -- misc --------------------------------------------------------------
    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero() and self.c2.is_zero()

    def __eq__(self, o: object) -> bool:
        return (
            isinstance(o, Fq6)
            and self.c0 == o.c0
            and self.c1 == o.c1
            and self.c2 == o.c2
        )

    def __hash__(self) -> int:
        return hash(("Fq6", self.c0, self.c1, self.c2))

    def __repr__(self) -> str:
        return f"Fq6({self.c0!r}, {self.c1!r}, {self.c2!r})"

    @staticmethod
    def zero() -> "Fq6":
        return Fq6(Fq2.zero(), Fq2.zero(), Fq2.zero())

    @staticmethod
    def one() -> "Fq6":
        return Fq6(Fq2.one(), Fq2.zero(), Fq2.zero())


class Fq12:
    """Fq12 = Fq6[w] / (w² - v); element c0 + c1·w."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fq6, c1: Fq6) -> None:
        self.c0 = c0
        self.c1 = c1

    # -- arithmetic --------------------------------------------------------
    def __add__(self, o: "Fq12") -> "Fq12":
        return Fq12(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fq12") -> "Fq12":
        return Fq12(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self) -> "Fq12":
        return Fq12(-self.c0, -self.c1)

    def __mul__(self, o: "Fq12") -> "Fq12":
        a0, a1, b0, b1 = self.c0, self.c1, o.c0, o.c1
        t0 = a0 * b0
        t1 = a1 * b1
        c0 = t0 + t1.mul_by_v()
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1
        return Fq12(c0, c1)

    def square(self) -> "Fq12":
        return self * self

    def inv(self) -> "Fq12":
        a0, a1 = self.c0, self.c1
        denom = (a0.square() - a1.square().mul_by_v()).inv()
        return Fq12(a0 * denom, -(a1 * denom))

    def pow(self, e: int) -> "Fq12":
        if e < 0:
            return self.inv().pow(-e)
        result, base = Fq12.one(), self
        while e:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def conjugate(self) -> "Fq12":
        """x ↦ x^(p⁶): negates the w-coefficient. For elements on the
        cyclotomic subgroup (unit norm) this is the inverse."""
        return Fq12(self.c0, -self.c1)

    def frobenius(self) -> "Fq12":
        gw = _FROB12_GW  # ξ^((p-1)/6) ∈ Fq2
        return Fq12(self.c0.frobenius(), self.c1.frobenius().scale2(gw))

    def frobenius_n(self, n: int) -> "Fq12":
        out = self
        for _ in range(n % 12):
            out = out.frobenius()
        return out

    # -- misc --------------------------------------------------------------
    def is_one(self) -> bool:
        return self == Fq12.one()

    def __eq__(self, o: object) -> bool:
        return isinstance(o, Fq12) and self.c0 == o.c0 and self.c1 == o.c1

    def __hash__(self) -> int:
        return hash(("Fq12", self.c0, self.c1))

    def __repr__(self) -> str:
        return f"Fq12({self.c0!r}, {self.c1!r})"

    @staticmethod
    def zero() -> "Fq12":
        return Fq12(Fq6.zero(), Fq6.zero())

    @staticmethod
    def one() -> "Fq12":
        return Fq12(Fq6.one(), Fq6.zero())


# --- Frobenius coefficients (derived at import) ----------------------------

assert (P - 1) % 6 == 0
_FROB6_G1 = XI.pow((P - 1) // 3)
_FROB6_G2 = XI.pow(2 * (P - 1) // 3)
_FROB12_GW = XI.pow((P - 1) // 6)


@lru_cache(maxsize=None)
def frobenius_coefficients() -> dict:
    """Export the derived Frobenius coefficients (for the TPU backend).

    Returns integer pairs (c0, c1) for each Fq2 coefficient:
      fq6_g1 = ξ^((p-1)/3), fq6_g2 = ξ^(2(p-1)/3), fq12_gw = ξ^((p-1)/6)
    """
    return {
        "fq6_g1": (_FROB6_G1.c0.n, _FROB6_G1.c1.n),
        "fq6_g2": (_FROB6_G2.c0.n, _FROB6_G2.c1.n),
        "fq12_gw": (_FROB12_GW.c0.n, _FROB12_GW.c1.n),
    }


def batch_inverse(values, modulus):
    """Montgomery-trick batch inversion: ONE modular inverse for N values.
    Zeros map to zero (callers decide whether zero input is an error).
    Shared by the KZG Fr math and the host point-conversion paths."""
    n = len(values)
    prefix = [1] * (n + 1)
    for i, v in enumerate(values):
        prefix[i + 1] = prefix[i] * (v if v else 1) % modulus
    inv = pow(prefix[n], modulus - 2, modulus)
    out = [0] * n
    for i in range(n - 1, -1, -1):
        v = values[i]
        if v:
            out[i] = prefix[i] * inv % modulus
            inv = inv * v % modulus
    return out
