"""BLS signature API (min_pk: 48-byte public keys in G1, 96-byte signatures
in G2) — the equivalent of the reference's `bls` crate public surface:

  SecretKey.sign                    (bls/src/secret_key.rs:82-86)
  PublicKey aggregation/validate    (bls/src/public_key.rs:21-55)
  Signature.verify                  (bls/src/signature.rs:49)
  Signature.aggregate[_in_place]    (bls/src/signature.rs:64-75)
  fast_aggregate_verify             (bls/src/signature.rs:78-93)
  multi_verify (batch, RLC)         (bls/src/signature.rs:96-129)
  CachedPublicKey                   (bls/src/cached_public_key.rs)

Point serialization is the ZCash/Ethereum compressed encoding (flag bits in
the top three bits of the first byte; Fp2 x-coordinate serialized c1 ‖ c0).

This module is the pure-Python correctness anchor. The TPU batch backend
(`grandine_tpu.tpu.bls.TpuBlsBackend`) mirrors its policy semantics; the
consensus layer chooses between them at its Verifier seam (the equivalent
of the reference's `helper_functions/src/verifier.rs:16-69`).
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import secrets
import threading
from typing import Iterable, Optional, Sequence

from grandine_tpu.crypto import constants
from grandine_tpu.crypto.curves import (
    B1,
    B2,
    G1,
    G2,
    Point,
    g1_infinity,
    g2_infinity,
)
from grandine_tpu.crypto.fields import Fq, Fq2
from grandine_tpu.crypto.hash_to_curve import hash_to_g2

P = constants.P
R = constants.R

_COMPRESSED_FLAG = 0x80
_INFINITY_FLAG = 0x40
_SIGN_FLAG = 0x20


class BlsError(ValueError):
    pass


# --- point (de)serialization ----------------------------------------------


def g1_to_bytes(p: Point[Fq]) -> bytes:
    if p.is_infinity():
        return bytes([_COMPRESSED_FLAG | _INFINITY_FLAG]) + b"\x00" * 47
    aff = p.to_affine()
    assert aff is not None
    x, y = aff
    flags = _COMPRESSED_FLAG
    if y.n > P - y.n:
        flags |= _SIGN_FLAG
    raw = x.n.to_bytes(48, "big")
    return bytes([raw[0] | flags]) + raw[1:]


def g1_from_bytes(data: bytes, subgroup_check: bool = True) -> Point[Fq]:
    if len(data) != 48:
        raise BlsError("G1 compressed point must be 48 bytes")
    flags = data[0]
    if not flags & _COMPRESSED_FLAG:
        raise BlsError("uncompressed G1 encoding not supported")
    if flags & _INFINITY_FLAG:
        if (flags & ~(_COMPRESSED_FLAG | _INFINITY_FLAG)) or any(data[1:]):
            raise BlsError("malformed G1 infinity encoding")
        return g1_infinity()
    x_int = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
    if x_int >= P:
        raise BlsError("G1 x-coordinate out of range")
    x = Fq(x_int)
    y = (x.square() * x + B1).sqrt()
    if y is None:
        raise BlsError("G1 point not on curve")
    y_is_larger = y.n > P - y.n
    if bool(flags & _SIGN_FLAG) != y_is_larger:
        y = -y
    point = Point.from_affine(x, y, B1)
    if subgroup_check and not point.in_subgroup():
        raise BlsError("G1 point not in subgroup")
    return point


def _fq2_lex_larger(y: Fq2) -> bool:
    neg = -y
    return (y.c1.n, y.c0.n) > (neg.c1.n, neg.c0.n)


def g2_to_bytes(p: Point[Fq2]) -> bytes:
    if p.is_infinity():
        return bytes([_COMPRESSED_FLAG | _INFINITY_FLAG]) + b"\x00" * 95
    aff = p.to_affine()
    assert aff is not None
    x, y = aff
    flags = _COMPRESSED_FLAG
    if _fq2_lex_larger(y):
        flags |= _SIGN_FLAG
    raw = x.c1.n.to_bytes(48, "big") + x.c0.n.to_bytes(48, "big")
    return bytes([raw[0] | flags]) + raw[1:]


def g2_from_bytes(data: bytes, subgroup_check: bool = True) -> Point[Fq2]:
    if len(data) != 96:
        raise BlsError("G2 compressed point must be 96 bytes")
    flags = data[0]
    if not flags & _COMPRESSED_FLAG:
        raise BlsError("uncompressed G2 encoding not supported")
    if flags & _INFINITY_FLAG:
        if (flags & ~(_COMPRESSED_FLAG | _INFINITY_FLAG)) or any(data[1:]):
            raise BlsError("malformed G2 infinity encoding")
        return g2_infinity()
    c1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
    c0 = int.from_bytes(data[48:96], "big")
    if c0 >= P or c1 >= P:
        raise BlsError("G2 x-coordinate out of range")
    x = Fq2.from_ints(c0, c1)
    y = (x.square() * x + B2).sqrt()
    if y is None:
        raise BlsError("G2 point not on curve")
    if bool(flags & _SIGN_FLAG) != _fq2_lex_larger(y):
        y = -y
    point = Point.from_affine(x, y, B2)
    if subgroup_check and not point.in_subgroup():
        raise BlsError("G2 point not in subgroup")
    return point


# --- key and signature types ----------------------------------------------


class SecretKey:
    __slots__ = ("_sk",)

    def __init__(self, sk: int) -> None:
        if not 0 < sk < R:
            raise BlsError("secret key out of range")
        self._sk = sk

    @staticmethod
    def keygen(ikm: bytes, key_info: bytes = b"") -> "SecretKey":
        """RFC/draft-irtf-cfrg-bls-signature KeyGen (HKDF-SHA-256 mod r)."""
        if len(ikm) < 32:
            raise BlsError("IKM must be at least 32 bytes")
        salt = b"BLS-SIG-KEYGEN-SALT-"
        while True:
            salt = hashlib.sha256(salt).digest()
            prk = hmac_mod.new(salt, ikm + b"\x00", hashlib.sha256).digest()
            okm = b""
            prev = b""
            info = key_info + (48).to_bytes(2, "big")
            for i in range(1, 3):
                prev = hmac_mod.new(
                    prk, prev + info + i.to_bytes(1, "big"), hashlib.sha256
                ).digest()
                okm += prev
            sk = int.from_bytes(okm[:48], "big") % R
            if sk != 0:
                return SecretKey(sk)

    @staticmethod
    def from_bytes(data: bytes) -> "SecretKey":
        if len(data) != 32:
            raise BlsError("secret key must be 32 bytes")
        return SecretKey(int.from_bytes(data, "big"))

    def to_bytes(self) -> bytes:
        return self._sk.to_bytes(32, "big")

    @property
    def scalar(self) -> int:
        return self._sk

    def public_key(self) -> "PublicKey":
        return PublicKey(G1.mul(self._sk))

    def sign(self, message: bytes, dst: bytes = constants.DST_SIGNATURE) -> "Signature":
        return Signature(hash_to_g2(message, dst).mul(self._sk))

    def __repr__(self) -> str:  # never leak key material
        return "SecretKey(<redacted>)"


class PublicKey:
    __slots__ = ("point",)

    def __init__(self, point: Point[Fq]) -> None:
        self.point = point

    @staticmethod
    def from_bytes(data: bytes) -> "PublicKey":
        # Mandatory validation on decompress, as in the reference
        # (bls/src/public_key.rs:21-27): subgroup membership AND rejection of
        # the identity element (IETF KeyValidate).
        point = g1_from_bytes(data, subgroup_check=True)
        if point.is_infinity():
            raise BlsError("identity public key is invalid")
        return PublicKey(point)

    def to_bytes(self) -> bytes:
        return g1_to_bytes(self.point)

    @staticmethod
    def aggregate(keys: "Sequence[PublicKey]") -> "PublicKey":
        acc = g1_infinity()
        for k in keys:
            acc = acc + k.point
        return PublicKey(acc)

    def __eq__(self, o: object) -> bool:
        return isinstance(o, PublicKey) and self.point == o.point

    def __hash__(self) -> int:
        return hash(self.to_bytes())


class CachedPublicKey:
    """Bytes + lazily-decompressed point (reference: bls/src/cached_public_key.rs).

    `decompress` is reachable from the scheduler's completion thread and
    from block-replay workers at once, so the first-use fill holds a
    per-instance lock: an unlocked check-then-set would let two threads
    decompress the same key concurrently (wasted work) and, worse, let a
    reader observe the attribute mid-publication. All access to
    `_decompressed` stays inside the lock — no bare fast-path read — so
    the lock-coverage lints can prove the attribute consistently
    protected (schedule-fuzz scenario: cached_pubkey).
    """

    __slots__ = ("_bytes", "_decompressed", "_lock")

    def __init__(self, data: bytes) -> None:
        self._bytes = bytes(data)
        self._decompressed: Optional[PublicKey] = None
        self._lock = threading.Lock()

    def as_bytes(self) -> bytes:
        return self._bytes

    def decompress(self) -> PublicKey:
        with self._lock:
            if self._decompressed is None:
                self._decompressed = PublicKey.from_bytes(self._bytes)
            return self._decompressed


class Signature:
    __slots__ = ("point",)

    def __init__(self, point: Point[Fq2]) -> None:
        self.point = point

    @staticmethod
    def from_bytes(data: bytes) -> "Signature":
        return Signature(g2_from_bytes(data, subgroup_check=True))

    def to_bytes(self) -> bytes:
        return g2_to_bytes(self.point)

    @staticmethod
    def empty() -> "Signature":
        return Signature(g2_infinity())

    def is_empty(self) -> bool:
        return self.point.is_infinity()

    # -- verification ------------------------------------------------------
    def verify(
        self,
        message: bytes,
        public_key: PublicKey,
        dst: bytes = constants.DST_SIGNATURE,
    ) -> bool:
        """e(pk, H(m)) == e(g1, sig), as one product check."""
        from grandine_tpu.crypto.pairing import pairing_check

        if public_key.point.is_infinity():
            return False  # Eth2 rejects the identity public key
        return pairing_check(
            [(-G1, self.point), (public_key.point, hash_to_g2(message, dst))]
        )

    @staticmethod
    def aggregate(signatures: "Sequence[Signature]") -> "Signature":
        acc = g2_infinity()
        for s in signatures:
            acc = acc + s.point
        return Signature(acc)

    def aggregate_in_place(self, other: "Signature") -> None:
        self.point = self.point + other.point

    def fast_aggregate_verify(
        self,
        message: bytes,
        public_keys: "Sequence[PublicKey]",
        dst: bytes = constants.DST_SIGNATURE,
    ) -> bool:
        """All keys signed the same message (attestation aggregate)."""
        if not public_keys:
            return False
        if any(pk.point.is_infinity() for pk in public_keys):
            return False  # identity key would fake participation
        agg = PublicKey.aggregate(public_keys)
        return self.verify(message, agg, dst)

    def aggregate_verify(
        self,
        messages: "Sequence[bytes]",
        public_keys: "Sequence[PublicKey]",
        dst: bytes = constants.DST_SIGNATURE,
    ) -> bool:
        """Distinct messages: ∏ e(pkᵢ, H(mᵢ)) == e(g1, sig)."""
        from grandine_tpu.crypto.pairing import pairing_check

        if len(messages) != len(public_keys) or not messages:
            return False
        if len(set(messages)) != len(messages):
            return False  # RO-suite requires distinct messages
        if any(pk.point.is_infinity() for pk in public_keys):
            return False
        pairs = [(-G1, self.point)]
        pairs += [
            (pk.point, hash_to_g2(m, dst)) for pk, m in zip(public_keys, messages)
        ]
        return pairing_check(pairs)

    def __eq__(self, o: object) -> bool:
        return isinstance(o, Signature) and self.point == o.point

    def __hash__(self) -> int:
        return hash(self.to_bytes())


def multi_verify(
    messages: "Sequence[bytes]",
    signatures: "Sequence[Signature]",
    public_keys: "Sequence[PublicKey]",
    dst: bytes = constants.DST_SIGNATURE,
    rng=secrets,
) -> bool:
    """Batch verification by random linear combination, the algebraic twin of
    `Signature::multi_verify` (bls/src/signature.rs:96-129): nonzero 64-bit
    scalars rᵢ; accept iff

        e(g1, Σ rᵢ·sigᵢ) == ∏ e(rᵢ·pkᵢ, H(mᵢ))

    i.e. N+1 Miller loops and a single final exponentiation.
    """
    from grandine_tpu.crypto.pairing import pairing_check

    if not (len(messages) == len(signatures) == len(public_keys)):
        return False
    if not messages:
        return True
    if any(pk.point.is_infinity() for pk in public_keys):
        return False
    scalars = []
    for _ in messages:
        s = 0
        while s == 0:
            s = rng.randbits(64)
        scalars.append(s)
    sig_acc = g2_infinity()
    for s, sig in zip(scalars, signatures):
        sig_acc = sig_acc + sig.point.mul(s)
    pairs = [(-G1, sig_acc)]
    pairs += [
        (pk.point.mul(s), hash_to_g2(m, dst))
        for s, pk, m in zip(scalars, public_keys, messages)
    ]
    return pairing_check(pairs)


__all__ = [
    "BlsError",
    "SecretKey",
    "PublicKey",
    "CachedPublicKey",
    "Signature",
    "multi_verify",
    "g1_to_bytes",
    "g1_from_bytes",
    "g2_to_bytes",
    "g2_from_bytes",
]
