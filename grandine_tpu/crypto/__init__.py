"""BLS12-381 cryptography plane.

Pure-Python arbitrary-precision implementation serving as the correctness
anchor (the role blst plays in the reference: `bls/src/signature.rs`), plus
the backend seam through which the TPU (JAX) implementation is dispatched.

All curve constants are either well-known (p, r, x, generators) and verified
against structural identities at import, or derived computationally (twist
cofactor, Frobenius coefficients, SvdW map constants) — nothing is copied
from an implementation we cannot test against.
"""

from grandine_tpu.crypto import constants, fields, curves, pairing, hash_to_curve, bls

__all__ = ["constants", "fields", "curves", "pairing", "hash_to_curve", "bls"]
