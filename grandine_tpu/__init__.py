"""grandine-tpu: a TPU-native Ethereum consensus-layer framework.

Brand-new implementation with the capabilities of the reference client
(Grandine, Rust; see SURVEY.md and COMPONENTS.md) re-designed TPU-first:
the BLS12-381 signature plane (grouped RLC batch verification /
aggregation / signing) runs as jitted XLA kernels sharded over device
meshes, while the consensus core is a host framework feeding it through
the Verifier seam.

Layout (COMPONENTS.md maps every reference crate to these modules):
  crypto/      pure-Python BLS12-381 correctness anchor (replaces blst)
  tpu/         limb-vectorized batch crypto kernels + device backend
  core/        hashing (SHA-NI native ext) + swap-or-not shuffle
  ssz/         SSZ codec, merkleization, proofs
  types/       spec containers x5 forks, presets, config, combined dispatch
  consensus/   spec helpers, accessors, predicates, Verifier seam
  transition/  state transition (slots/epoch/block/fork upgrades)
  fork_choice/ LMD-GHOST + Casper FFG store
  runtime/     clock, thread pool, controller, firehose, node, liveness
  storage/     database (sqlite/memory) + persistence schema + resume
  kzg/         EIP-4844 blob commitments over the shared pairing kernels
  pools/       attestation/sync-committee/operation pools
  validator/   duties, service, signer, slashing protection, keymanager
  p2p/         transport seam, gossip service, sync, back-sync
  execution/   execution-engine seam (Null/Mock)
  http_api/    Beacon API subset + metrics exposition
  spec_tests/  consensus-spec-tests case loader + snappy codec
  eth1.py      deposit cache + incremental tree
  slasher.py   double/surround detection
  builder_api.py  MEV relay client seam
  metrics.py / features.py / cli.py
"""


__version__ = "0.1.0"
