"""grandine-tpu: a TPU-native Ethereum consensus-layer framework.

Brand-new implementation with the capabilities of the reference client
(Grandine, Rust; see SURVEY.md) re-designed TPU-first: the BLS12-381
signature plane (batch verification / aggregation / signing) runs as
vmapped XLA kernels on TPU, while the consensus core (SSZ, state
transition, fork choice, services) is a host-side framework feeding it.

Layout mirrors SURVEY.md §2's component inventory:
  crypto/     pure-Python BLS12-381 correctness anchor (replaces blst)
  tpu/        JAX/XLA limb-vectorized batch crypto kernels
  ssz/        SSZ serialization + merkleization
  types/      spec containers for all forks, presets, config
  transition/ state transition functions
  fork_choice/ store + controller
  services/   attestation verifier, validator duties, pools, signer...
"""

__version__ = "0.1.0"
