"""Loader for the C++ native runtime kernels (`gtnative.cpp`).

Compiles the shared library on first import (g++, cached next to the
source), then binds it via ctypes. If no toolchain is available the
package still works: `lib` is None and callers (grandine_tpu.core.hashing)
fall back to hashlib-based pure-Python paths.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src", "gtnative.cpp")
_SO = os.path.join(_DIR, "_gtnative.so")
_STAMP = _SO + ".srchash"  # content hash of the source the .so was built from

_lock = threading.Lock()
lib = None
shani = False


def _read(path: str) -> bytes | None:
    try:
        with open(path, "rb") as f:
            return f.read()
    except OSError:
        return None


def _build() -> bool:
    """(Re)build the .so whenever the stamped source hash doesn't match.

    Keyed on a content hash, not mtimes: on a fresh clone git gives the
    source near-identical mtimes to any stray binary, and a stale or
    foreign-platform .so must never silently serve the consensus-critical
    hashing path. A missing source degrades to the hashlib fallback."""
    src = _read(_SRC)
    if src is None:
        return False
    src_hash = hashlib.sha256(src).hexdigest().encode()
    if os.path.exists(_SO) and _read(_STAMP) == src_hash:
        return True
    tmp = f"{_SO}.{os.getpid()}.tmp"  # per-process name: parallel first
    # imports must not interleave writes into one file
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        with open(f"{_STAMP}.{os.getpid()}.tmp", "wb") as f:
            f.write(src_hash)
        os.replace(f"{_STAMP}.{os.getpid()}.tmp", _STAMP)
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        for leftover in (tmp, f"{_STAMP}.{os.getpid()}.tmp"):
            try:
                os.unlink(leftover)
            except OSError:
                pass
        return os.path.exists(_SO) and _read(_STAMP) == src_hash
    return True


def _bind():
    global lib, shani
    if lib is not None:
        return lib
    with _lock:
        if lib is not None:
            return lib
        if not _build():
            return None
        try:
            L = ctypes.CDLL(_SO)
            # c_char_p lets a Python bytes object pass zero-copy; outputs
            # are writable create_string_buffer()s (c_char_p compatible).
            cp = ctypes.c_char_p
            L.gt_init.restype = ctypes.c_int
            L.gt_sha256.argtypes = [cp, ctypes.c_uint64, cp]
            L.gt_hash_pairs.argtypes = [cp, ctypes.c_uint64, cp]
            L.gt_merkleize.argtypes = [cp, ctypes.c_uint64, ctypes.c_int, cp]
            L.gt_merkleize.restype = ctypes.c_int
            L.gt_merkleize_many.argtypes = [
                cp, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int, cp]
            L.gt_merkleize_many.restype = ctypes.c_int
            L.gt_mix_in_length.argtypes = [cp, ctypes.c_uint64, cp]
            L.gt_zero_hash.argtypes = [ctypes.c_int, cp]
            L.gt_crc32c.argtypes = [cp, ctypes.c_uint64]
            L.gt_crc32c.restype = ctypes.c_uint32
            shani = bool(L.gt_init())
        except (OSError, AttributeError):
            # missing/stale-ABI cached .so: degrade to hashlib fallback
            return None
        lib = L
        return lib


_bind()


def out_buf(n: int) -> ctypes.Array:
    """Writable output buffer for a gt_* call; read result via `.raw`."""
    return ctypes.create_string_buffer(n)


def available() -> bool:
    return lib is not None
