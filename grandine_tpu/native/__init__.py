"""Loader for the C++ native runtime kernels (`gtnative.cpp`).

Compiles the shared library on first import (g++, cached next to the
source), then binds it via ctypes. If no toolchain is available the
package still works: `lib` is None and callers (grandine_tpu.core.hashing)
fall back to hashlib-based pure-Python paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src", "gtnative.cpp")
_SO = os.path.join(_DIR, "_gtnative.so")

_lock = threading.Lock()
lib = None
shani = False


def _build() -> bool:
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return True
    tmp = f"{_SO}.{os.getpid()}.tmp"  # per-process name: parallel first
    # imports must not interleave writes into one file
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return os.path.exists(_SO)
    return True


def _bind():
    global lib, shani
    if lib is not None:
        return lib
    with _lock:
        if lib is not None:
            return lib
        if not _build():
            return None
        try:
            L = ctypes.CDLL(_SO)
            # c_char_p lets a Python bytes object pass zero-copy; outputs
            # are writable create_string_buffer()s (c_char_p compatible).
            cp = ctypes.c_char_p
            L.gt_init.restype = ctypes.c_int
            L.gt_sha256.argtypes = [cp, ctypes.c_uint64, cp]
            L.gt_hash_pairs.argtypes = [cp, ctypes.c_uint64, cp]
            L.gt_merkleize.argtypes = [cp, ctypes.c_uint64, ctypes.c_int, cp]
            L.gt_merkleize_many.argtypes = [
                cp, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int, cp]
            L.gt_mix_in_length.argtypes = [cp, ctypes.c_uint64, cp]
            L.gt_zero_hash.argtypes = [ctypes.c_int, cp]
            shani = bool(L.gt_init())
        except (OSError, AttributeError):
            # missing/stale-ABI cached .so: degrade to hashlib fallback
            return None
        lib = L
        return lib


_bind()


def out_buf(n: int) -> ctypes.Array:
    """Writable output buffer for a gt_* call; read result via `.raw`."""
    return ctypes.create_string_buffer(n)


def available() -> bool:
    return lib is not None
