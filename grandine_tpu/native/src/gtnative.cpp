// grandine-tpu native runtime kernels: SHA-256 merkleization hot loop.
//
// Equivalent of the reference's `hashing` crate (hashing/src/lib.rs:10-60 —
// sha2 crate with SIMD asm + ZERO_HASHES table) re-implemented for this
// framework: the per-node hash loop of SSZ hash-tree-root lives here so the
// Python/JAX host layer never pays per-hash interpreter overhead.
//
// Two SHA-256 compression backends, selected once at init by CPUID:
//   * x86 SHA-NI intrinsics (one 64-byte block ≈ tens of cycles)
//   * portable C++ fallback
//
// Exported C ABI (consumed via ctypes from grandine_tpu.native):
//   gt_init()                      -> 1 if SHA-NI active, 0 if portable
//   gt_sha256(data, len, out32)
//   gt_hash_pairs(in, n, out)      -- n 64-byte concatenated pairs -> n roots
//   gt_merkleize(chunks, n, depth, out32)
//   gt_merkleize_many(chunks, n_items, cpi, depth, out)
//   gt_zero_hash(level, out32)

#include <cstdint>
#include <cstring>
#include <cstdlib>

#if defined(__x86_64__)
#include <immintrin.h>
#include <cpuid.h>
#define GT_X86 1
#endif

namespace {

// ---------------------------------------------------------------- portable
const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

inline uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }
inline uint32_t rd32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}
inline void wr32(uint8_t* p, uint32_t v) {
  p[0] = uint8_t(v >> 24);
  p[1] = uint8_t(v >> 16);
  p[2] = uint8_t(v >> 8);
  p[3] = uint8_t(v);
}

void compress_portable(uint32_t st[8], const uint8_t* block) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++) w[i] = rd32(block + 4 * i);
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = st[0], b = st[1], c = st[2], d = st[3];
  uint32_t e = st[4], f = st[5], g = st[6], h = st[7];
  for (int i = 0; i < 64; i++) {
    uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + S1 + ch + K[i] + w[i];
    uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t mj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = S0 + mj;
    h = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  st[0] += a; st[1] += b; st[2] += c; st[3] += d;
  st[4] += e; st[5] += f; st[6] += g; st[7] += h;
}

// ---------------------------------------------------------------- SHA-NI
#ifdef GT_X86
__attribute__((target("sha,sse4.1")))
void compress_shani(uint32_t st[8], const uint8_t* block) {
  const __m128i MASK =
      _mm_set_epi64x(0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);
  __m128i tmp = _mm_loadu_si128((const __m128i*)&st[0]);
  __m128i s1 = _mm_loadu_si128((const __m128i*)&st[4]);
  tmp = _mm_shuffle_epi32(tmp, 0xB1);
  s1 = _mm_shuffle_epi32(s1, 0x1B);
  __m128i s0 = _mm_alignr_epi8(tmp, s1, 8);
  s1 = _mm_blend_epi16(s1, tmp, 0xF0);
  const __m128i abef_save = s0, cdgh_save = s1;

  __m128i msg, msg0, msg1, msg2, msg3;

  msg0 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i*)(block + 0)), MASK);
  msg = _mm_add_epi32(msg0, _mm_set_epi64x(0xE9B5DBA5B5C0FBCFULL, 0x71374491428A2F98ULL));
  s1 = _mm_sha256rnds2_epu32(s1, s0, msg);
  msg = _mm_shuffle_epi32(msg, 0x0E);
  s0 = _mm_sha256rnds2_epu32(s0, s1, msg);

  msg1 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i*)(block + 16)), MASK);
  msg = _mm_add_epi32(msg1, _mm_set_epi64x(0xAB1C5ED5923F82A4ULL, 0x59F111F13956C25BULL));
  s1 = _mm_sha256rnds2_epu32(s1, s0, msg);
  msg = _mm_shuffle_epi32(msg, 0x0E);
  s0 = _mm_sha256rnds2_epu32(s0, s1, msg);
  msg0 = _mm_sha256msg1_epu32(msg0, msg1);

  msg2 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i*)(block + 32)), MASK);
  msg = _mm_add_epi32(msg2, _mm_set_epi64x(0x550C7DC3243185BEULL, 0x12835B01D807AA98ULL));
  s1 = _mm_sha256rnds2_epu32(s1, s0, msg);
  msg = _mm_shuffle_epi32(msg, 0x0E);
  s0 = _mm_sha256rnds2_epu32(s0, s1, msg);
  msg1 = _mm_sha256msg1_epu32(msg1, msg2);

  msg3 = _mm_shuffle_epi8(_mm_loadu_si128((const __m128i*)(block + 48)), MASK);
  msg = _mm_add_epi32(msg3, _mm_set_epi64x(0xC19BF1749BDC06A7ULL, 0x80DEB1FE72BE5D74ULL));
  s1 = _mm_sha256rnds2_epu32(s1, s0, msg);
  tmp = _mm_alignr_epi8(msg3, msg2, 4);
  msg0 = _mm_add_epi32(msg0, tmp);
  msg0 = _mm_sha256msg2_epu32(msg0, msg3);
  msg = _mm_shuffle_epi32(msg, 0x0E);
  s0 = _mm_sha256rnds2_epu32(s0, s1, msg);
  msg2 = _mm_sha256msg1_epu32(msg2, msg3);

  const uint64_t k2[12][2] = {
      {0xEFBE4786E49B69C1ULL, 0x240CA1CC0FC19DC6ULL},
      {0x4A7484AA2DE92C6FULL, 0x76F988DA5CB0A9DCULL},
      {0xA831C66D983E5152ULL, 0xBF597FC7B00327C8ULL},
      {0xD5A79147C6E00BF3ULL, 0x1429296706CA6351ULL},
      {0x2E1B213827B70A85ULL, 0x53380D134D2C6DFCULL},
      {0x766A0ABB650A7354ULL, 0x92722C8581C2C92EULL},
      {0xA81A664BA2BFE8A1ULL, 0xC76C51A3C24B8B70ULL},
      {0xD6990624D192E819ULL, 0x106AA070F40E3585ULL},
      {0x1E376C0819A4C116ULL, 0x34B0BCB52748774CULL},
      {0x4ED8AA4A391C0CB3ULL, 0x682E6FF35B9CCA4FULL},
      {0x78A5636F748F82EEULL, 0x8CC7020884C87814ULL},
      {0xA4506CEB90BEFFFAULL, 0xC67178F2BEF9A3F7ULL}};
  // rounds 16..63, 4 at a time, msg registers rotating
  __m128i* m[4] = {&msg0, &msg1, &msg2, &msg3};
  for (int r = 0; r < 12; r++) {
    __m128i& cur = *m[r & 3];
    __m128i& nxt = *m[(r + 1) & 3];
    __m128i& prv = *m[(r + 3) & 3];
    msg = _mm_add_epi32(cur, _mm_set_epi64x((long long)k2[r][1], (long long)k2[r][0]));
    s1 = _mm_sha256rnds2_epu32(s1, s0, msg);
    tmp = _mm_alignr_epi8(cur, prv, 4);
    nxt = _mm_add_epi32(nxt, tmp);
    nxt = _mm_sha256msg2_epu32(nxt, cur);
    msg = _mm_shuffle_epi32(msg, 0x0E);
    s0 = _mm_sha256rnds2_epu32(s0, s1, msg);
    if (r < 11) prv = _mm_sha256msg1_epu32(prv, cur);
  }

  s0 = _mm_add_epi32(s0, abef_save);
  s1 = _mm_add_epi32(s1, cdgh_save);
  tmp = _mm_shuffle_epi32(s0, 0x1B);
  s1 = _mm_shuffle_epi32(s1, 0xB1);
  s0 = _mm_blend_epi16(tmp, s1, 0xF0);
  s1 = _mm_alignr_epi8(s1, tmp, 8);
  _mm_storeu_si128((__m128i*)&st[0], s0);
  _mm_storeu_si128((__m128i*)&st[4], s1);
}
#endif  // GT_X86

typedef void (*compress_fn)(uint32_t[8], const uint8_t*);
compress_fn g_compress = compress_portable;

const uint32_t IV[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                        0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};

// Constant second block for a 64-byte message: 0x80, zeros, bit length 512.
uint8_t PAD64[64];

// hash of a 64-byte input (the merkle node op): 2 compressions.
inline void hash64(const uint8_t* in, uint8_t* out) {
  uint32_t st[8];
  std::memcpy(st, IV, sizeof(IV));
  g_compress(st, in);
  g_compress(st, PAD64);
  for (int i = 0; i < 8; i++) wr32(out + 4 * i, st[i]);
}

const int MAX_DEPTH = 64;
uint8_t ZERO_HASH[MAX_DEPTH + 1][32];
bool g_inited = false;

}  // namespace

extern "C" {

int gt_init(void) {
  if (g_inited) {
#ifdef GT_X86
    return g_compress == compress_shani ? 1 : 0;
#else
    return 0;
#endif
  }
  std::memset(PAD64, 0, sizeof(PAD64));
  PAD64[0] = 0x80;
  PAD64[62] = 0x02;  // 512 bits big-endian = 0x0200
#ifdef GT_X86
  unsigned a, b, c, d;
  if (__get_cpuid_count(7, 0, &a, &b, &c, &d) && (b & (1u << 29))) {
    g_compress = compress_shani;
  }
#endif
  std::memset(ZERO_HASH[0], 0, 32);
  uint8_t buf[64];
  for (int i = 1; i <= MAX_DEPTH; i++) {
    std::memcpy(buf, ZERO_HASH[i - 1], 32);
    std::memcpy(buf + 32, ZERO_HASH[i - 1], 32);
    hash64(buf, ZERO_HASH[i]);
  }
  g_inited = true;
#ifdef GT_X86
  return g_compress == compress_shani ? 1 : 0;
#else
  return 0;
#endif
}

void gt_zero_hash(int level, uint8_t* out32) {
  std::memcpy(out32, ZERO_HASH[level <= MAX_DEPTH ? level : MAX_DEPTH], 32);
}

void gt_sha256(const uint8_t* data, uint64_t len, uint8_t* out32) {
  uint32_t st[8];
  std::memcpy(st, IV, sizeof(IV));
  uint64_t full = len / 64;
  for (uint64_t i = 0; i < full; i++) g_compress(st, data + 64 * i);
  uint8_t tail[128];
  uint64_t rem = len - 64 * full;
  std::memcpy(tail, data + 64 * full, rem);
  tail[rem] = 0x80;
  uint64_t tlen = (rem + 9 <= 64) ? 64 : 128;
  std::memset(tail + rem + 1, 0, tlen - rem - 1 - 8);
  uint64_t bits = len * 8;
  for (int i = 0; i < 8; i++) tail[tlen - 1 - i] = uint8_t(bits >> (8 * i));
  g_compress(st, tail);
  if (tlen == 128) g_compress(st, tail + 64);
  for (int i = 0; i < 8; i++) wr32(out32 + 4 * i, st[i]);
}

// n concatenated 64-byte pairs -> n 32-byte parent nodes. in != out allowed
// to alias only if out <= in (in-place tree reduction writes forward).
void gt_hash_pairs(const uint8_t* in, uint64_t n, uint8_t* out) {
  for (uint64_t i = 0; i < n; i++) hash64(in + 64 * i, out + 32 * i);
}

// Merkleize `n_chunks` 32-byte chunks into a subtree of height `depth`
// (2^depth leaf slots, zero-padded virtually). Scratch is O(n).
static void merkleize_into(const uint8_t* chunks, uint64_t n_chunks, int depth,
                           uint8_t* out32, uint8_t* scratch) {
  if (n_chunks == 0) {
    std::memcpy(out32, ZERO_HASH[depth], 32);
    return;
  }
  if (depth == 0) {
    std::memcpy(out32, chunks, 32);
    return;
  }
  // copy level 0 into scratch
  uint64_t n = n_chunks;
  std::memcpy(scratch, chunks, n * 32);
  uint8_t buf[64];
  for (int level = 0; level < depth; level++) {
    uint64_t pairs = n / 2;
    for (uint64_t i = 0; i < pairs; i++)
      hash64(scratch + 64 * i, scratch + 32 * i);
    if (n & 1) {
      std::memcpy(buf, scratch + 32 * (n - 1), 32);
      std::memcpy(buf + 32, ZERO_HASH[level], 32);
      hash64(buf, scratch + 32 * pairs);
      n = pairs + 1;
    } else {
      n = pairs;
    }
    if (n == 1 && level + 1 < depth) {
      // remaining right siblings are all zero subtrees
      for (int l = level + 1; l < depth; l++) {
        std::memcpy(buf, scratch, 32);
        std::memcpy(buf + 32, ZERO_HASH[l], 32);
        hash64(buf, scratch);
      }
      break;
    }
  }
  std::memcpy(out32, scratch, 32);
}

// Returns 1 on success, 0 on allocation failure (caller falls back to the
// hashlib path).
int gt_merkleize(const uint8_t* chunks, uint64_t n_chunks, int depth,
                 uint8_t* out32) {
  uint8_t* scratch =
      (uint8_t*)std::malloc((n_chunks ? n_chunks : 1) * 32 + 32);
  if (!scratch) return 0;
  merkleize_into(chunks, n_chunks, depth, out32, scratch);
  std::free(scratch);
  return 1;
}

// Batch: n_items independent subtrees, each `cpi` chunks wide, each
// merkleized to height `depth`. The 50k-validator registry path: one call
// hashes every validator's 8-field subtree. Returns 1 on success, 0 on
// allocation failure.
int gt_merkleize_many(const uint8_t* chunks, uint64_t n_items, uint64_t cpi,
                      int depth, uint8_t* out) {
  uint8_t* scratch = (uint8_t*)std::malloc((cpi ? cpi : 1) * 32 + 32);
  if (!scratch) return 0;
  for (uint64_t i = 0; i < n_items; i++)
    merkleize_into(chunks + i * cpi * 32, cpi, depth, out + 32 * i, scratch);
  std::free(scratch);
  return 1;
}

// mix_in_length / mix_in_selector: hash(root ++ le64(value) ++ zeros24)
void gt_mix_in_length(const uint8_t* root, uint64_t value, uint8_t* out32) {
  uint8_t buf[64];
  std::memcpy(buf, root, 32);
  std::memset(buf + 32, 0, 32);
  for (int i = 0; i < 8; i++) buf[32 + i] = uint8_t(value >> (8 * i));
  hash64(buf, out32);
}

// ------------------------------------------------------------------ crc32c
// CRC-32C (Castagnoli) for the snappy framing layer: every database put
// checksums its value, so the byte-at-a-time Python loop was a systemic
// tax on storage. SSE4.2 has the polynomial in hardware (crc32 instr);
// the portable path is a table-driven fallback built at init.

namespace {
uint32_t CRC_TABLE[256];
bool g_crc_table_built = false;

void build_crc_table() {
  const uint32_t poly = 0x82F63B78u;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = i;
    for (int j = 0; j < 8; j++)
      crc = (crc & 1) ? (crc >> 1) ^ poly : crc >> 1;
    CRC_TABLE[i] = crc;
  }
  g_crc_table_built = true;
}

uint32_t crc32c_portable(uint32_t crc, const uint8_t* p, uint64_t len) {
  for (uint64_t i = 0; i < len; i++)
    crc = CRC_TABLE[(crc ^ p[i]) & 0xFF] ^ (crc >> 8);
  return crc;
}

#ifdef GT_X86
__attribute__((target("sse4.2")))
uint32_t crc32c_hw(uint32_t crc, const uint8_t* p, uint64_t len) {
  uint64_t c = crc;
  while (len >= 8) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    c = _mm_crc32_u64(c, v);
    p += 8;
    len -= 8;
  }
  uint32_t c32 = (uint32_t)c;
  while (len--) c32 = _mm_crc32_u8(c32, *p++);
  return c32;
}

bool have_sse42() {
  unsigned a, b, c, d;
  return __get_cpuid(1, &a, &b, &c, &d) && (c & (1u << 20));
}
#endif
}  // namespace

uint32_t gt_crc32c(const uint8_t* data, uint64_t len) {
  uint32_t crc = 0xFFFFFFFFu;
#ifdef GT_X86
  static const bool hw = have_sse42();
  if (hw) return crc32c_hw(crc, data, len) ^ 0xFFFFFFFFu;
#endif
  if (!g_crc_table_built) build_crc_table();
  return crc32c_portable(crc, data, len) ^ 0xFFFFFFFFu;
}

}  // extern "C"
