"""KZG / EIP-4844 blob commitments — reference: `kzg_utils` crate
(kzg_utils/src/eip_4844.rs: blob_to_kzg_commitment, compute_kzg_proof,
compute_blob_kzg_proof, verify_kzg_proof, verify_blob_kzg_proof[_batch]
over rust-kzg-blst; trusted_setup.rs embeds the ceremony output).

TPU-first: the two hot operations are 4096-point G1 multi-scalar
multiplications (commitment and proof) — mapped onto the existing batch
scalar-mul + sum-tree kernels as ONE device launch each. Pairing checks
(2 pairings per verify) run on the anchor. The embedded trusted setup is
the public KZG ceremony output (data, not code), bit-reversal-permuted at
load exactly as the deneb spec requires.
"""

from grandine_tpu.kzg.eip4844 import (  # noqa: F401
    KzgError,
    blob_to_kzg_commitment,
    compute_blob_kzg_proof,
    compute_kzg_proof,
    verify_blob_kzg_proof,
    verify_blob_kzg_proof_batch,
    verify_kzg_proof,
)
from grandine_tpu.kzg.setup import TrustedSetup, dev_setup, official_setup  # noqa: F401
