"""EIP-4844 KZG operations — reference: kzg_utils/src/eip_4844.rs (the six
public functions over rust-kzg-blst) and the deneb
polynomial-commitments.md spec they implement.

The hot path is the G1 multi-scalar multiplication (one per commitment /
proof): on device it is ONE batched scalar-mul launch + a log-depth sum
tree over the existing TPU curve kernels; the host fallback is a windowed
Pippenger. Single-proof verification (2 pairings) runs on the anchor
pairing.

Batch verification has a full device plane (`KzgDeviceBackend`, the
`blob_kzg` entry of the scheme dispatch table): host prep decodes and
subgroup-checks the G1 inputs, computes the Fiat–Shamir challenges and
barycentric evaluations, and lays the WHOLE batch equation

    e(Σ rⁱ(Cᵢ − yᵢG1 + zᵢWᵢ), G2) · e(−Σ rⁱWᵢ, [τ]G2) == 1

out as ONE flat scalar-mul batch in four contiguous groups
(commitments·rⁱ | proofs·rⁱzᵢ | generator·(−Σrⁱyᵢ) | proofs·(−rⁱ)); the
device then runs one ladder, one grouped sum tree, and a width-4
multi-pairing check against [G2, G2, G2, τG2] — a single dispatch per
batch. The challenge r is deterministic, so the device verdict is
IDENTICAL to the host batch path on every input (forged included), and
the n == 1 batch is algebraically the single-verify equation.
"""

from __future__ import annotations

import hashlib
from typing import Optional, Sequence

from grandine_tpu.crypto import bls as A
from grandine_tpu.crypto.curves import G1, G2, Point, g1_infinity
from grandine_tpu.crypto.pairing import pairing_check
from grandine_tpu.kzg import fr
from grandine_tpu.kzg.setup import TrustedSetup, official_setup

BLS_MODULUS = fr.BLS_MODULUS
BYTES_PER_FIELD_ELEMENT = 32
FIAT_SHAMIR_PROTOCOL_DOMAIN = b"FSBLOBVERIFY_V1_"
RANDOM_CHALLENGE_KZG_BATCH_DOMAIN = b"RCKZGBATCH___V1_"
KZG_ENDIANNESS = "big"

G1_POINT_AT_INFINITY = bytes([0xC0]) + b"\x00" * 47

#: flip to False to force the host Pippenger MSM (no JAX)
USE_DEVICE_MSM = True

#: flip to False to force the host pairing tail of batch verification
USE_DEVICE_KZG = True


class KzgError(ValueError):
    pass


# ------------------------------------------------------------ (de)serialize


def _bytes_to_bls_field(b: bytes) -> int:
    v = int.from_bytes(b, KZG_ENDIANNESS)
    if v >= BLS_MODULUS:
        raise KzgError("field element out of range")
    return v


def _field_to_bytes(v: int) -> bytes:
    return int(v).to_bytes(BYTES_PER_FIELD_ELEMENT, KZG_ENDIANNESS)


def _blob_to_polynomial(blob: bytes, width: int) -> "list[int]":
    if len(blob) != width * BYTES_PER_FIELD_ELEMENT:
        raise KzgError(f"blob must be {width * BYTES_PER_FIELD_ELEMENT} bytes")
    return [
        _bytes_to_bls_field(blob[i * 32 : (i + 1) * 32]) for i in range(width)
    ]


def _g1_from_commitment_bytes(b: bytes) -> Point:
    try:
        return A.g1_from_bytes(bytes(b), subgroup_check=True)
    except A.BlsError as e:
        raise KzgError(f"invalid G1 encoding: {e}") from e


def _hash_to_bls_field(data: bytes) -> int:
    return int.from_bytes(hashlib.sha256(data).digest(), KZG_ENDIANNESS) % BLS_MODULUS


def _compute_challenge(blob: bytes, commitment: bytes, width: int) -> int:
    degree_poly = width.to_bytes(16, KZG_ENDIANNESS)
    return _hash_to_bls_field(
        FIAT_SHAMIR_PROTOCOL_DOMAIN + degree_poly + blob + commitment
    )


# ----------------------------------------------------------------------- MSM


def _msm_host(points: "Sequence[Point]", scalars: "Sequence[int]") -> Point:
    """Windowed Pippenger MSM (host fallback)."""
    window = 8
    acc_total = g1_infinity()
    n_windows = (255 + window - 1) // window
    for w in range(n_windows - 1, -1, -1):
        shift = w * window
        buckets: "dict[int, Point]" = {}
        for p, s in zip(points, scalars):
            digit = (s >> shift) & ((1 << window) - 1)
            if digit:
                cur = buckets.get(digit)
                buckets[digit] = p if cur is None else cur + p
        if w != n_windows - 1:
            for _ in range(window):
                acc_total = acc_total.double()
        # Σ d·B_d via descending running sums weighted by digit gaps
        running = g1_infinity()
        window_sum = g1_infinity()
        digits = sorted(buckets, reverse=True)
        for i, digit in enumerate(digits):
            running = running + buckets[digit]
            next_digit = digits[i + 1] if i + 1 < len(digits) else 0
            window_sum = window_sum + running.mul(digit - next_digit)
        acc_total = acc_total + window_sum
    return acc_total


def _msm_device(setup: TrustedSetup, scalars: "Sequence[int]") -> Point:
    """Device MSM over the setup's (cached, limb-form) G1 points: one
    batched scalar-mul kernel + a complete-addition sum tree."""
    import jax
    import numpy as np

    from grandine_tpu.tpu import curve as C
    from grandine_tpu.tpu import limbs as L

    cache = setup._dev_cache
    if cache is None:
        n = setup.width
        xs = np.zeros((n, L.NLIMBS), np.int32)
        ys = np.zeros((n, L.NLIMBS), np.int32)
        inf = np.zeros(n, bool)
        for i, pt in enumerate(setup.g1_lagrange_brp):
            xs[i], ys[i], inf[i] = C.g1_point_to_dev(pt)
        cache = setup._dev_cache = (xs, ys, inf)
    xs, ys, inf = cache

    from grandine_tpu.tpu.bls import _jitted_global, note_dispatch_shapes

    def msm_kernel(px, py, p_inf, bits):
        import jax.numpy as jnp

        qx, qy = L.split(jnp.asarray(px)), L.split(jnp.asarray(py))
        jac = C.scalar_mul(qx, qy, p_inf, jnp.transpose(bits), C.FP_OPS)
        X, Y, Z = C.sum_points(jac, C.FP_OPS)
        return L.merge(X), L.merge(Y), L.merge(Z)

    # ONE process-wide jitted wrapper; jit re-specializes per setup width,
    # and each width is a distinct ledger signature (tools/shapes contract)
    fn = _jitted_global("kzg_msm", msm_kernel)
    bits = C.scalars_to_bits_msb([s % BLS_MODULUS for s in scalars], 255)
    args = (xs, ys, inf, bits)
    note_dispatch_shapes("kzg_msm", args)
    from grandine_tpu.tpu.bls import _node_profiler

    with _node_profiler().annotate("kzg_msm", len(scalars)):
        X, Y, Z = fn(*args)
    import numpy as np

    return C.dev_to_g1_point(np.asarray(X), np.asarray(Y), np.asarray(Z))


def _g1_lincomb(setup: TrustedSetup, scalars: "Sequence[int]") -> Point:
    if USE_DEVICE_MSM:
        try:
            return _msm_device(setup, scalars)
        except ImportError:
            pass  # no JAX: host path
        except Exception as e:
            import warnings

            warnings.warn(
                f"device MSM failed ({e!r}); falling back to host Pippenger"
            )
    return _msm_host(setup.g1_lagrange_brp, scalars)


# ------------------------------------------------------------ the six calls


def blob_to_kzg_commitment(
    blob: bytes, setup: "Optional[TrustedSetup]" = None
) -> bytes:
    setup = setup or official_setup()
    poly = _blob_to_polynomial(bytes(blob), setup.width)
    return A.g1_to_bytes(_g1_lincomb(setup, poly))


def compute_kzg_proof(
    blob: bytes, z_bytes: bytes, setup: "Optional[TrustedSetup]" = None
) -> "tuple[bytes, bytes]":
    """Returns (proof, y) for the evaluation p(z) = y."""
    setup = setup or official_setup()
    poly = _blob_to_polynomial(bytes(blob), setup.width)
    z = _bytes_to_bls_field(bytes(z_bytes))
    proof, y = _compute_kzg_proof_impl(poly, z, setup)
    return proof, _field_to_bytes(y)


def _compute_kzg_proof_impl(poly, z: int, setup: TrustedSetup):
    roots = setup.roots_brp
    y = fr.evaluate_polynomial_in_evaluation_form(poly, z, roots)
    # quotient q_i = (f_i - y) / (w_i - z), with the special row when
    # z equals a root (spec compute_kzg_proof_impl)
    width = setup.width
    denoms = [(w - z) % BLS_MODULUS for w in roots]
    inv_denoms = fr.batch_inverse(denoms)
    q = [0] * width
    special = None
    for i in range(width):
        if denoms[i] == 0:
            special = i
            continue
        q[i] = (poly[i] - y) % BLS_MODULUS * inv_denoms[i] % BLS_MODULUS
    if special is not None:
        # q_m = sum_{i != m} f_i * w_i / (m_root * (m_root - w_i))... spec:
        # build from the other rows
        m = special
        zm = roots[m]
        inv_z = fr.batch_inverse(
            [zm * ((zm - w) % BLS_MODULUS) % BLS_MODULUS for w in roots]
        )
        acc = 0
        for i in range(width):
            if i == m:
                continue
            acc += (
                (poly[i] - y)
                % BLS_MODULUS
                * roots[i]
                % BLS_MODULUS
                * inv_z[i]
                % BLS_MODULUS
            )
        q[m] = acc % BLS_MODULUS
    return A.g1_to_bytes(_g1_lincomb(setup, q)), y


def verify_kzg_proof(
    commitment_bytes: bytes,
    z_bytes: bytes,
    y_bytes: bytes,
    proof_bytes: bytes,
    setup: "Optional[TrustedSetup]" = None,
) -> bool:
    """e(P - [y]G1, G2) == e(proof, [tau - z]G2) — spec verify_kzg_proof."""
    setup = setup or official_setup()
    commitment = _g1_from_commitment_bytes(commitment_bytes)
    proof = _g1_from_commitment_bytes(proof_bytes)
    z = _bytes_to_bls_field(bytes(z_bytes))
    y = _bytes_to_bls_field(bytes(y_bytes))
    return _verify_kzg_proof_impl(commitment, z, y, proof, setup)


def _verify_kzg_proof_impl(commitment, z, y, proof, setup) -> bool:
    # X_minus_z = [tau]G2 - [z]G2 ; P_minus_y = commitment - [y]G1
    x_minus_z = setup.tau_g2 + (-G2.mul(z) if z else _g2_zero())
    p_minus_y = commitment + (-G1.mul(y) if y else g1_infinity())
    # e(P - y, G2) * e(-proof, X - z) == 1
    return pairing_check([(p_minus_y, G2), (-proof, x_minus_z)])


def _g2_zero():
    from grandine_tpu.crypto.curves import g2_infinity

    return g2_infinity()


def compute_blob_kzg_proof(
    blob: bytes, commitment_bytes: bytes, setup: "Optional[TrustedSetup]" = None
) -> bytes:
    setup = setup or official_setup()
    _g1_from_commitment_bytes(commitment_bytes)  # validate encoding
    poly = _blob_to_polynomial(bytes(blob), setup.width)
    z = _compute_challenge(bytes(blob), bytes(commitment_bytes), setup.width)
    proof, _y = _compute_kzg_proof_impl(poly, z, setup)
    return proof


def verify_blob_kzg_proof(
    blob: bytes,
    commitment_bytes: bytes,
    proof_bytes: bytes,
    setup: "Optional[TrustedSetup]" = None,
) -> bool:
    setup = setup or official_setup()
    commitment = _g1_from_commitment_bytes(commitment_bytes)
    proof = _g1_from_commitment_bytes(proof_bytes)
    poly = _blob_to_polynomial(bytes(blob), setup.width)
    z = _compute_challenge(bytes(blob), bytes(commitment_bytes), setup.width)
    y = fr.evaluate_polynomial_in_evaluation_form(poly, z, setup.roots_brp)
    return _verify_kzg_proof_impl(commitment, z, y, proof, setup)


def verify_blob_kzg_proof_batch(
    blobs: "Sequence[bytes]",
    commitments: "Sequence[bytes]",
    proofs: "Sequence[bytes]",
    setup: "Optional[TrustedSetup]" = None,
) -> bool:
    """Random-linear-combination batch verification (spec
    verify_blob_kzg_proof_batch): ONE pairing check for N blobs."""
    setup = setup or official_setup()
    n = len(blobs)
    if not (n == len(commitments) == len(proofs)):
        raise KzgError("length mismatch")
    if n == 0:
        return True
    if n == 1:
        return verify_blob_kzg_proof(blobs[0], commitments[0], proofs[0], setup)

    commitment_points = [_g1_from_commitment_bytes(c) for c in commitments]
    proof_points = [_g1_from_commitment_bytes(p) for p in proofs]
    zs, ys = [], []
    for blob, commitment in zip(blobs, commitments):
        poly = _blob_to_polynomial(bytes(blob), setup.width)
        z = _compute_challenge(bytes(blob), bytes(commitment), setup.width)
        zs.append(z)
        ys.append(
            fr.evaluate_polynomial_in_evaluation_form(poly, z, setup.roots_brp)
        )

    # powers of r from the spec's batch-challenge domain
    data = (
        RANDOM_CHALLENGE_KZG_BATCH_DOMAIN
        + setup.width.to_bytes(8, KZG_ENDIANNESS)
        + n.to_bytes(8, KZG_ENDIANNESS)
    )
    for commitment, z, y, proof in zip(commitments, zs, ys, proofs):
        data += bytes(commitment) + _field_to_bytes(z) + _field_to_bytes(y) + bytes(proof)
    r = _hash_to_bls_field(data)
    r_powers = [pow(r, i, BLS_MODULUS) for i in range(n)]

    if USE_DEVICE_KZG:
        got = _batch_pairing_device(
            setup, commitment_points, proof_points, zs, ys, r_powers
        )
        if got is not None:
            return got

    # Σ r^i (C_i - [y_i]G1 + z_i·proof_i)  vs  Σ r^i proof_i under tau:
    #   e(Σ r^i(C_i - y_i + z_i·W_i), G2) == e(Σ r^i W_i, [tau]G2)
    proof_lincomb = g1_infinity()
    rhs_lincomb = g1_infinity()
    for ri, C_pt, W_pt, z, y in zip(
        r_powers, commitment_points, proof_points, zs, ys
    ):
        proof_lincomb = proof_lincomb + W_pt.mul(ri)
        interp = C_pt + (-G1.mul(y) if y else g1_infinity())
        interp = interp + W_pt.mul(z)
        rhs_lincomb = rhs_lincomb + interp.mul(ri)
    return pairing_check(
        [(rhs_lincomb, G2), (-proof_lincomb, setup.tau_g2)]
    )


# ----------------------------------------------------- device batch verify


def _blob_verify_kernel(px, py, p_inf, bits, q2x, q2y):
    """One-dispatch batch blob-proof verdict. Inputs (REST format):
    px/py (4s, 26) affine G1 Montgomery coords, p_inf (4s,) bool, bits
    (4s, 255) MSB-first scalar bits, q2x/q2y (4, 2, 26) affine G2 coords
    [G2, G2, G2, τG2]. The flat batch is four contiguous s-groups (see
    module docstring); the grouped sum tree yields the four pairing P's
    directly. Returns the (1,) bool verdict."""
    import jax.numpy as jnp

    from grandine_tpu.tpu import curve as C
    from grandine_tpu.tpu import field as F
    from grandine_tpu.tpu import limbs as L
    from grandine_tpu.tpu import pairing as TP

    s = int(px.shape[0]) // 4
    qx, qy = L.split(jnp.asarray(px)), L.split(jnp.asarray(py))
    jac = C.scalar_mul(qx, qy, p_inf, jnp.transpose(bits), C.FP_OPS)
    X, Y, Z = C.sum_points_contiguous(jac, s, C.FP_OPS)
    # a group sum CAN legitimately be infinity (adversarial cancellation)
    # — the pairing needs the mask explicitly; one fused Montgomery
    # reduction pulls the relaxed Z into the 8p-bounded zero test's range
    one4 = L.const_fp(L.ONE_MONT_DIGITS, (4,))
    inf = L.is_zero_val(L.montmul(Z, one4))
    Qx, Qy = F.fp2_split(jnp.asarray(q2x)), F.fp2_split(jnp.asarray(q2y))
    return TP.multi_pairing_check((X, Y, Z), (Qx, Qy, F.fp2_one((4,))), inf)


def _setup_for_width(width: int) -> TrustedSetup:
    """Blob width → trusted setup: the official 4096 setup in production,
    the INSECURE known-tau dev setup for test widths."""
    if width == 4096:
        return official_setup()
    from grandine_tpu.kzg.setup import dev_setup

    return dev_setup(width)


class KzgDeviceBackend:
    """The blob_kzg scheme backend (built via schemes.get("blob_kzg"),
    one per lane; also the tail of `verify_blob_kzg_proof_batch` when
    USE_DEVICE_KZG). All verdict-relevant decoding (G1 subgroup checks,
    blob field-element range checks) and the Fiat–Shamir transcript run
    on host in `prepare`; the device evaluates the batch equation in one
    dispatch. Deterministic challenge → verdicts identical to the host
    batch path bit-for-bit."""

    ASYNC_SEAM = ("verify_blobs_async",)
    #: bucket cap: lane batches pad into {4, 8}; anything larger degrades
    #: to the host path rather than minting an unwarmed ladder shape
    MAX_ITEMS = 8

    def __init__(self, *, metrics=None, tracer=None, lane: str = "blob_kzg",
                 mesh=None) -> None:
        self.metrics = metrics
        self.tracer = tracer
        self.lane = lane
        self._g2_dev: dict = {}  # (setup name, width) → (q2x, q2y)

    def _count_kernel(self, kernel: str, sigs: int) -> None:
        if self.metrics is not None:
            self.metrics.device_kernel_calls.labels(kernel).inc()
            if sigs:
                self.metrics.device_kernel_sigs.labels(kernel).inc(sigs)

    def _g2_cache(self, setup: TrustedSetup):
        key = (setup.name, setup.width)
        hit = self._g2_dev.get(key)
        if hit is None:
            from grandine_tpu.tpu import curve as C

            q2x, q2y, _inf = C.g2_points_to_dev([G2, G2, G2, setup.tau_g2])
            hit = self._g2_dev[key] = (q2x, q2y)
        return hit

    def prepare(self, items):
        """Scheduler item geometry (message=blob, public_keys=(commitment,),
        signature=proof) → (status, payload): "ok" → device arrays,
        "invalid" → some item can never verify (the batch must FAIL so
        bisection isolates against the host twin), "mixed"/"oversize" →
        host degradation (per-item verdicts stay correct)."""
        n = len(items)
        if n == 0:
            return "ok", ()
        if n > self.MAX_ITEMS:
            return "oversize", None
        widths = set()
        for it in items:
            keys = it.public_keys
            if keys is None or len(keys) != 1:
                return "invalid", None
            blob_len = len(bytes(it.message))
            if blob_len % BYTES_PER_FIELD_ELEMENT:
                return "invalid", None
            widths.add(blob_len // BYTES_PER_FIELD_ELEMENT)
        if len(widths) != 1:
            # blob widths select the trusted setup — a mixed batch has no
            # single device shape; host degradation handles each item
            return "mixed", None
        width = widths.pop()
        if width < 2 or width & (width - 1):
            return "invalid", None
        setup = _setup_for_width(width)
        return self.prepare_raw(
            [bytes(it.message) for it in items],
            [bytes(it.public_keys[0]) for it in items],
            [bytes(it.signature) for it in items],
            setup,
        )

    def prepare_raw(self, blobs, commitments, proofs, setup: TrustedSetup):
        """Raw byte triples → (status, payload) — the shared prep of the
        scheduler path and verify_blob_kzg_proof_batch's device tail."""
        n = len(blobs)
        if n == 0:
            return "ok", ()
        try:
            commitment_points = [
                _g1_from_commitment_bytes(c) for c in commitments
            ]
            proof_points = [_g1_from_commitment_bytes(p) for p in proofs]
            zs, ys = [], []
            for blob, commitment in zip(blobs, commitments):
                poly = _blob_to_polynomial(bytes(blob), setup.width)
                z = _compute_challenge(
                    bytes(blob), bytes(commitment), setup.width
                )
                zs.append(z)
                ys.append(
                    fr.evaluate_polynomial_in_evaluation_form(
                        poly, z, setup.roots_brp
                    )
                )
        except KzgError:
            return "invalid", None
        data = (
            RANDOM_CHALLENGE_KZG_BATCH_DOMAIN
            + setup.width.to_bytes(8, KZG_ENDIANNESS)
            + n.to_bytes(8, KZG_ENDIANNESS)
        )
        for commitment, z, y, proof in zip(commitments, zs, ys, proofs):
            data += (
                bytes(commitment) + _field_to_bytes(z)
                + _field_to_bytes(y) + bytes(proof)
            )
        r = _hash_to_bls_field(data)
        r_powers = [pow(r, i, BLS_MODULUS) for i in range(n)]
        return "ok", self.pack(
            setup, commitment_points, proof_points, zs, ys, r_powers
        )

    def pack(self, setup, commitment_points, proof_points, zs, ys, r_powers):
        """Decoded points + challenges → the kernel's array payload: the
        four-group flat MSM batch of the module docstring."""
        import numpy as np

        from grandine_tpu.tpu import curve as C
        from grandine_tpu.tpu import limbs as L
        from grandine_tpu.tpu.bls import _bucket

        n = len(commitment_points)
        q = BLS_MODULUS
        bn = _bucket(n, lo=4, hi=self.MAX_ITEMS)
        total = 4 * bn
        px = np.zeros((total, L.NLIMBS), np.int32)
        py = np.zeros((total, L.NLIMBS), np.int32)
        pinf = np.ones(total, bool)  # pads: infinity with scalar 0
        scalars = [0] * total
        for i, (cp, wp, z, ri) in enumerate(
            zip(commitment_points, proof_points, zs, r_powers)
        ):
            px[i], py[i], pinf[i] = C.g1_point_to_dev(cp)
            scalars[i] = ri
            px[bn + i], py[bn + i], pinf[bn + i] = C.g1_point_to_dev(wp)
            scalars[bn + i] = ri * z % q
            px[3 * bn + i] = px[bn + i]
            py[3 * bn + i] = py[bn + i]
            pinf[3 * bn + i] = pinf[bn + i]
            scalars[3 * bn + i] = (q - ri) % q  # −Σ rⁱWᵢ via negated scalars
        px[2 * bn], py[2 * bn], pinf[2 * bn] = C.g1_point_to_dev(G1)
        scalars[2 * bn] = (-sum(
            ri * y for ri, y in zip(r_powers, ys)
        )) % q
        bits = C.scalars_to_bits_msb(scalars, 255)
        q2x, q2y = self._g2_cache(setup)
        return (px, py, pinf, bits, q2x, q2y, n)

    def verify_blobs_async(self, prep):
        """Dispatch the packed batch; returns the zero-arg settle (forces
        the device verdict)."""
        if not prep:
            return lambda: True
        import numpy as np

        from grandine_tpu.tpu.bls import _jitted_global, note_dispatch_shapes

        px, py, pinf, bits, q2x, q2y, n = prep
        fn = _jitted_global("kzg_blob_verify", _blob_verify_kernel)
        args = (px, py, pinf, bits, q2x, q2y)
        note_dispatch_shapes("kzg_blob_verify", args, self.metrics)
        self._count_kernel("kzg_blob_verify", n)
        from grandine_tpu.tpu.bls import _node_profiler

        prof_scope = _node_profiler().annotate("kzg_blob_verify", n)
        if self.tracer is not None:
            with self.tracer.span(
                "device_dispatch",
                {"kernel": "kzg_blob_verify", "lane": self.lane},
            ):
                with prof_scope:
                    out = fn(*args)
        else:
            with prof_scope:
                out = fn(*args)

        def settle() -> bool:
            return bool(np.asarray(out).all())

        return settle


_DEVICE_BACKEND: "Optional[KzgDeviceBackend]" = None


def _batch_pairing_device(
    setup, commitment_points, proof_points, zs, ys, r_powers
):
    """Device tail of verify_blob_kzg_proof_batch: the inputs are already
    decoded and the challenge fixed, so the verdict CANNOT differ from
    the host tail — any device failure returns None and the caller falls
    back. Batches beyond the warmed buckets also decline (None) rather
    than mint a novel ladder shape."""
    global _DEVICE_BACKEND
    if len(commitment_points) > KzgDeviceBackend.MAX_ITEMS:
        return None
    try:
        if _DEVICE_BACKEND is None:
            _DEVICE_BACKEND = KzgDeviceBackend()
        prep = _DEVICE_BACKEND.pack(
            setup, commitment_points, proof_points, zs, ys, r_powers
        )
        return _DEVICE_BACKEND.verify_blobs_async(prep)()
    except ImportError:
        return None
    except Exception as e:
        import warnings

        warnings.warn(
            f"device KZG batch verify failed ({e!r}); "
            "falling back to host pairing"
        )
        return None


def host_check_item(item) -> bool:
    """VerifyItem adapter (blob_kzg lane geometry) — the scheduler's
    bisection leaf and host degradation pass. Never raises: undecodable
    bytes are a False verdict, exactly as the device path scores them."""
    keys = item.public_keys
    if keys is None or len(keys) != 1:
        return False
    blob = bytes(item.message)
    width = len(blob) // BYTES_PER_FIELD_ELEMENT
    if len(blob) % BYTES_PER_FIELD_ELEMENT or width < 2 or width & (width - 1):
        return False
    try:
        return verify_blob_kzg_proof(
            blob, bytes(keys[0]), bytes(item.signature),
            _setup_for_width(width),
        )
    except KzgError:
        return False


__all__ = [
    "KzgError",
    "KzgDeviceBackend",
    "blob_to_kzg_commitment",
    "compute_kzg_proof",
    "compute_blob_kzg_proof",
    "verify_kzg_proof",
    "verify_blob_kzg_proof",
    "verify_blob_kzg_proof_batch",
    "host_check_item",
    "G1_POINT_AT_INFINITY",
]
