"""EIP-4844 KZG operations — reference: kzg_utils/src/eip_4844.rs (the six
public functions over rust-kzg-blst) and the deneb
polynomial-commitments.md spec they implement.

The hot path is the G1 multi-scalar multiplication (one per commitment /
proof): on device it is ONE batched scalar-mul launch + a log-depth sum
tree over the existing TPU curve kernels; the host fallback is a windowed
Pippenger. Verification (2 pairings) runs on the anchor pairing.
"""

from __future__ import annotations

import hashlib
from typing import Optional, Sequence

from grandine_tpu.crypto import bls as A
from grandine_tpu.crypto.curves import G1, G2, Point, g1_infinity
from grandine_tpu.crypto.pairing import pairing_check
from grandine_tpu.kzg import fr
from grandine_tpu.kzg.setup import TrustedSetup, official_setup

BLS_MODULUS = fr.BLS_MODULUS
BYTES_PER_FIELD_ELEMENT = 32
FIAT_SHAMIR_PROTOCOL_DOMAIN = b"FSBLOBVERIFY_V1_"
RANDOM_CHALLENGE_KZG_BATCH_DOMAIN = b"RCKZGBATCH___V1_"
KZG_ENDIANNESS = "big"

G1_POINT_AT_INFINITY = bytes([0xC0]) + b"\x00" * 47

#: flip to False to force the host Pippenger MSM (no JAX)
USE_DEVICE_MSM = True


class KzgError(ValueError):
    pass


# ------------------------------------------------------------ (de)serialize


def _bytes_to_bls_field(b: bytes) -> int:
    v = int.from_bytes(b, KZG_ENDIANNESS)
    if v >= BLS_MODULUS:
        raise KzgError("field element out of range")
    return v


def _field_to_bytes(v: int) -> bytes:
    return int(v).to_bytes(BYTES_PER_FIELD_ELEMENT, KZG_ENDIANNESS)


def _blob_to_polynomial(blob: bytes, width: int) -> "list[int]":
    if len(blob) != width * BYTES_PER_FIELD_ELEMENT:
        raise KzgError(f"blob must be {width * BYTES_PER_FIELD_ELEMENT} bytes")
    return [
        _bytes_to_bls_field(blob[i * 32 : (i + 1) * 32]) for i in range(width)
    ]


def _g1_from_commitment_bytes(b: bytes) -> Point:
    try:
        return A.g1_from_bytes(bytes(b), subgroup_check=True)
    except A.BlsError as e:
        raise KzgError(f"invalid G1 encoding: {e}") from e


def _hash_to_bls_field(data: bytes) -> int:
    return int.from_bytes(hashlib.sha256(data).digest(), KZG_ENDIANNESS) % BLS_MODULUS


def _compute_challenge(blob: bytes, commitment: bytes, width: int) -> int:
    degree_poly = width.to_bytes(16, KZG_ENDIANNESS)
    return _hash_to_bls_field(
        FIAT_SHAMIR_PROTOCOL_DOMAIN + degree_poly + blob + commitment
    )


# ----------------------------------------------------------------------- MSM


def _msm_host(points: "Sequence[Point]", scalars: "Sequence[int]") -> Point:
    """Windowed Pippenger MSM (host fallback)."""
    window = 8
    acc_total = g1_infinity()
    n_windows = (255 + window - 1) // window
    for w in range(n_windows - 1, -1, -1):
        shift = w * window
        buckets: "dict[int, Point]" = {}
        for p, s in zip(points, scalars):
            digit = (s >> shift) & ((1 << window) - 1)
            if digit:
                cur = buckets.get(digit)
                buckets[digit] = p if cur is None else cur + p
        if w != n_windows - 1:
            for _ in range(window):
                acc_total = acc_total.double()
        # Σ d·B_d via descending running sums weighted by digit gaps
        running = g1_infinity()
        window_sum = g1_infinity()
        digits = sorted(buckets, reverse=True)
        for i, digit in enumerate(digits):
            running = running + buckets[digit]
            next_digit = digits[i + 1] if i + 1 < len(digits) else 0
            window_sum = window_sum + running.mul(digit - next_digit)
        acc_total = acc_total + window_sum
    return acc_total


def _msm_device(setup: TrustedSetup, scalars: "Sequence[int]") -> Point:
    """Device MSM over the setup's (cached, limb-form) G1 points: one
    batched scalar-mul kernel + a complete-addition sum tree."""
    import jax
    import numpy as np

    from grandine_tpu.tpu import curve as C
    from grandine_tpu.tpu import limbs as L

    cache = setup._dev_cache
    if cache is None:
        n = setup.width
        xs = np.zeros((n, L.NLIMBS), np.int32)
        ys = np.zeros((n, L.NLIMBS), np.int32)
        inf = np.zeros(n, bool)
        for i, pt in enumerate(setup.g1_lagrange_brp):
            xs[i], ys[i], inf[i] = C.g1_point_to_dev(pt)
        cache = setup._dev_cache = (xs, ys, inf)
    xs, ys, inf = cache

    from grandine_tpu.tpu.bls import _jitted_global

    def msm_kernel(px, py, p_inf, bits):
        import jax.numpy as jnp

        qx, qy = L.split(jnp.asarray(px)), L.split(jnp.asarray(py))
        jac = C.scalar_mul(qx, qy, p_inf, jnp.transpose(bits), C.FP_OPS)
        X, Y, Z = C.sum_points(jac, C.FP_OPS)
        return L.merge(X), L.merge(Y), L.merge(Z)

    fn = _jitted_global(f"kzg_msm_{setup.width}", msm_kernel)
    bits = C.scalars_to_bits_msb([s % BLS_MODULUS for s in scalars], 255)
    X, Y, Z = fn(xs, ys, inf, bits)
    import numpy as np

    return C.dev_to_g1_point(np.asarray(X), np.asarray(Y), np.asarray(Z))


def _g1_lincomb(setup: TrustedSetup, scalars: "Sequence[int]") -> Point:
    if USE_DEVICE_MSM:
        try:
            return _msm_device(setup, scalars)
        except ImportError:
            pass  # no JAX: host path
        except Exception as e:
            import warnings

            warnings.warn(
                f"device MSM failed ({e!r}); falling back to host Pippenger"
            )
    return _msm_host(setup.g1_lagrange_brp, scalars)


# ------------------------------------------------------------ the six calls


def blob_to_kzg_commitment(
    blob: bytes, setup: "Optional[TrustedSetup]" = None
) -> bytes:
    setup = setup or official_setup()
    poly = _blob_to_polynomial(bytes(blob), setup.width)
    return A.g1_to_bytes(_g1_lincomb(setup, poly))


def compute_kzg_proof(
    blob: bytes, z_bytes: bytes, setup: "Optional[TrustedSetup]" = None
) -> "tuple[bytes, bytes]":
    """Returns (proof, y) for the evaluation p(z) = y."""
    setup = setup or official_setup()
    poly = _blob_to_polynomial(bytes(blob), setup.width)
    z = _bytes_to_bls_field(bytes(z_bytes))
    proof, y = _compute_kzg_proof_impl(poly, z, setup)
    return proof, _field_to_bytes(y)


def _compute_kzg_proof_impl(poly, z: int, setup: TrustedSetup):
    roots = setup.roots_brp
    y = fr.evaluate_polynomial_in_evaluation_form(poly, z, roots)
    # quotient q_i = (f_i - y) / (w_i - z), with the special row when
    # z equals a root (spec compute_kzg_proof_impl)
    width = setup.width
    denoms = [(w - z) % BLS_MODULUS for w in roots]
    inv_denoms = fr.batch_inverse(denoms)
    q = [0] * width
    special = None
    for i in range(width):
        if denoms[i] == 0:
            special = i
            continue
        q[i] = (poly[i] - y) % BLS_MODULUS * inv_denoms[i] % BLS_MODULUS
    if special is not None:
        # q_m = sum_{i != m} f_i * w_i / (m_root * (m_root - w_i))... spec:
        # build from the other rows
        m = special
        zm = roots[m]
        inv_z = fr.batch_inverse(
            [zm * ((zm - w) % BLS_MODULUS) % BLS_MODULUS for w in roots]
        )
        acc = 0
        for i in range(width):
            if i == m:
                continue
            acc += (
                (poly[i] - y)
                % BLS_MODULUS
                * roots[i]
                % BLS_MODULUS
                * inv_z[i]
                % BLS_MODULUS
            )
        q[m] = acc % BLS_MODULUS
    return A.g1_to_bytes(_g1_lincomb(setup, q)), y


def verify_kzg_proof(
    commitment_bytes: bytes,
    z_bytes: bytes,
    y_bytes: bytes,
    proof_bytes: bytes,
    setup: "Optional[TrustedSetup]" = None,
) -> bool:
    """e(P - [y]G1, G2) == e(proof, [tau - z]G2) — spec verify_kzg_proof."""
    setup = setup or official_setup()
    commitment = _g1_from_commitment_bytes(commitment_bytes)
    proof = _g1_from_commitment_bytes(proof_bytes)
    z = _bytes_to_bls_field(bytes(z_bytes))
    y = _bytes_to_bls_field(bytes(y_bytes))
    return _verify_kzg_proof_impl(commitment, z, y, proof, setup)


def _verify_kzg_proof_impl(commitment, z, y, proof, setup) -> bool:
    # X_minus_z = [tau]G2 - [z]G2 ; P_minus_y = commitment - [y]G1
    x_minus_z = setup.tau_g2 + (-G2.mul(z) if z else _g2_zero())
    p_minus_y = commitment + (-G1.mul(y) if y else g1_infinity())
    # e(P - y, G2) * e(-proof, X - z) == 1
    return pairing_check([(p_minus_y, G2), (-proof, x_minus_z)])


def _g2_zero():
    from grandine_tpu.crypto.curves import g2_infinity

    return g2_infinity()


def compute_blob_kzg_proof(
    blob: bytes, commitment_bytes: bytes, setup: "Optional[TrustedSetup]" = None
) -> bytes:
    setup = setup or official_setup()
    _g1_from_commitment_bytes(commitment_bytes)  # validate encoding
    poly = _blob_to_polynomial(bytes(blob), setup.width)
    z = _compute_challenge(bytes(blob), bytes(commitment_bytes), setup.width)
    proof, _y = _compute_kzg_proof_impl(poly, z, setup)
    return proof


def verify_blob_kzg_proof(
    blob: bytes,
    commitment_bytes: bytes,
    proof_bytes: bytes,
    setup: "Optional[TrustedSetup]" = None,
) -> bool:
    setup = setup or official_setup()
    commitment = _g1_from_commitment_bytes(commitment_bytes)
    proof = _g1_from_commitment_bytes(proof_bytes)
    poly = _blob_to_polynomial(bytes(blob), setup.width)
    z = _compute_challenge(bytes(blob), bytes(commitment_bytes), setup.width)
    y = fr.evaluate_polynomial_in_evaluation_form(poly, z, setup.roots_brp)
    return _verify_kzg_proof_impl(commitment, z, y, proof, setup)


def verify_blob_kzg_proof_batch(
    blobs: "Sequence[bytes]",
    commitments: "Sequence[bytes]",
    proofs: "Sequence[bytes]",
    setup: "Optional[TrustedSetup]" = None,
) -> bool:
    """Random-linear-combination batch verification (spec
    verify_blob_kzg_proof_batch): ONE pairing check for N blobs."""
    setup = setup or official_setup()
    n = len(blobs)
    if not (n == len(commitments) == len(proofs)):
        raise KzgError("length mismatch")
    if n == 0:
        return True
    if n == 1:
        return verify_blob_kzg_proof(blobs[0], commitments[0], proofs[0], setup)

    commitment_points = [_g1_from_commitment_bytes(c) for c in commitments]
    proof_points = [_g1_from_commitment_bytes(p) for p in proofs]
    zs, ys = [], []
    for blob, commitment in zip(blobs, commitments):
        poly = _blob_to_polynomial(bytes(blob), setup.width)
        z = _compute_challenge(bytes(blob), bytes(commitment), setup.width)
        zs.append(z)
        ys.append(
            fr.evaluate_polynomial_in_evaluation_form(poly, z, setup.roots_brp)
        )

    # powers of r from the spec's batch-challenge domain
    data = (
        RANDOM_CHALLENGE_KZG_BATCH_DOMAIN
        + setup.width.to_bytes(8, KZG_ENDIANNESS)
        + n.to_bytes(8, KZG_ENDIANNESS)
    )
    for commitment, z, y, proof in zip(commitments, zs, ys, proofs):
        data += bytes(commitment) + _field_to_bytes(z) + _field_to_bytes(y) + bytes(proof)
    r = _hash_to_bls_field(data)
    r_powers = [pow(r, i, BLS_MODULUS) for i in range(n)]

    # Σ r^i (C_i - [y_i]G1 + z_i·proof_i)  vs  Σ r^i proof_i under tau:
    #   e(Σ r^i(C_i - y_i + z_i·W_i), G2) == e(Σ r^i W_i, [tau]G2)
    proof_lincomb = g1_infinity()
    rhs_lincomb = g1_infinity()
    for ri, C_pt, W_pt, z, y in zip(
        r_powers, commitment_points, proof_points, zs, ys
    ):
        proof_lincomb = proof_lincomb + W_pt.mul(ri)
        interp = C_pt + (-G1.mul(y) if y else g1_infinity())
        interp = interp + W_pt.mul(z)
        rhs_lincomb = rhs_lincomb + interp.mul(ri)
    return pairing_check(
        [(rhs_lincomb, G2), (-proof_lincomb, setup.tau_g2)]
    )


__all__ = [
    "KzgError",
    "blob_to_kzg_commitment",
    "compute_kzg_proof",
    "compute_blob_kzg_proof",
    "verify_kzg_proof",
    "verify_blob_kzg_proof",
    "verify_blob_kzg_proof_batch",
    "G1_POINT_AT_INFINITY",
]
