"""BLS12-381 scalar-field (Fr) helpers for KZG: roots of unity,
bit-reversal permutation, batch inversion, barycentric evaluation.

Spec parity: deneb/polynomial-commitments.md (compute_roots_of_unity,
bit_reversal_permutation, evaluate_polynomial_in_evaluation_form).
"""

from __future__ import annotations

from typing import Sequence

from grandine_tpu.crypto.constants import R as BLS_MODULUS

#: multiplicative generator of Fr* (c-kzg PRIMITIVE_ROOT_OF_UNITY)
PRIMITIVE_ROOT = 7


def compute_roots_of_unity(order: int) -> "list[int]":
    """order-th roots of unity, natural order: w^0, w^1, …"""
    assert order & (order - 1) == 0, "order must be a power of two"
    assert (BLS_MODULUS - 1) % order == 0
    w = pow(PRIMITIVE_ROOT, (BLS_MODULUS - 1) // order, BLS_MODULUS)
    out = [1] * order
    for i in range(1, order):
        out[i] = out[i - 1] * w % BLS_MODULUS
    return out


def bit_reversal_permutation(values: Sequence) -> list:
    n = len(values)
    assert n & (n - 1) == 0
    bits = n.bit_length() - 1
    return [
        values[int(format(i, f"0{bits}b")[::-1], 2)] if bits else values[i]
        for i in range(n)
    ]


def batch_inverse(values: "Sequence[int]") -> "list[int]":
    """Montgomery batch inversion over Fr; zeros map to zero (callers
    guard the z == root case). Delegates to the shared field helper."""
    from grandine_tpu.crypto.fields import batch_inverse as _bi

    return _bi(values, BLS_MODULUS)


def evaluate_polynomial_in_evaluation_form(
    evaluations: "Sequence[int]", z: int, roots_brp: "Sequence[int]"
) -> int:
    """Barycentric evaluation at z of the polynomial given by its
    evaluations at the bit-reversed roots of unity (spec
    evaluate_polynomial_in_evaluation_form)."""
    width = len(evaluations)
    assert len(roots_brp) == width
    z %= BLS_MODULUS
    # z coincides with a root: the evaluation is just that entry
    for i, r in enumerate(roots_brp):
        if z == r:
            return evaluations[i] % BLS_MODULUS
    inverses = batch_inverse([(z - r) % BLS_MODULUS for r in roots_brp])
    result = 0
    for f_i, r_i, inv_i in zip(evaluations, roots_brp, inverses):
        result += f_i * r_i % BLS_MODULUS * inv_i % BLS_MODULUS
    result %= BLS_MODULUS
    result = result * (pow(z, width, BLS_MODULUS) - 1) % BLS_MODULUS
    result = (
        result * pow(width % BLS_MODULUS, BLS_MODULUS - 2, BLS_MODULUS)
        % BLS_MODULUS
    )
    return result


__all__ = [
    "BLS_MODULUS",
    "PRIMITIVE_ROOT",
    "compute_roots_of_unity",
    "bit_reversal_permutation",
    "batch_inverse",
    "evaluate_polynomial_in_evaluation_form",
]
