"""Blob-sidecar validation — reference: the deneb blob plane
(types/src/deneb containers, fork-choice BlobSidecar tasks, and
helper_functions misc::kzg_commitment_inclusion_proof).

A BlobSidecar carries (blob, commitment, proof) plus a Merkle branch
proving the commitment sits in the signed block body it claims. Both the
branch and the KZG proof must verify before a sidecar enters the blob
cache.
"""

from __future__ import annotations

from typing import Optional

from grandine_tpu.core import hashing
from grandine_tpu.kzg import eip4844
from grandine_tpu.ssz import Bytes48
from grandine_tpu.ssz.merkle import verify_merkle_proof


def _body_layout(body_cls, p):
    """(field_position, body_depth, list_depth) for blob_kzg_commitments."""
    names = [name for name, _ in body_cls.FIELDS]
    field_pos = names.index("blob_kzg_commitments")
    n_fields = len(names)
    body_depth = max(1, (n_fields - 1).bit_length())
    list_depth = (p.MAX_BLOB_COMMITMENTS_PER_BLOCK - 1).bit_length()
    return field_pos, body_depth, list_depth


def inclusion_proof_depth(body_cls, p) -> int:
    field_pos, body_depth, list_depth = _body_layout(body_cls, p)
    return body_depth + 1 + list_depth  # +1: list length mixin


from grandine_tpu.ssz.merkle import merkle_branch as _merkle_branch  # noqa: E402


def build_commitment_inclusion_proof(body, index: int, p) -> "list[bytes]":
    """Merkle branch for commitment `index` of `body.blob_kzg_commitments`
    against the body root (producer side; reference
    misc::kzg_commitment_inclusion_proof)."""
    body_cls = type(body)
    field_pos, body_depth, list_depth = _body_layout(body_cls, p)
    commitments = list(body.blob_kzg_commitments)
    if not 0 <= index < len(commitments):
        raise IndexError(index)

    leaves = [Bytes48.hash_tree_root(bytes(c)) for c in commitments]
    branch = _merkle_branch(leaves, index, list_depth)
    branch.append(len(commitments).to_bytes(32, "little"))  # length mixin
    field_roots = [
        ftyp.hash_tree_root(getattr(body, fname))
        for fname, ftyp in body_cls.FIELDS
    ]
    branch += _merkle_branch(field_roots, field_pos, body_depth)
    return branch


def verify_commitment_inclusion(
    commitment: bytes,
    index: int,
    branch,
    body_root: bytes,
    body_cls,
    p,
) -> bool:
    """Spec verify_blob_sidecar_inclusion_proof."""
    field_pos, body_depth, list_depth = _body_layout(body_cls, p)
    depth = body_depth + 1 + list_depth
    gindex = (field_pos << (list_depth + 1)) | index
    leaf = Bytes48.hash_tree_root(bytes(commitment))
    return verify_merkle_proof(leaf, list(branch), depth, gindex, body_root)


def validate_blob_sidecar(
    sidecar, body_cls, p, setup: "Optional[object]" = None
) -> None:
    """Full sidecar validation: index bound, inclusion proof against the
    signed header's body root, then the KZG proof. Raises KzgError."""
    validate_blob_sidecar_structure(sidecar, body_cls, p)
    if not eip4844.verify_blob_kzg_proof(
        bytes(sidecar.blob),
        bytes(sidecar.kzg_commitment),
        bytes(sidecar.kzg_proof),
        setup,
    ):
        raise eip4844.KzgError("blob KZG proof invalid")


def validate_blob_sidecar_structure(sidecar, body_cls, p) -> None:
    """The host-only legs of sidecar validation — index bound and the
    commitment inclusion proof — WITHOUT the KZG proof check, so callers
    with a verify-scheduler `blob_kzg` lane can run the proof leg as a
    device batch (runtime/controller.py) and keep this part on the
    gossip pool. Raises KzgError."""
    if int(sidecar.index) >= p.MAX_BLOBS_PER_BLOCK:
        raise eip4844.KzgError("sidecar index out of range")
    header = sidecar.signed_block_header.message
    ok = verify_commitment_inclusion(
        bytes(sidecar.kzg_commitment),
        int(sidecar.index),
        [bytes(b) for b in sidecar.kzg_commitment_inclusion_proof],
        bytes(header.body_root),
        body_cls,
        p,
    )
    if not ok:
        raise eip4844.KzgError("commitment inclusion proof invalid")


def make_blob_sidecars(
    ns, p, signed_block, blobs, setup: "Optional[object]" = None,
    proofs: "Optional[list]" = None,
):
    """Proposer side: BlobSidecar containers for a signed deneb block
    (spec get_blob_sidecars; validator/src/validator.rs blob bundle
    handling). `blobs[i]` must match body.blob_kzg_commitments[i]; proofs
    are computed when not supplied (the builder/EL normally supplies
    them)."""
    block = signed_block.message
    body = block.body
    commitments = [bytes(c) for c in body.blob_kzg_commitments]
    assert len(blobs) == len(commitments), "one blob per commitment"
    header = ns.BeaconBlockHeader(
        slot=int(block.slot),
        proposer_index=int(block.proposer_index),
        parent_root=bytes(block.parent_root),
        state_root=bytes(block.state_root),
        body_root=body.hash_tree_root(),
    )
    signed_header = ns.SignedBeaconBlockHeader(
        message=header, signature=bytes(signed_block.signature)
    )
    out = []
    for i, blob in enumerate(blobs):
        proof = (
            proofs[i]
            if proofs is not None
            else eip4844.compute_blob_kzg_proof(
                bytes(blob), commitments[i], setup
            )
        )
        out.append(
            ns.BlobSidecar(
                index=i,
                blob=bytes(blob),
                kzg_commitment=commitments[i],
                kzg_proof=bytes(proof),
                signed_block_header=signed_header,
                kzg_commitment_inclusion_proof=
                    build_commitment_inclusion_proof(body, i, p),
            )
        )
    return out


__all__ = [
    "build_commitment_inclusion_proof",
    "verify_commitment_inclusion",
    "validate_blob_sidecar",
    "make_blob_sidecars",
    "inclusion_proof_depth",
]
