"""Trusted setup loading — reference: kzg_utils/src/trusted_setup.rs
(embedded ceremony output, lazily parsed into library settings).

Two sources:
  - `official_setup()`: the vendored public KZG-ceremony file
    (data/trusted_setup.txt — 4096 Lagrange-form G1 points + 65 monomial
    G2 points; PUBLIC DATA from the Ethereum ceremony). Decompression of
    4096 G1 points is pure-Python sqrt work (~seconds), so the affine
    integer coordinates are cached beside the file after the first load.
  - `dev_setup(n)`: an INSECURE synthetic setup from a known tau, any
    power-of-two size — for tests and small-degree development; never for
    production verification of real blobs.

Per the deneb spec, the G1 Lagrange points are stored/used in
bit-reversal-permuted order.
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional, Sequence

import numpy as np

from grandine_tpu.crypto import bls as A
from grandine_tpu.crypto.curves import G1, G2, Point, g1_infinity
from grandine_tpu.crypto.fields import Fq
from grandine_tpu.kzg import fr

_DATA_DIR = os.path.join(os.path.dirname(__file__), "data")
_OFFICIAL_TXT = os.path.join(_DATA_DIR, "trusted_setup.txt")
_OFFICIAL_CACHE = os.path.join(_DATA_DIR, "trusted_setup.cache.npz")


class TrustedSetup:
    """g1_lagrange_brp: [L_i(tau)]·G1 in bit-reversed order (length n);
    g2_monomial: [tau^i]·G2 (length >= 2); roots_brp: matching roots."""

    def __init__(self, g1_lagrange_brp, g2_monomial, name: str) -> None:
        self.g1_lagrange_brp = list(g1_lagrange_brp)
        self.g2_monomial = list(g2_monomial)
        self.name = name
        self.width = len(self.g1_lagrange_brp)
        assert self.width & (self.width - 1) == 0
        self.roots_brp = fr.bit_reversal_permutation(
            fr.compute_roots_of_unity(self.width)
        )
        self._dev_cache = None  # device-limb arrays, built lazily

    @property
    def tau_g2(self):
        return self.g2_monomial[1]


_OFFICIAL: "Optional[TrustedSetup]" = None
_DEV: dict = {}


def official_setup() -> TrustedSetup:
    """The production setup (FIELD_ELEMENTS_PER_BLOB = 4096)."""
    global _OFFICIAL
    if _OFFICIAL is not None:
        return _OFFICIAL
    points = _load_cached_official()
    if points is None:
        points = _parse_official_txt()
        _store_cache(points)
    g1, g2 = points
    g1_points = [_g1_from_affine(x, y) for x, y in g1]
    g2_points = [_g2_from_bytes_unchecked(b) for b in g2[:2]]
    _OFFICIAL = TrustedSetup(
        fr.bit_reversal_permutation(g1_points), g2_points, "official"
    )
    return _OFFICIAL


def dev_setup(n: int = 64, tau: int = 0x1337_F00D_D00D_5EED) -> TrustedSetup:
    """INSECURE known-tau setup for tests/dev (tau is public!)."""
    key = (n, tau)
    hit = _DEV.get(key)
    if hit is not None:
        return hit
    roots = fr.compute_roots_of_unity(n)
    # Lagrange basis at tau: L_i(tau) = (tau^n - 1) * w^i / (n * (tau - w^i))
    R = fr.BLS_MODULUS
    tau %= R
    tn = (pow(tau, n, R) - 1) % R
    n_inv = pow(n % R, R - 2, R)
    denoms = fr.batch_inverse([(tau - w) % R for w in roots])
    lag = [tn * w % R * d % R * n_inv % R for w, d in zip(roots, denoms)]
    g1_points = [G1.mul(v) if v else g1_infinity() for v in lag]
    g2_points = [G2.mul(pow(tau, i, R)) for i in range(2)]
    setup = TrustedSetup(
        fr.bit_reversal_permutation(g1_points), g2_points, f"dev-{n}"
    )
    _DEV[key] = setup
    return setup


# ----------------------------------------------------------------- parsing


def _parse_official_txt():
    with open(_OFFICIAL_TXT) as f:
        lines = [ln.strip() for ln in f if ln.strip()]
    n_g1 = int(lines[0])
    n_g2 = int(lines[1])
    g1_hex = lines[2 : 2 + n_g1]
    g2_hex = lines[2 + n_g1 : 2 + n_g1 + n_g2]
    g1 = []
    for h in g1_hex:
        p = A.g1_from_bytes(bytes.fromhex(h), subgroup_check=False)
        aff = p.to_affine()
        g1.append((aff[0].n, aff[1].n))
    g2 = [bytes.fromhex(h) for h in g2_hex]
    return g1, g2


def _txt_digest() -> bytes:
    with open(_OFFICIAL_TXT, "rb") as f:
        return hashlib.sha256(f.read()).digest()


def _load_cached_official():
    """npz cache (no pickle: nothing executable in the file), keyed on a
    content hash of the source txt."""
    try:
        with np.load(_OFFICIAL_CACHE, allow_pickle=False) as z:
            if bytes(z["digest"].tobytes()) != _txt_digest():
                return None
            g1_raw = z["g1"]  # (N, 2, 48) big-endian affine coords
            g2_raw = z["g2"]  # (M, 96) compressed points
        g1 = [
            (
                int.from_bytes(g1_raw[i, 0].tobytes(), "big"),
                int.from_bytes(g1_raw[i, 1].tobytes(), "big"),
            )
            for i in range(g1_raw.shape[0])
        ]
        g2 = [g2_raw[i].tobytes() for i in range(g2_raw.shape[0])]
        return g1, g2
    except Exception:
        # any unreadable/corrupt cache (incl. zipfile.BadZipFile from a
        # truncated write) falls back to re-parsing the source txt
        return None


def _store_cache(points) -> None:
    g1, g2 = points
    try:
        g1_raw = np.zeros((len(g1), 2, 48), np.uint8)
        for i, (x, y) in enumerate(g1):
            g1_raw[i, 0] = np.frombuffer(x.to_bytes(48, "big"), np.uint8)
            g1_raw[i, 1] = np.frombuffer(y.to_bytes(48, "big"), np.uint8)
        g2_raw = np.stack(
            [np.frombuffer(b, np.uint8) for b in g2]
        )
        tmp = _OFFICIAL_CACHE + ".tmp"
        np.savez(
            tmp,
            digest=np.frombuffer(_txt_digest(), np.uint8),
            g1=g1_raw,
            g2=g2_raw,
        )
        os.replace(tmp + ".npz", _OFFICIAL_CACHE)  # atomic publish
    except OSError:
        pass


def _g1_from_affine(x: int, y: int):
    return Point.from_affine(Fq(x), Fq(y), A.B1)


def _g2_from_bytes_unchecked(data: bytes):
    return A.g2_from_bytes(data, subgroup_check=False)


__all__ = ["TrustedSetup", "official_setup", "dev_setup"]
