"""Beacon API routes + dispatch — reference: http_api/src/routing.rs
(route table :221-234, states :341-369, pools :389-410), standard.rs
(handlers), http_api_utils (StateId/BlockId parsing).

The router is dependency-free: `(method, pattern)` pairs with `{param}`
segments; handlers take (ctx, params, query, body) and return JSON-able
dicts. `ApiContext` bundles the live services the handlers read.
"""

from __future__ import annotations

import json
import re
from typing import Any, Callable, Optional

from grandine_tpu import __version__
from grandine_tpu.consensus import accessors
from grandine_tpu.types.combined import state_phase_of
from grandine_tpu.types.primitives import FAR_FUTURE_EPOCH


class ApiError(Exception):
    def __init__(self, status: int, message: str) -> None:
        super().__init__(message)
        self.status = status
        self.message = message


class ApiContext:
    """What handlers see (reference http_api context): controller snapshot
    access plus the pools/trackers/metrics wired by the runtime."""

    def __init__(
        self,
        controller,
        cfg,
        attestation_pool=None,
        operation_pool=None,
        liveness=None,
        metrics=None,
        genesis_time: "Optional[int]" = None,
        keymanager=None,
        event_bus=None,
        validator_service=None,
        sync_pool=None,
        network=None,
        subnet_service=None,
        keymanager_token: "Optional[str]" = None,
        data_dir: "Optional[str]" = None,
        tracer=None,
        flight=None,
        profiler=None,
    ) -> None:
        self.controller = controller
        self.cfg = cfg
        self.attestation_pool = attestation_pool
        self.operation_pool = operation_pool
        self.liveness = liveness
        self.metrics = metrics
        self.genesis_time = genesis_time
        self.keymanager = keymanager
        self.event_bus = event_bus
        self.validator_service = validator_service
        self.sync_pool = sync_pool
        self.network = network
        self.subnet_service = subnet_service
        #: bearer token gating the keymanager routes at the server layer
        #: (server.py _authorized); None = open (in-process tests)
        self.keymanager_token = keymanager_token
        #: data directory whose on-disk size /metrics reports
        self.data_dir = data_dir
        #: grandine_tpu.tracing.Tracer backing /eth/v1/debug/grandine/trace
        self.tracer = tracer
        #: runtime.flight.FlightRecorder backing
        #: /eth/v1/debug/grandine/flight (verify-plane batch timeline)
        self.flight = flight
        #: runtime.profiler.KernelProfiler backing
        #: /eth/v1/debug/grandine/profile (device-time attribution +
        #: capture session control)
        self.profiler = profiler
        #: pubkey-hex -> SignedValidatorRegistrationV1 JSON (builder flow)
        self.validator_registrations: "dict[str, dict]" = {}
        #: validator index -> fee recipient (prepare_beacon_proposer)
        self.prepared_proposers: "dict[int, str]" = {}

    def snapshot(self):
        return self.controller.snapshot()

    def resolve_state(self, state_id: str):
        """StateId: head | finalized | justified | genesis | <slot> | <0xroot>."""
        snap = self.snapshot()
        if state_id == "head":
            return snap.head_state
        if state_id == "finalized":
            root = bytes(snap.finalized_checkpoint.root)
            node = self.controller.store.blocks.get(root)
            if node is not None:
                return node.state
            return snap.head_state  # anchor pruned: best effort
        if state_id == "justified":
            return self.controller.store.justified_state
        if state_id == "genesis":
            state_id = "0"
        if state_id.startswith("0x"):
            root = bytes.fromhex(state_id[2:])
            for node in self.controller.store.blocks.values():
                if node.state.hash_tree_root() == root:
                    return node.state
            raise ApiError(404, f"state {state_id} not found")
        try:
            slot = int(state_id)
        except ValueError:
            raise ApiError(400, f"invalid state id {state_id!r}") from None
        for node in sorted(
            self.controller.store.blocks.values(), key=lambda n: n.slot
        ):
            if node.slot == slot:
                return node.state
        raise ApiError(404, f"no state at slot {slot}")

    def resolve_block(self, block_id: str):
        snap = self.snapshot()
        store = self.controller.store
        if block_id == "head":
            return store.blocks[snap.head_root]
        if block_id == "finalized":
            node = store.blocks.get(bytes(snap.finalized_checkpoint.root))
            if node is None:
                raise ApiError(404, "finalized block pruned")
            return node
        if block_id.startswith("0x"):
            node = store.blocks.get(bytes.fromhex(block_id[2:]))
            if node is None:
                raise ApiError(404, f"block {block_id} not found")
            return node
        try:
            slot = int(block_id)
        except ValueError:
            raise ApiError(400, f"invalid block id {block_id!r}") from None
        for node in store.blocks.values():
            if node.slot == slot:
                return node
        raise ApiError(404, f"no block at slot {slot}")


def hex_(b: bytes) -> str:
    return "0x" + bytes(b).hex()


def _parse_int(value, what: str) -> int:
    try:
        return int(value)
    except (TypeError, ValueError):
        raise ApiError(400, f"invalid {what}: {value!r}") from None


# ------------------------------------------------------------------ router


class Router:
    def __init__(self) -> None:
        self.routes: "list[tuple[str, re.Pattern, Callable]]" = []

    def add(self, method: str, pattern: str, handler: Callable) -> None:
        regex = re.compile(
            "^" + re.sub(r"\{(\w+)\}", r"(?P<\1>[^/]+)", pattern) + "$"
        )
        self.routes.append((method.upper(), regex, handler))

    def dispatch(
        self, ctx: ApiContext, method: str, path: str,
        query: "Optional[dict]" = None, body: Any = None,
    ):
        """Returns (status, payload). JSON endpoints return dicts; /metrics
        returns text."""
        for m, regex, handler in self.routes:
            if m != method.upper():
                continue
            match = regex.match(path)
            if match is None:
                continue
            try:
                payload = handler(ctx, match.groupdict(), query or {}, body)
                return 200, payload
            except ApiError as e:
                return e.status, {"code": e.status, "message": e.message}
            except Exception as e:  # handler crash -> 500, not a dead server
                return 500, {"code": 500, "message": repr(e)}
        return 404, {"code": 404, "message": f"no route for {method} {path}"}


# ---------------------------------------------------------------- handlers


def get_node_version(ctx, params, query, body):
    return {"data": {"version": f"grandine-tpu/{__version__}"}}


def get_node_health(ctx, params, query, body):
    return {}


def get_node_syncing(ctx, params, query, body):
    snap = ctx.snapshot()
    head_slot = int(snap.head_state.slot)
    return {
        "data": {
            "head_slot": str(head_slot),
            "sync_distance": str(max(0, snap.slot - head_slot)),
            "is_syncing": snap.slot - head_slot > 1,
            "is_optimistic": bool(getattr(snap, "is_optimistic", False)),
            "el_offline": True,
        }
    }


def get_genesis(ctx, params, query, body):
    snap = ctx.snapshot()
    state = snap.head_state
    return {
        "data": {
            "genesis_time": str(int(state.genesis_time)),
            "genesis_validators_root": hex_(state.genesis_validators_root),
            "genesis_fork_version": hex_(ctx.cfg.genesis_fork_version),
        }
    }


def get_state_root(ctx, params, query, body):
    state = ctx.resolve_state(params["state_id"])
    return {"data": {"root": hex_(state.hash_tree_root())}}


def get_debug_fork_choice(ctx, params, query, body):
    """Beacon API /eth/v1/debug/fork_choice (http_api/src/routing.rs:461):
    the store's block DAG with per-node weight/viability detail.

    The DAG is mutator-owned; this handler reads it racily (blocks is
    insert-only except at finality/invalidation pruning) and retries the
    whole computation on a concurrent-mutation error instead of taking a
    lock on the hot path — a debug endpoint must never slow the mutator."""
    store = ctx.controller.store
    snap = ctx.snapshot()
    last_err = None
    for _attempt in range(3):
        try:
            return _debug_fork_choice_once(store, snap)
        except RuntimeError as e:  # dict mutated during iteration
            last_err = e
    raise last_err


def _debug_fork_choice_once(store, snap):
    weights = store._subtree_weights(bytes(store.justified_checkpoint.root))
    nodes = []
    for root, node in list(store.blocks.items()):
        nodes.append({
            "slot": str(node.slot),
            "block_root": hex_(root),
            "parent_root": hex_(node.parent_root),
            "justified_epoch": str(
                int(node.state.current_justified_checkpoint.epoch)
            ),
            "finalized_epoch": str(int(node.state.finalized_checkpoint.epoch)),
            "weight": str(weights.get(root, 0)),
            "validity": "optimistic" if node.optimistic else "valid",
            "execution_block_hash": hex_(
                node.execution_block_hash or b"\x00" * 32
            ),
        })
    return {
        "justified_checkpoint": {
            "epoch": str(int(snap.justified_checkpoint.epoch)),
            "root": hex_(snap.justified_checkpoint.root),
        },
        "finalized_checkpoint": {
            "epoch": str(int(snap.finalized_checkpoint.epoch)),
            "root": hex_(snap.finalized_checkpoint.root),
        },
        "fork_choice_nodes": nodes,
    }


def get_debug_heads(ctx, params, query, body):
    """Chain tips (blocks without children) — /eth/v2/debug/beacon/heads.
    Same racy-read + snapshot-copy discipline as debug_fork_choice."""
    store = ctx.controller.store
    snap = ctx.snapshot()
    blocks = dict(store.blocks)
    children = dict(store.children)
    heads = [
        {
            "root": hex_(root),
            "slot": str(node.slot),
            "execution_optimistic": bool(node.optimistic),
        }
        for root, node in blocks.items()
        if not children.get(root)
    ]
    return {"data": heads or [{
        "root": hex_(snap.head_root),
        "slot": str(int(snap.head_state.slot)),
        "execution_optimistic": bool(snap.is_optimistic),
    }]}


def get_debug_state(ctx, params, query, body):
    """Full SSZ state dump — /eth/v2/debug/beacon/states/{state_id}
    (returns the raw container; the server layer SSZ/JSON-encodes)."""
    from grandine_tpu.types.combined import state_phase_of

    state = ctx.resolve_state(params["state_id"])
    return {
        "version": state_phase_of(state, ctx.cfg).key,
        "execution_optimistic": bool(
            getattr(ctx.snapshot(), "is_optimistic", False)
        ),
        "data": {"ssz": "0x" + state.serialize().hex()},
    }


def get_state_fork(ctx, params, query, body):
    state = ctx.resolve_state(params["state_id"])
    return {
        "data": {
            "previous_version": hex_(state.fork.previous_version),
            "current_version": hex_(state.fork.current_version),
            "epoch": str(int(state.fork.epoch)),
        }
    }


def get_finality_checkpoints(ctx, params, query, body):
    state = ctx.resolve_state(params["state_id"])

    def cp(c):
        return {"epoch": str(int(c.epoch)), "root": hex_(c.root)}

    return {
        "data": {
            "previous_justified": cp(state.previous_justified_checkpoint),
            "current_justified": cp(state.current_justified_checkpoint),
            "finalized": cp(state.finalized_checkpoint),
        }
    }


def _validator_status(v, balance: int, epoch: int) -> str:
    if int(v.activation_epoch) > epoch:
        return (
            "pending_queued"
            if int(v.activation_eligibility_epoch) != FAR_FUTURE_EPOCH
            else "pending_initialized"
        )
    if epoch < int(v.exit_epoch):
        return "active_slashed" if bool(v.slashed) else "active_ongoing"
    if epoch < int(v.withdrawable_epoch):
        return "exited_slashed" if bool(v.slashed) else "exited_unslashed"
    return "withdrawal_done" if balance == 0 else "withdrawal_possible"


def get_state_validators(ctx, params, query, body):
    state = ctx.resolve_state(params["state_id"])
    p = ctx.cfg.preset
    epoch = accessors.get_current_epoch(state, p)
    ids = query.get("id")
    if ids:
        try:
            indices = [int(i) for i in ids.split(",")]
        except ValueError:
            raise ApiError(400, f"invalid validator id list {ids!r}") from None
        if any(i < 0 for i in indices):
            raise ApiError(400, "validator indices must be non-negative")
    else:
        indices = range(len(state.validators))
    rows = []
    for i in indices:
        if i >= len(state.validators):
            continue
        v = state.validators[i]
        balance = int(state.balances[i])
        rows.append({
            "index": str(i),
            "balance": str(balance),
            "status": _validator_status(v, balance, epoch),
            "validator": {
                "pubkey": hex_(v.pubkey),
                "withdrawal_credentials": hex_(v.withdrawal_credentials),
                "effective_balance": str(int(v.effective_balance)),
                "slashed": bool(v.slashed),
                "activation_eligibility_epoch": str(int(v.activation_eligibility_epoch)),
                "activation_epoch": str(int(v.activation_epoch)),
                "exit_epoch": str(int(v.exit_epoch)),
                "withdrawable_epoch": str(int(v.withdrawable_epoch)),
            },
        })
    return {"execution_optimistic": False, "finalized": False, "data": rows}


def get_block(ctx, params, query, body):
    node = ctx.resolve_block(params["block_id"])
    signed = node.signed_block
    state = ctx.snapshot().head_state
    version = state_phase_of(node.state, ctx.cfg).key
    message = getattr(signed, "message", None)
    if message is None or not hasattr(signed, "serialize"):
        raise ApiError(404, "anchor block body unavailable")
    return {
        "version": version,
        "execution_optimistic": False,
        "finalized": node.slot
        <= int(ctx.snapshot().finalized_checkpoint.epoch)
        * ctx.cfg.preset.SLOTS_PER_EPOCH,
        "data": {"message_root": hex_(message.hash_tree_root()),
                 "slot": str(node.slot),
                 "proposer_index": str(int(message.proposer_index)),
                 "ssz": hex_(signed.serialize())},
    }


def get_block_root(ctx, params, query, body):
    node = ctx.resolve_block(params["block_id"])
    return {"data": {"root": hex_(node.root)}}


def get_headers(ctx, params, query, body):
    snap = ctx.snapshot()
    node = ctx.controller.store.blocks[snap.head_root]
    return {
        "data": [{
            "root": hex_(node.root),
            "canonical": True,
            "header": {
                "message": {
                    "slot": str(node.slot),
                    "parent_root": hex_(node.parent_root),
                    "state_root": hex_(node.state.hash_tree_root()),
                },
            },
        }]
    }


def post_pool_attestations(ctx, params, query, body):
    if ctx.attestation_pool is None:
        raise ApiError(503, "attestation pool not wired")
    from grandine_tpu.types.combined import fork_namespace
    from grandine_tpu.types.primitives import Phase

    failures = []
    for i, att_json in enumerate(body or []):
        try:
            att = _attestation_from_json(ctx, att_json)
            ctx.attestation_pool.insert(att)
            if ctx.event_bus is not None:
                ctx.event_bus.publish("attestation", att_json)
        except Exception as e:
            failures.append({"index": i, "message": repr(e)})
    if failures:
        raise ApiError(400, json.dumps(failures))
    return {}


def _attestation_from_json(ctx, j):
    ns = _ns_of_head(ctx)
    bits_type = _field_type(ns.Attestation, "aggregation_bits")
    bits = bits_type.deserialize(_b(j["aggregation_bits"]))
    return ns.Attestation(
        aggregation_bits=bits,
        data=_json_to_attestation_data(ns, j["data"]),
        signature=_b(j["signature"], 96),
    )


def get_pool_voluntary_exits(ctx, params, query, body):
    if ctx.operation_pool is None:
        raise ApiError(503, "operation pool not wired")
    exits = ctx.operation_pool.contents()["voluntary_exits"]
    return {
        "data": [
            {
                "message": {
                    "epoch": str(int(e.message.epoch)),
                    "validator_index": str(int(e.message.validator_index)),
                },
                "signature": hex_(e.signature),
            }
            for e in exits
        ]
    }


def get_config_spec(ctx, params, query, body):
    cfg = ctx.cfg
    p = cfg.preset
    data = {
        "PRESET_BASE": cfg.preset_base,
        "CONFIG_NAME": cfg.config_name,
        "SECONDS_PER_SLOT": str(cfg.seconds_per_slot),
        "SLOTS_PER_EPOCH": str(p.SLOTS_PER_EPOCH),
        "GENESIS_FORK_VERSION": hex_(cfg.genesis_fork_version),
        "ALTAIR_FORK_EPOCH": str(cfg.altair_fork_epoch),
        "BELLATRIX_FORK_EPOCH": str(cfg.bellatrix_fork_epoch),
        "CAPELLA_FORK_EPOCH": str(cfg.capella_fork_epoch),
        "DENEB_FORK_EPOCH": str(cfg.deneb_fork_epoch),
        "MAX_EFFECTIVE_BALANCE": str(p.MAX_EFFECTIVE_BALANCE),
        "MIN_ATTESTATION_INCLUSION_DELAY": str(p.MIN_ATTESTATION_INCLUSION_DELAY),
        "DEPOSIT_CONTRACT_ADDRESS": hex_(cfg.deposit_contract_address),
        "DEPOSIT_CHAIN_ID": str(cfg.deposit_chain_id),
    }
    return {"data": data}


def get_deposit_contract(ctx, params, query, body):
    return {
        "data": {
            "chain_id": str(ctx.cfg.deposit_chain_id),
            "address": hex_(ctx.cfg.deposit_contract_address),
        }
    }


def get_proposer_duties(ctx, params, query, body):
    """eth/v1/validator/duties/proposer/{epoch}: proposer per slot of the
    epoch. One in-epoch state suffices — the proposer seed mixes the slot
    into the epoch's RANDAO-derived seed (misc.proposer_seed), so all
    SLOTS_PER_EPOCH proposers come from per-slot seeds over one shuffle."""
    from grandine_tpu.consensus import misc

    p = ctx.cfg.preset
    epoch = _parse_int(params["epoch"], "epoch")
    snap = ctx.snapshot()
    state = snap.head_state
    cur = accessors.get_current_epoch(state, p)
    if epoch > cur + 1:
        raise ApiError(400, f"epoch {epoch} beyond the lookahead window")
    start = misc.compute_start_slot_at_epoch(epoch, p)
    if epoch > cur:  # advance into the epoch (StateCache memoizes)
        state = ctx.controller.state_at_slot(start)
    cols = accessors.registry_columns(state)
    active = cols.active_indices(epoch)
    duties = []
    for slot in range(start, start + p.SLOTS_PER_EPOCH):
        seed = misc.proposer_seed(state, slot, p)
        index = misc.compute_proposer_index(
            cols.effective_balance, active, seed, p
        )
        duties.append({
            "pubkey": hex_(cols.pubkeys[index]),
            "validator_index": str(index),
            "slot": str(slot),
        })
    return {"dependent_root": hex_(snap.head_root), "data": duties}


def post_attester_duties(ctx, params, query, body):
    """eth/v1/validator/duties/attester/{epoch} for the posted indices."""
    from grandine_tpu.consensus import misc

    p = ctx.cfg.preset
    epoch = _parse_int(params["epoch"], "epoch")
    snap = ctx.snapshot()
    state = snap.head_state
    cur = accessors.get_current_epoch(state, p)
    if epoch > cur + 1:
        raise ApiError(400, f"epoch {epoch} beyond the lookahead window")
    want = {_parse_int(i, "validator index") for i in (body or [])}
    if not want:
        # Beacon API contract: duties only for the POSTED indices
        return {"dependent_root": hex_(snap.head_root), "data": []}
    cols = accessors.registry_columns(state)
    duties = []
    start = misc.compute_start_slot_at_epoch(epoch, p)
    count = accessors.get_committee_count_per_slot(state, epoch, p)
    for slot in range(start, start + p.SLOTS_PER_EPOCH):
        for index in range(count):
            committee = accessors.get_beacon_committee(state, slot, index, p)
            for pos, vi in enumerate(committee):
                vi = int(vi)
                if vi not in want:
                    continue
                duties.append({
                    "pubkey": hex_(cols.pubkeys[vi]),
                    "validator_index": str(vi),
                    "committee_index": str(index),
                    "committee_length": str(len(committee)),
                    "committees_at_slot": str(count),
                    "validator_committee_index": str(pos),
                    "slot": str(slot),
                })
    return {"dependent_root": hex_(snap.head_root), "data": duties}


def post_validator_liveness(ctx, params, query, body):
    if ctx.liveness is None:
        raise ApiError(503, "liveness tracker not wired")
    epoch = int(params["epoch"])
    indices = [int(i) for i in (body or [])]
    return {"data": ctx.liveness.liveness(epoch, indices)}


def get_metrics(ctx, params, query, body):
    if ctx.metrics is None:
        raise ApiError(503, "metrics not wired")
    ctx.metrics.collect_system_stats(ctx.data_dir)
    return ctx.metrics.expose()  # text payload


def get_debug_trace(ctx, params, query, body):
    """Chrome trace-event dump of the tracer's span ring buffer — load
    the payload in chrome://tracing or Perfetto. `?clear=true` drains
    the buffer after the dump so successive captures don't overlap."""
    if ctx.tracer is None:
        raise ApiError(503, "tracer not wired")
    payload = ctx.tracer.chrome_trace()
    if str(query.get("clear", "")).lower() in ("1", "true", "yes"):
        ctx.tracer.clear()
    return payload


def get_debug_flight(ctx, params, query, body):
    """Verify-plane flight-recorder dump: the newest batch/canary/breaker
    records plus the aggregate summary (SLO misses by lane+cause, bucket
    fill, duty cycle, top failing origins). `?lane=` filters to one lane,
    `?kind=` to one record kind, `?n=` bounds the record count."""
    if ctx.flight is None:
        raise ApiError(503, "flight recorder not wired")
    lane = query.get("lane") or None
    kind = query.get("kind") or None
    try:
        n = int(query.get("n", 256))
    except ValueError:
        raise ApiError(400, "n must be an integer") from None
    if n < 0:
        raise ApiError(400, "n must be non-negative")
    records = ctx.flight.snapshot(lane=lane, n=n, kind=kind)
    return {
        "data": {
            "records": [r.as_dict() for r in records],
            "summary": ctx.flight.summary(),
            "slo": ctx.flight.slo_misses(),
            "origins": ctx.flight.origins.snapshot(),
        }
    }


def get_debug_profile(ctx, params, query, body):
    """Kernel-profiler view + capture control. Default GET serves the
    always-on estimator (per-kernel device seconds, dispatch counts,
    the finished-session ring, HBM family bytes, coverage vs the flight
    recorder); `?kernel=` / `?scheme=` filter the estimator rows,
    `?n=` bounds the session list. `?action=start[&trace_dir=...]`
    opens a capture session (409 when one is active), `?action=stop`
    closes it and returns the finished session record."""
    if ctx.profiler is None:
        raise ApiError(503, "profiler not wired")
    action = str(query.get("action", "")).lower()
    if action == "start":
        trace_dir = query.get("trace_dir") or None
        try:
            sess = ctx.profiler.start(trace_dir=trace_dir)
        except RuntimeError as exc:
            raise ApiError(409, str(exc)) from None
        return {"data": {"session": sess}}
    if action == "stop":
        try:
            sess = ctx.profiler.stop()
        except RuntimeError as exc:
            raise ApiError(409, str(exc)) from None
        return {"data": {"session": sess}}
    if action:
        raise ApiError(400, "action must be start or stop")
    kernel = query.get("kernel") or None
    scheme = query.get("scheme") or None
    try:
        n = int(query.get("n", 32))
    except ValueError:
        raise ApiError(400, "n must be an integer") from None
    if n < 0:
        raise ApiError(400, "n must be non-negative")
    return {
        "data": ctx.profiler.summary(
            kernel=kernel, scheme=scheme, n_sessions=n, flight=ctx.flight
        )
    }


# ------------------------------------------- JSON <-> container codecs
# (the reference serializes via serde; these hand-rolled converters cover
# the Beacon API pool/validator payloads)


def _ns_of_head(ctx):
    from grandine_tpu.types.combined import fork_namespace

    snap = ctx.snapshot()
    phase = state_phase_of(snap.head_state, ctx.cfg)
    return fork_namespace(ctx.cfg, phase)


def _b(hexstr: str, length: "Optional[int]" = None) -> bytes:
    raw = bytes.fromhex(hexstr.removeprefix("0x"))
    if length is not None and len(raw) != length:
        raise ApiError(400, f"expected {length} bytes, got {len(raw)}")
    return raw


def _json_to_attestation_data(ns, d):
    return ns.AttestationData(
        slot=int(d["slot"]),
        index=int(d["index"]),
        beacon_block_root=_b(d["beacon_block_root"], 32),
        source=ns.Checkpoint(
            epoch=int(d["source"]["epoch"]), root=_b(d["source"]["root"], 32)
        ),
        target=ns.Checkpoint(
            epoch=int(d["target"]["epoch"]), root=_b(d["target"]["root"], 32)
        ),
    )


def _attestation_data_to_json(d) -> dict:
    return {
        "slot": str(int(d.slot)),
        "index": str(int(d.index)),
        "beacon_block_root": hex_(d.beacon_block_root),
        "source": {
            "epoch": str(int(d.source.epoch)),
            "root": hex_(d.source.root),
        },
        "target": {
            "epoch": str(int(d.target.epoch)),
            "root": hex_(d.target.root),
        },
    }


def _field_type(container, name: str):
    cls = container if isinstance(container, type) else type(container)
    for n, t in cls.FIELDS:
        if n == name:
            return t
    raise KeyError(name)


def _attestation_to_json(att) -> dict:
    bits_type = _field_type(att, "aggregation_bits")
    return {
        "aggregation_bits": hex_(bits_type.serialize(att.aggregation_bits)),
        "data": _attestation_data_to_json(att.data),
        "signature": hex_(att.signature),
    }


def _json_to_indexed_attestation(ns, j):
    return ns.IndexedAttestation(
        attesting_indices=[int(i) for i in j["attesting_indices"]],
        data=_json_to_attestation_data(ns, j["data"]),
        signature=_b(j["signature"], 96),
    )


def _indexed_attestation_to_json(a) -> dict:
    return {
        "attesting_indices": [str(int(i)) for i in a.attesting_indices],
        "data": _attestation_data_to_json(a.data),
        "signature": hex_(a.signature),
    }


def _json_to_signed_header(ns, j):
    m = j["message"]
    return ns.SignedBeaconBlockHeader(
        message=ns.BeaconBlockHeader(
            slot=int(m["slot"]),
            proposer_index=int(m["proposer_index"]),
            parent_root=_b(m["parent_root"], 32),
            state_root=_b(m["state_root"], 32),
            body_root=_b(m["body_root"], 32),
        ),
        signature=_b(j["signature"], 96),
    )


def _signed_header_to_json(h) -> dict:
    return {
        "message": {
            "slot": str(int(h.message.slot)),
            "proposer_index": str(int(h.message.proposer_index)),
            "parent_root": hex_(h.message.parent_root),
            "state_root": hex_(h.message.state_root),
            "body_root": hex_(h.message.body_root),
        },
        "signature": hex_(h.signature),
    }


# -------------------------------------------------- pool breadth handlers
# reference: http_api/src/routing.rs:389-410 (pool GET/POST per op type)


def _require_op_pool(ctx):
    if ctx.operation_pool is None:
        raise ApiError(503, "operation pool not wired")
    return ctx.operation_pool


def get_pool_attestations(ctx, params, query, body):
    if ctx.attestation_pool is None:
        raise ApiError(503, "attestation pool not wired")
    atts = ctx.attestation_pool.all_attestations()
    slot = query.get("slot")
    if slot is not None:
        atts = [a for a in atts if int(a.data.slot) == int(slot)]
    index = query.get("committee_index")
    if index is not None:
        atts = [a for a in atts if int(a.data.index) == int(index)]
    return {"data": [_attestation_to_json(a) for a in atts]}


def post_pool_voluntary_exits(ctx, params, query, body):
    pool = _require_op_pool(ctx)
    ns = _ns_of_head(ctx)
    j = body or {}
    try:
        exit_ = ns.SignedVoluntaryExit(
            message=ns.VoluntaryExit(
                epoch=int(j["message"]["epoch"]),
                validator_index=int(j["message"]["validator_index"]),
            ),
            signature=_b(j["signature"], 96),
        )
    except (KeyError, ValueError, TypeError) as e:
        raise ApiError(400, f"malformed voluntary exit: {e!r}") from None
    pool.insert_voluntary_exit(exit_)
    if ctx.event_bus is not None:
        ctx.event_bus.publish("voluntary_exit", j)
    return {}


def get_pool_proposer_slashings(ctx, params, query, body):
    ops = _require_op_pool(ctx).contents()["proposer_slashings"]
    return {
        "data": [
            {
                "signed_header_1": _signed_header_to_json(s.signed_header_1),
                "signed_header_2": _signed_header_to_json(s.signed_header_2),
            }
            for s in ops
        ]
    }


def post_pool_proposer_slashings(ctx, params, query, body):
    pool = _require_op_pool(ctx)
    ns = _ns_of_head(ctx)
    j = body or {}
    try:
        slashing = ns.ProposerSlashing(
            signed_header_1=_json_to_signed_header(ns, j["signed_header_1"]),
            signed_header_2=_json_to_signed_header(ns, j["signed_header_2"]),
        )
    except (KeyError, ValueError, TypeError) as e:
        raise ApiError(400, f"malformed proposer slashing: {e!r}") from None
    pool.insert_proposer_slashing(slashing)
    if ctx.event_bus is not None:
        ctx.event_bus.publish("proposer_slashing", j)
    return {}


def get_pool_attester_slashings(ctx, params, query, body):
    ops = _require_op_pool(ctx).contents()["attester_slashings"]
    return {
        "data": [
            {
                "attestation_1": _indexed_attestation_to_json(s.attestation_1),
                "attestation_2": _indexed_attestation_to_json(s.attestation_2),
            }
            for s in ops
        ]
    }


def post_pool_attester_slashings(ctx, params, query, body):
    pool = _require_op_pool(ctx)
    ns = _ns_of_head(ctx)
    j = body or {}
    try:
        slashing = ns.AttesterSlashing(
            attestation_1=_json_to_indexed_attestation(ns, j["attestation_1"]),
            attestation_2=_json_to_indexed_attestation(ns, j["attestation_2"]),
        )
    except (KeyError, ValueError, TypeError) as e:
        raise ApiError(400, f"malformed attester slashing: {e!r}") from None
    pool.insert_attester_slashing(slashing)
    if ctx.event_bus is not None:
        ctx.event_bus.publish("attester_slashing", j)
    return {}


def get_pool_bls_changes(ctx, params, query, body):
    ops = _require_op_pool(ctx).contents()["bls_to_execution_changes"]
    return {
        "data": [
            {
                "message": {
                    "validator_index": str(int(c.message.validator_index)),
                    "from_bls_pubkey": hex_(c.message.from_bls_pubkey),
                    "to_execution_address": hex_(
                        c.message.to_execution_address
                    ),
                },
                "signature": hex_(c.signature),
            }
            for c in ops
        ]
    }


def post_pool_bls_changes(ctx, params, query, body):
    pool = _require_op_pool(ctx)
    ns = _ns_of_head(ctx)
    failures = []
    for i, j in enumerate(body or []):
        try:
            change = ns.SignedBLSToExecutionChange(
                message=ns.BLSToExecutionChange(
                    validator_index=int(j["message"]["validator_index"]),
                    from_bls_pubkey=_b(j["message"]["from_bls_pubkey"], 48),
                    to_execution_address=_b(
                        j["message"]["to_execution_address"], 20
                    ),
                ),
                signature=_b(j["signature"], 96),
            )
            pool.insert_bls_to_execution_change(change)
            if ctx.event_bus is not None:
                ctx.event_bus.publish("bls_to_execution_change", j)
        except Exception as e:
            failures.append({"index": i, "message": repr(e)})
    if failures:
        raise ApiError(400, json.dumps(failures))
    return {}


def post_pool_sync_committees(ctx, params, query, body):
    """POST /eth/v1/beacon/pool/sync_committees: SyncCommitteeMessages
    placed at the validator's position(s) in the current committee."""
    if ctx.sync_pool is None:
        raise ApiError(503, "sync committee pool not wired")
    snap = ctx.snapshot()
    state = snap.head_state
    if not hasattr(state, "current_sync_committee"):
        raise ApiError(400, "pre-Altair state has no sync committees")
    cols = accessors.registry_columns(state)
    committee_pks = [bytes(pk) for pk in state.current_sync_committee.pubkeys]
    failures = []
    for i, j in enumerate(body or []):
        try:
            vi = int(j["validator_index"])
            pk = bytes(cols.pubkeys[vi])
            positions = [
                pos for pos, cpk in enumerate(committee_pks) if cpk == pk
            ]
            if not positions:
                raise ValueError(
                    f"validator {vi} not in the current sync committee"
                )
            for pos in positions:
                ctx.sync_pool.insert_message(
                    int(j["slot"]),
                    _b(j["beacon_block_root"], 32),
                    pos,
                    _b(j["signature"], 96),
                )
        except Exception as e:
            failures.append({"index": i, "message": repr(e)})
    if failures:
        raise ApiError(400, json.dumps(failures))
    return {}


# -------------------------------------------------- state breadth handlers
# reference: http_api/src/routing.rs:341-369


def get_state_committees(ctx, params, query, body):
    from grandine_tpu.consensus import misc

    p = ctx.cfg.preset
    state = ctx.resolve_state(params["state_id"])
    epoch = (
        int(query["epoch"])
        if "epoch" in query
        else accessors.get_current_epoch(state, p)
    )
    want_slot = int(query["slot"]) if "slot" in query else None
    want_index = int(query["index"]) if "index" in query else None
    start = misc.compute_start_slot_at_epoch(epoch, p)
    try:
        count = accessors.get_committee_count_per_slot(state, epoch, p)
    except Exception:
        raise ApiError(400, f"epoch {epoch} out of committee range") from None
    rows = []
    for slot in range(start, start + p.SLOTS_PER_EPOCH):
        if want_slot is not None and slot != want_slot:
            continue
        for index in range(count):
            if want_index is not None and index != want_index:
                continue
            committee = accessors.get_beacon_committee(state, slot, index, p)
            rows.append({
                "index": str(index),
                "slot": str(slot),
                "validators": [str(int(v)) for v in committee],
            })
    return {"execution_optimistic": False, "finalized": False, "data": rows}


def _sync_committee_for_epoch(state, epoch: int, p):
    """Current or next sync committee covering `epoch`, or a 400 —
    shared by the sync_committees state route and sync duties."""
    if not hasattr(state, "current_sync_committee"):
        raise ApiError(400, "pre-Altair state has no sync committees")
    cur_epoch = accessors.get_current_epoch(state, p)
    period = p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
    if epoch // period == cur_epoch // period:
        return state.current_sync_committee
    if epoch // period == cur_epoch // period + 1:
        return state.next_sync_committee
    raise ApiError(400, f"epoch {epoch} outside known sync periods")


def get_state_sync_committees(ctx, params, query, body):
    state = ctx.resolve_state(params["state_id"])
    p = ctx.cfg.preset
    epoch = (
        int(query["epoch"])
        if "epoch" in query
        else accessors.get_current_epoch(state, p)
    )
    committee = _sync_committee_for_epoch(state, epoch, p)
    cols = accessors.registry_columns(state)
    by_pk = {bytes(cols.pubkeys[i]): i for i in range(len(cols))}
    indices = []
    for pk in committee.pubkeys:
        vi = by_pk.get(bytes(pk))
        if vi is None:
            raise ApiError(500, "sync committee pubkey not in registry")
        indices.append(vi)
    from grandine_tpu.p2p.subnets import SYNC_COMMITTEE_SUBNET_COUNT

    agg_size = p.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
    aggregates = [
        [str(v) for v in indices[i : i + agg_size]]
        for i in range(0, len(indices), agg_size)
    ]
    return {
        "execution_optimistic": False,
        "finalized": False,
        "data": {
            "validators": [str(v) for v in indices],
            "validator_aggregates": aggregates,
        },
    }


def get_state_validator_balances(ctx, params, query, body):
    state = ctx.resolve_state(params["state_id"])
    ids = query.get("id")
    if ids:
        try:
            indices = [int(i) for i in ids.split(",")]
        except ValueError:
            raise ApiError(400, f"invalid id list {ids!r}") from None
    else:
        indices = range(len(state.balances))
    return {
        "execution_optimistic": False,
        "finalized": False,
        "data": [
            {"index": str(i), "balance": str(int(state.balances[i]))}
            for i in indices
            if 0 <= i < len(state.balances)
        ],
    }


def get_state_validator(ctx, params, query, body):
    state = ctx.resolve_state(params["state_id"])
    p = ctx.cfg.preset
    epoch = accessors.get_current_epoch(state, p)
    vid = params["validator_id"]
    if vid.startswith("0x"):
        pk = _b(vid, 48)
        cols = accessors.registry_columns(state)
        matches = [
            i for i in range(len(cols)) if bytes(cols.pubkeys[i]) == pk
        ]
        if not matches:
            raise ApiError(404, "validator not found")
        index = matches[0]
    else:
        index = _parse_int(vid, "validator id")
        if not 0 <= index < len(state.validators):
            raise ApiError(404, "validator not found")
    v = state.validators[index]
    balance = int(state.balances[index])
    return {
        "execution_optimistic": False,
        "finalized": False,
        "data": {
            "index": str(index),
            "balance": str(balance),
            "status": _validator_status(v, balance, epoch),
            "validator": {
                "pubkey": hex_(v.pubkey),
                "withdrawal_credentials": hex_(v.withdrawal_credentials),
                "effective_balance": str(int(v.effective_balance)),
                "slashed": bool(v.slashed),
                "activation_eligibility_epoch": str(
                    int(v.activation_eligibility_epoch)
                ),
                "activation_epoch": str(int(v.activation_epoch)),
                "exit_epoch": str(int(v.exit_epoch)),
                "withdrawable_epoch": str(int(v.withdrawable_epoch)),
            },
        },
    }


def get_header_by_id(ctx, params, query, body):
    node = ctx.resolve_block(params["block_id"])
    snap = ctx.snapshot()
    return {
        "execution_optimistic": False,
        "finalized": False,
        "data": {
            "root": hex_(node.root),
            "canonical": node.root == snap.head_root
            or _is_canonical(ctx, node),
            "header": {
                "message": {
                    "slot": str(node.slot),
                    "parent_root": hex_(node.parent_root),
                    "state_root": hex_(node.state.hash_tree_root()),
                },
            },
        },
    }


def _is_canonical(ctx, node) -> bool:
    store = ctx.controller.store
    cur = store.blocks.get(ctx.snapshot().head_root)
    while cur is not None and cur.slot > node.slot:
        cur = store.blocks.get(cur.parent_root)
    return cur is not None and cur.root == node.root


def get_block_attestations(ctx, params, query, body):
    node = ctx.resolve_block(params["block_id"])
    signed = node.signed_block
    message = getattr(signed, "message", None)
    if message is None:
        raise ApiError(404, "anchor block body unavailable")
    return {
        "execution_optimistic": False,
        "finalized": False,
        "data": [
            _attestation_to_json(a) for a in message.body.attestations
        ],
    }


# --------------------------------------------- block production / publish
# reference: http_api block production v2/v3 + publish (routing.rs:221-287)


def produce_block_v3(ctx, params, query, body):
    from grandine_tpu.validator.duties import produce_block_unsigned

    slot = _parse_int(params["slot"], "slot")
    reveal_hex = query.get("randao_reveal")
    if not reveal_hex:
        raise ApiError(400, "randao_reveal query parameter is required")
    reveal = _b(reveal_hex, 96)
    graffiti = (
        _b(query["graffiti"], 32) if "graffiti" in query else b"\x00" * 32
    )
    snap = ctx.snapshot()
    if slot <= int(snap.head_state.slot):
        raise ApiError(400, f"slot {slot} is not beyond the head")
    state = ctx.controller.state_at_slot(slot, snap)
    attestations = (
        ctx.attestation_pool.pack_attestations(state, ctx.cfg, slot=slot)
        if ctx.attestation_pool is not None
        else []
    )
    ops = (
        ctx.operation_pool.pack(state)
        if ctx.operation_pool is not None
        else {}
    )
    try:
        block, _pre, post = produce_block_unsigned(
            state,
            slot,
            ctx.cfg,
            reveal,
            graffiti=graffiti,
            attestations=attestations,
            full_sync_participation=False,
            voluntary_exits=ops.get("voluntary_exits", ()),
            proposer_slashings=ops.get("proposer_slashings", ()),
            attester_slashings=ops.get("attester_slashings", ()),
            bls_to_execution_changes=ops.get("bls_to_execution_changes", ()),
        )
    except Exception as e:
        raise ApiError(500, f"block production failed: {e!r}")
    version = state_phase_of(post, ctx.cfg).key
    return {
        "version": version,
        "execution_payload_blinded": False,
        "execution_payload_value": "0",
        "consensus_block_value": "0",
        "data": {
            "slot": str(slot),
            "proposer_index": str(int(block.proposer_index)),
            "message_root": hex_(block.hash_tree_root()),
            "ssz": hex_(block.serialize()),
        },
    }


def publish_block(ctx, params, query, body):
    """POST /eth/v{1,2}/beacon/blocks: signed block as {"ssz": "0x…"}
    (the SSZ octet body of the reference, carried in JSON)."""
    from grandine_tpu.types.combined import decode_signed_block

    if not isinstance(body, dict) or "ssz" not in body:
        raise ApiError(400, 'expected {"ssz": "0x…"} body')
    try:
        signed = decode_signed_block(_b(body["ssz"]), ctx.cfg)
    except Exception as e:
        raise ApiError(400, f"malformed block: {e!r}") from None
    ctx.controller.on_gossip_block(signed)
    if ctx.network is not None:
        try:
            ctx.network.publish_block(signed)
        except Exception:
            pass  # local import already queued; gossip is best-effort
    return {}


# ------------------------------------------------- validator breadth
# reference: http_api validator routes (aggregates, sync duties,
# preparation/registration)


def post_aggregate_and_proofs(ctx, params, query, body):
    if ctx.attestation_pool is None:
        raise ApiError(503, "attestation pool not wired")
    ns = _ns_of_head(ctx)
    failures = []
    for i, j in enumerate(body or []):
        try:
            att = _attestation_from_json(ctx, j["message"]["aggregate"])
            ctx.attestation_pool.insert(att)
            if ctx.network is not None:
                # rebroadcast so peers see the aggregate (network.rs
                # publishes API-submitted aggregates to gossip)
                signed = ns.SignedAggregateAndProof(
                    message=ns.AggregateAndProof(
                        aggregator_index=int(j["message"]["aggregator_index"]),
                        aggregate=att,
                        selection_proof=_b(
                            j["message"]["selection_proof"], 96
                        ),
                    ),
                    signature=_b(j["signature"], 96),
                )
                ctx.network.publish_aggregate(signed)
        except Exception as e:
            failures.append({"index": i, "message": repr(e)})
    if failures:
        raise ApiError(400, json.dumps(failures))
    return {}


def get_aggregate_attestation(ctx, params, query, body):
    if ctx.attestation_pool is None:
        raise ApiError(503, "attestation pool not wired")
    slot = _parse_int(query.get("slot"), "slot")
    root = _b(query.get("attestation_data_root", ""), 32)
    att = ctx.attestation_pool.best_by_data_root(slot, root)
    if att is None:
        raise ApiError(404, "no matching aggregate")
    return {"data": _attestation_to_json(att)}


def post_sync_duties(ctx, params, query, body):
    """POST /eth/v1/validator/duties/sync/{epoch} for the posted indices."""
    p = ctx.cfg.preset
    epoch = _parse_int(params["epoch"], "epoch")
    snap = ctx.snapshot()
    state = snap.head_state
    if not hasattr(state, "current_sync_committee"):
        return {"data": []}
    committee = _sync_committee_for_epoch(state, epoch, p)
    want = {_parse_int(i, "validator index") for i in (body or [])}
    cols = accessors.registry_columns(state)
    duties = []
    for vi in sorted(want):
        if not 0 <= vi < len(cols):
            continue
        pk = bytes(cols.pubkeys[vi])
        positions = [
            pos
            for pos, cpk in enumerate(committee.pubkeys)
            if bytes(cpk) == pk
        ]
        if positions:
            duties.append({
                "pubkey": hex_(pk),
                "validator_index": str(vi),
                "validator_sync_committee_indices": [
                    str(p_) for p_ in positions
                ],
            })
    return {"data": duties}


def post_prepare_beacon_proposer(ctx, params, query, body):
    for j in body or []:
        try:
            index = int(j["validator_index"])
            ctx.prepared_proposers[index] = j["fee_recipient"]
        except (KeyError, ValueError, TypeError) as e:
            raise ApiError(400, f"malformed preparation: {e!r}") from None
    return {}


def post_register_validator(ctx, params, query, body):
    for j in body or []:
        try:
            pk = j["message"]["pubkey"]
            ctx.validator_registrations[pk] = j
        except (KeyError, TypeError) as e:
            raise ApiError(400, f"malformed registration: {e!r}") from None
    return {}


def post_beacon_committee_subscriptions(ctx, params, query, body):
    if ctx.subnet_service is None:
        raise ApiError(503, "subnet service not wired")
    for j in body or []:
        try:
            ctx.subnet_service.subscribe_attestation(
                validator_index=int(j["validator_index"]),
                committee_index=int(j["committee_index"]),
                committees_at_slot=int(j["committees_at_slot"]),
                slot=int(j["slot"]),
                is_aggregator=bool(j.get("is_aggregator", False)),
            )
        except (KeyError, ValueError, TypeError) as e:
            raise ApiError(400, f"malformed subscription: {e!r}") from None
    return {}


def post_sync_committee_subscriptions(ctx, params, query, body):
    if ctx.subnet_service is None:
        raise ApiError(503, "subnet service not wired")
    for j in body or []:
        try:
            ctx.subnet_service.subscribe_sync_committee(
                validator_index=int(j["validator_index"]),
                sync_committee_indices=[
                    int(i) for i in j["sync_committee_indices"]
                ],
                until_epoch=int(j["until_epoch"]),
            )
        except (KeyError, ValueError, TypeError) as e:
            raise ApiError(400, f"malformed subscription: {e!r}") from None
    return {}


# ------------------------------------------------------- node breadth


def get_node_identity(ctx, params, query, body):
    net = ctx.network
    transport = getattr(net, "transport", net) if net is not None else None
    return {
        "data": {
            "peer_id": getattr(transport, "peer_id", ""),
            "enr": getattr(transport, "enr", ""),
            "p2p_addresses": list(getattr(transport, "addresses", ()) or ()),
            "discovery_addresses": [],
            "metadata": {"seq_number": "0", "attnets": "0x" + "00" * 8},
        }
    }


def get_node_peers(ctx, params, query, body):
    peers = []
    for p in _net_peers(ctx):
        if not isinstance(p, dict):  # Transport.peers() returns ids
            p = {"peer_id": p}
        peers.append({
            "peer_id": str(p.get("peer_id", "")),
            "last_seen_p2p_address": str(p.get("address", "")),
            "state": p.get("state", "connected"),
            "direction": p.get("direction", "outbound"),
        })
    return {"data": peers, "meta": {"count": len(peers)}}


def _net_peers(ctx) -> list:
    net = ctx.network
    if net is None:
        return []
    # a Network wraps its Transport; either may be handed in
    transport = getattr(net, "transport", net)
    try:
        return list(transport.peers())
    except Exception:
        return []


def get_node_peer_count(ctx, params, query, body):
    connected = len(_net_peers(ctx))
    return {
        "data": {
            "disconnected": "0",
            "connecting": "0",
            "connected": str(connected),
            "disconnecting": "0",
        }
    }


# ----------------------------------------------- keymanager API handlers
# reference: the keymanager crate's routes served by http_api
# (keymanager-API spec: keystores / remotekeys / per-validator
# feerecipient, gas_limit, graffiti)


def _require_km(ctx):
    if ctx.keymanager is None:
        raise ApiError(503, "keymanager not wired")
    return ctx.keymanager


def _pubkey_param(params) -> bytes:
    raw = params["pubkey"]
    try:
        pk = bytes.fromhex(raw.removeprefix("0x"))
    except ValueError:
        raise ApiError(400, f"invalid pubkey {raw!r}") from None
    if len(pk) != 48:
        raise ApiError(400, "pubkey must be 48 bytes")
    return pk


def get_keystores(ctx, params, query, body):
    return {"data": _require_km(ctx).list_keystores()}


def post_keystores(ctx, params, query, body):
    km = _require_km(ctx)
    body = body or {}
    keystores = [
        json.loads(k) if isinstance(k, str) else k
        for k in body.get("keystores", [])
    ]
    passwords = body.get("passwords", [])
    if len(keystores) != len(passwords):
        raise ApiError(400, "keystores/passwords length mismatch")
    interchange = body.get("slashing_protection")
    if interchange and km.slashing_protection is not None:
        km.slashing_protection.import_interchange(
            json.loads(interchange)
            if isinstance(interchange, str)
            else interchange
        )
    return {"data": km.import_keystores(keystores, passwords)}


def delete_keystores(ctx, params, query, body):
    km = _require_km(ctx)
    try:
        pubkeys = [_b(p, 48) for p in (body or {}).get("pubkeys", [])]
    except ValueError:
        raise ApiError(400, "malformed pubkey in delete request") from None
    statuses = km.delete_keystores(pubkeys)
    protection = (
        json.dumps(km.slashing_protection.export_interchange())
        if km.slashing_protection is not None
        else json.dumps({"metadata": {}, "data": []})
    )
    return {"data": statuses, "slashing_protection": protection}


def get_remote_keys(ctx, params, query, body):
    return {"data": _require_km(ctx).list_remote_keys()}


def post_remote_keys(ctx, params, query, body):
    km = _require_km(ctx)
    return {"data": km.import_remote_keys((body or {}).get("remote_keys", []))}


def delete_remote_keys(ctx, params, query, body):
    km = _require_km(ctx)
    try:
        pubkeys = [_b(p, 48) for p in (body or {}).get("pubkeys", [])]
    except ValueError:
        raise ApiError(400, "malformed pubkey in delete request") from None
    return {"data": km.delete_remote_keys(pubkeys)}


def get_fee_recipient(ctx, params, query, body):
    km = _require_km(ctx)
    pk = _pubkey_param(params)
    addr = km.proposer_config(pk).get("fee_recipient")
    if addr is None:
        raise ApiError(404, "no fee recipient configured")
    return {"data": {"pubkey": hex_(pk), "ethaddress": hex_(addr)}}


def post_fee_recipient(ctx, params, query, body):
    km = _require_km(ctx)
    pk = _pubkey_param(params)
    try:
        addr = _b((body or {}).get("ethaddress", ""), 20)
    except ValueError:
        raise ApiError(400, "malformed ethaddress") from None
    km.set_fee_recipient(pk, addr)
    return {}


def delete_fee_recipient(ctx, params, query, body):
    km = _require_km(ctx)
    if not km.delete_proposer_field(_pubkey_param(params), "fee_recipient"):
        raise ApiError(404, "no fee recipient configured")
    return {}


def get_gas_limit(ctx, params, query, body):
    km = _require_km(ctx)
    pk = _pubkey_param(params)
    limit = km.proposer_config(pk).get("gas_limit")
    if limit is None:
        raise ApiError(404, "no gas limit configured")
    return {"data": {"pubkey": hex_(pk), "gas_limit": str(limit)}}


def post_gas_limit(ctx, params, query, body):
    km = _require_km(ctx)
    pk = _pubkey_param(params)
    km.set_gas_limit(pk, _parse_int((body or {}).get("gas_limit"), "gas_limit"))
    return {}


def delete_gas_limit(ctx, params, query, body):
    km = _require_km(ctx)
    if not km.delete_proposer_field(_pubkey_param(params), "gas_limit"):
        raise ApiError(404, "no gas limit configured")
    return {}


def get_graffiti(ctx, params, query, body):
    km = _require_km(ctx)
    pk = _pubkey_param(params)
    graffiti = km.proposer_config(pk).get("graffiti")
    if graffiti is None:
        raise ApiError(404, "no graffiti configured")
    return {
        "data": {
            "pubkey": hex_(pk),
            "graffiti": graffiti.decode("utf-8", "replace").rstrip("\x00"),
        }
    }


def post_graffiti(ctx, params, query, body):
    km = _require_km(ctx)
    pk = _pubkey_param(params)
    text = (body or {}).get("graffiti", "")
    raw = text.encode()[:32].ljust(32, b"\x00")
    km.set_graffiti(pk, raw)
    return {}


def delete_graffiti(ctx, params, query, body):
    km = _require_km(ctx)
    if not km.delete_proposer_field(_pubkey_param(params), "graffiti"):
        raise ApiError(404, "no graffiti configured")
    return {}


def build_router() -> Router:
    r = Router()
    r.add("GET", "/eth/v1/node/version", get_node_version)
    r.add("GET", "/eth/v1/node/health", get_node_health)
    r.add("GET", "/eth/v1/node/syncing", get_node_syncing)
    r.add("GET", "/eth/v1/debug/fork_choice", get_debug_fork_choice)
    r.add("GET", "/eth/v2/debug/beacon/heads", get_debug_heads)
    r.add("GET", "/eth/v2/debug/beacon/states/{state_id}", get_debug_state)
    r.add("GET", "/eth/v1/beacon/genesis", get_genesis)
    r.add("GET", "/eth/v1/beacon/states/{state_id}/root", get_state_root)
    r.add("GET", "/eth/v1/beacon/states/{state_id}/fork", get_state_fork)
    r.add(
        "GET",
        "/eth/v1/beacon/states/{state_id}/finality_checkpoints",
        get_finality_checkpoints,
    )
    r.add(
        "GET", "/eth/v1/beacon/states/{state_id}/validators", get_state_validators
    )
    r.add("GET", "/eth/v1/beacon/headers", get_headers)
    r.add("GET", "/eth/v2/beacon/blocks/{block_id}", get_block)
    r.add("GET", "/eth/v1/beacon/blocks/{block_id}/root", get_block_root)
    r.add("POST", "/eth/v1/beacon/pool/attestations", post_pool_attestations)
    r.add("GET", "/eth/v1/beacon/pool/voluntary_exits", get_pool_voluntary_exits)
    r.add("GET", "/eth/v1/config/spec", get_config_spec)
    r.add("GET", "/eth/v1/config/deposit_contract", get_deposit_contract)
    r.add("POST", "/eth/v1/validator/liveness/{epoch}", post_validator_liveness)
    r.add("GET", "/eth/v1/validator/duties/proposer/{epoch}", get_proposer_duties)
    r.add("POST", "/eth/v1/validator/duties/attester/{epoch}", post_attester_duties)
    r.add("GET", "/metrics", get_metrics)
    r.add("GET", "/eth/v1/debug/grandine/trace", get_debug_trace)
    r.add("GET", "/eth/v1/debug/grandine/flight", get_debug_flight)
    r.add("GET", "/eth/v1/debug/grandine/profile", get_debug_profile)
    # state breadth (routing.rs:341-369)
    r.add(
        "GET", "/eth/v1/beacon/states/{state_id}/committees",
        get_state_committees,
    )
    r.add(
        "GET", "/eth/v1/beacon/states/{state_id}/sync_committees",
        get_state_sync_committees,
    )
    r.add(
        "GET", "/eth/v1/beacon/states/{state_id}/validator_balances",
        get_state_validator_balances,
    )
    r.add(
        "GET",
        "/eth/v1/beacon/states/{state_id}/validators/{validator_id}",
        get_state_validator,
    )
    r.add("GET", "/eth/v1/beacon/headers/{block_id}", get_header_by_id)
    r.add(
        "GET", "/eth/v1/beacon/blocks/{block_id}/attestations",
        get_block_attestations,
    )
    # pool breadth (routing.rs:389-410)
    r.add("GET", "/eth/v1/beacon/pool/attestations", get_pool_attestations)
    r.add(
        "POST", "/eth/v1/beacon/pool/voluntary_exits",
        post_pool_voluntary_exits,
    )
    r.add(
        "GET", "/eth/v1/beacon/pool/proposer_slashings",
        get_pool_proposer_slashings,
    )
    r.add(
        "POST", "/eth/v1/beacon/pool/proposer_slashings",
        post_pool_proposer_slashings,
    )
    r.add(
        "GET", "/eth/v1/beacon/pool/attester_slashings",
        get_pool_attester_slashings,
    )
    r.add(
        "POST", "/eth/v1/beacon/pool/attester_slashings",
        post_pool_attester_slashings,
    )
    r.add(
        "GET", "/eth/v1/beacon/pool/bls_to_execution_changes",
        get_pool_bls_changes,
    )
    r.add(
        "POST", "/eth/v1/beacon/pool/bls_to_execution_changes",
        post_pool_bls_changes,
    )
    r.add(
        "POST", "/eth/v1/beacon/pool/sync_committees",
        post_pool_sync_committees,
    )
    # block production + publish
    r.add("GET", "/eth/v2/validator/blocks/{slot}", produce_block_v3)
    r.add("GET", "/eth/v3/validator/blocks/{slot}", produce_block_v3)
    r.add("POST", "/eth/v1/beacon/blocks", publish_block)
    r.add("POST", "/eth/v2/beacon/blocks", publish_block)
    # validator breadth
    r.add(
        "POST", "/eth/v1/validator/aggregate_and_proofs",
        post_aggregate_and_proofs,
    )
    r.add(
        "GET", "/eth/v1/validator/aggregate_attestation",
        get_aggregate_attestation,
    )
    r.add("POST", "/eth/v1/validator/duties/sync/{epoch}", post_sync_duties)
    r.add(
        "POST", "/eth/v1/validator/prepare_beacon_proposer",
        post_prepare_beacon_proposer,
    )
    r.add(
        "POST", "/eth/v1/validator/register_validator",
        post_register_validator,
    )
    r.add(
        "POST", "/eth/v1/validator/beacon_committee_subscriptions",
        post_beacon_committee_subscriptions,
    )
    r.add(
        "POST", "/eth/v1/validator/sync_committee_subscriptions",
        post_sync_committee_subscriptions,
    )
    # node breadth
    r.add("GET", "/eth/v1/node/identity", get_node_identity)
    r.add("GET", "/eth/v1/node/peers", get_node_peers)
    r.add("GET", "/eth/v1/node/peer_count", get_node_peer_count)
    # keymanager API (served on the same router; the reference runs the
    # keymanager crate's routes under http_api with token auth)
    r.add("GET", "/eth/v1/keystores", get_keystores)
    r.add("POST", "/eth/v1/keystores", post_keystores)
    r.add("DELETE", "/eth/v1/keystores", delete_keystores)
    r.add("GET", "/eth/v1/remotekeys", get_remote_keys)
    r.add("POST", "/eth/v1/remotekeys", post_remote_keys)
    r.add("DELETE", "/eth/v1/remotekeys", delete_remote_keys)
    r.add("GET", "/eth/v1/validator/{pubkey}/feerecipient", get_fee_recipient)
    r.add("POST", "/eth/v1/validator/{pubkey}/feerecipient", post_fee_recipient)
    r.add(
        "DELETE", "/eth/v1/validator/{pubkey}/feerecipient",
        delete_fee_recipient,
    )
    r.add("GET", "/eth/v1/validator/{pubkey}/gas_limit", get_gas_limit)
    r.add("POST", "/eth/v1/validator/{pubkey}/gas_limit", post_gas_limit)
    r.add("DELETE", "/eth/v1/validator/{pubkey}/gas_limit", delete_gas_limit)
    r.add("GET", "/eth/v1/validator/{pubkey}/graffiti", get_graffiti)
    r.add("POST", "/eth/v1/validator/{pubkey}/graffiti", post_graffiti)
    r.add("DELETE", "/eth/v1/validator/{pubkey}/graffiti", delete_graffiti)
    return r


__all__ = ["ApiContext", "ApiError", "Router", "build_router"]
