"""Beacon API routes + dispatch — reference: http_api/src/routing.rs
(route table :221-234, states :341-369, pools :389-410), standard.rs
(handlers), http_api_utils (StateId/BlockId parsing).

The router is dependency-free: `(method, pattern)` pairs with `{param}`
segments; handlers take (ctx, params, query, body) and return JSON-able
dicts. `ApiContext` bundles the live services the handlers read.
"""

from __future__ import annotations

import json
import re
from typing import Any, Callable, Optional

from grandine_tpu import __version__
from grandine_tpu.consensus import accessors
from grandine_tpu.types.combined import state_phase_of
from grandine_tpu.types.primitives import FAR_FUTURE_EPOCH


class ApiError(Exception):
    def __init__(self, status: int, message: str) -> None:
        super().__init__(message)
        self.status = status
        self.message = message


class ApiContext:
    """What handlers see (reference http_api context): controller snapshot
    access plus the pools/trackers/metrics wired by the runtime."""

    def __init__(
        self,
        controller,
        cfg,
        attestation_pool=None,
        operation_pool=None,
        liveness=None,
        metrics=None,
        genesis_time: "Optional[int]" = None,
    ) -> None:
        self.controller = controller
        self.cfg = cfg
        self.attestation_pool = attestation_pool
        self.operation_pool = operation_pool
        self.liveness = liveness
        self.metrics = metrics
        self.genesis_time = genesis_time

    def snapshot(self):
        return self.controller.snapshot()

    def resolve_state(self, state_id: str):
        """StateId: head | finalized | justified | genesis | <slot> | <0xroot>."""
        snap = self.snapshot()
        if state_id == "head":
            return snap.head_state
        if state_id == "finalized":
            root = bytes(snap.finalized_checkpoint.root)
            node = self.controller.store.blocks.get(root)
            if node is not None:
                return node.state
            return snap.head_state  # anchor pruned: best effort
        if state_id == "justified":
            return self.controller.store.justified_state
        if state_id == "genesis":
            state_id = "0"
        if state_id.startswith("0x"):
            root = bytes.fromhex(state_id[2:])
            for node in self.controller.store.blocks.values():
                if node.state.hash_tree_root() == root:
                    return node.state
            raise ApiError(404, f"state {state_id} not found")
        try:
            slot = int(state_id)
        except ValueError:
            raise ApiError(400, f"invalid state id {state_id!r}") from None
        for node in sorted(
            self.controller.store.blocks.values(), key=lambda n: n.slot
        ):
            if node.slot == slot:
                return node.state
        raise ApiError(404, f"no state at slot {slot}")

    def resolve_block(self, block_id: str):
        snap = self.snapshot()
        store = self.controller.store
        if block_id == "head":
            return store.blocks[snap.head_root]
        if block_id == "finalized":
            node = store.blocks.get(bytes(snap.finalized_checkpoint.root))
            if node is None:
                raise ApiError(404, "finalized block pruned")
            return node
        if block_id.startswith("0x"):
            node = store.blocks.get(bytes.fromhex(block_id[2:]))
            if node is None:
                raise ApiError(404, f"block {block_id} not found")
            return node
        try:
            slot = int(block_id)
        except ValueError:
            raise ApiError(400, f"invalid block id {block_id!r}") from None
        for node in store.blocks.values():
            if node.slot == slot:
                return node
        raise ApiError(404, f"no block at slot {slot}")


def hex_(b: bytes) -> str:
    return "0x" + bytes(b).hex()


def _parse_int(value, what: str) -> int:
    try:
        return int(value)
    except (TypeError, ValueError):
        raise ApiError(400, f"invalid {what}: {value!r}") from None


# ------------------------------------------------------------------ router


class Router:
    def __init__(self) -> None:
        self.routes: "list[tuple[str, re.Pattern, Callable]]" = []

    def add(self, method: str, pattern: str, handler: Callable) -> None:
        regex = re.compile(
            "^" + re.sub(r"\{(\w+)\}", r"(?P<\1>[^/]+)", pattern) + "$"
        )
        self.routes.append((method.upper(), regex, handler))

    def dispatch(
        self, ctx: ApiContext, method: str, path: str,
        query: "Optional[dict]" = None, body: Any = None,
    ):
        """Returns (status, payload). JSON endpoints return dicts; /metrics
        returns text."""
        for m, regex, handler in self.routes:
            if m != method.upper():
                continue
            match = regex.match(path)
            if match is None:
                continue
            try:
                payload = handler(ctx, match.groupdict(), query or {}, body)
                return 200, payload
            except ApiError as e:
                return e.status, {"code": e.status, "message": e.message}
            except Exception as e:  # handler crash -> 500, not a dead server
                return 500, {"code": 500, "message": repr(e)}
        return 404, {"code": 404, "message": f"no route for {method} {path}"}


# ---------------------------------------------------------------- handlers


def get_node_version(ctx, params, query, body):
    return {"data": {"version": f"grandine-tpu/{__version__}"}}


def get_node_health(ctx, params, query, body):
    return {}


def get_node_syncing(ctx, params, query, body):
    snap = ctx.snapshot()
    head_slot = int(snap.head_state.slot)
    return {
        "data": {
            "head_slot": str(head_slot),
            "sync_distance": str(max(0, snap.slot - head_slot)),
            "is_syncing": snap.slot - head_slot > 1,
            "is_optimistic": False,
            "el_offline": True,
        }
    }


def get_genesis(ctx, params, query, body):
    snap = ctx.snapshot()
    state = snap.head_state
    return {
        "data": {
            "genesis_time": str(int(state.genesis_time)),
            "genesis_validators_root": hex_(state.genesis_validators_root),
            "genesis_fork_version": hex_(ctx.cfg.genesis_fork_version),
        }
    }


def get_state_root(ctx, params, query, body):
    state = ctx.resolve_state(params["state_id"])
    return {"data": {"root": hex_(state.hash_tree_root())}}


def get_state_fork(ctx, params, query, body):
    state = ctx.resolve_state(params["state_id"])
    return {
        "data": {
            "previous_version": hex_(state.fork.previous_version),
            "current_version": hex_(state.fork.current_version),
            "epoch": str(int(state.fork.epoch)),
        }
    }


def get_finality_checkpoints(ctx, params, query, body):
    state = ctx.resolve_state(params["state_id"])

    def cp(c):
        return {"epoch": str(int(c.epoch)), "root": hex_(c.root)}

    return {
        "data": {
            "previous_justified": cp(state.previous_justified_checkpoint),
            "current_justified": cp(state.current_justified_checkpoint),
            "finalized": cp(state.finalized_checkpoint),
        }
    }


def _validator_status(v, balance: int, epoch: int) -> str:
    if int(v.activation_epoch) > epoch:
        return (
            "pending_queued"
            if int(v.activation_eligibility_epoch) != FAR_FUTURE_EPOCH
            else "pending_initialized"
        )
    if epoch < int(v.exit_epoch):
        return "active_slashed" if bool(v.slashed) else "active_ongoing"
    if epoch < int(v.withdrawable_epoch):
        return "exited_slashed" if bool(v.slashed) else "exited_unslashed"
    return "withdrawal_done" if balance == 0 else "withdrawal_possible"


def get_state_validators(ctx, params, query, body):
    state = ctx.resolve_state(params["state_id"])
    p = ctx.cfg.preset
    epoch = accessors.get_current_epoch(state, p)
    ids = query.get("id")
    if ids:
        try:
            indices = [int(i) for i in ids.split(",")]
        except ValueError:
            raise ApiError(400, f"invalid validator id list {ids!r}") from None
        if any(i < 0 for i in indices):
            raise ApiError(400, "validator indices must be non-negative")
    else:
        indices = range(len(state.validators))
    rows = []
    for i in indices:
        if i >= len(state.validators):
            continue
        v = state.validators[i]
        balance = int(state.balances[i])
        rows.append({
            "index": str(i),
            "balance": str(balance),
            "status": _validator_status(v, balance, epoch),
            "validator": {
                "pubkey": hex_(v.pubkey),
                "withdrawal_credentials": hex_(v.withdrawal_credentials),
                "effective_balance": str(int(v.effective_balance)),
                "slashed": bool(v.slashed),
                "activation_eligibility_epoch": str(int(v.activation_eligibility_epoch)),
                "activation_epoch": str(int(v.activation_epoch)),
                "exit_epoch": str(int(v.exit_epoch)),
                "withdrawable_epoch": str(int(v.withdrawable_epoch)),
            },
        })
    return {"execution_optimistic": False, "finalized": False, "data": rows}


def get_block(ctx, params, query, body):
    node = ctx.resolve_block(params["block_id"])
    signed = node.signed_block
    state = ctx.snapshot().head_state
    version = state_phase_of(node.state, ctx.cfg).key
    message = getattr(signed, "message", None)
    if message is None or not hasattr(signed, "serialize"):
        raise ApiError(404, "anchor block body unavailable")
    return {
        "version": version,
        "execution_optimistic": False,
        "finalized": node.slot
        <= int(ctx.snapshot().finalized_checkpoint.epoch)
        * ctx.cfg.preset.SLOTS_PER_EPOCH,
        "data": {"message_root": hex_(message.hash_tree_root()),
                 "slot": str(node.slot),
                 "proposer_index": str(int(message.proposer_index)),
                 "ssz": hex_(signed.serialize())},
    }


def get_block_root(ctx, params, query, body):
    node = ctx.resolve_block(params["block_id"])
    return {"data": {"root": hex_(node.root)}}


def get_headers(ctx, params, query, body):
    snap = ctx.snapshot()
    node = ctx.controller.store.blocks[snap.head_root]
    return {
        "data": [{
            "root": hex_(node.root),
            "canonical": True,
            "header": {
                "message": {
                    "slot": str(node.slot),
                    "parent_root": hex_(node.parent_root),
                    "state_root": hex_(node.state.hash_tree_root()),
                },
            },
        }]
    }


def post_pool_attestations(ctx, params, query, body):
    if ctx.attestation_pool is None:
        raise ApiError(503, "attestation pool not wired")
    from grandine_tpu.types.combined import fork_namespace
    from grandine_tpu.types.primitives import Phase

    failures = []
    for i, att_json in enumerate(body or []):
        try:
            att = _attestation_from_json(ctx, att_json)
            ctx.attestation_pool.insert(att)
        except Exception as e:
            failures.append({"index": i, "message": repr(e)})
    if failures:
        raise ApiError(400, json.dumps(failures))
    return {}


def _attestation_from_json(ctx, j):
    from grandine_tpu.types.combined import fork_namespace

    snap = ctx.snapshot()
    phase = state_phase_of(snap.head_state, ctx.cfg)
    ns = fork_namespace(ctx.cfg, phase)
    d = j["data"]
    bits_hex = j["aggregation_bits"]
    bitlist_bytes = bytes.fromhex(bits_hex[2:])
    typ = ns.Attestation.FIELDS[0][1]
    bits = typ.deserialize(bitlist_bytes)
    return ns.Attestation(
        aggregation_bits=bits,
        data=ns.AttestationData(
            slot=int(d["slot"]),
            index=int(d["index"]),
            beacon_block_root=bytes.fromhex(d["beacon_block_root"][2:]),
            source=ns.Checkpoint(
                epoch=int(d["source"]["epoch"]),
                root=bytes.fromhex(d["source"]["root"][2:]),
            ),
            target=ns.Checkpoint(
                epoch=int(d["target"]["epoch"]),
                root=bytes.fromhex(d["target"]["root"][2:]),
            ),
        ),
        signature=bytes.fromhex(j["signature"][2:]),
    )


def get_pool_voluntary_exits(ctx, params, query, body):
    if ctx.operation_pool is None:
        raise ApiError(503, "operation pool not wired")
    exits = ctx.operation_pool.contents()["voluntary_exits"]
    return {
        "data": [
            {
                "message": {
                    "epoch": str(int(e.message.epoch)),
                    "validator_index": str(int(e.message.validator_index)),
                },
                "signature": hex_(e.signature),
            }
            for e in exits
        ]
    }


def get_config_spec(ctx, params, query, body):
    cfg = ctx.cfg
    p = cfg.preset
    data = {
        "PRESET_BASE": cfg.preset_base,
        "CONFIG_NAME": cfg.config_name,
        "SECONDS_PER_SLOT": str(cfg.seconds_per_slot),
        "SLOTS_PER_EPOCH": str(p.SLOTS_PER_EPOCH),
        "GENESIS_FORK_VERSION": hex_(cfg.genesis_fork_version),
        "ALTAIR_FORK_EPOCH": str(cfg.altair_fork_epoch),
        "BELLATRIX_FORK_EPOCH": str(cfg.bellatrix_fork_epoch),
        "CAPELLA_FORK_EPOCH": str(cfg.capella_fork_epoch),
        "DENEB_FORK_EPOCH": str(cfg.deneb_fork_epoch),
        "MAX_EFFECTIVE_BALANCE": str(p.MAX_EFFECTIVE_BALANCE),
        "MIN_ATTESTATION_INCLUSION_DELAY": str(p.MIN_ATTESTATION_INCLUSION_DELAY),
        "DEPOSIT_CONTRACT_ADDRESS": hex_(cfg.deposit_contract_address),
        "DEPOSIT_CHAIN_ID": str(cfg.deposit_chain_id),
    }
    return {"data": data}


def get_deposit_contract(ctx, params, query, body):
    return {
        "data": {
            "chain_id": str(ctx.cfg.deposit_chain_id),
            "address": hex_(ctx.cfg.deposit_contract_address),
        }
    }


def get_proposer_duties(ctx, params, query, body):
    """eth/v1/validator/duties/proposer/{epoch}: proposer per slot of the
    epoch. One in-epoch state suffices — the proposer seed mixes the slot
    into the epoch's RANDAO-derived seed (misc.proposer_seed), so all
    SLOTS_PER_EPOCH proposers come from per-slot seeds over one shuffle."""
    from grandine_tpu.consensus import misc

    p = ctx.cfg.preset
    epoch = _parse_int(params["epoch"], "epoch")
    snap = ctx.snapshot()
    state = snap.head_state
    cur = accessors.get_current_epoch(state, p)
    if epoch > cur + 1:
        raise ApiError(400, f"epoch {epoch} beyond the lookahead window")
    start = misc.compute_start_slot_at_epoch(epoch, p)
    if epoch > cur:  # advance into the epoch (StateCache memoizes)
        state = ctx.controller.state_at_slot(start)
    cols = accessors.registry_columns(state)
    active = cols.active_indices(epoch)
    duties = []
    for slot in range(start, start + p.SLOTS_PER_EPOCH):
        seed = misc.proposer_seed(state, slot, p)
        index = misc.compute_proposer_index(
            cols.effective_balance, active, seed, p
        )
        duties.append({
            "pubkey": hex_(cols.pubkeys[index]),
            "validator_index": str(index),
            "slot": str(slot),
        })
    return {"dependent_root": hex_(snap.head_root), "data": duties}


def post_attester_duties(ctx, params, query, body):
    """eth/v1/validator/duties/attester/{epoch} for the posted indices."""
    from grandine_tpu.consensus import misc

    p = ctx.cfg.preset
    epoch = _parse_int(params["epoch"], "epoch")
    snap = ctx.snapshot()
    state = snap.head_state
    cur = accessors.get_current_epoch(state, p)
    if epoch > cur + 1:
        raise ApiError(400, f"epoch {epoch} beyond the lookahead window")
    want = {_parse_int(i, "validator index") for i in (body or [])}
    if not want:
        # Beacon API contract: duties only for the POSTED indices
        return {"dependent_root": hex_(snap.head_root), "data": []}
    cols = accessors.registry_columns(state)
    duties = []
    start = misc.compute_start_slot_at_epoch(epoch, p)
    count = accessors.get_committee_count_per_slot(state, epoch, p)
    for slot in range(start, start + p.SLOTS_PER_EPOCH):
        for index in range(count):
            committee = accessors.get_beacon_committee(state, slot, index, p)
            for pos, vi in enumerate(committee):
                vi = int(vi)
                if vi not in want:
                    continue
                duties.append({
                    "pubkey": hex_(cols.pubkeys[vi]),
                    "validator_index": str(vi),
                    "committee_index": str(index),
                    "committee_length": str(len(committee)),
                    "committees_at_slot": str(count),
                    "validator_committee_index": str(pos),
                    "slot": str(slot),
                })
    return {"dependent_root": hex_(snap.head_root), "data": duties}


def post_validator_liveness(ctx, params, query, body):
    if ctx.liveness is None:
        raise ApiError(503, "liveness tracker not wired")
    epoch = int(params["epoch"])
    indices = [int(i) for i in (body or [])]
    return {"data": ctx.liveness.liveness(epoch, indices)}


def get_metrics(ctx, params, query, body):
    if ctx.metrics is None:
        raise ApiError(503, "metrics not wired")
    return ctx.metrics.expose()  # text payload


def build_router() -> Router:
    r = Router()
    r.add("GET", "/eth/v1/node/version", get_node_version)
    r.add("GET", "/eth/v1/node/health", get_node_health)
    r.add("GET", "/eth/v1/node/syncing", get_node_syncing)
    r.add("GET", "/eth/v1/beacon/genesis", get_genesis)
    r.add("GET", "/eth/v1/beacon/states/{state_id}/root", get_state_root)
    r.add("GET", "/eth/v1/beacon/states/{state_id}/fork", get_state_fork)
    r.add(
        "GET",
        "/eth/v1/beacon/states/{state_id}/finality_checkpoints",
        get_finality_checkpoints,
    )
    r.add(
        "GET", "/eth/v1/beacon/states/{state_id}/validators", get_state_validators
    )
    r.add("GET", "/eth/v1/beacon/headers", get_headers)
    r.add("GET", "/eth/v2/beacon/blocks/{block_id}", get_block)
    r.add("GET", "/eth/v1/beacon/blocks/{block_id}/root", get_block_root)
    r.add("POST", "/eth/v1/beacon/pool/attestations", post_pool_attestations)
    r.add("GET", "/eth/v1/beacon/pool/voluntary_exits", get_pool_voluntary_exits)
    r.add("GET", "/eth/v1/config/spec", get_config_spec)
    r.add("GET", "/eth/v1/config/deposit_contract", get_deposit_contract)
    r.add("POST", "/eth/v1/validator/liveness/{epoch}", post_validator_liveness)
    r.add("GET", "/eth/v1/validator/duties/proposer/{epoch}", get_proposer_duties)
    r.add("POST", "/eth/v1/validator/duties/attester/{epoch}", post_attester_duties)
    r.add("GET", "/metrics", get_metrics)
    return r


__all__ = ["ApiContext", "ApiError", "Router", "build_router"]
