"""HTTP API — reference: `http_api` crate (Beacon API eth/v1-v3 +
keymanager + GUI routes on axum, http_api/src/routing.rs:221-234; state
routes :341-369; pool routes :389-410) and `http_api_utils` (middleware,
BlockId/StateId parsing).

`routing.py` defines handlers over an `ApiContext` (controller + pools +
services) with a dependency-free router; `server.py` serves it over the
stdlib's threading HTTP server. Tests drive handlers in-process through
the same dispatch (the reference's http_api context.rs pattern).
"""

from grandine_tpu.http_api.routing import ApiContext, ApiError, Router  # noqa: F401
from grandine_tpu.http_api.server import serve  # noqa: F401
