"""Threaded HTTP server over the router — the serving half of the
reference's http_api (axum server) using only the stdlib. Serves JSON
routes through `Router.dispatch` and the `/eth/v1/events` SSE stream
(http_api/src/events.rs) as a long-lived chunked response per client.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, urlsplit

from grandine_tpu.http_api.events import TOPICS, sse_frame
from grandine_tpu.http_api.routing import ApiContext, build_router

#: dead-client detection cadence for idle event streams (a keepalive
#: comment forces a write, surfacing BrokenPipe on closed sockets)
SSE_KEEPALIVE_SECONDS = 5.0

_KEYMANAGER_PREFIXES = ("/eth/v1/keystores", "/eth/v1/remotekeys")
_KEYMANAGER_SUFFIXES = {"feerecipient", "gas_limit", "graffiti"}


def _is_keymanager_path(path: str) -> bool:
    if path.startswith(_KEYMANAGER_PREFIXES):
        return True
    # /eth/v1/validator/{pubkey}/{feerecipient|gas_limit|graffiti} —
    # matched STRUCTURALLY (the router accepts pubkeys with or without
    # the 0x prefix, so a prefix test would be bypassable)
    parts = path.strip("/").split("/")
    return (
        len(parts) == 5
        and parts[:3] == ["eth", "v1", "validator"]
        and parts[4] in _KEYMANAGER_SUFFIXES
    )


def serve(ctx: ApiContext, host: str = "127.0.0.1", port: int = 5052):
    """Start the API server on a daemon thread; returns (server, thread).
    `server.shutdown()` stops it (event streams notice within one
    keepalive interval via the stopping flag)."""
    router = build_router()
    stopping = threading.Event()

    class Handler(BaseHTTPRequestHandler):
        def _dispatch(self, body=None):
            split = urlsplit(self.path)
            query = dict(parse_qsl(split.query))
            if not self._authorized(split.path):
                raw = json.dumps(
                    {"code": 403, "message": "keymanager token required"}
                ).encode()
                self.send_response(403)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)
                return
            status, payload = router.dispatch(
                ctx, self.command, split.path, query, body
            )
            if isinstance(payload, str):  # /metrics text exposition
                raw = payload.encode()
                ctype = "text/plain; version=0.0.4"
            else:
                raw = json.dumps(payload).encode()
                ctype = "application/json"
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)

        def _stream_events(self, split) -> None:
            query = dict(parse_qsl(split.query))
            topics = [t for t in query.get("topics", "").split(",") if t]
            try:
                sub = ctx.event_bus.subscribe(topics or TOPICS)
            except ValueError as e:
                raw = json.dumps({"code": 400, "message": str(e)}).encode()
                self.send_response(400)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)
                return
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            self.end_headers()
            try:
                idle = 0.0
                while not stopping.is_set():
                    item = sub.next(timeout=0.25)
                    if item is None:
                        idle += 0.25
                        if idle >= SSE_KEEPALIVE_SECONDS:
                            self.wfile.write(b": keepalive\n\n")
                            self.wfile.flush()
                            idle = 0.0
                        continue
                    idle = 0.0
                    self.wfile.write(sse_frame(*item))
                    self.wfile.flush()
            except (BrokenPipeError, ConnectionError, OSError):
                pass  # client went away
            finally:
                ctx.event_bus.unsubscribe(sub)

        def _authorized(self, path: str) -> bool:
            """Keymanager routes require the bearer token when one is
            configured (the reference's keymanager API runs behind token
            auth; Beacon API routes stay open)."""
            token = getattr(ctx, "keymanager_token", None)
            if not token or not _is_keymanager_path(path):
                return True
            import hmac

            header = self.headers.get("Authorization", "")
            return hmac.compare_digest(header, f"Bearer {token}")

        def do_GET(self):  # noqa: N802
            split = urlsplit(self.path)
            if split.path == "/eth/v1/events" and ctx.event_bus is not None:
                self._stream_events(split)
                return
            self._dispatch()

        def _read_body(self):
            length = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(length) if length else b""
            if not raw:
                return None, True
            try:
                return json.loads(raw), True
            except json.JSONDecodeError:
                self.send_response(400)
                self.end_headers()
                return None, False

        def do_POST(self):  # noqa: N802
            body, ok = self._read_body()
            if ok:
                self._dispatch(body)

        def do_DELETE(self):  # noqa: N802
            body, ok = self._read_body()
            if ok:
                self._dispatch(body)

        def log_message(self, *args):  # quiet
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    server.daemon_threads = True
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    inner_shutdown = server.shutdown

    def shutdown():
        stopping.set()
        inner_shutdown()

    server.shutdown = shutdown
    return server, thread


__all__ = ["serve"]
