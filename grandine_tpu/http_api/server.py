"""Threaded HTTP server over the router — the serving half of the
reference's http_api (axum server) using only the stdlib.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, urlsplit

from grandine_tpu.http_api.routing import ApiContext, build_router


def serve(ctx: ApiContext, host: str = "127.0.0.1", port: int = 5052):
    """Start the API server on a daemon thread; returns (server, thread).
    `server.shutdown()` stops it."""
    router = build_router()

    class Handler(BaseHTTPRequestHandler):
        def _dispatch(self, body=None):
            split = urlsplit(self.path)
            query = dict(parse_qsl(split.query))
            status, payload = router.dispatch(
                ctx, self.command, split.path, query, body
            )
            if isinstance(payload, str):  # /metrics text exposition
                raw = payload.encode()
                ctype = "text/plain; version=0.0.4"
            else:
                raw = json.dumps(payload).encode()
                ctype = "application/json"
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)

        def do_GET(self):  # noqa: N802
            self._dispatch()

        def do_POST(self):  # noqa: N802
            length = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(length) if length else b""
            try:
                body = json.loads(raw) if raw else None
            except json.JSONDecodeError:
                self.send_response(400)
                self.end_headers()
                return
            self._dispatch(body)

        def log_message(self, *args):  # quiet
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, thread


__all__ = ["serve"]
