"""Server-sent Beacon API event stream — reference: http_api/src/events.rs
(per-topic broadcast channels with bounded lagging receivers; topics
head/block/attestation/voluntary_exit/finalized_checkpoint/chain_reorg/…)
and the EventChannels the controller publishes into.

Design: one `EventBus` with per-subscriber bounded queues (a lagging
subscriber drops its OLDEST pending event, like a tokio broadcast channel,
so one stalled SSE client can never back-pressure the mutator thread).
`wire_controller_events` installs publication callbacks on a live
`Controller` — block/head/chain_reorg/finalized_checkpoint payloads are
built from the post-mutation snapshot on the mutator thread (cheap dict
construction only; the wire encode happens on the subscriber's thread).
"""

from __future__ import annotations

import hashlib
import json
import queue
import threading
from typing import Iterable, Optional

#: Beacon API event topics served by `/eth/v1/events?topics=…`
#: (events.rs TopicKind).
TOPICS = (
    "head",
    "block",
    "attestation",
    "voluntary_exit",
    "proposer_slashing",
    "attester_slashing",
    "bls_to_execution_change",
    "finalized_checkpoint",
    "chain_reorg",
    "contribution_and_proof",
    "blob_sidecar",
)


class Subscription:
    """One SSE client's bounded event queue."""

    def __init__(self, topics: "frozenset[str]", capacity: int) -> None:
        self.topics = topics
        self._q: "queue.Queue" = queue.Queue(maxsize=capacity)
        self.dropped = 0

    def push(self, topic: str, data: dict) -> None:
        while True:
            try:
                self._q.put_nowait((topic, data))
                return
            except queue.Full:
                # broadcast lag: shed the oldest event, keep the stream live
                try:
                    self._q.get_nowait()
                    self.dropped += 1
                except queue.Empty:
                    pass

    def next(self, timeout: "Optional[float]" = None):
        """Blocking pop; returns (topic, data) or None on timeout."""
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None


class EventBus:
    def __init__(self, capacity: int = 256) -> None:
        self._lock = threading.Lock()
        self._subs: "list[Subscription]" = []
        self.capacity = capacity

    def subscribe(self, topics: "Iterable[str]") -> Subscription:
        topics = frozenset(topics)
        unknown = topics - set(TOPICS)
        if unknown:
            raise ValueError(f"unknown event topics: {sorted(unknown)}")
        sub = Subscription(topics or frozenset(TOPICS), self.capacity)
        with self._lock:
            self._subs.append(sub)
        return sub

    def unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)

    def subscriber_count(self) -> int:
        with self._lock:
            return len(self._subs)

    def publish(self, topic: str, data: dict) -> None:
        with self._lock:
            subs = list(self._subs)
        for sub in subs:
            if topic in sub.topics:
                sub.push(topic, data)


def sse_frame(topic: str, data: dict) -> bytes:
    """One `text/event-stream` frame."""
    payload = json.dumps(data, separators=(",", ":"))
    return f"event: {topic}\ndata: {payload}\n\n".encode()


# ------------------------------------------------------- controller wiring


def _hex(b) -> str:
    return "0x" + bytes(b).hex()


def _ancestor_at_slot(store, root: bytes, slot: int):
    """Walk parents until the chain reaches `slot` (insert-only dict read:
    safe off-thread). Returns the node, or None if pruned past it."""
    node = store.blocks.get(root)
    while node is not None and node.slot > slot:
        node = store.blocks.get(node.parent_root)
    return node


def _common_ancestor(store, a: bytes, b: bytes):
    """Lowest common ancestor of two block roots by slot-levelling."""
    na, nb = store.blocks.get(a), store.blocks.get(b)
    while na is not None and nb is not None and na.root != nb.root:
        if na.slot >= nb.slot:
            na = store.blocks.get(na.parent_root)
        else:
            nb = store.blocks.get(nb.parent_root)
    return na if (nb is not None and na is not None) else None


def _duty_dependent_roots(store, head_root: bytes, slots_per_epoch: int):
    """(previous, current) duty dependent roots: the block root as of the
    last slot of epoch-2 / epoch-1 relative to the head's epoch."""
    head = store.blocks.get(head_root)
    if head is None:
        return _hex(head_root), _hex(head_root)
    epoch_start = (head.slot // slots_per_epoch) * slots_per_epoch
    cur = _ancestor_at_slot(store, head_root, max(0, epoch_start - 1))
    prev = _ancestor_at_slot(
        store, head_root, max(0, epoch_start - slots_per_epoch - 1)
    )
    cur_root = cur.root if cur is not None else head_root
    prev_root = prev.root if prev is not None else cur_root
    return _hex(prev_root), _hex(cur_root)


def wire_controller_events(controller, bus: EventBus) -> None:
    """Publish block / head / chain_reorg / finalized_checkpoint events
    from a Controller's mutator-thread callbacks (events.rs publication
    points in the reference's mutator: on_block, head change, finality)."""
    slots_per_epoch = controller.cfg.preset.SLOTS_PER_EPOCH
    last_finalized = [int(controller.snapshot().finalized_checkpoint.epoch)]

    def check_finality(snap) -> None:
        fin = int(snap.finalized_checkpoint.epoch)
        if fin <= last_finalized[0]:
            return
        last_finalized[0] = fin
        fin_root = bytes(snap.finalized_checkpoint.root)
        fin_node = controller.store.blocks.get(fin_root)
        bus.publish(
            "finalized_checkpoint",
            {
                "block": _hex(fin_root),
                "state": _hex(fin_node.state.hash_tree_root())
                if fin_node is not None
                else _hex(b"\x00" * 32),
                "epoch": str(fin),
                "execution_optimistic": bool(
                    fin_node is not None
                    and getattr(fin_node, "optimistic", False)
                ),
            },
        )

    def on_head_change(old_head_root, snap) -> None:
        store = controller.store
        head_node = store.blocks.get(snap.head_root)
        old_node = store.blocks.get(old_head_root)
        prev_dep, cur_dep = _duty_dependent_roots(
            store, snap.head_root, slots_per_epoch
        )
        epoch_transition = (
            head_node is not None
            and old_node is not None
            and head_node.slot // slots_per_epoch
            != old_node.slot // slots_per_epoch
        )
        bus.publish(
            "head",
            {
                # the HEAD BLOCK's slot, not the wall-clock store slot
                # (they differ after a missed slot)
                "slot": str(
                    head_node.slot if head_node is not None else snap.slot
                ),
                "block": _hex(snap.head_root),
                "state": _hex(snap.head_state.hash_tree_root()),
                "epoch_transition": epoch_transition,
                "previous_duty_dependent_root": prev_dep,
                "current_duty_dependent_root": cur_dep,
                "execution_optimistic": bool(
                    getattr(snap, "is_optimistic", False)
                ),
            },
        )
        # a reorg is a head change whose old head is NOT an ancestor of
        # the new head (events.rs chain_reorg)
        if old_node is not None and head_node is not None:
            lca = _common_ancestor(store, old_head_root, snap.head_root)
            if lca is not None and lca.root != old_head_root:
                bus.publish(
                    "chain_reorg",
                    {
                        "slot": str(snap.slot),
                        "depth": str(old_node.slot - lca.slot),
                        "old_head_block": _hex(old_head_root),
                        "new_head_block": _hex(snap.head_root),
                        "old_head_state": _hex(old_node.state.hash_tree_root()),
                        "new_head_state": _hex(
                            snap.head_state.hash_tree_root()
                        ),
                        "epoch": str(snap.slot // slots_per_epoch),
                        "execution_optimistic": bool(
                            getattr(snap, "is_optimistic", False)
                        ),
                    },
                )
        check_finality(snap)

    def on_block_applied(valid, old_head_root, snap) -> None:
        bus.publish(
            "block",
            {
                "slot": str(int(valid.signed_block.message.slot)),
                "block": _hex(valid.root),
                "execution_optimistic": bool(
                    getattr(valid, "optimistic", False)
                ),
            },
        )
        check_finality(snap)

    def on_blob_sidecar(block_root, sidecar) -> None:
        bus.publish(
            "blob_sidecar",
            {
                "block_root": _hex(block_root),
                "index": str(int(sidecar.index)),
                "slot": str(int(sidecar.signed_block_header.message.slot)),
                "kzg_commitment": _hex(bytes(sidecar.kzg_commitment)),
                "versioned_hash": _hex(
                    b"\x01"
                    + hashlib.sha256(bytes(sidecar.kzg_commitment)).digest()[1:]
                ),
            },
        )

    controller.on_head_change.append(on_head_change)
    controller.on_block_applied.append(on_block_applied)
    if hasattr(controller, "on_blob_sidecar"):
        controller.on_blob_sidecar.append(on_blob_sidecar)


__all__ = [
    "TOPICS",
    "EventBus",
    "Subscription",
    "sse_frame",
    "wire_controller_events",
]
