"""Eth1 deposit plane — reference: `eth1` crate (deposit/block cache +
genesis detection, eth1/src/lib.rs) and `deposit_tree` (incremental
Merkle tree the proposer proves deposits against), with the eth1 data
voting helpers the validator uses.

The JSON-RPC fetch boundary is injected (like the checkpoint-sync
fetcher); everything else — the incremental tree, proof production for
block inclusion, vote selection — is real.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

from grandine_tpu.ssz.merkle import MerkleTree
from grandine_tpu.types.primitives import DEPOSIT_CONTRACT_TREE_DEPTH


class DepositCacheError(Exception):
    """The deposit cache cannot serve what the state requires (proposers
    must skip proposing rather than build an invalid block)."""


class DepositRecord:
    __slots__ = ("index", "data", "block_number")

    def __init__(self, index: int, data, block_number: int = 0) -> None:
        self.index = index
        self.data = data  # DepositData container
        self.block_number = block_number


class Eth1Cache:
    """Deposit log cache + the incremental deposit tree
    (eth1 crate + deposit_tree crate)."""

    def __init__(self, cfg) -> None:
        self.cfg = cfg
        self.tree = MerkleTree(DEPOSIT_CONTRACT_TREE_DEPTH, track_leaves=True)
        self.deposits: "list[DepositRecord]" = []

    # ------------------------------------------------------------ ingest

    def add_deposit(self, data, block_number: int = 0) -> DepositRecord:
        """One deposit event from the contract log stream, in order."""
        record = DepositRecord(len(self.deposits), data, block_number)
        self.tree.push(data.hash_tree_root())
        self.deposits.append(record)
        return record

    def follow(self, fetch_logs: "Callable[[int], Sequence]") -> int:
        """Pull new logs via the injected fetcher (the eth1 JSON-RPC
        boundary): fetch_logs(next_index) -> iterable of DepositData."""
        added = 0
        for data in fetch_logs(len(self.deposits)):
            self.add_deposit(data)
            added += 1
        return added

    # ------------------------------------------------------------- views

    @property
    def deposit_count(self) -> int:
        return len(self.deposits)

    def deposit_root(self) -> bytes:
        """The deposit contract's root (length-mixed)."""
        return self.tree.root_with_length()

    def eth1_data(self, types_ns, block_hash: bytes = b"\x00" * 32):
        return types_ns.Eth1Data(
            deposit_root=self.deposit_root(),
            deposit_count=self.deposit_count,
            block_hash=block_hash,
        )

    # ---------------------------------------------------------- proposing

    def deposits_for_block(self, state, types_ns) -> list:
        """The deposits a proposer must include, with inclusion proofs
        against the STATE's eth1_data (spec: min(MAX_DEPOSITS, pending)).
        Proofs are built over the first `state.eth1_data.deposit_count`
        leaves — the tree snapshot the state committed to, not the cache's
        (possibly newer) tip."""
        from grandine_tpu.ssz.merkle import merkle_branch

        p = self.cfg.preset
        start = int(state.eth1_deposit_index)
        state_count = int(state.eth1_data.deposit_count)
        want = min(p.MAX_DEPOSITS, max(0, state_count - start))
        if want == 0:
            return []
        if self.deposit_count < state_count:
            # a rebuilt/lagging cache cannot produce the REQUIRED deposits
            # (truncated leaves would yield invalid proofs)
            raise DepositCacheError(
                f"deposit cache has {self.deposit_count} deposits, state "
                f"requires {state_count}"
            )
        leaves = [r.data.hash_tree_root() for r in self.deposits[:state_count]]
        out = []
        for i in range(start, start + want):
            proof = merkle_branch(
                leaves, i, DEPOSIT_CONTRACT_TREE_DEPTH
            ) + [state_count.to_bytes(32, "little")]
            out.append(
                types_ns.Deposit(proof=proof, data=self.deposits[i].data)
            )
        return out


def select_eth1_vote(state, candidates, cfg):
    """Majority vote selection from the state's current voting period
    (validator/src/eth1_storage.rs shape): pick the candidate with the
    most existing period votes; with no votes yet, vote our own view
    (the first candidate); with no candidates, re-vote the state's
    current eth1_data."""
    votes = list(state.eth1_data_votes)
    counts: dict = {}
    for v in votes:
        counts[v.hash_tree_root()] = counts.get(v.hash_tree_root(), 0) + 1
    best = None
    best_count = 0
    for cand in candidates:
        c = counts.get(cand.hash_tree_root(), 0)
        if c > best_count:
            best, best_count = cand, c
    if best is not None:
        return best
    return candidates[0] if candidates else state.eth1_data


__all__ = [
    "Eth1Cache",
    "DepositCacheError",
    "DepositRecord",
    "select_eth1_vote",
]
