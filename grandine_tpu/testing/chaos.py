"""Seeded fault injection over the async device seam.

`ChaosBackend` wraps any backend implementing the verify plane's async
seam (health.REQUIRED_SEAM_METHODS) and injects faults scheduled by a
deterministic `FaultPlan` — the same seed always produces the same
fault sequence, so a chaos soak is a reproducible test, not a flake
generator. Five fault kinds, matching the real failure modes the health
supervisor defends against:

  raise_dispatch — the seam call itself raises (XLA compile/transfer
      error at dispatch time)
  raise_settle   — dispatch succeeds, the returned settle raises
      (readback fault)
  hang           — the settle blocks until released (wedged device);
      pairs with the settle watchdog, released at teardown via
      `release_hangs()` so abandoned threads don't linger
  wrong_verdict  — dispatch and settle succeed but the verdict is
      INVERTED (silently corrupt accelerator) — the kind only canary
      probes and host bisection can catch
  slow_settle    — the settle sleeps before answering (degraded link);
      must NOT trip the breaker when within the watchdog deadline
  wrong_signature — `batch_sign` (the SIGN-side seam) returns a batch
      where one signature is valid-looking but wrong (signed over a
      different message) — the kind only the signing plane's release
      gate can catch before a caller publishes it

`KnownAnswerBackend` is the truth-table stub used underneath the chaos
wrapper by tests and `bench.py --chaos`: verdicts come from a dict
keyed by message bytes, so the fault-free expectation is known exactly.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional, Sequence

import numpy as np

from grandine_tpu.runtime.health import REQUIRED_SEAM_METHODS

#: injectable fault kinds, in plan-draw order ("wrong_signature" is
#: appended so existing seeded rate plans keep their draw sequence)
FAULT_KINDS = (
    "raise_dispatch",
    "raise_settle",
    "hang",
    "wrong_verdict",
    "slow_settle",
    "wrong_signature",
)


class ChaosFault(RuntimeError):
    """The injected failure (distinguishable from real bugs in logs)."""


class FaultPlan:
    """Deterministic fault schedule over seam calls.

    Either scripted — `script[i]` is the fault kind (or None) for the
    i-th seam call, with calls past the end of the script fault-free —
    or rate-driven: per-call, one seeded uniform draw selects a fault
    kind by cumulative `rates` (mapping kind -> probability; the
    remainder is fault-free). `injected` counts draws per kind."""

    def __init__(self, seed: int = 0,
                 rates: "Optional[dict]" = None,
                 script: "Optional[Sequence[Optional[str]]]" = None) -> None:
        self.rng = random.Random(seed)
        self.rates = dict(rates or {})
        for kind in self.rates:
            if kind not in FAULT_KINDS:
                raise ValueError(f"unknown fault kind {kind!r}")
        self.script = list(script) if script is not None else None
        self.calls = 0
        self.injected = {k: 0 for k in FAULT_KINDS}
        self._lock = threading.Lock()

    def next_fault(self) -> "Optional[str]":
        with self._lock:
            i = self.calls
            self.calls += 1
            if self.script is not None:
                kind = self.script[i] if i < len(self.script) else None
            else:
                draw = self.rng.random()
                kind = None
                edge = 0.0
                for k in FAULT_KINDS:
                    edge += self.rates.get(k, 0.0)
                    if draw < edge:
                        kind = k
                        break
            if kind is not None:
                self.injected[kind] += 1
            return kind


class ChaosBackend:
    """Async-seam wrapper injecting `plan`-scheduled faults around an
    inner backend. Everything else delegates to the inner backend via
    `__getattr__`, so the wrapper is transparent to registry/tracer
    plumbing."""

    #: seams the wrapper can inject into; the inner backend only needs
    #: to implement the ones its scheme actually dispatches (the BLS
    #: pair from REQUIRED_SEAM_METHODS, ed25519's verify_batch_async,
    #: blob_kzg's verify_blobs_async, or the sign-side batch_sign)
    KNOWN_SEAMS = REQUIRED_SEAM_METHODS + (
        "verify_batch_async",
        "verify_blobs_async",
        "batch_sign",
    )

    def __init__(self, inner, plan: FaultPlan, slow_s: float = 0.05) -> None:
        assert any(hasattr(inner, m) for m in self.KNOWN_SEAMS)
        self.inner = inner
        self.plan = plan
        self.slow_s = float(slow_s)
        self.dispatches = 0  # seam calls that reached past the breaker
        self._lock = threading.Lock()
        self._hung: "list[threading.Event]" = []

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def release_hangs(self) -> None:
        """Unblock every injected hang (teardown: lets abandoned
        watchdog threads finish instead of sleeping forever)."""
        with self._lock:
            hung, self._hung = self._hung, []
        for ev in hung:
            ev.set()

    # ------------------------------------------------------ seam wrapping

    def _wrap(self, method: str, invert, args):
        with self._lock:
            self.dispatches += 1
        kind = self.plan.next_fault()
        if kind == "raise_dispatch":
            raise ChaosFault(f"injected dispatch fault on {method}")
        inner_settle = getattr(self.inner, method)(*args)

        def settle():
            if kind == "raise_settle":
                raise ChaosFault(f"injected settle fault on {method}")
            if kind == "hang":
                ev = threading.Event()
                with self._lock:
                    self._hung.append(ev)
                ev.wait()
                raise ChaosFault(f"released injected hang on {method}")
            if kind == "slow_settle":
                time.sleep(self.slow_s)
            value = inner_settle()
            if kind == "wrong_verdict":
                return invert(value)
            return value

        return settle

    def fast_aggregate_verify_batch_async(self, messages, signatures, keys):
        return self._wrap(
            "fast_aggregate_verify_batch_async",
            lambda v: not v,
            (messages, signatures, keys),
        )

    def fast_aggregate_verify_batch_indexed_async(self, messages, signatures,
                                                  indices, registry):
        return self._wrap(
            "fast_aggregate_verify_batch_indexed_async",
            lambda v: not v,
            (messages, signatures, indices, registry),
        )

    def g2_subgroup_check_batch_async(self, points):
        return self._wrap(
            "g2_subgroup_check_batch_async",
            lambda arr: ~np.asarray(arr),
            (points,),
        )

    def rlc_partition_verify_async(self, messages, signatures, member_keys,
                                   groups):
        return self._wrap(
            "rlc_partition_verify_async",
            lambda arr: ~np.asarray(arr),
            (messages, signatures, member_keys, groups),
        )

    # ------------------------------------------- non-BLS verify seams

    def verify_batch_async(self, prep):
        """ed25519 lane seam: scalar verdict, wrong_verdict inverts it
        — the silently-corrupt-accelerator mode the ed25519 lane's
        host-twin canary and quarantine path must catch."""
        return self._wrap("verify_batch_async", lambda v: not v, (prep,))

    def verify_blobs_async(self, prep):
        """blob_kzg lane seam: scalar verdict over the whole sidecar
        batch, wrong_verdict inverts it."""
        return self._wrap("verify_blobs_async", lambda v: not v, (prep,))

    # ---------------------------------------------------- sign-side seam

    def batch_sign(self, messages, secret_keys):
        """The signing plane's device seam (blocking, unlike the verify
        seams). `wrong_signature`/`wrong_verdict` corrupt the FIRST
        signature of the batch with a structurally valid signature over
        a different message — decodes cleanly, fails the release gate.
        Dispatch/hang/slow faults behave as on the verify seams."""
        with self._lock:
            self.dispatches += 1
        kind = self.plan.next_fault()
        if kind in ("raise_dispatch", "raise_settle"):
            raise ChaosFault("injected dispatch fault on batch_sign")
        if kind == "hang":
            ev = threading.Event()
            with self._lock:
                self._hung.append(ev)
            ev.wait()
            raise ChaosFault("released injected hang on batch_sign")
        if kind == "slow_settle":
            time.sleep(self.slow_s)
        sigs = self.inner.batch_sign(messages, secret_keys)
        if kind in ("wrong_signature", "wrong_verdict") and sigs:
            sigs = list(sigs)
            sigs[0] = secret_keys[0].sign(
                b"chaos: wrong message " + bytes(messages[0])
            )
        return sigs


class KnownAnswerBackend:
    """Truth-table async seam: the batch verdict is the AND of
    `truth[message_bytes]` over the batch (missing messages are
    invalid). Subgroup checks always pass — signature geometry is not
    under test here, verdict plumbing is."""

    def __init__(self, truth: "Optional[dict]" = None) -> None:
        self.truth = dict(truth or {})
        self.batches: "list[int]" = []
        #: (items, groups) per rlc_partition dispatch — lets tests
        #: assert the localization pass count and ladder shape
        self.partitions: "list[tuple]" = []

    def g2_subgroup_check_batch_async(self, points):
        n = len(points)
        return lambda: np.ones((n,), dtype=bool)

    # ------------------------------------- ed25519 / blob_kzg seams
    # (scheme dispatch calls prepare() first, then the async seam; the
    # "prep" here is just the message bytes so verdicts stay keyed by
    # the same truth table as the BLS seam)

    def prepare(self, items):
        return "ok", [bytes(it.message) for it in items]

    def verify_batch_async(self, prep):
        self.batches.append(len(prep))
        return lambda: all(self.truth.get(m, False) for m in prep)

    def verify_blobs_async(self, prep):
        self.batches.append(len(prep))
        return lambda: all(self.truth.get(m, False) for m in prep)

    def fast_aggregate_verify_batch_async(self, messages, signatures, keys):
        self.batches.append(len(messages))
        msgs = [bytes(m) for m in messages]
        return lambda: all(self.truth.get(m, False) for m in msgs)

    def rlc_partition_verify_async(self, messages, signatures, member_keys,
                                   groups):
        """Per-group AND over the truth table with the device backend's
        padding geometry (pow-2 bucket lo=4, pad groups are clean)."""
        n = len(messages)
        self.partitions.append((n, int(groups)))
        b = 4
        while b < n:
            b <<= 1
        g = 4
        while g < groups:
            g <<= 1
        if g > b:
            g = b
        span = b // g
        flags = [self.truth.get(bytes(m), False) for m in messages]
        flags += [True] * (b - n)
        out = np.array(
            [all(flags[j * span:(j + 1) * span]) for j in range(g)],
            dtype=bool,
        )
        return lambda: out


__all__ = [
    "FAULT_KINDS",
    "ChaosBackend",
    "ChaosFault",
    "FaultPlan",
    "KnownAnswerBackend",
]
