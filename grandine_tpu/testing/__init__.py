"""Deterministic test/bench instrumentation for the verify plane."""
