"""Deterministic cross-thread schedule fuzzer for the verify plane.

The thread-affinity lint rule (tools/lint/rules/thread_affinity.py)
proves lock coverage statically, but every `# lint: atomic=<attr>:`
annotation is a claim the static analysis cannot check — "this bare
access is safe because of a happens-before edge the lock graph doesn't
see". This module is the dynamic side of that contract: a seeded,
fully deterministic interleaving fuzzer that drives the annotated
objects (plus the other lock-dense runtime structures) through
adversarial schedules and checks their invariants after every run.

How determinism works:

* Exactly ONE thread runs at a time. A controller thread and N worker
  threads hand a baton around via per-worker Event pairs — the
  controller resumes one worker, the worker runs until its step budget
  expires (or it blocks), parks, and the controller picks again.
* Steps are BYTECODE OPCODES, delivered by a per-thread `sys.settrace`
  hook with `f_trace_opcodes` enabled — but only for frames whose code
  lives in the watched module files. Harness code is unwatched, so its
  operations are atomic w.r.t. the schedule; a preemption can land
  between the LOAD and STORE of `self.n = self.n + 1` in watched code,
  which is exactly the window a torn read-modify-write needs.
* All randomness (which worker next, how many opcodes it may run) is
  drawn from ONE `random.Random(seed)` owned by the controller. The
  workers never consult a clock or an RNG, so the full schedule — and
  the sha256 trace hash over every (worker, file, line, opcode) step —
  is a pure function of the seed.
* The scenario objects' real `threading.Lock`/`RLock`/`Event` fields
  are swapped for Fuzz* proxies BEFORE the workers start. A would-block
  acquire parks the worker in a "blocked" state instead of blocking the
  (serialized) scheduler; the controller wakes it when the holder
  releases. Runnable-set-empty with blocked workers remaining is
  reported as a deadlock violation.

`COVERAGE` maps every `atomic=` annotation in the runtime sources to
the scenario that exercises it; tests/test_schedule_fuzz.py fails if an
annotation appears without a backing scenario (or vice versa).
"""

from __future__ import annotations

import hashlib
import os
import random
import sys
import threading
from typing import Callable, Optional

__all__ = [
    "COVERAGE",
    "FuzzEvent",
    "FuzzLock",
    "FuzzRLock",
    "SCENARIOS",
    "ScheduleFuzzer",
    "run_fuzz",
]

_RUNNABLE = "runnable"
_BLOCKED = "blocked"
_FINISHED = "finished"

#: identity of the controller/setup thread for lock bookkeeping
_MAIN = object()


class _FuzzAbort(BaseException):
    """Raised inside workers to unwind them when the run is aborted
    (deadlock, hang, step-budget blown). BaseException so scenario code
    cannot swallow it with `except Exception`."""


class _TickClock:
    """Injectable clock: strictly increasing, schedule-independent-ish
    (ticks advance per call, and calls are serialized by the baton), so
    timestamps never feed nondeterminism back into a trace."""

    def __init__(self, step: float = 1e-4) -> None:
        self.t = 0.0
        self.step = step

    def __call__(self) -> float:
        self.t += self.step
        return self.t


# ---------------------------------------------------------------- workers


class _Worker:
    """One fuzzed thread: a real threading.Thread serialized under the
    controller's baton. `budget` opcodes of watched code per turn."""

    def __init__(self, harness: "ScheduleFuzzer", name: str,
                 fn: Callable[[], None]) -> None:
        self.harness = harness
        self.name = name
        self.fn = fn
        self.state = _RUNNABLE
        self.budget = 0
        self.wake_pred: "Optional[Callable[[], bool]]" = None
        self.blocked_on: "Optional[str]" = None
        self.error: "Optional[BaseException]" = None
        self.resume = threading.Event()
        self.parked = threading.Event()
        self.thread = threading.Thread(
            target=self._main, name=f"fuzz-{name}", daemon=True
        )

    def _main(self) -> None:
        self.harness._by_ident[threading.get_ident()] = self
        try:
            self._wait_resume()
            sys.settrace(self._trace)
            try:
                self.fn()
            finally:
                sys.settrace(None)
        except _FuzzAbort:
            pass
        except BaseException as exc:  # noqa: BLE001 — report, don't mask
            self.error = exc
        finally:
            sys.settrace(None)
            self.state = _FINISHED
            self.parked.set()

    def _wait_resume(self) -> None:
        self.resume.wait()
        self.resume.clear()
        if self.harness._aborted:
            raise _FuzzAbort

    def _park(self) -> None:
        """Hand the baton back and wait to be scheduled again."""
        self.parked.set()
        self._wait_resume()

    def block(self, pred: Callable[[], bool], why: str) -> None:
        """Park in the blocked state until `pred` goes true (checked by
        the controller between turns)."""
        self.state = _BLOCKED
        self.wake_pred = pred
        self.blocked_on = why
        self.harness._note(f"block|{self.name}|{why}")
        self._park()
        self.blocked_on = None

    # trace hooks — installed via sys.settrace in THIS thread only

    def _trace(self, frame, event, arg):
        if frame.f_code.co_filename not in self.harness.watched:
            return None
        frame.f_trace_opcodes = True
        return self._local

    def _local(self, frame, event, arg):
        if event == "opcode":
            self.harness._on_step(self, frame)
        return self._local


# ----------------------------------------------------------- lock proxies


class FuzzLock:
    """Drop-in for threading.Lock on a fuzzed object. Acquire from a
    worker parks it when contended; acquire from the controller (setup
    or invariant checks, when no worker runs) is uncontended by
    construction."""

    _reentrant = False

    def __init__(self, harness: "ScheduleFuzzer", name: str = "lock") -> None:
        self._h = harness
        self.name = name
        self._owner = None
        self._depth = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = self._h._current() or _MAIN
        while True:
            if self._owner is None:
                self._owner = me
                self._depth = 1
                return True
            if self._reentrant and self._owner is me:
                self._depth += 1
                return True
            if not blocking:
                return False
            if me is _MAIN:
                raise RuntimeError(
                    f"{self.name}: controller would block — a worker "
                    f"still holds the lock after the run"
                )
            me.block(lambda: self._owner is None, f"lock:{self.name}")

    def release(self) -> None:
        me = self._h._current() or _MAIN
        if self._owner is not me:
            if self._h._aborted:
                return  # unwinding after abort: tolerate
            raise RuntimeError(f"{self.name}: release by non-owner")
        self._depth -= 1
        if self._depth <= 0:
            self._owner = None
            self._depth = 0

    def locked(self) -> bool:
        return self._owner is not None

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


class FuzzRLock(FuzzLock):
    """Reentrant variant (DevicePubkeyRegistry's lock)."""

    _reentrant = True


class FuzzEvent:
    """Drop-in for threading.Event: wait() parks the worker instead of
    sleeping, so the happens-before edge annotations rely on is visible
    to the schedule."""

    def __init__(self, harness: "ScheduleFuzzer") -> None:
        self._h = harness
        self._flag = False

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        self._flag = True

    def clear(self) -> None:
        self._flag = False

    def wait(self, timeout: "Optional[float]" = None) -> bool:
        me = self._h._current()
        if me is None:
            return self._flag
        while not self._flag:
            me.block(lambda: self._flag, "event")
        return True


# --------------------------------------------------------------- harness


class ScheduleFuzzer:
    """One seeded run: add workers, then `run()`. The result dict holds
    the violation list (empty == clean), the sha256 trace hash (equal
    for equal seeds), and every preemption point the schedule hit."""

    def __init__(
        self,
        seed: int,
        watched: "list[str]",
        max_quantum: int = 6,
        max_steps: int = 200_000,
        hang_timeout_s: float = 30.0,
    ) -> None:
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self.watched = {os.path.abspath(p) for p in watched}
        self.max_quantum = max(1, int(max_quantum))
        self.max_steps = int(max_steps)
        self.hang_timeout_s = float(hang_timeout_s)
        self.workers: "list[_Worker]" = []
        self.violations: "list[dict]" = []
        self.preempt_points: "set[tuple[str, int]]" = set()
        self.steps = 0
        self.switches = 0
        self._hash = hashlib.sha256()
        self._by_ident: "dict[int, _Worker]" = {}
        self._aborted = False

    # -- plumbing used by workers/locks (single-runner, so no locking)

    def _current(self) -> "Optional[_Worker]":
        return self._by_ident.get(threading.get_ident())

    def _note(self, event: str) -> None:
        self._hash.update(event.encode())
        self._hash.update(b";")

    def _on_step(self, worker: _Worker, frame) -> None:
        if self._aborted:
            raise _FuzzAbort
        self.steps += 1
        if self.steps > self.max_steps:
            self.violations.append({
                "kind": "step-budget",
                "detail": f"exceeded {self.max_steps} steps — livelock?",
            })
            self._abort()
            raise _FuzzAbort
        code = frame.f_code
        lineno = frame.f_lineno or 0  # some opcodes carry no line
        self._note(
            f"{worker.name}|{os.path.basename(code.co_filename)}"
            f"|{lineno}|{frame.f_lasti}"
        )
        worker.budget -= 1
        if worker.budget <= 0:
            self.preempt_points.add(
                (os.path.basename(code.co_filename), lineno)
            )
            worker._park()

    # -- controller

    def add_worker(self, name: str, fn: Callable[[], None]) -> None:
        self.workers.append(_Worker(self, name, fn))

    def lock(self, name: str) -> FuzzLock:
        return FuzzLock(self, name)

    def rlock(self, name: str) -> FuzzRLock:
        return FuzzRLock(self, name)

    def event(self) -> FuzzEvent:
        return FuzzEvent(self)

    def _abort(self) -> None:
        self._aborted = True
        for w in self.workers:
            if w.state != _FINISHED:
                w.state = _RUNNABLE
                w.resume.set()

    def run(self) -> dict:
        for w in self.workers:
            w.thread.start()
        while True:
            for w in self.workers:
                if (
                    w.state == _BLOCKED
                    and w.wake_pred is not None
                    and w.wake_pred()
                ):
                    w.state = _RUNNABLE
                    w.wake_pred = None
            runnable = [w for w in self.workers if w.state == _RUNNABLE]
            if not runnable:
                blocked = {
                    w.name: w.blocked_on
                    for w in self.workers if w.state == _BLOCKED
                }
                if blocked:
                    self.violations.append({
                        "kind": "deadlock", "detail": repr(blocked),
                    })
                    self._abort()
                break
            w = runnable[self.rng.randrange(len(runnable))]
            w.budget = self.rng.randint(1, self.max_quantum)
            self.switches += 1
            self._note(f"pick|{w.name}|{w.budget}")
            w.parked.clear()
            w.resume.set()
            if not w.parked.wait(self.hang_timeout_s):
                self.violations.append({
                    "kind": "hung",
                    "detail": f"{w.name} did not yield within "
                              f"{self.hang_timeout_s}s — real blocking "
                              f"primitive left unproxied?",
                })
                self._abort()
                break
        for w in self.workers:
            w.thread.join(timeout=5.0)
            if w.error is not None:
                self.violations.append({
                    "kind": "exception",
                    "detail": f"{w.name}: {w.error!r}",
                })
        return {
            "seed": self.seed,
            "steps": self.steps,
            "switches": self.switches,
            "trace_sha256": self._hash.hexdigest(),
            "preemption_points": sorted(
                [f, ln] for f, ln in self.preempt_points
            ),
            "violations": self.violations,
        }


# -------------------------------------------------------------- scenarios


def _invariant(res: dict, scenario: str, failures: "list[str]") -> dict:
    for msg in failures:
        res["violations"].append({
            "kind": "invariant", "detail": f"{scenario}: {msg}",
        })
    return res


def scenario_ticket_verdict(seed: int, **kw) -> dict:
    """Backs `atomic=_ok` on VerifyTicket: two racing settlers, a
    result() reader gated on the Event, and a racing add_callback. The
    happens-before claim is that any reader passing the Event gate sees
    the winning settler's verdict, and callbacks fire exactly once."""
    import grandine_tpu.runtime.verify_scheduler as vs

    fz = ScheduleFuzzer(seed, watched=[vs.__file__], **kw)
    t = vs.VerifyTicket("attestation", origin="peer:fuzz")
    t._lock = fz.lock("ticket._lock")
    t._event = fz.event()
    fired: "list[bool]" = []
    seen: dict = {}

    def settle_ok() -> None:
        t._resolve(True)

    def settle_drop() -> None:
        t._resolve(False, dropped=True)

    def reader() -> None:
        seen["result"] = t.result(timeout=5.0)

    def register() -> None:
        t.add_callback(lambda tk: fired.append(tk.ok))

    fz.add_worker("settle_ok", settle_ok)
    fz.add_worker("settle_drop", settle_drop)
    fz.add_worker("reader", reader)
    fz.add_worker("register", register)
    res = fz.run()

    bad: "list[str]" = []
    if not t.done():
        bad.append("ticket never settled")
    if (t.ok, t.dropped) not in {(True, False), (False, True)}:
        bad.append(f"mixed verdict: ok={t.ok} dropped={t.dropped}")
    if len(fired) != 1:
        bad.append(f"callback fired {len(fired)} times (want 1)")
    elif fired[0] != t.ok:
        bad.append(f"callback saw ok={fired[0]}, settled ok={t.ok}")
    if "result" not in seen:
        bad.append("reader never returned")
    elif seen["result"] != t.ok:
        bad.append(f"reader saw {seen['result']}, settled ok={t.ok}")
    return _invariant(res, "ticket_verdict", bad)


def scenario_sign_ticket(seed: int, **kw) -> dict:
    """Backs `atomic=_sig` on SignTicket (runtime/sign_plane.py): two
    racing settlers (a signature vs a drop), a result() reader gated on
    the Event, and a racing add_callback. The happens-before claim is
    that any reader passing the Event gate sees the winning settler's
    outcome — the signature bytes or the dropped RuntimeError — and
    callbacks fire exactly once."""
    import grandine_tpu.runtime.sign_plane as sp

    fz = ScheduleFuzzer(seed, watched=[sp.__file__], **kw)
    t = sp.SignTicket("attestation")
    t._lock = fz.lock("sign_ticket._lock")
    t._event = fz.event()
    fired: "list[bool]" = []
    seen: dict = {}

    def settle_sig() -> None:
        t._resolve(b"fuzz-signature")

    def settle_drop() -> None:
        t._resolve(None, dropped=True)

    def reader() -> None:
        try:
            seen["result"] = t.result(timeout=5.0)
        except RuntimeError:
            seen["result"] = "dropped"

    def register() -> None:
        t.add_callback(lambda tk: fired.append(tk.dropped))

    fz.add_worker("settle_sig", settle_sig)
    fz.add_worker("settle_drop", settle_drop)
    fz.add_worker("reader", reader)
    fz.add_worker("register", register)
    res = fz.run()

    bad: "list[str]" = []
    if not t.done():
        bad.append("ticket never settled")
    if (t._sig, t.dropped) not in {(b"fuzz-signature", False), (None, True)}:
        bad.append(f"mixed outcome: sig={t._sig!r} dropped={t.dropped}")
    if len(fired) != 1:
        bad.append(f"callback fired {len(fired)} times (want 1)")
    elif fired[0] != t.dropped:
        bad.append(f"callback saw dropped={fired[0]}, settled {t.dropped}")
    if "result" not in seen:
        bad.append("reader never returned")
    elif t.dropped and seen["result"] != "dropped":
        bad.append(f"reader saw {seen['result']!r} on a dropped ticket")
    elif not t.dropped and seen["result"] != t._sig:
        bad.append(f"reader saw {seen['result']!r}, settled {t._sig!r}")
    return _invariant(res, "sign_ticket", bad)


def scenario_flight_ring(seed: int, **kw) -> dict:
    """FlightRecorder under concurrent commit/snapshot/duty traffic: the
    ring, aggregate counters, origin table, and occupancy integrals must
    stay coherent."""
    import grandine_tpu.runtime.flight as fl

    fz = ScheduleFuzzer(seed, watched=[fl.__file__], **kw)
    fr = fl.FlightRecorder(capacity=16, origin_top_k=4, clock=_TickClock())
    fr._lock = fz.lock("flight._lock")
    fr.origins._lock = fz.lock("origins._lock")
    n = 5

    def writer(lane: str, origin: str) -> Callable[[], None]:
        def fn() -> None:
            for i in range(n):
                bf = fr.begin_batch(lane, "verify_fixed", items=3,
                                    queue_wait_s=0.01)
                bf.note_device(0.001)
                if i % 2:
                    bf.note_fault("watchdog")
                    bf.note_origin_failure(origin)
                bf.finish(i % 2 == 0)
        return fn

    def reader() -> None:
        for _ in range(4):
            fr.snapshot()
            fr.summary()
            fr.duty_cycle()
            fr.slo_misses()

    def duty() -> None:
        for _ in range(n):
            fr.device_enter()
            fr.device_exit()

    fz.add_worker("writer_att", writer("attestation", "peer:a"))
    fz.add_worker("writer_blk", writer("block", "peer:b"))
    fz.add_worker("reader", reader)
    fz.add_worker("duty", duty)
    res = fz.run()

    bad: "list[str]" = []
    s = fr.summary()
    if s["batches"] != 2 * n:
        bad.append(f"batches={s['batches']} (want {2 * n}) — lost commit")
    if s["records_total"] != 2 * n:
        bad.append(f"records_total={s['records_total']} (want {2 * n})")
    if s["faults"].get("watchdog", 0) != 2 * (n // 2):
        bad.append(f"faults={s['faults']} — lost fault count")
    if fr._inflight != 0:
        bad.append(f"inflight={fr._inflight} after balanced enter/exit")
    origins = {r["origin"]: r["failures"] for r in fr.origins.snapshot()}
    if origins != {"peer:a": n // 2, "peer:b": n // 2}:
        bad.append(f"origin table {origins} — lost attribution")
    return _invariant(res, "flight_ring", bad)


def scenario_breaker_walk(seed: int, **kw) -> dict:
    """CircuitBreaker legal-state walk: faulters, succeeders, and a
    probe installer race; the breaker must stay in a legal state with
    transition counters that balance."""
    import grandine_tpu.runtime.health as hl

    fz = ScheduleFuzzer(seed, watched=[hl.__file__], **kw)
    br = hl.CircuitBreaker(
        name="fuzz", fault_threshold=2, window=4, fault_rate=0.5,
        backoff_initial_s=0.0, backoff_max_s=0.0, jitter_frac=0.0,
        clock=_TickClock(), rng=random.Random(seed),
    )
    br._lock = fz.lock("breaker._lock")

    def probe() -> bool:
        return True

    def faulter() -> None:
        for _ in range(4):
            br.allow()
            br.record_fault("settle")

    def succeeder() -> None:
        for _ in range(4):
            br.allow()
            br.record_success()

    def prober() -> None:
        for _ in range(3):
            br.ensure_probe(probe)
            br.allow()

    fz.add_worker("faulter", faulter)
    fz.add_worker("succeeder", succeeder)
    fz.add_worker("prober", prober)
    res = fz.run()

    bad: "list[str]" = []
    final = br.state
    if final not in (hl.CLOSED, hl.OPEN, hl.HALF_OPEN):
        bad.append(f"illegal state {final!r}")
    expect = 0 if final == hl.CLOSED else 1
    if br.stats["opens"] - br.stats["closes"] != expect:
        bad.append(
            f"state {final} with opens={br.stats['opens']} "
            f"closes={br.stats['closes']} — transition counters torn"
        )
    if len(br._window) > br.window_size:
        bad.append(f"window overflow: {len(br._window)}")
    if br._consecutive < 0:
        bad.append(f"negative consecutive: {br._consecutive}")
    if br.probe is not probe:
        bad.append("ensure_probe lost the first-writer race to nobody")
    return _invariant(res, "breaker_walk", bad)


def scenario_registry_lifecycle(seed: int, **kw) -> dict:
    """DevicePubkeyRegistry ensure/mark_stale/invalidate churn under the
    RLock, with the numpy/JAX upload seams stubbed so the fuzz stays
    kernel-free. Hit/miss accounting must balance and the visible set
    must always be one of the ensured tuples (or empty)."""
    import grandine_tpu.tpu.registry as rg

    fz = ScheduleFuzzer(seed, watched=[rg.__file__], **kw)
    reg = rg.DevicePubkeyRegistry()
    reg._lock = fz.rlock("registry._lock")
    # device-upload seams: called only under the (fuzz) RLock, so plain
    # state pokes preserve ensure()'s locked-section semantics
    reg._append = lambda pubkeys, start: None
    reg._refresh = lambda pubkeys: setattr(reg, "_pubkeys", pubkeys)

    set_a = (b"k1", b"k2")
    set_b = (b"k1", b"k2", b"k3")

    def ensure(pubkeys: tuple) -> Callable[[], None]:
        def fn() -> None:
            for _ in range(3):
                reg.ensure(pubkeys)
        return fn

    def churn() -> None:
        reg.mark_stale()
        reg.invalidate()
        reg.mark_stale()

    def reader() -> None:
        for _ in range(4):
            reg.count
            reg.capacity

    fz.add_worker("ensure_a", ensure(set_a))
    fz.add_worker("ensure_b", ensure(set_b))
    fz.add_worker("churn", churn)
    fz.add_worker("reader", reader)
    res = fz.run()

    bad: "list[str]" = []
    if reg._pubkeys not in (None, set_a, set_b):
        bad.append(f"torn pubkey set: {reg._pubkeys!r}")
    if reg.count not in (0, len(set_a), len(set_b)):
        bad.append(f"impossible count {reg.count}")
    total = reg.stats["hits"] + reg.stats["misses"]
    if total != 6:
        bad.append(f"hits+misses={total} (want 6) — lost ensure() bump")
    if reg._stale not in (True, False):
        bad.append(f"stale flag corrupt: {reg._stale!r}")
    return _invariant(res, "registry_lifecycle", bad)


def scenario_cached_pubkey(seed: int, **kw) -> dict:
    """CachedPublicKey first-use fill race: concurrent decompress()
    callers on one shared key must decompress exactly once and all
    observe the same object. The pre-lock code's unlocked check-then-set
    let two threads both see None and both pay the pure-Python G1
    decompress — this scenario preempts between the check and the set
    and fails on any duplicate fill or torn read."""
    import grandine_tpu.crypto.bls as cb

    fz = ScheduleFuzzer(seed, watched=[cb.__file__], **kw)
    key = cb.CachedPublicKey(b"\x99" * 48)
    key._lock = fz.lock("cached_pubkey._lock")

    calls = [0]
    sentinel = object()
    real_from_bytes = cb.PublicKey.from_bytes

    def counting_from_bytes(data: bytes):
        calls[0] += 1
        return sentinel

    seen: "list[object]" = []

    def reader() -> None:
        for _ in range(3):
            seen.append(key.decompress())

    cb.PublicKey.from_bytes = staticmethod(counting_from_bytes)
    try:
        fz.add_worker("reader_a", reader)
        fz.add_worker("reader_b", reader)
        fz.add_worker("reader_c", reader)
        res = fz.run()
    finally:
        cb.PublicKey.from_bytes = real_from_bytes

    bad: "list[str]" = []
    if calls[0] != 1:
        bad.append(
            f"from_bytes ran {calls[0]} times (want 1) — unlocked "
            "check-then-set let two fills race"
        )
    if any(obj is not sentinel for obj in seen):
        bad.append("a reader observed a torn/foreign decompressed value")
    if key._decompressed is not sentinel:
        bad.append("cached value lost after the fill")
    return _invariant(res, "cached_pubkey", bad)


def scenario_brownout_ladder(seed: int, **kw) -> dict:
    """BrownoutController ladder walk under concurrent evaluate() calls:
    pressure feeders push SLO misses into a stub flight recorder while
    several workers tick the controller. The ladder must only ever move
    one adjacent step per transition, the engaged-actuator set must
    match the level exactly (a torn _shift would strand a shrunk lane
    config at NORMAL or skip an engage on the way up), and replaying
    the transition log from NORMAL must land on the final level."""
    import grandine_tpu.runtime.brownout as bo
    from grandine_tpu.runtime.thread_pool import Priority

    class _StubLane:
        def __init__(self, priority, shed):
            self.priority = priority
            self.shed = shed
            self.max_wait_s = 1.0
            self.max_queue = 64

    class _StubSched:
        def __init__(self):
            self.merge_window_s = 0.5
            self.lanes = {
                "high": _StubLane(Priority.HIGH, False),
                "low": _StubLane(Priority.LOW, True),
            }
            self.brownout_route_host = frozenset()
            self.brownout_shed_lanes = frozenset()
            self.depth = 0.0

        def lane_pressure(self):
            return {"low": self.depth}

    class _StubFlight:
        def __init__(self):
            self.miss = 0
            self.brownout_level = "normal"

        def slo_misses(self):
            return {"low": {"queue_wait": self.miss}}

        def duty_cycle(self):
            return 0.0

    sched = _StubSched()
    flight = _StubFlight()
    ctrl = bo.BrownoutController(
        sched, flight=flight, clock=_TickClock(),
        recovery_window_s=3e-4, escalate_dwell_s=0.0,
    )
    fz = ScheduleFuzzer(seed, watched=[bo.__file__], **kw)
    ctrl._lock = fz.lock("brownout._lock")

    def pressurize() -> None:
        for _ in range(5):
            flight.miss += 1  # harness code: atomic w.r.t. the schedule
            ctrl.evaluate()

    def cooldown() -> None:
        for _ in range(6):
            ctrl.evaluate()

    fz.add_worker("pressure_a", pressurize)
    fz.add_worker("pressure_b", pressurize)
    fz.add_worker("cooler", cooldown)
    res = fz.run()

    bad: "list[str]" = []
    final = ctrl._idx
    if not 0 <= final < len(bo.LEVELS):
        bad.append(f"level index {final} outside the ladder")
    replay_idx = 0
    for _t, frm, to in ctrl._transitions:
        if frm != bo.LEVELS[replay_idx]:
            bad.append(
                f"transition {frm}->{to} does not chain from "
                f"{bo.LEVELS[replay_idx]} — a torn _shift"
            )
            break
        step = bo.LEVELS.index(to) - bo.LEVELS.index(frm)
        if abs(step) != 1:
            bad.append(f"non-adjacent transition {frm}->{to}")
            break
        replay_idx = bo.LEVELS.index(to)
    else:
        if replay_idx != final:
            bad.append(
                f"transition log replays to {bo.LEVELS[replay_idx]} "
                f"but controller sits at {bo.LEVELS[final]}"
            )
    want_engaged = sorted(
        lvl for lvl in (bo.B1, bo.B2)
        if final >= bo.LEVELS.index(lvl)
    )
    if sorted(ctrl._baselines) != want_engaged:
        bad.append(
            f"engaged baselines {sorted(ctrl._baselines)} != "
            f"{want_engaged} for level {bo.LEVELS[final]}"
        )
    if final < 1 and sched.merge_window_s != 0.5:
        bad.append("merge_window_s not restored at NORMAL")
    if (final >= 3) != bool(sched.brownout_route_host):
        bad.append("brownout_route_host inconsistent with level")
    if (final >= 4) != bool(sched.brownout_shed_lanes):
        bad.append("brownout_shed_lanes inconsistent with level")
    if flight.brownout_level != bo.LEVELS[final] and ctrl._transitions:
        bad.append(
            f"flight stamp {flight.brownout_level!r} lags level "
            f"{bo.LEVELS[final]!r}"
        )
    return _invariant(res, "brownout_ladder", bad)


SCENARIOS: "dict[str, Callable[..., dict]]" = {
    "ticket_verdict": scenario_ticket_verdict,
    "sign_ticket": scenario_sign_ticket,
    "flight_ring": scenario_flight_ring,
    "breaker_walk": scenario_breaker_walk,
    "registry_lifecycle": scenario_registry_lifecycle,
    "cached_pubkey": scenario_cached_pubkey,
    "brownout_ladder": scenario_brownout_ladder,
}

#: every `# lint: atomic=<attr>:` annotation in the runtime sources maps
#: to the scenario whose invariants back it. Key format:
#: "<module basename>.<Class>.<attr>". tests/test_schedule_fuzz.py
#: cross-checks this against the annotations the lint rule actually
#: parses — an annotation without a scenario (or a stale entry here)
#: fails the suite.
COVERAGE: "dict[str, str]" = {
    "verify_scheduler.VerifyTicket._ok": "ticket_verdict",
    "sign_plane.SignTicket._sig": "sign_ticket",
}


def run_fuzz(
    seeds=(0, 1, 2),
    scenarios: "Optional[list[str]]" = None,
    max_quantum: int = 6,
    max_steps: int = 200_000,
) -> dict:
    """Run every scenario under every seed; aggregate violations, the
    preemption-point union, and the per-(scenario, seed) trace hashes
    (equal seeds reproduce equal hashes — the determinism contract)."""
    names = sorted(SCENARIOS) if scenarios is None else list(scenarios)
    traces: "dict[str, str]" = {}
    union: "set[tuple[str, int]]" = set()
    violations: "list[dict]" = []
    steps = switches = 0
    for seed in seeds:
        for name in names:
            res = SCENARIOS[name](
                seed, max_quantum=max_quantum, max_steps=max_steps
            )
            traces[f"{name}:{seed}"] = res["trace_sha256"]
            union.update((f, ln) for f, ln in res["preemption_points"])
            for v in res["violations"]:
                violations.append({"scenario": name, "seed": seed, **v})
            steps += res["steps"]
            switches += res["switches"]
    return {
        "seeds": list(seeds),
        "scenarios": names,
        "steps": steps,
        "switches": switches,
        "preemption_points": len(union),
        "violations": violations,
        "traces": traces,
    }
