"""Compile-time-style preset constants (reference: types/src/preset.rs:44 —
`Preset` trait with `Mainnet`/`Minimal` impls of type-level constants).

Here a frozen dataclass: one instance per preset, hashable, passed to the
container factory and spec functions.
"""

from dataclasses import dataclass, replace


@dataclass(frozen=True)
class Preset:
    name: str

    # misc
    MAX_COMMITTEES_PER_SLOT: int = 64
    TARGET_COMMITTEE_SIZE: int = 128
    MAX_VALIDATORS_PER_COMMITTEE: int = 2048
    SHUFFLE_ROUND_COUNT: int = 90
    HYSTERESIS_QUOTIENT: int = 4
    HYSTERESIS_DOWNWARD_MULTIPLIER: int = 1
    HYSTERESIS_UPWARD_MULTIPLIER: int = 5

    # gwei values
    MIN_DEPOSIT_AMOUNT: int = 10**9
    MAX_EFFECTIVE_BALANCE: int = 32 * 10**9
    EFFECTIVE_BALANCE_INCREMENT: int = 10**9

    # time parameters (slots/epochs)
    MIN_ATTESTATION_INCLUSION_DELAY: int = 1
    SLOTS_PER_EPOCH: int = 32
    MIN_SEED_LOOKAHEAD: int = 1
    MAX_SEED_LOOKAHEAD: int = 4
    EPOCHS_PER_ETH1_VOTING_PERIOD: int = 64
    SLOTS_PER_HISTORICAL_ROOT: int = 8192
    MIN_EPOCHS_TO_INACTIVITY_PENALTY: int = 4

    # state list lengths
    EPOCHS_PER_HISTORICAL_VECTOR: int = 65536
    EPOCHS_PER_SLASHINGS_VECTOR: int = 8192
    HISTORICAL_ROOTS_LIMIT: int = 2**24
    VALIDATOR_REGISTRY_LIMIT: int = 2**40

    # rewards & penalties (phase0)
    BASE_REWARD_FACTOR: int = 64
    WHISTLEBLOWER_REWARD_QUOTIENT: int = 512
    PROPOSER_REWARD_QUOTIENT: int = 8
    INACTIVITY_PENALTY_QUOTIENT: int = 2**26
    MIN_SLASHING_PENALTY_QUOTIENT: int = 128
    PROPORTIONAL_SLASHING_MULTIPLIER: int = 1

    # max operations per block
    MAX_PROPOSER_SLASHINGS: int = 16
    MAX_ATTESTER_SLASHINGS: int = 2
    MAX_ATTESTATIONS: int = 128
    MAX_DEPOSITS: int = 16
    MAX_VOLUNTARY_EXITS: int = 16

    # altair
    INACTIVITY_PENALTY_QUOTIENT_ALTAIR: int = 3 * 2**24
    MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR: int = 64
    PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR: int = 2
    SYNC_COMMITTEE_SIZE: int = 512
    EPOCHS_PER_SYNC_COMMITTEE_PERIOD: int = 256
    MIN_SYNC_COMMITTEE_PARTICIPANTS: int = 1

    # bellatrix
    INACTIVITY_PENALTY_QUOTIENT_BELLATRIX: int = 2**24
    MIN_SLASHING_PENALTY_QUOTIENT_BELLATRIX: int = 32
    PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX: int = 3
    MAX_BYTES_PER_TRANSACTION: int = 2**30
    MAX_TRANSACTIONS_PER_PAYLOAD: int = 2**20
    BYTES_PER_LOGS_BLOOM: int = 256
    MAX_EXTRA_DATA_BYTES: int = 32

    # capella
    MAX_BLS_TO_EXECUTION_CHANGES: int = 16
    MAX_WITHDRAWALS_PER_PAYLOAD: int = 16
    MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP: int = 16384

    # deneb
    MAX_BLOB_COMMITMENTS_PER_BLOCK: int = 4096
    MAX_BLOBS_PER_BLOCK: int = 6
    FIELD_ELEMENTS_PER_BLOB: int = 4096
    KZG_COMMITMENT_INCLUSION_PROOF_DEPTH: int = 17


MAINNET = Preset(name="mainnet")

MINIMAL = Preset(
    name="minimal",
    MAX_COMMITTEES_PER_SLOT=4,
    TARGET_COMMITTEE_SIZE=4,
    SHUFFLE_ROUND_COUNT=10,
    INACTIVITY_PENALTY_QUOTIENT=2**25,
    MIN_SLASHING_PENALTY_QUOTIENT=64,
    PROPORTIONAL_SLASHING_MULTIPLIER=2,
    KZG_COMMITMENT_INCLUSION_PROOF_DEPTH=9,
    SLOTS_PER_EPOCH=8,
    EPOCHS_PER_ETH1_VOTING_PERIOD=4,
    SLOTS_PER_HISTORICAL_ROOT=64,
    EPOCHS_PER_HISTORICAL_VECTOR=64,
    EPOCHS_PER_SLASHINGS_VECTOR=64,
    SYNC_COMMITTEE_SIZE=32,
    EPOCHS_PER_SYNC_COMMITTEE_PERIOD=8,
    MAX_WITHDRAWALS_PER_PAYLOAD=4,
    MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP=16,
    MAX_BLOB_COMMITMENTS_PER_BLOCK=16,
    FIELD_ELEMENTS_PER_BLOB=4096,
)


# Medalla testnet: mainnet with the early-2020 penalty parameters and a
# 32-epoch eth1 voting period (reference types/src/preset.rs:350-409).
MEDALLA = Preset(
    name="medalla",
    EPOCHS_PER_ETH1_VOTING_PERIOD=32,
    INACTIVITY_PENALTY_QUOTIENT=1 << 24,
    MIN_SLASHING_PENALTY_QUOTIENT=32,
    PROPORTIONAL_SLASHING_MULTIPLIER=3,
)


def by_name(name: str) -> Preset:
    presets = {"mainnet": MAINNET, "minimal": MINIMAL, "medalla": MEDALLA}
    try:
        return presets[name]
    except KeyError:
        raise ValueError(f"unknown preset {name!r}") from None
