"""Runtime chain configuration (reference: types/src/config.rs — fork
versions/epochs and network-level parameters, YAML-loadable for custom
networks)."""

from dataclasses import dataclass, field, fields

from grandine_tpu.types.preset import MAINNET, MINIMAL, Preset, by_name
from grandine_tpu.types.primitives import FAR_FUTURE_EPOCH, Phase


@dataclass(frozen=True)
class Config:
    config_name: str = "mainnet"
    preset_base: str = "mainnet"

    # genesis
    min_genesis_active_validator_count: int = 16384
    min_genesis_time: int = 1606824000
    genesis_fork_version: bytes = bytes.fromhex("00000000")
    genesis_delay: int = 604800

    # forks
    altair_fork_version: bytes = bytes.fromhex("01000000")
    altair_fork_epoch: int = 74240
    bellatrix_fork_version: bytes = bytes.fromhex("02000000")
    bellatrix_fork_epoch: int = 144896
    capella_fork_version: bytes = bytes.fromhex("03000000")
    capella_fork_epoch: int = 194048
    deneb_fork_version: bytes = bytes.fromhex("04000000")
    deneb_fork_epoch: int = 269568

    # time
    seconds_per_slot: int = 12
    seconds_per_eth1_block: int = 14
    min_validator_withdrawability_delay: int = 256
    shard_committee_period: int = 256
    eth1_follow_distance: int = 2048

    # validator cycle
    inactivity_score_bias: int = 4
    inactivity_score_recovery_rate: int = 16
    ejection_balance: int = 16 * 10**9
    min_per_epoch_churn_limit: int = 4
    churn_limit_quotient: int = 65536
    max_per_epoch_activation_churn_limit: int = 8

    # transition
    terminal_total_difficulty: int = 58750000000000000000000
    terminal_block_hash: bytes = b"\x00" * 32
    terminal_block_hash_activation_epoch: int = FAR_FUTURE_EPOCH

    # deposit contract
    deposit_chain_id: int = 1
    deposit_network_id: int = 1
    deposit_contract_address: bytes = bytes.fromhex(
        "00000000219ab540356cbb839cbe05303d7705fa")

    # networking (subset used by services)
    gossip_max_size: int = 10 * 2**20
    max_request_blocks: int = 1024
    max_request_blocks_deneb: int = 128
    max_request_blob_sidecars: int = 768
    min_epochs_for_block_requests: int = 33024
    min_epochs_for_blob_sidecars_requests: int = 4096
    attestation_subnet_count: int = 64
    sync_committee_subnet_count: int = 4
    target_aggregators_per_committee: int = 16
    epochs_per_subnet_subscription: int = 256
    attestation_propagation_slot_range: int = 32
    maximum_gossip_clock_disparity_ms: int = 500
    blob_sidecar_subnet_count: int = 6

    @property
    def preset(self) -> Preset:
        return by_name(self.preset_base)

    # -- fork schedule ------------------------------------------------------

    def fork_epoch(self, phase: Phase) -> int:
        return {
            Phase.PHASE0: 0,
            Phase.ALTAIR: self.altair_fork_epoch,
            Phase.BELLATRIX: self.bellatrix_fork_epoch,
            Phase.CAPELLA: self.capella_fork_epoch,
            Phase.DENEB: self.deneb_fork_epoch,
        }[phase]

    def fork_version(self, phase: Phase) -> bytes:
        return {
            Phase.PHASE0: self.genesis_fork_version,
            Phase.ALTAIR: self.altair_fork_version,
            Phase.BELLATRIX: self.bellatrix_fork_version,
            Phase.CAPELLA: self.capella_fork_version,
            Phase.DENEB: self.deneb_fork_version,
        }[phase]

    def phase_at_epoch(self, epoch: int) -> Phase:
        phase = Phase.PHASE0
        for p in Phase:
            if self.fork_epoch(p) <= epoch:
                phase = p
        return phase

    def phase_at_slot(self, slot: int) -> Phase:
        return self.phase_at_epoch(slot // self.preset.SLOTS_PER_EPOCH)

    # -- construction -------------------------------------------------------

    @classmethod
    def mainnet(cls) -> "Config":
        return cls()

    @classmethod
    def minimal(cls) -> "Config":
        """Minimal-preset interop config with all forks at genesis."""
        return cls(
            config_name="minimal",
            preset_base="minimal",
            min_genesis_active_validator_count=64,
            genesis_fork_version=bytes.fromhex("00000001"),
            altair_fork_version=bytes.fromhex("01000001"),
            altair_fork_epoch=0,
            bellatrix_fork_version=bytes.fromhex("02000001"),
            bellatrix_fork_epoch=0,
            capella_fork_version=bytes.fromhex("03000001"),
            capella_fork_epoch=0,
            deneb_fork_version=bytes.fromhex("04000001"),
            deneb_fork_epoch=0,
            seconds_per_slot=6,
            eth1_follow_distance=16,
            min_validator_withdrawability_delay=256,
            shard_committee_period=64,
            churn_limit_quotient=32,
            max_per_epoch_activation_churn_limit=4,
            deposit_chain_id=5,
            deposit_network_id=5,
        )

    @classmethod
    def from_dict(cls, raw: dict) -> "Config":
        """Load from a consensus-specs-style config mapping (UPPER_SNAKE
        keys, 0x-hex for bytes), ignoring unknown keys."""
        known = {f.name: f for f in fields(cls)}
        kwargs = {}
        for key, value in raw.items():
            name = key.lower()
            if name not in known:
                continue
            typ = known[name].type
            if typ is bytes or known[name].default.__class__ is bytes:
                if isinstance(value, str):
                    value = bytes.fromhex(value.removeprefix("0x"))
            elif isinstance(value, str) and value.isdigit():
                value = int(value)
            kwargs[name] = value
        return cls(**kwargs)

    @classmethod
    def from_yaml(cls, path: str) -> "Config":
        import yaml

        with open(path) as f:
            return cls.from_dict(yaml.safe_load(f))
