"""Per-fork SSZ containers, built per preset.

Reference parity: types/src/{phase0,altair,bellatrix,capella,deneb}/
containers.rs. The reference makes containers generic over a `Preset` type
parameter; here a cached factory builds concrete container classes per
preset, with later forks composing earlier forks' field dicts (field order
is the spec's — altair *replaces* the pending-attestation state fields,
later forks append).

Access through `spec_types(preset)`:
    T = spec_types(MAINNET)
    T.phase0.BeaconState, T.deneb.SignedBeaconBlock, T.capella.Withdrawal...
"""

from types import SimpleNamespace

from grandine_tpu.ssz import (
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Bytes4,
    Bytes20,
    Bytes32,
    Bytes48,
    Bytes96,
    Container,
    List,
    Vector,
    boolean,
    uint8,
    uint64,
    uint256,
)
from grandine_tpu.ssz.base import ContainerMeta
from grandine_tpu.types.preset import Preset
from grandine_tpu.types.primitives import (
    DEPOSIT_CONTRACT_TREE_DEPTH,
    JUSTIFICATION_BITS_LENGTH,
)

BYTES_PER_FIELD_ELEMENT = 32


def _container(name: str, fields: dict) -> ContainerMeta:
    return ContainerMeta(name, (Container,), {"__annotations__": dict(fields)})


def _build(p: Preset) -> SimpleNamespace:
    ns = SimpleNamespace(preset=p)

    # ---------------------------------------------------------------- phase0
    Fork = _container("Fork", dict(
        previous_version=Bytes4, current_version=Bytes4, epoch=uint64))
    ForkData = _container("ForkData", dict(
        current_version=Bytes4, genesis_validators_root=Bytes32))
    Checkpoint = _container("Checkpoint", dict(epoch=uint64, root=Bytes32))
    Validator = _container("Validator", dict(
        pubkey=Bytes48,
        withdrawal_credentials=Bytes32,
        effective_balance=uint64,
        slashed=boolean,
        activation_eligibility_epoch=uint64,
        activation_epoch=uint64,
        exit_epoch=uint64,
        withdrawable_epoch=uint64,
    ))
    AttestationData = _container("AttestationData", dict(
        slot=uint64,
        index=uint64,
        beacon_block_root=Bytes32,
        source=Checkpoint,
        target=Checkpoint,
    ))
    IndexedAttestation = _container("IndexedAttestation", dict(
        attesting_indices=List(uint64, p.MAX_VALIDATORS_PER_COMMITTEE),
        data=AttestationData,
        signature=Bytes96,
    ))
    PendingAttestation = _container("PendingAttestation", dict(
        aggregation_bits=Bitlist(p.MAX_VALIDATORS_PER_COMMITTEE),
        data=AttestationData,
        inclusion_delay=uint64,
        proposer_index=uint64,
    ))
    Eth1Data = _container("Eth1Data", dict(
        deposit_root=Bytes32, deposit_count=uint64, block_hash=Bytes32))
    HistoricalBatch = _container("HistoricalBatch", dict(
        block_roots=Vector(Bytes32, p.SLOTS_PER_HISTORICAL_ROOT),
        state_roots=Vector(Bytes32, p.SLOTS_PER_HISTORICAL_ROOT),
    ))
    DepositMessage = _container("DepositMessage", dict(
        pubkey=Bytes48, withdrawal_credentials=Bytes32, amount=uint64))
    DepositData = _container("DepositData", dict(
        pubkey=Bytes48,
        withdrawal_credentials=Bytes32,
        amount=uint64,
        signature=Bytes96,
    ))
    BeaconBlockHeader = _container("BeaconBlockHeader", dict(
        slot=uint64,
        proposer_index=uint64,
        parent_root=Bytes32,
        state_root=Bytes32,
        body_root=Bytes32,
    ))
    SigningData = _container("SigningData", dict(
        object_root=Bytes32, domain=Bytes32))
    SignedBeaconBlockHeader = _container("SignedBeaconBlockHeader", dict(
        message=BeaconBlockHeader, signature=Bytes96))
    ProposerSlashing = _container("ProposerSlashing", dict(
        signed_header_1=SignedBeaconBlockHeader,
        signed_header_2=SignedBeaconBlockHeader,
    ))
    AttesterSlashing = _container("AttesterSlashing", dict(
        attestation_1=IndexedAttestation, attestation_2=IndexedAttestation))
    Attestation = _container("Attestation", dict(
        aggregation_bits=Bitlist(p.MAX_VALIDATORS_PER_COMMITTEE),
        data=AttestationData,
        signature=Bytes96,
    ))
    Deposit = _container("Deposit", dict(
        proof=Vector(Bytes32, DEPOSIT_CONTRACT_TREE_DEPTH + 1),
        data=DepositData,
    ))
    VoluntaryExit = _container("VoluntaryExit", dict(
        epoch=uint64, validator_index=uint64))
    SignedVoluntaryExit = _container("SignedVoluntaryExit", dict(
        message=VoluntaryExit, signature=Bytes96))
    AggregateAndProof = _container("AggregateAndProof", dict(
        aggregator_index=uint64,
        aggregate=Attestation,
        selection_proof=Bytes96,
    ))
    SignedAggregateAndProof = _container("SignedAggregateAndProof", dict(
        message=AggregateAndProof, signature=Bytes96))

    _phase0_body_fields = dict(
        randao_reveal=Bytes96,
        eth1_data=Eth1Data,
        graffiti=Bytes32,
        proposer_slashings=List(ProposerSlashing, p.MAX_PROPOSER_SLASHINGS),
        attester_slashings=List(AttesterSlashing, p.MAX_ATTESTER_SLASHINGS),
        attestations=List(Attestation, p.MAX_ATTESTATIONS),
        deposits=List(Deposit, p.MAX_DEPOSITS),
        voluntary_exits=List(SignedVoluntaryExit, p.MAX_VOLUNTARY_EXITS),
    )

    _state_prefix = lambda: dict(  # noqa: E731 — shared leading fields
        genesis_time=uint64,
        genesis_validators_root=Bytes32,
        slot=uint64,
        fork=Fork,
        latest_block_header=BeaconBlockHeader,
        block_roots=Vector(Bytes32, p.SLOTS_PER_HISTORICAL_ROOT),
        state_roots=Vector(Bytes32, p.SLOTS_PER_HISTORICAL_ROOT),
        historical_roots=List(Bytes32, p.HISTORICAL_ROOTS_LIMIT),
        eth1_data=Eth1Data,
        eth1_data_votes=List(
            Eth1Data, p.EPOCHS_PER_ETH1_VOTING_PERIOD * p.SLOTS_PER_EPOCH),
        eth1_deposit_index=uint64,
        validators=List(Validator, p.VALIDATOR_REGISTRY_LIMIT),
        balances=List(uint64, p.VALIDATOR_REGISTRY_LIMIT),
        randao_mixes=Vector(Bytes32, p.EPOCHS_PER_HISTORICAL_VECTOR),
        slashings=Vector(uint64, p.EPOCHS_PER_SLASHINGS_VECTOR),
    )
    _justice_suffix = dict(
        justification_bits=Bitvector(JUSTIFICATION_BITS_LENGTH),
        previous_justified_checkpoint=Checkpoint,
        current_justified_checkpoint=Checkpoint,
        finalized_checkpoint=Checkpoint,
    )

    def _block_types(body_cls, prefix=""):
        block = _container(prefix + "BeaconBlock", dict(
            slot=uint64,
            proposer_index=uint64,
            parent_root=Bytes32,
            state_root=Bytes32,
            body=body_cls,
        ))
        signed = _container("Signed" + prefix + "BeaconBlock", dict(
            message=block, signature=Bytes96))
        return block, signed

    ph = SimpleNamespace(
        Fork=Fork, ForkData=ForkData, Checkpoint=Checkpoint,
        Validator=Validator, AttestationData=AttestationData,
        IndexedAttestation=IndexedAttestation,
        PendingAttestation=PendingAttestation, Eth1Data=Eth1Data,
        HistoricalBatch=HistoricalBatch, DepositMessage=DepositMessage,
        DepositData=DepositData, BeaconBlockHeader=BeaconBlockHeader,
        SigningData=SigningData,
        SignedBeaconBlockHeader=SignedBeaconBlockHeader,
        ProposerSlashing=ProposerSlashing, AttesterSlashing=AttesterSlashing,
        Attestation=Attestation, Deposit=Deposit,
        VoluntaryExit=VoluntaryExit, SignedVoluntaryExit=SignedVoluntaryExit,
        AggregateAndProof=AggregateAndProof,
        SignedAggregateAndProof=SignedAggregateAndProof,
    )
    ph.BeaconBlockBody = _container("BeaconBlockBody", _phase0_body_fields)
    ph.BeaconBlock, ph.SignedBeaconBlock = _block_types(ph.BeaconBlockBody)
    ph.BeaconState = _container("BeaconState", {
        **_state_prefix(),
        "previous_epoch_attestations": List(
            PendingAttestation, p.MAX_ATTESTATIONS * p.SLOTS_PER_EPOCH),
        "current_epoch_attestations": List(
            PendingAttestation, p.MAX_ATTESTATIONS * p.SLOTS_PER_EPOCH),
        **_justice_suffix,
    })
    ns.phase0 = ph

    # ---------------------------------------------------------------- altair
    SyncCommittee = _container("SyncCommittee", dict(
        pubkeys=Vector(Bytes48, p.SYNC_COMMITTEE_SIZE),
        aggregate_pubkey=Bytes48,
    ))
    SyncAggregate = _container("SyncAggregate", dict(
        sync_committee_bits=Bitvector(p.SYNC_COMMITTEE_SIZE),
        sync_committee_signature=Bytes96,
    ))
    SyncCommitteeMessage = _container("SyncCommitteeMessage", dict(
        slot=uint64,
        beacon_block_root=Bytes32,
        validator_index=uint64,
        signature=Bytes96,
    ))
    SyncCommitteeContribution = _container("SyncCommitteeContribution", dict(
        slot=uint64,
        beacon_block_root=Bytes32,
        subcommittee_index=uint64,
        aggregation_bits=Bitvector(p.SYNC_COMMITTEE_SIZE // 4),
        signature=Bytes96,
    ))
    ContributionAndProof = _container("ContributionAndProof", dict(
        aggregator_index=uint64,
        contribution=SyncCommitteeContribution,
        selection_proof=Bytes96,
    ))
    SignedContributionAndProof = _container(
        "SignedContributionAndProof", dict(
            message=ContributionAndProof, signature=Bytes96))
    SyncAggregatorSelectionData = _container(
        "SyncAggregatorSelectionData", dict(
            slot=uint64, subcommittee_index=uint64))

    _altair_body_fields = dict(
        **_phase0_body_fields, sync_aggregate=SyncAggregate)
    _participation = dict(
        previous_epoch_participation=List(
            uint8, p.VALIDATOR_REGISTRY_LIMIT),
        current_epoch_participation=List(uint8, p.VALIDATOR_REGISTRY_LIMIT),
    )
    _altair_state_suffix = dict(
        inactivity_scores=List(uint64, p.VALIDATOR_REGISTRY_LIMIT),
        current_sync_committee=SyncCommittee,
        next_sync_committee=SyncCommittee,
    )

    al = SimpleNamespace(
        **vars(ph),
        SyncCommittee=SyncCommittee,
        SyncAggregate=SyncAggregate,
        SyncCommitteeMessage=SyncCommitteeMessage,
        SyncCommitteeContribution=SyncCommitteeContribution,
        ContributionAndProof=ContributionAndProof,
        SignedContributionAndProof=SignedContributionAndProof,
        SyncAggregatorSelectionData=SyncAggregatorSelectionData,
    )
    al.BeaconBlockBody = _container("BeaconBlockBody", _altair_body_fields)
    al.BeaconBlock, al.SignedBeaconBlock = _block_types(al.BeaconBlockBody)
    al.BeaconState = _container("BeaconState", {
        **_state_prefix(), **_participation, **_justice_suffix,
        **_altair_state_suffix,
    })
    ns.altair = al

    # ------------------------------------------------------------- bellatrix
    Transaction = ByteList(p.MAX_BYTES_PER_TRANSACTION)
    _payload_prefix = dict(
        parent_hash=Bytes32,
        fee_recipient=Bytes20,
        state_root=Bytes32,
        receipts_root=Bytes32,
        logs_bloom=ByteVector(p.BYTES_PER_LOGS_BLOOM),
        prev_randao=Bytes32,
        block_number=uint64,
        gas_limit=uint64,
        gas_used=uint64,
        timestamp=uint64,
        extra_data=ByteList(p.MAX_EXTRA_DATA_BYTES),
        base_fee_per_gas=uint256,
        block_hash=Bytes32,
    )
    ExecutionPayload = _container("ExecutionPayload", {
        **_payload_prefix,
        "transactions": List(Transaction, p.MAX_TRANSACTIONS_PER_PAYLOAD),
    })
    ExecutionPayloadHeader = _container("ExecutionPayloadHeader", {
        **_payload_prefix, "transactions_root": Bytes32})
    PowBlock = _container("PowBlock", dict(
        block_hash=Bytes32, parent_hash=Bytes32,
        total_difficulty=uint256))

    be = SimpleNamespace(
        **vars(al),
        Transaction=Transaction,
        ExecutionPayload=ExecutionPayload,
        ExecutionPayloadHeader=ExecutionPayloadHeader,
        PowBlock=PowBlock,
    )
    be.BeaconBlockBody = _container("BeaconBlockBody", dict(
        **_altair_body_fields, execution_payload=ExecutionPayload))
    be.BlindedBeaconBlockBody = _container("BlindedBeaconBlockBody", dict(
        **_altair_body_fields, execution_payload_header=ExecutionPayloadHeader))
    be.BeaconBlock, be.SignedBeaconBlock = _block_types(be.BeaconBlockBody)
    be.BlindedBeaconBlock, be.SignedBlindedBeaconBlock = _block_types(
        be.BlindedBeaconBlockBody, "Blinded")
    be.BeaconState = _container("BeaconState", {
        **_state_prefix(), **_participation, **_justice_suffix,
        **_altair_state_suffix,
        "latest_execution_payload_header": ExecutionPayloadHeader,
    })
    ns.bellatrix = be

    # --------------------------------------------------------------- capella
    Withdrawal = _container("Withdrawal", dict(
        index=uint64, validator_index=uint64, address=Bytes20, amount=uint64))
    BLSToExecutionChange = _container("BLSToExecutionChange", dict(
        validator_index=uint64,
        from_bls_pubkey=Bytes48,
        to_execution_address=Bytes20,
    ))
    SignedBLSToExecutionChange = _container(
        "SignedBLSToExecutionChange", dict(
            message=BLSToExecutionChange, signature=Bytes96))
    HistoricalSummary = _container("HistoricalSummary", dict(
        block_summary_root=Bytes32, state_summary_root=Bytes32))

    CapellaExecutionPayload = _container("ExecutionPayload", {
        **_payload_prefix,
        "transactions": List(Transaction, p.MAX_TRANSACTIONS_PER_PAYLOAD),
        "withdrawals": List(Withdrawal, p.MAX_WITHDRAWALS_PER_PAYLOAD),
    })
    CapellaExecutionPayloadHeader = _container("ExecutionPayloadHeader", {
        **_payload_prefix,
        "transactions_root": Bytes32,
        "withdrawals_root": Bytes32,
    })

    ca = SimpleNamespace(
        **vars(be),
        Withdrawal=Withdrawal,
        BLSToExecutionChange=BLSToExecutionChange,
        SignedBLSToExecutionChange=SignedBLSToExecutionChange,
        HistoricalSummary=HistoricalSummary,
    )
    ca.ExecutionPayload = CapellaExecutionPayload
    ca.ExecutionPayloadHeader = CapellaExecutionPayloadHeader
    _capella_body_fields = dict(
        **_altair_body_fields,
        execution_payload=CapellaExecutionPayload,
        bls_to_execution_changes=List(
            SignedBLSToExecutionChange, p.MAX_BLS_TO_EXECUTION_CHANGES),
    )
    _capella_blinded_fields = dict(
        **_altair_body_fields,
        execution_payload_header=CapellaExecutionPayloadHeader,
        bls_to_execution_changes=List(
            SignedBLSToExecutionChange, p.MAX_BLS_TO_EXECUTION_CHANGES),
    )
    ca.BeaconBlockBody = _container("BeaconBlockBody", _capella_body_fields)
    ca.BlindedBeaconBlockBody = _container(
        "BlindedBeaconBlockBody", _capella_blinded_fields)
    ca.BeaconBlock, ca.SignedBeaconBlock = _block_types(ca.BeaconBlockBody)
    ca.BlindedBeaconBlock, ca.SignedBlindedBeaconBlock = _block_types(
        ca.BlindedBeaconBlockBody, "Blinded")
    _capella_state_suffix = dict(
        next_withdrawal_index=uint64,
        next_withdrawal_validator_index=uint64,
        historical_summaries=List(
            HistoricalSummary, p.HISTORICAL_ROOTS_LIMIT),
    )
    ca.BeaconState = _container("BeaconState", {
        **_state_prefix(), **_participation, **_justice_suffix,
        **_altair_state_suffix,
        "latest_execution_payload_header": CapellaExecutionPayloadHeader,
        **_capella_state_suffix,
    })
    ns.capella = ca

    # ----------------------------------------------------------------- deneb
    KZGCommitment = Bytes48
    KZGProof = Bytes48
    Blob = ByteVector(BYTES_PER_FIELD_ELEMENT * p.FIELD_ELEMENTS_PER_BLOB)

    DenebExecutionPayload = _container("ExecutionPayload", {
        **_payload_prefix,
        "transactions": List(Transaction, p.MAX_TRANSACTIONS_PER_PAYLOAD),
        "withdrawals": List(Withdrawal, p.MAX_WITHDRAWALS_PER_PAYLOAD),
        "blob_gas_used": uint64,
        "excess_blob_gas": uint64,
    })
    DenebExecutionPayloadHeader = _container("ExecutionPayloadHeader", {
        **_payload_prefix,
        "transactions_root": Bytes32,
        "withdrawals_root": Bytes32,
        "blob_gas_used": uint64,
        "excess_blob_gas": uint64,
    })

    de = SimpleNamespace(**vars(ca))
    de.KZGCommitment = KZGCommitment
    de.KZGProof = KZGProof
    de.Blob = Blob
    de.ExecutionPayload = DenebExecutionPayload
    de.ExecutionPayloadHeader = DenebExecutionPayloadHeader
    _deneb_common = dict(
        bls_to_execution_changes=List(
            SignedBLSToExecutionChange, p.MAX_BLS_TO_EXECUTION_CHANGES),
        blob_kzg_commitments=List(
            KZGCommitment, p.MAX_BLOB_COMMITMENTS_PER_BLOCK),
    )
    de.BeaconBlockBody = _container("BeaconBlockBody", dict(
        **_altair_body_fields,
        execution_payload=DenebExecutionPayload,
        **_deneb_common,
    ))
    de.BlindedBeaconBlockBody = _container("BlindedBeaconBlockBody", dict(
        **_altair_body_fields,
        execution_payload_header=DenebExecutionPayloadHeader,
        **_deneb_common,
    ))
    de.BeaconBlock, de.SignedBeaconBlock = _block_types(de.BeaconBlockBody)
    de.BlindedBeaconBlock, de.SignedBlindedBeaconBlock = _block_types(
        de.BlindedBeaconBlockBody, "Blinded")
    de.BeaconState = _container("BeaconState", {
        **_state_prefix(), **_participation, **_justice_suffix,
        **_altair_state_suffix,
        "latest_execution_payload_header": DenebExecutionPayloadHeader,
        **_capella_state_suffix,
    })
    de.BlobIdentifier = _container("BlobIdentifier", dict(
        block_root=Bytes32, index=uint64))
    de.BlobSidecar = _container("BlobSidecar", dict(
        index=uint64,
        blob=Blob,
        kzg_commitment=KZGCommitment,
        kzg_proof=KZGProof,
        signed_block_header=SignedBeaconBlockHeader,
        kzg_commitment_inclusion_proof=Vector(
            Bytes32, p.KZG_COMMITMENT_INCLUSION_PROOF_DEPTH),
    ))
    ns.deneb = de

    return ns


_CACHE: dict = {}


def spec_types(preset: Preset) -> SimpleNamespace:
    """All fork namespaces for `preset` (cached — container classes are
    identity-compared by the SSZ layer)."""
    hit = _CACHE.get(preset.name)
    if hit is None:
        hit = _build(preset)
        _CACHE[preset.name] = hit
    return hit
