"""Primitive aliases, fork enum, and spec constants.

Reference parity: types/src/primitives.rs (Slot/Epoch/Gwei/... aliases) and
the domain-type constants used by helper_functions/src/signing.rs.
"""

import enum

# SSZ-level aliases (values are plain ints/bytes; these names document
# intent at call sites, mirroring types/src/primitives.rs)
Slot = int
Epoch = int
CommitteeIndex = int
ValidatorIndex = int
Gwei = int
Root = bytes       # 32
Hash32 = bytes     # 32
BLSPubkey = bytes  # 48 compressed
BLSSignature = bytes  # 96 compressed
DomainType = bytes  # 4
Domain = bytes     # 32
Version = bytes    # 4

GENESIS_SLOT = 0
GENESIS_EPOCH = 0
FAR_FUTURE_EPOCH = 2**64 - 1

DEPOSIT_CONTRACT_TREE_DEPTH = 32
JUSTIFICATION_BITS_LENGTH = 4
ENDIANNESS = "little"

BLS_WITHDRAWAL_PREFIX = b"\x00"
ETH1_ADDRESS_WITHDRAWAL_PREFIX = b"\x01"

DOMAIN_BEACON_PROPOSER = bytes.fromhex("00000000")
DOMAIN_BEACON_ATTESTER = bytes.fromhex("01000000")
DOMAIN_RANDAO = bytes.fromhex("02000000")
DOMAIN_DEPOSIT = bytes.fromhex("03000000")
DOMAIN_VOLUNTARY_EXIT = bytes.fromhex("04000000")
DOMAIN_SELECTION_PROOF = bytes.fromhex("05000000")
DOMAIN_AGGREGATE_AND_PROOF = bytes.fromhex("06000000")
DOMAIN_SYNC_COMMITTEE = bytes.fromhex("07000000")
DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF = bytes.fromhex("08000000")
DOMAIN_CONTRIBUTION_AND_PROOF = bytes.fromhex("09000000")
DOMAIN_BLS_TO_EXECUTION_CHANGE = bytes.fromhex("0A000000")
DOMAIN_APPLICATION_MASK = bytes.fromhex("00000001")

# altair sync-committee aggregation (p2p spec constant: target number of
# aggregators electing themselves per subcommittee per slot)
TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE = 16

# altair participation flag indices
TIMELY_SOURCE_FLAG_INDEX = 0
TIMELY_TARGET_FLAG_INDEX = 1
TIMELY_HEAD_FLAG_INDEX = 2
TIMELY_SOURCE_WEIGHT = 14
TIMELY_TARGET_WEIGHT = 26
TIMELY_HEAD_WEIGHT = 14
SYNC_REWARD_WEIGHT = 2
PROPOSER_WEIGHT = 8
WEIGHT_DENOMINATOR = 64

PARTICIPATION_FLAG_WEIGHTS = (
    TIMELY_SOURCE_WEIGHT, TIMELY_TARGET_WEIGHT, TIMELY_HEAD_WEIGHT)


class Phase(enum.IntEnum):
    """Fork phases, ordered (types/src/combined.rs fork enums)."""

    PHASE0 = 0
    ALTAIR = 1
    BELLATRIX = 2
    CAPELLA = 3
    DENEB = 4

    @property
    def key(self) -> str:
        return self.name.lower()
