"""Spec types: presets, chain config, and per-fork SSZ containers.

Equivalent of the reference `types` crate (types/src/{preset.rs,config.rs,
phase0,altair,bellatrix,capella,deneb,combined.rs}).

Usage:
    from grandine_tpu.types import MAINNET, MINIMAL, spec_types, Phase
    T = spec_types(MAINNET)          # container classes for every fork
    state = T.phase0.BeaconState(...)
    block = T.deneb.SignedBeaconBlock(...)
"""

from grandine_tpu.types.preset import MAINNET, MINIMAL, Preset
from grandine_tpu.types.config import Config
from grandine_tpu.types.primitives import (
    DOMAIN_AGGREGATE_AND_PROOF,
    DOMAIN_APPLICATION_MASK,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_BLS_TO_EXECUTION_CHANGE,
    DOMAIN_CONTRIBUTION_AND_PROOF,
    DOMAIN_DEPOSIT,
    DOMAIN_RANDAO,
    DOMAIN_SELECTION_PROOF,
    DOMAIN_SYNC_COMMITTEE,
    DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
    DOMAIN_VOLUNTARY_EXIT,
    FAR_FUTURE_EPOCH,
    GENESIS_EPOCH,
    GENESIS_SLOT,
    Phase,
)
from grandine_tpu.types.containers import spec_types

__all__ = [
    "MAINNET", "MINIMAL", "Preset", "Config", "Phase", "spec_types",
    "FAR_FUTURE_EPOCH", "GENESIS_EPOCH", "GENESIS_SLOT",
    "DOMAIN_BEACON_PROPOSER", "DOMAIN_BEACON_ATTESTER", "DOMAIN_RANDAO",
    "DOMAIN_DEPOSIT", "DOMAIN_VOLUNTARY_EXIT", "DOMAIN_SELECTION_PROOF",
    "DOMAIN_AGGREGATE_AND_PROOF", "DOMAIN_SYNC_COMMITTEE",
    "DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF", "DOMAIN_CONTRIBUTION_AND_PROOF",
    "DOMAIN_BLS_TO_EXECUTION_CHANGE", "DOMAIN_APPLICATION_MASK",
]
