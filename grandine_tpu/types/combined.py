"""Fork-combined type dispatch — reference: types/src/combined.rs (enums
over the per-fork BeaconState/SignedBeaconBlock with phase-aware SSZ
decode) consumed by storage, the HTTP API, and networking.

A value's concrete container class is chosen by its phase; the phase comes
from the chain config (by slot/epoch) or from the value itself (a state's
fork version). Decoding is therefore `(bytes, cfg[, slot]) -> container`.
"""

from __future__ import annotations

from typing import Optional

from grandine_tpu.types.config import Config
from grandine_tpu.types.containers import spec_types
from grandine_tpu.types.primitives import Phase


def fork_namespace(cfg: Config, phase: Phase):
    return getattr(spec_types(cfg.preset), phase.key)


def state_phase_of(state, cfg: Config) -> Phase:
    """Phase of a state container (by its fork's current version)."""
    version = bytes(state.fork.current_version)
    for phase in reversed(list(Phase)):
        if cfg.fork_version(phase) == version:
            return phase
    raise ValueError(f"unknown fork version {version.hex()}")


def block_phase_of(signed_block, cfg: Config) -> Phase:
    return cfg.phase_at_slot(int(signed_block.message.slot))


# --- SSZ decode with fork dispatch -----------------------------------------

# A serialized BeaconState starts with genesis_time (8) +
# genesis_validators_root (32) + slot (8) + fork: previous_version (4) +
# current_version (4) — the current version at fixed offset 52.
_STATE_VERSION_OFFSET = 48 + 4


def decode_state(data: bytes, cfg: Config):
    """Deserialize a BeaconState of any fork: the fork version is read
    from its fixed offset, then the phase's container class decodes
    (combined.rs `BeaconState::from_ssz`)."""
    data = bytes(data)
    if len(data) < _STATE_VERSION_OFFSET + 4:
        raise ValueError("state payload too short")
    version = data[_STATE_VERSION_OFFSET : _STATE_VERSION_OFFSET + 4]
    for phase in reversed(list(Phase)):
        if cfg.fork_version(phase) == version:
            return fork_namespace(cfg, phase).BeaconState.deserialize(data)
    raise ValueError(f"unknown fork version {version.hex()}")


# A SignedBeaconBlock is [offset(4) | signature(96) | message…]; the
# message starts with its slot.
_BLOCK_SLOT_OFFSET = 4 + 96


def decode_signed_block(data: bytes, cfg: Config,
                        slot: "Optional[int]" = None):
    """Deserialize a SignedBeaconBlock of any fork; the phase comes from
    the block's own slot (read at its fixed offset) unless given."""
    data = bytes(data)
    if slot is None:
        if len(data) < _BLOCK_SLOT_OFFSET + 8:
            raise ValueError("block payload too short")
        slot = int.from_bytes(
            data[_BLOCK_SLOT_OFFSET : _BLOCK_SLOT_OFFSET + 8], "little"
        )
    phase = cfg.phase_at_slot(slot)
    return fork_namespace(cfg, phase).SignedBeaconBlock.deserialize(data)


def decode_attestation(data: bytes, cfg: Config, slot: int):
    phase = cfg.phase_at_slot(slot)
    return fork_namespace(cfg, phase).Attestation.deserialize(data)


def decode_signed_aggregate(data: bytes, cfg: Config, slot: int):
    phase = cfg.phase_at_slot(slot)
    return fork_namespace(cfg, phase).SignedAggregateAndProof.deserialize(data)


__all__ = [
    "fork_namespace",
    "state_phase_of",
    "block_phase_of",
    "decode_state",
    "decode_signed_block",
    "decode_attestation",
    "decode_signed_aggregate",
]
