"""Builder API (MEV relay client) — reference: `builder_api` crate
(builder_api/src/api.rs: get execution payload header / submit blinded
block, circuit-breaker config.rs).

The HTTP boundary is an injected `relay` callable (like the eth1 fetcher
and checkpoint-sync seams); the circuit breaker, bid validation, and
blinded-block flow are real. A relay for tests just returns header dicts.
"""

from __future__ import annotations

import time
from typing import Callable, Optional


class BuilderApiError(Exception):
    pass


class BuilderConfig:
    """Circuit-breaker knobs (builder_api/src/config.rs)."""

    def __init__(
        self,
        max_skipped_slots: int = 3,
        max_skipped_slots_per_epoch: int = 8,
        request_timeout_s: float = 1.0,
    ) -> None:
        self.max_skipped_slots = max_skipped_slots
        self.max_skipped_slots_per_epoch = max_skipped_slots_per_epoch
        self.request_timeout_s = request_timeout_s


class BuilderApi:
    """get_header / submit_blinded_block against an injected relay, with
    the reference's missed-slot circuit breaker: when the chain recently
    skipped slots, stop asking the relay and fall back to local building."""

    def __init__(self, relay: "Callable[[str, dict], dict]",
                 cfg: "Optional[BuilderConfig]" = None,
                 chain_config=None,
                 relay_pubkey: "Optional[bytes]" = None) -> None:
        self.relay = relay
        self.cfg = cfg or BuilderConfig()
        # chain config enables bid signature verification; without it the
        # relay is trusted (test seams only — the node always passes one).
        # relay_pubkey additionally PINS the builder identity (mev-boost
        # style): bids from any other key are rejected — without a pin the
        # signature check provides integrity against corruption but a
        # malicious relay can sign with its own throwaway key.
        self.chain_config = chain_config
        self.relay_pubkey = bytes(relay_pubkey) if relay_pubkey else None
        self.stats = {"headers": 0, "submissions": 0, "circuit_breaks": 0}

    # -- circuit breaker ----------------------------------------------------

    def can_use_builder(self, controller, slot: int, slots_per_epoch: int) -> bool:
        """False when recent missed slots exceed the breaker thresholds
        (builder_api/src/api.rs circuit breaker)."""
        store = self.controller_store(controller)
        produced = {n.slot for n in store.blocks.values()}
        recent = range(max(0, slot - self.cfg.max_skipped_slots), slot)
        if sum(1 for s in recent if s not in produced) >= self.cfg.max_skipped_slots:
            self.stats["circuit_breaks"] += 1
            return False
        epoch_window = range(max(0, slot - slots_per_epoch), slot)
        missed = sum(1 for s in epoch_window if s not in produced)
        if missed >= self.cfg.max_skipped_slots_per_epoch:
            self.stats["circuit_breaks"] += 1
            return False
        return True

    @staticmethod
    def controller_store(controller):
        return controller.store

    # -- relay calls --------------------------------------------------------

    def get_execution_payload_header(
        self, slot: int, parent_hash: bytes, pubkey: bytes, ns=None
    ) -> dict:
        """builder-specs getHeader: returns the relay's bid
        {header: {...}, value: int, pubkey: hex, signature: hex}.

        When a chain config was provided, the relay's SignedBuilderBid is
        verified against its embedded builder pubkey before the header is
        trusted (reference builder_api/src/api.rs:168-185); `ns` is the
        per-phase spec-types namespace used to reconstruct the header's
        hash tree root."""
        bid = self.relay("get_header", {
            "slot": slot,
            "parent_hash": bytes(parent_hash).hex(),
            "pubkey": bytes(pubkey).hex(),
        })
        bid = self._flatten_bid(bid)
        if not isinstance(bid, dict) or "header" not in bid:
            raise BuilderApiError("malformed bid")
        bid_parent = str(bid["header"].get("parent_hash", "")).removeprefix(
            "0x"
        )
        if bid_parent != bytes(parent_hash).hex():
            raise BuilderApiError("bid parent hash mismatch")
        if self.chain_config is not None:
            if ns is None:
                raise BuilderApiError(
                    "bid verification requires the spec-types namespace"
                )
            self._verify_bid(bid, ns)
        self.stats["headers"] += 1
        return bid

    @staticmethod
    def _flatten_bid(bid):
        """Normalize a builder-specs GetHeaderResponse — possibly nested as
        {version, data: {message: {header, value, pubkey, …},
        signature}} — into the flat {header, value, pubkey, signature}
        shape the rest of this class speaks."""
        if not isinstance(bid, dict):
            return bid
        inner = bid.get("data", bid)
        if isinstance(inner, dict) and "message" in inner:
            flat = dict(inner["message"])
            if "signature" in inner:
                flat["signature"] = inner["signature"]
            return flat
        return inner

    def _verify_bid(self, bid: dict, ns) -> None:
        """Reject a bid whose BuilderBid signature does not verify against
        the builder pubkey it carries (builder_api/src/api.rs:168-185)."""
        from grandine_tpu.crypto.bls import PublicKey, Signature
        from grandine_tpu.validator.blinded import (
            builder_bid_signing_root,
            header_from_bid,
        )

        try:
            builder_pk = bytes.fromhex(
                str(bid["pubkey"]).removeprefix("0x")
            )
            sig_bytes = bytes.fromhex(
                str(bid["signature"]).removeprefix("0x")
            )
        except (KeyError, ValueError) as e:
            raise BuilderApiError(f"bid missing pubkey/signature: {e!r}")
        if self.relay_pubkey is not None and builder_pk != self.relay_pubkey:
            raise BuilderApiError("bid signed by unpinned builder pubkey")
        # the bid container's shape is a property of the FORK, not of the
        # relay's JSON: deneb+ bids sign over blob_kzg_commitments
        # (builder_api/src/deneb/containers.rs), earlier forks do not
        deneb_shape = any(
            name == "blob_kzg_commitments"
            for name, _ in ns.BeaconBlockBody.FIELDS
        )
        try:
            if deneb_shape:
                commitments = [
                    bytes.fromhex(str(c).removeprefix("0x"))
                    for c in bid.get("blob_kzg_commitments", [])
                ]
            else:
                commitments = None
            header = header_from_bid(ns, bid["header"])
            value = int(bid["value"])
            pk = PublicKey.from_bytes(builder_pk)
            sig = Signature.from_bytes(sig_bytes)
            root = builder_bid_signing_root(
                header, value, builder_pk,
                self.chain_config, blob_kzg_commitments=commitments,
            )
        except (KeyError, ValueError, TypeError) as e:
            raise BuilderApiError(f"undecodable bid: {e!r}")
        if not sig.verify(root, pk):
            raise BuilderApiError("bid signature verification failed")

    def submit_blinded_block(self, signed_blinded_block) -> dict:
        """builder-specs submitBlindedBlock: relay unblinds and returns the
        full payload."""
        payload = self.relay("submit_blinded_block", {
            "ssz": signed_blinded_block.serialize().hex(),
        })
        if not isinstance(payload, dict) or "execution_payload" not in payload:
            raise BuilderApiError("relay did not return a payload")
        self.stats["submissions"] += 1
        return payload


__all__ = ["BuilderApi", "BuilderApiError", "BuilderConfig"]
