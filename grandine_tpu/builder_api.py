"""Builder API (MEV relay client) — reference: `builder_api` crate
(builder_api/src/api.rs: get execution payload header / submit blinded
block, circuit-breaker config.rs).

The HTTP boundary is an injected `relay` callable (like the eth1 fetcher
and checkpoint-sync seams); the circuit breaker, bid validation, and
blinded-block flow are real. A relay for tests just returns header dicts.
"""

from __future__ import annotations

import time
from typing import Callable, Optional


class BuilderApiError(Exception):
    pass


class BuilderConfig:
    """Circuit-breaker knobs (builder_api/src/config.rs)."""

    def __init__(
        self,
        max_skipped_slots: int = 3,
        max_skipped_slots_per_epoch: int = 8,
        request_timeout_s: float = 1.0,
    ) -> None:
        self.max_skipped_slots = max_skipped_slots
        self.max_skipped_slots_per_epoch = max_skipped_slots_per_epoch
        self.request_timeout_s = request_timeout_s


class BuilderApi:
    """get_header / submit_blinded_block against an injected relay, with
    the reference's missed-slot circuit breaker: when the chain recently
    skipped slots, stop asking the relay and fall back to local building."""

    def __init__(self, relay: "Callable[[str, dict], dict]",
                 cfg: "Optional[BuilderConfig]" = None) -> None:
        self.relay = relay
        self.cfg = cfg or BuilderConfig()
        self.stats = {"headers": 0, "submissions": 0, "circuit_breaks": 0}

    # -- circuit breaker ----------------------------------------------------

    def can_use_builder(self, controller, slot: int, slots_per_epoch: int) -> bool:
        """False when recent missed slots exceed the breaker thresholds
        (builder_api/src/api.rs circuit breaker)."""
        store = self.controller_store(controller)
        produced = {n.slot for n in store.blocks.values()}
        recent = range(max(0, slot - self.cfg.max_skipped_slots), slot)
        if sum(1 for s in recent if s not in produced) >= self.cfg.max_skipped_slots:
            self.stats["circuit_breaks"] += 1
            return False
        epoch_window = range(max(0, slot - slots_per_epoch), slot)
        missed = sum(1 for s in epoch_window if s not in produced)
        if missed >= self.cfg.max_skipped_slots_per_epoch:
            self.stats["circuit_breaks"] += 1
            return False
        return True

    @staticmethod
    def controller_store(controller):
        return controller.store

    # -- relay calls --------------------------------------------------------

    def get_execution_payload_header(
        self, slot: int, parent_hash: bytes, pubkey: bytes
    ) -> dict:
        """builder-specs getHeader: returns the relay's bid
        {header: {...}, value: int}."""
        bid = self.relay("get_header", {
            "slot": slot,
            "parent_hash": bytes(parent_hash).hex(),
            "pubkey": bytes(pubkey).hex(),
        })
        if not isinstance(bid, dict) or "header" not in bid:
            raise BuilderApiError("malformed bid")
        bid_parent = str(bid["header"].get("parent_hash", "")).removeprefix(
            "0x"
        )
        if bid_parent != bytes(parent_hash).hex():
            raise BuilderApiError("bid parent hash mismatch")
        self.stats["headers"] += 1
        return bid

    def submit_blinded_block(self, signed_blinded_block) -> dict:
        """builder-specs submitBlindedBlock: relay unblinds and returns the
        full payload."""
        payload = self.relay("submit_blinded_block", {
            "ssz": signed_blinded_block.serialize().hex(),
        })
        if not isinstance(payload, dict) or "execution_payload" not in payload:
            raise BuilderApiError("relay did not return a payload")
        self.stats["submissions"] += 1
        return payload


__all__ = ["BuilderApi", "BuilderApiError", "BuilderConfig"]
