"""Core primitives: hashing, shuffling, math helpers (reference layer 0)."""
