"""Swap-or-not shuffle — equivalent of the reference `shuffling` crate
(shuffling/src/lib.rs:14-50: whole-list optimized variant of the spec's
`compute_shuffled_index`).

The whole-list path is vectorized with numpy: per round, the pivot/flip/
decision-bit computation for all n indices is array arithmetic plus
ceil(n/256) SHA-256 calls, instead of the spec's per-index loop.
"""

from __future__ import annotations

import hashlib

import numpy as np


def _round_bits(seed: bytes, round_: int, n: int) -> np.ndarray:
    """Decision bit for every position 0..n-1 in one round."""
    n_sources = (n + 255) // 256
    buf = bytearray()
    rb = bytes([round_])
    for j in range(n_sources):
        buf += hashlib.sha256(seed + rb + j.to_bytes(4, "little")).digest()
    bits = np.unpackbits(np.frombuffer(bytes(buf), np.uint8),
                         bitorder="little")
    return bits[:n]


def shuffled_indices(seed: bytes, n: int, rounds: int = 90) -> np.ndarray:
    """sigma such that shuffled[pos] = items[sigma[pos]] matches the spec's
    `indices[compute_shuffled_index(pos)]` committee selection."""
    cur = np.arange(n, dtype=np.int64)
    if n <= 1:
        return cur
    for r in range(rounds):
        pivot = int.from_bytes(
            hashlib.sha256(seed + bytes([r])).digest()[:8], "little") % n
        bits = _round_bits(seed, r, n)
        flip = (pivot - cur) % n
        pos = np.maximum(cur, flip)
        cur = np.where(bits[pos] == 1, flip, cur)
    return cur


def shuffle_list(items: np.ndarray, seed: bytes, rounds: int = 90) -> np.ndarray:
    """Return the shuffled copy of `items`."""
    return np.asarray(items)[shuffled_indices(seed, len(items), rounds)]


def compute_shuffled_index(index: int, n: int, seed: bytes,
                           rounds: int = 90) -> int:
    """Spec-literal single-index variant (consensus-specs
    `compute_shuffled_index`), kept as the correctness anchor for the
    vectorized path."""
    assert 0 <= index < n
    for r in range(rounds):
        pivot = int.from_bytes(
            hashlib.sha256(seed + bytes([r])).digest()[:8], "little") % n
        flip = (pivot + n - index) % n
        position = max(index, flip)
        source = hashlib.sha256(
            seed + bytes([r]) + (position // 256).to_bytes(4, "little")
        ).digest()
        byte = source[(position % 256) // 8]
        if (byte >> (position % 8)) & 1:
            index = flip
    return index
