"""SSZ hashing primitives — equivalent of the reference `hashing` crate
(hashing/src/lib.rs:10-60: sha2-with-asm fast paths + precomputed
`ZERO_HASHES` zero-subtree roots).

Hot loops route to the C++ native extension (grandine_tpu.native, SHA-NI)
when built; every function has a hashlib fallback so the framework runs
anywhere.
"""

from __future__ import annotations

import hashlib

from grandine_tpu import native

H256 = bytes  # 32-byte root
ZERO_H256 = b"\x00" * 32

MAX_DEPTH = 64


def _zero_hashes() -> list[bytes]:
    out = [ZERO_H256]
    for _ in range(MAX_DEPTH):
        out.append(hashlib.sha256(out[-1] + out[-1]).digest())
    return out


#: ZERO_HASHES[i] = root of a depth-i subtree of zero chunks
#: (reference: hashing/src/lib.rs ZERO_HASHES[41]; we precompute to 64).
ZERO_HASHES: list[bytes] = _zero_hashes()


def hash_bytes(data: bytes) -> bytes:
    """Plain SHA-256."""
    return hashlib.sha256(data).digest()


def hash_pair(a: bytes, b: bytes) -> bytes:
    """Parent node of two 32-byte children."""
    return hashlib.sha256(a + b).digest()


def hash_pairs(data: bytes | bytearray) -> bytes:
    """N concatenated 64-byte pairs -> N concatenated 32-byte parents."""
    if len(data) % 64:
        raise ValueError(f"hash_pairs input must be 64-byte pairs, got {len(data)}")
    n = len(data) // 64
    if native.lib is not None and n >= 4:
        out = native.out_buf(n * 32)
        native.lib.gt_hash_pairs(bytes(data), n, out)
        return out.raw[: n * 32]
    sha = hashlib.sha256
    return b"".join(
        sha(data[64 * i : 64 * i + 64]).digest() for i in range(n)
    )


def merkleize_chunks(chunks: bytes | bytearray, limit: int | None = None) -> bytes:
    """Merkleize 32-byte chunks (SSZ `merkleize`): pad virtually with zero
    chunks to `limit` leaves (or next power of two of the chunk count) and
    return the root."""
    if len(chunks) % 32:
        raise ValueError(f"chunks must be 32-byte aligned, got {len(chunks)}")
    n = len(chunks) // 32
    if limit is None:
        limit = max(n, 1)
    elif n > limit:
        raise ValueError(f"{n} chunks exceed merkleization limit {limit}")
    depth = (limit - 1).bit_length() if limit > 1 else 0
    if n == 0:
        return ZERO_HASHES[depth]
    if native.lib is not None and n >= 2:
        out = native.out_buf(32)
        if native.lib.gt_merkleize(bytes(chunks), n, depth, out):
            return out.raw[:32]
    return _merkleize_py(bytes(chunks), n, depth)


def _merkleize_py(chunks: bytes, n: int, depth: int) -> bytes:
    level = [chunks[32 * i : 32 * i + 32] for i in range(n)]
    for d in range(depth):
        if len(level) == 1:
            level = [hash_pair(level[0], ZERO_HASHES[d])]
            continue
        if len(level) % 2:
            level.append(ZERO_HASHES[d])
        level = [
            hash_pair(level[i], level[i + 1]) for i in range(0, len(level), 2)
        ]
    return level[0]


def merkleize_many(chunks: bytes, n_items: int, chunks_per_item: int,
                   depth: int) -> bytes:
    """Batch-merkleize `n_items` independent fixed-shape subtrees laid out
    contiguously (`chunks_per_item` 32-byte chunks each) to height `depth`.
    Returns the concatenated 32-byte roots. This is the validator-registry
    hot path: one native call per 50k-item registry."""
    if len(chunks) != n_items * chunks_per_item * 32:
        raise ValueError(
            f"chunks length {len(chunks)} != {n_items}*{chunks_per_item}*32")
    if chunks_per_item > (1 << depth):
        raise ValueError(f"{chunks_per_item} chunks do not fit depth {depth}")
    if native.lib is not None and n_items >= 2:
        out = native.out_buf(n_items * 32)
        if native.lib.gt_merkleize_many(
                chunks, n_items, chunks_per_item, depth, out):
            return out.raw[: n_items * 32]
    stride = chunks_per_item * 32
    return b"".join(
        _merkleize_py(chunks[i * stride : (i + 1) * stride],
                      chunks_per_item, depth)
        for i in range(n_items)
    )


def mix_in_length(root: bytes, length: int) -> bytes:
    """hash(root ++ uint256_le(length)) — SSZ list length mixin."""
    return hash_pair(root, length.to_bytes(32, "little"))


mix_in_selector = mix_in_length  # SSZ union selector mixin, same shape
