"""Fork choice — reference: `fork_choice_store` (pure in-memory state
machine, fork_choice_store/src/lib.rs) + `fork_choice_control` (threading/
persistence orchestration).

`store.py` is the pure half: LMD-GHOST + Casper FFG with the reference's
validate_*/apply_* split (immutable, parallel-safe validation vs
mutator-only application). The controller/runtime wiring lives in
grandine_tpu.runtime.
"""

from grandine_tpu.fork_choice.store import (  # noqa: F401
    ForkChoiceError,
    Store,
    Tick,
    TickKind,
)
