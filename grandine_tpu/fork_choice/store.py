"""Pure fork-choice store: LMD-GHOST + Casper FFG.

Reference: fork_choice_store/src/store.rs — the split between `validate_*`
(immutable, runs the expensive work: full state transition, signature
batches; safe to run on many threads/tasks in parallel, store.rs:925,1013)
and `apply_*` (cheap, mutator-only DAG/checkpoint updates, store.rs:1784,
1860,2022). The controller (grandine_tpu.runtime) owns one Store and feeds
it applications from a single thread, exactly like the reference's mutator
actor.

Fork-choice semantics implemented (ethereum consensus spec, deneb-era):
  - LMD-GHOST head with effective-balance weights from the justified state
  - pull-up justification: a block's *unrealized* justification (running
    the justification calculation on its post-state) updates checkpoints
    immediately for blocks from prior epochs
  - proposer boost for timely blocks, reset every slot
  - equivocating validators (attester slashings) excluded from weights
  - attestation validity windows (target epoch current/previous, one-slot
    gossip delay for non-block attestations)
  - pruning at finalization

Weight accumulation is vectorized: latest messages are numpy columns
validator -> (epoch, block root) maps folded into per-block weights,
then a bottom-up subtree sum over the (small) block DAG; spec
filter_block_tree viability (voting-source / finalized consistency)
restricts which branches may win.
"""

from __future__ import annotations

import enum
from typing import Optional, Sequence

import numpy as np

from grandine_tpu.consensus import accessors, misc
from grandine_tpu.consensus.verifier import (
    MultiVerifier,
    SignatureInvalid,
    Verifier,
)
from grandine_tpu.execution import PayloadStatus
from grandine_tpu.transition.combined import custom_state_transition
from grandine_tpu.transition.fork_upgrade import state_phase
from grandine_tpu.transition.slots import process_slots
from grandine_tpu.types.primitives import Phase

ZERO32 = b"\x00" * 32
INTERVALS_PER_SLOT = 3


class ForkChoiceError(ValueError):
    pass


class TickKind(enum.IntEnum):
    """3 ticks per slot (reference clock crate: Propose/Attest/Aggregate)."""

    PROPOSE = 0
    ATTEST = 1
    AGGREGATE = 2


class Tick:
    __slots__ = ("slot", "kind")

    def __init__(self, slot: int, kind: TickKind) -> None:
        self.slot = slot
        self.kind = kind

    def __repr__(self) -> str:
        return f"Tick({self.slot}, {self.kind.name})"


class BlockNode:
    """One block in the DAG."""

    __slots__ = (
        "root",
        "signed_block",
        "state",
        "parent_root",
        "slot",
        "unrealized_justified",
        "unrealized_finalized",
        "optimistic",
        "execution_block_hash",
    )

    def __init__(self, root, signed_block, state,
                 unrealized_justified, unrealized_finalized,
                 optimistic: bool = False) -> None:
        self.root = root
        self.signed_block = signed_block
        self.state = state
        self.parent_root = bytes(signed_block.message.parent_root)
        self.slot = int(signed_block.message.slot)
        self.unrealized_justified = unrealized_justified
        self.unrealized_finalized = unrealized_finalized
        # optimistic-sync bookkeeping (fork_choice_control/src/controller.rs
        # :236-247): True while the EL has not yet judged this payload
        self.optimistic = optimistic
        body = getattr(signed_block.message, "body", None)
        payload = getattr(body, "execution_payload", None) if body else None
        self.execution_block_hash = (
            bytes(payload.block_hash) if payload is not None else None
        )


class ValidBlock:
    """Result of validate_block, ready for apply_block."""

    __slots__ = ("signed_block", "root", "state", "is_timely", "optimistic")

    def __init__(self, signed_block, root, state, is_timely,
                 optimistic: bool = False) -> None:
        self.signed_block = signed_block
        self.root = root
        self.state = state
        self.is_timely = is_timely
        # imported before the EL judged the payload (SYNCING/ACCEPTED)
        self.optimistic = optimistic


class _RecordingEngine:
    """Engine proxy capturing the last notify_new_payload verdict during a
    single validate_block (the verdict decides optimistic marking)."""

    __slots__ = ("inner", "last_status")

    def __init__(self, inner) -> None:
        self.inner = inner
        self.last_status = None

    def notify_new_payload(self, payload):
        self.last_status = self.inner.notify_new_payload(payload)
        return self.last_status

    def notify_forkchoice_updated(self, *args, **kwargs):
        return self.inner.notify_forkchoice_updated(*args, **kwargs)

    def allow_optimistic_import(self) -> bool:
        return self.inner.allow_optimistic_import()


class ValidAttestation:
    __slots__ = ("indices", "epoch", "beacon_block_root", "earliest_slot")

    def __init__(self, indices, epoch, beacon_block_root,
                 earliest_slot: int = 0) -> None:
        self.indices = indices
        self.epoch = epoch
        self.beacon_block_root = beacon_block_root
        # first store slot at which this vote may count (spec: an
        # attestation for slot S only enters fork choice from S+1; the
        # controller delays application until then — mutator.rs
        # delayed_until_slot)
        self.earliest_slot = earliest_slot


def unrealized_checkpoints(state, cfg):
    """Run ONLY the justification/finalization calculation on `state`
    (spec compute_pulled_up_tip / process_justification_and_finalization
    without committing the rest of epoch processing)."""
    from grandine_tpu.consensus.mutators import StateDraft
    from grandine_tpu.transition import epoch_altair, epoch_phase0

    draft = StateDraft(state, cfg)
    if state_phase(state, cfg) == Phase.PHASE0:
        epoch_phase0.process_justification_and_finalization(draft)
    else:
        epoch_altair.process_justification_and_finalization(draft)
    fields = object.__getattribute__(draft, "_fields")
    justified = fields.get(
        "current_justified_checkpoint", state.current_justified_checkpoint
    )
    finalized = fields.get("finalized_checkpoint", state.finalized_checkpoint)
    return justified, finalized


class Store:
    """The pure fork-choice state machine. NOT thread-safe for mutation:
    all apply_* calls must come from one mutator (the reference's actor
    model); validate_* methods touch no mutable state."""

    def __init__(self, anchor_state, cfg, anchor_block=None,
                 execution_engine=None) -> None:
        from grandine_tpu.execution import NullExecutionEngine

        self.cfg = cfg
        self.p = cfg.preset
        self.execution_engine = execution_engine or NullExecutionEngine()

        header = anchor_state.latest_block_header
        if bytes(header.state_root) == ZERO32:
            header = header.replace(state_root=anchor_state.hash_tree_root())
        anchor_root = header.hash_tree_root()

        self.anchor_root = anchor_root
        self.blocks: "dict[bytes, BlockNode]" = {}
        self.children: "dict[bytes, list[bytes]]" = {}

        anchor_epoch = accessors.get_current_epoch(anchor_state, self.p)
        Checkpoint = type(anchor_state.finalized_checkpoint)
        anchor_cp = Checkpoint(epoch=anchor_epoch, root=anchor_root)
        self.justified_checkpoint = anchor_cp
        self.finalized_checkpoint = anchor_cp
        self.justified_state = anchor_state
        # best unrealized checkpoints over all applied blocks, promoted at
        # epoch boundaries (spec store.unrealized_* + on_tick pull-up)
        self.unrealized_justified = anchor_cp
        self.unrealized_finalized = anchor_cp

        node = BlockNode(
            anchor_root,
            _AnchorBlock(header),
            anchor_state,
            anchor_cp,
            anchor_cp,
        )
        self.blocks[anchor_root] = node
        self.children[anchor_root] = []

        # latest messages, COLUMNAR (one row per validator index): the
        # 50k-scale get_head weight pass is a single np.bincount over these
        # instead of a Python dict walk (reference keeps incremental
        # segment weights — fork_choice_store/src/store.rs; here the
        # columnar pass is ≲ms at 50k so recompute-per-head stays simple).
        # Roots are interned to small ints (_id_roots) so the columns stay
        # fixed-width int32/int64.
        self._lm_epoch = np.full(0, -1, dtype=np.int64)
        self._lm_root_id = np.full(0, -1, dtype=np.int32)
        self._block_ids: "dict[bytes, int]" = {}
        self._id_roots: "list[bytes]" = []
        self.equivocating: "set[int]" = set()

        #: execution payload block_hash → block root (optimistic-sync
        #: status updates arrive keyed by execution hash)
        self._exec_index: "dict[bytes, bytes]" = {}

        self.proposer_boost_root: "Optional[bytes]" = None
        self.slot = int(anchor_state.slot)
        self.interval = 0
        #: called with the store right before finalization pruning discards
        #: pre-finalized blocks (the controller persists them here)
        self.pre_prune_hook: "Optional[callable]" = None

    # ------------------------------------------------------------ plumbing

    def contains_block(self, root: bytes) -> bool:
        return bytes(root) in self.blocks

    def block_slot(self, root: bytes) -> int:
        return self.blocks[bytes(root)].slot

    def state_at(self, root: bytes):
        return self.blocks[bytes(root)].state

    def ancestor_at_slot(self, root: bytes, slot: int) -> bytes:
        """Walk parents until the block's slot is <= slot (spec
        get_ancestor)."""
        node = self.blocks[bytes(root)]
        while node.slot > slot:
            parent = self.blocks.get(node.parent_root)
            if parent is None:
                return node.root
            node = parent
        return node.root

    def is_descendant(self, ancestor: bytes, root: bytes) -> bool:
        ancestor = bytes(ancestor)
        if ancestor not in self.blocks:
            return False
        return (
            self.ancestor_at_slot(root, self.blocks[ancestor].slot) == ancestor
        )

    # ------------------------------------------------------------ validate_*

    def validate_block(
        self,
        signed_block,
        verifier: "Optional[Verifier]" = None,
        state_root_policy: str = "verify",
    ) -> ValidBlock:
        """Immutable, expensive half: parent lookup, full state transition
        with batch signature verification (store.rs:925 validate_block →
        :1013 custom_state_transition). Parallel-safe: touches no mutable
        store state (reads immutable snapshots only)."""
        block = signed_block.message
        root = block.hash_tree_root()
        if root in self.blocks:
            raise ForkChoiceError("duplicate block")
        slot = int(block.slot)
        if slot > self.slot:
            raise ForkChoiceError(f"block from future slot {slot} > {self.slot}")
        parent = self.blocks.get(bytes(block.parent_root))
        if parent is None:
            raise ForkChoiceError("unknown parent")  # controller delays/retries
        fin_slot = misc.compute_start_slot_at_epoch(
            int(self.finalized_checkpoint.epoch), self.p
        )
        if slot <= fin_slot:
            raise ForkChoiceError("block not newer than finalized slot")
        if (
            self.ancestor_at_slot(bytes(block.parent_root), fin_slot)
            != bytes(self.finalized_checkpoint.root)
        ):
            raise ForkChoiceError("block does not descend from finalized root")

        if verifier is None:
            verifier = MultiVerifier()
        # record the EL's verdict so SYNCING/ACCEPTED imports are marked
        # optimistic on the node (spec optimistic sync; the async status
        # updates arrive later via apply_payload_status)
        recording = _RecordingEngine(self.execution_engine)
        post = custom_state_transition(
            parent.state,
            signed_block,
            self.cfg,
            verifier,
            execution_engine=recording,
            state_root_policy=state_root_policy,
        )
        optimistic = recording.last_status in (
            PayloadStatus.SYNCING, PayloadStatus.ACCEPTED,
        ) or (parent.optimistic and recording.last_status is None)
        if optimistic and not self.execution_engine.allow_optimistic_import():
            raise ForkChoiceError(
                "optimistic import disallowed by execution engine"
            )
        is_timely = self.slot == slot and self.interval == 0
        return ValidBlock(signed_block, root, post, is_timely,
                          optimistic=optimistic)

    def validate_attestation(
        self, data_slot: int, committee_index: int, target_epoch: int,
        beacon_block_root: bytes, target_root: bytes,
        attesting_indices: "Sequence[int]", is_from_block: bool = False,
    ) -> ValidAttestation:
        """Fork-choice attestation validation (spec on_attestation checks;
        signature verification happens in the gossip pipeline before this).
        Pure: reads the DAG, mutates nothing."""
        p = self.p
        current_epoch = misc.compute_epoch_at_slot(self.slot, p)
        if target_epoch not in (current_epoch, max(0, current_epoch - 1)):
            raise ForkChoiceError("attestation target epoch out of window")
        if target_epoch != misc.compute_epoch_at_slot(data_slot, p):
            raise ForkChoiceError("attestation target/slot mismatch")
        beacon_block_root = bytes(beacon_block_root)
        if beacon_block_root not in self.blocks:
            raise ForkChoiceError("unknown attestation head block")
        if self.blocks[beacon_block_root].slot > data_slot:
            raise ForkChoiceError("attestation head newer than its slot")
        target_root = bytes(target_root)
        if target_root not in self.blocks:
            raise ForkChoiceError("unknown attestation target")
        expected_target = self.ancestor_at_slot(
            beacon_block_root,
            misc.compute_start_slot_at_epoch(target_epoch, p),
        )
        if expected_target != target_root:
            raise ForkChoiceError("attestation target not on head's chain")
        if data_slot > self.slot:
            raise ForkChoiceError("attestation from future slot")
        earliest = data_slot if is_from_block else data_slot + 1
        return ValidAttestation(
            [int(i) for i in attesting_indices],
            target_epoch,
            beacon_block_root,
            earliest_slot=earliest,
        )

    # --------------------------------------------------------------- apply_*

    def apply_tick(self, tick: Tick) -> None:
        """Mutator-only (store.rs apply_tick): advance time, reset the
        proposer boost at each new slot."""
        if tick.slot < self.slot:
            return
        crossed_epoch = (
            misc.compute_epoch_at_slot(tick.slot, self.p)
            > misc.compute_epoch_at_slot(self.slot, self.p)
        )
        if tick.slot > self.slot:
            self.proposer_boost_root = None
        self.slot = tick.slot
        self.interval = int(tick.kind)
        if crossed_epoch:
            # promote unrealized justification at the boundary (spec
            # on_tick_per_slot → update_checkpoints(store.unrealized_*))
            self._update_checkpoints(
                self.unrealized_justified, self.unrealized_finalized
            )

    def apply_block(self, valid: ValidBlock) -> None:
        """Mutator-only cheap half (store.rs:1860 apply_block): insert into
        the DAG, pull up justification, boost, prune on new finality."""
        root = valid.root
        if root in self.blocks:
            return
        post = valid.state
        uj, uf = unrealized_checkpoints(post, self.cfg)
        node = BlockNode(
            root, valid.signed_block, post, uj, uf,
            optimistic=valid.optimistic,
        )
        self.blocks[root] = node
        self.children.setdefault(node.parent_root, []).append(root)
        self.children.setdefault(root, [])
        if node.execution_block_hash:
            self._exec_index[node.execution_block_hash] = root
        if not node.optimistic:
            # a VALID payload validates its whole ancestor chain (engine
            # API semantics) — promote any optimistic ancestors
            self._promote_valid(node.parent_root)

        # spec on_block (v1.3+) gates the boost with
        # is_first_block = (proposer_boost_root == Root()): only the FIRST
        # timely block in the slot gets it — letting a second
        # (equivocating) block overwrite the boost enables boost-stealing
        # ex-ante reorgs. proposer_boost_root resets at each slot tick.
        # Matches the reference exactly: store.rs:1878-1887 (is_first_block)
        # and store.rs:1803-1804 (per-slot reset).
        if valid.is_timely and self.proposer_boost_root is None:
            self.proposer_boost_root = root

        p = self.p
        block_epoch = misc.compute_epoch_at_slot(node.slot, p)
        current_epoch = misc.compute_epoch_at_slot(self.slot, p)
        # realized checkpoints always count; unrealized count immediately
        # for blocks from prior epochs (pull-up tip)
        candidates = [
            (post.current_justified_checkpoint, post.finalized_checkpoint)
        ]
        if block_epoch < current_epoch:
            candidates.append((uj, uf))
        for justified, finalized in candidates:
            self._update_checkpoints(justified, finalized)
        # track the best unrealized tip for boundary promotion
        if int(uj.epoch) > int(self.unrealized_justified.epoch):
            self.unrealized_justified = uj
        if int(uf.epoch) > int(self.unrealized_finalized.epoch):
            self.unrealized_finalized = uf

    @property
    def latest_message_root(self) -> "dict[int, bytes]":
        """Diagnostic dict view (validator → latest-vote block root) of the
        columnar latest-message store; built on demand, not the hot path."""
        idx = np.nonzero(self._lm_root_id >= 0)[0]
        return {int(i): self._id_roots[self._lm_root_id[i]] for i in idx}

    def _intern_root(self, root: bytes) -> int:
        rid = self._block_ids.get(root)
        if rid is None:
            rid = len(self._id_roots)
            self._block_ids[root] = rid
            self._id_roots.append(root)
        return rid

    def _ensure_lm_capacity(self, n: int) -> None:
        if len(self._lm_epoch) < n:
            grow = max(n, 2 * len(self._lm_epoch))
            e = np.full(grow, -1, dtype=np.int64)
            r = np.full(grow, -1, dtype=np.int32)
            e[: len(self._lm_epoch)] = self._lm_epoch
            r[: len(self._lm_root_id)] = self._lm_root_id
            self._lm_epoch, self._lm_root_id = e, r

    def apply_attestation(self, valid: ValidAttestation) -> None:
        """Mutator-only (store.rs:2022): LMD latest-message updates —
        one vectorized compare-and-set over the attestation's indices."""
        rid = self._intern_root(valid.beacon_block_root)
        epoch = int(valid.epoch)
        idx = np.asarray(valid.indices, dtype=np.int64)
        if idx.size == 0:
            return
        self._ensure_lm_capacity(int(idx.max()) + 1)
        newer = self._lm_epoch[idx] < epoch
        if self.equivocating:
            eq = np.fromiter(self.equivocating, np.int64)
            newer &= ~np.isin(idx, eq)
        upd = idx[newer]
        self._lm_epoch[upd] = epoch
        self._lm_root_id[upd] = rid

    def apply_attester_slashing(self, indices: "Sequence[int]") -> None:
        """Equivocating validators never count toward weights again."""
        for i in indices:
            i = int(i)
            self.equivocating.add(i)
            if i < len(self._lm_epoch):
                self._lm_epoch[i] = -1
                self._lm_root_id[i] = -1

    def _update_checkpoints(self, justified, finalized) -> None:
        if int(justified.epoch) > int(self.justified_checkpoint.epoch):
            jroot = bytes(justified.root)
            if jroot in self.blocks:
                self.justified_checkpoint = justified
                self.justified_state = self._checkpoint_state(justified)
        if int(finalized.epoch) > int(self.finalized_checkpoint.epoch):
            if bytes(finalized.root) in self.blocks:
                self.finalized_checkpoint = finalized
                if self.pre_prune_hook is not None:
                    self.pre_prune_hook(self)
                self._prune_finalized()

    def _checkpoint_state(self, checkpoint):
        """State at a checkpoint (advanced to the checkpoint's epoch start
        if the block is older) — spec store.checkpoint_states cache."""
        state = self.blocks[bytes(checkpoint.root)].state
        target_slot = misc.compute_start_slot_at_epoch(
            int(checkpoint.epoch), self.p
        )
        if int(state.slot) < target_slot:
            state = process_slots(state, target_slot, self.cfg)
        return state

    def _prune_finalized(self) -> None:
        fin_root = bytes(self.finalized_checkpoint.root)
        keep = {
            r
            for r in self.blocks
            if self.is_descendant(fin_root, r)
        }
        keep.add(fin_root)
        self.blocks = {r: n for r, n in self.blocks.items() if r in keep}
        self.children = {
            r: [c for c in cs if c in keep]
            for r, cs in self.children.items()
            if r in keep
        }
        self._exec_index = {
            h: r for h, r in self._exec_index.items() if r in keep
        }

    # -------------------------------------------------- optimistic sync

    def is_optimistic(self, root: "Optional[bytes]" = None) -> bool:
        """Is `root` (default: the current head) optimistically imported —
        i.e. does its chain contain a payload the EL has not yet judged?
        Nodes record their own status and VALID promotion clears ancestors,
        so one node read suffices."""
        root = bytes(root) if root is not None else self.get_head()
        node = self.blocks.get(root)
        return bool(node is not None and node.optimistic)

    def _promote_valid(self, root: bytes) -> None:
        """Mark `root` and all its optimistic ancestors valid (engine-API
        semantics: VALID for a payload validates its ancestor chain)."""
        node = self.blocks.get(bytes(root))
        while node is not None and node.optimistic:
            node.optimistic = False
            node = self.blocks.get(node.parent_root)

    def apply_payload_status(
        self,
        execution_block_hash: bytes,
        status: "PayloadStatus",
        latest_valid_hash: "Optional[bytes]" = None,
    ) -> "list[bytes]":
        """Mutator-only: apply an asynchronous EL verdict
        (on_notified_new_payload / on_notified_fork_choice_update —
        fork_choice_control/src/controller.rs:236-247).

        VALID promotes the block and its ancestors out of optimistic
        status. INVALID removes the block AND all its descendants from the
        DAG (they can never become canonical); with latest_valid_hash the
        invalidation extends up the chain to just above that payload.
        Returns the list of removed roots (empty for VALID/SYNCING)."""
        root = self._exec_index.get(bytes(execution_block_hash))
        if root is None or root not in self.blocks:
            return []
        if status == PayloadStatus.VALID:
            self._promote_valid(root)
            return []
        if status != PayloadStatus.INVALID:
            return []  # SYNCING/ACCEPTED carry no new information
        # find the oldest invalid ancestor: everything above
        # latest_valid_hash (when given and on this chain) is invalid too
        oldest_invalid = root
        if latest_valid_hash is not None:
            lv = bytes(latest_valid_hash)
            node = self.blocks[root]
            while True:
                parent = self.blocks.get(node.parent_root)
                if parent is None or parent.execution_block_hash == lv:
                    break
                oldest_invalid = parent.root
                node = parent
        fin_root = bytes(self.finalized_checkpoint.root)
        if oldest_invalid == fin_root or self.is_descendant(
            oldest_invalid, fin_root
        ):
            raise ForkChoiceError(
                "execution engine invalidated the finalized chain"
            )
        removed = [
            r for r in self.blocks if self.is_descendant(oldest_invalid, r)
        ]
        removed_set = set(removed)
        for r in removed:
            node = self.blocks.pop(r)
            self.children.pop(r, None)
            if node.execution_block_hash:
                self._exec_index.pop(node.execution_block_hash, None)
        self.children = {
            r: [c for c in cs if c not in removed_set]
            for r, cs in self.children.items()
        }
        if self.proposer_boost_root in removed_set:
            self.proposer_boost_root = None
        return removed

    # ------------------------------------------------------------------ head

    def get_head(self) -> bytes:
        """LMD-GHOST from the justified root, restricted to viable branches
        (spec get_head over filter_block_tree)."""
        justified_root = bytes(self.justified_checkpoint.root)
        if justified_root not in self.blocks:
            justified_root = self.anchor_root
        weights = self._subtree_weights(justified_root)
        viable = self._viable_subtrees()
        head = justified_root
        while True:
            kids = [
                k for k in self.children.get(head, ()) if viable.get(k, False)
            ]
            if not kids:
                return head
            head = max(kids, key=lambda r: (weights.get(r, 0), r))

    def _viable_for_head(self, node: BlockNode) -> bool:
        """Spec `is_head_viable`/filter_block_tree leaf condition: the
        branch's voting source and finalized checkpoint must be consistent
        with the store's."""
        p = self.p
        current_epoch = misc.compute_epoch_at_slot(self.slot, p)
        justified = self.justified_checkpoint
        block_epoch = misc.compute_epoch_at_slot(node.slot, p)
        # spec get_voting_source: prior-epoch blocks vote with their
        # unrealized justification (the pulled-up tip), current-epoch
        # blocks with their realized checkpoint
        if block_epoch < current_epoch:
            voting_source = node.unrealized_justified
        else:
            voting_source = node.state.current_justified_checkpoint
        correct_justified = (
            int(justified.epoch) == 0
            or int(voting_source.epoch) == int(justified.epoch)
            # post-capella fork-choice relaxation
            or int(voting_source.epoch) + 2 >= current_epoch
        )
        fin = self.finalized_checkpoint
        if int(fin.epoch) == 0:
            correct_finalized = True
        else:
            fin_slot = misc.compute_start_slot_at_epoch(int(fin.epoch), p)
            correct_finalized = (
                self.ancestor_at_slot(node.root, fin_slot) == bytes(fin.root)
            )
        return correct_justified and correct_finalized

    def _viable_subtrees(self) -> "dict[bytes, bool]":
        """root -> does the subtree contain a viable leaf (spec
        filter_block_tree: internal nodes survive iff some descendant leaf
        is viable)."""
        viable: "dict[bytes, bool]" = {}
        for root in sorted(
            self.blocks, key=lambda r: self.blocks[r].slot, reverse=True
        ):
            kids = self.children.get(root, ())
            if kids:
                # internal nodes survive only through viable descendants
                viable[root] = any(viable.get(k, False) for k in kids)
            else:
                viable[root] = self._viable_for_head(self.blocks[root])
        return viable

    def _subtree_weights(self, from_root: bytes) -> "dict[bytes, int]":
        """Per-node subtree weight: one numpy pass over latest messages,
        then a bottom-up accumulation over the DAG."""
        p = self.p
        jstate = self.justified_state
        cols = accessors.registry_columns(jstate)
        n = len(cols)

        own: "dict[bytes, int]" = {}
        m = min(len(self._lm_root_id), n)
        if m and self._id_roots:
            ids = self._lm_root_id[:m]
            mask = ids >= 0
            if mask.any():
                active = cols.active_indices(
                    accessors.get_current_epoch(jstate, p)
                )
                active_mask = np.zeros(n, dtype=bool)
                active_mask[active] = True
                mask &= active_mask[:m]
                mask &= ~np.asarray(cols.slashed[:m], dtype=bool)
                sel = ids[mask]
                # balances < 2⁵³ gwei total: float64 bincount is exact
                w = np.bincount(
                    sel,
                    weights=np.asarray(
                        cols.effective_balance[:m], dtype=np.float64
                    )[mask],
                    minlength=len(self._id_roots),
                )
                for rid in np.nonzero(w)[0]:
                    root = self._id_roots[rid]
                    if root in self.blocks:
                        own[root] = int(w[rid])

        if self.proposer_boost_root and self.proposer_boost_root in self.blocks:
            total_active = accessors.get_total_active_balance(jstate, p)
            committee_weight = total_active // p.SLOTS_PER_EPOCH
            boost = committee_weight * 40 // 100  # PROPOSER_SCORE_BOOST
            own[self.proposer_boost_root] = (
                own.get(self.proposer_boost_root, 0) + boost
            )

        # bottom-up: deepest-first accumulation into parents
        weights: "dict[bytes, int]" = dict(own)
        for root in sorted(
            self.blocks, key=lambda r: self.blocks[r].slot, reverse=True
        ):
            w = weights.get(root, 0)
            parent = self.blocks[root].parent_root
            if parent in self.blocks and root != from_root:
                weights[parent] = weights.get(parent, 0) + w
        return weights

    # -------------------------------------------------------------- queries

    def head_state(self):
        return self.blocks[self.get_head()].state

    def __len__(self) -> int:
        return len(self.blocks)


class _AnchorBlock:
    """Header-shaped stand-in for the anchor's signed block."""

    __slots__ = ("message",)

    def __init__(self, header) -> None:
        self.message = _AnchorMessage(header)


class _AnchorMessage:
    __slots__ = ("slot", "parent_root", "state_root")

    def __init__(self, header) -> None:
        self.slot = int(header.slot)
        self.parent_root = bytes(header.parent_root)
        self.state_root = bytes(header.state_root)


__all__ = [
    "ForkChoiceError",
    "Store",
    "Tick",
    "TickKind",
    "ValidBlock",
    "ValidAttestation",
    "unrealized_checkpoints",
]
