"""CLI — reference: the `grandine` binary crate (clap `GrandineArgs`,
grandine/src/grandine_args.rs:77,110-647; restart loop main.rs:101-123;
export/replay subcommands commands.rs).

Subcommands:
  run          in-process node on an interop genesis (devnet mode), with
               storage, HTTP API, metrics and the restart supervisor
  info         print resolved config/preset
  export / import-interchange   EIP-3076 slashing-protection data
  replay       re-validate a stored finalized chain from the database
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _bls_pubkey_arg(value: str) -> bytes:
    """argparse type: 48-byte hex BLS pubkey (rejects bad input at startup
    instead of bricking the builder path at proposal time)."""
    try:
        raw = bytes.fromhex(value.removeprefix("0x"))
    except ValueError:
        raise argparse.ArgumentTypeError(f"not hex: {value!r}")
    if len(raw) != 48:
        raise argparse.ArgumentTypeError(
            f"BLS pubkey must be 48 bytes, got {len(raw)}"
        )
    return raw


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="grandine-tpu",
        description="TPU-native Ethereum consensus framework",
    )
    parser.add_argument(
        "--network", default="minimal",
        help="named config: mainnet | minimal (default)")
    parser.add_argument(
        "--config-file", help="custom chain config YAML (consensus-specs format)")
    parser.add_argument("--data-dir", default="./grandine-tpu-data")
    parser.add_argument(
        "--features", default="",
        help="comma-separated runtime feature toggles")
    parser.add_argument(
        "--use-device", action="store_true",
        help="route batch verification through the TPU backend")
    parser.add_argument(
        "--devices", type=int, default=None, metavar="N",
        help="shard the verify plane over an N-device mesh (power of "
             "two; requires --use-device). On the CPU platform the "
             "visible device count comes from XLA_FLAGS="
             "--xla_force_host_platform_device_count=N, which XLA reads "
             "once at startup — set it in the environment BEFORE "
             "launching; --devices only selects from what is visible")
    parser.add_argument(
        "--no-warm", action="store_true",
        help="skip the startup kernel-bucket precompile warmer")
    parser.add_argument(
        "--no-isolation", action="store_true",
        help="disable on-device fault localization of failed verify "
             "batches (falls back to recursive host bisection)")
    parser.add_argument(
        "--quarantine-exit-clean", type=int, default=None, metavar="K",
        help="consecutive clean quarantine batches before a suspect "
             "origin exits quarantine (default 3)")
    parser.add_argument(
        "--brownout", action=argparse.BooleanOptionalAction, default=True,
        help="adaptive overload control: a hysteretic brownout ladder "
             "sheds batching latency, admission headroom, and finally "
             "bulk work when the verify plane misses its SLOs "
             "(runtime/brownout.py; --no-brownout disables)")
    parser.add_argument(
        "--admission-max-share", type=float, default=None, metavar="F",
        help="fair-share admission cap: one gossip origin may hold at "
             "most this fraction of the verify plane's sliding window "
             "(default 0.5; origins under the absolute floor are never "
             "rejected)")

    sub = parser.add_subparsers(dest="command")

    run = sub.add_parser("run", help="run an in-process devnet node")
    run.add_argument("--validators", type=int, default=32)
    run.add_argument("--slots", type=int, default=32,
                     help="stop after this many slots (0 = run forever)")
    run.add_argument("--http-port", type=int, default=0,
                     help="serve the Beacon API on this port (0 = off)")
    run.add_argument("--no-restart", action="store_true",
                     help="disable the crash-restart supervisor")
    run.add_argument("--engine-url", default=None,
                     help="execution-engine JSON-RPC endpoint "
                          "(requires --jwt-secret)")
    run.add_argument("--jwt-secret", default=None,
                     help="path to the hex-encoded engine-API JWT secret")
    run.add_argument("--web3signer-url", default=None,
                     help="remote signer (Web3Signer REST) endpoint")
    run.add_argument("--checkpoint-sync-url", default=None,
                     help="Beacon API to checkpoint-sync the anchor state from")
    run.add_argument("--builder-url", default=None,
                     help="MEV builder relay endpoint")
    run.add_argument("--builder-pubkey", default=None, type=_bls_pubkey_arg,
                     help="pin the relay's BLS pubkey (96 hex chars); bids "
                          "signed by any other key are rejected")
    run.add_argument("--key-cache-password-file", default=None,
                     help="enable the encrypted validator key cache "
                          "(skips per-keystore KDF on restart)")
    run.add_argument("--keymanager-token-file", default=None,
                     help="bearer token required by the keymanager API "
                          "routes (unset = open)")
    run.add_argument("--metrics-url", default=None,
                     help="push client stats to this beaconcha.in-style "
                          "endpoint every 60s")
    run.add_argument("--trace-out", default=None,
                     help="append finished spans to this JSONL file (the "
                          "live ring buffer also serves "
                          "/eth/v1/debug/grandine/trace)")
    run.add_argument("--profile-dir", default=None,
                     help="root directory for on-demand device profile "
                          "captures (GET /eth/v1/debug/grandine/profile"
                          "?action=start); unset = annotation-only "
                          "capture sessions")
    run.add_argument("--profile-on-start", action="store_true",
                     help="open a profiler capture session at node start "
                          "(stop it via /eth/v1/debug/grandine/profile"
                          "?action=stop)")
    run.add_argument("--listen-port", type=int, default=None,
                     help="serve p2p (TCP gossip + req/resp) on this port "
                          "(0 = pick a free port)")
    run.add_argument("--peer", action="append", default=[],
                     help="host:port of a peer to dial (repeatable)")
    run.add_argument("--follow", action="store_true",
                     help="run no duties; range-sync + gossip-follow peers "
                          "until --until-finalized is reached")
    run.add_argument("--until-finalized", type=int, default=1,
                     help="--follow exits 0 once finalized epoch reaches this")
    run.add_argument("--follow-timeout", type=float, default=300.0)

    sub.add_parser("info", help="print the resolved configuration")

    exp = sub.add_parser("export-interchange",
                         help="export EIP-3076 slashing-protection data")
    exp.add_argument("output", help="output JSON path")

    imp = sub.add_parser("import-interchange",
                         help="import EIP-3076 slashing-protection data")
    imp.add_argument("input", help="input JSON path")

    rep = sub.add_parser("replay",
                         help="re-validate the stored finalized chain")
    rep.add_argument("--window", type=int, default=None,
                     help="blocks per cross-block verification batch")
    rep.add_argument("--per-block", action="store_true",
                     help="legacy one-dispatch-per-block replay (baseline)")
    rep.add_argument("--no-slasher", action="store_true",
                     help="skip historical slashing surveillance")
    return parser


def load_config(args):
    from grandine_tpu.types.config import Config

    if args.config_file:
        return Config.from_yaml(args.config_file)
    if args.network == "mainnet":
        return Config.mainnet()
    if args.network == "minimal":
        return Config.minimal()
    raise SystemExit(f"unknown network {args.network!r}")


def cmd_info(args) -> int:
    cfg = load_config(args)
    print(json.dumps({
        "config_name": cfg.config_name,
        "preset": cfg.preset_base,
        "slots_per_epoch": cfg.preset.SLOTS_PER_EPOCH,
        "seconds_per_slot": cfg.seconds_per_slot,
        "genesis_fork_version": "0x" + cfg.genesis_fork_version.hex(),
        "fork_epochs": {
            "altair": cfg.altair_fork_epoch,
            "bellatrix": cfg.bellatrix_fork_epoch,
            "capella": cfg.capella_fork_epoch,
            "deneb": cfg.deneb_fork_epoch,
        },
        "data_dir": args.data_dir,
    }, indent=2))
    return 0


def _node_once(args, cfg) -> int:
    """One node lifetime (the body inside the restart supervisor)."""
    from grandine_tpu.consensus.verifier import MultiVerifier, TpuVerifier
    from grandine_tpu.http_api import ApiContext, serve
    from grandine_tpu.metrics import Metrics
    from grandine_tpu.pools import AttestationAggPool, OperationPool
    from grandine_tpu.runtime import Controller, InProcessNode
    from grandine_tpu.runtime.liveness import LivenessTracker
    from grandine_tpu.storage import Database, Storage
    from grandine_tpu.transition.genesis import interop_genesis_state

    os.makedirs(args.data_dir, exist_ok=True)
    db = Database.persistent(os.path.join(args.data_dir, "chain.sqlite"))
    storage = Storage(db, cfg)
    metrics = Metrics()
    from grandine_tpu.tracing import Tracer

    tracer = Tracer()
    if getattr(args, "trace_out", None):
        tracer.set_jsonl_path(args.trace_out)
        print(f"trace spans -> {args.trace_out}")

    # concrete HTTP clients behind the seams (http_clients.py); absent
    # flags keep the Null/Mock/injected defaults the tests use
    engine = None
    if getattr(args, "engine_url", None):
        from grandine_tpu.http_clients import EngineApiClient

        if not args.jwt_secret:
            raise SystemExit("--engine-url requires --jwt-secret")
        with open(args.jwt_secret) as f:
            secret = bytes.fromhex(f.read().strip().removeprefix("0x"))
        # transient EL failures retry with capped exponential backoff
        # (el_retry_total) instead of waiting for the next head
        engine = EngineApiClient(args.engine_url, secret).with_retries(
            metrics=metrics
        )

    if getattr(args, "checkpoint_sync_url", None) and (
        storage.load_anchor_state() is None
    ):
        # remote checkpoint only on FIRST start: a restart must resume from
        # the locally persisted anchor + unfinalized replay, not re-download
        # and discard local progress (reference StateLoadStrategy::Auto
        # prefers the local DB once one exists)
        from grandine_tpu.http_clients import checkpoint_fetcher
        from grandine_tpu.storage import StateLoadStrategy

        stored, unfinalized = storage.load(
            StateLoadStrategy.REMOTE,
            fetcher=checkpoint_fetcher(args.checkpoint_sync_url),
        )
    else:
        genesis = interop_genesis_state(args.validators, cfg)
        stored, unfinalized = storage.load(anchor_state=genesis)

    from grandine_tpu.slasher import Slasher

    operation_pool = OperationPool(cfg)
    slasher = Slasher(db, metrics=metrics)
    mesh = None
    if getattr(args, "devices", None):
        if not args.use_device:
            raise SystemExit("--devices requires --use-device")
        from grandine_tpu.tpu.mesh import VerifyMesh

        mesh = VerifyMesh.build(args.devices)
        print(f"verify mesh: {mesh.describe()}")
    node = InProcessNode(
        stored, cfg, use_device_firehose=args.use_device,
        execution_engine=engine,
        slasher=slasher, operation_pool=operation_pool,
        metrics=metrics, tracer=tracer,
        mesh=mesh,
        use_isolation=not getattr(args, "no_isolation", False),
        use_brownout=getattr(args, "brownout", True),
        database=db,
    )
    if getattr(args, "quarantine_exit_clean", None):
        node.reputation.exit_clean = max(1, args.quarantine_exit_clean)
    if getattr(args, "profile_dir", None):
        node.profiler.trace_root = args.profile_dir
        print(f"profile captures -> {args.profile_dir}")
    if getattr(args, "profile_on_start", False):
        node.profiler.start(note="cli --profile-on-start")
        # One-shot: the restart supervisor re-runs _node_once after a
        # crash, and on a saturated host the open trace can be what
        # starved the node — never re-open a session over the crashed
        # one (its global jax trace may still be running).
        args.profile_on_start = False
        print("profiler capture session open "
              "(GET /eth/v1/debug/grandine/profile?action=stop closes it)")
    if getattr(args, "admission_max_share", None):
        node.admission.max_share = args.admission_max_share
    if args.use_device and not getattr(args, "no_warm", False):
        # precompile the kernel shape manifest in the background while
        # the node syncs — an uncompiled bucket mid-chain stalls
        # verification for the whole compile (runtime/warmup.py). The
        # shared registry unlocks the indexed-kernel rows, and metrics
        # wires verify_recompiles_total so a post-warmup compile is
        # visible; completion seals the shape ledger.
        from grandine_tpu.runtime.warmup import warm_in_background

        verifier = getattr(node, "attestation_verifier", None)
        warm_in_background(
            progress=lambda m: print(f"[warmup] {m}"),
            registry=getattr(verifier, "registry", None),
            metrics=metrics,
            mesh=node.mesh,
        )
    if getattr(args, "web3signer_url", None):
        # remote-signer registry for a ValidatorService embedding; the
        # list_keys round-trip also fail-fasts on a bad endpoint
        from grandine_tpu.http_clients import Web3SignerClient
        from grandine_tpu.validator.signer import Signer

        client = Web3SignerClient(args.web3signer_url)
        remote_signer = Signer(web3signer=client)
        keys = client.list_keys()
        for pk_hex in keys:
            remote_signer.add_remote_key(bytes.fromhex(pk_hex))
        node.remote_signer = remote_signer
        print(f"web3signer: {len(keys)} remote keys at {args.web3signer_url}")
    if getattr(args, "builder_url", None):
        from grandine_tpu.builder_api import BuilderApi
        from grandine_tpu.http_clients import BuilderRelayClient

        node.builder_api = BuilderApi(
            BuilderRelayClient(args.builder_url), chain_config=cfg,
            relay_pubkey=getattr(args, "builder_pubkey", None),
        )
        print(f"builder relay: {args.builder_url}")
    node.controller.storage = storage
    node.controller.store.pre_prune_hook = node.controller._persist_finalized
    node.controller.metrics = metrics
    if getattr(args, "metrics_url", None):
        from grandine_tpu.metrics import RemoteMetricsService

        pusher = RemoteMetricsService(
            args.metrics_url, metrics, controller=node.controller,
            data_dir=args.data_dir,
        )
        pusher.start()
        print(f"metrics push: {args.metrics_url} every 60s")
    if unfinalized:
        # crash-restart: replay the persisted unfinalized head so we don't
        # regress to finality and double-propose already-signed slots
        from grandine_tpu.fork_choice.store import Tick, TickKind

        max_slot = max(int(b.message.slot) for b in unfinalized)
        node.controller.on_tick(Tick(max_slot, TickKind.AGGREGATE))
        for blk in unfinalized:
            node.controller.on_requested_block(blk)
        node.controller.wait()
        print(f"restored {len(unfinalized)} unfinalized blocks from storage")

    network = transport = None
    if getattr(args, "listen_port", None) is not None or getattr(args, "peer", None):
        from grandine_tpu.p2p.network import GossipTopics, Network
        from grandine_tpu.p2p.tcp import TcpTransport

        head_state = node.controller.snapshot().head_state
        transport = TcpTransport(
            peer_id=f"node-{os.getpid()}",
            fork_digest=GossipTopics.fork_digest(cfg, head_state),
            listen_port=args.listen_port or 0,
        )
        network = Network(
            transport, node.controller, cfg,
            attestation_verifier=node.attestation_verifier,
            storage=storage,
            operation_pool=operation_pool,
            verify_scheduler=node.verify_scheduler,
            admission=node.admission,
        )
        print(f"p2p listening on 127.0.0.1:{transport.port}", flush=True)
        for addr in args.peer:
            host, port = addr.rsplit(":", 1)
            pid = transport.connect(host, int(port))
            print(f"p2p connected to {pid} ({addr})", flush=True)

    server = None
    if args.http_port:
        from grandine_tpu.http_api.events import (
            EventBus,
            wire_controller_events,
        )
        from grandine_tpu.p2p.subnets import SubnetService
        from grandine_tpu.pools.sync_committee_pool import SyncCommitteeAggPool
        from grandine_tpu.validator.keymanager import KeyManager
        from grandine_tpu.validator.signer import Signer
        from grandine_tpu.validator.slashing_protection import (
            SlashingProtection,
        )

        bus = EventBus()
        wire_controller_events(node.controller, bus)
        # Keymanager backing registry: the Web3Signer-backed registry when
        # --web3signer-url is set, else a local-only Signer. NOTE: the
        # synthetic devnet driver (InProcessNode) signs duties with
        # interop keys; keys managed here drive a ValidatorService
        # embedding (validator/service.py), not the devnet loop — the
        # same split as the reference's validator-vs-node processes.
        km_signer = getattr(node, "remote_signer", None) or Signer()
        node.api_signer = km_signer
        key_cache = None
        if getattr(args, "key_cache_password_file", None):
            from grandine_tpu.validator.key_cache import (
                KeyCacheError,
                ValidatorKeyCache,
            )

            with open(args.key_cache_password_file) as f:
                key_cache = ValidatorKeyCache(
                    os.path.join(args.data_dir, "keys.cache"),
                    f.read().strip(),
                )
            try:
                n_cached = key_cache.load()  # fail fast on a wrong password
            except KeyCacheError as e:
                raise SystemExit(f"validator key cache: {e}")
            if n_cached:
                print(f"validator key cache: {n_cached} keys")
        km_token = None
        if getattr(args, "keymanager_token_file", None):
            with open(args.keymanager_token_file) as f:
                km_token = f.read().strip()
            if not km_token:
                # an empty token would silently DISABLE auth
                raise SystemExit(
                    f"--keymanager-token-file {args.keymanager_token_file} "
                    "is empty"
                )
        sync_pool = SyncCommitteeAggPool(cfg)
        if network is not None:
            network.sync_pool = sync_pool  # gossip sync topics feed it
        ctx = ApiContext(
            node.controller, cfg,
            attestation_pool=AttestationAggPool(cfg),
            operation_pool=operation_pool,
            liveness=LivenessTracker(args.validators),
            metrics=metrics,
            sync_pool=sync_pool,
            keymanager=KeyManager(
                km_signer,
                slashing_protection=SlashingProtection(db),
                key_cache=key_cache,
            ),
            event_bus=bus,
            network=network,
            subnet_service=SubnetService(cfg, network=network),
            keymanager_token=km_token,
            data_dir=args.data_dir,
            tracer=tracer,
            flight=node.flight,
            profiler=node.profiler,
        )
        server, _thread = serve(ctx, port=args.http_port)
        print(f"Beacon API on http://127.0.0.1:{args.http_port}")

    try:
        if getattr(args, "follow", False):
            return _follow_loop(args, node, transport)
        start = int(node.controller.snapshot().slot) + 1
        stop = start + args.slots if args.slots else None
        slot = start
        published = 0
        while stop is None or slot < stop:
            node.run_slot(slot)
            if network is not None:
                while published < len(node.produced_blocks):
                    network.publish_block(node.produced_blocks[published])
                    published += 1
            snap = node.head()
            print(
                f"slot {slot}: head={snap.head_root.hex()[:12]} "
                f"justified={int(snap.justified_checkpoint.epoch)} "
                f"finalized={int(snap.finalized_checkpoint.epoch)}",
                flush=True,
            )
            slot += 1
    finally:
        if transport is not None:
            transport.close()
        if server is not None:
            server.shutdown()
        node.stop()
        db.close()
    return 0


def _follow_loop(args, node, transport) -> int:
    """Dutiless follower: range-sync from peers (gossip rides alongside)
    until the finalized epoch reaches the target (two-process devnet)."""
    from grandine_tpu.p2p.sync import BlockSyncService

    if transport is None:
        raise SystemExit("--follow requires --peer/--listen-port")
    sync = BlockSyncService(transport, node.controller, node.cfg)
    deadline = time.time() + args.follow_timeout
    last_print = 0.0
    while time.time() < deadline:
        try:
            progress = sync.sync_once()
        except (ConnectionError, TimeoutError):
            progress = False
        snap = node.controller.snapshot()
        fin = int(snap.finalized_checkpoint.epoch)
        if time.time() - last_print > 1.0:
            print(
                f"follow: head_slot={int(snap.head_state.slot)} "
                f"finalized={fin} peers={len(transport.peers())}",
                flush=True,
            )
            last_print = time.time()
        if fin >= args.until_finalized:
            print(f"follow: finalized epoch {fin} reached", flush=True)
            return 0
        if not progress:
            time.sleep(0.25)
    print("follow: timeout before reaching finality target", file=sys.stderr)
    return 1


def cmd_run(args) -> int:
    """The restart supervisor (grandine/src/main.rs:101-123): a crash
    restarts the node from storage unless inhibited."""
    from grandine_tpu import features

    cfg = load_config(args)
    while True:
        try:
            return _node_once(args, cfg)
        except KeyboardInterrupt:
            return 130
        except Exception as e:
            if args.no_restart or features.is_enabled(
                features.Feature.INHIBIT_APPLICATION_RESTART
            ):
                raise
            print(f"node crashed ({e!r}); restarting from storage…",
                  file=sys.stderr)
            time.sleep(1)


def cmd_export_interchange(args) -> int:
    from grandine_tpu.storage import Database
    from grandine_tpu.validator.slashing_protection import SlashingProtection

    db = Database.persistent(
        os.path.join(args.data_dir, "slashing_protection.sqlite"))
    sp = SlashingProtection(db)
    with open(args.output, "w") as f:
        json.dump(sp.export_interchange(), f, indent=2)
    print(f"exported to {args.output}")
    return 0


def cmd_import_interchange(args) -> int:
    from grandine_tpu.storage import Database
    from grandine_tpu.validator.slashing_protection import SlashingProtection

    with open(args.input) as f:
        blob = json.load(f)
    gvr = bytes.fromhex(
        blob["metadata"]["genesis_validators_root"].removeprefix("0x"))
    db = Database.persistent(
        os.path.join(args.data_dir, "slashing_protection.sqlite"))
    sp = SlashingProtection(db, genesis_validators_root=gvr)
    sp.import_interchange(blob)
    print(f"imported {len(blob.get('data', []))} validator records")
    return 0


def cmd_replay(args) -> int:
    """Re-validate the stored finalized chain from its first anchor with
    cross-block batched signature verification, feeding every replayed
    attestation through the slasher (historical surveillance)."""
    from grandine_tpu.consensus.verifier import MultiVerifier, TpuVerifier
    from grandine_tpu.runtime.replay import (
        DEFAULT_WINDOW_BLOCKS,
        BulkReplayPipeline,
        ReplayInvalidBlock,
    )
    from grandine_tpu.slasher import Slasher
    from grandine_tpu.storage import Database, Storage
    from grandine_tpu.transition.combined import custom_state_transition

    cfg = load_config(args)
    db = Database.persistent(os.path.join(args.data_dir, "chain.sqlite"))
    storage = Storage(db, cfg)
    start_state = storage.load_genesis_state()
    if start_state is None:
        print("no stored chain", file=sys.stderr)
        return 1
    latest = storage.latest_persisted_slot()
    blocks = []
    for slot in range(int(start_state.slot) + 1, latest + 1):
        root = storage.finalized_root_by_slot(slot)
        if root is None:
            continue  # empty slot
        blocks.append(storage.finalized_block_by_root(root))
    t0 = time.time()
    if getattr(args, "per_block", False):
        cur = start_state
        for blk in blocks:
            verifier = TpuVerifier() if args.use_device else MultiVerifier()
            cur = custom_state_transition(cur, blk, cfg, verifier)
        n, sigsets, hits = len(blocks), 0, 0
    else:
        if getattr(args, "no_slasher", False):
            slasher = None
        elif args.use_device:
            # device replay: span updates for the window's solo
            # validators merge into one grid dispatch per window
            from grandine_tpu.tpu.spans import SpanPlane

            slasher = Slasher(span_plane=SpanPlane())
        else:
            slasher = Slasher()
        pipeline = BulkReplayPipeline(
            cfg, use_device=args.use_device,
            window_size=getattr(args, "window", None) or DEFAULT_WINDOW_BLOCKS,
            slasher=slasher,
        )
        try:
            pipeline.replay(start_state, blocks)
        except ReplayInvalidBlock as e:
            print(f"stored chain INVALID: {e}", file=sys.stderr)
            return 1
        n = pipeline.stats["blocks"]
        sigsets = pipeline.stats["sigsets"]
        hits = pipeline.stats["slasher_hits"]
    dt = time.time() - t0
    if n:
        print(f"replayed {n} blocks in {dt:.1f}s ({n / dt:.1f} blocks/s, "
              f"{sigsets} signature sets, {hits} slashing hit(s))")
    else:
        print("nothing to replay")
    return 0


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    from grandine_tpu import features

    for name in filter(None, args.features.split(",")):
        features.enable_by_name(name)
    commands = {
        "run": cmd_run,
        "info": cmd_info,
        "export-interchange": cmd_export_interchange,
        "import-interchange": cmd_import_interchange,
        "replay": cmd_replay,
    }
    if args.command is None:
        parser.print_help()
        return 2
    return commands[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
